"""Compiled-program cost model: MFU/roofline accounting + HBM forensics.

The telemetry plane (PR 5) reports what the runtime *did* (step times,
throughput) and the memory planner (``parallel/memory.py``) predicts what
a run *should* need — but nothing connected either to what XLA actually
compiled. This module is that connection: it introspects a compiled
executable through ``compiled.cost_analysis()`` /
``compiled.memory_analysis()`` and derives the numbers every TPU
training/serving stack is judged on:

* **step FLOPs and bytes accessed** — straight from the cost analysis of
  the per-device program;
* **arithmetic intensity + roofline class** — FLOPs/byte against the
  chip's ridge point (peak FLOP/s ÷ HBM bandwidth): below the ridge the
  program is bandwidth-bound and no kernel tuning will reach peak FLOPs;
* **achieved MFU** — (FLOPs / measured step seconds) ÷ peak chip FLOP/s;
* **peak-HBM breakdown** — argument / output / temp / generated-code
  bytes of the executable, the numbers an OOM postmortem needs.

Exported as gauges (``m2kt_train_mfu``, ``m2kt_hbm_peak_bytes{category}``,
``m2kt_roofline_bound``) through the existing registry, and folded into
two artifacts: the **preflight plan report** (``m2kt-plan-report.{json,md}``
— MemoryPlan prediction vs fit budget vs the measured memory_analysis of
the same compiled step, with the next fsdp re-split suggested when over
budget) and the **crash flight recorder** (a ``<flight>.mem`` sidecar the
supervisor folds into ``m2kt-flight.json`` on retryable/fatal deaths).

Graceful degradation is the contract: backends return ``None``, empty
dicts, lists of dicts (CPU), objects (TPU/CPU ``CompiledMemoryStats``) or
partial key sets depending on version — every accessor here tolerates
all of them and produces a degraded-but-valid report, never an exception.

Stdlib-only on import (jax and the parallel planner are loaded lazily)
so the module vendors into emitted images with the rest of ``obs/``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field

from move2kube_tpu.obs import tracing

PLAN_REPORT_ENV = "M2KT_PLAN_REPORT"
PLAN_REPORT_STRICT_ENV = "M2KT_PLAN_REPORT_STRICT"
ACCELERATOR_ENV = "M2KT_TPU_ACCELERATOR"

# predicted-vs-measured HBM tolerance, documented in docs/ARCHITECTURE.md:
# the analytic plan (remat activation model, fp32 master assumption) and
# XLA's buffer assignment agree within 4x either way on the seed models;
# drift beyond that factor means the memory model needs recalibrating and
# fails the mfu-smoke golden assert.
PLAN_DRIFT_TOLERANCE_FACTOR = 4.0

# roofline classes, also the value of the m2kt_roofline_bound gauge
COMPUTE_BOUND = 1.0
BANDWIDTH_BOUND = 0.0
UNKNOWN_BOUND = -1.0


@dataclass(frozen=True)
class ChipSpec:
    """Per-chip peak numbers for one TPU generation (public specs)."""

    name: str
    peak_bf16_flops: float
    peak_int8_flops: float
    hbm_bytes: float
    hbm_bandwidth: float  # bytes/s

    @property
    def ridge_flops_per_byte(self) -> float:
        """Arithmetic intensity at which the roofline's bandwidth and
        compute ceilings meet; programs below it are bandwidth-bound."""
        return self.peak_bf16_flops / self.hbm_bandwidth


# keyed on the GKE nodeSelector accelerator strings (the same keys as
# parallel/memory.HBM_BYTES — gpu_detect.py owns the mapping to them)
CHIP_SPECS = {
    "tpu-v4-podslice": ChipSpec(
        "v4", peak_bf16_flops=275e12, peak_int8_flops=275e12,
        hbm_bytes=32e9, hbm_bandwidth=1228e9),
    "tpu-v5-lite-podslice": ChipSpec(
        "v5e", peak_bf16_flops=197e12, peak_int8_flops=394e12,
        hbm_bytes=16e9, hbm_bandwidth=819e9),
    "tpu-v5p-slice": ChipSpec(
        "v5p", peak_bf16_flops=459e12, peak_int8_flops=918e12,
        hbm_bytes=95e9, hbm_bandwidth=2765e9),
    "tpu-v6e-slice": ChipSpec(
        "v6e", peak_bf16_flops=918e12, peak_int8_flops=1836e12,
        hbm_bytes=32e9, hbm_bandwidth=1640e9),
}

# v5e is the conservative default for unknown accelerators — the same
# budget-like-v5e convention as topology._DEFAULT_HBM
DEFAULT_CHIP = "tpu-v5-lite-podslice"

# alias -> canonical nodeSelector string; matched on the lowercased
# accelerator with separators stripped (so "TPU v5e", "v5litepod-8" and
# "tpu-v5-lite-device" all land on the v5e row)
_ALIASES = {
    "v4": "tpu-v4-podslice",
    "tpuv4": "tpu-v4-podslice",
    "v5e": "tpu-v5-lite-podslice",
    "v5lite": "tpu-v5-lite-podslice",
    "v5litepod": "tpu-v5-lite-podslice",
    "tpuv5e": "tpu-v5-lite-podslice",
    "tpuv5lite": "tpu-v5-lite-podslice",
    "tpuv5litedevice": "tpu-v5-lite-podslice",
    "v5p": "tpu-v5p-slice",
    "tpuv5p": "tpu-v5p-slice",
    "v6e": "tpu-v6e-slice",
    "tpuv6e": "tpu-v6e-slice",
    "trillium": "tpu-v6e-slice",
}


def normalize_accelerator(accelerator: str) -> str | None:
    """Canonical CHIP_SPECS/HBM_BYTES key for an accelerator string, or
    None when nothing matches (callers pick their own conservative
    fallback — :func:`chip_spec` here, the v5e budget in ``memory.py``)."""
    raw = str(accelerator or "").strip().lower()
    if not raw:
        return None
    if raw in CHIP_SPECS:
        return raw
    squashed = "".join(c for c in raw if c.isalnum())
    # strip a trailing chip/pod count ("v5litepod8", "v5e4")
    base = squashed.rstrip("0123456789")
    for key in (squashed, base):
        if key in _ALIASES:
            return _ALIASES[key]
    for key, canon in _ALIASES.items():
        if key in squashed and len(key) >= 3:
            return canon
    return None


def chip_spec(accelerator: str = "") -> tuple[ChipSpec, bool]:
    """(spec, assumed): the chip spec for ``accelerator`` (or, unset, the
    ``M2KT_TPU_ACCELERATOR`` env). ``assumed`` is True when the string
    didn't resolve and the conservative v5e default stands in — MFU
    numbers derived from an assumed spec are still emitted (a forced-host
    CI probe has no TPU string at all) but the reports flag them."""
    raw = accelerator or os.environ.get(ACCELERATOR_ENV, "")
    canon = normalize_accelerator(raw)
    if canon is None:
        return CHIP_SPECS[DEFAULT_CHIP], True
    return CHIP_SPECS[canon], False


# ---------------------------------------------------------------------------
# compiled-executable introspection (the fallback-tolerant wrappers)
# ---------------------------------------------------------------------------


def cost_analysis(compiled) -> dict:
    """``compiled.cost_analysis()`` as a flat float dict, tolerating every
    observed backend shape: a dict, a one-per-device list of dicts (CPU),
    None, or a raising/absent method. Always returns a dict (possibly
    empty); non-numeric values are dropped."""
    try:
        raw = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 - backend-specific, absent on some
        return {}
    if isinstance(raw, (list, tuple)):
        raw = raw[0] if raw else None
    if not isinstance(raw, dict):
        return {}
    out = {}
    for k, v in raw.items():
        try:
            out[str(k)] = float(v)
        except (TypeError, ValueError):
            continue
    return out


_MEM_KEYS = {
    "args": "argument_size_in_bytes",
    "outputs": "output_size_in_bytes",
    "temps": "temp_size_in_bytes",
    "generated_code": "generated_code_size_in_bytes",
    "aliased": "alias_size_in_bytes",
}


def memory_analysis(compiled) -> dict:
    """``compiled.memory_analysis()`` as ``{args, outputs, temps,
    generated_code, aliased}`` ints, tolerating the attribute-carrying
    ``CompiledMemoryStats`` object, a plain dict, None, and missing keys.
    Missing fields are simply absent from the result (empty dict when the
    backend reports nothing), never an exception."""
    try:
        raw = compiled.memory_analysis()
    except Exception:  # noqa: BLE001 - backend-specific, absent on some
        return {}
    if raw is None:
        return {}
    out = {}
    for name, attr in _MEM_KEYS.items():
        if isinstance(raw, dict):
            val = raw.get(attr, raw.get(name))
        else:
            val = getattr(raw, attr, None)
        try:
            if val is not None:
                out[name] = int(val)
        except (TypeError, ValueError):
            continue
    return out


def peak_hbm_total(mem: dict) -> int | None:
    """Peak executable footprint: arguments + outputs + temps (donated
    bytes counted once — ``aliased`` outputs reuse argument buffers) plus
    the program text itself. None when the analysis reported nothing."""
    if not mem:
        return None
    total = (mem.get("args", 0) + mem.get("outputs", 0)
             + mem.get("temps", 0) + mem.get("generated_code", 0)
             - mem.get("aliased", 0))
    return max(0, int(total))


@dataclass
class CostReport:
    """Derived cost model of ONE compiled executable (per-device program:
    cost_analysis describes the partitioned module each chip runs, so
    flops/bytes — and any MFU derived from them — are per chip)."""

    flops: float | None = None
    bytes_accessed: float | None = None
    memory: dict = field(default_factory=dict)
    raw_cost_keys: int = 0

    @property
    def arithmetic_intensity(self) -> float | None:
        if not self.flops or not self.bytes_accessed:
            return None
        return self.flops / self.bytes_accessed

    @property
    def peak_hbm_bytes(self) -> int | None:
        return peak_hbm_total(self.memory)

    def roofline(self, spec: ChipSpec) -> str:
        """"compute" / "bandwidth" / "unknown" against the chip ridge."""
        ai = self.arithmetic_intensity
        if ai is None:
            return "unknown"
        return ("compute" if ai >= spec.ridge_flops_per_byte
                else "bandwidth")

    def mfu(self, step_seconds: float | None, spec: ChipSpec,
            int8: bool = False) -> float | None:
        """Achieved model-FLOP utilization of one chip for a measured
        step wall time; None when either half is unknown. ``int8``
        divides by the chip's int8 peak instead of bf16 — quantized
        serving must be judged against the throughput the quantization
        unlocked, or its MFU reads dishonestly high."""
        if not self.flops or not step_seconds or step_seconds <= 0:
            return None
        peak = spec.peak_int8_flops if int8 else spec.peak_bf16_flops
        return (self.flops / step_seconds) / peak

    def mfu_ceiling(self, spec: ChipSpec) -> float | None:
        """Roofline MFU ceiling: a bandwidth-bound program cannot exceed
        intensity/ridge no matter how well it schedules; 1.0 when
        compute-bound."""
        ai = self.arithmetic_intensity
        if ai is None:
            return None
        return min(1.0, ai / spec.ridge_flops_per_byte)

    def to_dict(self) -> dict:
        return {
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "arithmetic_intensity": self.arithmetic_intensity,
            "memory": dict(self.memory),
            "peak_hbm_bytes": self.peak_hbm_bytes,
        }


def analyze_compiled(compiled) -> CostReport:
    """Full degraded-tolerant report for one compiled executable. Never
    raises: a backend reporting nothing yields an all-None report."""
    cost = cost_analysis(compiled)
    return CostReport(
        flops=cost.get("flops"),
        bytes_accessed=cost.get("bytes accessed"),
        memory=memory_analysis(compiled),
        raw_cost_keys=len(cost),
    )


def lower_and_compile(step_fn, *args):
    """AOT-compile a (possibly mesh-wrapped) jitted function for
    introspection — the ``_m2kt_jit``/``_m2kt_mesh`` unwrap that
    ``train.assert_state_donated`` established. Returns the compiled
    executable, or None when the function isn't jitted or the lowering
    fails (introspection must never kill a training run)."""
    jit_fn = getattr(step_fn, "_m2kt_jit", step_fn)
    mesh = getattr(step_fn, "_m2kt_mesh", None)
    if not hasattr(jit_fn, "lower"):
        return None
    try:
        if mesh is not None:
            from move2kube_tpu.models.train import _mesh_context

            with _mesh_context(mesh):
                return jit_fn.lower(*args).compile()
        return jit_fn.lower(*args).compile()
    except Exception:  # noqa: BLE001 - best-effort introspection
        return None


def analyze_step_fn(step_fn, *args) -> CostReport | None:
    """Lower + compile + analyze in one call; None when the function
    can't be lowered (not jitted, tracing failure)."""
    compiled = lower_and_compile(step_fn, *args)
    if compiled is None:
        return None
    report = analyze_compiled(compiled)
    note_memory_report(report)
    return report


# ---------------------------------------------------------------------------
# gauge export
# ---------------------------------------------------------------------------


def export_train_gauges(report: CostReport, registry=None, *,
                        accelerator: str = "",
                        step_seconds: float | None = None) -> float | None:
    """Set the training cost-model gauges from one report: MFU (0 when
    flops or timing are unknown — the gauge stays present so dashboards
    and the mfu-smoke assert never see a missing family), the roofline
    class, per-category peak-HBM bytes, and the raw flops/intensity.
    Returns the MFU value (None when it could not be derived)."""
    from move2kube_tpu.obs.metrics import default_registry

    reg = registry if registry is not None else default_registry()
    spec, assumed = chip_spec(accelerator)
    mfu = report.mfu(step_seconds, spec)
    reg.gauge(
        "m2kt_train_mfu",
        "Achieved model-FLOP utilization per chip (0 = unknown)",
    ).set(mfu or 0.0)
    reg.gauge(
        "m2kt_roofline_bound",
        "Roofline class of the train step (1 compute-bound, "
        "0 bandwidth-bound, -1 unknown)",
    ).set({"compute": COMPUTE_BOUND, "bandwidth": BANDWIDTH_BOUND,
           "unknown": UNKNOWN_BOUND}[report.roofline(spec)])
    reg.gauge(
        "m2kt_train_step_flops",
        "Per-chip FLOPs of the compiled train step",
    ).set(report.flops or 0.0)
    reg.gauge(
        "m2kt_train_arithmetic_intensity",
        "Train-step FLOPs per HBM byte accessed",
    ).set(report.arithmetic_intensity or 0.0)
    reg.gauge(
        "m2kt_chip_spec_assumed",
        "1 when the accelerator string did not resolve and the v5e "
        "spec was assumed for MFU/roofline math",
    ).set(1.0 if assumed else 0.0)
    # the denominator the M2KTHBMHeadroomLow rule divides peak-HBM by
    reg.gauge(
        "m2kt_chip_hbm_bytes",
        "HBM capacity of the chip generation the cost model resolved",
    ).set(spec.hbm_bytes)
    hbm = reg.gauge(
        "m2kt_hbm_peak_bytes",
        "Compiled-executable HBM footprint by category",
        labels=("category",))
    for category, nbytes in report.memory.items():
        hbm.labels(category=category).set(nbytes)
    total = report.peak_hbm_bytes
    if total is not None:
        hbm.labels(category="total").set(total)
    return mfu


def export_serving_gauges(reports: dict, registry=None, *,
                          accelerator: str = "",
                          decode_step_seconds: float | None = None,
                          quant: str = "off") -> None:
    """Per-executable serving gauges from ``{name: CostReport}`` (the
    engine's bucketed prefills + the decode/verify steps): roofline class
    and step FLOPs labeled by executable, peak-HBM by (executable,
    category), and an achieved decode MFU when the engine has timing.
    ``quant`` names the serving quant policy: the cost reports already
    reflect the quantized buffers (memory_analysis sees the int8
    executables), and MFU is judged against the chip's int8 peak when
    weights are quantized."""
    from move2kube_tpu.obs.metrics import default_registry

    reg = registry if registry is not None else default_registry()
    spec, _ = chip_spec(accelerator)
    int8 = quant != "off"
    bound = reg.gauge(
        "m2kt_serve_roofline_bound",
        "Roofline class per serving executable (1 compute, 0 bandwidth, "
        "-1 unknown)", labels=("executable",))
    flops = reg.gauge(
        "m2kt_serve_step_flops",
        "Per-chip FLOPs per serving executable", labels=("executable",))
    hbm = reg.gauge(
        "m2kt_serve_hbm_peak_bytes",
        "Serving executable HBM footprint by category",
        labels=("executable", "category"))
    for name, report in reports.items():
        bound.labels(executable=name).set(
            {"compute": COMPUTE_BOUND, "bandwidth": BANDWIDTH_BOUND,
             "unknown": UNKNOWN_BOUND}[report.roofline(spec)])
        flops.labels(executable=name).set(report.flops or 0.0)
        for category, nbytes in report.memory.items():
            hbm.labels(executable=name, category=category).set(nbytes)
        total = report.peak_hbm_bytes
        if total is not None:
            hbm.labels(executable=name, category="total").set(total)
    # with spec decoding on, verify IS the steady-state decode executable
    decode = reports.get("verify") or reports.get("decode")
    if decode is not None:
        reg.gauge(
            "m2kt_serve_mfu",
            "Achieved decode-step MFU per chip (0 = unknown)",
        ).set(decode.mfu(decode_step_seconds, spec, int8=int8) or 0.0)


def export_drift_gauge(predicted_total: float | None,
                       measured_total: float | None,
                       registry=None) -> float | None:
    """The calibration loop for ``parallel/memory.py``: predicted/measured
    peak-HBM ratio as a gauge (1.0 = the analytic model matched XLA's
    buffer assignment exactly). Returns the ratio, or None (gauge set to
    0) when either side is unknown."""
    from move2kube_tpu.obs.metrics import default_registry

    reg = registry if registry is not None else default_registry()
    ratio = None
    if predicted_total and measured_total:
        ratio = float(predicted_total) / float(measured_total)
    reg.gauge(
        "m2kt_plan_hbm_drift_ratio",
        "Predicted (MemoryPlan) over measured (memory_analysis) peak-HBM "
        "bytes; 0 = unknown",
    ).set(ratio or 0.0)
    return ratio


# ---------------------------------------------------------------------------
# preflight plan report
# ---------------------------------------------------------------------------


def plan_report_dir() -> str | None:
    """Where ``m2kt-plan-report.{json,md}`` lands: ``M2KT_PLAN_REPORT``
    unset/0/false -> None (off), "1"/true -> ``M2KT_METRICS_DIR`` or cwd,
    anything else -> treated as the target directory."""
    raw = os.environ.get(PLAN_REPORT_ENV, "").strip()
    if not raw or raw.lower() in ("0", "false", "off"):
        return None
    if raw.lower() in ("1", "true", "on"):
        return os.environ.get("M2KT_METRICS_DIR", "") or "."
    return raw


def build_plan_report(memory_plan, accelerator: str, *,
                      mesh_plan=None, n_devices: int | None = None,
                      cost: CostReport | None = None,
                      step_seconds: float | None = None,
                      headroom: float = 0.9,
                      optimizer_slots: int = 2) -> dict:
    """The preflight fit report: MemoryPlan prediction vs chip budget,
    the chosen mesh plan, the roofline/MFU estimate from the compiled
    step (when one exists — emission-time reports carry prediction only),
    and — over budget — the smallest fsdp re-split that would fit.

    ``memory_plan`` is a ``parallel.memory.MemoryPlan``; ``mesh_plan`` a
    ``parallel.topology.MeshPlan`` (optional). Pure dict output so the
    emitter can render it without jax."""
    spec, assumed = chip_spec(accelerator)
    budget = spec.hbm_bytes * headroom
    predicted_total = int(memory_plan.total)
    fits = predicted_total <= budget
    report = {
        "schema": "m2kt-plan-report/v1",
        "accelerator": {
            "requested": accelerator,
            "resolved": normalize_accelerator(accelerator),
            "chip": spec.name,
            "assumed_default": assumed,
            "peak_bf16_flops": spec.peak_bf16_flops,
            "hbm_bytes": spec.hbm_bytes,
            "hbm_bandwidth_bytes_s": spec.hbm_bandwidth,
        },
        "predicted": {
            "params_bytes": int(memory_plan.params),
            "grads_bytes": int(memory_plan.grads),
            "opt_state_bytes": int(memory_plan.opt_state),
            "activations_bytes": int(memory_plan.activations),
            "total_bytes": predicted_total,
            "breakdown": [
                {"leaf": name, "bytes": int(nbytes)}
                for name, nbytes in memory_plan.breakdown
            ],
        },
        "fit": {
            "fits": fits,
            "headroom": headroom,
            "budget_bytes": int(budget),
            "utilization": (predicted_total / budget) if budget else None,
        },
        "verdict": "fit" if fits else "over-budget",
    }
    if mesh_plan is not None:
        report["mesh"] = {
            "describe": mesh_plan.describe(),
            "extents": {
                axis: getattr(mesh_plan.config, axis)
                for axis in type(mesh_plan.config).AXES
            },
            "dcn_dp": mesh_plan.dcn_dp,
            "source": mesh_plan.source,
        }
    if not fits:
        report["suggestion"] = _fsdp_suggestion(
            memory_plan, mesh_plan, n_devices, spec, headroom,
            optimizer_slots)
    if cost is not None:
        report["compiled"] = cost.to_dict()
        report["compiled"]["roofline"] = cost.roofline(spec)
        report["estimated_mfu"] = {
            "roofline_ceiling": cost.mfu_ceiling(spec),
            "achieved": cost.mfu(step_seconds, spec),
            "step_seconds": step_seconds,
        }
        measured_total = cost.peak_hbm_bytes
        drift = None
        if measured_total:
            drift = predicted_total / measured_total
        report["drift"] = {
            "measured_peak_hbm_bytes": measured_total,
            "predicted_over_measured": drift,
            "tolerance_factor": PLAN_DRIFT_TOLERANCE_FACTOR,
            "within_tolerance": (
                None if drift is None else
                1 / PLAN_DRIFT_TOLERANCE_FACTOR <= drift
                <= PLAN_DRIFT_TOLERANCE_FACTOR),
        }
    return report


def _fsdp_suggestion(memory_plan, mesh_plan, n_devices, spec: ChipSpec,
                     headroom: float, optimizer_slots: int) -> dict:
    """Next fsdp re-split that fits: reuse the planner's own memory
    split (``topology._memory_min_fsdp``) over the dp x fsdp pool so the
    suggestion is exactly what ``plan_parallelism`` would choose given
    the measured parameter bytes."""
    suggestion: dict = {"action": "re-split fsdp"}
    try:
        from move2kube_tpu.parallel.topology import _memory_min_fsdp

        if mesh_plan is not None:
            cfg = mesh_plan.config
            resident = cfg.data * cfg.fsdp
            tensor, current = cfg.tensor, cfg.fsdp
        else:
            resident = max(1, int(n_devices or 1))
            tensor, current = 1, 1
        # params in the plan are already per-chip: scale back to the
        # replica-pool total the planner's split reasons over
        param_bytes = int(memory_plan.params) * max(1, current)
        fsdp = _memory_min_fsdp(
            resident, tensor, param_bytes, spec.hbm_bytes, headroom,
            optimizer_slots)
        suggestion.update({
            "current_fsdp": current,
            "suggested_fsdp": max(fsdp, current),
            "resident_pool": resident,
        })
        if fsdp <= current:
            # state already sharded as far as the pool allows: the
            # overage is activations — suggest the other lever
            suggestion["action"] = (
                "state fully sharded; reduce batch/sequence or add chips")
    except Exception:  # noqa: BLE001 - a suggestion must not fail the report
        suggestion["action"] = "add chips or reduce model/batch"
    return suggestion


def _fmt_bytes(n) -> str:
    if n is None:
        return "-"
    value = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(value) < 1024 or unit == "GiB":
            return f"{value:.2f} {unit}" if unit != "B" else f"{int(value)} B"
        value /= 1024
    return f"{value:.2f} GiB"


def render_plan_markdown(report: dict) -> str:
    """Human half of the artifact pair: the same report as a short
    markdown brief (the JSON is for tooling/golden asserts)."""
    acc = report.get("accelerator", {})
    pred = report.get("predicted", {})
    fit = report.get("fit", {})
    lines = [
        "# m2kt preflight plan report",
        "",
        f"- **verdict**: {report.get('verdict', '?')}",
        f"- **chip**: {acc.get('chip', '?')}"
        + (" (assumed default)" if acc.get("assumed_default") else "")
        + f" — HBM {_fmt_bytes(acc.get('hbm_bytes'))}, "
          f"peak bf16 {acc.get('peak_bf16_flops', 0) / 1e12:.0f} TFLOP/s",
        f"- **budget**: {_fmt_bytes(fit.get('budget_bytes'))} "
        f"(headroom {fit.get('headroom')})",
        "",
        "| component | bytes/chip |",
        "|---|---|",
    ]
    for key, label in (("params_bytes", "params"), ("grads_bytes", "grads"),
                       ("opt_state_bytes", "optimizer state"),
                       ("activations_bytes", "activations"),
                       ("total_bytes", "**total**")):
        lines.append(f"| {label} | {_fmt_bytes(pred.get(key))} |")
    if report.get("mesh"):
        lines += ["", f"Mesh plan: `{report['mesh']['describe']}`"]
    if report.get("suggestion"):
        s = report["suggestion"]
        lines += ["", f"**Over budget** — {s.get('action')}"]
        if s.get("suggested_fsdp"):
            lines.append(f"Suggested fsdp: {s['current_fsdp']} -> "
                         f"{s['suggested_fsdp']} "
                         f"(pool {s['resident_pool']})")
    est = report.get("estimated_mfu")
    if est:
        ceil = est.get("roofline_ceiling")
        ach = est.get("achieved")
        lines += ["", "Compiled-step estimate: "
                  + (f"MFU ceiling {ceil:.1%}" if ceil is not None
                     else "MFU ceiling unknown")
                  + (f", achieved {ach:.2%}" if ach is not None else "")]
    drift = report.get("drift")
    if drift and drift.get("predicted_over_measured") is not None:
        lines += ["", f"Predicted/measured peak HBM: "
                  f"{drift['predicted_over_measured']:.2f}x "
                  f"(tolerance {drift['tolerance_factor']}x, "
                  f"{'OK' if drift['within_tolerance'] else 'DRIFTED'})"]
    return "\n".join(lines) + "\n"


def write_plan_report(report: dict, out_dir: str | None = None,
                      strict: bool | None = None) -> tuple[str, str] | None:
    """Atomically write ``m2kt-plan-report.json`` + ``.md`` into
    ``out_dir`` (default: the ``M2KT_PLAN_REPORT`` directory; None when
    the knob is off). ``strict`` (default ``M2KT_PLAN_REPORT_STRICT``)
    turns an over-budget verdict into a SystemExit — the fail-fast half
    of the preflight loop; non-strict callers get the suggestion in the
    artifact and a warning on stderr."""
    out_dir = out_dir if out_dir is not None else plan_report_dir()
    if out_dir is None:
        return None
    paths = None
    try:
        os.makedirs(out_dir, exist_ok=True)
        json_path = os.path.join(out_dir, "m2kt-plan-report.json")
        md_path = os.path.join(out_dir, "m2kt-plan-report.md")
        for path, payload in ((json_path, json.dumps(
                report, indent=2, sort_keys=True) + "\n"),
                (md_path, render_plan_markdown(report))):
            tmp = path + f".tmp.{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(payload)
            os.replace(tmp, path)
        paths = (json_path, md_path)
    except OSError:
        pass
    if report.get("verdict") == "over-budget":
        if strict is None:
            strict = os.environ.get(
                PLAN_REPORT_STRICT_ENV, "0").lower() in ("1", "true", "on")
        msg = (f"[m2kt] plan report: predicted "
               f"{report['predicted']['total_bytes'] / 1e9:.2f} GB/chip "
               f"exceeds the {report['fit']['budget_bytes'] / 1e9:.2f} GB "
               f"budget; suggestion: {report.get('suggestion', {})}")
        if strict:
            raise SystemExit(msg)
        import sys

        print(msg, file=sys.stderr, flush=True)
    return paths


# ---------------------------------------------------------------------------
# OOM forensics: memory snapshot sidecar for the flight recorder
# ---------------------------------------------------------------------------

_latest_memory: dict = {}
_mem_lock = threading.Lock()
_mem_flush_installed = False


def mem_snapshot_path() -> str:
    """Child-side memory-snapshot dump: derived from the flight path the
    same way as the span ring, so the supervisor needs no handshake."""
    return tracing.flight_path() + ".mem"


def note_memory_report(report: CostReport) -> None:
    """Remember the latest compiled-executable memory analysis so a later
    death dumps it into the flight sidecar (the analysis of the step that
    was running is exactly what an OOM postmortem wants)."""
    if report.memory:
        with _mem_lock:
            _latest_memory["memory_analysis"] = dict(report.memory)
            _latest_memory["peak_hbm_bytes"] = report.peak_hbm_bytes


def live_buffer_summary(top_n: int = 8) -> dict:
    """Host-visible live device buffers via ``jax.live_arrays()`` —
    count, total bytes, and the largest shapes. Best-effort and lazy
    (jax may not even be importable in the caller); {} on any failure."""
    try:
        import jax

        arrays = jax.live_arrays()
        sizes = []
        total = 0
        for a in arrays:
            nbytes = int(getattr(a, "nbytes", 0))
            total += nbytes
            sizes.append((nbytes, str(getattr(a, "shape", "?")),
                          str(getattr(a, "dtype", "?"))))
        sizes.sort(key=lambda t: -t[0])
        return {
            "count": len(arrays),
            "total_bytes": total,
            "top": [{"bytes": b, "shape": s, "dtype": d}
                    for b, s, d in sizes[:top_n]],
        }
    except Exception:  # noqa: BLE001 - forensics must never raise
        return {}


def write_memory_snapshot(path: str | None = None) -> str | None:
    """Atomic dump of the latest memory analysis + a live-buffer summary
    for the supervisor's flight recorder. Best-effort by design: it runs
    on dying-process paths (RESOURCE_EXHAUSTED raises through teardown;
    a SIGKILL'd OOM leaves only the analysis from a previous flush)."""
    path = path or mem_snapshot_path()
    with _mem_lock:
        doc = dict(_latest_memory)
    doc["live_buffers"] = live_buffer_summary()
    doc["written_unix"] = time.time()
    doc["pid"] = os.getpid()
    try:
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = path + f".tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f)
            f.write("\n")
        os.replace(tmp, path)
        return path
    except OSError:
        return None


def install_memory_snapshot(path: str | None = None) -> None:
    """Dump the memory snapshot on every teardown-running exit path —
    the same ``threading._register_atexit`` trick as
    ``tracing.install_ring_flush`` (see there for why plain atexit is
    too late), so a RESOURCE_EXHAUSTED abort still leaves the OOM
    forensics on disk next to the span ring."""
    global _mem_flush_installed
    if _mem_flush_installed:
        return
    _mem_flush_installed = True

    def _flush() -> None:
        try:
            write_memory_snapshot(path)
        except Exception:  # noqa: BLE001 - dying process, best effort
            pass

    register = getattr(threading, "_register_atexit", None)
    if register is None:
        import atexit

        atexit.register(_flush)
    else:
        register(_flush)
