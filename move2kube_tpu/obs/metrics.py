"""Dependency-free, thread-safe metrics registry with Prometheus
text-format v0.0.4 exposition.

Why not prometheus_client: emitted images vendor this package next to the
serving engine and must not grow a pip dependency (the container build is
hermetic), and the subset a trainer/server needs — Counter, Gauge,
Histogram, one exposition format — is small enough to own.

Concurrency model: one re-entrant lock per registry guards the family
table and every sample update. Updates are a dict write under the lock
(~100ns); exposition walks a consistent snapshot. Collect hooks run
*outside* the lock so they may themselves set gauges.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque

# prometheus_client's default buckets: latency-shaped, seconds
DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                   1.0, 2.5, 5.0, 10.0)

# where capped families send series beyond ``max_series``: one shared
# overflow bucket instead of unbounded growth from untrusted label
# values (tenant ids arrive on request headers)
OVERFLOW_LABEL = "other"

# every labels() call a capped family redirected into the overflow seat,
# by family — the cap used to fire silently, which made "tenant 'other'
# is hot" indistinguishable from "the cap is eating real tenants"
DROPPED_SERIES = "m2kt_obs_series_dropped_total"


def _escape_label(value: str) -> str:
    return (str(value).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _escape_help(value: str) -> str:
    return str(value).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt(value: float) -> str:
    """Prometheus sample formatting: integral floats render bare."""
    if value == math.inf:
        return "+Inf"
    if value == -math.inf:
        return "-Inf"
    f = float(value)
    if f.is_integer() and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class _Family:
    """One metric family: a name + help + label names + children keyed by
    label-value tuples. A label-less family has a single child keyed ().

    ``max_series > 0`` caps distinct children: the first ``max_series``
    label tuples get their own series, everything after collapses into a
    shared ``("other", ...)`` child — first-come seats approximate the
    top-K heavy hitters, and an adversary spraying unique tenant headers
    grows the exposition by at most one series."""

    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: tuple[str, ...],
                 lock: threading.RLock, max_series: int = 0) -> None:
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self.max_series = int(max_series)
        self._lock = lock
        self._children: dict[tuple[str, ...], object] = {}
        # registry-installed callback fired on every overflow redirect
        self._on_overflow = None

    def labels(self, *values, **kwvalues):
        if kwvalues:
            if values:
                raise ValueError("pass label values positionally or by "
                                 "keyword, not both")
            try:
                values = tuple(str(kwvalues[n]) for n in self.labelnames)
            except KeyError as e:
                raise ValueError(f"missing label {e} for {self.name}") from e
            if len(kwvalues) != len(self.labelnames):
                raise ValueError(f"unexpected labels for {self.name}")
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name} takes {len(self.labelnames)} label values, "
                f"got {len(values)}")
        overflowed = False
        with self._lock:
            child = self._children.get(values)
            if child is None:
                if (self.max_series > 0 and self.labelnames
                        and len(self._children) >= self.max_series):
                    overflowed = True
                    values = (OVERFLOW_LABEL,) * len(self.labelnames)
                    child = self._children.get(values)
            if child is None:
                child = self._children[values] = self._make_child()
        if overflowed and self._on_overflow is not None:
            try:
                self._on_overflow(self.name)
            except Exception:  # noqa: BLE001 - accounting must not break updates
                pass
        return child

    def _default_child(self):
        if self.labelnames:
            raise ValueError(f"{self.name} has labels "
                             f"{self.labelnames}; use .labels(...)")
        return self.labels()

    def _make_child(self):
        raise NotImplementedError

    def _label_str(self, values: tuple[str, ...], extra: str = "") -> str:
        parts = [f'{n}="{_escape_label(v)}"'
                 for n, v in zip(self.labelnames, values)]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    def _render(self, out: list[str]) -> None:
        out.append(f"# HELP {self.name} {_escape_help(self.help)}")
        out.append(f"# TYPE {self.name} {self.kind}")
        for values in sorted(self._children):
            self._render_child(out, values, self._children[values])

    def _render_child(self, out, values, child) -> None:
        raise NotImplementedError

    def total(self) -> float:
        """Sum of every child's scalar value across label sets — the
        PromQL ``sum(family)`` a single process can answer directly.
        Counters and gauges only; families whose children carry no
        scalar ``value`` (histograms) contribute 0."""
        with self._lock:
            return float(sum(getattr(c, "value", 0.0)
                             for c in self._children.values()))

    def samples(self) -> list[tuple[tuple[str, ...], float]]:
        """Consistent ``[(label_values, value)]`` snapshot of every
        scalar child — the usage ledger reads per-tenant counters this
        way instead of reparsing its own exposition page. Histogram
        children (no scalar ``value``) are skipped; use
        :meth:`Histogram.snapshots` for those."""
        with self._lock:
            return [(values, float(child.value))
                    for values, child in self._children.items()
                    if hasattr(child, "value")]


class _Value:
    __slots__ = ("value",)

    def __init__(self) -> None:
        self.value = 0.0


class Counter(_Family):
    kind = "counter"

    def _make_child(self):
        return _CounterChild(self._lock)

    def inc(self, amount: float = 1.0) -> None:
        self._default_child().inc(amount)

    @property
    def value(self) -> float:
        return self._default_child().value

    def _render_child(self, out, values, child) -> None:
        out.append(f"{self.name}{self._label_str(values)} "
                   f"{_fmt(child.value)}")


class _CounterChild:
    def __init__(self, lock: threading.RLock) -> None:
        self._lock = lock
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters can only go up")
        with self._lock:
            self.value += amount


class Gauge(_Family):
    kind = "gauge"

    def _make_child(self):
        return _GaugeChild(self._lock)

    def set(self, value: float) -> None:
        self._default_child().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._default_child().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default_child().inc(-amount)

    @property
    def value(self) -> float:
        return self._default_child().value

    def _render_child(self, out, values, child) -> None:
        out.append(f"{self.name}{self._label_str(values)} "
                   f"{_fmt(child.value)}")


class _GaugeChild:
    def __init__(self, lock: threading.RLock) -> None:
        self._lock = lock
        self.value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self.value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount


class Histogram(_Family):
    kind = "histogram"

    def __init__(self, name, help, labelnames, lock,
                 buckets=DEFAULT_BUCKETS, max_series: int = 0) -> None:
        super().__init__(name, help, labelnames, lock,
                         max_series=max_series)
        edges = sorted(float(b) for b in buckets)
        if not edges:
            raise ValueError("histogram needs at least one bucket")
        if edges[-1] != math.inf:
            edges.append(math.inf)
        self.buckets = tuple(edges)

    def _make_child(self):
        return HistogramChild(self.buckets, self._lock)

    def observe(self, value: float) -> None:
        self._default_child().observe(value)

    def quantile(self, q: float) -> float:
        return self._default_child().quantile(q)

    def snapshot(self) -> "HistogramSnapshot":
        return self._default_child().snapshot()

    def snapshots(self) -> dict[tuple[str, ...], "HistogramSnapshot"]:
        """Per-label-set :class:`HistogramSnapshot` copies — how the
        usage ledger freezes the per-tenant latency distributions."""
        with self._lock:
            children = dict(self._children)
        return {values: child.snapshot()
                for values, child in children.items()}

    @property
    def count(self) -> int:
        return self._default_child().count

    @property
    def sum(self) -> float:
        return self._default_child().sum

    def _render_child(self, out, values, child) -> None:
        cumulative = 0
        for edge, n in zip(self.buckets, child.bucket_counts):
            cumulative += n
            le = self._label_str(values, f'le="{_fmt(edge)}"')
            out.append(f"{self.name}_bucket{le} {cumulative}")
        out.append(f"{self.name}_sum{self._label_str(values)} "
                   f"{_fmt(child.sum)}")
        out.append(f"{self.name}_count{self._label_str(values)} "
                   f"{child.count}")


class HistogramChild:
    """Fixed-bucket accumulator: O(buckets) memory no matter how many
    observations — the bounded replacement for grow-forever latency
    lists in long-running servers."""

    def __init__(self, buckets: tuple[float, ...],
                 lock: threading.RLock) -> None:
        self.buckets = buckets
        self._lock = lock
        self.bucket_counts = [0] * len(buckets)
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        with self._lock:
            self.sum += value
            self.count += 1
            for i, edge in enumerate(self.buckets):
                if value <= edge:
                    self.bucket_counts[i] += 1
                    break

    def quantile(self, q: float) -> float:
        """Estimate a quantile by linear interpolation inside the bucket
        the rank falls in (Prometheus ``histogram_quantile`` semantics).
        Ranks landing in the +Inf bucket clamp to the last finite edge."""
        with self._lock:
            total = self.count
            if total == 0:
                return 0.0
            rank = q * total
            cumulative = 0
            for i, edge in enumerate(self.buckets):
                prev_cum = cumulative
                cumulative += self.bucket_counts[i]
                if cumulative >= rank and self.bucket_counts[i]:
                    if edge == math.inf:
                        finite = [e for e in self.buckets if e != math.inf]
                        return finite[-1] if finite else 0.0
                    lo = self.buckets[i - 1] if i else 0.0
                    frac = (rank - prev_cum) / self.bucket_counts[i]
                    return lo + (edge - lo) * min(1.0, max(0.0, frac))
            return 0.0

    def snapshot(self) -> "HistogramSnapshot":
        """Consistent point-in-time copy of this child's state, safe to
        read (or sample from) without holding the registry lock."""
        with self._lock:
            return HistogramSnapshot(self.buckets,
                                     tuple(self.bucket_counts),
                                     self.sum, self.count)


class HistogramSnapshot:
    """Immutable copy of one histogram's buckets — the engine's own
    latency distributions handed to consumers that must not race the
    serving hot path: the fleet simulator samples per-phase service
    times from these via :meth:`sample`, and offline analysis reads
    :meth:`quantile` without touching the live registry."""

    __slots__ = ("buckets", "bucket_counts", "sum", "count")

    def __init__(self, buckets: tuple[float, ...],
                 bucket_counts: tuple[int, ...],
                 sum_: float, count: int) -> None:
        self.buckets = tuple(buckets)
        self.bucket_counts = tuple(bucket_counts)
        self.sum = float(sum_)
        self.count = int(count)

    def quantile(self, q: float) -> float:
        """Same interpolation as the live child (Prometheus
        ``histogram_quantile`` semantics), off the frozen counts."""
        total = self.count
        if total == 0:
            return 0.0
        rank = q * total
        cumulative = 0
        for i, edge in enumerate(self.buckets):
            prev_cum = cumulative
            cumulative += self.bucket_counts[i]
            if cumulative >= rank and self.bucket_counts[i]:
                if edge == math.inf:
                    finite = [e for e in self.buckets if e != math.inf]
                    return finite[-1] if finite else 0.0
                lo = self.buckets[i - 1] if i else 0.0
                frac = (rank - prev_cum) / self.bucket_counts[i]
                return lo + (edge - lo) * min(1.0, max(0.0, frac))
        return 0.0

    def sample(self, u: float) -> float:
        """Inverse-CDF draw: map a uniform ``u`` in [0, 1) to a value
        distributed like the recorded observations (linear within each
        bucket; the +Inf bucket clamps to the last finite edge). Feed it
        seeded uniforms and a million draws replay the engine's own
        latency shape deterministically."""
        return self.quantile(min(1.0, max(0.0, float(u))))


class TimedWindow:
    """Bounded, thread-safe deque of ``(t, item)`` samples with horizon
    pruning and trailing-window queries — the one owner of the sliding-
    window math the SLO tracker, the demand forecaster's rate sampler,
    and anything else windowing a timeline kept re-implementing.

    ``clock`` is injectable (synthetic timelines in tests and the fleet
    simulator); the horizon and the item cap both prune on append, so a
    flood can never grow the window without bound."""

    def __init__(self, horizon_s: float, max_items: int = 65536,
                 clock=time.monotonic) -> None:
        self.horizon_s = float(horizon_s)
        self.max_items = max(1, int(max_items))
        self._clock = clock
        self._lock = threading.Lock()
        self._items: deque[tuple[float, object]] = deque()

    def append(self, item, t: float | None = None) -> float:
        """Record one sample (at ``t``, default now); returns the
        timestamp used."""
        now = self._clock() if t is None else float(t)
        with self._lock:
            self._items.append((now, item))
            floor = now - self.horizon_s
            while self._items and (len(self._items) > self.max_items
                                   or self._items[0][0] < floor):
                self._items.popleft()
        return now

    def window(self, window_s: float,
               now: float | None = None) -> list:
        """Items whose timestamp falls inside the trailing window."""
        if now is None:
            now = self._clock()
        floor = now - float(window_s)
        with self._lock:
            return [item for t, item in self._items if t >= floor]

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)


class WindowRate:
    """Windowed per-second rate over a monotone counter reading.

    ``read`` is any zero-arg callable returning the counter's current
    value (e.g. ``family.total``); :meth:`sample` records one
    ``(t, value)`` observation and :meth:`rate` differences the newest
    sample against the last sample at or before the window floor, so
    the rate covers the whole window instead of whatever sub-span two
    in-window samples happen to straddle. This is the counter
    ``rate(window_s)`` the forecaster consumes — callers stop keeping
    their own (t, value) deques."""

    def __init__(self, read, clock=time.monotonic,
                 horizon_s: float = 7200.0,
                 max_samples: int = 4096) -> None:
        self._read = read
        self._clock = clock
        self._lock = threading.Lock()
        self._samples: deque[tuple[float, float]] = deque()
        self.horizon_s = float(horizon_s)
        self.max_samples = max(2, int(max_samples))

    def sample(self, t: float | None = None,
               value: float | None = None) -> tuple[float, float]:
        """Record one observation (``value`` defaults to ``read()``,
        ``t`` to now); returns the ``(t, value)`` pair recorded."""
        now = self._clock() if t is None else float(t)
        val = float(self._read() if value is None else value)
        with self._lock:
            self._samples.append((now, val))
            floor = now - self.horizon_s
            # keep ONE sample below the floor as the differencing base
            while (len(self._samples) > self.max_samples
                   or (len(self._samples) > 2
                       and self._samples[1][0] <= floor)):
                self._samples.popleft()
        return now, val

    def rate(self, window_s: float, now: float | None = None) -> float:
        """Per-second rate over the trailing window; 0.0 with fewer
        than two samples. A counter that stepped backwards (a correction
        outpacing admissions) clamps to 0 — a demand rate is never
        negative."""
        if now is None:
            now = self._clock()
        floor = now - float(window_s)
        with self._lock:
            if len(self._samples) < 2:
                return 0.0
            t1, v1 = self._samples[-1]
            base = self._samples[0]
            for t, v in self._samples:
                if t > floor:
                    break
                base = (t, v)
            t0, v0 = base
        if t1 <= t0:
            return 0.0
        return max(0.0, (v1 - v0) / (t1 - t0))


class Registry:
    """Named metric families + get-or-create registration + exposition.

    get-or-create (vs prometheus_client's register-once-or-raise) because
    instruments live inside reusable classes (ServingEngine,
    StepTelemetry) that tests construct many times per process."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._families: dict[str, _Family] = {}
        self._collect_hooks: list = []

    def _get_or_create(self, cls, name, help, labels, **kw) -> _Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if not isinstance(fam, cls):
                    raise ValueError(
                        f"{name} already registered as {fam.kind}")
                return fam
            fam = cls(name, help, tuple(labels), self._lock, **kw)
            if name != DROPPED_SERIES:
                fam._on_overflow = self._note_series_drop
            self._families[name] = fam
            return fam

    def _note_series_drop(self, family: str) -> None:
        """Count one cardinality-cap trip: a ``labels()`` lookup this
        registry redirected into a family's overflow seat. The drop
        counter itself is uncapped (family names are code-controlled)
        and exempt from the callback, so the accounting can't recurse."""
        self.counter(
            DROPPED_SERIES,
            "Label lookups redirected into the 'other' overflow series "
            "by a family's max_series cap", labels=("family",),
        ).labels(family=family).inc()

    def counter(self, name: str, help: str = "",
                labels: tuple[str, ...] = (),
                max_series: int = 0) -> Counter:
        return self._get_or_create(Counter, name, help, labels,
                                   max_series=max_series)

    def gauge(self, name: str, help: str = "",
              labels: tuple[str, ...] = (),
              max_series: int = 0) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels,
                                   max_series=max_series)

    def histogram(self, name: str, help: str = "",
                  labels: tuple[str, ...] = (),
                  buckets=DEFAULT_BUCKETS,
                  max_series: int = 0) -> Histogram:
        return self._get_or_create(Histogram, name, help, labels,
                                   buckets=buckets, max_series=max_series)

    def add_collect_hook(self, fn) -> None:
        """Run ``fn()`` at every exposition, before rendering — the pull
        model's answer to metrics whose source of truth lives elsewhere
        (goodput tracker, trace recorder): refresh on scrape instead of
        polling on a timer."""
        with self._lock:
            if fn not in self._collect_hooks:
                self._collect_hooks.append(fn)

    def render(self) -> str:
        """Prometheus text-format v0.0.4 exposition of every family."""
        for hook in list(self._collect_hooks):
            try:
                hook()
            except Exception:  # noqa: BLE001 - a bad hook must not 500 /metrics
                pass
        out: list[str] = []
        with self._lock:
            for name in sorted(self._families):
                self._families[name]._render(out)
        return "\n".join(out) + "\n" if out else ""


_default = Registry()


def default_registry() -> Registry:
    return _default
