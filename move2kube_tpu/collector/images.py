"""Images collector: inspect container images referenced by the sources.

Parity: ``internal/collector/imagescollector.go`` — image names from k8s /
compose yamls in the source dir (or all local docker images), then
``docker inspect`` for user, exposed ports and accessed dirs.
"""

from __future__ import annotations

import json
import os
import subprocess

from move2kube_tpu.types import collection as collecttypes
from move2kube_tpu.utils import common
from move2kube_tpu.utils.log import get_logger

log = get_logger("collector.images")


def _docker_inspect(image: str) -> dict | None:
    if common.IGNORE_ENVIRONMENT:
        return None
    try:
        res = subprocess.run(
            ["docker", "inspect", image],
            capture_output=True, text=True, timeout=60, check=False,
        )
        if res.returncode != 0:
            return None
        data = json.loads(res.stdout)
        return data[0] if data else None
    except (OSError, subprocess.TimeoutExpired, json.JSONDecodeError):
        return None


def images_from_sources(source_dir: str) -> list[str]:
    images: list[str] = []
    for path in common.get_files_by_ext(source_dir, [".yaml", ".yml"]):
        try:
            doc = common.read_yaml(path)
        except Exception:  # noqa: BLE001
            continue
        if isinstance(doc, dict) and isinstance(doc.get("services"), dict):
            for svc in doc["services"].values():
                if isinstance(svc, dict) and svc.get("image"):
                    images.append(str(svc["image"]))
        elif isinstance(doc, dict) and doc.get("kind"):
            tmpl = doc.get("spec", {}).get("template", {})
            for c in tmpl.get("spec", {}).get("containers", []) or []:
                if c.get("image"):
                    images.append(str(c["image"]))
    return sorted(set(images))


class ImagesCollector:
    def get_annotations(self) -> list[str]:
        return ["k8s", "docker", "images"]

    def collect(self, source_dir: str, out_dir: str) -> None:
        for image in images_from_sources(source_dir):
            inspected = _docker_inspect(image)
            if inspected is None:
                continue
            cfg = inspected.get("Config", {}) or {}
            info = collecttypes.ImageInfo()
            name, _, tag = image.partition(":")
            info.tags = [(name, tag or "latest")]
            user = str(cfg.get("User", "") or "")
            if user.isdigit():
                info.user_id = int(user)
            info.ports_to_expose = [
                int(p.split("/")[0]) for p in (cfg.get("ExposedPorts") or {})
                if p.split("/")[0].isdigit()
            ]
            dirs = set()
            for env in cfg.get("Env") or []:
                if env.startswith("PATH="):
                    dirs.update(p for p in env[5:].split(":") if p)
            dirs.update((inspected.get("Config", {}).get("Volumes") or {}).keys())
            if cfg.get("WorkingDir"):
                dirs.add(cfg["WorkingDir"])
            info.accessed_dirs = sorted(dirs)
            fname = common.make_dns_label(image.replace("/", "-").replace(":", "-"))
            path = os.path.join(out_dir, "images", fname + ".yaml")
            common.write_yaml(path, info.to_dict())
            log.info("image metadata written to %s", path)
