"""CF buildpack -> containerizer-options collector.

Parity: ``internal/collector/cfcontainertypescollector.go`` — maps CF
buildpacks (from the running instance when a ``cf`` session exists, else
from ``manifest.yml`` files in the source tree) to candidate
containerization options and writes a ``CfContainerizers`` yaml.
"""

from __future__ import annotations

import os
import re

from move2kube_tpu.collector.cfapps import _cf_curl_all_pages, apps_from_v2_payload
from move2kube_tpu.source.cfmanifest2kube import find_cf_manifests
from move2kube_tpu.types import collection as collecttypes
from move2kube_tpu.types.plan import ContainerBuildType
from move2kube_tpu.utils import common
from move2kube_tpu.utils.log import get_logger

log = get_logger("collector.cfcontainertypes")

# Known CF buildpack name fragments -> containerization options. The
# reference ships an equivalent curated mapping; options are build types
# our containerizers implement, most specific first.
BUILDPACK_OPTIONS: dict[str, list[str]] = {
    "python": [ContainerBuildType.NEW_DOCKERFILE, ContainerBuildType.S2I,
               ContainerBuildType.CNB],
    "nodejs": [ContainerBuildType.NEW_DOCKERFILE, ContainerBuildType.S2I,
               ContainerBuildType.CNB],
    "java": [ContainerBuildType.NEW_DOCKERFILE, ContainerBuildType.S2I,
             ContainerBuildType.CNB],
    "go": [ContainerBuildType.NEW_DOCKERFILE, ContainerBuildType.S2I],
    "ruby": [ContainerBuildType.NEW_DOCKERFILE, ContainerBuildType.S2I,
             ContainerBuildType.CNB],
    "php": [ContainerBuildType.NEW_DOCKERFILE, ContainerBuildType.S2I,
            ContainerBuildType.CNB],
    "staticfile": [ContainerBuildType.NEW_DOCKERFILE, ContainerBuildType.CNB],
    "binary": [ContainerBuildType.MANUAL],
}


def options_for_buildpack(buildpack: str,
                          builder_buildpacks: set[str] | None = None) -> list[str]:
    """Curated mapping, refined by the CNB builders' actual buildpack list
    when a live provider could read it (parity: the reference vets CNB
    candidacy via cnb.GetAllBuildpacks, cfcontainertypescollector.go)."""
    bp = buildpack.lower()
    for frag, opts in BUILDPACK_OPTIONS.items():
        # word-anchored: 'go' must not match 'django_buildpack'
        if re.search(rf"(^|[^a-z]){frag}([^a-z]|$)", bp):
            opts = list(opts)
            # same word-anchored match as above: frag 'go' must not hit
            # builder ids like 'google.python'
            if (builder_buildpacks and ContainerBuildType.CNB in opts
                    and not any(re.search(rf"(^|[^a-z]){frag}([^a-z]|$)", b)
                                for b in builder_buildpacks)):
                opts.remove(ContainerBuildType.CNB)
            return opts
    return [ContainerBuildType.MANUAL]


def builder_buildpack_ids() -> set[str]:
    """All buildpack ids baked into the default CNB builders, lowercased;
    empty when no live provider (docker/pack) is available."""
    from move2kube_tpu.containerizer.cnb import CNBContainerizer

    listing = CNBContainerizer().get_all_buildpacks()
    return {bp.lower() for bps in listing.values() for bp in bps}


def buildpacks_from_manifests(source_dir: str) -> list[str]:
    """Buildpack names declared in CF manifest.yml files in the tree
    (cfcontainertypescollector.go manifest fallback)."""
    found: list[str] = []
    for _path, apps in find_cf_manifests(source_dir):
        for app in apps:
            for bp in app.get("buildpacks") or []:
                found.append(str(bp))
            if app.get("buildpack"):
                found.append(str(app["buildpack"]))
    return sorted(set(found))


class CFContainerTypesCollector:
    def get_annotations(self) -> list[str]:
        return ["cf", "cloudfoundry", "containerizers"]

    def collect(self, source_dir: str, out_dir: str) -> None:
        buildpacks: list[str] = []
        payload = _cf_curl_all_pages("/v2/apps")
        if payload is not None:
            for app in apps_from_v2_payload(payload).apps:
                if app.buildpack:
                    buildpacks.append(app.buildpack)
                if app.detected_buildpack:
                    buildpacks.append(app.detected_buildpack)
        buildpacks.extend(buildpacks_from_manifests(source_dir))
        buildpacks = sorted(set(buildpacks))
        if not buildpacks:
            log.debug("no CF buildpacks found; skipping")
            return
        builder_bps = builder_buildpack_ids()
        mapping = collecttypes.CfContainerizers(
            buildpack_containerizers={
                bp: options_for_buildpack(bp, builder_bps) for bp in buildpacks
            }
        )
        dest = os.path.join(out_dir, "cf", "cfcontainerizers.yaml")
        common.write_yaml(dest, mapping.to_dict())
        log.info("mapped %d CF buildpacks -> %s", len(buildpacks), dest)
