"""Cloud Foundry running-apps collector.

Parity: ``internal/collector/cfappscollector.go`` — queries the CF API for
running applications (env, ports, buildpack, memory, instances) via the
``cf`` CLI (``cf curl /v2/apps``) and writes a ``CfApps`` yaml into the
collect output directory. Environment-gated: silently skips when no ``cf``
session is available or IGNORE_ENVIRONMENT is set.
"""

from __future__ import annotations

import json
import os
import subprocess

from move2kube_tpu.types import collection as collecttypes
from move2kube_tpu.utils import common
from move2kube_tpu.utils.log import get_logger

log = get_logger("collector.cfapps")


_curl_cache: dict[str, dict | None] = {}


def _cf_curl(path: str) -> dict | None:
    """One `cf curl` per path per process — multiple collectors hit
    /v2/apps; a single-shot CLI run never needs a second fetch."""
    if common.IGNORE_ENVIRONMENT:
        return None
    if path in _curl_cache:
        return _curl_cache[path]
    result: dict | None = None
    try:
        res = subprocess.run(
            ["cf", "curl", path],
            capture_output=True, text=True, timeout=120, check=False,
        )
        if res.returncode == 0:
            result = json.loads(res.stdout)
    except (OSError, subprocess.TimeoutExpired, json.JSONDecodeError):
        result = None
    _curl_cache[path] = result
    return result


def _cf_curl_all_pages(path: str) -> dict | None:
    """Follow v2 pagination (next_url) and return one merged payload."""
    payload = _cf_curl(path)
    if payload is None:
        return None
    resources = list(payload.get("resources", []) or [])
    next_url = payload.get("next_url")
    pages = 1
    while next_url and pages < 100:  # hard stop against a looping endpoint
        page = _cf_curl(str(next_url))
        if page is None:
            break
        resources.extend(page.get("resources", []) or [])
        next_url = page.get("next_url")
        pages += 1
    if next_url:
        log.warning("CF pagination stopped after %d pages; results truncated "
                    "(next_url=%s)", pages, next_url)
    return {"resources": resources}


def apps_from_v2_payload(payload: dict) -> collecttypes.CfInstanceApps:
    """Convert a ``/v2/apps`` response document into CfInstanceApps
    (cfappscollector.go:43 onward; kept separate so tests can feed recorded
    fixtures instead of a live CF session)."""
    out = collecttypes.CfInstanceApps()
    for res in payload.get("resources", []) or []:
        entity = res.get("entity", {}) or {}
        env = entity.get("environment_json") or {}
        out.apps.append(
            collecttypes.CfApp(
                name=str(entity.get("name", "")),
                buildpack=str(entity.get("buildpack") or ""),
                detected_buildpack=str(entity.get("detected_buildpack") or ""),
                memory_mb=int(entity.get("memory", 0) or 0),
                instances=int(entity.get("instances", 1) or 1),
                ports=[int(p) for p in (entity.get("ports") or []) if p],
                env={str(k): str(v) for k, v in env.items()},
            )
        )
    return out


class CfAppsCollector:
    def get_annotations(self) -> list[str]:
        return ["cf", "cloudfoundry"]

    def collect(self, source_dir: str, out_dir: str) -> None:
        payload = _cf_curl_all_pages("/v2/apps")
        if payload is None:
            log.debug("no cf session; skipping CfApps collection")
            return
        apps = apps_from_v2_payload(payload)
        if not apps.apps:
            return
        dest = os.path.join(out_dir, "cf", "cfapps.yaml")
        common.write_yaml(dest, apps.to_dict())
        log.info("collected %d CF apps -> %s", len(apps.apps), dest)
