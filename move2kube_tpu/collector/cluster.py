"""Cluster collector: introspect the live target cluster.

Parity: ``internal/collector/clustercollector.go`` — prefers the discovery
API; we have no client-go, so the primary path shells out to ``kubectl
api-resources`` / ``api-versions`` (collectUsingCLI :491) and also gathers
storage classes and (net-new) TPU node-pool capability from node labels
(``cloud.google.com/gke-tpu-accelerator``).
"""

from __future__ import annotations

import os
import subprocess

from move2kube_tpu.types import collection as collecttypes
from move2kube_tpu.utils import common
from move2kube_tpu.utils.log import get_logger

log = get_logger("collector.cluster")


def _kubectl(*args: str) -> str | None:
    if common.IGNORE_ENVIRONMENT:
        return None
    try:
        res = subprocess.run(
            ["kubectl", *args], capture_output=True, text=True, timeout=60, check=False,
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    return res.stdout if res.returncode == 0 else None


class ClusterCollector:
    def get_annotations(self) -> list[str]:
        return ["k8s", "cluster"]

    def collect(self, source_dir: str, out_dir: str) -> None:
        out = _kubectl("api-resources", "--no-headers")
        if out is None:
            log.info("kubectl unavailable; skipping cluster collection")
            return
        spec = collecttypes.ClusterMetadataSpec()
        for line in out.splitlines():
            parts = line.split()
            if len(parts) < 4:
                continue
            # NAME [SHORTNAMES] APIVERSION NAMESPACED KIND
            kind = parts[-1]
            api_version = parts[-3]
            spec.api_kind_version_map.setdefault(kind, [])
            if api_version not in spec.api_kind_version_map[kind]:
                spec.api_kind_version_map[kind].append(api_version)
        sc_out = _kubectl("get", "storageclass", "-o", "name")
        if sc_out:
            spec.storage_classes = [
                line.split("/", 1)[-1] for line in sc_out.splitlines() if line
            ]
        # net-new: TPU node pools
        tpu_out = _kubectl(
            "get", "nodes",
            "-o", r"jsonpath={range .items[*]}{.metadata.labels.cloud\.google\.com/gke-tpu-accelerator}{'\n'}{end}",
        )
        if tpu_out:
            spec.tpu_accelerators = sorted({l for l in tpu_out.splitlines() if l})
        ctx = _kubectl("config", "current-context") or "cluster"
        name = common.make_dns_label(ctx.strip())
        cm = collecttypes.ClusterMetadata(name=name, spec=spec)
        path = os.path.join(out_dir, "clusters", name + ".yaml")
        common.write_yaml(path, cm.to_dict())
        log.info("cluster metadata written to %s", path)
