"""Cluster collector: introspect the live target cluster.

Parity: ``internal/collector/clustercollector.go`` — the reference prefers
the client-go discovery API (collectUsingAPI :301) and falls back to
kubectl exec (collectUsingCLI :491). We have no client-go; the primary
path here talks to the *same* discovery REST endpoints through
``kubectl get --raw /apis`` + ``/api`` (APIGroupList / APIResourceList
JSON), gathering every group's preferred version and full version list,
then orders each kind's group/versions by preference
(sortGroupVersionByPreferrence :148 + groupOrderPolicy :365 +
sortVersionList :412 — our policy lives in types/collection.py). The
fallback parses ``kubectl api-resources`` / ``api-versions`` output.

Also gathers storage classes and (net-new) TPU node-pool capability from
``cloud.google.com/gke-tpu-accelerator`` node labels.

The kubectl runner is injectable so tests drive the whole pipeline from
recorded fixtures (the reference leaves this layer untested; SURVEY §4).
"""

from __future__ import annotations

import json
import os
import subprocess
from typing import Callable

from move2kube_tpu.types import collection as collecttypes
from move2kube_tpu.types.collection import sort_version_list
from move2kube_tpu.utils import common
from move2kube_tpu.utils.log import get_logger

log = get_logger("collector.cluster")

Runner = Callable[..., "str | None"]


def _kubectl(*args: str) -> str | None:
    if common.IGNORE_ENVIRONMENT:
        return None
    try:
        res = subprocess.run(
            ["kubectl", *args], capture_output=True, text=True, timeout=60, check=False,
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    return res.stdout if res.returncode == 0 else None


class ClusterCollector:
    def __init__(self, runner: Runner | None = None):
        self._run = runner or _kubectl

    def get_annotations(self) -> list[str]:
        return ["k8s", "cluster"]

    # -- discovery-API path (collectUsingAPI :301) ---------------------------

    def _discovery_groups(self) -> tuple[list[str], dict[str, str]] | None:
        """-> (group/versions in preference order, group -> preferred gv).

        Preference order per group: preferred version first, remaining
        versions stage-sorted (GA > beta > alpha) — the same shape
        getPreferredResourceUsingAPI builds from ServerGroups.
        """
        apis_raw = self._run("get", "--raw", "/apis")
        core_raw = self._run("get", "--raw", "/api")
        if apis_raw is None or core_raw is None:
            # partial discovery is worse than none: recording a kind map
            # without (say) the core group would flag every Service as
            # cluster-unsupported at emission — fall back to the CLI path
            return None
        gv_order: list[str] = []
        preferred: dict[str, str] = {}
        try:
            core_versions = json.loads(core_raw).get("versions", [])
        except (ValueError, AttributeError):
            core_versions = []
        for v in core_versions:  # core group "" — always most preferred
            if v not in gv_order:
                gv_order.append(v)
        if core_versions:
            preferred[""] = core_versions[0]
        try:
            groups = json.loads(apis_raw).get("groups", [])
        except (ValueError, AttributeError):
            groups = []
        for group in groups:
            pref = (group.get("preferredVersion") or {}).get("groupVersion", "")
            versions = [v.get("groupVersion", "")
                        for v in group.get("versions", []) if v.get("groupVersion")]
            if pref:
                preferred[group.get("name", "")] = pref
            ordered = ([pref] if pref in versions else []) + sort_version_list(
                [v for v in versions if v != pref])
            for gv in ordered:
                if gv not in gv_order:
                    gv_order.append(gv)
        return (gv_order, preferred) if gv_order else None

    def _kinds_for_group_version(self, gv: str) -> list[str]:
        path = f"/apis/{gv}" if "/" in gv else f"/api/{gv}"
        raw = self._run("get", "--raw", path)
        if raw is None:
            # reference behavior (getKindsForGroups): a single erroring
            # group-version (e.g. a down aggregated APIService) is logged
            # and skipped, not fatal
            log.warning("discovery of %s failed; skipping that group/version", gv)
            return []
        try:
            resources = json.loads(raw).get("resources", [])
        except ValueError:
            return []
        # skip subresources (pods/log, deployments/scale)
        return sorted({r["kind"] for r in resources
                       if r.get("kind") and "/" not in r.get("name", "")})

    def collect_using_api(self) -> dict[str, list[str]] | None:
        found = self._discovery_groups()
        if found is None:
            return None
        gv_order, preferred = found
        kind_map: dict[str, list[str]] = {}
        # one kubectl exec per group/version: fetch concurrently (a real
        # cluster has 30-60 of these; serial would block collect for ~10s)
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=8) as pool:
            kinds_per_gv = list(pool.map(self._kinds_for_group_version, gv_order))
        for gv, kinds in zip(gv_order, kinds_per_gv):
            for kind in kinds:
                versions = kind_map.setdefault(kind, [])
                if gv not in versions:
                    versions.append(gv)
        for kind, versions in kind_map.items():
            kind_map[kind] = self._order_kind_versions(versions, preferred)
        return kind_map or None

    @staticmethod
    def _order_kind_versions(versions: list[str],
                             preferred: dict[str, str]) -> list[str]:
        """Group-preference policy + per-group preferred-version-first
        (parity: groupOrderPolicy :365 + sortGroupVersionByPreferrence)."""
        policy_sorted = sort_version_list(versions)
        out: list[str] = []
        for gv in policy_sorted:
            group = gv.rsplit("/", 1)[0] if "/" in gv else ""
            pref = preferred.get(group)
            if pref in versions and pref not in out:
                out.append(pref)
            if gv not in out:
                out.append(gv)
        return out

    # -- CLI fallback (collectUsingCLI :491) ---------------------------------

    def collect_using_cli(self) -> dict[str, list[str]] | None:
        out = self._run("api-resources", "--no-headers")
        if out is None:
            return None
        kind_map: dict[str, list[str]] = {}
        kind_groups: dict[str, set[str]] = {}
        for line in out.splitlines():
            parts = line.split()
            # NAME [SHORTNAMES] APIVERSION NAMESPACED KIND — NAMESPACED is
            # the only boolean column; anchor on it instead of counting
            try:
                ns_idx = next(i for i, p in enumerate(parts)
                              if p in ("true", "false"))
            except StopIteration:
                continue
            if ns_idx < 1 or ns_idx + 1 >= len(parts):
                continue
            api_version = parts[ns_idx - 1]
            kind = parts[ns_idx + 1]
            group = api_version.rsplit("/", 1)[0] if "/" in api_version else ""
            versions = kind_map.setdefault(kind, [])
            if api_version not in versions:
                versions.append(api_version)
            kind_groups.setdefault(kind, set()).add(group)
        if not kind_map:
            return None
        # api-resources shows only each group's preferred version; fill in
        # the rest of the group's versions from `kubectl api-versions`
        av = self._run("api-versions")
        all_gvs = [l.strip() for l in av.splitlines() if l.strip()] if av else []
        for kind, groups in kind_groups.items():
            for gv in all_gvs:
                group = gv.rsplit("/", 1)[0] if "/" in gv else ""
                if group in groups and gv not in kind_map[kind]:
                    kind_map[kind].append(gv)
        # preferred (= first seen from api-resources) stays first; the
        # backfill is policy-sorted behind it
        return {k: v[:1] + sort_version_list(v[1:]) for k, v in kind_map.items()}

    # -- driver --------------------------------------------------------------

    def collect_spec(self) -> collecttypes.ClusterMetadataSpec | None:
        kind_map = self.collect_using_api()
        if kind_map is None:
            log.info("discovery API unavailable; trying kubectl api-resources")
            kind_map = self.collect_using_cli()
        if kind_map is None:
            return None
        spec = collecttypes.ClusterMetadataSpec(api_kind_version_map=kind_map)
        sc_out = self._run("get", "storageclass", "-o", "name")
        if sc_out:
            spec.storage_classes = [
                line.split("/", 1)[-1] for line in sc_out.splitlines() if line
            ]
        # net-new: TPU node pools
        tpu_out = self._run(
            "get", "nodes",
            "-o", (r"jsonpath={range .items[*]}{.metadata.labels"
                  r".cloud\.google\.com/gke-tpu-accelerator}{'\n'}{end}"),
        )
        if tpu_out:
            spec.tpu_accelerators = sorted({l for l in tpu_out.splitlines() if l})
        return spec

    def collect(self, source_dir: str, out_dir: str) -> None:
        spec = self.collect_spec()
        if spec is None:
            log.info("kubectl unavailable; skipping cluster collection")
            return
        ctx = self._run("config", "current-context") or "cluster"
        name = common.make_dns_label(ctx.strip())
        cm = collecttypes.ClusterMetadata(name=name, spec=spec)
        path = os.path.join(out_dir, "clusters", name + ".yaml")
        common.write_yaml(path, cm.to_dict())
        log.info("cluster metadata written to %s", path)
