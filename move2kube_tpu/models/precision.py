"""Mixed-precision policy: bf16 compute, fp32 master weights.

TPU MXUs run bf16 matmuls at full rate and fp32 at a fraction of it, so
the zoo's compute dtype is the single biggest MFU knob after sharding.
The policy split is standard: parameters and optimizer state live in
float32 (flax's default param dtype — the "master weights"), activations
and matmuls run in the policy's compute dtype (modules cast at use via
their ``dtype`` config field), and gradients are computed/accumulated in
fp32.  bf16 shares fp32's exponent range so it needs no loss scaling;
the optional ``bf16-scaled`` policy multiplies the loss by a constant
scale and divides it back out of the gradients — the hook a future fp16
or fp8 recipe needs, wired through ``apply_if_finite`` so a rare
non-finite scaled step is skipped instead of poisoning the weights.

Resolved once at trainer startup from ``M2KT_PRECISION`` (emitted
default comes from the QA answer recorded at translate time) with
``M2KT_LOSS_SCALE`` as a numeric override.
"""

from __future__ import annotations

import dataclasses
import os

PRECISION_OPTIONS = ("bf16", "fp32", "bf16-scaled")


@dataclasses.dataclass(frozen=True)
class PrecisionPolicy:
    name: str = "bf16"
    compute_dtype: str = "bfloat16"
    param_dtype: str = "float32"  # master weights + optimizer state
    loss_scale: float = 0.0  # 0 = off (bf16 needs none)

    @property
    def jnp_compute_dtype(self):
        import jax.numpy as jnp

        return jnp.dtype(self.compute_dtype)

    def cast_params(self, params):
        """Compute-dtype view of the fp32 master weights (identity for
        fp32 policies); non-float leaves pass through untouched."""
        import jax
        import jax.numpy as jnp

        target = self.jnp_compute_dtype
        if target == jnp.float32:
            return params
        return jax.tree_util.tree_map(
            lambda x: x.astype(target)
            if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
            else x,
            params,
        )

    def scale_loss(self, loss):
        return loss * self.loss_scale if self.loss_scale else loss

    def unscale(self, tree):
        """Undo :meth:`scale_loss` on a loss or gradient tree."""
        if not self.loss_scale:
            return tree
        import jax

        inv = 1.0 / self.loss_scale
        return jax.tree_util.tree_map(lambda x: x * inv, tree)

    def wrap_optimizer(self, tx):
        """Skip (not crash on) non-finite updates when loss scaling is
        active — overflowed scaled grads are expected occasionally."""
        if not self.loss_scale:
            return tx
        import optax

        return optax.apply_if_finite(tx, max_consecutive_errors=10)

    def apply_to_model_config(self, cfg):
        """Return ``cfg`` with its ``dtype`` field set to the compute
        dtype (LlamaConfig / GPT2Config style); configs without a dtype
        field pass through."""
        if not dataclasses.is_dataclass(cfg) or "dtype" not in {
            f.name for f in dataclasses.fields(cfg)
        }:
            return cfg
        return dataclasses.replace(cfg, dtype=self.jnp_compute_dtype)


_POLICIES = {
    "bf16": PrecisionPolicy(),
    "fp32": PrecisionPolicy(name="fp32", compute_dtype="float32"),
    "bf16-scaled": PrecisionPolicy(name="bf16-scaled", loss_scale=1024.0),
}


def policy(name: str) -> PrecisionPolicy:
    try:
        return _POLICIES[name]
    except KeyError:
        raise ValueError(
            f"unknown precision policy {name!r}; options: {', '.join(PRECISION_OPTIONS)}"
        ) from None


def find_apply_if_finite_state(state):
    """The ``optax.apply_if_finite`` state inside an (arbitrarily
    nested) optimizer state, or None when no loss-scaled wrapper is
    active. Duck-typed on the state's field names rather than the optax
    class so an optax rename can't silently kill telemetry; recursion
    covers ``chain`` tuples and wrapper ``inner_state`` fields."""

    def find(node):
        if (hasattr(node, "total_notfinite")
                and hasattr(node, "notfinite_count")):
            return node
        if isinstance(node, (tuple, list)):
            for item in node:
                hit = find(item)
                if hit is not None:
                    return hit
        inner = getattr(node, "inner_state", None)
        if inner is not None:
            return find(inner)
        return None

    return find(getattr(state, "opt_state", state))


def skipped_updates(state) -> int | None:
    """Cumulative updates ``apply_if_finite`` swallowed because the
    (scaled) gradients went non-finite — the number StepTelemetry
    surfaces as ``m2kt_train_skipped_steps_total`` instead of letting
    those steps vanish silently. None when no wrapper is active."""
    hit = find_apply_if_finite_state(state)
    return int(hit.total_notfinite) if hit is not None else None


def notfinite_streak(state) -> int | None:
    """Consecutive non-finite updates so far (resets on a finite one);
    ``apply_if_finite`` raises after its ``max_consecutive_errors``, so
    a climbing streak is the early warning."""
    hit = find_apply_if_finite_state(state)
    return int(hit.notfinite_count) if hit is not None else None


def from_env(default: str = "bf16", env=None) -> PrecisionPolicy:
    """``M2KT_PRECISION`` names the policy; ``M2KT_LOSS_SCALE`` (float)
    overrides its loss scale. Unknown names fall back to ``default``
    rather than killing a training job over an env typo."""
    env = os.environ if env is None else env
    name = env.get("M2KT_PRECISION", "") or default
    try:
        pol = policy(name)
    except ValueError:
        pol = policy(default)
    raw_scale = env.get("M2KT_LOSS_SCALE", "")
    if raw_scale:
        try:
            pol = dataclasses.replace(pol, loss_scale=float(raw_scale))
        except ValueError:
            pass
    return pol
