"""Pipeline-parallel (staged) Llama training over the ``pipe`` mesh axis.

TPU-native replacement for detected GPU pipeline parallelism that ZeRO
can't absorb (reference behavior: DeepSpeed ``runtime/pipe/module.py``
PipelineModule partitions layers across ranks and a runtime scheduler
pushes microbatches; Megatron ``core/pipeline_parallel/schedules.py``).
Here the schedule is *compiled* (parallel/pipeline.py GPipe-over-ppermute):

- embedding, final norm and LM head run outside the pipeline, replicated
  over ``pipe`` and batch-sharded over ``(data, fsdp)``;
- the transformer blocks split into ``num_stages`` equal stages whose
  params carry a leading ``[P, ...]`` axis sharded over ``pipe`` — each
  pipe index holds only its stage's weights, the same per-device memory
  saving GPU pipeline parallelism buys;
- microbatches flow stage-to-stage via ICI neighbour ``ppermute``; the
  backward schedule falls out of ``jax.grad`` through the compiled loop.

Emitted by containerizer/jax_emit.py when gpu_detect reports pp>1 without
ZeRO>=2 on a decoder-LM workload (SURVEY.md §5 GPipe/Megatron-PP mapping).
"""

from __future__ import annotations

import dataclasses
import functools

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from move2kube_tpu.models.llama import Llama, LlamaBlock, LlamaConfig, RMSNorm
from move2kube_tpu.models.train import TrainState, _mesh_context, _with_mesh, lm_loss
from move2kube_tpu.parallel.pipeline import pipeline_sharded, stack_stage_params

BATCH_AXES = ("data", "fsdp")


def _check_cfg(cfg: LlamaConfig, num_stages: int) -> None:
    if cfg.num_layers % num_stages:
        raise ValueError(
            f"num_layers={cfg.num_layers} must divide evenly into "
            f"{num_stages} pipeline stages")
    if cfg.moe_experts:
        raise ValueError("staged pipeline supports dense models only; "
                         "MoE maps to the expert axis instead (jax_emit)")


def _regroup_stages(layer_params: dict, num_layers: int, num_stages: int):
    """[layer_0..layer_{L-1}] -> stacked [P, ...] trees of block_0..block_{k-1}."""
    lps = num_layers // num_stages
    return stack_stage_params([
        {f"block_{j}": layer_params[f"layer_{s * lps + j}"] for j in range(lps)}
        for s in range(num_stages)
    ])


def init_pipeline_lm_params(rng, cfg: LlamaConfig, num_stages: int,
                            sample_ids) -> dict:
    """Init the full Llama once, regroup its blocks into staged params:
    {"embed", "stages" [P, ...], "final_norm", "lm_head"}."""
    _check_cfg(cfg, num_stages)
    variables = Llama(cfg).init(rng, sample_ids)
    p = dict(variables["params"])
    return {
        "embed": p["embed"],
        "stages": _regroup_stages(p, cfg.num_layers, num_stages),
        "final_norm": p["final_norm"],
        "lm_head": p["lm_head"],
    }


def pipeline_param_shardings(params_or_shapes, mesh: Mesh) -> dict:
    """Stage params shard over ``pipe`` on their leading axis; the small
    embed/norm/head trees are replicated (pipe meshes keep tensor=1)."""
    return {
        k: jax.tree.map(
            lambda _: NamedSharding(mesh, P("pipe") if k == "stages" else P()),
            v)
        for k, v in params_or_shapes.items()
    }


def create_pipeline_lm_state(rng, cfg: LlamaConfig, num_stages: int,
                             sample_ids, tx: optax.GradientTransformation,
                             mesh: Mesh) -> TrainState:
    """Sharded-init a pipeline TrainState (same jit/out_shardings recipe as
    train.create_sharded_state, with the staged layout above)."""
    init_fn = functools.partial(init_pipeline_lm_params, cfg=cfg,
                                num_stages=num_stages, sample_ids=sample_ids)
    with _mesh_context(mesh):
        shapes = jax.eval_shape(init_fn, rng)
        out_shardings = pipeline_param_shardings(shapes, mesh)
        params = jax.jit(init_fn, out_shardings=out_shardings)(rng)
    return TrainState.create(apply_fn=None, params=params, tx=tx)


def graft_ported_params(state: TrainState, flat_params: dict,
                        cfg: LlamaConfig, num_stages: int,
                        mesh: Mesh) -> TrainState:
    """Regroup a ported flat Llama param tree (port_weights.py layout:
    ``embed``/``layer_i``/``final_norm``/``lm_head``) into the staged
    pipeline layout and graft it into ``state`` with the pipe shardings
    (same adapter as models/gpt2_pipe.py)."""
    staged = {
        "embed": flat_params["embed"],
        "stages": _regroup_stages(flat_params, cfg.num_layers, num_stages),
        "final_norm": flat_params["final_norm"],
        "lm_head": flat_params["lm_head"],
    }
    staged = jax.device_put(staged, pipeline_param_shardings(staged, mesh))
    return state.replace(params=staged)


def flat_param_shapes(cfg: LlamaConfig):
    """Abstract flat Llama param tree (the ported-checkpoint layout)."""
    return jax.eval_shape(
        lambda r: Llama(cfg).init(r, jnp.zeros((1, 8), jnp.int32))["params"],
        jax.random.PRNGKey(0))


def apply_pipeline_lm(cfg: LlamaConfig, num_stages: int, mesh: Mesh, params,
                      input_ids, *, num_microbatches: int,
                      remat: bool = True):
    """Forward pass: embed -> compiled GPipe over the blocks -> norm+head.

    ``input_ids`` [batch, seq]; batch must divide into ``num_microbatches``
    x (data*fsdp shards). Returns [batch, seq, vocab] float32 logits.
    """
    _check_cfg(cfg, num_stages)
    lps = cfg.num_layers // num_stages
    # activation-sharding constraints are invalid inside shard_map (the
    # mesh axes there are manual); the pipe wrapper specs shard the batch
    block_cfg = dataclasses.replace(cfg, shard_activations=False)

    x = nn.Embed(cfg.vocab_size, cfg.d_model, dtype=cfg.dtype).apply(
        {"params": params["embed"]}, input_ids)

    def stage_fn(p, x):
        b, s, _ = x.shape
        positions = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
        mask = jnp.where(
            jnp.arange(s)[:, None] >= jnp.arange(s)[None, :], 0.0, -1e30
        ).astype(jnp.float32)[None, None]
        for j in range(lps):
            x = LlamaBlock(block_cfg).apply(
                {"params": p[f"block_{j}"]}, x, positions, mask)
        return x

    if remat:
        stage_fn = jax.checkpoint(stage_fn)
    x = pipeline_sharded(mesh, stage_fn, params["stages"], x,
                         num_microbatches=num_microbatches,
                         batch_axes=BATCH_AXES)
    x = RMSNorm(cfg.norm_eps).apply({"params": params["final_norm"]}, x)
    return nn.Dense(cfg.vocab_size, use_bias=False, dtype=jnp.float32).apply(
        {"params": params["lm_head"]}, x.astype(jnp.float32))


def make_pipeline_lm_train_step(cfg: LlamaConfig, num_stages: int, mesh: Mesh,
                                *, num_microbatches: int, remat: bool = True):
    """Next-token-prediction train step through the compiled pipeline."""

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(state: TrainState, batch: dict):
        ids = jax.lax.with_sharding_constraint(
            batch["input_ids"], NamedSharding(mesh, P(BATCH_AXES)))

        def loss_fn(params):
            logits = apply_pipeline_lm(
                cfg, num_stages, mesh, params, ids,
                num_microbatches=num_microbatches, remat=remat)
            return lm_loss(logits, ids)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        return state.apply_gradients(grads=grads), loss

    return _with_mesh(mesh, step)
