"""Host-sharded input pipeline for emitted training programs.

The reference's north star requires translated workloads to *train* — which
needs data, not just a model. The TPU-first shape of an input pipeline is
per-host sharding: every JobSet pod (host) loads only the examples that
land on its chips, builds its process-local array, and
``jax.make_array_from_process_local_data`` assembles the logical global
batch without any cross-host transfer (data-parallel dims are
host-partitioned; DCN never carries input data).

Three sources, selected by path (emitted programs read ``M2KT_DATA``):

- ``*.npy``  — a dict-like npz/npy of arrays (``input``/``label`` or
  ``input_ids``), memory-mapped so hosts touch only their slices
- ``*.jsonl`` — one JSON object per line with token/feature lists
- a directory — every ``*.npy``/``*.jsonl`` inside, concatenated
- anything else / empty — synthetic batches (shape-compatible random data)

No tf.data/grain dependency: numpy + a double-buffered device prefetch.
"""

from __future__ import annotations

import json
import os
import queue
import threading

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from move2kube_tpu.native import gather_rows
from move2kube_tpu.utils.log import get_logger

log = get_logger("models.data")


def _process_slice(n: int) -> tuple[int, int]:
    """[start, stop) of this host's contiguous shard of n examples."""
    pc, pi = jax.process_count(), jax.process_index()
    per = n // pc
    return pi * per, (pi + 1) * per if pi < pc - 1 else n


def load_arrays(path: str) -> dict[str, np.ndarray]:
    """Load feature arrays from npy/npz/jsonl file or a directory of them."""
    if os.path.isdir(path):
        parts = [load_arrays(os.path.join(path, f))
                 for f in sorted(os.listdir(path))
                 if f.endswith((".npy", ".npz", ".jsonl"))]
        if not parts:
            return {}
        keys = parts[0].keys()
        return {k: np.concatenate([p[k] for p in parts if k in p]) for k in keys}
    if path.endswith(".npz"):
        with np.load(path, mmap_mode="r") as z:
            return {k: z[k] for k in z.files}
    if path.endswith(".npy"):
        return {"input": np.load(path, mmap_mode="r")}
    if path.endswith(".jsonl"):
        rows: dict[str, list] = {}
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                obj = json.loads(line)
                for k, v in obj.items():
                    rows.setdefault(k, []).append(v)
        return {k: np.asarray(v) for k, v in rows.items()}
    raise ValueError(f"unsupported data path: {path}")


def batch_sharding(mesh: Mesh):
    """Batch sharding for loader output. Delegates to
    ``train.batch_sharding`` — the single source of truth for the
    trivial-mesh rule (SingleDeviceSharding on one-device meshes so
    committed inputs never force the SPMD compile; the ~7x CPU-backend
    tax measured in docs/ROUND5_NOTES.md) and for the AbstractMesh guard
    (``mesh.devices`` raises on device-less meshes; the shape-
    verification path gets the bare PartitionSpec instead)."""
    from move2kube_tpu.models.train import batch_sharding as _train_bs

    return _train_bs(mesh)


class HostShardedLoader:
    """Iterate global batches assembled from per-host shards.

    Each host cycles through its own contiguous slice with an epoch-seeded
    shuffle (same seed everywhere, disjoint index ranges, so the global
    epoch is a true permutation)."""

    def __init__(self, arrays: dict[str, np.ndarray], global_batch: int,
                 mesh: Mesh, seed: int = 0, to_device: bool = True):
        if not arrays:
            raise ValueError("no arrays to load")
        # to_device=False yields host (numpy) batches and leaves the
        # device transfer to a downstream PrefetchLoader, so H2D happens
        # on the pump thread while the previous step computes
        self.to_device = to_device
        n = min(len(v) for v in arrays.values())
        self.arrays = {k: v[:n] for k, v in arrays.items()}
        self.global_batch = global_batch
        self.mesh = mesh
        self.seed = seed
        pc = jax.process_count()
        if global_batch % pc:
            raise ValueError(
                f"global batch {global_batch} not divisible by {pc} hosts")
        self.local_batch = global_batch // pc
        self.start, self.stop = _process_slice(n)
        if self.stop - self.start < self.local_batch:
            raise ValueError(
                f"host shard has {self.stop - self.start} examples, "
                f"needs >= {self.local_batch}")
        self._sharding = batch_sharding(mesh)
        self._epoch = 0
        self._order = self._reshuffle()
        self._cursor = 0

    def _reshuffle(self) -> np.ndarray:
        rng = np.random.default_rng(self.seed + self._epoch)
        idx = np.arange(self.start, self.stop)
        rng.shuffle(idx)
        return idx

    def __iter__(self):
        return self

    def _advance(self) -> np.ndarray:
        if self._cursor + self.local_batch > len(self._order):
            self._epoch += 1
            self._order = self._reshuffle()
            self._cursor = 0
        take = self._order[self._cursor:self._cursor + self.local_batch]
        self._cursor += self.local_batch
        return take

    def __next__(self) -> dict[str, jax.Array]:
        take = self._advance()
        out = {}
        for k, v in self.arrays.items():
            # parallel C row-gather when built (move2kube_tpu/native);
            # numpy fancy-index fallback otherwise
            local = gather_rows(v, take)
            if self.to_device:
                local = jax.make_array_from_process_local_data(
                    self._sharding, local)
            out[k] = local
        return out

    def skip(self, n: int) -> None:
        """Advance the stream n batches WITHOUT materializing them —
        resume fast-forward must be cursor arithmetic, not n host-to-
        device transfers."""
        for _ in range(n):
            self._advance()

    # uniform loader protocol: every make_loader() product is a context
    # manager, so retry loops (resilience.supervisor) can hold ANY loader
    # in a `with` without caring which variant owns a pump thread
    def close(self) -> None:
        """Nothing to release (no thread, no buffered device batches)."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class PrefetchLoader:
    """Double-buffered *device* prefetch: a background thread assembles
    the next batch (shuffle gather) and — when constructed with the batch
    ``sharding`` — starts its host->device transfer, all while the device
    runs the current step. JAX dispatches transfers asynchronously, so by
    the time the consumer calls ``next()`` the batch is typically already
    resident on device: steady-state step time is ~max(host, compute)
    instead of their sum.

    ``skip`` must be called before iteration starts (resume fast-forward
    happens before the training loop) — once the thread is running the
    already-buffered batches would be from the pre-skip stream."""

    def __init__(self, inner, depth: int = 2, sharding=None):
        self._inner = inner
        self._sharding = sharding
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._thread: threading.Thread | None = None
        self._dead: BaseException | None = None
        self._terminated = False  # the one None sentinel was consumed
        self._closed = False

    def _transfer(self, item):
        """Start the H2D transfer for every host-resident leaf (device
        arrays pass through untouched — inner loaders that already
        transferred, or synthetic jnp batches)."""
        if self._sharding is None:
            return item

        def leaf(x):
            if isinstance(x, np.ndarray):
                return jax.make_array_from_process_local_data(
                    self._sharding, x)
            return x

        return jax.tree.map(leaf, item)

    def _pump(self):
        try:
            while not self._closed:
                item = self._transfer(next(self._inner))
                # bounded put so an abandoned loader (consumer broke out
                # mid-epoch) unblocks and exits once close() is called,
                # instead of pinning depth+1 batches for the process life
                while not self._closed:
                    try:
                        self._q.put(item, timeout=0.5)
                        break
                    except queue.Full:
                        continue
        except BaseException as e:  # noqa: BLE001 - re-raised in __next__
            self._dead = e
            # bounded put for the sentinel too: if close() races this
            # exception path, the queue may never drain again — the pump
            # must observe _closed rather than block forever
            while not self._closed:
                try:
                    self._q.put(None, timeout=0.5)
                    break
                except queue.Full:
                    continue

    def close(self) -> None:
        """Stop the pump thread and drop buffered batches. Call when
        abandoning iteration early (the emitted trainers drain fully and
        don't need it; context-manager use covers ad-hoc consumers)."""
        self._closed = True
        if self._thread is not None:
            while True:  # drain so a put-blocked pump can observe _closed
                try:
                    self._q.get_nowait()
                except queue.Empty:
                    break
            self._thread.join(timeout=5.0)
            if self._thread.is_alive():
                log.warning(
                    "PrefetchLoader pump thread still alive 5s after "
                    "close(); leaking a daemon thread (inner loader "
                    "blocked in next()?)")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def skip(self, n: int) -> None:
        if self._thread is not None:
            raise RuntimeError("skip() after iteration started")
        self._inner.skip(n)

    def __iter__(self):
        return self

    def __next__(self):
        if self._closed:
            # close() drained the queue and stopped the pump; there is
            # nothing left to deliver and nothing to block on
            raise StopIteration
        if self._terminated:
            # the pump thread is dead and its one sentinel was already
            # consumed — keep raising instead of blocking forever on an
            # empty queue (buffered good batches before the sentinel are
            # still delivered by the branch below)
            raise self._dead if self._dead is not None else StopIteration
        if self._thread is None:
            self._thread = threading.Thread(target=self._pump, daemon=True)
            self._thread.start()
        item = self._q.get()
        if item is None:
            self._terminated = True
            raise self._dead if self._dead is not None else StopIteration
        return item


class AccumLoader:
    """Group ``k`` consecutive microbatches from an inner loader into one
    stacked batch with a leading ``[k, ...]`` axis — the shape the
    ``grad_accum=k`` train steps consume (one optimizer update per
    ``next()``). ``skip`` counts in optimizer steps, so a resumed run
    fast-forwards ``n * k`` microbatches."""

    def __init__(self, inner, k: int):
        if k < 1:
            raise ValueError(f"accumulation factor must be >= 1, got {k}")
        self._inner = inner
        self._k = k

    def __iter__(self):
        return self

    def __next__(self):
        micro = [next(self._inner) for _ in range(self._k)]
        return jax.tree.map(lambda *xs: jnp.stack(xs), *micro)

    def skip(self, n: int) -> None:
        self._inner.skip(n * self._k)

    def close(self) -> None:
        close = getattr(self._inner, "close", None)
        if close is not None:
            close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def make_loader(path: str, global_batch: int, mesh: Mesh,
                synthetic_fn=None, seed: int = 0, prefetch: bool = True):
    """Return a batch iterator: real data when ``path`` exists, else the
    synthetic generator (the emitted programs' out-of-the-box mode).
    Real-data loaders are wrapped in a double-buffered *device* prefetch
    unless ``prefetch=False`` (or M2KT_PREFETCH=0): the inner loader
    stays on the host and the pump thread owns the sharded H2D transfer,
    overlapping it with the running step."""
    if path and os.path.exists(path):
        use_prefetch = (prefetch
                        and os.environ.get("M2KT_PREFETCH", "1") != "0")
        loader = HostShardedLoader(load_arrays(path), global_batch, mesh,
                                   seed, to_device=not use_prefetch)
        if use_prefetch:
            return PrefetchLoader(loader, sharding=batch_sharding(mesh))
        return loader
    if synthetic_fn is None:
        raise ValueError(f"data path {path!r} not found and no synthetic fn")

    class _Synthetic:
        def __init__(self):
            self._i = 0

        def __iter__(self):
            return self

        def __next__(self):
            batch = synthetic_fn(self._i)
            self._i += 1
            return batch

        def skip(self, n: int) -> None:
            self._i += n

        def close(self) -> None:
            pass

        def __enter__(self):
            return self

        def __exit__(self, *exc):
            return False

    return _Synthetic()
