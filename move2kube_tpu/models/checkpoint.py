"""Sharded training checkpoint/resume (orbax).

The reference's resumability story is plan + QA-cache files (SURVEY.md §5
"checkpoint/resume"); for the *generated training programs* the equivalent
is real model checkpointing: a JobSet pod that is preempted or fails must
restart from the latest step, not step 0. Orbax handles the TPU-specific
parts — per-host shard writing (each process persists only its addressable
shards), async save off the training thread, and restore into an arbitrary
new sharding layout, so a job can resume on a different mesh shape.

Emitted training entrypoints (assets/jax/train_tpu.py) call
``restore_or_init`` at startup and ``CheckpointManager.maybe_save`` every
``M2KT_CKPT_EVERY`` steps, pointed at ``M2KT_CKPT_DIR`` (a GCS bucket or
ReadWriteMany PVC mount in the JobSet spec).
"""

from __future__ import annotations

import logging
import os

import jax

# stdlib logging, not utils.log: the jax-xla containerizer vendors only the
# dependency-light models/parallel/ops packages into emitted images
log = logging.getLogger("m2kt.checkpoint")


def _maybe_span(name: str, attrs: dict | None = None):
    """Span into the runtime trace ring when tracing is on; a no-op
    context otherwise. The async-save submit/sync/wait phases are
    exactly what the crash flight recorder needs to show whether a
    death raced an in-flight checkpoint commit."""
    from move2kube_tpu.obs import tracing

    if tracing.enabled():
        return tracing.get().span(name, attrs)
    import contextlib

    return contextlib.nullcontext()


def _manager(ckpt_dir: str, max_to_keep: int = 3):
    import orbax.checkpoint as ocp

    return ocp.CheckpointManager(
        os.path.abspath(ckpt_dir),
        options=ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            enable_async_checkpointing=True,
        ),
    )


class CheckpointManager:
    """Thin wrapper owning an orbax CheckpointManager.

    Keeps the emitted training loop to three calls: ``restore_or_init``,
    ``maybe_save``, ``close``.
    """

    def __init__(self, ckpt_dir: str, every: int = 100, max_to_keep: int = 3):
        self.every = max(1, every)
        self._mngr = _manager(ckpt_dir, max_to_keep)

    def latest_step(self) -> int | None:
        return self._mngr.latest_step()

    def restore_or_init(self, state, ported_restore=None):
        """Return (state, start_step): the newest *readable* checkpoint
        restored into ``state``'s sharding layout, or ``state`` itself at
        step 0.

        Two checkpoint shapes are accepted: a full TrainState (periodic
        saves from the training loop), and a params-only dict written by
        ``port_weights.py`` (torch weights converted to our layout) — the
        latter grafts params into the fresh state, keeping new optimizer
        state, so a GPU fine-tune resumes from its pretrained weights.

        Corrupt-latest fallback: a preempted or failed host can leave the
        newest step truncated or partially written. Instead of crashing
        the restarted pod in a loop (which burns the JobSet's maxRestarts
        on an unfixable artifact), an unreadable step falls back to the
        previous retained step, oldest-retained last; when *no* retained
        step restores, training restarts from step 0 with a loud error —
        forward progress with bounded loss beats a crashloop. Exercised
        in tier-1 by ``resilience.faults.corrupt_latest``.

        ``ported_restore``: optional ``(abstract_params, graft_fn)`` for
        states whose param layout differs from the ported flat layout —
        the pipeline trainers' staged trees (models/{gpt2,llama}_pipe
        ``flat_param_shapes`` + ``graft_ported_params``). The checkpoint
        is restored into ``abstract_params`` and ``graft_fn(state,
        flat_params)`` regroups it into the live state."""
        steps = sorted(self._mngr.all_steps(), reverse=True)
        if not steps:
            return state, 0
        for i, step in enumerate(steps):
            try:
                return self._restore_at(step, state, ported_restore)
            except Exception as e:  # noqa: BLE001 - orbax raises many types
                log.warning(
                    "checkpoint step %d unreadable (%s: %s); %s", step,
                    type(e).__name__, e,
                    "falling back to previous retained step"
                    if i + 1 < len(steps) else "no retained steps left")
        log.error(
            "no retained checkpoint under %r is restorable; starting from "
            "step 0 (corrupt artifacts left in place for inspection)",
            self._mngr.directory if hasattr(self._mngr, "directory") else "?")
        return state, 0

    def _restore_at(self, step: int, state, ported_restore=None):
        """Restore one specific step, negotiating the checkpoint shape
        (full TrainState → ported flat layout → params-only partial).
        Raises when the step is unreadable in every shape."""
        import orbax.checkpoint as ocp

        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, state)
        try:
            restored = self._mngr.restore(step, args=ocp.args.StandardRestore(abstract))
            log.info("resumed from checkpoint step %d", step)
            return restored, step
        except (ValueError, KeyError, TypeError):
            pass
        if ported_restore is not None:
            flat_abstract, graft_fn = ported_restore
            try:
                restored = self._mngr.restore(
                    step, args=ocp.args.StandardRestore({"params": flat_abstract}))
                log.info("grafted ported weights from checkpoint step %d", step)
                return graft_fn(state, restored["params"]), 0
            except (ValueError, KeyError, TypeError):
                pass  # not the flat-ported layout either; try partial
        partial = {"params": abstract.params}
        if getattr(state, "batch_stats", None) is not None:
            partial["batch_stats"] = abstract.batch_stats
        restored = self._mngr.restore(step, args=ocp.args.StandardRestore(partial))
        state = state.replace(params=restored["params"])
        if restored.get("batch_stats") is not None:
            state = state.replace(batch_stats=restored["batch_stats"])
        log.info("loaded ported weights from checkpoint step %d", step)
        return state, 0

    def maybe_save(self, step: int, state, force: bool = False) -> bool:
        """Save when ``step`` hits the cadence (async; returns immediately).

        ``M2KT_CKPT_SYNC=1`` blocks until the save commits — trades the
        async overlap for a guarantee that every step the goodput ledger
        reports as saved is actually durable (short runs on flaky
        capacity, CI fault drills); default async can lose the newest
        in-flight save to an abrupt death, falling back one cadence."""
        if not force and step % self.every:
            return False
        import orbax.checkpoint as ocp

        with _maybe_span("ckpt.save_submit", {"step": step}):
            self._mngr.save(step, args=ocp.args.StandardSave(state))
        if os.environ.get("M2KT_CKPT_SYNC", "0") == "1":
            with _maybe_span("ckpt.save_sync", {"step": step}):
                self._mngr.wait_until_finished()
        return True

    def wait(self) -> None:
        """Block until in-flight async saves commit. The last-chance
        preemption path and the fault-injection tests need the step
        durably on disk before the process may die."""
        with _maybe_span("ckpt.wait"):
            self._mngr.wait_until_finished()

    def install_exit_flush(self) -> None:
        """Guarantee in-flight async saves land on EVERY interpreter
        exit path that runs teardown — including ``sys.exit`` from an
        injected fault or a slice-loss death (exit code 83), which
        bypasses the training loop's normal ``close()``. Without this
        barrier an async save started one cadence before the death is
        silently dropped and the supervisor's restarted attempt resumes
        a cadence early. Best-effort by design: the process is dying, so
        a failed flush must not mask the original exit code. (SIGKILL
        still skips interpreter teardown — that loss is priced into the
        goodput ledger's ``lost`` category, not recoverable from inside.)

        Registered via ``threading._register_atexit``, NOT ``atexit``:
        orbax commits checkpoints through concurrent.futures executors,
        and CPython joins those executor threads in ``threading._shutdown``
        — which runs *before* atexit callbacks. An atexit-time flush
        finds the executors already shut down and the commit dies with
        "cannot schedule new futures after shutdown". Threading-atexit
        callbacks run LIFO before that teardown; this method is called
        after orbax's import registered its own handler, so the flush
        sees live executors."""
        import threading

        def _flush() -> None:
            try:
                self._mngr.wait_until_finished()
            except Exception:  # noqa: BLE001 - dying process, best effort
                pass

        register = getattr(threading, "_register_atexit", None)
        if register is None:  # pre-3.9 fallback: better late than never
            import atexit

            atexit.register(_flush)
        else:
            register(_flush)

    def close(self) -> None:
        """Block until in-flight async saves land, then release."""
        self._mngr.wait_until_finished()
        self._mngr.close()


def _flatten_paths(tree, prefix: str = "") -> dict:
    """``{"params/layer_0/.../kernel": leaf}`` for a plain-dict pytree —
    the path naming the bad-array diagnostics below use."""
    out = {}
    if isinstance(tree, dict):
        for key, child in tree.items():
            out.update(_flatten_paths(
                child, f"{prefix}/{key}" if prefix else str(key)))
    else:
        out[prefix] = tree
    return out


def _name_bad_arrays(mngr, step: int, abstract: dict) -> str:
    """Best-effort: which array(s) made ``step`` unrestorable? The
    checkpoint's own metadata (cheap — no array reads) is diffed against
    the tree the caller wants: a partial write is missing leaves, a
    stale/foreign checkpoint mismatches shapes. Empty string when the
    metadata itself is unreadable — the caller falls back to the raw
    restore error."""
    try:
        meta = mngr.item_metadata(step)
    except Exception:  # noqa: BLE001 - metadata as corrupt as the data
        return ""
    if not isinstance(meta, dict):
        meta = getattr(meta, "tree", None)
        if not isinstance(meta, dict):
            return ""
    want = _flatten_paths(abstract)
    have = _flatten_paths(meta)
    missing = sorted(set(want) - set(have))
    if missing:
        return (f"missing array(s) {missing[:3]}"
                + (f" (+{len(missing) - 3} more)" if len(missing) > 3
                   else ""))
    mismatched = sorted(
        path for path in want
        if tuple(getattr(have[path], "shape", None) or ())
        != tuple(want[path].shape))
    if mismatched:
        return (f"shape-mismatched array(s) {mismatched[:3]}"
                + (f" (+{len(mismatched) - 3} more)"
                   if len(mismatched) > 3 else ""))
    return ""


def restore_variables(ckpt_dir: str, variables: dict) -> dict:
    """Restore model weights into an inference ``variables`` pytree (the
    serving entrypoint has no TrainState — just the model's init output).

    Accepts the same checkpoint shapes the trainer writes: a full
    TrainState (its ``params`` leaf is grafted) or a params-only dict from
    ``port_weights.py``. Same corrupt-latest fallback as
    ``restore_or_init``: an unreadable newest step falls back to older
    retained steps. An *empty* checkpoint dir returns the fresh variables
    unchanged (first boot); but a dir that HAS retained steps none of
    which restore is a corrupted store, and serving randomly initialized
    weights behind a healthy /readyz would be silent garbage — that case
    raises a clean ``ValueError`` naming the bad array (same contract as
    ``KVHandoff.from_bytes``: damage surfaces as ValueError, never a raw
    numpy/zip/orbax error from a worker thread)."""
    import orbax.checkpoint as ocp

    try:
        mngr = _manager(ckpt_dir)
        steps = sorted(mngr.all_steps(), reverse=True)
    except Exception as err:  # noqa: BLE001 - orbax raises many types
        raise ValueError(
            f"checkpoint dir {ckpt_dir!r} is unreadable: "
            f"{type(err).__name__}: {err}") from err
    abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct,
                            {"params": variables["params"]})
    failures: list[tuple[int, str]] = []
    for step in steps:
        try:
            restored = mngr.restore(step, args=ocp.args.StandardRestore(abstract))
        except Exception as e:  # noqa: BLE001 - orbax raises many types
            detail = (_name_bad_arrays(mngr, step, abstract)
                      or f"{type(e).__name__}: {e}")
            failures.append((step, detail))
            log.warning("checkpoint step %d unreadable (%s)", step, detail)
            continue
        log.info("serving weights restored from checkpoint step %d", step)
        return {**variables, "params": restored["params"]}
    if steps:
        step, detail = failures[0]
        raise ValueError(
            f"no retained checkpoint under {ckpt_dir!r} is restorable; "
            f"newest step {step}: {detail}")
    return variables


def from_env(default_every: int = 100) -> CheckpointManager | None:
    """Build a manager from the env the TPU apiresources inject
    (M2KT_CKPT_DIR / M2KT_CKPT_EVERY); None when checkpointing is off."""
    ckpt_dir = os.environ.get("M2KT_CKPT_DIR", "")
    if not ckpt_dir:
        return None
    every = int(os.environ.get("M2KT_CKPT_EVERY", str(default_every)))
    return CheckpointManager(ckpt_dir, every=every)
