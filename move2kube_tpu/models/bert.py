"""BERT encoder in Flax, TPU-first.

Emission target for detected HF BERT fine-tunes over torch.distributed/NCCL
(BASELINE config 3: "HF BERT-base fine-tune -> v5e-8 JobSet").

TPU notes: bfloat16 activations, float32 layernorm/softmax accumulation,
fused QKV projection (one MXU matmul instead of three), sequence lengths
padded to multiples of 128 to match lane tiling.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class BertSelfAttention(nn.Module):
    num_heads: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, mask=None):
        d_model = x.shape[-1]
        head_dim = d_model // self.num_heads
        qkv = nn.Dense(3 * d_model, dtype=self.dtype, name="qkv")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(*t.shape[:-1], self.num_heads, head_dim)

        q, k, v = heads(q), heads(k), heads(v)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
        s = s * (head_dim ** -0.5)
        if mask is not None:
            s = jnp.where(mask[:, None, None, :], s, -1e30)
        p = nn.softmax(s, axis=-1).astype(self.dtype)
        o = jnp.einsum("bhqk,bkhd->bqhd", p, v)
        o = o.reshape(*o.shape[:-2], d_model)
        return nn.Dense(d_model, dtype=self.dtype, name="out")(o)


class BertLayer(nn.Module):
    num_heads: int
    mlp_dim: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, mask=None):
        y = BertSelfAttention(self.num_heads, dtype=self.dtype)(x, mask)
        x = nn.LayerNorm(dtype=jnp.float32)(x + y)
        y = nn.Dense(self.mlp_dim, dtype=self.dtype)(x)
        y = nn.gelu(y)
        y = nn.Dense(x.shape[-1], dtype=self.dtype)(y)
        return nn.LayerNorm(dtype=jnp.float32)(x + y)


class BertEncoder(nn.Module):
    vocab_size: int = 30522
    num_layers: int = 12
    num_heads: int = 12
    d_model: int = 768
    mlp_dim: int = 3072
    max_len: int = 512
    num_classes: int = 2  # sequence classification head (fine-tune target)
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None):
        b, s = input_ids.shape
        tok = nn.Embed(self.vocab_size, self.d_model, dtype=self.dtype,
                       name="tok_embed")(input_ids)
        pos = nn.Embed(self.max_len, self.d_model, dtype=self.dtype,
                       name="pos_embed")(jnp.arange(s)[None, :])
        # segment embedding always participates (HF semantics: absent
        # token_type_ids mean segment 0, whose embedding is learned) — and
        # the param tree must not depend on which inputs were passed
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        seg = nn.Embed(2, self.d_model, dtype=self.dtype,
                       name="seg_embed")(token_type_ids)
        x = nn.LayerNorm(dtype=jnp.float32)(tok + pos + seg)
        mask = attention_mask if attention_mask is not None else jnp.ones((b, s), bool)
        for _ in range(self.num_layers):
            x = BertLayer(self.num_heads, self.mlp_dim, dtype=self.dtype)(x, mask)
        cls = x[:, 0]
        pooled = jnp.tanh(nn.Dense(self.d_model, dtype=jnp.float32,
                                   name="pooler")(cls.astype(jnp.float32)))
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="classifier")(pooled)


def bert_base(num_classes: int = 2, dtype=jnp.bfloat16) -> BertEncoder:
    return BertEncoder(num_classes=num_classes, dtype=dtype)


def bert_tiny(num_classes: int = 2, dtype=jnp.bfloat16) -> BertEncoder:
    """Small variant for tests/dry-runs."""
    return BertEncoder(vocab_size=1024, num_layers=2, num_heads=2, d_model=64,
                       mlp_dim=128, max_len=128, num_classes=num_classes,
                       dtype=dtype)
