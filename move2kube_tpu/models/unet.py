"""Diffusion UNet (DDPM-style noise predictor) in Flax, TPU-first.

Emission target for detected diffusion training workloads (gpu_detect
family ``unet``: diffusers / DDPM / stable-diffusion-style scripts, see
reference parity note in containerizer/jax_emit.py). Round-3 verdict
item: the family used to be detected but unemittable, silently falling
back to the generic MLP scaffold.

Architecture: classic DDPM UNet — sinusoidal timestep embedding through
a 2-layer MLP; a down path of residual conv blocks with
timestep-conditioned shifts and strided-conv downsampling; a bottleneck
with global self-attention over spatial tokens; an up path with skip
concatenation and nearest-neighbor upsampling. Predicts the added noise.

TPU notes: NHWC layout (XLA's native conv layout on TPU), bfloat16 conv
compute with float32 GroupNorm (stability), attention tokens go through
jnp einsum (spatial seq lengths at the bottleneck are small, 64-256 —
below the Pallas kernel's tile-friendly threshold, XLA fuses fine).
Channel dims stay multiples of 128 at the bottleneck so the MXU tiles
convs-as-matmuls without padding.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class UNetConfig:
    in_channels: int = 3
    base_channels: int = 128
    channel_mults: tuple = (1, 2, 2)
    num_res_blocks: int = 2
    time_dim: int = 512
    norm_groups: int = 32
    dtype: Any = jnp.bfloat16


def unet_small() -> UNetConfig:
    """CIFAR-scale DDPM UNet (~35M params)."""
    return UNetConfig()


def unet_tiny() -> UNetConfig:
    """Small variant for tests / dry-runs."""
    return UNetConfig(base_channels=16, channel_mults=(1, 2),
                      num_res_blocks=1, time_dim=32, norm_groups=4)


def timestep_embedding(t, dim: int, max_period: float = 10000.0):
    """Sinusoidal embeddings ([b] int32 -> [b, dim]), float32."""
    half = dim // 2
    freqs = jnp.exp(-jnp.log(max_period)
                    * jnp.arange(half, dtype=jnp.float32) / half)
    args = t.astype(jnp.float32)[:, None] * freqs[None, :]
    return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)


class ResBlock(nn.Module):
    """GroupNorm -> SiLU -> Conv, twice, with a timestep-conditioned shift
    between; identity (or 1x1-projected) residual."""

    channels: int
    cfg: UNetConfig

    @nn.compact
    def __call__(self, x, temb):
        cfg = self.cfg
        groups = min(cfg.norm_groups, self.channels)
        h = nn.GroupNorm(num_groups=min(groups, x.shape[-1]),
                         dtype=jnp.float32, name="norm1")(x)
        h = nn.silu(h)
        h = nn.Conv(self.channels, (3, 3), padding="SAME", dtype=cfg.dtype,
                    name="conv1")(h.astype(cfg.dtype))
        shift = nn.Dense(self.channels, dtype=cfg.dtype,
                         name="time_proj")(nn.silu(temb).astype(cfg.dtype))
        h = h + shift[:, None, None, :]
        h = nn.GroupNorm(num_groups=groups, dtype=jnp.float32,
                         name="norm2")(h)
        h = nn.silu(h)
        h = nn.Conv(self.channels, (3, 3), padding="SAME", dtype=cfg.dtype,
                    name="conv2")(h.astype(cfg.dtype))
        if x.shape[-1] != self.channels:
            x = nn.Conv(self.channels, (1, 1), dtype=cfg.dtype,
                        name="skip_proj")(x.astype(cfg.dtype))
        return x + h


class SpatialAttention(nn.Module):
    """Single-head global self-attention over flattened spatial tokens
    (bottleneck resolution only), computed in float32."""

    cfg: UNetConfig

    @nn.compact
    def __call__(self, x):
        b, hh, ww, c = x.shape
        groups = min(self.cfg.norm_groups, c)
        h = nn.GroupNorm(num_groups=groups, dtype=jnp.float32,
                         name="norm")(x)
        tokens = h.reshape(b, hh * ww, c)
        qkv = nn.Dense(3 * c, dtype=jnp.float32, name="qkv")(tokens)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        s = jnp.einsum("bqc,bkc->bqk", q, k) * (c ** -0.5)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bqk,bkc->bqc", p, v)
        o = nn.Dense(c, dtype=self.cfg.dtype, name="out")(
            o.astype(self.cfg.dtype))
        return x + o.reshape(b, hh, ww, c)


class UNet(nn.Module):
    """x: [b, H, W, C] noisy images, t: [b] int32 timesteps -> predicted
    noise [b, H, W, C]."""

    cfg: UNetConfig

    @nn.compact
    def __call__(self, x, t):
        cfg = self.cfg
        temb = timestep_embedding(t, cfg.time_dim)
        temb = nn.Dense(cfg.time_dim, dtype=jnp.float32, name="time_mlp1")(temb)
        temb = nn.Dense(cfg.time_dim, dtype=jnp.float32,
                        name="time_mlp2")(nn.silu(temb))

        h = nn.Conv(cfg.base_channels, (3, 3), padding="SAME",
                    dtype=cfg.dtype, name="conv_in")(x.astype(cfg.dtype))
        skips = [h]
        # down path
        for li, mult in enumerate(cfg.channel_mults):
            ch = cfg.base_channels * mult
            for bi in range(cfg.num_res_blocks):
                h = ResBlock(ch, cfg, name=f"down_{li}_{bi}")(h, temb)
                skips.append(h)
            if li != len(cfg.channel_mults) - 1:
                h = nn.Conv(ch, (3, 3), strides=(2, 2), padding="SAME",
                            dtype=cfg.dtype, name=f"down_{li}_pool")(h)
                skips.append(h)
        # bottleneck
        mid_ch = cfg.base_channels * cfg.channel_mults[-1]
        h = ResBlock(mid_ch, cfg, name="mid_1")(h, temb)
        h = SpatialAttention(cfg, name="mid_attn")(h)
        h = ResBlock(mid_ch, cfg, name="mid_2")(h, temb)
        # up path (mirror, consuming skips)
        for li, mult in reversed(list(enumerate(cfg.channel_mults))):
            ch = cfg.base_channels * mult
            for bi in range(cfg.num_res_blocks + 1):
                h = jnp.concatenate([h, skips.pop()], axis=-1)
                h = ResBlock(ch, cfg, name=f"up_{li}_{bi}")(h, temb)
            if li != 0:
                b, hh, ww, c = h.shape
                h = jax.image.resize(h, (b, hh * 2, ww * 2, c), "nearest")
                h = nn.Conv(c, (3, 3), padding="SAME", dtype=cfg.dtype,
                            name=f"up_{li}_unpool")(h)
        assert not skips
        h = nn.GroupNorm(num_groups=min(cfg.norm_groups, h.shape[-1]),
                         dtype=jnp.float32, name="norm_out")(h)
        h = nn.silu(h)
        return nn.Conv(cfg.in_channels, (3, 3), padding="SAME",
                       dtype=jnp.float32, name="conv_out")(h)


def ddpm_alpha_bars(num_steps: int = 1000, beta_start: float = 1e-4,
                    beta_end: float = 0.02):
    """Cumulative noise schedule (linear betas, DDPM defaults)."""
    betas = jnp.linspace(beta_start, beta_end, num_steps, dtype=jnp.float32)
    return jnp.cumprod(1.0 - betas)
