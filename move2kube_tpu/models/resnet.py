"""ResNet-50 in Flax, TPU-first.

Emission target for detected torchvision/CUDA ResNet training scripts
(BASELINE config 2: "PyTorch ResNet-50 CUDA train.py -> jax-xla
containerizer, single v5e chip").

TPU notes: NHWC layout (XLA's native conv layout on TPU), bfloat16 compute
with float32 params/accumulation. BatchNorm computes in the MODEL dtype
(the public Flax imagenet recipe): at bf16 this keeps the BN+ReLU chain
fused into the convs without f32 round-trips on the activation path —
ResNet-50 is HBM-bound, so those casts cost real throughput (bench.py's
hand-ported comparator uses the same recipe; f32-dtype instantiations,
e.g. ported-weight parity tests, still get f32 BN). Convs lower onto the
MXU.
"""

from __future__ import annotations

from dataclasses import field
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp


class BottleneckBlock(nn.Module):
    features: int
    strides: int = 1
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        norm = lambda: nn.BatchNorm(  # noqa: E731
            use_running_average=not train, momentum=0.9, epsilon=1e-5,
            dtype=self.dtype,
        )
        residual = x
        y = nn.Conv(self.features, (1, 1), use_bias=False, dtype=self.dtype)(x)
        y = norm()(y)
        y = nn.relu(y)
        y = nn.Conv(self.features, (3, 3), strides=(self.strides, self.strides),
                    padding="SAME", use_bias=False, dtype=self.dtype)(y)
        y = norm()(y)
        y = nn.relu(y)
        y = nn.Conv(self.features * 4, (1, 1), use_bias=False, dtype=self.dtype)(y)
        y = norm()(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.features * 4, (1, 1),
                               strides=(self.strides, self.strides),
                               use_bias=False, dtype=self.dtype)(residual)
            residual = norm()(residual)
        return nn.relu(y + residual.astype(y.dtype))


class ResNet(nn.Module):
    stage_sizes: Sequence[int] = field(default_factory=lambda: [3, 4, 6, 3])
    num_classes: int = 1000
    width: int = 64
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        x = nn.Conv(self.width, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)],
                    use_bias=False, dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-5, dtype=self.dtype)(x)
        x = nn.relu(x)
        # explicit symmetric pad (torch maxpool pad=1); SAME would pad
        # asymmetrically and diverge from ported torchvision weights
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                strides = 2 if i > 0 and j == 0 else 1
                x = BottleneckBlock(self.width * 2 ** i, strides=strides,
                                    dtype=self.dtype)(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x


def resnet50(num_classes: int = 1000, dtype=jnp.bfloat16) -> ResNet:
    return ResNet(stage_sizes=[3, 4, 6, 3], num_classes=num_classes, dtype=dtype)


def resnet18_ish(num_classes: int = 1000, dtype=jnp.bfloat16) -> ResNet:
    """Small variant for tests/dry-runs (still bottleneck blocks)."""
    return ResNet(stage_sizes=[1, 1, 1, 1], width=16, num_classes=num_classes,
                  dtype=dtype)
