"""Mixture-of-Experts MLP with expert parallelism, GSPMD style.

Emission target for detected DeepSpeed-MoE / Megatron ``--num-experts``
workloads (gpu_detect reports ``ep``). The GPU pattern — an expert-parallel
process group doing explicit all-to-all token exchange — becomes pure
sharding here: expert weights carry an ``experts -> expert`` mesh-axis
annotation and dispatch/combine are einsums against a one-hot routing
tensor, so XLA inserts the all-to-alls on the ``expert`` axis (GShard
recipe). No hand-written collectives; the same code runs unsharded on one
chip.

Router: top-k gating (Switch/GShard): softmax router probs, per-expert
capacity ``ceil(T/E * capacity_factor * k)``, tokens over capacity are
dropped (residual passes through), load-balancing aux loss returned for
the trainer to add.
"""

from __future__ import annotations

import math
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from move2kube_tpu.parallel.sharding import maybe_shard as _maybe_shard


def top_k_routing(router_logits, num_experts: int, top_k: int, capacity: int):
    """-> (dispatch [T,E,C] float, combine [T,E,C] float, aux_loss scalar).

    Token t is routed to its top-k experts; position within each expert's
    queue comes from a cumulative count; tokens beyond ``capacity`` drop.
    """
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)  # [T,E]
    topk_p, topk_idx = jax.lax.top_k(probs, top_k)                      # [T,k]
    topk_p = topk_p / jnp.maximum(topk_p.sum(-1, keepdims=True), 1e-9)
    gates = jnp.zeros_like(probs)
    for j in range(top_k):  # k is tiny (1-2); unrolled, stays static
        gates = gates + jax.nn.one_hot(topk_idx[:, j], num_experts) * topk_p[:, j:j + 1]
    mask = gates > 0                                                    # [T,E]
    position = jnp.cumsum(mask, axis=0) - 1                             # [T,E]
    keep = mask & (position < capacity)
    dispatch = jax.nn.one_hot(
        jnp.where(keep, position, capacity), capacity + 1,
        dtype=jnp.float32)[..., :capacity]                              # [T,E,C]
    combine = dispatch * gates[..., None].astype(jnp.float32)
    # GShard aux loss: E * mean_fraction_routed . mean_router_prob
    frac_tokens = jnp.mean(mask.astype(jnp.float32), axis=0)
    frac_probs = jnp.mean(probs, axis=0)
    aux = num_experts * jnp.sum(frac_tokens * frac_probs)
    return dispatch, combine, aux


class MoEMlp(nn.Module):
    """Drop-in MLP replacement: ``(x [b,s,d]) -> (y [b,s,d], aux_loss)``."""

    num_experts: int
    mlp_dim: int
    top_k: int = 2
    capacity_factor: float = 1.25
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        b, s, d = x.shape
        tokens = b * s
        e = self.num_experts
        capacity = max(self.top_k, math.ceil(
            tokens * self.capacity_factor * self.top_k / e))
        xt = x.reshape(tokens, d)

        router = nn.Dense(e, use_bias=False, dtype=jnp.float32, name="router")
        dispatch, combine, aux = top_k_routing(router(xt), e, self.top_k, capacity)

        # expert weights: [E, d, m] / [E, m, d]. No in-module weight
        # constraints: the canonical layout (experts->expert, d->fsdp,
        # m->tensor) comes from TrainState via infer_param_axes, and a
        # conflicting constraint here would force a reshard every step.
        w_in = self.param("w_in", nn.initializers.lecun_normal(),
                          (e, d, self.mlp_dim))
        w_gate = self.param("w_gate", nn.initializers.lecun_normal(),
                            (e, d, self.mlp_dim))
        w_out = self.param("w_out", nn.initializers.lecun_normal(),
                           (e, self.mlp_dim, d))

        # dispatch: [T,E,C] x [T,d] -> [E,C,d]  (XLA: all-to-all on expert)
        xe = jnp.einsum("tec,td->ecd", dispatch.astype(self.dtype),
                        xt.astype(self.dtype))
        xe = _maybe_shard(xe, P("expert", None, None))
        h = jnp.einsum("ecd,edm->ecm", xe, w_in.astype(self.dtype))
        g = jnp.einsum("ecd,edm->ecm", xe, w_gate.astype(self.dtype))
        h = nn.silu(g) * h
        ye = jnp.einsum("ecm,emd->ecd", h, w_out.astype(self.dtype))
        ye = _maybe_shard(ye, P("expert", None, None))
        # combine: [T,E,C] x [E,C,d] -> [T,d]
        yt = jnp.einsum("tec,ecd->td", combine.astype(self.dtype), ye)
        return yt.reshape(b, s, d).astype(x.dtype), aux
