"""Pipeline-parallel (staged) GPT-2 training over the ``pipe`` mesh axis.

VERDICT r4 #7: detected Megatron pipeline parallelism on GPT sources now
maps to a true GPT-2 staged trainer instead of the Llama-class one, so
``port_weights.py`` checkpoints and the architecture stay faithful.

Same compiled-GPipe design as models/llama_pipe.py (reference behavior:
Megatron ``core/pipeline_parallel/schedules.py`` partitions GPT layers
across ranks and pushes microbatches over NCCL p2p; here the schedule is
compiled via parallel/pipeline.py ppermute hops):

- token + position embeddings, final LayerNorm and the tied LM head run
  outside the pipeline, replicated over ``pipe``;
- the transformer blocks split into ``num_stages`` equal stages whose
  params carry a leading ``[P, ...]`` axis sharded over ``pipe``;
- microbatches flow stage-to-stage via ICI neighbour ``ppermute``.
"""

from __future__ import annotations

import dataclasses
import functools

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from move2kube_tpu.models.gpt2 import GPT2, GPT2Block, GPT2Config
from move2kube_tpu.models.train import TrainState, _mesh_context, _with_mesh, lm_loss
from move2kube_tpu.parallel.pipeline import pipeline_sharded, stack_stage_params

BATCH_AXES = ("data", "fsdp")


def _check_cfg(cfg: GPT2Config, num_stages: int) -> None:
    if cfg.num_layers % num_stages:
        raise ValueError(
            f"num_layers={cfg.num_layers} must divide evenly into "
            f"{num_stages} pipeline stages")


def _regroup_stages(params: dict, num_layers: int, num_stages: int):
    """[h_0..h_{L-1}] -> stacked [P, ...] trees of block_0..block_{k-1}."""
    lps = num_layers // num_stages
    return stack_stage_params([
        {f"block_{j}": params[f"h_{s * lps + j}"] for j in range(lps)}
        for s in range(num_stages)
    ])


def init_pipeline_gpt2_params(rng, cfg: GPT2Config, num_stages: int,
                              sample_ids) -> dict:
    """Init the full GPT-2 once, regroup its blocks into staged params:
    {"wte", "wpe", "stages" [P, ...], "ln_f"} (the LM head is tied to
    wte, so there is no separate head tree)."""
    _check_cfg(cfg, num_stages)
    variables = GPT2(cfg).init(rng, sample_ids)
    p = dict(variables["params"])
    return {
        "wte": p["wte"],
        "wpe": p["wpe"],
        "stages": _regroup_stages(p, cfg.num_layers, num_stages),
        "ln_f": p["ln_f"],
    }


def pipeline_param_shardings(params_or_shapes, mesh: Mesh) -> dict:
    """Stage params shard over ``pipe`` on their leading axis; the
    embeddings/norm are replicated (pipe meshes keep tensor=1)."""
    return {
        k: jax.tree.map(
            lambda _: NamedSharding(mesh, P("pipe") if k == "stages" else P()),
            v)
        for k, v in params_or_shapes.items()
    }


def create_pipeline_gpt2_state(rng, cfg: GPT2Config, num_stages: int,
                               sample_ids, tx: optax.GradientTransformation,
                               mesh: Mesh) -> TrainState:
    """Sharded-init a pipeline TrainState (same jit/out_shardings recipe
    as train.create_sharded_state, with the staged layout above)."""
    init_fn = functools.partial(init_pipeline_gpt2_params, cfg=cfg,
                                num_stages=num_stages, sample_ids=sample_ids)
    with _mesh_context(mesh):
        shapes = jax.eval_shape(init_fn, rng)
        out_shardings = pipeline_param_shardings(shapes, mesh)
        params = jax.jit(init_fn, out_shardings=out_shardings)(rng)
    return TrainState.create(apply_fn=None, params=params, tx=tx)


def graft_ported_params(state: TrainState, flat_params: dict,
                        cfg: GPT2Config, num_stages: int,
                        mesh: Mesh) -> TrainState:
    """Regroup a ported flat GPT-2 param tree (port_weights.py layout:
    ``wte``/``wpe``/``h_i``/``ln_f``) into the staged pipeline layout and
    graft it into ``state`` with the pipe shardings — the adapter
    ``CheckpointManager.restore_or_init`` needs so a real
    GPT2LMHeadModel checkpoint resumes on the pipeline path."""
    staged = {
        "wte": flat_params["wte"],
        "wpe": flat_params["wpe"],
        "stages": _regroup_stages(flat_params, cfg.num_layers, num_stages),
        "ln_f": flat_params["ln_f"],
    }
    staged = jax.device_put(staged, pipeline_param_shardings(staged, mesh))
    return state.replace(params=staged)


def flat_param_shapes(cfg: GPT2Config):
    """Abstract flat GPT-2 param tree (the ported-checkpoint layout)."""
    return jax.eval_shape(
        lambda r: GPT2(cfg).init(r, jnp.zeros((1, 8), jnp.int32))["params"],
        jax.random.PRNGKey(0))


def apply_pipeline_gpt2(cfg: GPT2Config, num_stages: int, mesh: Mesh, params,
                        input_ids, *, num_microbatches: int,
                        remat: bool = True):
    """Forward: embed -> compiled GPipe over the blocks -> ln_f + tied
    head. ``input_ids`` [batch, seq]; returns [batch, seq, vocab] f32."""
    _check_cfg(cfg, num_stages)
    lps = cfg.num_layers // num_stages
    # activation-sharding constraints are invalid inside shard_map (the
    # mesh axes there are manual); the pipe wrapper specs shard the batch
    block_cfg = dataclasses.replace(cfg, shard_activations=False)

    b, s = input_ids.shape
    wte = nn.Embed(cfg.vocab_size, cfg.d_model, dtype=cfg.dtype)
    x = wte.apply({"params": params["wte"]}, input_ids)
    positions = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
    x = x + nn.Embed(cfg.n_positions, cfg.d_model, dtype=cfg.dtype).apply(
        {"params": params["wpe"]}, positions)

    def stage_fn(p, x):
        for j in range(lps):
            x = GPT2Block(block_cfg).apply({"params": p[f"block_{j}"]}, x)
        return x

    if remat:
        stage_fn = jax.checkpoint(stage_fn)
    x = pipeline_sharded(mesh, stage_fn, params["stages"], x,
                         num_microbatches=num_microbatches,
                         batch_axes=BATCH_AXES)
    x = nn.LayerNorm(epsilon=cfg.norm_eps, dtype=jnp.float32).apply(
        {"params": params["ln_f"]}, x)
    # LM head tied to the token embedding (HF GPT2LMHeadModel ties)
    embedding = params["wte"]["embedding"].astype(jnp.float32)
    return x.astype(jnp.float32) @ embedding.T


def make_pipeline_gpt2_train_step(cfg: GPT2Config, num_stages: int,
                                  mesh: Mesh, *, num_microbatches: int,
                                  remat: bool = True):
    """Next-token-prediction train step through the compiled pipeline."""

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(state: TrainState, batch: dict):
        ids = jax.lax.with_sharding_constraint(
            batch["input_ids"], NamedSharding(mesh, P(BATCH_AXES)))

        def loss_fn(params):
            logits = apply_pipeline_gpt2(
                cfg, num_stages, mesh, params, ids,
                num_microbatches=num_microbatches, remat=remat)
            return lm_loss(logits, ids)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        return state.apply_gradients(grads=grads), loss

    return _with_mesh(mesh, step)
