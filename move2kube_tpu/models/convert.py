"""Torch/HF state_dict -> model-zoo Flax params (weight porting).

The translated workloads rarely train from scratch — the BERT BASELINE
config is a *fine-tune*, which only makes sense starting from pretrained
GPU-side weights. These converters map a HuggingFace/torchvision
``state_dict`` (tensors or numpy arrays; torch never required) onto the
param trees of models/{bert,llama,resnet}.py, handling the TPU-first
layout differences:

- torch ``Linear`` stores ``[out, in]`` -> flax kernel ``[in, out]``
- separate q/k/v (and gate/up) projections -> our fused MXU-friendly
  ``qkv`` / ``gate_up`` kernels (concatenated along the out dim)
- torch conv ``OIHW`` -> flax ``HWIO``

Verified by tests/test_convert.py: logits of the converted Flax model
match the torch model's on the same inputs.
"""

from __future__ import annotations

import re

import numpy as np

_LAYER_RES = {
    # regex, not fixed split positions: keys may be bare
    # ('encoder.layer.0...', 'layers.0...') or prefixed
    # ('bert.encoder.layer.0...', 'model.layers.0...')
    "bert": re.compile(r"(?:^|\.)encoder\.layer\.(\d+)\."),
    "llama": re.compile(r"(?:^|\.)layers\.(\d+)\."),
    # family 'gpt' (Megatron-style, ported via the llama converter) must
    # NOT match HF GPT-2's 'h.N' keys: a clear "no layer keys" ValueError
    # beats a KeyError deep inside llama_params_from_torch; HF GPT-2
    # checkpoints go through the 'gpt2' entry
    "gpt": re.compile(r"(?:^|\.)layers\.(\d+)\."),
    "gpt2": re.compile(r"(?:^|\.)h\.(\d+)\."),
}


def infer_num_layers(state_dict: dict, family: str) -> int:
    """Count transformer blocks in a torch/HF state_dict by key pattern."""
    pat = _LAYER_RES.get(family)
    if pat is None:
        raise ValueError(f"no layer pattern for model family {family!r}")
    ids = [int(m.group(1)) for k in state_dict if (m := pat.search(k))]
    if not ids:
        raise ValueError(
            f"no {family!r} layer keys found in state_dict "
            f"(looked for {pat.pattern!r})")
    return 1 + max(ids)


def _np(t) -> np.ndarray:
    if isinstance(t, np.ndarray):
        return t
    detach = getattr(t, "detach", None)
    if detach is not None:
        return detach().cpu().numpy()
    return np.asarray(t)


def _linear(sd: dict, prefix: str) -> dict:
    """torch Linear -> flax Dense dict (kernel transposed; bias optional)."""
    out = {"kernel": _np(sd[prefix + ".weight"]).T}
    if prefix + ".bias" in sd:
        out["bias"] = _np(sd[prefix + ".bias"])
    return out


def _layernorm(sd: dict, prefix: str) -> dict:
    return {"scale": _np(sd[prefix + ".weight"]),
            "bias": _np(sd[prefix + ".bias"])}


def bert_params_from_torch(state_dict: dict, num_layers: int) -> dict:
    """HF ``BertForSequenceClassification`` (or bare ``BertModel``)
    state_dict -> models/bert.py BertEncoder params."""
    sd = dict(state_dict)
    # bare BertModel checkpoints lack the "bert." prefix
    pre = "bert." if any(k.startswith("bert.") for k in sd) else ""
    emb = pre + "embeddings."
    params: dict = {
        "tok_embed": {"embedding": _np(sd[emb + "word_embeddings.weight"])},
        "pos_embed": {"embedding": _np(sd[emb + "position_embeddings.weight"])},
        "seg_embed": {"embedding": _np(sd[emb + "token_type_embeddings.weight"])},
        "LayerNorm_0": _layernorm(sd, emb + "LayerNorm"),
    }
    for i in range(num_layers):
        lp = f"{pre}encoder.layer.{i}."
        q = _linear(sd, lp + "attention.self.query")
        k = _linear(sd, lp + "attention.self.key")
        v = _linear(sd, lp + "attention.self.value")
        params[f"BertLayer_{i}"] = {
            "BertSelfAttention_0": {
                "qkv": {
                    "kernel": np.concatenate(
                        [q["kernel"], k["kernel"], v["kernel"]], axis=1),
                    "bias": np.concatenate([q["bias"], k["bias"], v["bias"]]),
                },
                "out": _linear(sd, lp + "attention.output.dense"),
            },
            "LayerNorm_0": _layernorm(sd, lp + "attention.output.LayerNorm"),
            "Dense_0": _linear(sd, lp + "intermediate.dense"),
            "Dense_1": _linear(sd, lp + "output.dense"),
            "LayerNorm_1": _layernorm(sd, lp + "output.LayerNorm"),
        }
    if pre + "pooler.dense.weight" in sd:
        params["pooler"] = _linear(sd, pre + "pooler.dense")
    if "classifier.weight" in sd:
        params["classifier"] = _linear(sd, "classifier")
    return params


def llama_params_from_torch(state_dict: dict, num_layers: int) -> dict:
    """HF ``LlamaForCausalLM`` (or bare ``LlamaModel``) state_dict ->
    models/llama.py Llama params."""
    sd = dict(state_dict)
    pre = "model." if any(k.startswith("model.") for k in sd) else ""
    params: dict = {
        "embed": {"embedding": _np(sd[pre + "embed_tokens.weight"])},
        "final_norm": {"scale": _np(sd[pre + "norm.weight"])},
    }
    for i in range(num_layers):
        lp = f"{pre}layers.{i}."
        qk = _np(sd[lp + "self_attn.q_proj.weight"]).T
        kk = _np(sd[lp + "self_attn.k_proj.weight"]).T
        vk = _np(sd[lp + "self_attn.v_proj.weight"]).T
        gk = _np(sd[lp + "mlp.gate_proj.weight"]).T
        uk = _np(sd[lp + "mlp.up_proj.weight"]).T
        params[f"layer_{i}"] = {
            "attn_norm": {"scale": _np(sd[lp + "input_layernorm.weight"])},
            "qkv": {"kernel": np.concatenate([qk, kk, vk], axis=1)},
            "attn_out": {"kernel": _np(sd[lp + "self_attn.o_proj.weight"]).T},
            "mlp_norm": {"scale": _np(sd[lp + "post_attention_layernorm.weight"])},
            "gate_up": {"kernel": np.concatenate([gk, uk], axis=1)},
            "down": {"kernel": _np(sd[lp + "mlp.down_proj.weight"]).T},
        }
    if "lm_head.weight" in sd:
        params["lm_head"] = {"kernel": _np(sd["lm_head.weight"]).T}
    elif pre + "embed_tokens.weight" in sd:  # tied embeddings
        params["lm_head"] = {"kernel": _np(sd[pre + "embed_tokens.weight"]).T}
    return params


def gpt2_params_from_torch(state_dict: dict, num_layers: int) -> dict:
    """HF ``GPT2LMHeadModel`` (or bare ``GPT2Model``) state_dict ->
    models/gpt2.py GPT2 params.

    HF GPT-2 uses Conv1D modules storing weights ``[in, out]`` — the SAME
    orientation as a flax Dense kernel, so unlike Linear they are NOT
    transposed. The LM head is tied to wte in both models, so no separate
    head tensor is ported."""
    sd = dict(state_dict)
    pre = ("transformer."
           if any(k.startswith("transformer.") for k in sd) else "")

    def conv1d(prefix: str) -> dict:
        return {"kernel": _np(sd[prefix + ".weight"]),
                "bias": _np(sd[prefix + ".bias"])}

    params: dict = {
        "wte": {"embedding": _np(sd[pre + "wte.weight"])},
        "wpe": {"embedding": _np(sd[pre + "wpe.weight"])},
        "ln_f": _layernorm(sd, pre + "ln_f"),
    }
    for i in range(num_layers):
        lp = f"{pre}h.{i}."
        params[f"h_{i}"] = {
            "ln_1": _layernorm(sd, lp + "ln_1"),
            "c_attn": conv1d(lp + "attn.c_attn"),
            "attn_out": conv1d(lp + "attn.c_proj"),
            "ln_2": _layernorm(sd, lp + "ln_2"),
            "c_fc": conv1d(lp + "mlp.c_fc"),
            "mlp_out": conv1d(lp + "mlp.c_proj"),
        }
    return params


def resnet_params_from_torch(state_dict: dict) -> tuple[dict, dict]:
    """torchvision ``resnet50`` state_dict -> (params, batch_stats) for
    models/resnet.py (conv OIHW -> HWIO; BN split into scale/bias vs
    running mean/var collections)."""
    sd = {k: _np(v) for k, v in state_dict.items()
          if not k.endswith("num_batches_tracked")}
    params: dict = {}
    stats: dict = {}

    def put(tree: dict, path: list[str], leaf):
        node = tree
        for p in path[:-1]:
            node = node.setdefault(p, {})
        node[path[-1]] = leaf

    def conv(name: str) -> dict:
        return {"kernel": sd[name + ".weight"].transpose(2, 3, 1, 0)}

    def bn(dst: list[str], name: str) -> None:
        put(params, dst + ["scale"], sd[name + ".weight"])
        put(params, dst + ["bias"], sd[name + ".bias"])
        put(stats, dst + ["mean"], sd[name + ".running_mean"])
        put(stats, dst + ["var"], sd[name + ".running_var"])

    put(params, ["Conv_0"], conv("conv1"))
    bn(["BatchNorm_0"], "bn1")
    sizes = {1: 3, 2: 4, 3: 6, 4: 3}  # resnet50 blocks per stage
    block = 0
    for stage in range(1, 5):
        for unit in range(sizes[stage]):
            tp = f"layer{stage}.{unit}"
            fp = f"BottleneckBlock_{block}"
            # flax auto-naming inside the block: Conv_0..2/BatchNorm_0..2
            # for the main path, Conv_3/BatchNorm_3 for the projection
            for j in (1, 2, 3):
                put(params, [fp, f"Conv_{j-1}"], conv(f"{tp}.conv{j}"))
                bn([fp, f"BatchNorm_{j-1}"], f"{tp}.bn{j}")
            if f"{tp}.downsample.0.weight" in sd:
                put(params, [fp, "Conv_3"], conv(f"{tp}.downsample.0"))
                bn([fp, "BatchNorm_3"], f"{tp}.downsample.1")
            block += 1
    if "fc.weight" in sd:
        params["Dense_0"] = {"kernel": sd["fc.weight"].T, "bias": sd["fc.bias"]}
    return params, stats
