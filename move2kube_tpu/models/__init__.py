"""JAX/Flax model zoo: the curated TPU programs the jax-xla containerizer
emits for detected GPU training workloads (BASELINE configs 2/3/5), and the
flagship models for bench.py / __graft_entry__.py.

Dependency-light on purpose (jax / flax / optax / numpy only): this package
is vendored verbatim into emitted training images (containerizer/jax_emit.py).

Families map detected workloads to curated programs (SURVEY.md §7 "template
zoo" approach — mirror of how the reference containerizes via curated
per-stack templates rather than general build inference):

- ``resnet``  — torchvision ResNet-50 CUDA scripts -> models.resnet
- ``bert``    — HF BERT fine-tunes (torch.distributed/NCCL) -> models.bert
- ``llama``/``gpt`` — DeepSpeed ZeRO-3 decoder LMs -> models.llama (FSDP+TP)
- ``generic`` — unrecognised: MLP scaffold the user fills in
"""

from move2kube_tpu.models import bert, llama, resnet, train  # noqa: F401
