"""Llama-class decoder-only LM in Flax, TPU-first.

Emission target for detected DeepSpeed / Megatron decoder-LM training
(BASELINE config 5: "DeepSpeed Llama-3-8B ZeRO-3 -> multi-host v5p-64
JobSet + ICI allreduce"). ZeRO-3 maps to the ``fsdp`` mesh axis, Megatron
TP to ``tensor``, context parallelism to ``seq`` (parallel/mesh.py).

TPU notes: RMSNorm/softmax in float32, everything else bfloat16; fused QKV
and gate+up projections (bigger MXU matmuls); GQA; rotary embeddings
computed in float32. Tensor-parallel sharding is annotated with
``with_sharding_constraint`` on the activations: column-split QKV/gate-up,
row-split out/down projections — XLA inserts the psum on the ``tensor``
axis exactly where Megatron would call all-reduce.

Attention is selected by ``LlamaConfig.attn_impl``:

- ``dense``   — plain einsum attention (default; XLA/GSPMD partitions it)
- ``flash``   — the Pallas fused kernel (ops/attention.py) on TPU
- ``ring``    — ring attention over the ``seq`` mesh axis (long context)
- ``ulysses`` — all-to-all head-resharded attention over ``seq``

``ring``/``ulysses`` need an active mesh with a non-trivial ``seq`` axis
(jax.set_mesh / use_mesh); otherwise they fall back to ``flash``.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

# activation sharding constraint, pruned to the active mesh (shared with
# moe.py; a no-op when no mesh context is set, so single-chip runs work)
from move2kube_tpu.parallel.compat import get_abstract_mesh, shard_map
from move2kube_tpu.parallel.sharding import maybe_shard as _maybe_shard


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    d_model: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    mlp_dim: int = 14336
    max_len: int = 4096
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    attn_impl: str = "dense"  # dense | flash | ring | ulysses
    moe_experts: int = 0      # 0 = dense MLP; >0 = MoE with expert parallelism
    moe_top_k: int = 2
    # GSPMD activation constraints; llama_pipe.py turns this off inside
    # shard_map, where the mesh axes are manual and constraints are invalid
    shard_activations: bool = True


def llama_8b() -> LlamaConfig:
    return LlamaConfig()


def llama_tiny() -> LlamaConfig:
    """Small variant for tests / dry-runs / the graft entry."""
    return LlamaConfig(vocab_size=512, d_model=128, num_layers=2, num_heads=4,
                       num_kv_heads=2, mlp_dim=256, max_len=256)


def _rope(x, positions, theta: float):
    """Rotary embeddings in float32 ([b, s, h, d])."""
    d = x.shape[-1]
    freqs = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [b, s, d/2]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)




def _lora_delta(x32, lora):
    """Per-row LoRA logit delta for the lm_head: gather each batch row's
    A/B matrices from the stacked adapter store and apply ``x·A·B``.
    ``x32`` is the fp32 pre-head hidden ``[b, s, d]``; returns
    ``[b, s, vocab]``. Everything is a traced operand — batched gather
    plus two einsums — so one fixed-shape executable serves any mix of
    adapters (row 0 is the all-zeros base-model adapter)."""
    a_stack, b_stack, rows = lora
    av = jnp.take(a_stack, rows, axis=0)   # [b, d, r]
    bv = jnp.take(b_stack, rows, axis=0)   # [b, r, vocab]
    u = jnp.einsum("bsd,bdr->bsr", x32, av)
    return jnp.einsum("bsr,brv->bsv", u, bv)


class RMSNorm(nn.Module):
    epsilon: float = 1e-5

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
        x32 = x.astype(jnp.float32)
        norm = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True)
                                   + self.epsilon)
        return (norm * scale).astype(x.dtype)


def _seq_axis_size() -> int:
    """Size of the ambient mesh's ``seq`` axis (1 when no mesh is set)."""
    mesh = get_abstract_mesh()
    if getattr(mesh, "empty", True) or "seq" not in mesh.axis_names:
        return 1
    return mesh.shape["seq"]


def _attention(q, k, v, mask, impl: str):
    """Dispatch on LlamaConfig.attn_impl; q/k/v [b, s, h, d] -> [b, s, h, d].

    ``mask`` is the additive causal mask used by the dense path; the other
    implementations derive causality themselves. ring/ulysses run under
    shard_map on the ambient mesh's ``seq`` axis and degrade to flash when
    that axis is trivial (single chip, seq=1 meshes).
    """
    from move2kube_tpu.ops.attention import flash_attention

    head_dim = q.shape[-1]
    if impl in ("ring", "ulysses") and _seq_axis_size() > 1:
        from move2kube_tpu.parallel.ring_attention import ring_attention
        from move2kube_tpu.parallel.ulysses import ulysses_attention

        fn = ring_attention if impl == "ring" else ulysses_attention
        spec = P(("data", "fsdp"), "seq", "tensor", None)
        run = shard_map(
            functools.partial(fn, axis_name="seq", causal=True),
            in_specs=(spec, spec, spec), out_specs=spec,
        )
        return run(q, k, v)
    if impl in ("flash", "ring", "ulysses"):
        return flash_attention(q, k, v, causal=True)
    s_logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
    s_logits = s_logits * (head_dim ** -0.5) + mask
    p = jax.nn.softmax(s_logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


class LlamaBlock(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, positions, mask, cache=None, return_kv=False):
        cfg = self.cfg
        head_dim = cfg.d_model // cfg.num_heads
        q_size = cfg.num_heads * head_dim
        kv_size = cfg.num_kv_heads * head_dim

        h = RMSNorm(cfg.norm_eps, name="attn_norm")(x)
        # fused QKV projection, column-split over the tensor axis
        qkv = nn.Dense(q_size + 2 * kv_size, use_bias=False, dtype=cfg.dtype,
                       name="qkv")(h)
        if cfg.shard_activations:
            qkv = _maybe_shard(qkv, P(("data", "fsdp"), None, "tensor"))
        q, k, v = jnp.split(qkv, [q_size, q_size + kv_size], axis=-1)
        b, s, _ = q.shape
        q = q.reshape(b, s, cfg.num_heads, head_dim)
        k = k.reshape(b, s, cfg.num_kv_heads, head_dim)
        v = v.reshape(b, s, cfg.num_kv_heads, head_dim)
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)
        new_kv = (k, v) if return_kv else None
        if cache is not None:
            # single-token decode against the paged KV cache (serving):
            # write this token's K/V into its page, then attend over the
            # pages named by the block table. No GQA repeat here — the
            # paged kernel batches query heads per KV head itself.
            from move2kube_tpu.ops.attention import (
                paged_decode_attention, quantize_kv_rows)

            k_pages, v_pages = cache["k"], cache["v"]
            block_size = k_pages.shape[1]
            pos = positions[:, 0]
            slot = jnp.arange(b)
            blk = cache["block_tables"][slot, pos // block_size]
            off = pos % block_size
            k_scale = cache.get("k_scale")
            v_scale = cache.get("v_scale")
            if k_scale is not None:
                # int8 cache: quantize this token's rows and write the
                # per-(token, kv-head) scales alongside the pages
                qk, sk = quantize_kv_rows(k[:, 0])
                qv, sv = quantize_kv_rows(v[:, 0])
                k_pages = k_pages.at[blk, off].set(qk)
                v_pages = v_pages.at[blk, off].set(qv)
                k_scale = k_scale.at[blk, off].set(sk)
                v_scale = v_scale.at[blk, off].set(sv)
            else:
                k_pages = k_pages.at[blk, off].set(
                    k[:, 0].astype(k_pages.dtype))
                v_pages = v_pages.at[blk, off].set(
                    v[:, 0].astype(v_pages.dtype))
            o = paged_decode_attention(
                q[:, 0], k_pages, v_pages, cache["block_tables"],
                cache["seq_lens"], k_scale=k_scale,
                v_scale=v_scale).reshape(b, 1, q_size)
            new_kv = (k_pages, v_pages, k_scale, v_scale)
        else:
            # GQA: repeat KV heads up to the query head count
            rep = cfg.num_heads // cfg.num_kv_heads
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
            o = _attention(q, k, v, mask, cfg.attn_impl).reshape(b, s, q_size)
        # row-split output projection: XLA inserts the tensor-axis psum here
        o = nn.Dense(cfg.d_model, use_bias=False, dtype=cfg.dtype, name="attn_out")(o)
        x = x + o

        h = RMSNorm(cfg.norm_eps, name="mlp_norm")(x)
        if cfg.moe_experts > 0:
            from move2kube_tpu.models.moe import MoEMlp

            h, aux = MoEMlp(num_experts=cfg.moe_experts, mlp_dim=cfg.mlp_dim,
                            top_k=cfg.moe_top_k, dtype=cfg.dtype,
                            name="moe")(h)
            # surfaced to the trainer via mutable=["losses"] (train.py)
            self.sow("losses", "moe_aux", aux)
            if new_kv is not None:
                return x + h, new_kv
            return x + h
        # fused gate+up, column-split
        gate_up = nn.Dense(2 * cfg.mlp_dim, use_bias=False, dtype=cfg.dtype,
                           name="gate_up")(h)
        if cfg.shard_activations:
            gate_up = _maybe_shard(gate_up, P(("data", "fsdp"), None, "tensor"))
        gate, up = jnp.split(gate_up, 2, axis=-1)
        h = nn.silu(gate) * up
        # row-split down projection (tensor-axis psum)
        h = nn.Dense(cfg.d_model, use_bias=False, dtype=cfg.dtype, name="down")(h)
        if new_kv is not None:
            return x + h, new_kv
        return x + h


class Llama(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, input_ids, positions=None, cache=None,
                 return_kv=False, return_hidden=False, lora=None):
        """Three modes, one parameter tree:

        - training / full forward (default): ``(input_ids[b, s]) -> logits``
        - prefill (``return_kv=True``): also returns the per-layer rotary-
          embedded K/V ``[(k, v), ...]`` (``[b, s, kv_heads, head_dim]``,
          pre-GQA-repeat) for the serving layer to scatter into its paged
          cache
        - decode (``cache=``): ``input_ids`` is ``[b]`` — ONE new token per
          slot at ``positions`` ``[b]``; ``cache`` is the paged-KV pytree
          (serving/kvcache.py) whose ``k``/``v`` are per-layer page lists.
          Returns ``(logits[b, vocab], updated_cache)``.

        ``lora`` is the serving scheduler's paged multi-LoRA hook
        (serving/sched/lora.py): ``(a_stack [rows, d, r], b_stack
        [rows, r, vocab], rows [b])`` adds each slot's gathered
        ``x·A·B`` delta to the lm_head logits. The stacks ride in as
        traced arguments, so registering or swapping adapters never
        recompiles; row 0 is all-zeros (the base model).
        """
        cfg = self.cfg
        if cache is not None:
            b = input_ids.shape[0]
            x = nn.Embed(cfg.vocab_size, cfg.d_model, dtype=cfg.dtype,
                         name="embed")(input_ids[:, None])
            pos2d = positions[:, None]
            quantized = "k_scale" in cache
            new_k, new_v, new_ks, new_vs = [], [], [], []
            for i in range(cfg.num_layers):
                layer_cache = {
                    "k": cache["k"][i], "v": cache["v"][i],
                    "block_tables": cache["block_tables"],
                    "seq_lens": cache["seq_lens"],
                }
                if quantized:
                    layer_cache["k_scale"] = cache["k_scale"][i]
                    layer_cache["v_scale"] = cache["v_scale"][i]
                x, (kp, vp, ksp, vsp) = LlamaBlock(cfg, name=f"layer_{i}")(
                    x, pos2d, None, cache=layer_cache)
                new_k.append(kp)
                new_v.append(vp)
                new_ks.append(ksp)
                new_vs.append(vsp)
            x = RMSNorm(cfg.norm_eps, name="final_norm")(x)
            x32 = x.astype(jnp.float32)
            logits = nn.Dense(cfg.vocab_size, use_bias=False,
                              dtype=jnp.float32,
                              name="lm_head")(x32)
            if lora is not None:
                logits = logits + _lora_delta(x32, lora)
            out_cache = dict(cache)
            out_cache["k"] = type(cache["k"])(new_k)
            out_cache["v"] = type(cache["v"])(new_v)
            if quantized:
                out_cache["k_scale"] = type(cache["k_scale"])(new_ks)
                out_cache["v_scale"] = type(cache["v_scale"])(new_vs)
            return logits[:, 0], out_cache
        b, s = input_ids.shape
        x = nn.Embed(cfg.vocab_size, cfg.d_model, dtype=cfg.dtype,
                     name="embed")(input_ids)
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
        causal = jnp.where(
            jnp.arange(s)[:, None] >= jnp.arange(s)[None, :], 0.0, -1e30
        ).astype(jnp.float32)[None, None]
        kvs = []
        for i in range(cfg.num_layers):
            out = LlamaBlock(cfg, name=f"layer_{i}")(
                x, positions, causal, return_kv=return_kv)
            if return_kv:
                x, kv = out
                kvs.append(kv)
            else:
                x = out
        x = RMSNorm(cfg.norm_eps, name="final_norm")(x)
        if return_hidden:
            # pre-head hidden states for the fused chunked lm-head CE
            # (ops/crossentropy.py): the caller folds the lm_head matmul
            # into the loss so the [b, s, vocab] logits never materialize
            return x
        x32 = x.astype(jnp.float32)
        logits = nn.Dense(cfg.vocab_size, use_bias=False, dtype=jnp.float32,
                          name="lm_head")(x32)
        if lora is not None:
            logits = logits + _lora_delta(x32, lora)
        if return_kv:
            return logits, kvs
        return logits
