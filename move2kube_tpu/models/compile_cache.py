"""Persistent XLA compilation cache setup.

Shared by every jax entrypoint this repo owns — bench.py children, the
multichip dryrun (__graft_entry__) and the emitted ``train_tpu.py``
programs (this module is vendored into images with the rest of
``models/``). Pointing ``jax_compilation_cache_dir`` at a durable
directory means a re-spawned bench child, a retried phase, or a
restarted JobSet pod deserializes yesterday's executable instead of
recompiling it from scratch — for the bench that is the difference
between fitting the 440s budget and burning it all on warmup.

Knobs:

- ``M2KT_COMPILE_CACHE=0``      disable entirely
- ``M2KT_COMPILE_CACHE_DIR``    cache directory (wins over the caller's
  default — emitted images bake in ``/app/.jax-cache`` but operators can
  redirect to a mounted volume without editing the program)
- ``M2KT_PREWARM_DIR``          read-only prewarm artifact: executables
  baked into the image (or an init-container volume) under the same
  topology-fingerprint layout; a cold replica's empty cache dir is
  seeded from it before jax looks, so scale-up skips the compile step

Executables compiled for different meshes are NOT interchangeable: the
same train step lowered on a 1x8 fsdp mesh and a 4x2 dp x tp mesh are
different programs, and a cache dir mounted across heterogeneous slices
(or across a topology change of the same JobSet) must not mix them.
``setup_compilation_cache(..., mesh=mesh)`` partitions the directory by
a :func:`topology_fingerprint` — device kind, device count, mesh dims
and axis names — so every (hardware, mesh) pair gets its own namespace.
"""

from __future__ import annotations

import os
import re

_DEFAULT_DIR = os.path.join("~", ".cache", "m2kt-jax-cache")


def topology_fingerprint(mesh, num_slices: int = 1) -> str:
    """Filesystem-safe cache-key component for a concrete mesh:
    ``<device_kind>-n<ndev>-<dim x dim x ...>-<axisinitials>[-s<K>]``.
    Empty string for None or device-less (abstract) meshes — those
    callers get the unpartitioned directory.

    ``num_slices`` > 1 appends a ``-s<K>`` slice tag: the same logical
    mesh laid over 2 DCN-connected slices and over one big ICI slice
    lowers to different collectives (DCN transfers vs ICI rings), and an
    elastic restart that shrinks the slice count must not deserialize
    the pre-loss generation's executables."""
    if mesh is None:
        return ""
    try:
        devs = mesh.devices.ravel()
        kind = str(devs[0].device_kind)
        dims = "x".join(str(s) for s in mesh.devices.shape)
        axes = "".join(str(a)[0] for a in mesh.axis_names)
        n = devs.size
    except Exception:  # noqa: BLE001 - AbstractMesh etc: no fingerprint
        return ""
    kind = re.sub(r"[^A-Za-z0-9_.-]+", "_", kind)
    fp = f"{kind}-n{n}-{dims}-{axes}"
    if num_slices > 1:
        fp += f"-s{num_slices}"
    return fp


def setup_compilation_cache(default_dir: str | None = None,
                            mesh=None, num_slices: int = 1) -> str | None:
    """Enable jax's persistent compilation cache; returns the directory
    in use, or None when disabled or unsupported.

    ``default_dir`` is the *caller's* default; the operator env var
    ``M2KT_COMPILE_CACHE_DIR`` takes precedence, and the user cache dir
    is the last resort. With ``mesh`` given, executables land in a
    per-(device kind, mesh shape, axis names) subdirectory — see
    :func:`topology_fingerprint`. Safe to call more than once: emitted
    trainers call it early (warmup compiles cached too) and again with
    ``mesh=`` once the planner has built one."""
    if os.environ.get("M2KT_COMPILE_CACHE", "1") == "0":
        return None
    path = (os.environ.get("M2KT_COMPILE_CACHE_DIR") or default_dir
            or _DEFAULT_DIR)
    path = os.path.abspath(os.path.expanduser(path))
    fp = topology_fingerprint(mesh, num_slices=num_slices)
    if fp:
        path = os.path.join(path, fp)
    try:
        os.makedirs(path, exist_ok=True)
    except OSError:
        return None  # read-only filesystem etc: run uncached, don't crash
    seed_from_prewarm(path, fp)

    import jax  # deferred: the bench parent imports nothing jax-ish

    try:
        jax.config.update("jax_compilation_cache_dir", path)
        # persist every executable, however small/fast: bench children
        # re-spawn per retry and per OOM batch-halving, and the emitted
        # trainers recompile identical programs on every pod restart
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:  # noqa: BLE001 - a jax without the knobs: uncached
        return None
    try:
        # keep entries RELOCATABLE: by default jax nests an XLA autotune
        # cache inside the cache dir and bakes that absolute path into
        # the compile options — and so into every cache key — which
        # silently invalidates the whole cache whenever the directory
        # path differs (a prewarm artifact baked at translate time and
        # thawed under /app/.jax-cache, a volume remount, bench dirs)
        jax.config.update("jax_persistent_cache_enable_xla_caches",
                          "none")
    except Exception:  # noqa: BLE001 - older jax: path-pinned keys
        pass
    try:
        # the persistent cache initializes lazily ONCE: if anything
        # compiled before this call (or an earlier call pointed at a
        # different dir — the trainers' early-then-with-mesh pattern),
        # the dir update above is silently ignored until a reset
        from jax.experimental.compilation_cache import (
            compilation_cache as _cc)
        _cc.reset_cache()
    except Exception:  # noqa: BLE001 - older jax: first dir sticks
        pass
    return path


def seed_from_prewarm(cache_dir: str, fingerprint: str = "",
                      prewarm_dir: str | None = None) -> int:
    """Copy baked executables into a (possibly empty) live cache dir.

    The prewarm artifact (``M2KT_PREWARM_DIR``; the emitted serving
    images bake ``/app/.jax-prewarm``) mirrors the cache layout: entries
    for a fingerprinted topology live under ``<prewarm>/<fingerprint>``,
    unfingerprinted ones at the top level. Only missing entries are
    copied — the live cache (a mounted volume that already compiled) is
    never overwritten — and any filesystem trouble degrades to an
    ordinary cold compile. Returns the number of entries seeded."""
    src = (prewarm_dir if prewarm_dir is not None
           else os.environ.get("M2KT_PREWARM_DIR", ""))
    if not src:
        return 0
    src = os.path.abspath(os.path.expanduser(src))
    if fingerprint:
        src = os.path.join(src, fingerprint)
    if not os.path.isdir(src) or os.path.realpath(src) == \
            os.path.realpath(cache_dir):
        return 0
    import shutil

    seeded = 0
    try:
        for fname in sorted(os.listdir(src)):
            s = os.path.join(src, fname)
            d = os.path.join(cache_dir, fname)
            if not os.path.isfile(s) or os.path.exists(d):
                continue
            shutil.copyfile(s, d)
            seeded += 1
    except OSError:
        return seeded  # partial seed is still a head start
    return seeded


def bake_prewarm(prewarm_dir: str, mesh=None, num_slices: int = 1,
                 cache_dir: str | None = None) -> int:
    """The translate-time half of the prewarm story: snapshot a live,
    populated compile cache into a prewarm artifact directory (what the
    emitted image's ``jax-prewarm/`` build-context layer or an
    init-container volume is filled from). Entries land under the same
    topology fingerprint ``seed_from_prewarm`` reads, so the artifact
    only ever thaws on matching hardware+mesh. Returns entries baked."""
    if cache_dir is None:
        import jax

        cache_dir = jax.config.jax_compilation_cache_dir
    if not cache_dir or not os.path.isdir(cache_dir):
        return 0
    dst = os.path.abspath(os.path.expanduser(prewarm_dir))
    fp = topology_fingerprint(mesh, num_slices=num_slices)
    if fp:
        dst = os.path.join(dst, fp)
    import shutil

    try:
        os.makedirs(dst, exist_ok=True)
    except OSError:
        return 0
    baked = 0
    try:
        for fname in sorted(os.listdir(cache_dir)):
            s = os.path.join(cache_dir, fname)
            d = os.path.join(dst, fname)
            if not os.path.isfile(s) or os.path.exists(d):
                continue
            shutil.copyfile(s, d)
            baked += 1
    except OSError:
        return baked
    return baked
