"""Persistent XLA compilation cache setup.

Shared by every jax entrypoint this repo owns — bench.py children, the
multichip dryrun (__graft_entry__) and the emitted ``train_tpu.py``
programs (this module is vendored into images with the rest of
``models/``). Pointing ``jax_compilation_cache_dir`` at a durable
directory means a re-spawned bench child, a retried phase, or a
restarted JobSet pod deserializes yesterday's executable instead of
recompiling it from scratch — for the bench that is the difference
between fitting the 440s budget and burning it all on warmup.

Knobs:

- ``M2KT_COMPILE_CACHE=0``      disable entirely
- ``M2KT_COMPILE_CACHE_DIR``    cache directory (wins over the caller's
  default — emitted images bake in ``/app/.jax-cache`` but operators can
  redirect to a mounted volume without editing the program)
"""

from __future__ import annotations

import os

_DEFAULT_DIR = os.path.join("~", ".cache", "m2kt-jax-cache")


def setup_compilation_cache(default_dir: str | None = None) -> str | None:
    """Enable jax's persistent compilation cache; returns the directory
    in use, or None when disabled or unsupported.

    ``default_dir`` is the *caller's* default; the operator env var
    ``M2KT_COMPILE_CACHE_DIR`` takes precedence, and the user cache dir
    is the last resort. Safe to call more than once."""
    if os.environ.get("M2KT_COMPILE_CACHE", "1") == "0":
        return None
    path = (os.environ.get("M2KT_COMPILE_CACHE_DIR") or default_dir
            or _DEFAULT_DIR)
    path = os.path.abspath(os.path.expanduser(path))
    try:
        os.makedirs(path, exist_ok=True)
    except OSError:
        return None  # read-only filesystem etc: run uncached, don't crash

    import jax  # deferred: the bench parent imports nothing jax-ish

    try:
        jax.config.update("jax_compilation_cache_dir", path)
        # persist every executable, however small/fast: bench children
        # re-spawn per retry and per OOM batch-halving, and the emitted
        # trainers recompile identical programs on every pod restart
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:  # noqa: BLE001 - a jax without the knobs: uncached
        return None
    return path
