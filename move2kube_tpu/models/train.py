"""Shared training machinery: sharded state creation + train steps.

The emitted training programs (containerizer/jax_emit.py templates) and
bench.py both drive these. Everything compiles once under jit: sharded init
via ``eval_shape`` (no host-side giant arrays), train steps with donated
state, sharding-constrained batches, and loss in float32.
"""

from __future__ import annotations

import functools
import math
import time
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax
from flax.training import train_state
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from move2kube_tpu.ops import crossentropy
from move2kube_tpu.parallel.compat import ambient_mesh, bare_spec_constraints_ok
from move2kube_tpu.parallel.overlap import (
    fsdp_prefetch_mode,
    is_pure_data_parallel,
    is_pure_fsdp,
    overlapped_accum_grads,
    prefetched_fsdp_accum_grads,
)
from move2kube_tpu.parallel.sharding import ShardingRules, infer_param_axes


class TrainState(train_state.TrainState):
    batch_stats: Any = None  # BatchNorm stats (ResNet); None elsewhere


def _mesh_context(mesh: Mesh):
    """Context that makes bare PartitionSpecs resolvable inside traced code
    (models annotate activations with P(...) without threading the mesh).
    AbstractMesh works too: the shape-verification path
    (tests/test_memory_plan.py) traces train steps on device-less meshes.
    Version dispatch (use_mesh vs the legacy resource env + abstract-mesh
    pair) lives in ``parallel/compat.ambient_mesh``."""
    return ambient_mesh(mesh)


def _with_mesh(mesh: Mesh, fn: Callable) -> Callable:
    if _trivial(mesh):
        return fn  # no ambient mesh: keep the plain single-device compile

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        with _mesh_context(mesh):
            return fn(*args, **kwargs)

    # expose the underlying jit function + its mesh so AOT consumers
    # (assert_state_donated) can .lower() under the right mesh context;
    # the trivial path above returns the jit object itself, which
    # carries .lower natively
    wrapper._m2kt_jit = fn
    wrapper._m2kt_mesh = mesh
    return wrapper


def compiled_alias_count(compiled_text: str) -> int:
    """Number of input buffers the compiled executable aliases into its
    outputs (XLA emits one ``may-alias``/``must-alias`` entry per donated
    buffer in the HloModule ``input_output_alias`` header)."""
    return (compiled_text.count("may-alias")
            + compiled_text.count("must-alias"))


def assert_state_donated(step_fn, state, batch,
                         min_aliased: int | None = None) -> int:
    """Verify that ``step_fn``'s compiled executable really aliases the
    donated state buffers (donate_argnums alone is a *request* — a jit
    wrapper, an out-sharding mismatch or an engine change can silently
    drop it, doubling peak memory). Lowers and compiles for the current
    backend — works on CPU, no TPU needed — and asserts at least
    ``min_aliased`` input-output aliases (default: one per param leaf).
    Returns the alias count."""
    jit_fn = getattr(step_fn, "_m2kt_jit", step_fn)
    mesh = getattr(step_fn, "_m2kt_mesh", None)
    if not hasattr(jit_fn, "lower"):
        raise TypeError(
            "step_fn is not jit-compiled (no .lower); donation cannot be "
            "verified")
    if mesh is not None:
        with _mesh_context(mesh):
            compiled = jit_fn.lower(state, batch).compile()
    else:
        compiled = jit_fn.lower(state, batch).compile()
    n = compiled_alias_count(compiled.as_text())
    params = getattr(state, "params", state)
    floor = (min_aliased if min_aliased is not None
             else len(jax.tree.leaves(params)))
    if n < floor:
        raise AssertionError(
            f"compiled train step aliases only {n} input buffers; expected "
            f">= {floor} — state donation is not reaching the executable")
    return n


def cross_entropy_loss(logits, labels) -> jax.Array:
    logits = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    # gather the label log-prob instead of materialising a one-hot
    # (batch, classes) float32 tensor — saves HBM bandwidth on the
    # backward pass; identical math
    picked = jnp.take_along_axis(logp, labels[..., None].astype(jnp.int32),
                                 axis=-1)
    return -jnp.mean(picked)


def lm_loss(logits, input_ids) -> jax.Array:
    """Next-token prediction loss. Dispatches over the M2KT_FUSED_CE
    ladder (ops/crossentropy.py): the chunked online-logsumexp path when
    the vocab is wide enough to pay for it, the jnp reference otherwise
    — identical math, gated by tests/test_crossentropy.py."""
    return crossentropy.cross_entropy(logits[:, :-1], input_ids[:, 1:])


def data_axes(mesh) -> tuple[str, ...]:
    """Every data-like mesh axis, in mesh order. The batch dim must shard
    over dp x fsdp together — a planner-produced mesh may put all devices
    on ``fsdp`` (ZeRO) or split them dp x fsdp from the memory model, and
    sharding over only one of the two would replicate the batch across
    the other, silently multiplying per-device batch work."""
    names = getattr(mesh, "axis_names", ())
    axes = tuple(a for a in ("data", "fsdp") if a in names)
    return axes or ("data", "fsdp")


def batch_sharding(mesh: Mesh):
    """Input-batch sharding over ALL data-like axes (dp x fsdp);
    SingleDeviceSharding on trivial meshes so committed batches never
    trigger the SPMD pipeline (see _trivial)."""
    if _trivial(mesh):
        return jax.sharding.SingleDeviceSharding(mesh.devices.flat[0])
    return _sharding(mesh, P(data_axes(mesh)))


def _sharding(mesh, spec: P):
    """NamedSharding for a concrete Mesh; the bare PartitionSpec for an
    AbstractMesh (with_sharding_constraint resolves it against the
    ambient mesh, letting train steps trace under ``jax.eval_shape`` on
    device-less meshes — the BASELINE config-5 shape-verification path,
    tests/test_memory_plan.py)."""
    if isinstance(mesh, jax.sharding.AbstractMesh):
        return spec
    return NamedSharding(mesh, spec)


def _trivial(mesh) -> bool:
    """True for a single-device concrete mesh. Trivial meshes compile
    the PLAIN jit path — no sharding constraints, no mesh context, no
    out_shardings: semantically identical (every constraint is a no-op
    at one device) but compiled WITHOUT the SPMD pipeline. Measured:
    a mesh-compiled ResNet-50 train step runs ~7x slower than the
    identical plain-jit program on the CPU backend despite structurally
    identical HLO (round-5 bisection, docs/ROUND5_NOTES.md) — single
    chips must never pay a partitioner tax for machinery they don't
    use."""
    return (not isinstance(mesh, jax.sharding.AbstractMesh)
            and mesh.devices.size == 1)


def _constrain(x, mesh: Mesh, spec: P):
    """with_sharding_constraint, skipped on trivial meshes (and on legacy
    jax under an abstract-only mesh, where bare specs can't resolve —
    shape-inert on that eval_shape verification path)."""
    if _trivial(mesh):
        return x
    if (isinstance(mesh, jax.sharding.AbstractMesh)
            and not bare_spec_constraints_ok()):
        return x
    return jax.lax.with_sharding_constraint(x, _sharding(mesh, spec))


def create_sharded_state(
    rng: jax.Array,
    model,
    sample_input: dict,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    rules: ShardingRules | None = None,
    has_batch_stats: bool = False,
) -> TrainState:
    """Initialize params directly into their shards (ZeRO-3-style): shapes
    come from eval_shape, shardings from the logical-axis heuristic, and the
    actual init runs under jit with those out_shardings so no device ever
    materialises the full tree."""
    rules = rules or ShardingRules.default()

    def init_fn(rng):
        variables = model.init(rng, **sample_input)
        # keep only persistent state: sown collections like MoE "losses"
        # are per-forward outputs, not state to carry in TrainState
        return {k: v for k, v in variables.items()
                if k in ("params", "batch_stats")}

    if _trivial(mesh):
        # single device: SingleDeviceSharding outputs, no NamedShardings
        # — the train step compiles WITHOUT the SPMD pipeline (see
        # _trivial; ~7x on the CPU backend) while still landing on the
        # MESH'S device (which need not be the default one: per-chip
        # trainer processes build one-device meshes over their own chip)
        variables = jax.jit(
            init_fn,
            out_shardings=jax.sharding.SingleDeviceSharding(
                mesh.devices.flat[0]))(rng)
        return _make_state(model, variables, tx)

    with _mesh_context(mesh):
        shapes = jax.eval_shape(init_fn, rng)
    params_axes = infer_param_axes(shapes["params"])

    def _sharding_for(axes, shape_leaf):
        """Heuristic axes -> NamedSharding, dropping any dim whose size
        isn't divisible by its mesh extent (e.g. a 3-channel conv_out on
        an fsdp=2 mesh): GSPMD refuses uneven param shards outright, and
        replicating one small leaf beats failing init."""
        if not isinstance(axes, tuple):
            return NamedSharding(mesh, P())
        spec = rules.spec(axes)
        pruned = []
        for dim, entry in enumerate(spec):
            names = (entry,) if isinstance(entry, str) else (entry or ())
            extent = 1
            for nm in names:
                extent *= mesh.shape[nm]
            pruned.append(entry if extent > 1
                          and shape_leaf.shape[dim] % extent == 0 else None)
        return NamedSharding(mesh, P(*pruned))

    param_shardings = jax.tree.map(
        _sharding_for, params_axes, shapes["params"],
        is_leaf=lambda x: isinstance(x, tuple) or x is None,
    )
    out_shardings = {"params": param_shardings}
    if has_batch_stats and "batch_stats" in shapes:
        out_shardings["batch_stats"] = jax.tree.map(
            lambda _: NamedSharding(mesh, P()), shapes["batch_stats"]
        )
    with _mesh_context(mesh):
        variables = jax.jit(init_fn, out_shardings=out_shardings)(rng)
    return _make_state(model, variables, tx)


def _make_state(model, variables, tx) -> TrainState:
    return TrainState.create(
        apply_fn=model.apply,
        params=variables["params"],
        tx=tx,
        batch_stats=variables.get("batch_stats"),
    )


def make_classifier_train_step(mesh: Mesh, has_batch_stats: bool = False,
                               scan_steps: int | None = None,
                               grad_accum: int = 1):
    """Train step for image/sequence classifiers (ResNet, BERT).

    With ``scan_steps=k`` the returned function consumes a batch whose
    leaves carry a leading axis of length k and runs k optimizer steps in
    ONE compiled call via ``lax.scan`` (returns per-step losses). One
    dispatch per k steps matters when the host-device link is
    high-latency (remote TPU tunnels) and lets emitted programs prefetch
    k host batches per device call.

    ``grad_accum=k`` instead folds k stacked microbatches into ONE
    optimizer update (sequential scan accumulation; BatchNorm stats are
    threaded through the microbatches so the final stats reflect all k).
    Mutually exclusive with ``scan_steps``.
    """
    if scan_steps is not None and grad_accum > 1:
        raise ValueError("scan_steps and grad_accum are mutually exclusive")

    def grads_of(state: TrainState, batch: dict, stats):
        x = _constrain(batch["input"], mesh, P(data_axes(mesh)))
        y = batch["label"]

        def loss_fn(params):
            variables = {"params": params}
            if has_batch_stats:
                variables["batch_stats"] = stats
                logits, updates = state.apply_fn(
                    variables, x, mutable=["batch_stats"])
                return cross_entropy_loss(logits, y), updates["batch_stats"]
            logits = state.apply_fn(variables, x)
            return cross_entropy_loss(logits, y), None

        return jax.value_and_grad(loss_fn, has_aux=True)(state.params)

    def one_step(state: TrainState, batch: dict):
        (loss, new_stats), grads = grads_of(state, batch, state.batch_stats)
        state = state.apply_gradients(grads=grads)
        if has_batch_stats:
            state = state.replace(batch_stats=new_stats)
        return state, loss

    if grad_accum > 1:
        @functools.partial(jax.jit, donate_argnums=(0,))
        def step_accum(state: TrainState, batches: dict):
            def micro(carry, batch):
                acc, stats = carry
                (loss, new_stats), g = grads_of(state, batch, stats)
                return (jax.tree.map(jnp.add, acc, g),
                        new_stats if has_batch_stats else stats), loss

            zeros = jax.tree.map(jnp.zeros_like, state.params)
            (acc, stats), losses = jax.lax.scan(
                micro, (zeros, state.batch_stats), batches, length=grad_accum)
            grads = jax.tree.map(lambda g: g / grad_accum, acc)
            state = state.apply_gradients(grads=grads)
            if has_batch_stats:
                state = state.replace(batch_stats=stats)
            return state, jnp.mean(losses)

        return _with_mesh(mesh, step_accum)

    if scan_steps is None:
        step = functools.partial(jax.jit, donate_argnums=(0,))(one_step)
        return _with_mesh(mesh, step)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step_k(state: TrainState, batches: dict):
        return jax.lax.scan(one_step, state, batches, length=scan_steps)

    return _with_mesh(mesh, step_k)


def make_bert_train_step(mesh: Mesh, scan_steps: int | None = None):
    """Fine-tune step for BertEncoder (input_ids/attention_mask/label).

    ``scan_steps`` as in :func:`make_classifier_train_step`: fuse k steps
    into one compiled call over a batch with a leading k axis.
    """

    def one_step(state: TrainState, batch: dict):
        ids = _constrain(batch["input_ids"], mesh, P(("data", "fsdp")))
        mask = batch.get("attention_mask")

        def loss_fn(params):
            logits = state.apply_fn({"params": params}, ids, mask)
            return cross_entropy_loss(logits, batch["label"])

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        return state.apply_gradients(grads=grads), loss

    if scan_steps is None:
        step = functools.partial(jax.jit, donate_argnums=(0,))(one_step)
        return _with_mesh(mesh, step)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step_k(state: TrainState, batches: dict):
        return jax.lax.scan(one_step, state, batches, length=scan_steps)

    return _with_mesh(mesh, step_k)


def make_diffusion_train_step(mesh: Mesh, scan_steps: int | None = None,
                              num_diffusion_steps: int = 1000):
    """DDPM denoising step for the UNet (models/unet.py): the batch
    carries clean images, pre-sampled gaussian noise and integer
    timesteps; the step forms x_t from the (static, on-device) linear-
    beta schedule and regresses the predicted noise with MSE — the
    standard DDPM objective.

    ``scan_steps`` as in :func:`make_classifier_train_step`: fuse k steps
    into one compiled call over a batch with a leading k axis.
    """
    from move2kube_tpu.models.unet import ddpm_alpha_bars

    alpha_bars = ddpm_alpha_bars(num_diffusion_steps)

    def one_step(state: TrainState, batch: dict):
        x0 = _constrain(batch["image"], mesh, P(("data", "fsdp")))
        noise = _constrain(batch["noise"], mesh, P(("data", "fsdp")))
        t = batch["t"]
        ab = alpha_bars[t][:, None, None, None]
        x_t = (jnp.sqrt(ab) * x0.astype(jnp.float32)
               + jnp.sqrt(1.0 - ab) * noise.astype(jnp.float32))

        def loss_fn(params):
            pred = state.apply_fn({"params": params}, x_t, t)
            return jnp.mean((pred - noise.astype(jnp.float32)) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        return state.apply_gradients(grads=grads), loss

    if scan_steps is None:
        step = functools.partial(jax.jit, donate_argnums=(0,))(one_step)
        return _with_mesh(mesh, step)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step_k(state: TrainState, batches: dict):
        return jax.lax.scan(one_step, state, batches, length=scan_steps)

    return _with_mesh(mesh, step_k)


def make_lm_train_step(mesh: Mesh, remat: bool = True,
                       moe_aux_weight: float = 0.01,
                       grad_accum: int = 1,
                       precision=None):
    """Next-token-prediction step for Llama-class models; rematerialises
    per-block activations (jax.checkpoint) to trade FLOPs for HBM.

    MoE models sow their load-balancing losses into the ``losses``
    collection (llama.py LlamaBlock); they are summed into the loss with
    weight ``moe_aux_weight`` (no-op for dense models: the collection is
    empty).

    ``grad_accum=k`` switches the step to consume ``k`` stacked
    microbatches (``input_ids`` of shape [k, batch, seq]) per optimizer
    update.  On a pure data-parallel mesh the per-microbatch gradient
    reduction rides an explicit ppermute ring that overlaps the next
    microbatch's backward (parallel/overlap.py); on a pure-fsdp (ZeRO)
    mesh the param all-gather is issued as independent per-leaf rings
    ahead of the backward and the grad reduce-scatter rides the same
    overlap (prefetched_fsdp_accum_grads); on meshes with model-parallel
    axes it falls back to a sequential lax.scan accumulation and lets
    GSPMD place the final reduce.

    ``precision`` (models/precision.py PrecisionPolicy) casts the fp32
    master params to the compute dtype inside the loss and applies/undoes
    optional loss scaling around the backward; gradients and the reported
    loss come back unscaled fp32."""

    def _aux(sown):
        return sum((jnp.sum(v) for v in jax.tree.leaves(sown)),
                   jnp.float32(0.0))

    def _loss(apply_fn, params, ids):
        if precision is not None:
            params = precision.cast_params(params)

        # head-folded fused CE (ops/crossentropy.py): when the ladder says
        # fuse and the param tree exposes a recognizable LM head, ask the
        # model for its pre-head hidden states and fold the lm-head matmul
        # into the chunked loss so the [B, T, V] logit tensor never
        # materializes. Models without return_hidden (or any trace-time
        # failure) fall through to the logits path below with a warning.
        head_w = crossentropy.lm_head_weight(params)
        if head_w is not None and crossentropy.should_fuse(head_w.shape[-1]):
            def fwd_h(p, x):
                return apply_fn({"params": p}, x, mutable=["losses"],
                                return_hidden=True)

            if remat:
                fwd_h = jax.checkpoint(fwd_h)
            try:
                hidden, sown = fwd_h(params, ids)
                loss = (crossentropy.linear_lm_loss(hidden, head_w, ids)
                        + moe_aux_weight * _aux(sown))
            except Exception as e:  # noqa: BLE001 - reference fallback
                crossentropy._warn_once("head-folded lm loss", e)
            else:
                if precision is not None:
                    loss = precision.scale_loss(loss)
                return loss

        def fwd(p, x):
            return apply_fn({"params": p}, x, mutable=["losses"])

        if remat:
            fwd = jax.checkpoint(fwd)
        logits, sown = fwd(params, ids)
        loss = lm_loss(logits, ids) + moe_aux_weight * _aux(sown)
        if precision is not None:
            loss = precision.scale_loss(loss)
        return loss

    def _finish(state: TrainState, grads, loss):
        if precision is not None:
            grads = precision.unscale(grads)
            loss = precision.unscale(loss)
        return state.apply_gradients(grads=grads), loss

    if grad_accum <= 1:
        @functools.partial(jax.jit, donate_argnums=(0,))
        def step(state: TrainState, batch: dict):
            ids = _constrain(batch["input_ids"], mesh, P(data_axes(mesh)))
            loss, grads = jax.value_and_grad(
                lambda p: _loss(state.apply_fn, p, ids))(state.params)
            return _finish(state, grads, loss)

        return _with_mesh(mesh, step)

    overlap = not _trivial(mesh) and is_pure_data_parallel(mesh)

    if overlap:
        @functools.partial(jax.jit, donate_argnums=(0,))
        def step_overlap(state: TrainState, batch: dict):
            grads, loss = overlapped_accum_grads(
                mesh,
                lambda p, mb: _loss(state.apply_fn, p, mb["input_ids"]),
                state.params, batch, axis_name="data")
            return _finish(state, grads, loss)

        return _with_mesh(mesh, step_overlap)

    # ZeRO meshes (all devices on fsdp): explicit ring all-gather of the
    # param shards issued ahead of the backward, grad reduce-scatter
    # overlapped with the next microbatch (parallel/overlap.py); the
    # sequential GSPMD scan below stays the fallback (M2KT_FSDP_PREFETCH=off
    # or any non-pure-fsdp topology).
    prefetch = (not _trivial(mesh) and is_pure_fsdp(mesh)
                and fsdp_prefetch_mode() != "off")

    if prefetch:
        @functools.partial(jax.jit, donate_argnums=(0,))
        def step_prefetch(state: TrainState, batch: dict):
            grads, loss = prefetched_fsdp_accum_grads(
                mesh,
                lambda p, mb: _loss(state.apply_fn, p, mb["input_ids"]),
                state.params, batch, axis_name="fsdp")
            return _finish(state, grads, loss)

        return _with_mesh(mesh, step_prefetch)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step_accum(state: TrainState, batch: dict):
        def micro(acc, ids):
            ids = _constrain(ids, mesh, P(data_axes(mesh)))
            loss, g = jax.value_and_grad(
                lambda p: _loss(state.apply_fn, p, ids))(state.params)
            return jax.tree.map(jnp.add, acc, g), loss

        zeros = jax.tree.map(jnp.zeros_like, state.params)
        acc, losses = jax.lax.scan(micro, zeros, batch["input_ids"])
        k = batch["input_ids"].shape[0]
        grads = jax.tree.map(lambda g: g / k, acc)
        return _finish(state, grads, jnp.mean(losses))

    return _with_mesh(mesh, step_accum)


class GradNormState(NamedTuple):
    """Opt-state slot the grad-norm recorder writes into each update."""
    norm: jax.Array


def grad_norm_recorder() -> optax.GradientTransformation:
    """Identity transform that stows ``global_norm(updates)`` in its
    state. Instrumenting the OPTIMIZER (not the step function) means no
    train-step factory changes signature and every model family gets a
    grad-norm gauge for free: the host reads it back off the optimizer
    state at sync points (:func:`grad_norm_from_state`). Cost: one tree
    reduction per update, noise next to the backward pass."""

    def init(params):
        del params
        return GradNormState(norm=jnp.zeros((), jnp.float32))

    def update(updates, state, params=None):
        del state, params
        return updates, GradNormState(
            norm=optax.global_norm(updates).astype(jnp.float32))

    return optax.GradientTransformation(init, update)


def instrument_optimizer(
        tx: optax.GradientTransformation) -> optax.GradientTransformation:
    """Chain the grad-norm and tensor-health recorders in front of
    ``tx``. NOTE: this changes the opt-state pytree structure — wrap
    unconditionally (not gated on a telemetry flag) so checkpoints stay
    restorable when telemetry is toggled between runs; the health
    recorder's state shape is likewise identical whether ``M2KT_NUMERICS``
    is on or off. Both sit OUTSIDE any ``apply_if_finite`` wrapper ``tx``
    carries, so a skipped non-finite update is still recorded — that is
    the step the forensics exist for."""
    from move2kube_tpu.obs import numerics

    return optax.chain(grad_norm_recorder(), numerics.health_recorder(), tx)


def grad_norm_from_state(state) -> float | None:
    """Latest global grad norm recorded by :func:`grad_norm_recorder`,
    walking the (arbitrarily nested) optimizer state; None when the
    optimizer wasn't instrumented."""

    def find(node):
        if isinstance(node, GradNormState):
            return node
        if isinstance(node, (tuple, list)):
            for item in node:
                hit = find(item)
                if hit is not None:
                    return hit
        return None

    hit = find(getattr(state, "opt_state", state))
    return float(hit.norm) if hit is not None else None


class StepTelemetry:
    """Per-step training telemetry into an obs registry.

    The loop calls :meth:`record_step` with host-measured wall time; the
    callback folds it into a step-time histogram, throughput/loss/grad-
    norm gauges, and (every ``mem_every`` steps — ``jax.live_arrays``
    walks every live buffer) a device-memory gauge. All host-side dict
    writes: the ``obs`` bench phase bounds total overhead at <= 3% of
    step time."""

    def __init__(self, registry=None, items_per_step: int = 0,
                 unit: str = "tokens", mem_every: int = 10, tracer=None):
        from move2kube_tpu.obs import tracing
        from move2kube_tpu.obs.metrics import default_registry
        reg = registry if registry is not None else default_registry()
        self.registry = reg
        # per-step spans into the runtime trace ring (obs/tracing.py):
        # record() with the step's own clock readings, so tracing adds no
        # timing calls to the loop and the same <=3% overhead budget holds.
        # None -> the process tracer when M2KT_TRACE is on; False -> off
        # (the bench probe times the telemetry-only variant this way)
        if tracer is None:
            tracer = tracing.get() if tracing.enabled() else None
        self.tracer = tracer or None
        self.items_per_step = items_per_step
        self.mem_every = max(1, mem_every)
        # step times: sub-ms (tiny CPU models) up to tens of seconds
        # (large accum steps)
        self._step_hist = reg.histogram(
            "m2kt_train_step_seconds", "Train step wall time",
            buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                     0.5, 1.0, 2.5, 5.0, 10.0, 30.0))
        self._steps = reg.counter(
            "m2kt_train_steps_total", "Optimizer steps completed")
        self._throughput = reg.gauge(
            f"m2kt_train_{unit}_per_second",
            f"Training throughput ({unit}/s, most recent step)")
        self._loss = reg.gauge("m2kt_train_loss", "Most recent step loss")
        self._grad_norm = reg.gauge(
            "m2kt_train_grad_norm", "Global gradient norm (last update)")
        self._step_gauge = reg.gauge(
            "m2kt_train_step", "Current step number")
        self._device_bytes = reg.gauge(
            "m2kt_train_device_live_bytes",
            "Bytes held by live jax arrays on this host's devices")
        self._compiles = reg.counter(
            "m2kt_train_compile_events_total",
            "Compile events observed by the training loop")
        self._compile_seconds = reg.counter(
            "m2kt_train_compile_seconds_total",
            "Wall seconds spent in observed compile events")
        # tensor-health plane (obs/numerics.py): per-layer-group gauges
        # fed from the health recorder's opt-state slot at sync points.
        # Cardinality is groups x {grad, param}, bounded by the same
        # max_series overflow contract the tenant families use.
        from move2kube_tpu.obs import numerics as numericslib
        self._numerics = numericslib
        self._numerics_on = numericslib.enabled()
        cap = 2 * numericslib.max_groups()
        self._tensor_rms = reg.gauge(
            "m2kt_train_tensor_rms",
            "Per-layer-group rms over finite entries",
            labels=("group", "kind"), max_series=cap + 1)
        self._tensor_max_abs = reg.gauge(
            "m2kt_train_tensor_max_abs",
            "Per-layer-group max |x| (Inf shows as Inf)",
            labels=("group", "kind"), max_series=cap + 1)
        self._tensor_nonfinite = reg.gauge(
            "m2kt_train_tensor_nonfinite",
            "Per-layer-group non-finite entry count (last recorded step)",
            labels=("group", "kind"), max_series=cap + 1)
        self._nonfinite_steps = reg.counter(
            "m2kt_train_nonfinite_steps_total",
            "Recorded steps carrying a non-finite gradient, parameter, "
            "or loss")
        self._skipped_steps = reg.counter(
            "m2kt_train_skipped_steps_total",
            "Updates apply_if_finite skipped over non-finite (scaled) "
            "gradients")
        self._loss_scale_gauge = reg.gauge(
            "m2kt_train_loss_scale",
            "Active loss scale (0 = no scaling)")
        self._group_names: list[str] | None = None
        self._skipped_seen = 0
        self._last_bad_group: str | None = None
        # filled by record_cost_model; record_step then keeps the MFU
        # gauge live from measured wall times
        self._cost_report = None
        self._chip_spec = None
        # optional anomaly watchdog (obs/bridge.DiagWatchdog): sync-point
        # step times feed its regression baseline and a detected
        # non-finite step edge-triggers a diagnostic capture. The loop
        # assigns it post-construction; None keeps telemetry standalone.
        self.watchdog = None

    def record_cost_model(self, step_fn, *args,
                          accelerator: str = "") -> None:
        """AOT-introspect the compiled train step (obs/costmodel.py) and
        export the static cost gauges — step FLOPs, roofline class,
        peak-HBM breakdown. Call once after the first step has compiled;
        subsequent :meth:`record_step` calls derive live MFU from it.
        Best-effort: a non-jitted step or an introspection failure is
        recorded as absent, never raised."""
        from move2kube_tpu.obs import costmodel
        try:
            report = costmodel.analyze_step_fn(step_fn, *args)
        except Exception:  # noqa: BLE001 - accounting must never kill a run
            report = None
        if report is None:
            return
        self._cost_report = report
        self._chip_spec, _ = costmodel.chip_spec(accelerator)
        costmodel.export_train_gauges(
            report, self.registry, accelerator=accelerator)

    def record_compile(self, seconds: float) -> None:
        self._compiles.inc()
        self._compile_seconds.inc(max(0.0, seconds))
        if self.tracer is not None:
            now = time.perf_counter()
            self.tracer.record("train.compile", now - max(0.0, seconds), now)

    def record_step(self, step: int, seconds: float, loss=None,
                    state=None, items: int | None = None) -> None:
        if self.tracer is not None:
            now = time.perf_counter()
            attrs = {"step": step}
            if loss is not None:
                try:
                    attrs["loss"] = float(loss)
                except (TypeError, ValueError):
                    pass
            self.tracer.record("train.step", now - max(0.0, seconds), now,
                               attrs=attrs)
        self._step_hist.observe(seconds)
        self._steps.inc()
        self._step_gauge.set(step)
        n = self.items_per_step if items is None else items
        if n and seconds > 0:
            self._throughput.set(n / seconds)
        if loss is not None:
            try:
                self._loss.set(float(loss))
            except (TypeError, ValueError):
                pass
        if state is not None:
            norm = grad_norm_from_state(state)
            if norm is not None:
                self._grad_norm.set(norm)
            if self._numerics_on:
                try:
                    self._record_numerics(step, state, loss)
                except Exception:  # noqa: BLE001 - never kill a run
                    pass
            if self.watchdog is not None:
                # sync points only: unsynced steps record dispatch time,
                # which would poison the regression baseline
                try:
                    self.watchdog.observe_step(seconds)
                except Exception:  # noqa: BLE001 - never kill a run
                    pass
        if (self._cost_report is not None and self._chip_spec is not None
                and seconds > 0):
            mfu = self._cost_report.mfu(seconds, self._chip_spec)
            if mfu is not None:
                self.registry.gauge(
                    "m2kt_train_mfu",
                    "Achieved model-FLOP utilization per chip "
                    "(0 = unknown)").set(mfu)
        if step % self.mem_every == 0:
            self.record_device_memory()

    def record_precision(self, policy) -> None:
        """Export the resolved precision policy's loss scale — call once
        at loop start; the skipped-step counter then tracks what
        ``apply_if_finite`` does with it."""
        try:
            self._loss_scale_gauge.set(float(policy.loss_scale))
        except (AttributeError, TypeError, ValueError):
            pass

    def _record_numerics(self, step: int, state, loss) -> None:
        """Tensor-health read-back (sync points only — ``record_step``
        gates on ``state is not None``): six small vectors cross to
        host, the gauges update per group, and a non-finite step dumps
        the ``<flight>.numerics`` forensics sidecar naming the first bad
        layer group."""
        from move2kube_tpu.models import precision as precisionlib
        numerics = self._numerics
        health = numerics.health_from_state(state)
        if health is None:
            return
        if self._group_names is None:
            self._group_names = numerics.group_index(state.params)[0]
        doc = numerics.summary(self._group_names, health)
        for group, fields in doc.items():
            for kind in ("grad", "param"):
                self._tensor_rms.labels(group, kind).set(
                    fields[f"{kind}_rms"])
                self._tensor_max_abs.labels(group, kind).set(
                    fields[f"{kind}_max_abs"])
                self._tensor_nonfinite.labels(group, kind).set(
                    fields[f"{kind}_nonfinite"])
        skipped = precisionlib.skipped_updates(state)
        if skipped is not None and skipped > self._skipped_seen:
            self._skipped_steps.inc(skipped - self._skipped_seen)
            self._skipped_seen = skipped
        loss_bad = False
        if loss is not None:
            try:
                loss_bad = not math.isfinite(float(loss))
            except (TypeError, ValueError):
                loss_bad = False
        bad = numerics.first_bad_group(doc)
        if bad is None and not loss_bad:
            self._last_bad_group = None
            return
        self._nonfinite_steps.inc()
        self._last_bad_group = bad or "loss"
        if self.watchdog is not None:
            try:
                self.watchdog.note_nonfinite()
            except Exception:  # noqa: BLE001 - never kill a run
                pass
        if self.tracer is not None:
            now = time.perf_counter()
            self.tracer.record("train.numerics.nonfinite", now, now,
                               attrs={"step": step,
                                      "group": self._last_bad_group})
        numerics.write_sidecar({
            "step": step,
            "first_bad_group": self._last_bad_group,
            "loss_nonfinite": loss_bad,
            "skipped_updates": skipped or 0,
            "groups": doc,
        })

    def record_device_memory(self) -> None:
        try:
            self._device_bytes.set(
                sum(int(x.nbytes) for x in jax.live_arrays()))
        except Exception:  # noqa: BLE001 - accounting must never kill a run
            pass

    def timed_step(self, step: int, step_fn, state, batch, sync: bool = False):
        """Run one step under timing. ``sync`` blocks on the loss (true
        step time, used at logging boundaries); unsynced steps measure
        dispatch time, which converges to device time once the pipeline
        is full."""
        t0 = time.perf_counter()
        new_state, loss = step_fn(state, batch)
        if sync:
            loss = jax.block_until_ready(loss)
        self.record_step(step, time.perf_counter() - t0,
                         loss=float(loss) if sync else None,
                         state=new_state if sync else None)
        return new_state, loss


def default_optimizer(lr: float = 1e-3, weight_decay: float = 0.0,
                      warmup_steps: int = 100,
                      total_steps: int = 10000,
                      precision=None) -> optax.GradientTransformation:
    """Warmup-cosine Adam(W). With a ``PrecisionPolicy`` the transform is
    wrapped so non-finite grads (loss-scaling overflow under
    ``bf16-scaled``) skip the update instead of poisoning the fp32
    master weights."""
    schedule = optax.warmup_cosine_decay_schedule(
        0.0, lr, warmup_steps, max(total_steps, warmup_steps + 1))
    tx = (optax.adamw(schedule, weight_decay=weight_decay)
          if weight_decay else optax.adam(schedule))
    if precision is not None:
        tx = precision.wrap_optimizer(tx)
    return tx
