"""Shared training machinery: sharded state creation + train steps.

The emitted training programs (containerizer/jax_emit.py templates) and
bench.py both drive these. Everything compiles once under jit: sharded init
via ``eval_shape`` (no host-side giant arrays), train steps with donated
state, sharding-constrained batches, and loss in float32.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from flax.training import train_state
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from move2kube_tpu.parallel.sharding import ShardingRules, infer_param_axes


class TrainState(train_state.TrainState):
    batch_stats: Any = None  # BatchNorm stats (ResNet); None elsewhere


def _mesh_context(mesh: Mesh):
    """Context that makes bare PartitionSpecs resolvable inside traced code
    (models annotate activations with P(...) without threading the mesh).
    AbstractMesh gets its own context manager: the shape-verification
    path (tests/test_memory_plan.py) traces train steps on device-less
    meshes and ``use_mesh``/``set_mesh`` only accept concrete meshes."""
    if isinstance(mesh, jax.sharding.AbstractMesh):
        return jax.sharding.use_abstract_mesh(mesh)
    use_mesh = getattr(jax.sharding, "use_mesh", None) or getattr(jax, "set_mesh", None)
    return use_mesh(mesh) if use_mesh is not None else mesh


def _with_mesh(mesh: Mesh, fn: Callable) -> Callable:
    if _trivial(mesh):
        return fn  # no ambient mesh: keep the plain single-device compile

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        with _mesh_context(mesh):
            return fn(*args, **kwargs)

    # expose the underlying jit function + its mesh so AOT consumers
    # (assert_state_donated) can .lower() under the right mesh context;
    # the trivial path above returns the jit object itself, which
    # carries .lower natively
    wrapper._m2kt_jit = fn
    wrapper._m2kt_mesh = mesh
    return wrapper


def compiled_alias_count(compiled_text: str) -> int:
    """Number of input buffers the compiled executable aliases into its
    outputs (XLA emits one ``may-alias``/``must-alias`` entry per donated
    buffer in the HloModule ``input_output_alias`` header)."""
    return (compiled_text.count("may-alias")
            + compiled_text.count("must-alias"))


def assert_state_donated(step_fn, state, batch,
                         min_aliased: int | None = None) -> int:
    """Verify that ``step_fn``'s compiled executable really aliases the
    donated state buffers (donate_argnums alone is a *request* — a jit
    wrapper, an out-sharding mismatch or an engine change can silently
    drop it, doubling peak memory). Lowers and compiles for the current
    backend — works on CPU, no TPU needed — and asserts at least
    ``min_aliased`` input-output aliases (default: one per param leaf).
    Returns the alias count."""
    jit_fn = getattr(step_fn, "_m2kt_jit", step_fn)
    mesh = getattr(step_fn, "_m2kt_mesh", None)
    if not hasattr(jit_fn, "lower"):
        raise TypeError(
            "step_fn is not jit-compiled (no .lower); donation cannot be "
            "verified")
    if mesh is not None:
        with _mesh_context(mesh):
            compiled = jit_fn.lower(state, batch).compile()
    else:
        compiled = jit_fn.lower(state, batch).compile()
    n = compiled_alias_count(compiled.as_text())
    params = getattr(state, "params", state)
    floor = (min_aliased if min_aliased is not None
             else len(jax.tree.leaves(params)))
    if n < floor:
        raise AssertionError(
            f"compiled train step aliases only {n} input buffers; expected "
            f">= {floor} — state donation is not reaching the executable")
    return n


def cross_entropy_loss(logits, labels) -> jax.Array:
    logits = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    # gather the label log-prob instead of materialising a one-hot
    # (batch, classes) float32 tensor — saves HBM bandwidth on the
    # backward pass; identical math
    picked = jnp.take_along_axis(logp, labels[..., None].astype(jnp.int32),
                                 axis=-1)
    return -jnp.mean(picked)


def lm_loss(logits, input_ids) -> jax.Array:
    """Next-token prediction loss."""
    return cross_entropy_loss(logits[:, :-1], input_ids[:, 1:])


def batch_sharding(mesh: Mesh):
    """Input-batch sharding; SingleDeviceSharding on trivial meshes so
    committed batches never trigger the SPMD pipeline (see _trivial)."""
    if _trivial(mesh):
        return jax.sharding.SingleDeviceSharding(mesh.devices.flat[0])
    return _sharding(mesh, P(("data", "fsdp")))


def _sharding(mesh, spec: P):
    """NamedSharding for a concrete Mesh; the bare PartitionSpec for an
    AbstractMesh (with_sharding_constraint resolves it against the
    ambient mesh, letting train steps trace under ``jax.eval_shape`` on
    device-less meshes — the BASELINE config-5 shape-verification path,
    tests/test_memory_plan.py)."""
    if isinstance(mesh, jax.sharding.AbstractMesh):
        return spec
    return NamedSharding(mesh, spec)


def _trivial(mesh) -> bool:
    """True for a single-device concrete mesh. Trivial meshes compile
    the PLAIN jit path — no sharding constraints, no mesh context, no
    out_shardings: semantically identical (every constraint is a no-op
    at one device) but compiled WITHOUT the SPMD pipeline. Measured:
    a mesh-compiled ResNet-50 train step runs ~7x slower than the
    identical plain-jit program on the CPU backend despite structurally
    identical HLO (round-5 bisection, docs/ROUND5_NOTES.md) — single
    chips must never pay a partitioner tax for machinery they don't
    use."""
    return (not isinstance(mesh, jax.sharding.AbstractMesh)
            and mesh.devices.size == 1)


def _constrain(x, mesh: Mesh, spec: P):
    """with_sharding_constraint, skipped on trivial meshes."""
    if _trivial(mesh):
        return x
    return jax.lax.with_sharding_constraint(x, _sharding(mesh, spec))


def create_sharded_state(
    rng: jax.Array,
    model,
    sample_input: dict,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    rules: ShardingRules | None = None,
    has_batch_stats: bool = False,
) -> TrainState:
    """Initialize params directly into their shards (ZeRO-3-style): shapes
    come from eval_shape, shardings from the logical-axis heuristic, and the
    actual init runs under jit with those out_shardings so no device ever
    materialises the full tree."""
    rules = rules or ShardingRules.default()

    def init_fn(rng):
        variables = model.init(rng, **sample_input)
        # keep only persistent state: sown collections like MoE "losses"
        # are per-forward outputs, not state to carry in TrainState
        return {k: v for k, v in variables.items()
                if k in ("params", "batch_stats")}

    if _trivial(mesh):
        # single device: SingleDeviceSharding outputs, no NamedShardings
        # — the train step compiles WITHOUT the SPMD pipeline (see
        # _trivial; ~7x on the CPU backend) while still landing on the
        # MESH'S device (which need not be the default one: per-chip
        # trainer processes build one-device meshes over their own chip)
        variables = jax.jit(
            init_fn,
            out_shardings=jax.sharding.SingleDeviceSharding(
                mesh.devices.flat[0]))(rng)
        return _make_state(model, variables, tx)

    with _mesh_context(mesh):
        shapes = jax.eval_shape(init_fn, rng)
    params_axes = infer_param_axes(shapes["params"])

    def _sharding_for(axes, shape_leaf):
        """Heuristic axes -> NamedSharding, dropping any dim whose size
        isn't divisible by its mesh extent (e.g. a 3-channel conv_out on
        an fsdp=2 mesh): GSPMD refuses uneven param shards outright, and
        replicating one small leaf beats failing init."""
        if not isinstance(axes, tuple):
            return NamedSharding(mesh, P())
        spec = rules.spec(axes)
        pruned = []
        for dim, entry in enumerate(spec):
            names = (entry,) if isinstance(entry, str) else (entry or ())
            extent = 1
            for nm in names:
                extent *= mesh.shape[nm]
            pruned.append(entry if extent > 1
                          and shape_leaf.shape[dim] % extent == 0 else None)
        return NamedSharding(mesh, P(*pruned))

    param_shardings = jax.tree.map(
        _sharding_for, params_axes, shapes["params"],
        is_leaf=lambda x: isinstance(x, tuple) or x is None,
    )
    out_shardings = {"params": param_shardings}
    if has_batch_stats and "batch_stats" in shapes:
        out_shardings["batch_stats"] = jax.tree.map(
            lambda _: NamedSharding(mesh, P()), shapes["batch_stats"]
        )
    with _mesh_context(mesh):
        variables = jax.jit(init_fn, out_shardings=out_shardings)(rng)
    return _make_state(model, variables, tx)


def _make_state(model, variables, tx) -> TrainState:
    return TrainState.create(
        apply_fn=model.apply,
        params=variables["params"],
        tx=tx,
        batch_stats=variables.get("batch_stats"),
    )


def make_classifier_train_step(mesh: Mesh, has_batch_stats: bool = False,
                               scan_steps: int | None = None):
    """Train step for image/sequence classifiers (ResNet, BERT).

    With ``scan_steps=k`` the returned function consumes a batch whose
    leaves carry a leading axis of length k and runs k optimizer steps in
    ONE compiled call via ``lax.scan`` (returns per-step losses). One
    dispatch per k steps matters when the host-device link is
    high-latency (remote TPU tunnels) and lets emitted programs prefetch
    k host batches per device call.
    """

    def one_step(state: TrainState, batch: dict):
        x = _constrain(batch["input"], mesh, P(("data", "fsdp")))
        y = batch["label"]

        def loss_fn(params):
            variables = {"params": params}
            if has_batch_stats:
                variables["batch_stats"] = state.batch_stats
                logits, updates = state.apply_fn(
                    variables, x, mutable=["batch_stats"])
                return cross_entropy_loss(logits, y), updates["batch_stats"]
            logits = state.apply_fn(variables, x)
            return cross_entropy_loss(logits, y), None

        (loss, new_stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(state.params)
        state = state.apply_gradients(grads=grads)
        if has_batch_stats:
            state = state.replace(batch_stats=new_stats)
        return state, loss

    if scan_steps is None:
        step = functools.partial(jax.jit, donate_argnums=(0,))(one_step)
        return _with_mesh(mesh, step)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step_k(state: TrainState, batches: dict):
        return jax.lax.scan(one_step, state, batches, length=scan_steps)

    return _with_mesh(mesh, step_k)


def make_bert_train_step(mesh: Mesh, scan_steps: int | None = None):
    """Fine-tune step for BertEncoder (input_ids/attention_mask/label).

    ``scan_steps`` as in :func:`make_classifier_train_step`: fuse k steps
    into one compiled call over a batch with a leading k axis.
    """

    def one_step(state: TrainState, batch: dict):
        ids = _constrain(batch["input_ids"], mesh, P(("data", "fsdp")))
        mask = batch.get("attention_mask")

        def loss_fn(params):
            logits = state.apply_fn({"params": params}, ids, mask)
            return cross_entropy_loss(logits, batch["label"])

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        return state.apply_gradients(grads=grads), loss

    if scan_steps is None:
        step = functools.partial(jax.jit, donate_argnums=(0,))(one_step)
        return _with_mesh(mesh, step)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step_k(state: TrainState, batches: dict):
        return jax.lax.scan(one_step, state, batches, length=scan_steps)

    return _with_mesh(mesh, step_k)


def make_diffusion_train_step(mesh: Mesh, scan_steps: int | None = None,
                              num_diffusion_steps: int = 1000):
    """DDPM denoising step for the UNet (models/unet.py): the batch
    carries clean images, pre-sampled gaussian noise and integer
    timesteps; the step forms x_t from the (static, on-device) linear-
    beta schedule and regresses the predicted noise with MSE — the
    standard DDPM objective.

    ``scan_steps`` as in :func:`make_classifier_train_step`: fuse k steps
    into one compiled call over a batch with a leading k axis.
    """
    from move2kube_tpu.models.unet import ddpm_alpha_bars

    alpha_bars = ddpm_alpha_bars(num_diffusion_steps)

    def one_step(state: TrainState, batch: dict):
        x0 = _constrain(batch["image"], mesh, P(("data", "fsdp")))
        noise = _constrain(batch["noise"], mesh, P(("data", "fsdp")))
        t = batch["t"]
        ab = alpha_bars[t][:, None, None, None]
        x_t = (jnp.sqrt(ab) * x0.astype(jnp.float32)
               + jnp.sqrt(1.0 - ab) * noise.astype(jnp.float32))

        def loss_fn(params):
            pred = state.apply_fn({"params": params}, x_t, t)
            return jnp.mean((pred - noise.astype(jnp.float32)) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        return state.apply_gradients(grads=grads), loss

    if scan_steps is None:
        step = functools.partial(jax.jit, donate_argnums=(0,))(one_step)
        return _with_mesh(mesh, step)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step_k(state: TrainState, batches: dict):
        return jax.lax.scan(one_step, state, batches, length=scan_steps)

    return _with_mesh(mesh, step_k)


def make_lm_train_step(mesh: Mesh, remat: bool = True,
                       moe_aux_weight: float = 0.01):
    """Next-token-prediction step for Llama-class models; rematerialises
    per-block activations (jax.checkpoint) to trade FLOPs for HBM.

    MoE models sow their load-balancing losses into the ``losses``
    collection (llama.py LlamaBlock); they are summed into the loss with
    weight ``moe_aux_weight`` (no-op for dense models: the collection is
    empty)."""

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(state: TrainState, batch: dict):
        ids = _constrain(batch["input_ids"], mesh, P(("data", "fsdp")))

        def loss_fn(params):
            def fwd(p, x):
                return state.apply_fn({"params": p}, x, mutable=["losses"])

            if remat:
                fwd = jax.checkpoint(fwd)
            logits, sown = fwd(params, ids)
            aux = sum((jnp.sum(v) for v in jax.tree.leaves(sown)),
                      jnp.float32(0.0))
            return lm_loss(logits, ids) + moe_aux_weight * aux

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        return state.apply_gradients(grads=grads), loss

    return _with_mesh(mesh, step)


def default_optimizer(lr: float = 1e-3, weight_decay: float = 0.0,
                      warmup_steps: int = 100,
                      total_steps: int = 10000) -> optax.GradientTransformation:
    schedule = optax.warmup_cosine_decay_schedule(
        0.0, lr, warmup_steps, max(total_steps, warmup_steps + 1))
    if weight_decay:
        return optax.adamw(schedule, weight_decay=weight_decay)
    return optax.adam(schedule)
