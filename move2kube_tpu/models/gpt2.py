"""GPT-2 decoder in Flax, TPU-first.

Emission target for detected HF GPT-2 fine-tunes (gpu_detect family
``gpt`` with no model parallelism — jax_emit maps those to this model so
``port_weights.py`` can load real ``GPT2LMHeadModel`` checkpoints;
Megatron-style parallel GPT workloads keep the Llama-class trainer).

Architecture follows HF ``transformers`` GPT-2 exactly so converted
weights reproduce its logits (tests/test_convert.py): learned positional
embeddings, pre-LN blocks, fused c_attn projection, tanh-approx GELU,
LM head tied to the token embedding.

TPU notes: LayerNorm/softmax in float32, matmuls in bfloat16 on the MXU;
attention goes through ops/attention.py (Pallas flash kernel on TPU for
tile-friendly shapes, jnp reference elsewhere). Tensor-parallel sharding
mirrors models/llama.py: column-split fused c_attn/c_fc, row-split
attn_out/mlp_out (parallel/sharding.py rules), activation constraints on
the tensor axis so XLA inserts the psum where Megatron would all-reduce.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import flax.linen as nn
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from move2kube_tpu.ops.attention import flash_attention
from move2kube_tpu.parallel.sharding import maybe_shard as _maybe_shard


@dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    n_positions: int = 1024
    d_model: int = 768
    num_layers: int = 12
    num_heads: int = 12
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    # flash | ring | ulysses — same dispatch as LlamaConfig.attn_impl;
    # ring/ulysses engage context parallelism over the mesh's ``seq`` axis
    # for detected sequence-parallel fine-tunes (dense folds to flash:
    # this model has no separate einsum path)
    attn_impl: str = "flash"
    # False inside the compiled GPipe stages (models/gpt2_pipe.py):
    # sharding constraints are invalid under shard_map's manual axes
    # (same flag as LlamaConfig.shard_activations)
    shard_activations: bool = True


def gpt2_small() -> GPT2Config:
    return GPT2Config()


def gpt2_tiny() -> GPT2Config:
    """Small variant for tests / dry-runs."""
    return GPT2Config(vocab_size=256, n_positions=64, d_model=64,
                      num_layers=2, num_heads=4)


class GPT2Block(nn.Module):
    cfg: GPT2Config

    @nn.compact
    def __call__(self, x, cache=None, return_kv=False):
        cfg = self.cfg
        b, s, d = x.shape
        head_dim = d // cfg.num_heads

        h = nn.LayerNorm(epsilon=cfg.norm_eps, dtype=jnp.float32,
                         name="ln_1")(x)
        # fused qkv, HF Conv1D layout [in, 3*d] == flax Dense kernel
        qkv = nn.Dense(3 * d, dtype=cfg.dtype, name="c_attn")(h.astype(cfg.dtype))
        if cfg.shard_activations:
            qkv = _maybe_shard(qkv, P(("data", "fsdp"), None, "tensor"))
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, s, cfg.num_heads, head_dim)
        k = k.reshape(b, s, cfg.num_heads, head_dim)
        v = v.reshape(b, s, cfg.num_heads, head_dim)
        new_kv = (k, v) if return_kv else None
        if cache is not None:
            # single-token decode against the paged KV cache: write this
            # token's K/V into its page, attend over the block table
            # (models/llama.py LlamaBlock carries the same path; GPT-2 is
            # MHA, so the kernel's GQA batching degenerates to rep=1)
            from move2kube_tpu.ops.attention import (
                paged_decode_attention, quantize_kv_rows)

            k_pages, v_pages = cache["k"], cache["v"]
            block_size = k_pages.shape[1]
            pos = cache["positions"]
            blk = cache["block_tables"][jnp.arange(b), pos // block_size]
            off = pos % block_size
            k_scale = cache.get("k_scale")
            v_scale = cache.get("v_scale")
            if k_scale is not None:
                # int8 cache: quantized rows + per-(token, kv-head) scales
                qk, sk = quantize_kv_rows(k[:, 0])
                qv, sv = quantize_kv_rows(v[:, 0])
                k_pages = k_pages.at[blk, off].set(qk)
                v_pages = v_pages.at[blk, off].set(qv)
                k_scale = k_scale.at[blk, off].set(sk)
                v_scale = v_scale.at[blk, off].set(sv)
            else:
                k_pages = k_pages.at[blk, off].set(
                    k[:, 0].astype(k_pages.dtype))
                v_pages = v_pages.at[blk, off].set(
                    v[:, 0].astype(v_pages.dtype))
            o = paged_decode_attention(
                q[:, 0], k_pages, v_pages, cache["block_tables"],
                cache["seq_lens"], k_scale=k_scale,
                v_scale=v_scale).reshape(b, 1, d)
            new_kv = (k_pages, v_pages, k_scale, v_scale)
        elif cfg.attn_impl in ("ring", "ulysses"):
            # shared dispatcher with the Llama stack (ring/ulysses run
            # under shard_map on the mesh's seq axis, degrading to flash
            # when that axis is trivial)
            from move2kube_tpu.models.llama import _attention

            o = _attention(q, k, v, None, cfg.attn_impl).reshape(b, s, d)
        else:
            o = flash_attention(q, k, v, causal=True).reshape(b, s, d)
        o = nn.Dense(d, dtype=cfg.dtype, name="attn_out")(o)
        x = x + o

        h = nn.LayerNorm(epsilon=cfg.norm_eps, dtype=jnp.float32,
                         name="ln_2")(x)
        h = nn.Dense(4 * d, dtype=cfg.dtype, name="c_fc")(h.astype(cfg.dtype))
        if cfg.shard_activations:
            h = _maybe_shard(h, P(("data", "fsdp"), None, "tensor"))
        h = nn.gelu(h, approximate=True)  # HF gelu_new
        h = nn.Dense(d, dtype=cfg.dtype, name="mlp_out")(h)
        if new_kv is not None:
            return x + h, new_kv
        return x + h


class GPT2(nn.Module):
    cfg: GPT2Config

    @nn.compact
    def __call__(self, input_ids, positions=None, cache=None,
                 return_kv=False, return_hidden=False, lora=None):
        """Same three modes as models/llama.py ``Llama.__call__``:
        full forward (default), prefill (``return_kv=True`` also returns
        per-layer K/V), and paged single-token decode (``cache=`` with
        ``input_ids``/``positions`` shaped ``[b]``). ``lora`` is the
        scheduler's paged multi-LoRA hook on the (tied) LM head — see
        ``_lora_delta`` in models/llama.py."""
        cfg = self.cfg
        wte = nn.Embed(cfg.vocab_size, cfg.d_model, dtype=cfg.dtype,
                       name="wte")
        wpe = nn.Embed(cfg.n_positions, cfg.d_model, dtype=cfg.dtype,
                       name="wpe")
        if cache is not None:
            x = wte(input_ids[:, None]) + wpe(positions[:, None])
            quantized = "k_scale" in cache
            new_k, new_v, new_ks, new_vs = [], [], [], []
            for i in range(cfg.num_layers):
                layer_cache = {
                    "k": cache["k"][i], "v": cache["v"][i],
                    "block_tables": cache["block_tables"],
                    "seq_lens": cache["seq_lens"],
                    "positions": positions,
                }
                if quantized:
                    layer_cache["k_scale"] = cache["k_scale"][i]
                    layer_cache["v_scale"] = cache["v_scale"][i]
                x, (kp, vp, ksp, vsp) = GPT2Block(cfg, name=f"h_{i}")(
                    x, cache=layer_cache)
                new_k.append(kp)
                new_v.append(vp)
                new_ks.append(ksp)
                new_vs.append(vsp)
            x = nn.LayerNorm(epsilon=cfg.norm_eps, dtype=jnp.float32,
                             name="ln_f")(x)
            x32 = x.astype(jnp.float32)
            logits = x32 @ wte.embedding.astype(jnp.float32).T
            if lora is not None:
                from move2kube_tpu.models.llama import _lora_delta
                logits = logits + _lora_delta(x32, lora)
            out_cache = dict(cache)
            out_cache["k"] = type(cache["k"])(new_k)
            out_cache["v"] = type(cache["v"])(new_v)
            if quantized:
                out_cache["k_scale"] = type(cache["k_scale"])(new_ks)
                out_cache["v_scale"] = type(cache["v_scale"])(new_vs)
            return logits[:, 0], out_cache
        b, s = input_ids.shape
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
        x = wte(input_ids) + wpe(positions)
        kvs = []
        for i in range(cfg.num_layers):
            out = GPT2Block(cfg, name=f"h_{i}")(x, return_kv=return_kv)
            if return_kv:
                x, kv = out
                kvs.append(kv)
            else:
                x = out
        x = nn.LayerNorm(epsilon=cfg.norm_eps, dtype=jnp.float32,
                         name="ln_f")(x)
        if return_hidden:
            # pre-head hidden states for the fused chunked lm-head CE
            # (ops/crossentropy.py); the tied wte.embedding.T head is
            # folded into the loss chunk loop by the caller
            return x
        # LM head tied to the token embedding (HF GPT2LMHeadModel ties)
        x32 = x.astype(jnp.float32)
        logits = x32 @ wte.embedding.astype(jnp.float32).T
        if lora is not None:
            from move2kube_tpu.models.llama import _lora_delta
            logits = logits + _lora_delta(x32, lora)
        if return_kv:
            return logits, kvs
        return logits
