from move2kube_tpu.metadata.base import Loader, get_loaders  # noqa: F401
