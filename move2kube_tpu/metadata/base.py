"""Metadata loaders: feed collected/auxiliary data into plan and IR.

Parity: ``internal/metadata/metadata.go:25-34`` — loaders update the plan
at plan time and load data into the IR at translate time. Registry:
ClusterMDLoader, K8sFilesLoader, QACacheLoader.
"""

from __future__ import annotations

import os

from move2kube_tpu.metadata import clusters
from move2kube_tpu.source import kube2kube
from move2kube_tpu.types import collection as collecttypes
from move2kube_tpu.types.ir import IR
from move2kube_tpu.types.plan import Plan, PlanService, TargetCluster, TranslationType
from move2kube_tpu.utils import common
from move2kube_tpu.utils.log import get_logger

log = get_logger("metadata")


class Loader:
    def update_plan(self, plan: Plan) -> None:
        pass

    def load_to_ir(self, plan: Plan, ir: IR) -> None:
        pass


class ClusterMDLoader(Loader):
    """Parity: internal/metadata/clustermdloader.go:38-140."""

    def update_plan(self, plan: Plan) -> None:
        for path in common.get_files_by_ext(plan.root_dir, [".yaml", ".yml"]):
            try:
                doc = common.read_m2kt_yaml(path, collecttypes.CLUSTER_METADATA_KIND)
            except Exception:  # noqa: BLE001
                continue
            cm = collecttypes.ClusterMetadata.from_dict(doc)
            plan.target_info_artifacts.setdefault(
                Plan.TARGET_CLUSTERS_ARTIFACT, []
            ).append(path)
            log.info("found collected cluster metadata %s (%s)", cm.name, path)
        if not plan.kubernetes.target_cluster.type and not plan.kubernetes.target_cluster.path:
            # default: TPU cluster when the plan has GPU training services
            # (detected CUDA sources OR GPU-requesting k8s/compose inputs,
            # which carry AcceleratorInfo instead of the GPU2TPU type)
            has_tpu = any(
                s.translation_type == TranslationType.GPU2TPU
                or s.accelerator is not None
                for svcs in plan.services.values() for s in svcs
            )
            plan.kubernetes.target_cluster = TargetCluster(
                type=clusters.DEFAULT_TPU_CLUSTER if has_tpu else clusters.DEFAULT_CLUSTER
            )

    def load_to_ir(self, plan: Plan, ir: IR) -> None:
        ir.target_cluster_spec = clusters.resolve_target_cluster(
            plan.kubernetes.target_cluster)


class K8sFilesLoader(Loader):
    """Parity: internal/metadata/k8sfiles.go:35-95."""

    def update_plan(self, plan: Plan) -> None:
        max_gpus = 0
        for path in common.get_files_by_ext(plan.root_dir, [".yaml", ".yml"]):
            try:
                import yaml

                with open(path, encoding="utf-8") as f:
                    docs = list(yaml.safe_load_all(f))
            except Exception:  # noqa: BLE001
                continue
            k8s_docs = [
                d for d in docs
                if isinstance(d, dict) and d.get("kind") and d.get("apiVersion")
                and not str(d.get("apiVersion", "")).startswith("move2kube-tpu.io")
                and not isinstance(d.get("services"), dict)  # not a compose file
            ]
            if not k8s_docs:
                continue
            if path not in plan.k8s_files:
                plan.k8s_files.append(path)
            # scan every file (also on re-plan of an existing plan file)
            max_gpus = max(max_gpus, max(
                (kube2kube.k8s_doc_gpu_count(d) for d in k8s_docs), default=0))
        if plan.k8s_files:
            # register a kube2kube service so translate picks the files up
            svc = PlanService(
                service_name=common.make_dns_label(plan.name + "-k8s"),
                translation_type=TranslationType.KUBE2KUBE,
                container_build_type="Reuse",
            )
            for f in plan.k8s_files:
                svc.add_source_artifact(PlanService.K8S_ARTIFACT, f)
            if max_gpus:
                # record the GPU->TPU mapping in the plan so curation shows
                # it and ClusterMDLoader targets the TPU cluster profile
                from move2kube_tpu.source import gpu_detect
                from move2kube_tpu.types.plan import AcceleratorInfo

                acc_type, topo, hosts = gpu_detect.map_gpu_to_tpu(max_gpus)
                svc.accelerator = AcceleratorInfo(
                    gpu_count=max_gpus, gpu_vendor="nvidia.com/gpu",
                    tpu_accelerator=acc_type, tpu_topology=topo,
                    num_hosts=hosts)
            plan.add_service(svc)

    def load_to_ir(self, plan: Plan, ir: IR) -> None:
        pass  # kube2kube translator loads the files


class QACacheLoader(Loader):
    """Parity: internal/metadata/qacaches.go:33-60."""

    def update_plan(self, plan: Plan) -> None:
        for path in common.get_files_by_name(plan.root_dir, [common.QA_CACHE_FILE]):
            if path not in plan.qa_caches:
                plan.qa_caches.append(path)

    def load_to_ir(self, plan: Plan, ir: IR) -> None:
        from move2kube_tpu.qa import add_cache_engine

        for path in plan.qa_caches:
            if os.path.exists(path):
                add_cache_engine(path)


def get_loaders() -> list[Loader]:
    # K8sFilesLoader before ClusterMDLoader: the cluster default depends on
    # whether registered services carry accelerator info (GPU k8s inputs)
    return [K8sFilesLoader(), ClusterMDLoader(), QACacheLoader()]
