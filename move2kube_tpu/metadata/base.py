"""Metadata loaders: feed collected/auxiliary data into plan and IR.

Parity: ``internal/metadata/metadata.go:25-34`` — loaders update the plan
at plan time and load data into the IR at translate time. Registry:
ClusterMDLoader, K8sFilesLoader, QACacheLoader.
"""

from __future__ import annotations

import os

from move2kube_tpu.metadata import clusters
from move2kube_tpu.types import collection as collecttypes
from move2kube_tpu.types.ir import IR
from move2kube_tpu.types.plan import Plan, PlanService, TargetCluster, TranslationType
from move2kube_tpu.utils import common
from move2kube_tpu.utils.log import get_logger

log = get_logger("metadata")


class Loader:
    def update_plan(self, plan: Plan) -> None:
        pass

    def load_to_ir(self, plan: Plan, ir: IR) -> None:
        pass


class ClusterMDLoader(Loader):
    """Parity: internal/metadata/clustermdloader.go:38-140."""

    def update_plan(self, plan: Plan) -> None:
        for path in common.get_files_by_ext(plan.root_dir, [".yaml", ".yml"]):
            try:
                doc = common.read_m2kt_yaml(path, collecttypes.CLUSTER_METADATA_KIND)
            except Exception:  # noqa: BLE001
                continue
            cm = collecttypes.ClusterMetadata.from_dict(doc)
            plan.target_info_artifacts.setdefault(
                Plan.TARGET_CLUSTERS_ARTIFACT, []
            ).append(path)
            log.info("found collected cluster metadata %s (%s)", cm.name, path)
        if not plan.kubernetes.target_cluster.type and not plan.kubernetes.target_cluster.path:
            # default: TPU cluster when the plan has GPU training services
            has_tpu = any(
                s.translation_type == TranslationType.GPU2TPU
                for svcs in plan.services.values() for s in svcs
            )
            plan.kubernetes.target_cluster = TargetCluster(
                type=clusters.DEFAULT_TPU_CLUSTER if has_tpu else clusters.DEFAULT_CLUSTER
            )

    def load_to_ir(self, plan: Plan, ir: IR) -> None:
        tc = plan.kubernetes.target_cluster
        if tc.path:
            try:
                cm = collecttypes.read_cluster_metadata(tc.path)
                ir.target_cluster_spec = cm.spec
                return
            except Exception as e:  # noqa: BLE001
                log.warning("cannot read cluster metadata %s: %s", tc.path, e)
        name = tc.type or clusters.DEFAULT_CLUSTER
        cm = clusters.get_cluster(name)
        if cm is None:
            log.warning("unknown cluster profile %r; using %s", name, clusters.DEFAULT_CLUSTER)
            cm = clusters.get_cluster(clusters.DEFAULT_CLUSTER)
        ir.target_cluster_spec = cm.spec


class K8sFilesLoader(Loader):
    """Parity: internal/metadata/k8sfiles.go:35-95."""

    def update_plan(self, plan: Plan) -> None:
        for path in common.get_files_by_ext(plan.root_dir, [".yaml", ".yml"]):
            try:
                import yaml

                with open(path, encoding="utf-8") as f:
                    docs = list(yaml.safe_load_all(f))
            except Exception:  # noqa: BLE001
                continue
            k8s_docs = [
                d for d in docs
                if isinstance(d, dict) and d.get("kind") and d.get("apiVersion")
                and not str(d.get("apiVersion", "")).startswith("move2kube-tpu.io")
                and not isinstance(d.get("services"), dict)  # not a compose file
            ]
            if k8s_docs and path not in plan.k8s_files:
                plan.k8s_files.append(path)
        if plan.k8s_files:
            # register a kube2kube service so translate picks the files up
            svc = PlanService(
                service_name=common.make_dns_label(plan.name + "-k8s"),
                translation_type=TranslationType.KUBE2KUBE,
                container_build_type="Reuse",
            )
            for f in plan.k8s_files:
                svc.add_source_artifact(PlanService.K8S_ARTIFACT, f)
            plan.add_service(svc)

    def load_to_ir(self, plan: Plan, ir: IR) -> None:
        pass  # kube2kube translator loads the files


class QACacheLoader(Loader):
    """Parity: internal/metadata/qacaches.go:33-60."""

    def update_plan(self, plan: Plan) -> None:
        for path in common.get_files_by_name(plan.root_dir, [common.QA_CACHE_FILE]):
            if path not in plan.qa_caches:
                plan.qa_caches.append(path)

    def load_to_ir(self, plan: Plan, ir: IR) -> None:
        from move2kube_tpu.qa import add_cache_engine

        for path in plan.qa_caches:
            if os.path.exists(path):
                add_cache_engine(path)


def get_loaders() -> list[Loader]:
    return [ClusterMDLoader(), K8sFilesLoader(), QACacheLoader()]
