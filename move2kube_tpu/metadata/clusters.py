"""Built-in target-cluster profiles.

Parity: ``internal/metadata/clusters/constants.go`` — kind -> preferred
group/version tables for AWS-EKS, Azure-AKS, GCP-GKE, IBM-IKS,
IBM-Openshift, Kubernetes, Openshift. Each profile carries the
multi-version preference lists of the cluster vintage it names
(constants.go:23-1116): the FIRST same-group entry wins at write time
(apiresource/base.py ``_fix_version``), so e.g. an EKS target downgrades
emitted Ingresses to ``networking.k8s.io/v1beta1`` (with the legacy
backend schema) and CronJobs to ``batch/v1beta1``, while the vintage
Openshift profiles keep ``extensions/v1beta1`` Ingresses.

Net-new profiles:
- **GCP-GKE-TPU** adds JobSet (jobset.x-k8s.io) + modern versions so TPU
  training services emit multi-host JobSets; it is the default target
  when a plan contains Gpu2Tpu services.
- **Kubernetes-Knative** advertises ``serving.knative.dev`` so the
  Knative transformer's write-time version fix has a knative-capable
  builtin target.
"""

from __future__ import annotations

from move2kube_tpu.types.collection import ClusterMetadata, ClusterMetadataSpec

_COMMON_CORE: dict[str, list[str]] = {
    "Pod": ["v1"],
    "Service": ["v1"],
    "ConfigMap": ["v1"],
    "Secret": ["v1"],
    "PersistentVolumeClaim": ["v1"],
    "ServiceAccount": ["v1"],
    "ReplicationController": ["v1"],
    "Role": ["rbac.authorization.k8s.io/v1", "rbac.authorization.k8s.io/v1beta1"],
    "RoleBinding": ["rbac.authorization.k8s.io/v1",
                    "rbac.authorization.k8s.io/v1beta1"],
    "Deployment": ["apps/v1"],
    "DaemonSet": ["apps/v1"],
    "StatefulSet": ["apps/v1"],
    "Job": ["batch/v1"],
    # cluster vintages captured by the reference tables: CronJob GA'd
    # (batch/v1) only in k8s 1.21, so every profile prefers v1beta1
    "CronJob": ["batch/v1beta1"],
    "Ingress": ["networking.k8s.io/v1", "networking.k8s.io/v1beta1",
                "extensions/v1beta1"],
    "NetworkPolicy": ["networking.k8s.io/v1"],
    "HorizontalPodAutoscaler": ["autoscaling/v1", "autoscaling/v2beta1",
                                "autoscaling/v2beta2"],
    "PodSecurityPolicy": ["policy/v1beta1"],
}

# EKS/AKS/GKE vintage: Ingress pre-dates networking.k8s.io/v1
_HOSTED_CLOUD_OVERRIDES: dict[str, list[str]] = {
    "Ingress": ["networking.k8s.io/v1beta1", "extensions/v1beta1"],
}

_IKS_OVERRIDES: dict[str, list[str]] = {
    "CronJob": ["batch/v1beta1", "batch/v2alpha1"],
}

_OPENSHIFT_EXTRAS: dict[str, list[str]] = {
    "DeploymentConfig": ["apps.openshift.io/v1"],
    "Route": ["route.openshift.io/v1"],
    "ImageStream": ["image.openshift.io/v1"],
    "BuildConfig": ["build.openshift.io/v1"],
    # vintage 3.x/4.x Openshift: legacy apps groups still served, and
    # Ingress only via the extensions umbrella (Routes are the native way)
    "Deployment": ["apps/v1", "apps/v1beta1", "apps/v1beta2",
                   "extensions/v1beta1"],
    "DaemonSet": ["apps/v1", "apps/v1beta2", "extensions/v1beta1"],
    "StatefulSet": ["apps/v1", "apps/v1beta1", "apps/v1beta2"],
    "Ingress": ["extensions/v1beta1"],
    "NetworkPolicy": ["networking.k8s.io/v1", "extensions/v1beta1"],
    "HorizontalPodAutoscaler": ["autoscaling/v1", "autoscaling/v2beta1"],
    "PodSecurityPolicy": ["extensions/v1beta1", "policy/v1beta1"],
}

# modern-cluster overrides for the TPU profile: JobSet needs k8s >= 1.27,
# where the legacy groups are long gone and CronJob/HPA are GA
_MODERN_OVERRIDES: dict[str, list[str]] = {
    "CronJob": ["batch/v1"],
    "Ingress": ["networking.k8s.io/v1"],
    "HorizontalPodAutoscaler": ["autoscaling/v2"],
}

_TEKTON: dict[str, list[str]] = {
    "Pipeline": ["tekton.dev/v1beta1"],
    "PipelineRun": ["tekton.dev/v1beta1"],
    "Task": ["tekton.dev/v1beta1"],
    "EventListener": ["triggers.tekton.dev/v1alpha1"],
    "TriggerBinding": ["triggers.tekton.dev/v1alpha1"],
    "TriggerTemplate": ["triggers.tekton.dev/v1alpha1"],
}

_KNATIVE: dict[str, list[str]] = {
    "Service": ["serving.knative.dev/v1", "v1"],
}


def _profile(name: str, extra: dict[str, list[str]] | None = None,
             drop: list[str] | None = None,
             storage_classes: list[str] | None = None,
             tpu_accelerators: list[str] | None = None) -> ClusterMetadata:
    kinds = {k: list(v) for k, v in _COMMON_CORE.items()}
    kinds.update({k: list(v) for k, v in (_TEKTON | (extra or {})).items()})
    for k in drop or []:
        kinds.pop(k, None)
    return ClusterMetadata(
        name=name,
        spec=ClusterMetadataSpec(
            api_kind_version_map=kinds,
            storage_classes=storage_classes or ["default"],
            tpu_accelerators=tpu_accelerators or [],
        ),
    )


def builtin_clusters() -> dict[str, ClusterMetadata]:
    profiles = {
        "Kubernetes": _profile("Kubernetes", extra=_IKS_OVERRIDES),
        "AWS-EKS": _profile("AWS-EKS", extra=_HOSTED_CLOUD_OVERRIDES,
                            storage_classes=["gp2", "default"]),
        "Azure-AKS": _profile("Azure-AKS", extra=_HOSTED_CLOUD_OVERRIDES,
                              storage_classes=["managed-premium", "default"]),
        "GCP-GKE": _profile("GCP-GKE", extra=_HOSTED_CLOUD_OVERRIDES,
                            storage_classes=["standard-rwo", "standard"]),
        "IBM-IKS": _profile("IBM-IKS", extra=_IKS_OVERRIDES,
                            storage_classes=["ibmc-file-gold", "default"]),
        "IBM-Openshift": _profile("IBM-Openshift", extra=_OPENSHIFT_EXTRAS,
                                  storage_classes=["ibmc-file-gold", "default"]),
        "Openshift": _profile("Openshift", extra=_OPENSHIFT_EXTRAS),
        "Kubernetes-Knative": _profile("Kubernetes-Knative",
                                       extra=_IKS_OVERRIDES | _KNATIVE),
        "GCP-GKE-TPU": _profile(
            "GCP-GKE-TPU",
            extra=_MODERN_OVERRIDES | {
                "JobSet": ["jobset.x-k8s.io/v1alpha2"],
                # managed-collection GKE ships the prometheus-operator
                # CRDs; lets the optional PodMonitor emit un-dropped
                "PodMonitor": ["monitoring.coreos.com/v1"],
            },
            drop=["PodSecurityPolicy"],  # removed in k8s 1.25; JobSet needs 1.27
            storage_classes=["standard-rwo", "standard"],
            tpu_accelerators=[
                "tpu-v4-podslice",
                "tpu-v5-lite-podslice",
                "tpu-v5p-slice",
                "tpu-v6e-slice",
            ],
        ),
    }
    return profiles


DEFAULT_CLUSTER = "Kubernetes"
DEFAULT_TPU_CLUSTER = "GCP-GKE-TPU"


def get_cluster(name: str) -> ClusterMetadata | None:
    return builtin_clusters().get(name)


def resolve_target_cluster(target_cluster) -> ClusterMetadataSpec:
    """Resolve a plan TargetCluster (collected-yaml ``path`` first, then
    builtin ``type``, with unknown-name fallback) to its spec. Single
    owner of the resolution used by IR loading (metadata/base.py) and the
    TPU-slice QA defaults (containerizer/jax_emit.py)."""
    from move2kube_tpu.types import collection as collecttypes
    from move2kube_tpu.utils.log import get_logger

    log = get_logger("metadata.clusters")
    if getattr(target_cluster, "path", ""):
        try:
            return collecttypes.read_cluster_metadata(target_cluster.path).spec
        except Exception as e:  # noqa: BLE001 - fall back to builtin
            log.warning("cannot read cluster metadata %s: %s",
                        target_cluster.path, e)
    name = getattr(target_cluster, "type", "") or DEFAULT_CLUSTER
    cm = get_cluster(name)
    if cm is None:
        log.warning("unknown cluster profile %r; using %s", name,
                    DEFAULT_CLUSTER)
        cm = get_cluster(DEFAULT_CLUSTER)
    return cm.spec
