"""Ring attention: exact attention over sequence-sharded inputs.

Context parallelism for long sequences (the TPU-native answer to
DeepSpeed-Ulysses / Megatron CP, SURVEY.md §5): Q stays local, K/V blocks
rotate around the ``seq`` mesh axis via ``ppermute`` so each step overlaps
a neighbour exchange with a blockwise attention update. Online-softmax
accumulation (running max + weighted sums) keeps the result exact.

Used inside ``shard_map`` over a mesh with a non-trivial ``seq`` axis; for
seq=1 meshes it degrades to one local block (no collectives).

Memory contract: each ring step materializes one [b, h, s_local,
s_local] score block (s_local = seq / seq_axis_size), transient and
freed per step. Size the ``seq`` axis so shards stay <= ~4k (65k context
-> seq>=16; seq=8 leaves 8k shards whose score block alone is ~2GB/step
for b=1, h=8 f32 — too close to HBM limits);
a fused Pallas ring step (flash per block + lse-merge, whole-ring
custom_vjp) can replace _block_attn without changing callers if longer
shards are needed.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from move2kube_tpu.parallel.compat import axis_size as _axis_size, shard_map


def _block_attn(q, k, v, bias, scale):
    """One blockwise attention step -> (unnormalized out, row max, row sum)."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if bias is not None:
        s = s + bias
    m = jnp.max(s, axis=-1, keepdims=True)  # [b,h,q,1]
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
    return o, m, l


def ring_attention(q, k, v, *, axis_name: str = "seq", causal: bool = False,
                   scale: float | None = None):
    """Exact attention with K/V rotating around ``axis_name``.

    Args:
      q, k, v: [batch, seq_shard, heads, head_dim] local shards.
      causal: causal masking consistent with the global sequence order
        (shard i holds positions [i*S, (i+1)*S)).
    """
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    axis_size = _axis_size(axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    seq_len = q.shape[1]

    def mask_bias(kv_idx):
        if not causal:
            return None
        q_pos = my_idx * seq_len + jnp.arange(seq_len)[:, None]
        k_pos = kv_idx * seq_len + jnp.arange(seq_len)[None, :]
        return jnp.where(q_pos >= k_pos, 0.0, -1e30)[None, None]  # [1,1,q,k]

    def step(carry, _):
        o_acc, m_acc, l_acc, k_cur, v_cur, kv_idx = carry
        o_b, m_b, l_b = _block_attn(q, k_cur, v_cur, mask_bias(kv_idx), scale)
        # online softmax merge
        m_new = jnp.maximum(m_acc, m_b)
        alpha = jnp.exp(m_acc - m_new)
        beta = jnp.exp(m_b - m_new)
        # correction factors [b,h,q,1] -> [b,q,h,1] to match o's layout
        alpha_q = jnp.transpose(alpha, (0, 2, 1, 3))
        beta_q = jnp.transpose(beta, (0, 2, 1, 3))
        o_acc = o_acc * alpha_q + o_b * beta_q
        l_acc = l_acc * alpha + l_b * beta
        m_acc = m_new
        # rotate K/V to the next neighbour on the ring (ICI hop)
        perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        kv_idx = (kv_idx - 1) % axis_size
        return (o_acc, m_acc, l_acc, k_nxt, v_nxt, kv_idx), None

    b, s, h, d = q.shape
    o0 = jnp.zeros((b, s, h, d), jnp.float32)
    m0 = jnp.full((b, h, s, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, s, 1), jnp.float32)
    carry = (o0, m0, l0, k, v, my_idx)
    (o, m, l, *_), _ = jax.lax.scan(step, carry, None, length=axis_size)
    l_q = jnp.transpose(l, (0, 2, 1, 3))  # [b,q,h,1]
    return (o / jnp.maximum(l_q, 1e-30)).astype(q.dtype)


def ring_attention_sharded(mesh: Mesh, q, k, v, *, causal: bool = False):
    """Convenience wrapper: shard_map ring_attention over the mesh.

    Inputs are [batch, seq, heads, head_dim] global arrays; batch is sharded
    over (data, fsdp), seq over seq, heads over tensor.
    """
    spec = P(("data", "fsdp"), "seq", "tensor", None)

    @functools.partial(
        shard_map, mesh=mesh, in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    def run(ql, kl, vl):
        return ring_attention(ql, kl, vl, axis_name="seq", causal=causal)

    return run(q, k, v)
