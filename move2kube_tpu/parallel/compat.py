"""Version-tolerant shard_map + ambient-mesh entry points.

The multichip kernels (pipeline, ring attention, ulysses, overlapped
gradient reduction) and the sharding annotation helpers are written
against the modern jax surface — ``jax.shard_map`` with
``check_vma=False``, ``jax.sharding.get_abstract_mesh`` /
``use_mesh`` — while older jaxlib builds ship the same machinery as
``jax.experimental.shard_map`` (``check_rep=False``) plus the private
``jax._src.mesh`` abstract-mesh context and the classic ``with mesh:``
resource env.  Every caller in this package goes through these three
functions so the version choice is made in exactly one place.
"""

from __future__ import annotations

import contextlib
from typing import Any, Callable


def shard_map(f: Callable, *, mesh: Any = None, in_specs: Any, out_specs: Any) -> Callable:
    """Map ``f`` over ``mesh`` (or the ambient mesh when None) with
    per-argument specs, replication checking disabled (the kernels do
    their own psum/ppermute accounting)."""
    import jax

    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        kwargs = {} if mesh is None else {"mesh": mesh}
        return sm(f, in_specs=in_specs, out_specs=out_specs, check_vma=False, **kwargs)
    from jax.experimental.shard_map import shard_map as legacy

    if mesh is None:
        mesh = _ambient_concrete_or_abstract_mesh()
    return legacy(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False)


def axis_size(axis_name) -> "Any":
    """Static size of a named mapped axis inside shard_map/pmap code.
    Older jax lacks ``jax.lax.axis_size``; ``psum(1)`` of a unit constant
    folds to the same static value at trace time."""
    import jax

    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    return jax.lax.psum(1, axis_name)


def abstract_mesh(axis_sizes, axis_names):
    """AbstractMesh across both constructor generations (new:
    ``(sizes, names)``; old: one tuple of ``(name, size)`` pairs)."""
    import jax

    try:
        return jax.sharding.AbstractMesh(tuple(axis_sizes), tuple(axis_names))
    except TypeError:
        return jax.sharding.AbstractMesh(tuple(zip(axis_names, axis_sizes)))


def get_abstract_mesh():
    """The ambient (abstract) mesh, or an object whose ``empty`` is
    truthy when none is set — works on both API generations."""
    import jax

    fn = getattr(jax.sharding, "get_abstract_mesh", None)
    if fn is not None:
        return fn()
    from jax._src.mesh import get_abstract_mesh as legacy

    return legacy()


def bare_spec_constraints_ok() -> bool:
    """Can ``with_sharding_constraint`` take a bare PartitionSpec right
    now?  New jax resolves it against the ambient (abstract) mesh; old
    jax needs the concrete resource-env mesh — under an abstract-only
    ambient mesh (the eval_shape verification path) the constraint must
    be skipped, which is shape-inert there."""
    import jax

    if getattr(jax.sharding, "get_abstract_mesh", None) is not None:
        return True
    from jax._src.mesh import thread_resources

    return not getattr(thread_resources.env.physical_mesh, "empty", True)


def _ambient_concrete_or_abstract_mesh():
    """Legacy-jax mesh lookup for :func:`shard_map` calls that rely on
    the ambient mesh: prefer the concrete resource-env mesh (set by
    ``with mesh:``), fall back to the abstract one."""
    from jax._src.mesh import thread_resources

    physical = thread_resources.env.physical_mesh
    if not getattr(physical, "empty", True):
        return physical
    am = get_abstract_mesh()
    if not getattr(am, "empty", True):
        return am
    raise ValueError("shard_map called with no mesh and no ambient mesh set")


@contextlib.contextmanager
def ambient_mesh(mesh):
    """Make ``mesh`` ambient so bare PartitionSpecs resolve inside traced
    code.  New jax: ``use_mesh`` / ``use_abstract_mesh``.  Old jax:
    enter ``with mesh:`` (resource env, resolves bare-spec sharding
    constraints) *and* set the abstract mesh (so
    :func:`get_abstract_mesh`-based annotation helpers see the axes)."""
    import jax

    if isinstance(mesh, jax.sharding.AbstractMesh):
        ctx = getattr(jax.sharding, "use_abstract_mesh", None)
        if ctx is not None:
            with ctx(mesh):
                yield
            return
        from jax._src.mesh import set_abstract_mesh

        with set_abstract_mesh(mesh):
            yield
        return

    use = getattr(jax.sharding, "use_mesh", None) or getattr(jax, "set_mesh", None)
    if use is not None:
        with use(mesh):
            yield
        return
    from jax._src.mesh import set_abstract_mesh

    with mesh, set_abstract_mesh(mesh.abstract_mesh):
        yield
