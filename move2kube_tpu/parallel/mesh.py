"""Device mesh construction and multi-host bootstrap.

The mesh axes follow the standard TPU recipe (scaling-book):

- ``data``   — pure data parallelism; gradients all-reduced (psum) over ICI/DCN
- ``fsdp``   — data parallelism with parameter/optimizer sharding
               (ZeRO-3 equivalent); params all-gathered per layer
- ``pipe``   — pipeline stages (compiled GPipe schedule, parallel/pipeline.py)
- ``tensor`` — tensor (megatron-style) model parallelism; activations
               all-reduced per block, so this axis must sit on ICI
- ``seq``    — sequence/context parallelism (ring / Ulysses attention)
- ``expert`` — MoE expert parallelism (models/moe.py; all-to-alls on ICI)

The GPU->TPU translation maps: DDP -> data, DeepSpeed ZeRO-3 -> fsdp,
GPipe/Megatron PP -> pipe, Megatron TP -> tensor, DeepSpeed-Ulysses /
context parallel -> seq, DeepSpeed-MoE EP -> expert (SURVEY.md §5).

Multi-host bootstrap honors the env the TPU apiresources inject into
JobSet pods (containerizer/jax_emit.py writes the consumer side).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import TYPE_CHECKING

if TYPE_CHECKING:  # jax is imported lazily: the CLI emit path only needs
    from jax.sharding import Mesh  # MeshConfig/infer_mesh_config (pure python)


@dataclass
class MeshConfig:
    data: int = 1
    fsdp: int = 1
    pipe: int = 1    # pipeline stages (parallel/pipeline.py)
    tensor: int = 1
    seq: int = 1
    expert: int = 1  # MoE expert parallelism (models/moe.py)

    # outer -> inner: DCN-tolerant axes (data, pipe) first, ICI-hungry axes
    # (tensor, seq, expert) innermost so their collectives ride ICI
    AXES = ("data", "fsdp", "pipe", "tensor", "seq", "expert")

    def total(self) -> int:
        n = 1
        for d in self.dims():
            n *= d
        return n

    def dims(self) -> tuple[int, ...]:
        return (self.data, self.fsdp, self.pipe, self.tensor, self.seq,
                self.expert)


def infer_mesh_config(n_devices: int, *, zero_stage: int = 0,
                      tensor_parallel: int = 1, seq_parallel: int = 1,
                      pipeline_parallel: int = 1,
                      expert_parallel: int = 1) -> MeshConfig:
    """Choose mesh dims for a device count + detected GPU parallelism.

    ZeRO>=2 maps the whole data dimension to fsdp; tensor/seq/expert
    parallel claim their factors first (innermost, so they land on
    adjacent ICI neighbours), pipeline next; the remainder is data (or
    fsdp) parallel. Degrees that don't divide the device count are
    dropped (fall back towards pure data parallel), mirroring how the
    detected GPU world may not map 1:1 onto the TPU slice.
    """
    tensor = max(1, tensor_parallel)
    seq = max(1, seq_parallel)
    expert = max(1, expert_parallel)
    pipe = max(1, pipeline_parallel)
    if n_devices % (tensor * seq * expert):
        tensor = seq = expert = 1
    inner = tensor * seq * expert
    if (n_devices // inner) % pipe:
        pipe = 1
    rest = n_devices // (inner * pipe)
    if zero_stage >= 2:
        return MeshConfig(data=1, fsdp=rest, pipe=pipe, tensor=tensor,
                          seq=seq, expert=expert)
    return MeshConfig(data=rest, fsdp=1, pipe=pipe, tensor=tensor, seq=seq,
                      expert=expert)


def make_mesh(config: MeshConfig | None = None, devices=None) -> "Mesh":
    """Build a 4-axis Mesh; axes of size 1 still exist (cheap, simplifies
    PartitionSpecs — XLA drops trivial collectives).

    Also accepts a ``topology.MeshPlan`` in place of a config: the plan
    supplies both the logical extents and a device-order permutation so
    each logical axis walks physically contiguous ICI neighbours (the
    heaviest-traffic axis gets torus wraparound rings — see
    ``parallel/topology.py``)."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    devices = devices if devices is not None else jax.devices()
    if config is not None and hasattr(config, "device_order"):
        plan = config
        devices = plan.device_order(devices)
        config = plan.config
    config = config or MeshConfig(data=len(devices))
    if config.total() != len(devices):
        raise ValueError(
            f"mesh {config.dims()} needs {config.total()} devices, have {len(devices)}"
        )
    dev_array = np.asarray(devices).reshape(config.dims())
    return Mesh(dev_array, MeshConfig.AXES)


def initialize_distributed() -> None:
    """Multi-host bootstrap from JobSet/indexed-Job env.

    The TPU apiresources inject:
      M2KT_COORDINATOR   - headless-service DNS of slice-0 pod 0 (host:port)
      M2KT_NUM_HOSTS     - host count per slice
      M2KT_NUM_SLICES / M2KT_SLICE_ID - multi-slice (DCN) coordinates;
        megascale DCN transport is configured separately via the
        MEGASCALE_* env the JobSet carries
      JOB_COMPLETION_INDEX - this host's index within its slice
    On GKE TPU node pools jax.distributed can also self-discover; explicit
    env wins so the same image runs under any indexed-job controller.
    """
    import jax

    num_hosts = int(os.environ.get("M2KT_NUM_HOSTS", "1"))
    num_slices = int(os.environ.get("M2KT_NUM_SLICES", "1"))
    if num_hosts * num_slices <= 1:
        return
    coordinator = os.environ.get("M2KT_COORDINATOR", "")
    index = int(os.environ.get("JOB_COMPLETION_INDEX",
                               os.environ.get("M2KT_HOST_INDEX", "0")))
    slice_id = int(os.environ.get("M2KT_SLICE_ID", "0") or 0)
    if coordinator:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_hosts * num_slices,
            process_id=slice_id * num_hosts + index,
        )
    else:
        jax.distributed.initialize()
