"""Device mesh construction and multi-host bootstrap.

The mesh axes follow the standard TPU recipe (scaling-book):

- ``data``   — pure data parallelism; gradients all-reduced (psum) over ICI/DCN
- ``fsdp``   — data parallelism with parameter/optimizer sharding
               (ZeRO-3 equivalent); params all-gathered per layer
- ``tensor`` — tensor (megatron-style) model parallelism; activations
               all-reduced per block, so this axis must sit on ICI
- ``seq``    — sequence/context parallelism for ring attention

The GPU->TPU translation maps: DDP -> data, DeepSpeed ZeRO-3 -> fsdp,
Megatron TP -> tensor, DeepSpeed-Ulysses / context parallel -> seq
(SURVEY.md §5 long-context mapping).

Multi-host bootstrap honors the env the TPU apiresources inject into
JobSet pods (containerizer/jax_emit.py writes the consumer side).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import TYPE_CHECKING

if TYPE_CHECKING:  # jax is imported lazily: the CLI emit path only needs
    from jax.sharding import Mesh  # MeshConfig/infer_mesh_config (pure python)


@dataclass
class MeshConfig:
    data: int = 1
    fsdp: int = 1
    tensor: int = 1
    seq: int = 1

    AXES = ("data", "fsdp", "tensor", "seq")

    def total(self) -> int:
        return self.data * self.fsdp * self.tensor * self.seq

    def dims(self) -> tuple[int, int, int, int]:
        return (self.data, self.fsdp, self.tensor, self.seq)


def infer_mesh_config(n_devices: int, *, zero_stage: int = 0,
                      tensor_parallel: int = 1, seq_parallel: int = 1) -> MeshConfig:
    """Choose mesh dims for a device count + detected GPU parallelism.

    ZeRO>=2 maps the whole data dimension to fsdp; tensor/seq parallel
    claim their factors first (innermost, so they land on adjacent ICI
    neighbours); the remainder is data (or fsdp) parallel.
    """
    tensor = max(1, tensor_parallel)
    seq = max(1, seq_parallel)
    if n_devices % (tensor * seq):
        tensor = seq = 1  # fall back to pure data parallel
    rest = n_devices // (tensor * seq)
    if zero_stage >= 2:
        return MeshConfig(data=1, fsdp=rest, tensor=tensor, seq=seq)
    return MeshConfig(data=rest, fsdp=1, tensor=tensor, seq=seq)


def make_mesh(config: MeshConfig | None = None, devices=None) -> "Mesh":
    """Build a 4-axis Mesh; axes of size 1 still exist (cheap, simplifies
    PartitionSpecs — XLA drops trivial collectives)."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    devices = devices if devices is not None else jax.devices()
    config = config or MeshConfig(data=len(devices))
    if config.total() != len(devices):
        raise ValueError(
            f"mesh {config.dims()} needs {config.total()} devices, have {len(devices)}"
        )
    dev_array = np.asarray(devices).reshape(config.dims())
    return Mesh(dev_array, MeshConfig.AXES)


def initialize_distributed() -> None:
    """Multi-host bootstrap from JobSet/indexed-Job env.

    The TPU apiresources inject:
      M2KT_COORDINATOR   - headless-service DNS of pod 0 (host:port)
      M2KT_NUM_HOSTS     - total host count
      JOB_COMPLETION_INDEX - this host's index (k8s indexed jobs)
    On GKE TPU node pools jax.distributed can also self-discover; explicit
    env wins so the same image runs under any indexed-job controller.
    """
    import jax

    num_hosts = int(os.environ.get("M2KT_NUM_HOSTS", "1"))
    if num_hosts <= 1:
        return
    coordinator = os.environ.get("M2KT_COORDINATOR", "")
    index = int(os.environ.get("JOB_COMPLETION_INDEX",
                               os.environ.get("M2KT_HOST_INDEX", "0")))
    if coordinator:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_hosts,
            process_id=index,
        )
    else:
        jax.distributed.initialize()
