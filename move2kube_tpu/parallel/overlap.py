"""Bucketed ring all-reduce + compute-overlapped gradient accumulation.

The data-parallel gradient all-reduce is the one collective GSPMD keeps
fully serialized behind the backward pass: ``psum`` of the whole
gradient tree fires after the last microbatch's backward completes, so
ICI sits idle during compute and compute sits idle during the reduce.
With gradient accumulation there is slack to hide it: microbatch k's
gradients can ride the ring while microbatch k+1's backward runs.

Two pieces:

* :func:`ring_all_reduce` — a bandwidth-optimal bucketed ring
  all-reduce built from ``ppermute`` (reduce-scatter then all-gather,
  2(n-1) single-neighbour hops).  All leaves are flattened into one
  contiguous bucket per call so the ring moves a few large messages
  instead of many small ones, and — because it is plain ``ppermute`` +
  adds inside the caller's traced computation — XLA's latency-hiding
  scheduler is free to interleave its hops with unrelated compute.

* :func:`overlapped_accum_grads` — gradient accumulation over ``k``
  stacked microbatches under ``shard_map`` where the scan carry holds
  the *previous* microbatch's unreduced gradients: each step reduces
  the pending bucket (no data dependency on the current backward) while
  computing the current backward, exactly the overlap in the module
  name.  Requires a pure data-parallel mesh (params replicated); model-
  parallel meshes keep the GSPMD sequential-accumulation path in
  ``models/train.py``.

CPU-correct: numerics tests run on 8 forced host devices.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from move2kube_tpu.parallel.compat import shard_map


def _flatten_bucket(tree):
    """Concatenate all leaves into one fp32 bucket (+ metadata to undo)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    shapes = [leaf.shape for leaf in leaves]
    dtypes = [leaf.dtype for leaf in leaves]
    bucket = jnp.concatenate([leaf.astype(jnp.float32).ravel() for leaf in leaves])
    return bucket, (treedef, shapes, dtypes)


def _unflatten_bucket(bucket, meta):
    treedef, shapes, dtypes = meta
    leaves, offset = [], 0
    for shape, dtype in zip(shapes, dtypes):
        size = 1
        for d in shape:
            size *= d
        leaves.append(bucket[offset:offset + size].reshape(shape).astype(dtype))
        offset += size
    return jax.tree_util.tree_unflatten(treedef, leaves)


def ring_all_reduce(tree, axis_name: str):
    """Sum ``tree`` across ``axis_name`` with a bucketed ring.

    Reduce-scatter: the bucket is split into n chunks; a travelling
    partial sum moves one neighbour per hop, each device adding the
    chunk the sum will need next, so after n-1 hops device r owns the
    complete sum of chunk (r+1) mod n.  All-gather: the owned chunk
    circulates n-1 more hops.  Every hop is a single-neighbour
    ``ppermute`` — on a torus axis this is one wraparound ring link.
    """
    n = lax.psum(1, axis_name)
    if n == 1:
        return tree
    bucket, meta = _flatten_bucket(tree)
    size = bucket.shape[0]
    pad = (-size) % n
    if pad:
        bucket = jnp.concatenate([bucket, jnp.zeros((pad,), bucket.dtype)])
    chunks = bucket.reshape(n, -1)
    r = lax.axis_index(axis_name)
    ring = [(i, (i + 1) % n) for i in range(n)]

    def chunk_at(idx):
        return lax.dynamic_index_in_dim(chunks, jnp.mod(idx, n), axis=0, keepdims=False)

    # reduce-scatter: after step s the travelling sum covers chunk r-1-s
    # of s+2 devices; after n-1 steps device r holds sum of chunk (r+1)%n
    total = chunk_at(r)
    for s in range(n - 1):
        total = lax.ppermute(total, axis_name, ring)
        total = total + chunk_at(r - 1 - s)

    # all-gather the owned chunks back around the ring
    out = jnp.zeros_like(chunks)
    out = lax.dynamic_update_index_in_dim(out, total, jnp.mod(r + 1, n), axis=0)
    for s in range(n - 1):
        total = lax.ppermute(total, axis_name, ring)
        out = lax.dynamic_update_index_in_dim(out, total, jnp.mod(r - s, n), axis=0)

    flat = out.reshape(-1)
    if pad:
        flat = flat[:size]
    return _unflatten_bucket(flat, meta)


def has_model_axis(mesh) -> bool:
    """True when the mesh splits parameters across a ``model`` axis —
    the precondition for the collective-overlapped decode matmul."""
    try:
        shape = dict(mesh.shape)
    except Exception:
        return False
    return shape.get("model", 1) > 1


def _overlapped_matmul_shard(x, w, axis_name: str):
    """Shard-local body of the collective decode matmul.

    Row-parallel layout: ``x`` [batch, in/n] activation shard, ``w``
    [in/n, out] weight shard; the full product needs the partial results
    summed over the axis. Instead of matmul-then-psum (which serializes
    ICI behind the whole product — exactly the latency a one-token
    decode step cannot hide), the output columns are split into n
    chunks and the ring reduce-scatter's travelling partial sum is
    interleaved with the per-chunk matmuls: each hop's ppermute has no
    data dependency on the next chunk's compute, so XLA's latency-hiding
    scheduler runs them concurrently (same trick as ring_all_reduce, but
    here the summand is *produced* between hops rather than read from a
    buffer). After n-1 hops device r owns the finished column chunk
    (r+1) mod n; n-1 more hops all-gather the full [batch, out] row.
    """
    n = lax.psum(1, axis_name)
    if n == 1:
        return x @ w
    out = w.shape[1]
    pad = (-out) % n
    if pad:
        w = jnp.pad(w, ((0, 0), (0, pad)))
    cols = w.reshape(w.shape[0], n, -1)        # [in/n, n, out_chunk]
    r = lax.axis_index(axis_name)
    ring = [(i, (i + 1) % n) for i in range(n)]

    def part(idx):
        wc = lax.dynamic_index_in_dim(cols, jnp.mod(idx, n), axis=1,
                                      keepdims=False)
        return jnp.dot(x, wc, preferred_element_type=jnp.float32)

    # reduce-scatter with the summand computed between hops
    total = part(r)
    for s in range(n - 1):
        total = lax.ppermute(total, axis_name, ring)
        total = total + part(r - 1 - s)

    # all-gather the finished column chunks back around the ring
    chunks = jnp.zeros((n,) + total.shape, total.dtype)
    chunks = lax.dynamic_update_index_in_dim(chunks, total,
                                             jnp.mod(r + 1, n), axis=0)
    for s in range(n - 1):
        total = lax.ppermute(total, axis_name, ring)
        chunks = lax.dynamic_update_index_in_dim(chunks, total,
                                                 jnp.mod(r - s, n), axis=0)
    y = chunks.transpose(1, 0, 2).reshape(x.shape[0], -1)
    if pad:
        y = y[:, :out]
    return y.astype(x.dtype)


def collective_decode_matmul(mesh, x, w, *, axis_name: str = "model"):
    """``x @ w`` with ``w``'s contraction dim sharded over ``axis_name``.

    ``x``: [batch, in] (replicated), ``w``: [in, out]. Returns the full
    replicated product; the cross-shard sum rides the overlapped ring in
    :func:`_overlapped_matmul_shard`. This is the latency-optimized path
    serving/engine.py selects for decode projections when the mesh has a
    model axis (select_decode_matmul).
    """
    mapped = shard_map(
        functools.partial(_overlapped_matmul_shard, axis_name=axis_name),
        mesh=mesh,
        in_specs=(P(None, axis_name), P(axis_name, None)),
        out_specs=P(),
    )
    return mapped(x, w)


def ring_all_gather(shard, axis_name: str, axis: int):
    """All-gather a leaf's sharded dim via n-1 single-neighbour
    ``ppermute`` hops. Device r starts with global slice r of ``axis``
    (the NamedSharding layout); at hop t it receives the slice of device
    (r - t) mod n and writes it at its global offset. Each leaf's ring is
    independent of every other leaf's — the latency-hiding scheduler is
    free to run layer k's matmuls while layer k+1's params are still in
    flight, which is the FSDP all-gather *prefetch* of
    :func:`prefetched_fsdp_accum_grads`."""
    n = lax.psum(1, axis_name)
    if n == 1:
        return shard
    r = lax.axis_index(axis_name)
    ring = [(i, (i + 1) % n) for i in range(n)]
    size = shard.shape[axis]
    full_shape = shard.shape[:axis] + (n * size,) + shard.shape[axis + 1:]
    full = jnp.zeros(full_shape, shard.dtype)
    full = lax.dynamic_update_slice_in_dim(full, shard, r * size, axis)
    cur = shard
    for t in range(1, n):
        cur = lax.ppermute(cur, axis_name, ring)
        src = jnp.mod(r - t, n)
        full = lax.dynamic_update_slice_in_dim(full, cur, src * size, axis)
    return full


def ring_reduce_scatter(full, axis_name: str, axis: int):
    """Sum ``full`` over the group, keeping only this device's global
    slice of ``axis`` (travelling partial sum, n-1 hops + one alignment
    hop so device r ends owning slice r — the NamedSharding layout the
    optimizer update expects). The per-hop summand is *read* between
    hops, so the hops carry no data dependency on concurrent compute."""
    n = lax.psum(1, axis_name)
    if n == 1:
        return full
    r = lax.axis_index(axis_name)
    ring = [(i, (i + 1) % n) for i in range(n)]
    size = full.shape[axis] // n

    def chunk(idx):
        return lax.dynamic_slice_in_dim(full, jnp.mod(idx, n) * size, size,
                                        axis)

    # after n-1 hops device r holds the group sum of chunk (r+1) mod n
    # (same schedule as ring_all_reduce); one extra forward hop aligns
    # ownership to device r <- chunk r
    total = chunk(r)
    for s in range(n - 1):
        total = lax.ppermute(total, axis_name, ring)
        total = total + chunk(r - 1 - s)
    return lax.ppermute(total, axis_name, ring)


def is_pure_data_parallel(mesh) -> bool:
    """True when every device sits on the ``data`` axis (params are then
    replicated, the precondition for the overlapped path)."""
    try:
        shape = dict(mesh.shape)
    except Exception:
        return False
    data = shape.get("data", 1)
    return data > 1 and all(v == 1 for k, v in shape.items() if k != "data")


def overlapped_accum_grads(mesh, loss_fn, params, batches, *, axis_name: str = "data"):
    """Mean loss + mean grads over ``k`` stacked microbatches with the
    pending reduction overlapped against the next backward.

    ``loss_fn(params, microbatch) -> scalar``; ``batches`` leaves are
    ``[k, global_batch, ...]``.  Scan carry = (accumulated reduced
    grads, previous microbatch's unreduced grads): each iteration issues
    the ring reduce of the pending tree *and* the current backward with
    no data dependency between them, then folds the reduced result into
    the accumulator.  The final pending tree is reduced in the epilogue.
    Returns grads and loss already averaged over microbatches and the
    ``axis_name`` group (identical on all devices).
    """
    batch_spec = jax.tree_util.tree_map(lambda _: P(None, (axis_name, "fsdp")), batches)
    param_spec = jax.tree_util.tree_map(lambda _: P(), params)

    def run(p, mbs):
        n = lax.psum(1, axis_name)
        k = jax.tree_util.tree_leaves(mbs)[0].shape[0]

        def fwd_bwd(mb):
            return jax.value_and_grad(loss_fn)(p, mb)

        loss0, g0 = fwd_bwd(jax.tree_util.tree_map(lambda x: x[0], mbs))

        def body(carry, mb):
            acc, pending = carry
            reduced = ring_all_reduce(pending, axis_name)  # <- independent of fwd_bwd(mb)
            loss, g = fwd_bwd(mb)
            acc = jax.tree_util.tree_map(jnp.add, acc, reduced)
            return (acc, g), loss

        rest = jax.tree_util.tree_map(lambda x: x[1:], mbs)
        zeros = jax.tree_util.tree_map(jnp.zeros_like, g0)
        (acc, last), losses = lax.scan(body, (zeros, g0), rest)
        acc = jax.tree_util.tree_map(jnp.add, acc, ring_all_reduce(last, axis_name))
        grads = jax.tree_util.tree_map(lambda g: (g / (k * n)).astype(g.dtype), acc)
        loss = (loss0 + jnp.sum(losses)) / k
        loss = lax.psum(loss, axis_name) / n
        return grads, loss

    mapped = shard_map(
        run, mesh=mesh,
        in_specs=(param_spec, batch_spec),
        out_specs=(param_spec, P()),
    )
    return mapped(params, batches)


def is_pure_fsdp(mesh) -> bool:
    """True when every device sits on the ``fsdp`` axis (the planner's
    ZeRO layout: params sharded leaf-wise, batch sharded over fsdp) —
    the precondition for :func:`prefetched_fsdp_accum_grads`. Mixed
    dp x fsdp or model-parallel meshes keep the GSPMD fallback."""
    try:
        shape = dict(mesh.shape)
    except Exception:
        return False
    fsdp = shape.get("fsdp", 1)
    return fsdp > 1 and all(v == 1 for k, v in shape.items() if k != "fsdp")


def fsdp_prefetch_mode() -> str:
    """``M2KT_FSDP_PREFETCH`` -> 'auto' | 'on' | 'off' (the serve-kernels
    ladder spellings). auto/on take the prefetched path whenever
    :func:`is_pure_fsdp` holds; off forces the sequential GSPMD
    accumulation even there."""
    raw = os.environ.get("M2KT_FSDP_PREFETCH", "auto").strip().lower()
    if raw in ("on", "1", "true"):
        return "on"
    if raw in ("off", "0", "false"):
        return "off"
    return "auto"


def _fsdp_leaf_dims(params, n: int, axis_name: str):
    """Per-leaf index of the dim sharded over ``axis_name`` under the
    repo's logical-axis heuristic (parallel/sharding.py — the same table
    create_sharded_state placed the params with), or None for replicated
    leaves and leaves whose sharded dim is not divisible by ``n`` (those
    shard_map cannot split evenly; they ride the replicated bucket).
    Returns (flat leaf list, treedef, dims list) in matching order."""
    from move2kube_tpu.parallel.sharding import ShardingRules, infer_param_axes

    rules = ShardingRules.default()
    axes_tree = infer_param_axes(params)
    leaves, treedef = jax.tree_util.tree_flatten(params)
    axes_leaves = treedef.flatten_up_to(axes_tree)

    dims = []
    for leaf, axes in zip(leaves, axes_leaves):
        dim = None
        spec = rules.spec(tuple(axes)) if axes else P()
        for i, entry in enumerate(spec):
            names = entry if isinstance(entry, tuple) else (entry,)
            if axis_name in names:
                dim = i
                break
        if dim is not None and leaf.shape[dim] % n != 0:
            dim = None
        dims.append(dim)
    return leaves, treedef, dims


def prefetched_fsdp_accum_grads(mesh, loss_fn, params, batches, *,
                                axis_name: str = "fsdp"):
    """ZeRO-mesh counterpart of :func:`overlapped_accum_grads`: params
    enter ``shard_map`` in their true sharded layout, are all-gathered
    ONCE per step through independent per-leaf ppermute rings (GSPMD's
    sequential accumulation re-gathers them for every microbatch, and
    serializes each gather behind the compute that needs it — here layer
    k's gather has no dependency on layer k-1's matmuls, so the
    latency-hiding scheduler prefetches it while those grads compute),
    and the per-microbatch grad reduce-scatter rides the scan carry
    exactly like the pure-dp ring: microbatch k's reduction overlaps
    microbatch k+1's backward. Grads come back in the params' own shard
    layout (out_specs below), so the optimizer update and its donation
    contract see exactly what the sequential path produces.

    ``loss_fn(params, microbatch) -> scalar``; ``batches`` leaves are
    ``[k, global_batch, ...]``. Returns (grads tree, loss) averaged over
    microbatches and the group.
    """
    leaves, treedef, dims = _fsdp_leaf_dims(
        params, dict(mesh.shape)[axis_name], axis_name)
    batch_spec = jax.tree_util.tree_map(
        lambda _: P(None, ("data", axis_name)), batches)

    def leaf_spec(leaf, dim):
        entries = [None] * leaf.ndim
        if dim is not None:
            entries[dim] = axis_name
        return P(*entries)

    param_specs = tuple(leaf_spec(l, d) for l, d in zip(leaves, dims))

    def run(shards, mbs):
        n = lax.psum(1, axis_name)
        k = jax.tree_util.tree_leaves(mbs)[0].shape[0]

        # prefetch: one independent all-gather ring per sharded leaf
        full = [x if d is None else ring_all_gather(x, axis_name, d)
                for x, d in zip(shards, dims)]
        p_full = jax.tree_util.tree_unflatten(treedef, full)

        def fwd_bwd(mb):
            loss, g = jax.value_and_grad(loss_fn)(p_full, mb)
            return loss, list(treedef.flatten_up_to(g))

        def reduce(pending):
            # sharded leaves: travelling-sum ring reduce-scatter back to
            # the shard layout; replicated leaves: one bucketed ring
            # all-reduce. Neither depends on the concurrent backward.
            rep = [x for x, d in zip(pending, dims) if d is None]
            rep = iter(ring_all_reduce(rep, axis_name) if rep else [])
            return [next(rep) if d is None
                    else ring_reduce_scatter(x, axis_name, d)
                    for x, d in zip(pending, dims)]

        loss0, g0 = fwd_bwd(jax.tree_util.tree_map(lambda x: x[0], mbs))

        def body(carry, mb):
            acc, pending = carry
            reduced = reduce(pending)  # <- independent of fwd_bwd(mb)
            loss, g = fwd_bwd(mb)
            acc = [a + r for a, r in zip(acc, reduced)]
            return (acc, g), loss

        rest = jax.tree_util.tree_map(lambda x: x[1:], mbs)
        zeros = [jnp.zeros_like(x) for x in shards]
        (acc, last), losses = lax.scan(body, (zeros, g0), rest)
        acc = [a + r for a, r in zip(acc, reduce(last))]
        grads = tuple((a / (k * n)).astype(a.dtype) for a in acc)
        loss = (loss0 + jnp.sum(losses)) / k
        loss = lax.psum(loss, axis_name) / n
        return grads, loss

    mapped = shard_map(
        run, mesh=mesh,
        in_specs=(param_specs, batch_spec),
        out_specs=(param_specs, P()),
    )
    grads, loss = mapped(tuple(leaves), batches)
    return jax.tree_util.tree_unflatten(treedef, list(grads)), loss
