"""Topology-aware parallelism planner: ICI grid -> mesh axis placement.

The emitter writes the physical slice geometry into the JobSet twice —
as the ``gke-tpu-topology`` node selector and as the ``M2KT_TPU_TOPOLOGY``
container env — but until now the runtime ignored it and laid logical
mesh axes over ``jax.devices()`` in enumeration order.  That is correct
(GSPMD collectives work on any assignment) but slow: an all-reduce whose
axis straddles torus dimensions pays multi-hop ICI latency on every
step, while the same axis mapped onto one wraparound ring moves each
byte exactly once per hop with bidirectional bandwidth.

This module turns a topology string (``2x4``, ``4x4x4``) plus the
desired parallelism degrees into a :class:`MeshPlan`:

* the logical extents (via :func:`mesh.infer_mesh_config`, optionally
  re-splitting dp/fsdp with the per-chip memory model so replicated
  optimizer state fits HBM), and
* a physical **device-order permutation** so each logical axis occupies
  contiguous physical dims, with the heaviest-traffic axis placed on
  wraparound (torus) dims first.

Multislice (``M2KT_NUM_SLICES`` > 1): the topology string describes ONE
ICI slice; slices are connected by DCN. Only data parallelism tolerates
DCN latency (the invariant gpu_detect.py documents), so the planner
plans each slice independently — memory-model dp×fsdp re-split, layout,
permutation all per-slice — and multiplies the data extent by a
``dcn_dp`` outer factor, one data-axis block per slice. ``data`` is the
outermost mesh axis, so in the row-major device enumeration each slice's
devices stay contiguous and every non-data collective rides ICI.

Traffic ranking follows per-step collective volume: tensor parallelism
all-reduces activations every layer (heaviest), sequence/context and
expert parallelism exchange activation-sized blocks per layer, fsdp
all-gathers parameters once per step, data parallelism all-reduces
gradients once per step, and pipeline parallelism only passes microbatch
boundary activations (lightest).  A dim of size >= 4 closes into a ring
on TPU tori; size-2 dims are plain links and rank below rings.

Pure python + numpy — importable by the emitter and unit tests without
initializing a jax backend.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

import numpy as np

from move2kube_tpu.parallel.memory import HBM_BYTES
from move2kube_tpu.parallel.mesh import MeshConfig, infer_mesh_config

# Heaviest-traffic first: placement order determines who gets the best
# (wraparound, largest) physical dims. Relative weights are per-step
# collective bytes in units of "one activation pass" — coarse, but the
# ordering is what matters for placement.
TRAFFIC_WEIGHT = {
    "tensor": 100.0,
    "seq": 40.0,
    "expert": 30.0,
    "fsdp": 10.0,
    "data": 3.0,
    "pipe": 1.0,
}
_PLACEMENT_ORDER = ("tensor", "seq", "expert", "fsdp", "data", "pipe")

# A torus dim closes into a wraparound ring at this size (a 2-dim is a
# single bidirectional link; v4/v5p tori wrap dims of 4 and up).
_RING_MIN = 4

_DEFAULT_HBM = 16e9  # unknown slice types budget like v5e


def parse_topology(topology: str) -> tuple[int, ...]:
    """``"4x4x4"`` -> ``(4, 4, 4)``; raises ValueError when malformed
    (same grammar as ``gpu_detect.topology_chip_count``, the sizing-side
    owner of these strings)."""
    dims = []
    for dim_str in str(topology).split("x"):
        dim = int(dim_str)
        if dim <= 0:
            raise ValueError(f"non-positive topology dim {dim} in {topology!r}")
        dims.append(dim)
    return tuple(dims)


@dataclass(frozen=True)
class Topology:
    """Physical ICI grid: dim sizes plus which dims wrap into rings."""

    dims: tuple[int, ...]
    slice_type: str = ""

    @property
    def chips(self) -> int:
        n = 1
        for d in self.dims:
            n *= d
        return n

    @property
    def wraparound(self) -> tuple[bool, ...]:
        return tuple(d >= _RING_MIN for d in self.dims)

    def hbm_bytes(self) -> float:
        return HBM_BYTES.get(self.slice_type, _DEFAULT_HBM)


@dataclass
class MeshPlan:
    """A logical mesh plus the physical device order realizing it.

    ``perm[i]`` is the index (into the topology's row-major device
    enumeration) of the device at flat logical position ``i``; feeding
    ``devices[perm]`` to ``make_mesh`` makes each logical axis walk
    physically adjacent chips. ``layout`` records which physical dims
    each axis spans (best dim first), for tests and the startup log.
    """

    config: MeshConfig
    topology: Topology | None = None
    perm: tuple[int, ...] = ()
    layout: dict[str, tuple[int, ...]] = field(default_factory=dict)
    source: str = "planner"
    # DCN data-parallel factor: number of ICI slices the data axis spans
    # (config.data == dcn_dp x per-slice data). topology/layout/ici_cost
    # describe ONE slice; perm covers all slices (slice-major blocks).
    dcn_dp: int = 1

    @property
    def ici_cost(self) -> float:
        """Traffic-weighted hop estimate: an axis on one wraparound dim
        costs 1 (ring all-reduce, every link busy both ways), a line
        costs 2 (bytes traverse twice without the closing link), and an
        axis straddling k dims costs 2k (one serialized phase per dim)."""
        if self.topology is None:
            return 0.0
        wrap = self.topology.wraparound
        cost = 0.0
        for axis, dims in self.layout.items():
            if not dims:
                continue
            if len(dims) == 1:
                hops = 1.0 if wrap[dims[0]] else 2.0
            else:
                hops = 2.0 * len(dims)
            cost += TRAFFIC_WEIGHT[axis] * hops
        return cost

    def device_order(self, devices) -> list:
        """Reorder a flat device list into plan order (identity when the
        planner had no topology to work from)."""
        devices = list(devices)
        if not self.perm or len(self.perm) != len(devices):
            return devices
        return [devices[i] for i in self.perm]

    def describe(self) -> str:
        dims = "x".join(str(d) for d in self.config.dims())
        topo = "x".join(str(d) for d in self.topology.dims) if self.topology else "-"
        lay = ",".join(
            f"{a}@{'+'.join(str(d) for d in ds)}" for a, ds in sorted(self.layout.items())
        )
        slices = f" dcn_dp={self.dcn_dp}" if self.dcn_dp > 1 else ""
        return (f"mesh={dims} topology={topo} layout=[{lay}]{slices} "
                f"source={self.source}")


def _memory_min_fsdp(
    resident: int, tensor: int, param_bytes: int, hbm: float, headroom: float,
    optimizer_slots: int,
) -> int:
    """Smallest fsdp divisor of ``resident`` (= dp*fsdp chips) so fp32
    master params + grads + optimizer slots fit ``headroom`` of HBM.
    Params are already split over the tensor axis; fsdp shards the rest."""
    state_bytes = param_bytes * (2 + optimizer_slots)  # params + grads + slots
    budget = hbm * headroom
    for fsdp in sorted(d for d in range(1, resident + 1) if resident % d == 0):
        if state_bytes / (fsdp * tensor) <= budget:
            return fsdp
    return resident


def _assign_layout(
    topo: Topology, config: MeshConfig
) -> tuple[list[list[tuple[str, int, int]]], dict[str, tuple[int, ...]]]:
    """Greedy factor placement: axes in traffic order each carve their
    extent out of the best-ranked physical dims (wraparound first, then
    larger, then innermost — the fastest-varying dim in row-major device
    enumeration).  gcd consumption cannot dead-end: every prime of an
    extent divides the remaining capacity product."""
    import math

    quality = sorted(
        range(len(topo.dims)),
        key=lambda i: (not topo.wraparound[i], -topo.dims[i], -i),
    )
    remaining = list(topo.dims)
    per_dim: list[list[tuple[str, int, int]]] = [[] for _ in topo.dims]  # (axis, factor, rank)
    layout: dict[str, tuple[int, ...]] = {}
    rank = 0
    for axis in _PLACEMENT_ORDER:
        extent = getattr(config, axis)
        if extent <= 1:
            continue
        spans = []
        while extent > 1:
            dim = next(
                (i for i in quality if remaining[i] > 1 and math.gcd(extent, remaining[i]) > 1),
                None,
            )
            if dim is None:  # extent doesn't divide the grid; no physical plan
                return [[] for _ in topo.dims], {}
            f = math.gcd(extent, remaining[dim])
            per_dim[dim].append((axis, f, rank))
            spans.append(dim)
            remaining[dim] //= f
            extent //= f
            rank += 1
        layout[axis] = tuple(spans)
    return per_dim, layout


def _build_perm(
    topo: Topology, config: MeshConfig
) -> tuple[tuple[int, ...], dict[str, tuple[int, ...]]]:
    """Permutation of the row-major topology enumeration realizing the
    layout.  Each physical dim is reshaped into its factors with the
    first-placed (heaviest) factor innermost — stride-1 along the dim,
    i.e. physically adjacent chips; then factors are transposed into
    logical-axis-major order and flattened to mesh shape."""
    per_dim, layout = _assign_layout(topo, config)
    if not layout and config.total() > 1:
        return tuple(range(topo.chips)), {}
    shape: list[int] = []
    tags: list[tuple[str, int]] = []  # (axis, rank) per reshape factor
    for dim_idx, d in enumerate(topo.dims):
        factors = sorted(per_dim[dim_idx], key=lambda t: -t[2])  # outer = placed later
        prod = 1
        for _, f, _ in factors:
            prod *= f
        if prod != d:  # unconsumed capacity only when all extents were 1
            shape.append(d // prod)
            tags.append(("data", -1))
        for axis, f, rnk in factors:
            shape.append(f)
            tags.append((axis, rnk))
    grid = np.arange(topo.chips).reshape(shape or (1,))
    order: list[int] = []
    for axis in MeshConfig.AXES:
        positions = [i for i, (a, _) in enumerate(tags) if a == axis]
        # latest-placed factor outermost: adjacent logical indices step
        # along the best (earliest-placed) physical dim first
        positions.sort(key=lambda i: -tags[i][1])
        order.extend(positions)
    grid = grid.transpose(order).reshape(-1)
    return tuple(int(x) for x in grid), layout


def plan_parallelism(
    n_devices: int,
    *,
    topology: str = "",
    slice_type: str = "",
    zero_stage: int = 0,
    tensor_parallel: int = 1,
    seq_parallel: int = 1,
    pipeline_parallel: int = 1,
    expert_parallel: int = 1,
    param_bytes: int | None = None,
    optimizer_slots: int = 2,
    headroom: float = 0.9,
    num_slices: int = 1,
) -> MeshPlan:
    """Full plan: logical extents + physical placement.

    Extents come from :func:`infer_mesh_config` (same fallbacks: inner
    axes claimed first, non-dividing degrees dropped).  When
    ``param_bytes`` is known and ZeRO is off, the residual dp pool is
    re-split dp x fsdp with the smallest fsdp that fits fp32 master
    state in ``headroom`` x HBM — the memory model deciding the axis
    split rather than the user.  Placement then maps each axis onto the
    parsed ICI grid (see :func:`_assign_layout`).

    ``num_slices`` > 1 plans ONE slice of ``n_devices // num_slices``
    devices (``topology`` describes a single slice) and multiplies the
    data extent by the resulting ``dcn_dp`` — DP gradients ride DCN
    between slices, every other collective stays on intra-slice ICI. A
    device count that doesn't divide into the slices falls back to a
    single-slice plan rather than producing a ragged mesh.
    """
    n_devices = max(1, n_devices)
    num_slices = max(1, num_slices)
    if num_slices > 1 and n_devices % num_slices:
        num_slices = 1
    per_slice = n_devices // num_slices
    config = infer_mesh_config(
        per_slice,
        zero_stage=zero_stage,
        tensor_parallel=tensor_parallel,
        seq_parallel=seq_parallel,
        pipeline_parallel=pipeline_parallel,
        expert_parallel=expert_parallel,
    )

    topo: Topology | None = None
    source = "planner"
    if topology:
        try:
            dims = parse_topology(topology)
        except ValueError:
            dims = ()
        if dims and int(np.prod(dims)) == per_slice:
            topo = Topology(dims=dims, slice_type=slice_type)
        else:
            source = "fallback-chain"
    if topo is None:
        # no/mismatched topology: model the slice as a 1-D chain so the
        # permutation is identity and only the memory split applies
        topo = Topology(dims=(per_slice,), slice_type=slice_type)

    if param_bytes and zero_stage < 2 and config.data > 1:
        # per-slice re-split: each slice holds a full replica pool of
        # config.data x config.fsdp chips; DCN neighbours can't shard
        # parameters (per-layer all-gathers would ride DCN every step)
        resident = config.data * config.fsdp
        fsdp = _memory_min_fsdp(
            resident, config.tensor, param_bytes, topo.hbm_bytes(), headroom,
            optimizer_slots,
        )
        fsdp = max(fsdp, config.fsdp)
        config = MeshConfig(
            data=resident // fsdp, fsdp=fsdp, pipe=config.pipe,
            tensor=config.tensor, seq=config.seq, expert=config.expert,
        )

    if n_devices == 1:
        return MeshPlan(config=config, topology=topo, perm=(0,), layout={},
                        source="single-chip")

    if per_slice == 1:
        perm, layout = tuple(range(per_slice)), {}
    else:
        perm, layout = _build_perm(topo, config)
        if not layout:
            source = "fallback-chain" if source == "planner" else source
    if num_slices > 1:
        # slice-major blocks: data is the outermost mesh axis, so block s
        # of the data axis == slice s's contiguous device range and every
        # non-data axis stays within one slice (ICI)
        perm = tuple(s * per_slice + p
                     for s in range(num_slices) for p in perm)
        config = MeshConfig(
            data=config.data * num_slices, fsdp=config.fsdp,
            pipe=config.pipe, tensor=config.tensor, seq=config.seq,
            expert=config.expert,
        )
    return MeshPlan(config=config, topology=topo, perm=perm, layout=layout,
                    source=source, dcn_dp=num_slices)


def _env_mesh_config(env) -> MeshConfig | None:
    """Explicit ``M2KT_MESH_*`` overrides win over the planner (operator
    escape hatch; missing axes default to 1)."""
    keys = {axis: f"M2KT_MESH_{axis.upper()}" for axis in MeshConfig.AXES}
    if not any(k in env for k in keys.values()):
        return None
    try:
        return MeshConfig(**{axis: int(env.get(key, "1")) for axis, key in keys.items()})
    except ValueError:
        return None


def resolve_mesh_plan(
    n_devices: int,
    *,
    default_topology: str = "",
    default_slice_type: str = "",
    zero_stage: int = 0,
    tensor_parallel: int = 1,
    seq_parallel: int = 1,
    pipeline_parallel: int = 1,
    expert_parallel: int = 1,
    param_bytes: int | None = None,
    num_slices: int | None = None,
    env=None,
) -> MeshPlan:
    """What the emitted trainer calls at startup: resolve the mesh from
    ``M2KT_TPU_TOPOLOGY`` / ``M2KT_TPU_ACCELERATOR`` (injected by the
    deployment emitter from the JobSet's topology annotation), with
    ``M2KT_MESH_*`` as an explicit override and the emitter's QA-derived
    parallelism degrees as planner inputs.

    ``num_slices=None`` reads ``M2KT_NUM_SLICES`` (the JobSet's
    replicated-slice count, shrunk by the elastic supervisor after a
    slice loss) so a restarted attempt re-plans for the surviving world
    without any caller changes."""
    env = os.environ if env is None else env
    if num_slices is None:
        try:
            num_slices = int(env.get("M2KT_NUM_SLICES", "1") or 1)
        except ValueError:
            num_slices = 1
    explicit = _env_mesh_config(env)
    if explicit is not None and explicit.total() == n_devices:
        return MeshPlan(config=explicit, topology=None,
                        perm=tuple(range(n_devices)), layout={}, source="env-mesh")
    return plan_parallelism(
        n_devices,
        topology=env.get("M2KT_TPU_TOPOLOGY", "") or default_topology,
        slice_type=env.get("M2KT_TPU_ACCELERATOR", "") or default_slice_type,
        zero_stage=zero_stage,
        tensor_parallel=tensor_parallel,
        seq_parallel=seq_parallel,
        pipeline_parallel=pipeline_parallel,
        expert_parallel=expert_parallel,
        param_bytes=param_bytes,
        num_slices=num_slices,
    )
