"""Logical-axis sharding rules.

Params and activations are annotated with *logical* axis names (``embed``,
``mlp``, ``heads``, ``batch``, ``length``...); a ``ShardingRules`` table
maps logical names to mesh axes. This is the GSPMD recipe: annotate,
``with_sharding_constraint``, let XLA insert collectives.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from move2kube_tpu.parallel.compat import bare_spec_constraints_ok, get_abstract_mesh
from move2kube_tpu.utils.log import get_logger

log = get_logger("parallel.sharding")


@dataclass
class ShardingRules:
    """logical axis -> mesh axis (or None = replicated)."""

    rules: dict[str, str | tuple[str, ...] | None] = field(default_factory=dict)

    @classmethod
    def default(cls) -> "ShardingRules":
        """Standard FSDP + TP layout (scaling-book ch. sharding):

        - batch over (data, fsdp): each data-parallel group sees a shard
        - embed over fsdp: ZeRO-3-style parameter sharding
        - mlp/heads over tensor: megatron-style TP
        - length over seq: ring-attention context parallelism
        """
        return cls(rules={
            "batch": ("data", "fsdp"),
            "length": "seq",
            "embed": "fsdp",
            "mlp": "tensor",
            "heads": "tensor",
            "kv": None,
            "vocab": "tensor",
            "norm": None,
            "conv_kernel": None,
            "experts": "expert",  # MoE expert dim (models/moe.py)
            "stage": "pipe",      # pipeline stage dim (parallel/pipeline.py)
        })

    def spec(self, logical_axes: tuple[str | None, ...]) -> P:
        return P(*(self.rules.get(a) if a else None for a in logical_axes))

    def sharding(self, mesh: Mesh, logical_axes: tuple[str | None, ...]) -> NamedSharding:
        return NamedSharding(mesh, self.spec(logical_axes))


def logical_sharding(mesh: Mesh, rules: ShardingRules, logical_axes) -> NamedSharding:
    return rules.sharding(mesh, tuple(logical_axes))


def with_logical_constraint(x, mesh: Mesh, rules: ShardingRules, logical_axes):
    """Constrain an activation's sharding by logical names."""
    return jax.lax.with_sharding_constraint(x, rules.sharding(mesh, tuple(logical_axes)))


def shard_params(params, axes_tree, mesh: Mesh, rules: ShardingRules):
    """Device_put a param pytree according to a matching tree of logical
    axis tuples (None entries = replicated)."""

    def place(p, axes):
        sh = rules.sharding(mesh, axes) if axes else NamedSharding(mesh, P())
        return jax.device_put(p, sh)

    return jax.tree.map(place, params, axes_tree,
                        is_leaf=lambda x: x is None)


def param_shardings(axes_tree, mesh: Mesh, rules: ShardingRules):
    """Tree of NamedShardings for jit in_shardings/out_shardings."""
    return jax.tree.map(
        lambda axes: rules.sharding(mesh, axes) if axes else NamedSharding(mesh, P()),
        axes_tree,
        is_leaf=lambda x: x is None or (isinstance(x, tuple) and all(
            isinstance(e, (str, type(None))) for e in x)),
    )


def maybe_shard(x, spec: P):
    """with_sharding_constraint pruned to the active abstract mesh: axes
    not present in the mesh drop to None, and with no mesh at all the
    constraint is skipped — so model code can annotate unconditionally and
    still run unsharded on a single chip. Shared by llama.py / moe.py."""
    mesh = get_abstract_mesh()
    if getattr(mesh, "empty", True):
        return x
    names = set(mesh.axis_names)
    pruned = []
    for entry in spec:
        if entry is None:
            pruned.append(None)
        elif isinstance(entry, tuple):
            kept = tuple(a for a in entry if a in names)
            pruned.append(kept if kept else None)
        else:
            pruned.append(entry if entry in names else None)
    if not bare_spec_constraints_ok():
        return x  # legacy jax + abstract-only mesh: shape-inert, skip
    return jax.lax.with_sharding_constraint(x, P(*pruned))


def infer_param_axes(params, tp_layers: tuple[str, ...] = ()):
    """Heuristic logical axes for a flax param tree.

    Works for the model zoo's conventions:
    - 2D kernels: last dim is the output feature; shard it over fsdp unless
      the param path names a TP-split layer (gate/up/query/... -> mlp/heads)
    - embeddings: (vocab, None) — vocab-parallel only; feature dim
      replicated (see inline comment)
    - biases/norm scales: replicated
    - conv-DOMINATED trees (4D kernels holding >= half the params:
      ResNet, UNet): EVERYTHING replicated; the fsdp axis only
      contributes batch sharding (see the conv_family comment below).
      Hybrid models whose conv params are a minority (a conv stem on a
      transformer) keep ZeRO sharding for their dense kernels — only the
      4D kernels themselves stay replicated.
    """

    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    # conv-family detection: ZeRO-sharding inside conv nets buys ~100 MB
    # and provokes GSPMD full-remat (round-4 verdict weak #3) — the conv
    # kernels directly (output-channel vs batch on the same fsdp axis)
    # and the per-sample-vector projections (time-embedding MLPs, FiLM
    # shift/scale) via their batch-contraction kernel grads. In trees
    # DOMINATED by conv kernels, every param is replicated: the fsdp
    # axis still contributes batch sharding, so an fsdp>=2 mesh runs
    # clean (tests/test_models.py::test_conv_kernels_replicated_under_fsdp).
    # A stray conv stem must NOT trigger this — whole-tree replication of
    # a conv+transformer hybrid would undo ZeRO for the dominant dense
    # params — so the rule is gated on conv params holding >= half the
    # tree (4D kernels are individually replicated either way).
    def _n(p) -> int:
        return math.prod(getattr(p, "shape", ()) or (1,))

    conv_params = sum(_n(p) for _, p in flat if getattr(p, "ndim", 0) == 4)
    total_params = sum(_n(p) for _, p in flat) or 1
    conv_family = conv_params * 2 >= total_params and conv_params > 0
    if conv_family:
        log.info(
            "conv kernels hold %d/%d params (>= 50%%): replicating the "
            "whole tree (fsdp contributes batch sharding only)",
            conv_params, total_params)

    def axes_for(path, p):
        if conv_family:
            return (None,) * p.ndim
        names = [getattr(k, "key", getattr(k, "name", str(k))) for k in path]
        joined = "/".join(str(n) for n in names).lower()
        nd = p.ndim
        if nd <= 1:
            return (None,) * nd
        if "embedding" in joined:
            # vocab-dim sharding only: sharding the feature dim too would
            # force the backward scatter-add cotangent ([batch, len, embed],
            # batch-sharded) into a feature-sharded layout — GSPMD can only
            # do that reshard by full rematerialization (seen in the r2
            # multichip dryrun); vocab-parallel alone partitions the scatter
            # by masking with no activation reshard
            return ("vocab", None) if nd == 2 else (None,) * nd
        if nd == 2:
            if any(t in joined for t in tp_layers) or any(
                t in joined for t in ("gate", "up_proj", "wi", "query", "key",
                                      "value", "qkv", "lm_head",
                                      "c_attn", "c_fc")  # gpt2 fused names
            ):
                return ("embed", "mlp")
            if any(t in joined for t in ("down_proj", "wo", "out_proj",
                                         "attn_out", "mlp_out")):
                return ("mlp", "embed")
            return (None, "embed")  # generic dense: ZeRO-style shard of out dim
        if nd == 3:
            # MoE expert weights (models/moe.py): [E, d, m] / [E, m, d]
            if "moe" in joined or "expert" in joined:
                if any(t in joined for t in ("w_out", "wo", "down")):
                    return ("experts", "mlp", "embed")
                return ("experts", "embed", "mlp")
            return ("embed", "heads", None)  # attention (embed, heads, head_dim)
        return (None,) * nd

    # rebuild a matching tree
    paths_axes = {tuple(path): axes_for(path, p) for path, p in flat}

    def walk(tree, prefix=()):
        if isinstance(tree, dict):
            return {k: walk(v, prefix + (jax.tree_util.DictKey(k),)) for k, v in tree.items()}
        return paths_axes.get(prefix, (None,) * getattr(tree, "ndim", 0))

    return walk(params)
