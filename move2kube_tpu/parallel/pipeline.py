"""Pipeline parallelism over the ``pipe`` mesh axis.

The TPU-native replacement for detected GPipe/DeepSpeed/Megatron pipeline
stages (gpu_detect reports ``pp``; SURVEY.md §2.15 emission mapping).
Instead of a runtime scheduler pushing microbatches between GPU processes,
the whole schedule is *compiled*: stages live on the ``pipe`` mesh axis,
every device runs the same scanned loop under ``shard_map``, and
activations hop stage→stage with ``ppermute`` (one ICI neighbour exchange
per tick). XLA overlaps the permute with the next microbatch's compute.

Schedule: GPipe with M microbatches over P stages → M + P - 1 ticks; each
device computes every tick (bubble ticks produce garbage that is never
read — branchless, so the loop stays a single compiled ``lax.scan``).
Differentiable end-to-end: the backward pass of ``ppermute`` is the
reverse permute, so ``jax.grad`` yields the textbook 1F1B-equivalent
backward schedule without extra code.

The stage function is typically a block of transformer layers; params for
stage i live only on pipe index i (see ``stack_stage_params``), giving the
same per-device memory saving as GPU pipeline parallelism.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from move2kube_tpu.parallel.compat import axis_size as _axis_size, shard_map


def pipeline_apply(stage_fn, stage_params, x, *, axis_name: str = "pipe",
                   num_microbatches: int | None = None):
    """Run ``stage_fn`` as a P-stage pipeline inside ``shard_map``.

    Args:
      stage_fn: ``(params, x) -> y`` for one stage; same shape in/out.
      stage_params: this device's stage parameters (pytree).
      x: [M, mb, ...] microbatched input, identical on every stage (only
        stage 0 actually consumes it; replication keeps the loop SPMD).
      num_microbatches: defaults to x.shape[0].

    Returns [M, mb, ...] outputs (valid on the *last* stage; other stages
    hold garbage — combine with an out_spec that reads the last stage, or
    psum-mask as done in ``pipeline_sharded``).
    """
    n_stages = _axis_size(axis_name)
    stage_idx = jax.lax.axis_index(axis_name)
    n_micro = num_microbatches or x.shape[0]
    n_ticks = n_micro + n_stages - 1
    mb_shape = x.shape[1:]

    # stage i receives from i-1; stage 0's slot is fed from the input
    shift_perm = [(i, i + 1) for i in range(n_stages - 1)]

    def tick(carry, t):
        state, outputs = carry
        # stage 0 ingests microbatch t (garbage once t >= n_micro; never read)
        mb_in = jax.lax.dynamic_index_in_dim(
            x, jnp.minimum(t, n_micro - 1), axis=0, keepdims=False)
        state = jnp.where(stage_idx == 0, mb_in, state)
        state = stage_fn(stage_params, state)
        # last stage emits microbatch (t - (P-1)) at ticks t >= P-1
        out_slot = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
        valid = t >= (n_stages - 1)
        current = jax.lax.dynamic_index_in_dim(outputs, out_slot, axis=0,
                                               keepdims=False)
        outputs = jax.lax.dynamic_update_index_in_dim(
            outputs, jnp.where(valid, state, current), out_slot, axis=0)
        # hand activations to the next stage (ICI neighbour hop)
        state = jax.lax.ppermute(state, axis_name, shift_perm)
        return (state, outputs), None

    state0 = jnp.zeros(mb_shape, x.dtype)
    out0 = jnp.zeros((n_micro, *mb_shape), x.dtype)
    (_, outputs), _ = jax.lax.scan(tick, (state0, out0), jnp.arange(n_ticks))
    return outputs


def _mask_to_stage(outputs, axis_name: str, stage: int):
    """Zero everywhere except ``stage``, then psum: every device ends up
    holding that stage's outputs (replicated result)."""
    stage_idx = jax.lax.axis_index(axis_name)
    masked = jnp.where(stage_idx == stage, outputs, jnp.zeros_like(outputs))
    return jax.lax.psum(masked, axis_name)


def _mask_to_last_stage(outputs, axis_name: str):
    """Zero everywhere except the last stage, then psum: every stage ends
    up holding the last stage's outputs (replicated result)."""
    return _mask_to_stage(outputs, axis_name, _axis_size(axis_name) - 1)


def interleaved_ticks(n_micro: int, n_stages: int, n_chunks: int) -> int:
    """Tick count of the interleaved schedule (static): last microbatch
    is injected at tick ((M-1)//P)*P*V + (M-1)%P, spends P*V compute
    hops on the ring, and is written back at device 0 one tick later."""
    return ((n_micro - 1) // n_stages) * n_stages * n_chunks \
        + (n_micro - 1) % n_stages + n_stages * n_chunks + 1


def pipeline_apply_interleaved(stage_fn, stage_params, x, *,
                               axis_name: str = "pipe",
                               num_microbatches: int | None = None):
    """Interleaved (looped/1F1B-style) schedule: V chunks per device.

    ``stage_params`` leaves carry a leading [V, ...] chunk axis (see
    ``stack_stage_params_interleaved``): global stage g = v*P + p lives
    on device p as local chunk v, so a microbatch travels the ring V
    laps, applying chunk ``hops // P`` at each visit; the P-1 -> 0 hop
    between laps rides the torus wraparound link.  Device 0 injects a
    fresh microbatch whenever the slot arriving at it has finished all
    P*V hops (or is the initial empty slot), and collects finished
    activations into the output buffer just before reuse.

    Why: with V chunks the pipeline fill/drain bubble shrinks from
    GPipe's (P-1)/(M+P-1) of ticks to (P-1)/(M*V + P-1) — each device
    computes on every tick once the ring is full, and the fill is
    amortized over V times more compute per microbatch.  Branchless and
    scan-compiled like ``pipeline_apply``; the backward pass through
    ppermute/where gives the corresponding interleaved backward
    schedule via plain ``jax.grad``.

    Args mirror ``pipeline_apply``; outputs ([M, mb, ...]) are valid on
    device 0 (the collector) — combine with ``_mask_to_stage(out,
    axis_name, 0)``.
    """
    n_stages = _axis_size(axis_name)
    stage_idx = jax.lax.axis_index(axis_name)
    n_chunks = jax.tree.leaves(stage_params)[0].shape[0]
    n_micro = num_microbatches or x.shape[0]
    total_hops = n_stages * n_chunks
    n_ticks = interleaved_ticks(n_micro, n_stages, n_chunks)
    mb_shape = x.shape[1:]
    ring = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(carry, _t):
        act, hops, mbidx, next_inject, outputs = carry
        finished = hops >= total_hops
        at_collector = stage_idx == 0

        # collect: a finished, real (mbidx >= 0) slot arriving at device 0
        write = at_collector & finished & (mbidx >= 0)
        slot = jnp.clip(mbidx, 0, n_micro - 1)
        current = jax.lax.dynamic_index_in_dim(outputs, slot, axis=0,
                                               keepdims=False)
        outputs = jax.lax.dynamic_update_index_in_dim(
            outputs, jnp.where(write, act, current), slot, axis=0)

        # inject: reuse the freed slot for the next microbatch
        inject = at_collector & finished & (next_inject < n_micro)
        mb_new = jax.lax.dynamic_index_in_dim(
            x, jnp.clip(next_inject, 0, n_micro - 1), axis=0, keepdims=False)
        act = jnp.where(inject, mb_new, act)
        hops = jnp.where(inject, 0, hops)
        mbidx = jnp.where(inject, next_inject,
                          jnp.where(finished, -1, mbidx))
        next_inject = next_inject + inject.astype(next_inject.dtype)

        # compute: chunk index = completed laps (hops // P)
        active = hops < total_hops
        lap = jnp.clip(hops // n_stages, 0, n_chunks - 1)
        params_v = jax.tree.map(
            lambda p: jax.lax.dynamic_index_in_dim(p, lap, axis=0,
                                                   keepdims=False),
            stage_params)
        act = jnp.where(active, stage_fn(params_v, act), act)
        hops = hops + active.astype(hops.dtype)

        # the slot (activation + its bookkeeping) hops to the next device;
        # P-1 -> 0 is the wraparound link
        act = jax.lax.ppermute(act, axis_name, ring)
        hops = jax.lax.ppermute(hops, axis_name, ring)
        mbidx = jax.lax.ppermute(mbidx, axis_name, ring)
        return (act, hops, mbidx, next_inject, outputs), None

    act0 = jnp.zeros(mb_shape, x.dtype)
    hops0 = jnp.int32(total_hops)  # empty slot: "finished", carries no mb
    mbidx0 = jnp.int32(-1)
    out0 = jnp.zeros((n_micro, *mb_shape), x.dtype)
    carry0 = (act0, hops0, mbidx0, jnp.int32(0), out0)
    (_, _, _, _, outputs), _ = jax.lax.scan(tick, carry0, jnp.arange(n_ticks))
    return outputs


def pipeline_sharded(mesh: Mesh, stage_fn, stacked_params, x,
                     *, num_microbatches: int,
                     batch_axes: tuple[str, ...] | None = None,
                     interleave: int = 1):
    """Convenience wrapper: microbatch, shard over the mesh, run, unbatch.

    Args:
      stage_fn: ``(params, x) -> y`` one-stage function.
      stacked_params: pytree with a leading stage axis [P, ...] (see
        ``stack_stage_params``); sharded so each pipe index holds its slice.
        With ``interleave=V`` > 1, leaves are [P, V, ...] (see
        ``stack_stage_params_interleaved``) and the interleaved schedule
        runs V chunks per device, shrinking the bubble to
        (P-1)/(M*V + P-1).
      x: [batch, ...] global input; batch must divide into
        ``num_microbatches`` microbatches.
      batch_axes: mesh axes to shard the microbatch dim over (e.g.
        ``("data", "fsdp")`` composes dp x pp: each data-parallel group
        runs its own pipeline on its batch shard). None = replicated.

    Returns [batch, ...] outputs, replicated over the pipe axis.
    """
    b = x.shape[0]
    if b % num_microbatches:
        raise ValueError(f"batch {b} not divisible into {num_microbatches} microbatches")
    if batch_axes:
        dp = 1
        for a in batch_axes:
            dp *= mesh.shape[a]
        if (b // num_microbatches) % dp:
            raise ValueError(
                f"microbatch size {b // num_microbatches} not divisible over "
                f"batch axes {batch_axes} (={dp} shards); batch must be a "
                f"multiple of num_microbatches*shards = {num_microbatches * dp}")
    xm = x.reshape(num_microbatches, b // num_microbatches, *x.shape[1:])

    param_spec = jax.tree.map(lambda _: P("pipe"), stacked_params)
    x_spec = P(None, tuple(batch_axes)) if batch_axes else P()

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(param_spec, x_spec), out_specs=x_spec,
    )
    def run(params, xs):
        # shard_map gives a [1, ...] stage slice; drop the stage axis
        local = jax.tree.map(lambda p: p[0], params)
        if interleave > 1:
            out = pipeline_apply_interleaved(
                stage_fn, local, xs, num_microbatches=num_microbatches)
            # interleaved outputs finish their last lap at the collector
            # (device 0), not the last stage
            return _mask_to_stage(out, "pipe", 0)
        out = pipeline_apply(stage_fn, local, xs, num_microbatches=num_microbatches)
        return _mask_to_last_stage(out, "pipe")

    out = run(stacked_params, xm)
    return out.reshape(b, *out.shape[2:])


def stack_stage_params(per_stage_params: list):
    """Stack per-stage param pytrees along a new leading [P, ...] axis, the
    layout ``pipeline_sharded`` shards over the ``pipe`` axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)


def stack_stage_params_interleaved(per_stage_params: list, n_devices: int):
    """Stack S = P*V per-stage param pytrees into the [P, V, ...] layout
    ``pipeline_sharded(..., interleave=V)`` shards over ``pipe``: global
    stage g lives on device g mod P as local chunk g div P, so one lap
    of the ring advances the microbatch P consecutive stages."""
    total = len(per_stage_params)
    if total % n_devices:
        raise ValueError(
            f"{total} stages not divisible over {n_devices} pipe devices")
    n_chunks = total // n_devices
    rows = [
        jax.tree.map(
            lambda *xs: jnp.stack(xs),
            *[per_stage_params[v * n_devices + p] for v in range(n_chunks)],
        )
        for p in range(n_devices)
    ]
    return jax.tree.map(lambda *xs: jnp.stack(xs), *rows)
