"""Distributed TPU execution: meshes, shardings, collectives, ring attention.

This package (with ``models`` and ``ops``) is dependency-light by design —
jax / flax / optax / numpy only — because the jax-xla containerizer vendors
it into every emitted training image (see containerizer/jax_emit.py).

Design follows the scaling-book recipe: pick a Mesh, annotate shardings
with NamedSharding/PartitionSpec, let XLA insert the collectives, and keep
ICI-heavy axes (tensor/sequence) innermost so collectives ride ICI, not DCN.
"""

from move2kube_tpu.parallel.mesh import (  # noqa: F401
    MeshConfig,
    make_mesh,
    initialize_distributed,
)
from move2kube_tpu.parallel.ulysses import (  # noqa: F401
    ulysses_attention,
    ulysses_attention_sharded,
)
from move2kube_tpu.parallel.sharding import (  # noqa: F401
    ShardingRules,
    logical_sharding,
    shard_params,
    with_logical_constraint,
)
