"""Per-chip HBM budgeting for sharded training (shape-level, no devices).

Answers "does this training run FIT?" for an emitted translation before
any hardware exists: parameter/gradient/optimizer bytes are computed from
``jax.eval_shape`` of the model init and the same logical-axis sharding
rules ``create_sharded_state`` applies (``infer_param_axes`` +
``ShardingRules``), activations from the remat policy of the LM train
step (per-layer checkpoint boundaries + the largest transient working
set, which for decoder LMs is the float32 logits block).

Used by the BASELINE config-5 gate (DeepSpeed Llama-3-8B ZeRO-3 ->
v5p-64): tests/test_memory_plan.py eval-shapes the full train step on an
abstract 64-chip mesh and asserts the plan fits v5p HBM.

TPU HBM per chip (public specs): v5e 16 GB, v5p 95 GB, v4 32 GB,
v6e 32 GB.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field

import jax
import numpy as np

from move2kube_tpu.parallel.sharding import ShardingRules, infer_param_axes

logger = logging.getLogger(__name__)

HBM_BYTES = {
    "tpu-v5-lite-podslice": 16e9,
    "tpu-v5p-slice": 95e9,
    "tpu-v4-podslice": 32e9,
    "tpu-v6e-slice": 32e9,
}


def hbm_budget_bytes(accelerator: str) -> float:
    """HBM capacity for an accelerator string, tolerating the aliases
    users actually type ("v5e", "v5litepod-8", "TPU v5p"). Strings that
    resolve to no known generation budget like v5e — the smallest table
    entry, so a fit verdict is conservative — with a logged warning
    rather than a KeyError."""
    if accelerator in HBM_BYTES:
        return HBM_BYTES[accelerator]
    from move2kube_tpu.obs.costmodel import normalize_accelerator

    canon = normalize_accelerator(accelerator)
    if canon in HBM_BYTES:
        return HBM_BYTES[canon]
    fallback = min(HBM_BYTES.values())
    logger.warning(
        "unknown accelerator %r: assuming conservative %d GB HBM budget",
        accelerator, int(fallback / 1e9))
    return fallback


@dataclass
class MemoryPlan:
    """Byte budget per chip; ``total`` is the sum the fit check gates on."""

    params: int = 0
    grads: int = 0
    opt_state: int = 0
    activations: int = 0
    # largest single leaves, for the "what dominates" question
    breakdown: list = field(default_factory=list)  # (path, bytes/chip)

    @property
    def total(self) -> int:
        return self.params + self.grads + self.opt_state + self.activations

    def fits(self, accelerator: str, headroom: float = 0.9) -> bool:
        """True when total fits ``headroom`` of the chip's HBM (the
        remaining fraction covers XLA scratch + fragmentation).
        Accelerator aliases are normalized; unknown strings budget
        conservatively (smallest table entry) instead of raising."""
        return self.total <= hbm_budget_bytes(accelerator) * headroom


def _sharded_bytes(shape_dtype, spec, extents: dict[str, int]) -> int:
    """Bytes per chip for one leaf under a PartitionSpec, mirroring
    create_sharded_state._sharding_for: a dim whose size isn't divisible
    by its mesh extent is replicated rather than unevenly sharded."""
    shape = list(shape_dtype.shape)
    for dim, entry in enumerate(spec):
        names = (entry,) if isinstance(entry, str) else (entry or ())
        extent = 1
        for nm in names:
            extent *= extents.get(nm, 1)
        if extent > 1 and shape[dim] % extent == 0:
            shape[dim] //= extent
    return int(np.prod(shape, dtype=np.int64)) * shape_dtype.dtype.itemsize


def train_memory_plan(
    model,
    sample_input: dict,
    mesh_extents: dict[str, int],
    *,
    rules: ShardingRules | None = None,
    optimizer_slots: int = 2,  # adam/adamw: m + v
    seq_len: int | None = None,
    batch_per_chip: int = 1,
    d_model: int | None = None,
    num_layers: int | None = None,
    vocab_size: int | None = None,
    activation_dtype_bytes: int = 2,  # bf16 activations
    top_n: int = 5,
) -> MemoryPlan:
    """Shape-level per-chip memory plan for a remat LM train step.

    Parameter-derived terms come from ``jax.eval_shape`` of
    ``model.init`` + the sharding heuristic (exact). The activation term
    is the analytic remat model: per-layer checkpoint boundaries
    (``num_layers * batch * seq * d_model``) plus the dominant transient
    (float32 logits ``batch * seq * vocab`` for LMs with ``vocab_size``
    set) — the same policy make_lm_train_step compiles (jax.checkpoint
    around each block, loss in float32).
    """
    rules = rules or ShardingRules.default()

    def init_fn(rng):
        variables = model.init(rng, **sample_input)
        return {k: v for k, v in variables.items()
                if k in ("params", "batch_stats")}

    shapes = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
    axes = infer_param_axes(shapes["params"])

    plan = MemoryPlan()
    leaves: list[tuple[str, int]] = []
    flat = jax.tree_util.tree_flatten_with_path(shapes["params"])[0]
    flat_axes = {tuple(p): a for p, a in
                 jax.tree_util.tree_flatten_with_path(
                     axes, is_leaf=lambda x: isinstance(x, tuple) or x is None
                 )[0]}
    for path, leaf in flat:
        ax = flat_axes.get(tuple(path))
        spec = rules.spec(ax) if isinstance(ax, tuple) else ()
        nbytes = _sharded_bytes(leaf, spec, mesh_extents)
        name = "/".join(str(getattr(k, "key", k)) for k in path)
        leaves.append((name, nbytes))
        plan.params += nbytes
    # grads mirror params; adam moments are f32 like the f32 master params
    plan.grads = plan.params
    plan.opt_state = optimizer_slots * plan.params

    if seq_len and d_model and num_layers:
        boundary = (num_layers * batch_per_chip * seq_len * d_model
                    * activation_dtype_bytes)
        transient = 0
        if vocab_size:
            # f32 logits + log_softmax cotangent (2x) dominate LM steps
            transient = 2 * 4 * batch_per_chip * seq_len * vocab_size
        plan.activations = boundary + transient

    plan.breakdown = sorted(leaves, key=lambda t: -t[1])[:top_n]
    return plan
