"""Ulysses-style sequence parallelism: all-to-all head/sequence reshard.

The second context-parallel scheme next to ring attention (SURVEY.md §5
maps DeepSpeed-Ulysses onto the ``seq`` mesh axis). Where ring attention
rotates K/V blocks around the ring (axis_size ppermute hops), Ulysses does
two ``all_to_all`` collectives: reshard [batch, seq/P, heads, d] into
[batch, seq, heads/P, d], run *unsharded* attention on the local head
subset, and reshard back. On a TPU ICI torus the all-to-all rides the same
links with one logical exchange each way, so it wins whenever the head
count divides the seq axis — ring attention remains the fallback for few
heads or sequences too long to materialize per-device.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from move2kube_tpu.parallel.compat import axis_size as _axis_size, shard_map


def _full_attention(q, k, v, *, causal: bool, scale: float):
    """Plain attention on [b, s, h, d] (full sequence, local heads)."""
    s_len = q.shape[1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        q_pos = jnp.arange(s_len)[:, None]
        k_pos = jnp.arange(s_len)[None, :]
        logits = jnp.where((q_pos >= k_pos)[None, None], logits, -1e30)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)


def ulysses_attention(q, k, v, *, axis_name: str = "seq",
                      causal: bool = False, scale: float | None = None):
    """Exact attention over sequence-sharded inputs via head all-to-all.

    Args:
      q, k, v: [batch, seq_shard, heads, head_dim] local shards; ``heads``
        must be divisible by the ``axis_name`` mesh-axis size.
    """
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    axis_size = _axis_size(axis_name)
    if q.shape[2] % axis_size:
        raise ValueError(
            f"heads ({q.shape[2]}) not divisible by |{axis_name}| ({axis_size}); "
            "use ring_attention instead"
        )
    # [b, s/P, h, d] -> [b, s, h/P, d]: gather sequence, scatter heads
    a2a = functools.partial(jax.lax.all_to_all, axis_name=axis_name,
                            split_axis=2, concat_axis=1, tiled=True)
    qf, kf, vf = a2a(q), a2a(k), a2a(v)
    of = _full_attention(qf, kf, vf, causal=causal, scale=scale)
    # [b, s, h/P, d] -> [b, s/P, h, d]: scatter sequence, gather heads
    return jax.lax.all_to_all(of, axis_name=axis_name, split_axis=1,
                              concat_axis=2, tiled=True)


def ulysses_attention_sharded(mesh: Mesh, q, k, v, *, causal: bool = False):
    """Convenience wrapper: shard_map ulysses_attention over the mesh.

    Inputs are [batch, seq, heads, head_dim] global arrays; batch sharded
    over (data, fsdp), seq over seq, heads over tensor (same layout as
    ring_attention_sharded, so the two are drop-in interchangeable).
    """
    spec = P(("data", "fsdp"), "seq", "tensor", None)

    @functools.partial(
        shard_map, mesh=mesh, in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    def run(ql, kl, vl):
        return ulysses_attention(ql, kl, vl, axis_name="seq", causal=causal)

    return run(q, k, v)
