"""ImageStream apiresource (OpenShift).

Parity: ``internal/apiresource/imagestream.go`` — one ImageStream per
built image when the target cluster supports the kind.
"""

from __future__ import annotations

from move2kube_tpu.apiresource.base import APIResource, make_obj
from move2kube_tpu.types.ir import IR
from move2kube_tpu.utils import common

IMAGE_STREAM = "ImageStream"


class ImageStreamAPIResource(APIResource):
    def get_supported_kinds(self) -> list[str]:
        return [IMAGE_STREAM]

    def get_supported_groups(self) -> set[str]:
        return {"image.openshift.io"}

    def create_new_resources(self, ir: IR, supported_kinds: set[str]) -> list[dict]:
        if IMAGE_STREAM not in supported_kinds:
            return []
        objs = []
        for container in ir.containers:
            if not container.new or not container.image_names:
                continue
            image = container.image_names[0]
            name = common.make_dns_label(image.split("/")[-1].split(":")[0])
            obj = make_obj(IMAGE_STREAM, "image.openshift.io/v1", name)
            obj["spec"] = {
                "tags": [{
                    "name": image.rsplit(":", 1)[1] if ":" in image else "latest",
                    "from": {"kind": "DockerImage", "name": image},
                }]
            }
            objs.append(obj)
        return objs
