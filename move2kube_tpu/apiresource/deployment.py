"""Workload apiresource: Deployment and friends, plus TPU JobSets.

Parity: ``internal/apiresource/deployment.go`` — creates the right workload
kind per IR service (Deployment / DeploymentConfig / ReplicationController
/ Pod / DaemonSet / Job by cluster support + service flags) with
bidirectional conversions between them (:106-300).

Net-new: services carrying AcceleratorInfo become **JobSet** workloads with
one replicated job per TPU host group, ``google.com/tpu`` resources, GKE
TPU node selectors and completion indexing — the TPU-native equivalent of
the reference's nvidia.com/gpu Deployments (which it never had; see
SURVEY.md §2.15). Falls back to plain indexed Jobs when the cluster lacks
JobSet.
"""

from __future__ import annotations

import os

from move2kube_tpu.apiresource import obs_wiring
from move2kube_tpu.apiresource.base import APIResource, make_obj, obj_kind
# re-exported from obs_wiring (the shared JobSet/Deployment/Knative
# helper home); kept importable from here for callers predating the hoist
from move2kube_tpu.apiresource.obs_wiring import (  # noqa: F401
    METRICS_PATH,
    metrics_port_value,
    scrape_annotations,
)
from move2kube_tpu.resilience import preemption
from move2kube_tpu.resilience.faults import SLICE_LOST_EXIT_CODE
from move2kube_tpu.types.ir import IR, Service
from move2kube_tpu.utils.log import get_logger

log = get_logger("apiresource.deployment")

DEPLOYMENT = "Deployment"
DEPLOYMENT_CONFIG = "DeploymentConfig"
REPLICATION_CONTROLLER = "ReplicationController"
POD = "Pod"
DAEMON_SET = "DaemonSet"
JOB = "Job"
JOB_SET = "JobSet"

SELECTOR_LABEL = "move2kube-tpu.io/service"


def pod_template(svc: Service, labels: dict) -> dict:
    meta: dict = {"labels": dict(labels)}
    scrape = scrape_annotations(svc)
    if scrape:
        meta["annotations"] = scrape
    spec = svc.pod_spec()
    probe = obs_wiring.readiness_probe(svc)
    if probe:
        # serving pods gate traffic on /readyz (obs/server.py): the probe
        # goes on the container carrying the telemetry port
        for c in spec.get("containers", []) or []:
            env_names = {e.get("name") for e in c.get("env", []) or []}
            if "M2KT_METRICS_PORT" in env_names:
                c.setdefault("readinessProbe", probe)
                break
    return {"metadata": meta, "spec": spec}


def _tpu_resources(svc: Service, workload_kind: str = JOB_SET) -> None:
    """Inject google.com/tpu requests, node selectors and the multi-host
    bootstrap env (consumed by parallel.mesh.initialize_distributed in the
    emitted training program) into the pod spec.

    Pod 0's stable DNS name differs by controller: JobSet pods are named
    ``<jobset>-workers-0-<index>``, plain indexed-Job pods ``<job>-<index>``
    — both resolvable only via the headless service / subdomain.
    """
    acc = svc.accelerator
    if acc is None:
        return
    chips_per_host = _chips_per_host(acc.tpu_topology, acc.num_hosts)
    num_slices = max(1, acc.num_slices)
    svc.subdomain = svc.name  # headless service publishes the pod DNS names
    if workload_kind == JOB_SET:
        coordinator = f"{svc.name}-workers-0-0.{svc.name}:8476"
    else:
        coordinator = f"{svc.name}-0.{svc.name}:8476"
    multihost = acc.num_hosts > 1 or num_slices > 1
    for c in svc.containers:
        res = c.setdefault("resources", {})
        res.setdefault("limits", {})["google.com/tpu"] = chips_per_host
        res.setdefault("requests", {})["google.com/tpu"] = chips_per_host
        env = c.setdefault("env", [])
        existing = {e.get("name") for e in env}
        # checkpoint/resume: point the training program at the first
        # mounted volume so preempted JobSet pods restart from the latest
        # step (models/checkpoint.py reads M2KT_CKPT_DIR)
        mounts = c.get("volumeMounts") or []
        ckpt_dir = (
            mounts[0].get("mountPath", "").rstrip("/") + "/m2kt-checkpoints"
            if mounts else ""
        )
        for name, value in (
            ("M2KT_NUM_HOSTS", str(acc.num_hosts)),
            ("M2KT_COORDINATOR", coordinator if multihost else ""),
            ("M2KT_CKPT_DIR", ckpt_dir),
            # physical topology for the trainer's ICI mesh planner
            # (parallel/topology.py): same strings as the node selectors
            # below, so the mesh the planner lays out matches the slice
            # the scheduler actually places the pods on
            ("M2KT_TPU_TOPOLOGY", acc.tpu_topology or "1x1"),
            ("M2KT_TPU_ACCELERATOR",
             acc.tpu_accelerator or "tpu-v5-lite-podslice"),
            # preemption watcher budget mirrors the pod's grace period
            # (same derivation — the YAML and the trainer can't drift)
            ("M2KT_PREEMPT_GRACE_S", str(preemption.grace_period_seconds())),
            ("M2KT_PREEMPT_FILE", preemption.DEFAULT_SENTINEL),
        ):
            if value and name not in existing:
                env.append({"name": name, "value": value})
        if num_slices > 1 and workload_kind == JOB_SET:
            # multi-slice: DP gradients ride DCN between slices (megascale);
            # each replicatedJob replica is one slice, its index published
            # by the JobSet controller as the job-index annotation. The
            # megascale coordinator resolves through the dedicated
            # <name>-coord headless Service (selector pins slice-0 pod-0)
            # rather than a per-pod DNS name: plain <svc>:<port> resolution
            # works from any slice even before the subdomain records
            # propagate, and survives Helm renaming the workload pods.
            slice_id_ref = {"fieldRef": {"fieldPath":
                "metadata.annotations['jobset.sigs.k8s.io/job-index']"}}
            entries = [
                ("M2KT_NUM_SLICES", {"value": str(num_slices)}),
                ("M2KT_SLICE_ID", {"valueFrom": slice_id_ref}),
                ("MEGASCALE_NUM_SLICES", {"value": str(num_slices)}),
                ("MEGASCALE_SLICE_ID", {"valueFrom": slice_id_ref}),
                ("MEGASCALE_COORDINATOR_ADDRESS",
                 {"value": f"{svc.name}-coord:8080"}),
            ]
            elastic, min_slices = elastic_knobs(svc.name)
            if elastic:
                entries += [
                    ("M2KT_ELASTIC", {"value": "1"}),
                    ("M2KT_ELASTIC_MIN_SLICES", {"value": str(min_slices)}),
                ]
            for name, entry in entries:
                if name not in existing:
                    env.append({"name": name, **entry})
    svc.node_selector.setdefault("cloud.google.com/gke-tpu-accelerator",
                                 acc.tpu_accelerator or "tpu-v5-lite-podslice")
    svc.node_selector.setdefault("cloud.google.com/gke-tpu-topology",
                                 acc.tpu_topology or "1x1")


def _retry_budget(name: str, env_var: str, qa_suffix: str, desc: str,
                  default: int) -> int:
    """Resolve a retry budget knob: env var wins (CI / one-off overrides),
    else it is a QA problem like every other runtime decision (reference
    philosophy) with the env-or-builtin value as the headless default."""
    raw = os.environ.get(env_var, "")
    if raw:
        try:
            return max(0, int(raw))
        except ValueError:
            log.warning("bad %s=%r; ignoring", env_var, raw)
    from move2kube_tpu import qa

    answer = qa.fetch_input(
        f"m2kt.services.{name}.resilience.{qa_suffix}", desc,
        [f"override via {env_var}"], str(default))
    try:
        return max(0, int(answer))
    except (TypeError, ValueError):
        log.warning("non-integer answer %r for %s; keeping default %d",
                    answer, qa_suffix, default)
        return default


def elastic_knobs(name: str) -> tuple[bool, int]:
    """Resolve the elastic-restart knobs for a multislice service:
    whether a slice loss re-plans onto the survivors (``M2KT_ELASTIC``)
    and the surviving-slice floor (``M2KT_ELASTIC_MIN_SLICES``).

    Env wins (CI / one-off overrides); otherwise each is a QA problem —
    the SAME ids (``m2kt.services.<name>.elastic`` / ``.elastic.minslices``)
    the jax-xla emitter and the elastic optimizer pass ask, so one cached
    answer keeps the baked-in template default, the pod env, and the
    chart value agreed. Default is elastic ON: on preemptible multislice
    capacity, losing a slice is weather, and training degraded beats a
    full JobSet reschedule."""
    from move2kube_tpu import qa
    from move2kube_tpu.utils import common

    name = common.make_dns_label(name)
    raw = os.environ.get("M2KT_ELASTIC", "")
    if raw in ("0", "1"):
        elastic = raw == "1"
    else:
        elastic = qa.fetch_bool(
            f"m2kt.services.{name}.elastic",
            f"Keep training on the surviving slices when [{name}] loses a "
            f"TPU slice?",
            ["The in-pod supervisor re-plans the DCN data axis for the "
             "survivors and resumes from the last checkpoint; override "
             "via M2KT_ELASTIC"],
            True)
    raw = os.environ.get("M2KT_ELASTIC_MIN_SLICES", "")
    min_slices = 0
    if raw:
        try:
            min_slices = max(1, int(raw))
        except ValueError:
            log.warning("bad M2KT_ELASTIC_MIN_SLICES=%r; ignoring", raw)
    if not min_slices:
        answer = qa.fetch_input(
            f"m2kt.services.{name}.elastic.minslices",
            f"Minimum surviving slice count for [{name}] before the loss "
            f"is terminal",
            ["below this floor the JobSet failure policy reschedules the "
             "whole set; override via M2KT_ELASTIC_MIN_SLICES"],
            "1")
        try:
            min_slices = max(1, int(answer))
        except (TypeError, ValueError):
            min_slices = 1
    return elastic, min_slices


def _resilience_pod_hooks(template: dict) -> None:
    """Preemption plumbing on a training pod template: a termination grace
    period sized to the checkpoint budget (M2KT_CKPT_BUDGET_S + margin,
    or M2KT_GRACE_PERIOD_S verbatim) and a preStop hook touching the
    sentinel the emitted trainer's watcher polls — preStop fires before
    kubelet delivers SIGTERM, buying the earliest possible warning."""
    spec = template.setdefault("spec", {})
    spec["terminationGracePeriodSeconds"] = preemption.grace_period_seconds()
    for c in spec.get("containers", []):
        c.setdefault("lifecycle", {}).setdefault("preStop", {
            "exec": {"command": [
                "/bin/sh", "-c",
                f"touch {preemption.DEFAULT_SENTINEL}; sleep 2",
            ]},
        })


def _chips_per_host(topology: str, num_hosts: int) -> int:
    from move2kube_tpu.source.gpu_detect import (
        CHIPS_PER_HOST, topology_chip_count)

    try:
        return max(1, topology_chip_count(topology) // max(1, num_hosts))
    except (ValueError, AttributeError):
        log.warning(
            "malformed TPU topology %r; falling back to %d chips per host "
            "(google.com/tpu resource limits may not match the node pool)",
            topology, CHIPS_PER_HOST)
        return CHIPS_PER_HOST


class DeploymentAPIResource(APIResource):
    def get_supported_kinds(self) -> list[str]:
        return [DEPLOYMENT, DEPLOYMENT_CONFIG, REPLICATION_CONTROLLER, POD,
                DAEMON_SET, JOB, JOB_SET]

    def get_supported_groups(self) -> set[str]:
        return {"", "apps", "extensions", "batch", "apps.openshift.io",
                "jobset.x-k8s.io"}

    def create_new_resources(self, ir: IR, supported_kinds: set[str]) -> list[dict]:
        objs = []
        from move2kube_tpu.apiresource import fleet_wiring

        for svc in ir.services.values():
            if svc.only_ingress or not svc.containers:
                continue
            # fleet-mode serving fans out into per-role workloads
            # (router / prefill / decode) instead of one Deployment;
            # podmonitor/rules/coord objects ride along either way
            fleet = fleet_wiring.maybe_fleet_objects(self, svc, ir)
            if fleet is not None:
                objs.extend(fleet)
            else:
                objs.append(self._create_workload(svc, supported_kinds))
            pm = self._maybe_podmonitor(svc, ir)
            if pm:
                objs.append(pm)
            objs.extend(
                obs_wiring.maybe_rules_objects(svc, ir, SELECTOR_LABEL))
            if JOB_SET in supported_kinds:
                coord = self._coordinator_service(svc)
                if coord:
                    objs.append(coord)
        return [o for o in objs if o]

    @staticmethod
    def _coordinator_service(svc: Service) -> dict | None:
        """Headless Service resolving ``MEGASCALE_COORDINATOR_ADDRESS``
        (``<name>-coord``) for multislice JobSets. The selector pins
        slice 0's pod 0 via the labels the JobSet controller stamps on
        every pod (jobset-name + job-index) and the indexed Job's
        completion-index label; publishNotReadyAddresses because the
        megascale transport dials during bootstrap, long before any
        readiness probe can pass."""
        acc = svc.accelerator
        if acc is None or not svc.job or max(1, acc.num_slices) < 2:
            return None
        obj = make_obj("Service", "v1", f"{svc.name}-coord",
                       {SELECTOR_LABEL: svc.name})
        obj["spec"] = {
            "clusterIP": "None",
            "publishNotReadyAddresses": True,
            "selector": {
                "jobset.sigs.k8s.io/jobset-name": svc.name,
                "jobset.sigs.k8s.io/job-index": "0",
                "batch.kubernetes.io/job-completion-index": "0",
            },
            "ports": [
                {"name": "megascale", "port": 8080},
                {"name": "coordinator", "port": 8476},
            ],
        }
        return obj

    def _maybe_podmonitor(self, svc: Service, ir: IR) -> dict | None:
        """Optional prometheus-operator PodMonitor next to the workload,
        behind a QA knob: annotation-based scraping covers vanilla
        Prometheus, but operator-managed stacks only discover
        monitoring.coreos.com selectors. The endpoint references the
        named ``metrics`` container port the obs optimizer added."""
        if svc.accelerator is None or not metrics_port_value(svc):
            return None
        from move2kube_tpu import qa
        from move2kube_tpu.utils import common

        name = common.make_dns_label(svc.name)
        if not qa.fetch_bool(
                f"m2kt.services.{name}.obs.podmonitor",
                f"Emit a prometheus-operator PodMonitor for [{name}]?",
                ["Needs the monitoring.coreos.com CRDs on the cluster; "
                 "scrape annotations are emitted either way"],
                False):
            return None
        cluster = ir.target_cluster_spec
        if cluster.api_kind_version_map and not cluster.supports_kind(
                "PodMonitor"):
            log.warning(
                "%s: PodMonitor requested but the target cluster does not "
                "advertise monitoring.coreos.com; emitting anyway "
                "(honored once the CRDs are installed)", svc.name)
        obj = make_obj("PodMonitor", "monitoring.coreos.com/v1",
                       f"{svc.name}-metrics", {SELECTOR_LABEL: svc.name})
        obj["spec"] = {
            "selector": {"matchLabels": {SELECTOR_LABEL: svc.name}},
            "podMetricsEndpoints": [
                {"port": "metrics", "path": METRICS_PATH}],
        }
        return obj

    def _create_workload(self, svc: Service, supported: set[str]) -> dict | None:
        labels = {SELECTOR_LABEL: svc.name, **svc.labels}
        # TPU training service -> JobSet (net-new)
        if svc.accelerator is not None and svc.job:
            if JOB_SET in supported:
                _tpu_resources(svc, JOB_SET)
                return self._create_jobset(svc, labels)
            log.warning("%s: cluster lacks JobSet; emitting indexed Job", svc.name)
            _tpu_resources(svc, JOB)
            return self._create_job(svc, labels)
        if svc.job:
            return self._create_job(svc, labels)
        if svc.daemon:
            if DAEMON_SET in supported:
                return self._create_daemonset(svc, labels)
            log.warning("%s: cluster lacks DaemonSet; emitting Deployment", svc.name)
        if svc.accelerator is not None:
            # TPU serving service in k8s output mode (knative output emits
            # a knative Service instead): the long-running Deployment needs
            # the same chip requests + node selectors as the JobSet path
            _tpu_resources(svc, DEPLOYMENT)
        if DEPLOYMENT in supported or not supported:
            return self._create_deployment(svc, labels)
        if DEPLOYMENT_CONFIG in supported:
            return self._create_deploymentconfig(svc, labels)
        if REPLICATION_CONTROLLER in supported:
            return self._create_rc(svc, labels)
        if POD in supported:
            return self._create_pod(svc, labels)
        return self._create_deployment(svc, labels)

    # -- creators -----------------------------------------------------------

    def _create_deployment(self, svc: Service, labels: dict) -> dict:
        obj = make_obj(DEPLOYMENT, "apps/v1", svc.name, labels)
        svc.restart_policy = svc.restart_policy or "Always"
        if svc.restart_policy != "Always":
            svc.restart_policy = "Always"  # deployments only support Always
        obj["spec"] = {
            "replicas": svc.replicas,
            "selector": {"matchLabels": {SELECTOR_LABEL: svc.name}},
            "template": pod_template(svc, labels),
        }
        if svc.annotations:
            obj["metadata"]["annotations"] = dict(svc.annotations)
        return obj

    def _create_daemonset(self, svc: Service, labels: dict) -> dict:
        obj = make_obj(DAEMON_SET, "apps/v1", svc.name, labels)
        obj["spec"] = {
            "selector": {"matchLabels": {SELECTOR_LABEL: svc.name}},
            "template": pod_template(svc, labels),
        }
        return obj

    def _create_job(self, svc: Service, labels: dict) -> dict:
        obj = make_obj(JOB, "batch/v1", svc.name, labels)
        svc.restart_policy = svc.restart_policy or "Never"
        if svc.restart_policy == "Always":
            svc.restart_policy = "OnFailure"
        completions = svc.accelerator.num_hosts if svc.accelerator else svc.replicas
        template = pod_template(svc, labels)
        if svc.accelerator is not None:
            _resilience_pod_hooks(template)
        obj["spec"] = {
            "completions": completions,
            "parallelism": completions,
            "completionMode": "Indexed",
            "backoffLimit": _retry_budget(
                svc.name, "M2KT_BACKOFF_LIMIT", "backoffLimit",
                f"Pod failure budget (backoffLimit) for job [{svc.name}]", 4),
            "template": template,
        }
        return obj

    def _create_jobset(self, svc: Service, labels: dict) -> dict:
        """GKE TPU multi-host JobSet (jobset.x-k8s.io/v1alpha2).

        Preemption-aware failure policy: a TPU slice is reclaimed as a
        unit, so pod disruptions (DisruptionTarget condition: preemption,
        maintenance, node drain) fail the job *fast* via the pod failure
        policy and the JobSet-level rule restarts the whole set WITHOUT
        burning maxRestarts — eviction is the normal case, not a crash.
        Everything else (a real trainer bug → BackoffLimitExceeded)
        counts against ``maxRestarts`` so a broken image can't restart
        forever. In-pod transient retries are cheaper and happen first
        (resilience.supervisor, the image entrypoint)."""
        acc = svc.accelerator
        obj = make_obj(JOB_SET, "jobset.x-k8s.io/v1alpha2", svc.name, labels)
        # a source-declared OnFailure restart policy is honored (kubelet
        # restarts the container in place, cheapest possible recovery);
        # anything else is a run-to-completion Never
        if svc.restart_policy != "OnFailure":
            svc.restart_policy = "Never"
        svc.subdomain = svc.name  # stable host names for jax.distributed
        template = pod_template(svc, labels)
        _resilience_pod_hooks(template)
        job_spec = {
            "parallelism": acc.num_hosts,
            "completions": acc.num_hosts,
            "completionMode": "Indexed",
            "backoffLimit": 0,
            "template": template,
        }
        if svc.restart_policy == "Never":
            # podFailurePolicy requires restartPolicy: Never
            rules = [{
                "action": "FailJob",
                "onPodConditions": [
                    {"type": "DisruptionTarget", "status": "True"},
                ],
            }]
            if max(1, acc.num_slices) > 1:
                # terminal slice loss (supervisor exits 83: elastic off,
                # or survivors under the floor) fails the job fast; the
                # JobSet-level PodFailurePolicy rule then restarts the
                # whole set without burning maxRestarts — same free-
                # restart lane as preemption, because slice reclaim is
                # capacity weather, not a code bug
                rules.append({
                    "action": "FailJob",
                    "onExitCodes": {
                        "operator": "In",
                        "values": [SLICE_LOST_EXIT_CODE],
                    },
                })
            job_spec["podFailurePolicy"] = {"rules": rules}
        obj["spec"] = {
            "failurePolicy": {
                "maxRestarts": _retry_budget(
                    svc.name, "M2KT_MAX_RESTARTS", "maxRestarts",
                    f"JobSet restart budget (maxRestarts) for [{svc.name}]",
                    3),
                "rules": [{
                    # host failure / preemption: restart the whole JobSet
                    # (multihost jax needs a full re-bootstrap) for free
                    "name": "restart-on-host-failure",
                    "action": "RestartJobSetAndIgnoreMaxRestarts",
                    "onJobFailureReasons": ["PodFailurePolicy"],
                }],
            },
            "replicatedJobs": [{
                "name": "workers",
                "replicas": max(1, acc.num_slices),  # one Job replica per slice
                "template": {"spec": job_spec},
            }],
        }
        return obj

    def _create_deploymentconfig(self, svc: Service, labels: dict) -> dict:
        obj = make_obj(DEPLOYMENT_CONFIG, "apps.openshift.io/v1", svc.name, labels)
        obj["spec"] = {
            "replicas": svc.replicas,
            "selector": {SELECTOR_LABEL: svc.name},
            "template": pod_template(svc, labels),
        }
        return obj

    def _create_rc(self, svc: Service, labels: dict) -> dict:
        obj = make_obj(REPLICATION_CONTROLLER, "v1", svc.name, labels)
        obj["spec"] = {
            "replicas": svc.replicas,
            "selector": {SELECTOR_LABEL: svc.name},
            "template": pod_template(svc, labels),
        }
        return obj

    def _create_pod(self, svc: Service, labels: dict) -> dict:
        obj = make_obj(POD, "v1", svc.name, labels)
        obj["spec"] = svc.pod_spec()
        obj["spec"]["restartPolicy"] = svc.restart_policy or "Always"
        return obj

    # -- conversions (deployment.go:106-300) --------------------------------

    def convert_to_cluster_supported_kinds(
        self, obj: dict, supported: set[str], other_objs: list[dict], ir: IR,
    ) -> list[dict]:
        kind = obj_kind(obj)
        if kind in supported or not supported:
            return [obj]
        template, replicas = self._extract_template(obj)
        if kind == JOB_SET and JOB in supported:
            return [self._jobset_to_job(obj)]
        if DEPLOYMENT in supported:
            return [self._rebuild(obj, DEPLOYMENT, "apps/v1", template, replicas,
                                  match_labels=True)]
        if DEPLOYMENT_CONFIG in supported:
            return [self._rebuild(obj, DEPLOYMENT_CONFIG, "apps.openshift.io/v1",
                                  template, replicas, match_labels=False)]
        if REPLICATION_CONTROLLER in supported:
            return [self._rebuild(obj, REPLICATION_CONTROLLER, "v1", template,
                                  replicas, match_labels=False)]
        if POD in supported:
            pod = make_obj(POD, "v1", obj["metadata"]["name"],
                           obj.get("metadata", {}).get("labels"))
            pod["spec"] = template.get("spec", {})
            return [pod]
        return [obj]

    @staticmethod
    def _extract_template(obj: dict) -> tuple[dict, int]:
        kind = obj_kind(obj)
        spec = obj.get("spec", {})
        if kind == POD:
            return {"metadata": obj.get("metadata", {}), "spec": spec}, 1
        if kind == JOB_SET:
            jobs = spec.get("replicatedJobs", [])
            if jobs:
                jspec = jobs[0].get("template", {}).get("spec", {})
                return jspec.get("template", {}), jspec.get("parallelism", 1)
            return {}, 1
        return spec.get("template", {}), spec.get("replicas", 1)

    def _rebuild(self, obj: dict, kind: str, api_version: str, template: dict,
                 replicas: int, match_labels: bool) -> dict:
        name = obj["metadata"]["name"]
        labels = template.get("metadata", {}).get("labels") or {SELECTOR_LABEL: name}
        new = make_obj(kind, api_version, name, obj.get("metadata", {}).get("labels"))
        selector = {"matchLabels": labels} if match_labels else dict(labels)
        new["spec"] = {"replicas": replicas, "selector": selector, "template": template}
        return new

    @staticmethod
    def _jobset_to_job(obj: dict) -> dict:
        jobs = obj.get("spec", {}).get("replicatedJobs", [])
        jspec = jobs[0].get("template", {}).get("spec", {}) if jobs else {}
        job = make_obj(JOB, "batch/v1", obj["metadata"]["name"],
                       obj.get("metadata", {}).get("labels"))
        job["spec"] = jspec or {"template": {}}
        return job
