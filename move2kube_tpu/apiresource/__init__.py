from move2kube_tpu.apiresource.base import APIResource, convert_objects  # noqa: F401
