"""RBAC apiresources: ServiceAccount / Role / RoleBinding.

Parity: ``internal/apiresource/{serviceaccount,role,rolebinding}.go``.
"""

from __future__ import annotations

from move2kube_tpu.apiresource.base import APIResource, make_obj
from move2kube_tpu.types.ir import IR


class ServiceAccountAPIResource(APIResource):
    def get_supported_kinds(self) -> list[str]:
        return ["ServiceAccount"]

    def get_supported_groups(self) -> set[str]:
        return {""}

    def create_new_resources(self, ir: IR, supported_kinds: set[str]) -> list[dict]:
        objs = []
        for sa in ir.service_accounts:
            obj = make_obj("ServiceAccount", "v1", sa.get("name", ""))
            if sa.get("secrets"):
                obj["secrets"] = [{"name": s} for s in sa["secrets"]]
            objs.append(obj)
        return objs


class RoleAPIResource(APIResource):
    def get_supported_kinds(self) -> list[str]:
        return ["Role"]

    def get_supported_groups(self) -> set[str]:
        return {"rbac.authorization.k8s.io"}

    def create_new_resources(self, ir: IR, supported_kinds: set[str]) -> list[dict]:
        objs = []
        for role in ir.roles:
            obj = make_obj("Role", "rbac.authorization.k8s.io/v1", role.get("name", ""))
            obj["rules"] = role.get("rules", [])
            objs.append(obj)
        return objs


class RoleBindingAPIResource(APIResource):
    def get_supported_kinds(self) -> list[str]:
        return ["RoleBinding"]

    def get_supported_groups(self) -> set[str]:
        return {"rbac.authorization.k8s.io"}

    def create_new_resources(self, ir: IR, supported_kinds: set[str]) -> list[dict]:
        objs = []
        for rb in ir.role_bindings:
            obj = make_obj("RoleBinding", "rbac.authorization.k8s.io/v1", rb.get("name", ""))
            obj["subjects"] = [{
                "kind": "ServiceAccount",
                "name": rb.get("service_account", ""),
            }]
            obj["roleRef"] = {
                "kind": "Role",
                "name": rb.get("role", ""),
                "apiGroup": "rbac.authorization.k8s.io",
            }
            objs.append(obj)
        return objs
