"""Fleet-serving emission wiring shared by the workload emitters.

One serving IR service can fan out into a *fleet*: a CPU-only request
router fronting N decode replicas (each with its refcounted prefix
cache — serving/fleet/) and, optionally, dedicated prefill replicas for
disaggregated long prompts. This module is the single owner of that
fan-out so the Deployment path (``apiresource/deployment.py``) and the
Knative path (``apiresource/knative.py``) emit the same roles, env
contract, and autoscaling targets:

- :func:`fleet_knobs` — the ``m2kt.services.<name>.serve.fleet.*`` QA
  problems (env wins: ``M2KT_FLEET`` / ``M2KT_FLEET_ROUTERS`` /
  ``M2KT_FLEET_PREFILL`` / ``M2KT_FLEET_DECODE`` /
  ``M2KT_FLEET_AFFINITY_SALT`` / ``M2KT_FLEET_SWAP`` /
  ``M2KT_WEIGHTS_PORT``), asked once and cached so the optimizer
  pass baking the pod env, the parameterizer lifting it into chart
  values, and the emitters sizing the role workloads cannot disagree;
- :func:`role_service` — clones the IR service into one role
  (``router`` / ``prefill`` / ``decode``) with ``M2KT_FLEET_ROLE`` set
  and the router stripped of TPU resources (it never touches a chip);
- :func:`fleet_objects` — per-role Deployments, headless role Services
  (the router enumerates backend *pod* IPs for session affinity — a
  ClusterIP VIP would re-balance every request and destroy cache
  locality), and autoscaling/v2 HPAs on the serving gauges: router and
  prefill scale on ``m2kt_serve_queue_depth``, decode on
  ``m2kt_serve_slot_occupancy``.

The emitted serve template (assets/jax/serve_tpu.py) dispatches on
``M2KT_FLEET_ROLE`` at runtime; the front k8s Service keeps selecting
``SELECTOR_LABEL: <name>`` and only router pods carry that label, so
external traffic enters through the router without any Service edits.
"""

from __future__ import annotations

import copy
import os

from move2kube_tpu.apiresource.base import make_obj
from move2kube_tpu.types.ir import Service
from move2kube_tpu.utils.log import get_logger

log = get_logger("apiresource.fleetwiring")

ROLE_LABEL = "move2kube-tpu.io/role"
ROUTER_ROLE = "router"
PREFILL_ROLE = "prefill"
DECODE_ROLE = "decode"
# the predictive-autoscaler controller (serving/fleet/autoscaler.py):
# one CPU-only pod next to the router, scraping its admitted-token
# counters and exporting the m2kt_autoscale_* gauges. When this role is
# emitted the reactive per-role HPAs are NOT — two controllers writing
# the same Deployment's replica count would fight (dueling-controller
# guard, asserted by tests/test_autoscale.py).
AUTOSCALER_ROLE = "autoscaler"

# gauges exported by the serving engine (serving/engine.py) that the
# per-role HPAs target; names asserted by tests/test_fleet.py
QUEUE_DEPTH_METRIC = "m2kt_serve_queue_depth"
SLOT_OCCUPANCY_METRIC = "m2kt_serve_slot_occupancy"


def _int_env(var: str) -> int | None:
    raw = os.environ.get(var, "")
    if not raw:
        return None
    try:
        return max(0, int(raw))
    except ValueError:
        log.warning("bad %s=%r; ignoring", var, raw)
        return None


def fleet_knobs(svc_name: str) -> dict | None:
    """Resolve the fleet topology for a serving service, or None when
    fleet mode is off. Env wins (CI / one-off overrides); otherwise each
    knob is a QA problem under ``m2kt.services.<name>.serve.fleet.*`` —
    the SAME ids the fleet optimizer pass and both workload emitters
    ask, so one cached answer keeps the baked pod env, the chart values,
    and the role replica counts agreed."""
    from move2kube_tpu import qa
    from move2kube_tpu.utils import common

    name = common.make_dns_label(svc_name)
    raw = os.environ.get("M2KT_FLEET", "")
    if raw in ("0", "1"):
        enabled = raw == "1"
    else:
        enabled = qa.fetch_bool(
            f"m2kt.services.{name}.serve.fleet",
            f"Serve [{name}] as a fleet (router + replicated engines)?",
            ["Emits one workload per role — a prefix-affine request "
             "router fronting N decode replicas with refcounted prefix "
             "caching, plus optional disaggregated prefill replicas; "
             "override via M2KT_FLEET"],
            False)
    if not enabled:
        return None
    counts = {}
    for key, env_var, qid, desc, default in (
        ("routers", "M2KT_FLEET_ROUTERS", "serve.fleet.routers",
         "Router replicas for [{name}]", "1"),
        ("prefill", "M2KT_FLEET_PREFILL", "serve.fleet.prefill",
         "Dedicated prefill replicas for [{name}] (0 = no "
         "disaggregation)", "0"),
        ("decode", "M2KT_FLEET_DECODE", "serve.fleet.decode",
         "Decode engine replicas for [{name}]", "2"),
    ):
        value = _int_env(env_var)
        if value is None:
            answer = qa.fetch_input(
                f"m2kt.services.{name}.{qid}", desc.format(name=name),
                [f"override via {env_var}"], default)
            try:
                value = max(0, int(answer))
            except (TypeError, ValueError):
                log.warning("invalid %s answer %r for %s; using %s",
                            qid, answer, name, default)
                value = int(default)
        counts[key] = value
    counts["routers"] = max(1, counts["routers"])
    counts["decode"] = max(1, counts["decode"])
    # resilience knobs: the request deadline every hop inherits (router
    # -> replica -> engine admission), the drain grace the preStop hook
    # and SIGTERM handler honor, and the PodDisruptionBudget floor
    for key, env_var, qid, desc, default in (
        ("deadline", "M2KT_DEADLINE_S", "serve.fleet.deadline",
         "End-to-end request deadline (seconds) for [{name}]'s fleet "
         "(0 = none)", "120"),
        ("draingrace", "M2KT_DRAIN_GRACE_S", "serve.fleet.draingrace",
         "Graceful-drain budget (seconds) for [{name}]'s replicas",
         "30"),
    ):
        raw = os.environ.get(env_var, "")
        if not raw:
            raw = str(qa.fetch_input(
                f"m2kt.services.{name}.{qid}", desc.format(name=name),
                [f"override via {env_var}"], default) or default)
        try:
            counts[key] = max(0.0, float(raw))
        except ValueError:
            log.warning("bad %s %r for %s; using %s", qid, raw, name,
                        default)
            counts[key] = float(default)
    minavail = _int_env("M2KT_FLEET_MIN_AVAILABLE")
    if minavail is None:
        answer = qa.fetch_input(
            f"m2kt.services.{name}.serve.fleet.minavailable",
            f"PodDisruptionBudget minAvailable per role for [{name}]",
            ["Floor of pods a voluntary disruption (node drain, upgrade) "
             "must leave running in each fleet role; override via "
             "M2KT_FLEET_MIN_AVAILABLE"], "1")
        try:
            minavail = max(0, int(answer))
        except (TypeError, ValueError):
            log.warning("invalid minavailable answer %r for %s; using 1",
                        answer, name)
            minavail = 1
    counts["minavailable"] = minavail
    # weight plane: P2P shard streaming for joining replicas plus the
    # zero-downtime live weight swap (serving/fleet/weights.py). On by
    # default — with no healthy peer the fetch falls back to the store,
    # so the knob only exists to turn the extra listener off entirely.
    raw = os.environ.get("M2KT_FLEET_SWAP", "")
    if raw in ("0", "1"):
        counts["swap"] = raw == "1"
    else:
        counts["swap"] = qa.fetch_bool(
            f"m2kt.services.{name}.serve.fleet.swap",
            f"Enable [{name}]'s fleet weight plane (P2P weight "
            "streaming + live swap)?",
            ["Joining replicas stream parameter shards from serving "
             "peers instead of the checkpoint store, and POST /swap "
             "rolls new weights across the fleet without dropping "
             "in-flight streams; override via M2KT_FLEET_SWAP"],
            True)
    wport = _int_env("M2KT_WEIGHTS_PORT")
    if counts["swap"] and wport is None:
        answer = qa.fetch_input(
            f"m2kt.services.{name}.serve.fleet.weightsport",
            f"Weight-plane port for [{name}]'s engine replicas",
            ["The per-pod listener peers fetch shards from — its own "
             "named Service port, separate from serving and metrics "
             "traffic; override via M2KT_WEIGHTS_PORT"], "8981")
        try:
            wport = max(1, int(answer))
        except (TypeError, ValueError):
            log.warning("invalid weightsport answer %r for %s; using "
                        "8981", answer, name)
            wport = 8981
    counts["weightsport"] = wport if counts["swap"] else 0
    salt = os.environ.get("M2KT_FLEET_AFFINITY_SALT", "")
    if not salt:
        salt = str(qa.fetch_input(
            f"m2kt.services.{name}.serve.fleet.salt",
            f"Affinity salt for [{name}]'s prefix-hash routing",
            ["Mixed into the rendezvous hash so tenant->replica "
             "placement reshuffles on demand; override via "
             "M2KT_FLEET_AFFINITY_SALT"],
            "") or "")
    counts["salt"] = salt
    # predictive autoscaling: off by default (the reactive HPAs keep
    # working untouched); on, the controller Deployment replaces them
    raw = os.environ.get("M2KT_AUTOSCALE", "")
    if raw in ("0", "1"):
        counts["autoscale"] = raw == "1"
    else:
        counts["autoscale"] = qa.fetch_bool(
            f"m2kt.services.{name}.serve.fleet.autoscale",
            f"Enable predictive autoscaling for [{name}]'s fleet?",
            ["Emits a forecast-driven controller Deployment (demand "
             "forecast over the router's admitted-token counters, "
             "scale-up lead = cold-join time, drain-based scale-down) "
             "INSTEAD of the per-role reactive HPAs; override via "
             "M2KT_AUTOSCALE"],
            False)
    if counts["autoscale"]:
        for key, env_var, qid, desc, default in (
            ("autoscalelead", "M2KT_AUTOSCALE_LEAD_S",
             "serve.fleet.autoscale.lead",
             "Scale-up lead time (seconds) for [{name}] — the forecast "
             "horizon, sized to the measured replica cold-join time",
             "120"),
            ("autoscalemax", "M2KT_AUTOSCALE_MAX",
             "serve.fleet.autoscale.max",
             "Predictive autoscaler replica ceiling for [{name}]", "8"),
            ("autoscaleutil", "M2KT_AUTOSCALE_TARGET_UTIL",
             "serve.fleet.autoscale.util",
             "Target utilization (0..1) forecast demand may fill of "
             "[{name}]'s capacity", "0.7"),
        ):
            raw = os.environ.get(env_var, "")
            if not raw:
                raw = str(qa.fetch_input(
                    f"m2kt.services.{name}.{qid}", desc.format(name=name),
                    [f"override via {env_var}"], default) or default)
            try:
                counts[key] = max(0.0, float(raw))
            except ValueError:
                log.warning("bad %s %r for %s; using %s", qid, raw, name,
                            default)
                counts[key] = float(default)
    return counts


def _serving_port(svc: Service) -> int:
    acc = svc.accelerator
    port = getattr(acc, "serving_port", 0) or 0
    if not port:
        for c in svc.containers:
            for p in c.get("ports", []) or []:
                if p.get("name") != "metrics" and p.get("containerPort"):
                    return int(p["containerPort"])
    return int(port) or 8080


def _set_env(container: dict, name: str, value: str) -> None:
    env = container.setdefault("env", [])
    for e in env:
        if e.get("name") == name:
            e["value"] = value
            return
    env.append({"name": name, "value": value})


def role_service(svc: Service, role: str, knobs: dict) -> Service:
    """Clone the IR service into one fleet role. The clone's name is
    ``<name>-<role>``; its containers carry ``M2KT_FLEET_ROLE`` plus the
    role's wiring env. The router clone drops the accelerator entirely —
    it is a stdlib-HTTP process that must schedule on ordinary nodes,
    so TPU requests, node selectors and tolerations all go."""
    clone = copy.deepcopy(svc)
    clone.name = f"{svc.name}-{role}"
    clone.backend_service_name = ""
    clone.subdomain = ""
    port = _serving_port(svc)
    for c in clone.containers:
        _set_env(c, "M2KT_FLEET_ROLE", role)
        if role == AUTOSCALER_ROLE:
            # the controller scrapes the router's counters through the
            # front Service (the router serves /metrics on the traffic
            # port) and targets the decode Deployment's scale
            _set_env(c, "M2KT_AUTOSCALE", "1")
            _set_env(c, "M2KT_AUTOSCALE_METRICS_URL",
                     f"http://{svc.name}:{port}/metrics")
            _set_env(c, "M2KT_AUTOSCALE_TARGET",
                     f"{svc.name}-{DECODE_ROLE}")
            _set_env(c, "M2KT_AUTOSCALE_LEAD_S",
                     f"{knobs.get('autoscalelead', 120.0):g}")
            _set_env(c, "M2KT_AUTOSCALE_MAX",
                     f"{int(knobs.get('autoscalemax', 8))}")
            _set_env(c, "M2KT_AUTOSCALE_TARGET_UTIL",
                     f"{knobs.get('autoscaleutil', 0.7):g}")
            _set_env(c, "M2KT_AUTOSCALE_MIN",
                     f"{max(1, int(knobs.get('decode', 1)))}")
            c.get("resources", {}).get("limits", {}).pop(
                "google.com/tpu", None)
            c.get("resources", {}).get("requests", {}).pop(
                "google.com/tpu", None)
            continue
        if role == ROUTER_ROLE:
            _set_env(c, "M2KT_ROUTER_BACKENDS",
                     f"{svc.name}-{DECODE_ROLE}:{port}")
            if knobs.get("prefill", 0) > 0:
                _set_env(c, "M2KT_FLEET_PREFILL_SERVICE",
                         f"{svc.name}-{PREFILL_ROLE}:{port}")
            if knobs.get("salt"):
                _set_env(c, "M2KT_FLEET_AFFINITY_SALT", str(knobs["salt"]))
            c.get("resources", {}).get("limits", {}).pop(
                "google.com/tpu", None)
            c.get("resources", {}).get("requests", {}).pop(
                "google.com/tpu", None)
        elif role == DECODE_ROLE:
            # decode replicas own the refcounted prefix cache; the
            # router's session affinity only pays off if it is on
            _set_env(c, "M2KT_SERVE_PREFIX_CACHE", "1")
        if role != ROUTER_ROLE:
            # weight plane: every engine replica serves shards on the
            # weights port and fetches through the decode role's
            # headless DNS (one name fans out to every pod IP) before
            # falling back to the checkpoint store
            wport = int(knobs.get("weightsport", 0) or 0)
            _set_env(c, "M2KT_WEIGHTS_PORT", str(wport))
            if wport > 0:
                _set_env(c, "M2KT_WEIGHTS_PEERS",
                         f"{svc.name}-{DECODE_ROLE}:{wport}")
    if role in (ROUTER_ROLE, AUTOSCALER_ROLE):
        clone.accelerator = None
        clone.node_selector = {
            k: v for k, v in clone.node_selector.items()
            if not k.startswith("cloud.google.com/gke-tpu")}
        clone.tolerations = [
            t for t in clone.tolerations
            if t.get("key") != "google.com/tpu"]
    replicas = {ROUTER_ROLE: knobs.get("routers", 1),
                PREFILL_ROLE: knobs.get("prefill", 0),
                DECODE_ROLE: knobs.get("decode", 2),
                AUTOSCALER_ROLE: 1}[role]
    clone.replicas = max(1, int(replicas))
    return clone


def fleet_roles(knobs: dict) -> list[str]:
    roles = [ROUTER_ROLE]
    if knobs.get("prefill", 0) > 0:
        roles.append(PREFILL_ROLE)
    roles.append(DECODE_ROLE)
    return roles


def role_headless_service(svc: Service, role: str, selector_label: str,
                          port: int, weights_port: int = 0) -> dict:
    """Headless Service for a backend role: DNS on ``<name>-<role>``
    answers with the *pod* IPs, which is what the router's rendezvous
    hashing needs — a ClusterIP VIP would pick a random pod per request
    and the prefix caches would never warm.

    ``weights_port`` > 0 publishes the weight plane as its own *named*
    port: peer discovery (``M2KT_WEIGHTS_PEERS`` resolves this Service)
    and the prometheus scrape annotations each get a distinct name
    instead of both being inferred off the unnamed-extra-port/metrics
    convention — an unnamed second port is also simply invalid k8s once
    a Service has more than one."""
    name = f"{svc.name}-{role}"
    obj = make_obj("Service", "v1", name, {selector_label: svc.name,
                                           ROLE_LABEL: role})
    ports = [{"name": "serve", "port": port}]
    if weights_port and int(weights_port) != port:
        ports.append({"name": "weights", "port": int(weights_port)})
    obj["spec"] = {
        "clusterIP": "None",
        "selector": {selector_label: name},
        "ports": ports,
    }
    return obj


def role_hpa(svc: Service, role: str, replicas: int) -> dict:
    """autoscaling/v2 HPA for one role. Router and prefill scale on the
    queue building in front of them (``m2kt_serve_queue_depth``); decode
    scales on batch-slot saturation (``m2kt_serve_slot_occupancy`` is
    0..1, target 70%) — the gauges the engines already export through
    the scraped registry, surfaced to the HPA by any prometheus-adapter
    style metrics pipeline."""
    name = f"{svc.name}-{role}"
    if role == DECODE_ROLE:
        metric, target = SLOT_OCCUPANCY_METRIC, "700m"
    else:
        metric, target = QUEUE_DEPTH_METRIC, "4"
    obj = make_obj("HorizontalPodAutoscaler", "autoscaling/v2", name,
                   {ROLE_LABEL: role})
    obj["spec"] = {
        "scaleTargetRef": {"apiVersion": "apps/v1", "kind": "Deployment",
                           "name": name},
        "minReplicas": max(1, int(replicas)),
        "maxReplicas": max(2, int(replicas) * 4),
        "metrics": [{
            "type": "Pods",
            "pods": {
                "metric": {"name": metric},
                "target": {"type": "AverageValue",
                           "averageValue": target},
            },
        }],
    }
    return obj


def role_pdb(svc: Service, role: str, selector: dict,
             min_available) -> dict:
    """policy/v1 PodDisruptionBudget for one fleet role, so a node drain
    or upgrade never takes a whole role down at once. ``min_available``
    is an int, or the ``{{ .Values.tpufleetminavailable }}`` ref when
    the Helm parameterizer seeded the chart value (PDB minAvailable is
    an IntOrString field, so the rendered string form is valid)."""
    name = f"{svc.name}-{role}"
    obj = make_obj("PodDisruptionBudget", "policy/v1", name,
                   {ROLE_LABEL: role})
    obj["spec"] = {
        "minAvailable": min_available,
        "selector": {"matchLabels": dict(selector)},
    }
    return obj


# a serving pod's preStop: POST /drain on the traffic port and block
# until the replica finished (or gave up on) its in-flight streams —
# only then does kubelet deliver SIGTERM. stdlib urllib: the serving
# image carries no curl.
_DRAIN_PRESTOP = ("import urllib.request\n"
                  "urllib.request.urlopen(urllib.request.Request("
                  "'http://127.0.0.1:{port}/drain', data=b''), "
                  "timeout={timeout})")


def drain_pod_hooks(template: dict, role: str, port: int,
                    grace_s: float) -> None:
    """Graceful-drain plumbing on a serving pod template: a termination
    grace period sized to the drain budget (plus margin for the final
    SIGTERM->exit lap) and, on the engine roles, a preStop hook POSTing
    /drain so in-flight decode streams finish before kubelet's SIGTERM.
    The router/prefill roles hold no decode state — their preStop just
    waits out endpoint-removal propagation."""
    spec = template.setdefault("spec", {})
    spec["terminationGracePeriodSeconds"] = int(grace_s) + 15
    if role == DECODE_ROLE:
        hook = {"exec": {"command": [
            "python", "-c",
            _DRAIN_PRESTOP.format(port=port, timeout=int(grace_s) + 5),
        ]}}
    else:
        hook = {"exec": {"command": ["/bin/sh", "-c", "sleep 5"]}}
    for c in spec.get("containers", []):
        c.setdefault("lifecycle", {}).setdefault("preStop", hook)


def knative_autoscaling_annotations(role: str, replicas: int) -> dict:
    """Knative revision annotations for one role: the HPA autoscaler
    class pointed at the same serving gauges as the Deployment path's
    HPAs (the KPA only understands concurrency/RPS — the decode
    engine's real saturation signal is its slot occupancy)."""
    if role == DECODE_ROLE:
        metric, target = SLOT_OCCUPANCY_METRIC, "0.7"
    else:
        metric, target = QUEUE_DEPTH_METRIC, "4"
    return {
        "autoscaling.knative.dev/class": "hpa.autoscaling.knative.dev",
        "autoscaling.knative.dev/metric": metric,
        "autoscaling.knative.dev/target": target,
        "autoscaling.knative.dev/minScale": str(max(1, int(replicas))),
    }


def maybe_fleet_objects(deployer, svc: Service,
                        ir=None) -> list[dict] | None:
    """The Deployment path's fleet fan-out: per-role Deployments (built
    by the caller's ``_create_deployment`` so pod templates, probes and
    scrape annotations stay single-owner), headless role Services for
    the backend roles, one HPA per role, and one PodDisruptionBudget per
    role. Returns None when the service is not a fleet-mode serving
    service — the caller then emits its usual single workload.

    ``ir`` (when given) carries the Helm split contract: if the fleet
    parameterizer seeded ``tpufleetminavailable`` in
    ``ir.values.global_variables``, the PDBs bake the ``.Values`` ref so
    a Helm install retunes the disruption floor without re-emitting."""
    acc = svc.accelerator
    if acc is None or not getattr(acc, "serving", False) or svc.job:
        return None
    knobs = fleet_knobs(svc.name)
    if knobs is None:
        return None
    from move2kube_tpu.apiresource.deployment import (
        DEPLOYMENT,
        SELECTOR_LABEL,
        _tpu_resources,
    )

    min_available = int(knobs.get("minavailable", 1))
    gvs = getattr(getattr(ir, "values", None), "global_variables", {}) or {}
    if "tpufleetminavailable" in gvs:
        min_available = "{{ .Values.tpufleetminavailable }}"
    port = _serving_port(svc)
    objs: list[dict] = []
    for role in fleet_roles(knobs):
        clone = role_service(svc, role, knobs)
        if role != ROUTER_ROLE:
            _tpu_resources(clone, DEPLOYMENT)
            clone.subdomain = ""  # role DNS comes from the role Service
        labels = {SELECTOR_LABEL: clone.name, ROLE_LABEL: role,
                  **svc.labels}
        if role == ROUTER_ROLE:
            # the front Service selects SELECTOR_LABEL: <name>; only
            # router pods may carry it or external traffic would skip
            # the router and land on a random engine
            labels[SELECTOR_LABEL] = svc.name
        dep = deployer._create_deployment(clone, labels)
        selector = {SELECTOR_LABEL: labels[SELECTOR_LABEL],
                    ROLE_LABEL: role}
        dep["spec"]["selector"] = {"matchLabels": dict(selector)}
        drain_pod_hooks(dep["spec"]["template"], role, port,
                        float(knobs.get("draingrace", 30.0)))
        if role == ROUTER_ROLE:
            # no telemetry-port /readyz here (that probe is serving-only
            # and keyed on the accelerator); the router's own HTTP front
            # serves /readyz on the traffic port, 503 until a backend is up
            containers = dep["spec"]["template"]["spec"].get(
                "containers", [])
            if containers:
                containers[0].setdefault("readinessProbe", {
                    "httpGet": {"path": "/readyz", "port": port},
                    "periodSeconds": 10,
                })
        objs.append(dep)
        if role != ROUTER_ROLE:
            objs.append(role_headless_service(
                svc, role, SELECTOR_LABEL, port,
                weights_port=int(knobs.get("weightsport", 0) or 0)))
        if not knobs.get("autoscale"):
            # dueling-controller guard: with the predictive controller
            # on, the reactive HPAs are suppressed — two writers on one
            # Deployment's replica count oscillate against each other
            objs.append(role_hpa(svc, role, clone.replicas))
        objs.append(role_pdb(svc, role, selector, min_available))
    if knobs.get("autoscale"):
        clone = role_service(svc, AUTOSCALER_ROLE, knobs)
        labels = {SELECTOR_LABEL: clone.name, ROLE_LABEL: AUTOSCALER_ROLE,
                  **svc.labels}
        dep = deployer._create_deployment(clone, labels)
        dep["spec"]["selector"] = {"matchLabels": {
            SELECTOR_LABEL: clone.name, ROLE_LABEL: AUTOSCALER_ROLE}}
        objs.append(dep)
    log.info("%s: fleet mode — %d objects across roles (%s)", svc.name,
             len(objs), ", ".join(
                 fleet_roles(knobs)
                 + ([AUTOSCALER_ROLE] if knobs.get("autoscale") else [])))
    return objs
