"""Shared observability wiring for emitted workloads.

One helper set used by ALL workload emitters — JobSet/Deployment
(``apiresource/deployment.py``) and Knative (``apiresource/knative.py``)
— so the scrape annotations, readiness probes, and alert-rule/dashboard
objects a pod carries cannot drift between target kinds (the
scrape-annotation logic used to live in deployment.py with knative
importing it sideways; first concrete step toward the unified pass
pipeline in ROADMAP item 5).
"""

from __future__ import annotations

from move2kube_tpu.types.ir import IR, Service
from move2kube_tpu.utils.log import get_logger

log = get_logger("apiresource.obswiring")

METRICS_PATH = "/metrics"
READYZ_PATH = "/readyz"


def metrics_port_value(svc: Service) -> str | None:
    """The telemetry port the observability optimizer baked into the pod
    env (``M2KT_METRICS_PORT``), as a string — in Helm output this is the
    ``{{ .Values.tpumetricsport }}`` ref, which is exactly what the
    scrape annotation should carry so chart overrides retune both
    together. None / "0" means telemetry is off."""
    for c in svc.containers:
        for e in c.get("env", []) or []:
            if e.get("name") == "M2KT_METRICS_PORT":
                v = str(e.get("value", "")).strip()
                return v if v and v != "0" else None
    return None


def scrape_annotations(svc: Service) -> dict:
    """prometheus.io/* pod annotations for a telemetry-enabled service
    (empty when the obs optimizer left the service uninstrumented)."""
    port = metrics_port_value(svc)
    if not port:
        return {}
    return {
        "prometheus.io/scrape": "true",
        "prometheus.io/port": port,
        "prometheus.io/path": METRICS_PATH,
    }


def readiness_probe(svc: Service) -> dict | None:
    """readinessProbe for an emitted *serving* pod: ``/readyz`` on the
    telemetry port, which reports the engine's starting/serving/draining
    state (503 until warm — obs/server.py) so a pod compiling its decode
    executables takes no traffic. None for training services (a JobSet
    worker has no traffic to gate) and when telemetry is off (the
    template's own port would 503 forever on a trainer)."""
    acc = svc.accelerator
    if acc is None or not getattr(acc, "serving", False):
        return None
    port = metrics_port_value(svc)
    if not port:
        return None
    try:
        port_val: int | str = int(port)
    except ValueError:
        port_val = port  # Helm ref: stays a template string in chart mode
    return {
        "httpGet": {"path": READYZ_PATH, "port": port_val},
        "initialDelaySeconds": 5,
        "periodSeconds": 10,
        "failureThreshold": 6,
    }


def rules_enabled(svc_name: str) -> bool:
    """The ``m2kt.services.<name>.obs.rules`` QA knob — asked with the
    same id by the workload emitters (to decide whether to attach the
    objects) and the Helm parameterizer (to decide whether to seed the
    threshold chart values), so one cached answer keeps both agreed."""
    from move2kube_tpu import qa
    from move2kube_tpu.utils import common

    name = common.make_dns_label(svc_name)
    return qa.fetch_bool(
        f"m2kt.services.{name}.obs.rules",
        f"Emit PrometheusRule alerts + a Grafana dashboard for [{name}]?",
        ["Goodput floor, step-time p95 regression, restart storm and "
         "serving queue-depth alerts, plus a dashboard ConfigMap for "
         "the Grafana sidecar; needs the prometheus-operator stack"],
        False)


def plan_report_enabled(svc_name: str) -> bool:
    """The ``m2kt.services.<name>.obs.planreport`` QA knob: should the
    emitted trainer write the preflight fit report
    (``m2kt-plan-report.{json,md}`` — obs/costmodel.py) on startup?
    Asked here so the optimizer baking ``M2KT_PLAN_REPORT`` and any
    future emitter surfacing the artifact share one cached answer."""
    from move2kube_tpu import qa
    from move2kube_tpu.utils import common

    name = common.make_dns_label(svc_name)
    return qa.fetch_bool(
        f"m2kt.services.{name}.obs.planreport",
        f"Write a preflight HBM-fit/MFU plan report for [{name}]?",
        ["m2kt-plan-report.{json,md} into M2KT_METRICS_DIR at startup: "
         "predicted HBM plan vs the compiled step's memory_analysis, fit "
         "verdict, roofline/MFU estimate, and an fsdp re-split suggestion "
         "when over budget"],
        False)


def numerics_enabled(svc_name: str) -> bool:
    """The ``m2kt.services.<name>.obs.numerics`` QA knob — asked with
    the same id by ``tpu_numerics_optimizer`` (baking ``M2KT_NUMERICS``
    into the pod env) and jax_emit (baking the template default), so
    one cached answer keeps env and emitted source agreed. Default on:
    the in-graph summaries are fused into the compiled step and the
    bench ``numerics`` phase bounds the overhead at <= 3%."""
    from move2kube_tpu import qa
    from move2kube_tpu.utils import common

    name = common.make_dns_label(svc_name)
    return qa.fetch_bool(
        f"m2kt.services.{name}.obs.numerics",
        f"Enable the tensor-health numerics plane for [{name}]?",
        ["Per-layer-group rms/max-abs/non-finite gauges, skipped-step "
         "accounting, and NaN forensics into the flight recorder "
         "(training); sampled fp-reference quant-drift audits "
         "(serving). <= 3% step overhead, gated in the bench"],
        True)


def numerics_audit_rate(svc_name: str) -> str:
    """The ``m2kt.services.<name>.obs.numerics.auditrate`` QA knob:
    fraction of cold serving admissions replayed through the fp
    reference path (``M2KT_QUANT_AUDIT_RATE``). Only meaningful for
    quantized serving; the engine ignores it otherwise."""
    from move2kube_tpu import qa
    from move2kube_tpu.utils import common

    name = common.make_dns_label(svc_name)
    raw = qa.fetch_input(
        f"m2kt.services.{name}.obs.numerics.auditrate",
        f"Quant-drift audit rate for [{name}] (0 disables)?",
        ["Fraction of cold admissions whose prefill is replayed through "
         "retained fp weights, exporting max-rel logit error as "
         "m2kt_serve_quant_drift; the fp copy roughly doubles resident "
         "params, so keep the rate small"],
        "0.01")
    try:
        return str(min(1.0, max(0.0, float(raw))))
    except (TypeError, ValueError):
        return "0.01"


def usage_enabled(svc_name: str) -> bool:
    """The ``m2kt.services.<name>.obs.usage`` QA knob — asked with the
    same id by ``tpu_usage_optimizer`` (baking ``M2KT_USAGE`` into the
    pod env) and any emitter surfacing the artifact, so one cached
    answer keeps them agreed. Default on: the ledger is a periodic
    dict merge (bench ``usage`` phase bounds it at <= 1%) and an
    off-by-default ledger bills no one."""
    from move2kube_tpu import qa
    from move2kube_tpu.utils import common

    name = common.make_dns_label(svc_name)
    return qa.fetch_bool(
        f"m2kt.services.{name}.obs.usage",
        f"Keep a per-tenant usage ledger on [{name}]?",
        ["Bounded ring of periodic usage snapshots (per-tenant tokens, "
         "latency histograms, slot occupancy, weights version) served "
         "at /usage and exit-flushed to m2kt-usage.jsonl — the input "
         "to fleet chargeback and capture->replay; <= 1% overhead, "
         "gated in the bench"],
        True)


def diag_enabled(svc_name: str) -> bool:
    """The ``m2kt.services.<name>.obs.diag`` QA knob: should the
    anomaly watchdog auto-capture diagnostic bundles (profiler trace +
    span ring + ledger window into ``M2KT_DIAG_DIR``) on SLO fast-burn,
    step-time regression, or non-finite steps? Rate-limited by
    ``M2KT_DIAG_MIN_INTERVAL_S`` and capped by
    ``M2KT_DIAG_MAX_CAPTURES`` so a flapping SLO cannot fill a
    volume."""
    from move2kube_tpu import qa
    from move2kube_tpu.utils import common

    name = common.make_dns_label(svc_name)
    return qa.fetch_bool(
        f"m2kt.services.{name}.obs.diag",
        f"Auto-capture diagnostic bundles on anomalies for [{name}]?",
        ["One-shot bundle (jax.profiler trace, /traces drain, usage-"
         "ledger window) into M2KT_DIAG_DIR when SLO fast-burn fires, "
         "step-time p95 regresses vs the rolling baseline, or a "
         "non-finite step lands; rate-limited and capped"],
        True)


def maybe_rules_objects(svc: Service, ir: IR,
                        selector_label: str) -> list[dict]:
    """PrometheusRule + Grafana dashboard ConfigMap next to the
    workload, behind the ``m2kt.services.<name>.obs.rules`` QA knob
    (default off — they are useful only on clusters running the
    prometheus-operator/Grafana stack). Same emit-anyway-with-a-warning
    contract as the PodMonitor knob when the cluster does not advertise
    the monitoring.coreos.com CRDs."""
    if svc.accelerator is None or not metrics_port_value(svc):
        return []
    from move2kube_tpu.obs import rules

    if not rules_enabled(svc.name):
        return []
    cluster = ir.target_cluster_spec
    if cluster.api_kind_version_map and not cluster.supports_kind(
            "PrometheusRule"):
        log.warning(
            "%s: PrometheusRule requested but the target cluster does not "
            "advertise monitoring.coreos.com; emitting anyway "
            "(honored once the CRDs are installed)", svc.name)
    # Helm output: the rules parameterizer already seeded the threshold
    # chart values, so the exprs carry {{ .Values.<key> }} refs instead of
    # the literals — values.yaml holds the defaults
    thresholds = None
    if all(k in ir.values.global_variables for k in rules.THRESHOLDS):
        thresholds = {k: f"{{{{ .Values.{k} }}}}" for k in rules.THRESHOLDS}
    serving = bool(getattr(svc.accelerator, "serving", False))
    return [
        rules.prometheus_rule(svc.name, selector_label, serving=serving,
                              thresholds=thresholds),
        rules.dashboard_configmap(svc.name, selector_label, serving=serving),
    ]
