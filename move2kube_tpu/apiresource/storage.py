"""Storage apiresource: ConfigMap / Secret / PVC.

Parity: ``internal/apiresource/storage.go`` — creates storage objects from
IR storages (:42) and cross-converts when the cluster lacks a kind:
ConfigMap <-> Secret, PVC -> emptyDir rewrite in pod volumes (:160-290).
"""

from __future__ import annotations

import base64

from move2kube_tpu.apiresource.base import APIResource, make_obj, obj_kind, obj_name
from move2kube_tpu.types.ir import IR, StorageKind
from move2kube_tpu.utils.log import get_logger

log = get_logger("apiresource.storage")

CONFIG_MAP = "ConfigMap"
SECRET = "Secret"
PVC = "PersistentVolumeClaim"


def _b64(data: bytes) -> str:
    return base64.b64encode(data).decode()


class StorageAPIResource(APIResource):
    def get_supported_kinds(self) -> list[str]:
        return [CONFIG_MAP, SECRET, PVC]

    def get_supported_groups(self) -> set[str]:
        return {""}

    def create_new_resources(self, ir: IR, supported_kinds: set[str]) -> list[dict]:
        objs = []
        for storage in ir.storages:
            if storage.kind == StorageKind.CONFIGMAP:
                obj = make_obj(CONFIG_MAP, "v1", storage.name)
                obj["data"] = {
                    k: v.decode() if isinstance(v, bytes) else str(v)
                    for k, v in storage.content.items()
                }
            elif storage.kind in (StorageKind.SECRET, StorageKind.PULL_SECRET):
                obj = make_obj(SECRET, "v1", storage.name)
                if storage.secret_type:
                    obj["type"] = storage.secret_type
                elif storage.kind == StorageKind.PULL_SECRET:
                    obj["type"] = "kubernetes.io/dockerconfigjson"
                obj["data"] = {
                    k: _b64(v if isinstance(v, bytes) else str(v).encode())
                    for k, v in storage.content.items()
                }
            elif storage.kind == StorageKind.PVC:
                obj = make_obj(PVC, "v1", storage.name)
                obj["spec"] = storage.pvc_spec or {
                    "accessModes": ["ReadWriteOnce"],
                    "resources": {"requests": {"storage": "100Mi"}},
                }
            else:
                continue
            if storage.annotations:
                obj["metadata"]["annotations"] = dict(storage.annotations)
            objs.append(obj)
        return objs

    def convert_to_cluster_supported_kinds(
        self, obj: dict, supported: set[str], other_objs: list[dict], ir: IR,
    ) -> list[dict]:
        kind = obj_kind(obj)
        if kind in supported or not supported:
            return [obj]
        if kind == CONFIG_MAP and SECRET in supported:
            sec = make_obj(SECRET, "v1", obj_name(obj))
            sec["data"] = {k: _b64(str(v).encode()) for k, v in obj.get("data", {}).items()}
            return [sec]
        if kind == SECRET and CONFIG_MAP in supported:
            cm = make_obj(CONFIG_MAP, "v1", obj_name(obj))
            cm["data"] = {
                k: base64.b64decode(v).decode(errors="replace")
                for k, v in obj.get("data", {}).items()
            }
            return [cm]
        if kind == PVC:
            # cluster has no PVC: drop the claim; the workloads' dangling
            # volume references are rewritten to emptyDir by the engine's
            # final fixup pass (base.convert_objects; parity storage.go:230)
            log.warning("cluster lacks PVC; %s dropped, volumes become emptyDir",
                        obj_name(obj))
            return []
        return [obj]
