"""Knative Service apiresource: run Knative workloads on plain clusters.

Parity: ``internal/apiresourceset/knativeapiresourceset.go`` — the
Knative2Kube direction. A cached ``serving.knative.dev`` Service on a
cluster that supports the group passes through (version-fixed); on a
cluster without Knative it lowers into the equivalent core objects:
Deployment + Service (Knative's scale-to-zero/revisions have no vanilla
equivalent, so the lowering keeps one revision at replicas=1 and exposes
the declared container port).
"""

from __future__ import annotations

from move2kube_tpu.apiresource.base import (
    APIResource,
    group_of,
    make_obj,
    obj_name,
)
from move2kube_tpu.types.ir import IR
from move2kube_tpu.utils.log import get_logger

log = get_logger("apiresource.knative")

KNATIVE_GROUP = "serving.knative.dev"
DEFAULT_PORT = 8080


class KnativeServiceAPIResource(APIResource):
    def get_supported_kinds(self) -> list[str]:
        return ["Service"]

    def get_supported_groups(self) -> set[str]:
        return {KNATIVE_GROUP}

    def create_new_resources(self, ir: IR, supported_kinds: set[str]) -> list[dict]:
        return []  # creation lives in KnativeTransformer (knative output mode)

    def _supported_on(self, cluster) -> set[str]:
        if not cluster.api_kind_version_map:
            return {"Service"}
        knative = any(
            group_of(v) == KNATIVE_GROUP
            for v in cluster.get_supported_versions("Service")
        )
        return {"Service"} if knative else set()

    def convert_to_cluster_supported_kinds(
        self, obj: dict, supported_kinds: set[str], other_objs: list[dict], ir: IR,
    ) -> list[dict]:
        if supported_kinds:
            return [obj]
        name = obj_name(obj)
        tmpl = (obj.get("spec", {}).get("template", {}) or {})
        pod_spec = dict(tmpl.get("spec", {}) or {})
        containers = pod_spec.get("containers") or []
        port = next(
            (int(p["containerPort"]) for c in containers
             for p in c.get("ports", []) or [] if p.get("containerPort")),
            DEFAULT_PORT)  # first declared port across ALL containers wins
        labels = {"app": name}
        deployment = make_obj("Deployment", "apps/v1", name, labels)
        deployment["spec"] = {
            "replicas": 1,
            "selector": {"matchLabels": labels},
            "template": {"metadata": {"labels": labels}, "spec": pod_spec},
        }
        service = make_obj("Service", "v1", name, labels)
        service["spec"] = {
            "selector": labels,
            "ports": [{"name": "http", "port": 80, "targetPort": port}],
        }
        log.info("lowered knative service %s to Deployment+Service", name)
        return [deployment, service]
