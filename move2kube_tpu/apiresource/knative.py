"""Knative Service apiresource: run Knative workloads on plain clusters.

Parity: ``internal/apiresourceset/knativeapiresourceset.go`` — the
Knative2Kube direction. A cached ``serving.knative.dev`` Service on a
cluster that supports the group passes through (version-fixed); on a
cluster without Knative it lowers into the equivalent core objects:
Deployment + Service (Knative's scale-to-zero/revisions have no vanilla
equivalent, so the lowering keeps one revision at replicas=1 and exposes
the declared container port).
"""

from __future__ import annotations

from move2kube_tpu.apiresource.base import (
    APIResource,
    group_of,
    make_obj,
    obj_name,
)
from move2kube_tpu.types.ir import IR
from move2kube_tpu.utils.log import get_logger

log = get_logger("apiresource.knative")

KNATIVE_GROUP = "serving.knative.dev"
DEFAULT_PORT = 8080


class KnativeServiceAPIResource(APIResource):
    """``create=False`` (k8s output mode): only converts cached knative
    objects — lowering them to Deployment+Service on clusters without the
    serving.knative.dev group. ``create=True`` (knative output mode,
    parity ``internal/apiresource/knativeservice.go:41-70``): also
    creates one knative Service per IR service and keeps knative objects
    as knative regardless of cluster support — the user chose knative
    output, so lowering would defeat the choice (the reference's
    ConvertToClusterSupportedKinds likewise always passes them through).
    """

    def __init__(self, create: bool = False) -> None:
        self.create = create

    def get_supported_kinds(self) -> list[str]:
        return ["Service"]

    def get_supported_groups(self) -> set[str]:
        return {KNATIVE_GROUP}

    def owns(self, obj: dict) -> bool:
        if self.create:
            # knative output mode claims EVERY serving.knative.dev kind
            # (Route, Configuration, Revision...) so cached ones ride the
            # keep-as-knative path below instead of the unowned pass
            # where ignore_unsupported_kinds would drop them
            return group_of(obj.get("apiVersion", "")) == KNATIVE_GROUP
        return super().owns(obj)

    def create_new_resources(self, ir: IR, supported_kinds: set[str]) -> list[dict]:
        if not self.create:
            return []  # k8s output mode: conversion of cached objects only
        objs = []
        for svc in ir.services.values():
            if not svc.containers or svc.job:
                continue  # knative serves long-running HTTP, not batch jobs
            pod_spec = svc.pod_spec()
            # knative revisions are restarted by the autoscaler; parity:
            # knativeservice.go:46 pins RestartPolicy Always
            pod_spec["restartPolicy"] = "Always"
            labels = {"app": svc.name, **svc.labels}
            obj = make_obj("Service", f"{KNATIVE_GROUP}/v1", svc.name, labels)
            if svc.annotations:
                obj["metadata"]["annotations"] = dict(svc.annotations)
            obj["spec"] = {"template": {"spec": pod_spec}}
            objs.append(obj)
        return objs

    def _supported_on(self, cluster) -> set[str]:
        if not cluster.api_kind_version_map:
            return {"Service"}
        knative = any(
            group_of(v) == KNATIVE_GROUP
            for v in cluster.get_supported_versions("Service")
        )
        return {"Service"} if knative else set()

    def convert_to_cluster_supported_kinds(
        self, obj: dict, supported_kinds: set[str], other_objs: list[dict], ir: IR,
    ) -> list[dict]:
        if self.create or supported_kinds:
            return [obj]
        name = obj_name(obj)
        tmpl = (obj.get("spec", {}).get("template", {}) or {})
        pod_spec = dict(tmpl.get("spec", {}) or {})
        containers = pod_spec.get("containers") or []
        port = next(
            (int(p["containerPort"]) for c in containers
             for p in c.get("ports", []) or [] if p.get("containerPort")),
            DEFAULT_PORT)  # first declared port across ALL containers wins
        labels = {"app": name}
        deployment = make_obj("Deployment", "apps/v1", name, labels)
        deployment["spec"] = {
            "replicas": 1,
            "selector": {"matchLabels": labels},
            "template": {"metadata": {"labels": labels}, "spec": pod_spec},
        }
        service = make_obj("Service", "v1", name, labels)
        service["spec"] = {
            "selector": labels,
            "ports": [{"name": "http", "port": 80, "targetPort": port}],
        }
        log.info("lowered knative service %s to Deployment+Service", name)
        return [deployment, service]

    def _fix_version(self, obj, cluster, ir):
        if not self.create or group_of(obj.get("apiVersion", "")) != KNATIVE_GROUP:
            return super()._fix_version(obj, cluster, ir)
        # knative output mode: convert to the cluster's advertised knative
        # version when there is one; otherwise keep the object's version
        # (the user chose knative output — never drop or lower here)
        knative_versions = [
            v for v in cluster.get_supported_versions(obj.get("kind", ""))
            if group_of(v) == KNATIVE_GROUP
        ]
        if knative_versions:
            obj["apiVersion"] = knative_versions[0]
        return [obj]
