"""Knative Service apiresource: run Knative workloads on plain clusters.

Parity: ``internal/apiresourceset/knativeapiresourceset.go`` — the
Knative2Kube direction. A cached ``serving.knative.dev`` Service on a
cluster that supports the group passes through (version-fixed); on a
cluster without Knative it lowers into the equivalent core objects:
Deployment + Service (Knative's scale-to-zero/revisions have no vanilla
equivalent, so the lowering keeps one revision at replicas=1 and exposes
the declared container port).
"""

from __future__ import annotations

import json

from move2kube_tpu.apiresource.base import (
    APIResource,
    group_of,
    make_obj,
    obj_name,
)
from move2kube_tpu.types.ir import IR
from move2kube_tpu.utils.log import get_logger

log = get_logger("apiresource.knative")

KNATIVE_GROUP = "serving.knative.dev"
DEFAULT_PORT = 8080

# revision-template pod fields the v1beta1 schema accepts; anything else
# (nodeSelector, tolerations, runtimeClassName... — the TPU placement
# fields) is stashed into _STASH_ANNOTATION on down-conversion and
# restored on the way back up instead of being silently dropped
_V1BETA1_TEMPLATE_SPEC_FIELDS = {
    "containers", "volumes", "serviceAccountName", "containerConcurrency",
    "timeoutSeconds", "imagePullSecrets", "enableServiceLinks",
}
_STASH_ANNOTATION = "serving.knative.dev/v1-fields"


def _serving_concurrency(svc) -> int:
    """In-flight request cap for the revision: the decode engine admits at
    most M2KT_SERVE_MAX_BATCH sequences, so routing more concurrent
    requests than that to one pod only queues them behind the batch —
    autoscale instead. The env value is injected by the serving optimizer
    pass (same QA knob as the emitted server); default matches the
    engine's default max_batch."""
    for c in svc.containers:
        for e in c.get("env", []) or []:
            if e.get("name") == "M2KT_SERVE_MAX_BATCH":
                try:
                    return max(1, int(e.get("value", "")))
                except (TypeError, ValueError):
                    break
    return 8


def _tpu_pod_resources(svc, pod_spec: dict) -> None:
    """google.com/tpu chip requests + GKE TPU node selectors on a knative
    revision pod spec (same sizing as the JobSet path — single owner:
    deployment._chips_per_host). nodeSelector on a revision template needs
    the cluster's `kubernetes.podspec-nodeselector` feature flag, which
    GKE TPU-serving setups enable."""
    from move2kube_tpu.apiresource.deployment import _chips_per_host

    acc = svc.accelerator
    chips = _chips_per_host(acc.tpu_topology, acc.num_hosts)
    for c in pod_spec.get("containers", []):
        res = c.setdefault("resources", {})
        res.setdefault("limits", {})["google.com/tpu"] = chips
        res.setdefault("requests", {})["google.com/tpu"] = chips
    selector = pod_spec.setdefault("nodeSelector", {})
    selector.setdefault("cloud.google.com/gke-tpu-accelerator",
                        acc.tpu_accelerator or "tpu-v5-lite-podslice")
    selector.setdefault("cloud.google.com/gke-tpu-topology",
                        acc.tpu_topology or "1x1")


class KnativeServiceAPIResource(APIResource):
    """``create=False`` (k8s output mode): only converts cached knative
    objects — lowering them to Deployment+Service on clusters without the
    serving.knative.dev group. ``create=True`` (knative output mode,
    parity ``internal/apiresource/knativeservice.go:41-70``): also
    creates one knative Service per IR service and keeps knative objects
    as knative regardless of cluster support — the user chose knative
    output, so lowering would defeat the choice (the reference's
    ConvertToClusterSupportedKinds likewise always passes them through).
    """

    def __init__(self, create: bool = False) -> None:
        self.create = create

    def get_supported_kinds(self) -> list[str]:
        return ["Service"]

    def get_supported_groups(self) -> set[str]:
        return {KNATIVE_GROUP}

    def owns(self, obj: dict) -> bool:
        if self.create:
            # knative output mode claims EVERY serving.knative.dev kind
            # (Route, Configuration, Revision...) so cached ones ride the
            # keep-as-knative path below instead of the unowned pass
            # where ignore_unsupported_kinds would drop them
            return group_of(obj.get("apiVersion", "")) == KNATIVE_GROUP
        return super().owns(obj)

    def create_new_resources(self, ir: IR, supported_kinds: set[str]) -> list[dict]:
        if not self.create:
            return []  # k8s output mode: conversion of cached objects only
        from move2kube_tpu.apiresource import fleet_wiring, obs_wiring

        objs = []
        for svc in ir.services.values():
            if not svc.containers or svc.job:
                continue  # knative serves long-running HTTP, not batch jobs
            acc = svc.accelerator
            knobs = (fleet_wiring.fleet_knobs(svc.name)
                     if acc is not None and getattr(acc, "serving", False)
                     else None)
            if knobs is not None:
                # fleet mode: one knative Service (= one revision line)
                # per role, each pinned to the HPA autoscaler class so
                # it scales on the engine gauges instead of concurrency
                for role in fleet_wiring.fleet_roles(knobs):
                    clone = fleet_wiring.role_service(svc, role, knobs)
                    if knobs.get("autoscale"):
                        # dueling-controller guard (same as the HPA
                        # path): the predictive controller owns the
                        # replica count, so pin minScale only
                        ann = {"autoscaling.knative.dev/minScale":
                               str(max(1, int(clone.replicas)))}
                    else:
                        ann = fleet_wiring.knative_autoscaling_annotations(
                            role, clone.replicas)
                    objs.append(self._knative_service(clone, ann))
            else:
                objs.append(self._knative_service(svc, None))
            # alert rules + dashboard ride along with the knative Service
            # too (same QA knob); revision pod labels carry "app", so the
            # PromQL selector keys off that instead of the JobSet label
            objs.extend(obs_wiring.maybe_rules_objects(svc, ir, "app"))
        return objs

    @staticmethod
    def _knative_service(svc, autoscale_annotations: dict | None) -> dict:
        """One knative Service from one IR service (or fleet-role
        clone). ``autoscale_annotations`` overrides the default
        concurrency-based KPA annotations — fleet roles pass the
        hpa-class annotations targeting the serving gauges."""
        from move2kube_tpu.apiresource import obs_wiring

        pod_spec = svc.pod_spec()
        # knative revisions are restarted by the autoscaler; parity:
        # knativeservice.go:46 pins RestartPolicy Always
        pod_spec["restartPolicy"] = "Always"
        # knative revision schema has no subdomain (that's the JobSet
        # pod-DNS mechanism); drop it rather than fail validation
        pod_spec.pop("subdomain", None)
        # knative validates at most ONE containerPort (the traffic
        # port); the named metrics port the obs optimizer added must
        # not reach the revision — the scrape annotation carries the
        # port number and Prometheus scrapes the pod IP directly
        for c in pod_spec.get("containers", []) or []:
            ports = c.get("ports") or []
            kept = [p for p in ports if p.get("name") != "metrics"]
            if len(kept) != len(ports):
                c["ports"] = kept
        labels = {"app": svc.name, **svc.labels}
        obj = make_obj("Service", f"{KNATIVE_GROUP}/v1", svc.name, labels)
        if svc.annotations:
            obj["metadata"]["annotations"] = dict(svc.annotations)
        template: dict = {"spec": pod_spec}
        tmpl_annotations: dict = {}
        if svc.accelerator is not None:
            # TPU serving service: chip requests + placement on the
            # revision, and concurrency matched to the decode engine's
            # max batch so the autoscaler scales on batch saturation
            _tpu_pod_resources(svc, pod_spec)
            concurrency = _serving_concurrency(svc)
            pod_spec["containerConcurrency"] = concurrency
            tmpl_annotations.update({
                "autoscaling.knative.dev/metric": "concurrency",
                "autoscaling.knative.dev/target": str(concurrency),
            })
        if autoscale_annotations:
            # a fleet-role override REPLACES the concurrency KPA
            # defaults — under the predictive controller the only
            # annotation left is the minScale floor, so the revision
            # autoscaler never duels the controller on replica count
            for k in ("autoscaling.knative.dev/metric",
                      "autoscaling.knative.dev/target"):
                tmpl_annotations.pop(k, None)
            tmpl_annotations.update(autoscale_annotations)
        # telemetry-enabled revisions advertise the scrape target —
        # Prometheus scrapes the pod IP directly, so the telemetry
        # port needs no Knative routing (queue-proxy only fronts the
        # serving port)
        tmpl_annotations.update(obs_wiring.scrape_annotations(svc))
        if (obs_wiring.readiness_probe(svc) is not None
                or autoscale_annotations is not None):
            # knative probes may only target the traffic port, not the
            # telemetry port where /readyz lives — the serve template's
            # own /healthz 503s until the engine is warm, which is the
            # same gate the Deployment path reads from /readyz
            for c in pod_spec.get("containers", []) or []:
                c.setdefault("readinessProbe",
                             {"httpGet": {"path": "/healthz"}})
                break
        if tmpl_annotations:
            template["metadata"] = {"annotations": tmpl_annotations}
        obj["spec"] = {"template": template}
        return obj

    def _supported_on(self, cluster) -> set[str]:
        if not cluster.api_kind_version_map:
            return {"Service"}
        knative = any(
            group_of(v) == KNATIVE_GROUP
            for v in cluster.get_supported_versions("Service")
        )
        return {"Service"} if knative else set()

    def convert_to_cluster_supported_kinds(
        self, obj: dict, supported_kinds: set[str], other_objs: list[dict], ir: IR,
    ) -> list[dict]:
        if self.create or supported_kinds:
            return [obj]
        name = obj_name(obj)
        tmpl = (obj.get("spec", {}).get("template", {}) or {})
        pod_spec = dict(tmpl.get("spec", {}) or {})
        # version-converted objects keep v1-only pod fields (nodeSelector,
        # TPU placement) in the stash annotation — a plain Deployment
        # supports them all, so restore before lowering
        tmpl_annotations = dict((tmpl.get("metadata") or {})
                                .get("annotations") or {})
        stash = tmpl_annotations.pop(_STASH_ANNOTATION, "")
        if stash:
            try:
                pod_spec.update(json.loads(stash))
            except (ValueError, TypeError):
                log.warning("unreadable %s annotation on %s; stashed pod "
                            "fields lost in lowering", _STASH_ANNOTATION, name)
        pod_spec.pop("containerConcurrency", None)  # revision-only field
        containers = pod_spec.get("containers") or []
        port = next(
            (int(p["containerPort"]) for c in containers
             for p in c.get("ports", []) or [] if p.get("containerPort")),
            DEFAULT_PORT)  # first declared port across ALL containers wins
        labels = {"app": name}
        deployment = make_obj("Deployment", "apps/v1", name, labels)
        obj_annotations = dict((obj.get("metadata") or {})
                               .get("annotations") or {})
        if obj_annotations:
            deployment["metadata"]["annotations"] = obj_annotations
        pod_meta: dict = {"labels": labels}
        if tmpl_annotations:
            # autoscaling.knative.dev annotations have no Deployment
            # semantics but carry the operator's intent (e.g. the decode
            # concurrency target an HPA should be configured around)
            pod_meta["annotations"] = tmpl_annotations
        deployment["spec"] = {
            "replicas": 1,
            "selector": {"matchLabels": labels},
            "template": {"metadata": pod_meta, "spec": pod_spec},
        }
        service = make_obj("Service", "v1", name, labels)
        service["spec"] = {
            "selector": labels,
            "ports": [{"name": "http", "port": 80, "targetPort": port}],
        }
        log.info("lowered knative service %s to Deployment+Service", name)
        return [deployment, service]

    def _fix_version(self, obj, cluster, ir):
        if not self.create or group_of(obj.get("apiVersion", "")) != KNATIVE_GROUP:
            return super()._fix_version(obj, cluster, ir)
        # knative output mode: convert to the cluster's advertised knative
        # version when there is one; otherwise keep the object's version
        # (the user chose knative output — never drop or lower here)
        knative_versions = [
            v for v in cluster.get_supported_versions(obj.get("kind", ""))
            if group_of(v) == KNATIVE_GROUP
        ]
        if knative_versions:
            _convert_knative_version(obj, knative_versions[0])
        return [obj]


def _convert_knative_version(obj: dict, to_version: str) -> None:
    """Swap a knative Service between ``serving.knative.dev/v1`` and
    ``/v1beta1`` without dropping information. v1beta1's revision template
    rejects the pod-placement fields v1 accepts, so down-conversion moves
    them into the ``_STASH_ANNOTATION`` JSON blob (annotations survive any
    version) and up-conversion restores them. Round-trip identity:
    v1 -> v1beta1 -> v1 reproduces the original spec."""
    from_version = obj.get("apiVersion", "")
    if obj.get("kind") != "Service" or to_version == from_version:
        obj["apiVersion"] = to_version
        return
    tmpl = (obj.get("spec") or {}).get("template")
    if not isinstance(tmpl, dict):
        obj["apiVersion"] = to_version
        return
    spec = tmpl.get("spec")
    if isinstance(spec, dict):
        if to_version.endswith("/v1beta1"):
            extra = {k: spec.pop(k) for k in sorted(spec)
                     if k not in _V1BETA1_TEMPLATE_SPEC_FIELDS}
            if extra:
                ann = (tmpl.setdefault("metadata", {})
                       .setdefault("annotations", {}))
                ann[_STASH_ANNOTATION] = json.dumps(extra, sort_keys=True)
                log.info("%s: stashed %d v1-only pod fields for v1beta1",
                         obj_name(obj), len(extra))
        else:
            ann = (tmpl.get("metadata") or {}).get("annotations") or {}
            stash = ann.pop(_STASH_ANNOTATION, "")
            if stash:
                try:
                    restored = json.loads(stash)
                except (ValueError, TypeError):
                    log.warning("%s: unreadable %s annotation; stashed pod "
                                "fields dropped", obj_name(obj),
                                _STASH_ANNOTATION)
                    restored = {}
                for key, value in restored.items():
                    spec.setdefault(key, value)
    obj["apiVersion"] = to_version
