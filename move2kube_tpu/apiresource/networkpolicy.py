"""NetworkPolicy apiresource: one policy per compose network.

Parity: ``internal/apiresource/networkpolicy.go`` — services that declare
networks get a label per network; each network becomes a NetworkPolicy
allowing ingress among members.
"""

from __future__ import annotations

from move2kube_tpu.apiresource.base import APIResource, make_obj
from move2kube_tpu.types.ir import IR

NETWORK_LABEL_PREFIX = "move2kube-tpu.io/network."


class NetworkPolicyAPIResource(APIResource):
    def get_supported_kinds(self) -> list[str]:
        return ["NetworkPolicy"]

    def get_supported_groups(self) -> set[str]:
        return {"networking.k8s.io", "extensions"}

    def create_new_resources(self, ir: IR, supported_kinds: set[str]) -> list[dict]:
        networks: set[str] = set()
        for svc in ir.services.values():
            for net in svc.networks:
                networks.add(net)
                svc.labels[NETWORK_LABEL_PREFIX + net] = "true"
        objs = []
        for net in sorted(networks):
            obj = make_obj("NetworkPolicy", "networking.k8s.io/v1", net)
            selector = {"matchLabels": {NETWORK_LABEL_PREFIX + net: "true"}}
            obj["spec"] = {
                "podSelector": selector,
                "ingress": [{"from": [{"podSelector": selector}]}],
            }
            objs.append(obj)
        return objs
