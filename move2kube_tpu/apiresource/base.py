"""API-resource engine: kind creation + cluster-supported-kind conversion.

Parity: ``internal/apiresource/apiresource.go:37-179``. Each APIResource
declares the kinds it handles, creates new objects from the IR, and
converts any object (new or cached) into a kind/version the target cluster
supports (driven by ``ClusterMetadataSpec.get_supported_versions``).
Duplicates are merged by name + kind-group (loadResource :88,
isSameResource :121).
"""

from __future__ import annotations

from move2kube_tpu.types.collection import ClusterMetadataSpec
from move2kube_tpu.types.ir import IR
from move2kube_tpu.utils.log import get_logger

log = get_logger("apiresource")


_GROUP_ALIASES = {
    # pre-1.16 "extensions" umbrella <-> its split-out groups, both
    # directions: upgrade old objects to modern groups AND downgrade
    # modern objects for clusters that only advertise extensions/*
    "extensions": ("networking.k8s.io", "apps"),
    "networking.k8s.io": ("extensions",),
    "apps": ("extensions",),
}


def obj_name(obj: dict) -> str:
    return obj.get("metadata", {}).get("name", "")


def obj_kind(obj: dict) -> str:
    return obj.get("kind", "")


def group_of(api_version: str) -> str:
    return api_version.rsplit("/", 1)[0] if "/" in api_version else ""


def _convert_ingress_backend_v1beta1_to_v1(b: dict | None) -> dict | None:
    if not b or "service" in b:
        return b
    port = b.get("servicePort")
    svc: dict = {"name": b.get("serviceName", "")}
    if port is not None:
        svc["port"] = {"name" if isinstance(port, str) else "number": port}
    return {"service": svc}


def _convert_ingress_backend_v1_to_v1beta1(b: dict | None) -> dict | None:
    if not b or "service" not in b:
        return b
    svc = b.get("service") or {}
    port = (svc.get("port") or {})
    out: dict = {"serviceName": svc.get("name", "")}
    sp = port.get("number", port.get("name"))
    if sp is not None:
        out["servicePort"] = sp
    return out


def convert_ingress_spec(obj: dict, to_group: str) -> None:
    """Rewrite an Ingress spec between networking.k8s.io/v1 and
    extensions/v1beta1 schemas in place: the backend shape and pathType
    changed across the group rename, so an apiVersion bump alone emits
    schema-invalid yaml."""
    spec = obj.get("spec") or {}
    modern = to_group == "networking.k8s.io"
    conv = (_convert_ingress_backend_v1beta1_to_v1 if modern
            else _convert_ingress_backend_v1_to_v1beta1)
    if modern and "backend" in spec:
        spec["defaultBackend"] = conv(spec.pop("backend"))
    elif not modern and "defaultBackend" in spec:
        spec["backend"] = conv(spec.pop("defaultBackend"))
    if not modern and "ingressClassName" in spec:
        cls = spec.pop("ingressClassName")
        obj.setdefault("metadata", {}).setdefault("annotations", {})[
            "kubernetes.io/ingress.class"] = cls
    for rule in spec.get("rules") or []:
        for path in (rule.get("http") or {}).get("paths") or []:
            path["backend"] = conv(path.get("backend"))
            if modern:
                path.setdefault("pathType", "ImplementationSpecific")
            else:
                path.pop("pathType", None)


def make_obj(kind: str, api_version: str, name: str, labels: dict | None = None) -> dict:
    meta: dict = {"name": name}
    if labels:
        meta["labels"] = dict(labels)
    return {"apiVersion": api_version, "kind": kind, "metadata": meta}


class APIResource:
    """One kind family (Deployment-likes, Service-likes, Storage...)."""

    def get_supported_kinds(self) -> list[str]:
        raise NotImplementedError

    def get_supported_groups(self) -> set[str] | None:
        """API groups this resource understands; None = any group. Needed
        because kind names collide across groups — a serving.knative.dev
        Service must not be claimed (and version-rewritten) by the core
        Service resource."""
        return None

    def owns(self, obj: dict) -> bool:
        if obj_kind(obj) not in self.get_supported_kinds():
            return False
        groups = self.get_supported_groups()
        return groups is None or group_of(obj.get("apiVersion", "")) in groups

    def create_new_resources(self, ir: IR, supported_kinds: set[str]) -> list[dict]:
        raise NotImplementedError

    def convert_to_cluster_supported_kinds(
        self, obj: dict, supported_kinds: set[str], other_objs: list[dict], ir: IR,
    ) -> list[dict]:
        """Convert obj into kinds the cluster supports; [] = drop."""
        return [obj]

    # -- engine (parity: GetUpdatedResources apiresource.go:72) -------------

    def get_updated_resources(self, ir: IR, cluster: ClusterMetadataSpec,
                              cached: list[dict]) -> list[dict]:
        supported = self._supported_on(cluster)
        objs: list[dict] = []
        mine = [o for o in cached if self.owns(o)]
        for obj in self.create_new_resources(ir, supported):
            self._merge_or_add(obj, objs)
        for obj in mine:
            converted = self._convert(obj, supported, objs, ir)
            for c in converted:
                self._merge_or_add(c, objs)
        # final pass: every emitted object to a cluster-supported version
        out: list[dict] = []
        for obj in objs:
            out.extend(self._fix_version(obj, cluster, ir))
        return out

    def _supported_on(self, cluster: ClusterMetadataSpec) -> set[str]:
        if not cluster.api_kind_version_map:
            return set(self.get_supported_kinds())  # no cluster info: keep all
        return {k for k in self.get_supported_kinds() if cluster.supports_kind(k)}

    def _convert(self, obj: dict, supported: set[str], others: list[dict],
                 ir: IR) -> list[dict]:
        try:
            return self.convert_to_cluster_supported_kinds(obj, supported, others, ir)
        except Exception as e:  # noqa: BLE001 - plugin tolerance
            log.warning("conversion failed for %s/%s: %s", obj_kind(obj), obj_name(obj), e)
            return [obj]

    def _merge_or_add(self, obj: dict, objs: list[dict]) -> None:
        for existing in objs:
            if self._is_same(existing, obj):
                _deep_merge(existing, obj)
                return
        objs.append(obj)

    @staticmethod
    def _is_same(a: dict, b: dict) -> bool:
        """name + kind + group equality (isSameResource apiresource.go:121)."""
        return (
            obj_name(a) == obj_name(b)
            and obj_kind(a) == obj_kind(b)
            and group_of(a.get("apiVersion", "")) == group_of(b.get("apiVersion", ""))
        )

    def _fix_version(self, obj: dict, cluster: ClusterMetadataSpec, ir: IR) -> list[dict]:
        kind = obj_kind(obj)
        versions = cluster.get_supported_versions(kind)
        if not cluster.api_kind_version_map:
            return [obj]
        if versions:
            # same-group versions only: "Service v1" supported does NOT
            # make a serving.knative.dev Service expressible as core v1
            grp = group_of(obj.get("apiVersion", ""))
            same_group = [v for v in versions if group_of(v) == grp]
            if not same_group:
                # pre-1.16 "extensions" umbrella split into real groups;
                # crossing that rename is an apiVersion bump for most
                # kinds, plus a spec rewrite for Ingress
                for alias in _GROUP_ALIASES.get(grp, ()):
                    same_group = [v for v in versions if group_of(v) == alias]
                    if same_group:
                        if kind == "Ingress":
                            convert_ingress_spec(obj, group_of(same_group[0]))
                        break
            if same_group:
                obj["apiVersion"] = same_group[0]
                return [obj]
            versions = []  # cross-group only: fall through as unsupported
        if ir.kubernetes.ignore_unsupported_kinds:
            log.warning("dropping unsupported kind %s/%s", kind, obj_name(obj))
            return []
        return [obj]  # keep as-is; user asked to keep unsupported kinds


def convert_objects(ir: IR, resources: list[APIResource]) -> list[dict]:
    """Run every APIResource over the IR + cached objects; pass through
    cached kinds nobody owns (parity: apiresourceset loop)."""
    cluster = ir.target_cluster_spec
    out: list[dict] = []
    for r in resources:
        try:
            out.extend(r.get_updated_resources(ir, cluster, ir.cached_objects))
        except Exception as e:  # noqa: BLE001
            log.warning("apiresource %s failed: %s", type(r).__name__, e)
    for obj in ir.cached_objects:
        if not any(r.owns(obj) for r in resources):
            out.append(obj)
    _fixup_dangling_pvcs(out, cluster)
    return out


def _fixup_dangling_pvcs(objs: list[dict], cluster: ClusterMetadataSpec) -> None:
    """Rewrite persistentVolumeClaim volumes to emptyDir when the cluster
    lacks PVC support (parity: convertVolumesKindsByPolicy deployment.go:417
    + storage.go:230). Runs across ALL emitted objects, after every
    APIResource — a workload and its claim are handled by different
    resources, so the rewrite cannot live inside either one.
    """
    if not cluster.api_kind_version_map or cluster.supports_kind("PersistentVolumeClaim"):
        return
    for obj in objs:
        spec = obj.get("spec", {})
        pod_specs = []
        tmpl = spec.get("template", {})
        if tmpl.get("spec"):
            pod_specs.append(tmpl["spec"])
        for rj in spec.get("replicatedJobs", []):  # JobSet nesting
            inner = rj.get("template", {}).get("spec", {}).get("template", {}).get("spec")
            if inner:
                pod_specs.append(inner)
        if obj_kind(obj) == "Pod" and spec.get("volumes") is not None:
            pod_specs.append(spec)
        for ps in pod_specs:
            for vol in ps.get("volumes", []) or []:
                if "persistentVolumeClaim" in vol:
                    vol.pop("persistentVolumeClaim", None)
                    vol["emptyDir"] = {}


def _deep_merge(dst: dict, src: dict) -> None:
    for k, v in src.items():
        if isinstance(v, dict) and isinstance(dst.get(k), dict):
            _deep_merge(dst[k], v)
        elif isinstance(v, list) and isinstance(dst.get(k), list):
            for item in v:
                if item not in dst[k]:
                    dst[k].append(item)
        else:
            dst[k] = v
