"""API-resource engine: kind creation + cluster-supported-kind conversion.

Parity: ``internal/apiresource/apiresource.go:37-179``. Each APIResource
declares the kinds it handles, creates new objects from the IR, and
converts any object (new or cached) into a kind/version the target cluster
supports (driven by ``ClusterMetadataSpec.get_supported_versions``).
Duplicates are merged by name + kind-group (loadResource :88,
isSameResource :121).
"""

from __future__ import annotations

from move2kube_tpu.types.collection import ClusterMetadataSpec
from move2kube_tpu.types.ir import IR
from move2kube_tpu.utils.log import get_logger

log = get_logger("apiresource")


_GROUP_ALIASES = {
    # pre-1.16 "extensions" umbrella <-> its split-out groups, both
    # directions: upgrade old objects to modern groups AND downgrade
    # modern objects for clusters that only advertise extensions/*
    "extensions": ("networking.k8s.io", "apps"),
    "networking.k8s.io": ("extensions",),
    "apps": ("extensions",),
}


def obj_name(obj: dict) -> str:
    return obj.get("metadata", {}).get("name", "")


def obj_kind(obj: dict) -> str:
    return obj.get("kind", "")


def group_of(api_version: str) -> str:
    return api_version.rsplit("/", 1)[0] if "/" in api_version else ""


def _convert_ingress_backend_v1beta1_to_v1(b: dict | None) -> dict | None:
    if not b or "service" in b:
        return b
    port = b.get("servicePort")
    svc: dict = {"name": b.get("serviceName", "")}
    if port is not None:
        svc["port"] = {"name" if isinstance(port, str) else "number": port}
    return {"service": svc}


def _convert_ingress_backend_v1_to_v1beta1(b: dict | None) -> dict | None:
    if not b or "service" not in b:
        return b
    svc = b.get("service") or {}
    port = (svc.get("port") or {})
    out: dict = {"serviceName": svc.get("name", "")}
    sp = port.get("number", port.get("name"))
    if sp is not None:
        out["servicePort"] = sp
    return out


def convert_ingress_spec(obj: dict, to_version: str) -> None:
    """Rewrite an Ingress spec between the v1 and v1beta1 schemas in
    place: the backend shape and pathType changed at networking.k8s.io/v1,
    so an apiVersion bump alone emits schema-invalid yaml. Keyed on the
    target VERSION, not the group — ``networking.k8s.io/v1beta1`` (the
    EKS/AKS/GKE vintage in the reference tables, constants.go) uses the
    same legacy backend shape as ``extensions/v1beta1``."""
    spec = obj.get("spec") or {}
    modern = to_version == "networking.k8s.io/v1"
    conv = (_convert_ingress_backend_v1beta1_to_v1 if modern
            else _convert_ingress_backend_v1_to_v1beta1)
    if modern and "backend" in spec:
        spec["defaultBackend"] = conv(spec.pop("backend"))
    elif not modern and "defaultBackend" in spec:
        spec["backend"] = conv(spec.pop("defaultBackend"))
    if not modern and "ingressClassName" in spec:
        cls = spec.pop("ingressClassName")
        obj.setdefault("metadata", {}).setdefault("annotations", {})[
            "kubernetes.io/ingress.class"] = cls
    for rule in spec.get("rules") or []:
        for path in (rule.get("http") or {}).get("paths") or []:
            path["backend"] = conv(path.get("backend"))
            if modern:
                path.setdefault("pathType", "ImplementationSpecific")
            else:
                path.pop("pathType", None)


# metric-source key per HPA metric type (v2 field names; v2beta1 uses the
# same keys with flat target fields inside)
_HPA_SOURCE_KEYS = {"Resource": "resource", "ContainerResource":
                    "containerResource", "Pods": "pods", "Object": "object",
                    "External": "external"}


def _hpa_metric_to_v2beta1(m: dict) -> dict:
    """One metric entry: v2/v2beta2 shape -> v2beta1 flat fields, for
    every metric type (Resource/ContainerResource keep ``name``;
    Pods/Object/External carry ``metricName``/``selector`` flat)."""
    key = _HPA_SOURCE_KEYS.get(m.get("type", ""))
    if not key or not isinstance(m.get(key), dict):
        return m
    src = dict(m[key])
    target = src.pop("target", None)
    metric = src.pop("metric", None)
    # Object metrics: v2 names the scaled-object reference
    # ``describedObject``; v2beta1 calls that same field ``target`` (the
    # name v2 reuses for the metric target popped above)
    if "describedObject" in src:
        src["target"] = src.pop("describedObject")
    if isinstance(metric, dict):
        src["metricName"] = metric.get("name")
        if metric.get("selector") is not None:
            src["selector" if key != "external" else "metricSelector"] = \
                metric["selector"]
    if isinstance(target, dict):
        for vkey, legacy in (("averageUtilization", "targetAverageUtilization"),
                             ("averageValue", "targetAverageValue"),
                             ("value", "targetValue")):
            if vkey in target:
                src[legacy] = target[vkey]
    out = dict(m)
    out[key] = src
    return out


def _hpa_metric_from_v2beta1(m: dict) -> dict:
    """One metric entry: v2beta1 flat fields -> v2/v2beta2 shape, for
    every metric type."""
    key = _HPA_SOURCE_KEYS.get(m.get("type", ""))
    if not key or not isinstance(m.get(key), dict):
        return m
    src = dict(m[key])
    if "metric" in src:
        # already modern-shaped (Pods/Object/External carry a nested
        # ``metric``). NOTE: ``"target" in src`` is NOT a modern marker —
        # a v2beta1 Object metric uses ``target`` for the scaled-object
        # reference, which v2 renames ``describedObject``
        return m
    if m.get("type") == "Object" and isinstance(src.get("target"), dict) \
            and "name" in src["target"] and "type" not in src["target"]:
        src["describedObject"] = src.pop("target")
    target: dict = {}
    if "targetAverageUtilization" in src:
        target = {"type": "Utilization",
                  "averageUtilization": src.pop("targetAverageUtilization")}
    elif "targetAverageValue" in src:
        target = {"type": "AverageValue",
                  "averageValue": src.pop("targetAverageValue")}
    elif "targetValue" in src:
        target = {"type": "Value", "value": src.pop("targetValue")}
    metric_name = src.pop("metricName", None)
    selector = src.pop("metricSelector" if key == "external" else "selector",
                       None)
    if metric_name is not None:
        metric: dict = {"name": metric_name}
        if selector is not None:
            metric["selector"] = selector
        src["metric"] = metric
    if target:
        src["target"] = target
    out = dict(m)
    out[key] = src
    return out


def _hpa_cpu_utilization(m: dict) -> int | None:
    """CPU utilization percentage of a metric entry (any v2 shape)."""
    res = m.get("resource") or {}
    if m.get("type") != "Resource" or res.get("name") != "cpu":
        return None
    target = res.get("target") or {}
    return target.get("averageUtilization",
                      res.get("targetAverageUtilization"))


def _convert_hpa_spec(obj: dict, to_version: str) -> None:
    """HorizontalPodAutoscaler version rewrites (the reference vintage
    tables prefer ``autoscaling/v1`` everywhere, constants.go):

    - to v1: the metrics list collapses to its CPU-utilization entry
      (``targetCPUUtilizationPercentage``); anything else cannot be
      expressed and is dropped with a warning.
    - to v2beta1: per-metric ``target`` objects flatten to the legacy
      ``targetAverageUtilization``/``targetAverageValue`` fields.
    - to v2/v2beta2: flat v2beta1 fields re-expand into ``target``
      objects, and a v1 ``targetCPUUtilizationPercentage`` becomes a
      CPU-utilization metric."""
    spec = obj.get("spec") or {}
    if to_version == "autoscaling/v1":
        metrics = spec.pop("metrics", None) or []
        spec.pop("behavior", None)
        for m in metrics:
            util = _hpa_cpu_utilization(m)
            if util is not None:
                spec["targetCPUUtilizationPercentage"] = util
            else:
                log.warning("dropping HPA metric %s on %s (only CPU "
                            "utilization is expressible in autoscaling/v1)",
                            m.get("type"), obj_name(obj))
    elif to_version.startswith("autoscaling/v2"):
        if to_version == "autoscaling/v2beta1":
            spec.pop("behavior", None)  # behavior exists from v2beta2 on
            conv = _hpa_metric_to_v2beta1
        else:
            conv = _hpa_metric_from_v2beta1
        if spec.get("metrics"):
            spec["metrics"] = [conv(m) for m in spec["metrics"]]
        util = spec.pop("targetCPUUtilizationPercentage", None)
        if util is not None and not spec.get("metrics"):
            res = ({"name": "cpu", "targetAverageUtilization": util}
                   if to_version == "autoscaling/v2beta1" else
                   {"name": "cpu", "target": {"type": "Utilization",
                                              "averageUtilization": util}})
            spec["metrics"] = [{"type": "Resource", "resource": res}]


def convert_spec_between_versions(obj: dict, to_version: str) -> None:
    """Schema rewrites that must accompany an apiVersion change (parity:
    the reference's per-kind convert functions driven by the cluster's
    preferred-version tables, k8stransformer.go:94-156). Kinds not listed
    here (Deployment apps/v1beta*/extensions, CronJob batch/v1beta1,
    DaemonSet/StatefulSet vintages) are schema-compatible across their
    listed versions for everything this tool emits, so the apiVersion
    bump alone is valid."""
    if obj.get("apiVersion") == to_version:
        return
    kind = obj_kind(obj)
    if kind == "Ingress":
        convert_ingress_spec(obj, to_version)
    elif kind == "HorizontalPodAutoscaler":
        _convert_hpa_spec(obj, to_version)


def make_obj(kind: str, api_version: str, name: str, labels: dict | None = None) -> dict:
    meta: dict = {"name": name}
    if labels:
        meta["labels"] = dict(labels)
    return {"apiVersion": api_version, "kind": kind, "metadata": meta}


class APIResource:
    """One kind family (Deployment-likes, Service-likes, Storage...)."""

    def get_supported_kinds(self) -> list[str]:
        raise NotImplementedError

    def get_supported_groups(self) -> set[str] | None:
        """API groups this resource understands; None = any group. Needed
        because kind names collide across groups — a serving.knative.dev
        Service must not be claimed (and version-rewritten) by the core
        Service resource."""
        return None

    def owns(self, obj: dict) -> bool:
        if obj_kind(obj) not in self.get_supported_kinds():
            return False
        groups = self.get_supported_groups()
        return groups is None or group_of(obj.get("apiVersion", "")) in groups

    def create_new_resources(self, ir: IR, supported_kinds: set[str]) -> list[dict]:
        raise NotImplementedError

    def convert_to_cluster_supported_kinds(
        self, obj: dict, supported_kinds: set[str], other_objs: list[dict], ir: IR,
    ) -> list[dict]:
        """Convert obj into kinds the cluster supports; [] = drop."""
        return [obj]

    # -- engine (parity: GetUpdatedResources apiresource.go:72) -------------

    def get_updated_resources(self, ir: IR, cluster: ClusterMetadataSpec,
                              cached: list[dict]) -> list[dict]:
        supported = self._supported_on(cluster)
        objs: list[dict] = []
        mine = [o for o in cached if self.owns(o)]
        for obj in self.create_new_resources(ir, supported):
            self._merge_or_add(obj, objs)
        for obj in mine:
            converted = self._convert(obj, supported, objs, ir)
            for c in converted:
                self._merge_or_add(c, objs)
        # final pass: every emitted object to a cluster-supported version
        out: list[dict] = []
        for obj in objs:
            out.extend(self._fix_version(obj, cluster, ir))
        return out

    def _supported_on(self, cluster: ClusterMetadataSpec) -> set[str]:
        if not cluster.api_kind_version_map:
            return set(self.get_supported_kinds())  # no cluster info: keep all
        return {k for k in self.get_supported_kinds() if cluster.supports_kind(k)}

    def _convert(self, obj: dict, supported: set[str], others: list[dict],
                 ir: IR) -> list[dict]:
        try:
            return self.convert_to_cluster_supported_kinds(obj, supported, others, ir)
        except Exception as e:  # noqa: BLE001 - plugin tolerance
            log.warning("conversion failed for %s/%s: %s", obj_kind(obj), obj_name(obj), e)
            return [obj]

    def _merge_or_add(self, obj: dict, objs: list[dict]) -> None:
        for existing in objs:
            if self._is_same(existing, obj):
                _deep_merge(existing, obj)
                return
        objs.append(obj)

    @staticmethod
    def _is_same(a: dict, b: dict) -> bool:
        """name + kind + group equality (isSameResource apiresource.go:121)."""
        return (
            obj_name(a) == obj_name(b)
            and obj_kind(a) == obj_kind(b)
            and group_of(a.get("apiVersion", "")) == group_of(b.get("apiVersion", ""))
        )

    def _fix_version(self, obj: dict, cluster: ClusterMetadataSpec, ir: IR) -> list[dict]:
        return fix_object_version(
            obj, cluster, ir.kubernetes.ignore_unsupported_kinds)


def fix_object_version(obj: dict, cluster: ClusterMetadataSpec,
                       ignore_unsupported: bool) -> list[dict]:
    """Convert ``obj`` to the cluster's preferred supported version
    (parity: the reference converts EVERY written object this way —
    ``k8stransformer.go:108-142`` — so this also runs on cached kinds no
    APIResource owns, e.g. CronJob/HPA)."""
    kind = obj_kind(obj)
    versions = cluster.get_supported_versions(kind)
    if not cluster.api_kind_version_map:
        return [obj]
    if versions:
        # same-group versions only: "Service v1" supported does NOT
        # make a serving.knative.dev Service expressible as core v1
        grp = group_of(obj.get("apiVersion", ""))
        same_group = [v for v in versions if group_of(v) == grp]
        if not same_group:
            # pre-1.16 "extensions" umbrella split into real groups;
            # crossing that rename is an apiVersion bump for most
            # kinds, plus a spec rewrite for Ingress
            for alias in _GROUP_ALIASES.get(grp, ()):
                same_group = [v for v in versions if group_of(v) == alias]
                if same_group:
                    break
        if same_group:
            convert_spec_between_versions(obj, same_group[0])
            obj["apiVersion"] = same_group[0]
            return [obj]
        versions = []  # cross-group only: fall through as unsupported
    if ignore_unsupported:
        log.warning("dropping unsupported kind %s/%s", kind, obj_name(obj))
        return []
    return [obj]  # keep as-is; user asked to keep unsupported kinds


def convert_objects(ir: IR, resources: list[APIResource]) -> list[dict]:
    """Run every APIResource over the IR + cached objects; pass through
    cached kinds nobody owns (parity: apiresourceset loop)."""
    cluster = ir.target_cluster_spec
    out: list[dict] = []
    for r in resources:
        try:
            out.extend(r.get_updated_resources(ir, cluster, ir.cached_objects))
        except Exception as e:  # noqa: BLE001
            log.warning("apiresource %s failed: %s", type(r).__name__, e)
    for obj in ir.cached_objects:
        if not any(r.owns(obj) for r in resources):
            # unowned kinds still get the write-time version fix — the
            # reference converts every written object (k8stransformer.go:108)
            out.extend(fix_object_version(
                obj, cluster, ir.kubernetes.ignore_unsupported_kinds))
    _fixup_dangling_pvcs(out, cluster)
    return out


def _fixup_dangling_pvcs(objs: list[dict], cluster: ClusterMetadataSpec) -> None:
    """Rewrite persistentVolumeClaim volumes to emptyDir when the cluster
    lacks PVC support (parity: convertVolumesKindsByPolicy deployment.go:417
    + storage.go:230). Runs across ALL emitted objects, after every
    APIResource — a workload and its claim are handled by different
    resources, so the rewrite cannot live inside either one.
    """
    if not cluster.api_kind_version_map or cluster.supports_kind("PersistentVolumeClaim"):
        return
    for obj in objs:
        spec = obj.get("spec", {})
        pod_specs = []
        tmpl = spec.get("template", {})
        if tmpl.get("spec"):
            pod_specs.append(tmpl["spec"])
        for rj in spec.get("replicatedJobs", []):  # JobSet nesting
            inner = rj.get("template", {}).get("spec", {}).get("template", {}).get("spec")
            if inner:
                pod_specs.append(inner)
        if obj_kind(obj) == "Pod" and spec.get("volumes") is not None:
            pod_specs.append(spec)
        for ps in pod_specs:
            for vol in ps.get("volumes", []) or []:
                if "persistentVolumeClaim" in vol:
                    vol.pop("persistentVolumeClaim", None)
                    vol["emptyDir"] = {}


def _deep_merge(dst: dict, src: dict) -> None:
    for k, v in src.items():
        if isinstance(v, dict) and isinstance(dst.get(k), dict):
            _deep_merge(dst[k], v)
        elif isinstance(v, list) and isinstance(dst.get(k), list):
            for item in v:
                if item not in dst[k]:
                    dst[k].append(item)
        else:
            dst[k] = v
