"""Service / Ingress / Route apiresource.

Parity: ``internal/apiresource/service.go`` — one k8s Service per exposed
IR service (headless when nothing is exposed), a single fan-out Ingress
built from every service carrying the expose annotation (createIngress
:446) with optional TLS, and Route<->Ingress<->Service conversions for
OpenShift clusters (:147-389).
"""

from __future__ import annotations

from move2kube_tpu.apiresource.base import APIResource, make_obj, obj_kind, obj_name
from move2kube_tpu.apiresource.deployment import SELECTOR_LABEL
from move2kube_tpu.types.ir import IR, Service
from move2kube_tpu.utils import common
from move2kube_tpu.utils.log import get_logger

log = get_logger("apiresource.service")

SERVICE = "Service"
INGRESS = "Ingress"
ROUTE = "Route"

EXPOSE_ANNOTATION = common.EXPOSE_SERVICE_ANNOTATION


class ServiceAPIResource(APIResource):
    def get_supported_kinds(self) -> list[str]:
        return [SERVICE, INGRESS, ROUTE]

    def get_supported_groups(self) -> set[str]:
        # NOT serving.knative.dev: a Knative "Service" is a different kind
        return {"", "networking.k8s.io", "extensions", "route.openshift.io"}

    def create_new_resources(self, ir: IR, supported_kinds: set[str]) -> list[dict]:
        objs: list[dict] = []
        exposed: list[Service] = []
        for svc in ir.services.values():
            if svc.job:  # training workloads get headless services for ICI discovery
                if svc.accelerator is not None:
                    objs.append(self._create_headless(svc))
                continue
            if svc.port_forwardings:
                objs.append(self._create_service(svc))
                if svc.has_valid_annotation(EXPOSE_ANNOTATION):
                    exposed.append(svc)
            elif not svc.only_ingress:
                objs.append(self._create_headless(svc))
        if exposed:
            if INGRESS in supported_kinds or not supported_kinds:
                objs.append(self._create_ingress(ir, exposed))
            elif ROUTE in supported_kinds:
                objs.extend(self._create_route(svc) for svc in exposed)
        return objs

    def _create_service(self, svc: Service) -> dict:
        obj = make_obj(SERVICE, "v1", svc.name, {SELECTOR_LABEL: svc.name})
        ports = []
        for pf in svc.port_forwardings:
            port: dict = {
                "name": pf.name or f"port-{pf.service_port}",
                "port": pf.service_port,
                "targetPort": pf.container_port,
            }
            ports.append(port)
        obj["spec"] = {
            "type": "ClusterIP",
            "selector": {SELECTOR_LABEL: svc.name},
            "ports": ports,
        }
        if svc.annotations:
            obj["metadata"]["annotations"] = dict(svc.annotations)
        return obj

    def _create_headless(self, svc: Service) -> dict:
        obj = make_obj(SERVICE, "v1", svc.name, {SELECTOR_LABEL: svc.name})
        obj["spec"] = {
            "clusterIP": "None",
            "selector": {SELECTOR_LABEL: svc.name},
        }
        return obj

    def _create_ingress(self, ir: IR, exposed: list[Service]) -> dict:
        """Single fan-out ingress (service.go:446)."""
        name = common.make_dns_label(ir.name)
        obj = make_obj(INGRESS, "networking.k8s.io/v1", name)
        host = ir.values.ingress_host or ""
        paths = []
        for svc in exposed:
            port = (svc.port_forwardings[0].service_port
                    if svc.port_forwardings else common.DEFAULT_SERVICE_PORT)
            paths.append({
                "path": svc.service_rel_path or "/" + svc.name,
                "pathType": "Prefix",
                "backend": {
                    "service": {
                        "name": svc.backend_service_name or svc.name,
                        "port": {"number": port},
                    }
                },
            })
        rule: dict = {"http": {"paths": paths}}
        if host:
            rule["host"] = host
        obj["spec"] = {"rules": [rule]}
        if ir.ingress_tls_secret_name:
            tls: dict = {"secretName": ir.ingress_tls_secret_name}
            if host:
                tls["hosts"] = [host]
            obj["spec"]["tls"] = [tls]
        return obj

    def _create_route(self, svc: Service) -> dict:
        port = (svc.port_forwardings[0].service_port
                if svc.port_forwardings else common.DEFAULT_SERVICE_PORT)
        obj = make_obj(ROUTE, "route.openshift.io/v1", svc.name,
                       {SELECTOR_LABEL: svc.name})
        obj["spec"] = {
            "to": {"kind": "Service", "name": svc.name},
            "port": {"targetPort": port},
        }
        return obj

    # -- conversions (service.go:147-389) -----------------------------------

    def convert_to_cluster_supported_kinds(
        self, obj: dict, supported: set[str], other_objs: list[dict], ir: IR,
    ) -> list[dict]:
        kind = obj_kind(obj)
        if kind in supported or not supported:
            return [obj]
        if kind == INGRESS and ROUTE in supported:
            return self._ingress_to_routes(obj)
        if kind == ROUTE and INGRESS in supported:
            return [self._route_to_ingress(obj)]
        if kind in (INGRESS, ROUTE) and SERVICE in supported:
            # expose via NodePort instead (service.go:360): mutate the
            # already-accumulated Service objects in place and drop the obj
            for other in other_objs:
                if obj_kind(other) == SERVICE:
                    other.setdefault("spec", {})["type"] = "NodePort"
            return []
        return [obj]

    def _ingress_to_routes(self, obj: dict) -> list[dict]:
        routes = []
        for rule in obj.get("spec", {}).get("rules", []):
            host = rule.get("host", "")
            for path in rule.get("http", {}).get("paths", []):
                backend = path.get("backend", {}).get("service", {})
                name = backend.get("name", obj_name(obj))
                route = make_obj(ROUTE, "route.openshift.io/v1",
                                 common.make_dns_label(f"{obj_name(obj)}-{name}"))
                route["spec"] = {
                    "to": {"kind": "Service", "name": name},
                    "port": {"targetPort": backend.get("port", {}).get("number", 80)},
                }
                if host:
                    route["spec"]["host"] = host
                if path.get("path"):
                    route["spec"]["path"] = path["path"]
                routes.append(route)
        return routes

    def _route_to_ingress(self, obj: dict) -> dict:
        spec = obj.get("spec", {})
        ing = make_obj(INGRESS, "networking.k8s.io/v1", obj_name(obj))
        port = spec.get("port", {}).get("targetPort", 80)
        rule: dict = {
            "http": {
                "paths": [{
                    "path": spec.get("path", "/"),
                    "pathType": "Prefix",
                    "backend": {
                        "service": {
                            "name": spec.get("to", {}).get("name", ""),
                            "port": {"number": port if isinstance(port, int) else 80},
                        }
                    },
                }]
            }
        }
        if spec.get("host"):
            rule["host"] = spec["host"]
        ing["spec"] = {"rules": [rule]}
        return ing
