"""m2kt CLI: plan / translate / collect / version.

Parity: ``cmd/move2kube/`` (cobra commands move2kube.go:37-47,
translate.go:93-205, plan.go, collect.go, version.go). Every flag can also
come from the environment as ``M2KT_<FLAG>`` (viper.AutomaticEnv parity).
"""

from __future__ import annotations

import argparse
import os
import sys

import move2kube_tpu
from move2kube_tpu import qa
from move2kube_tpu.engine.collector import collect
from move2kube_tpu.engine.planner import create_plan, curate_plan
from move2kube_tpu.engine.translator import translate
from move2kube_tpu.types import plan as plantypes
from move2kube_tpu.utils import common, trace
from move2kube_tpu.utils.log import configure, get_logger

log = get_logger("cli")


def _env_default(flag: str, default):
    return os.environ.get("M2KT_" + flag.upper().replace("-", "_"), default)


def _env_bool(flag: str, default: bool = False) -> bool:
    """Boolean env parsing with viper semantics: 'false'/'0'/'' are False."""
    raw = os.environ.get("M2KT_" + flag.upper().replace("-", "_"))
    if raw is None:
        return default
    return raw.strip().lower() in ("1", "true", "yes", "on")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="m2kt",
        description="move2kube-tpu: re-platform applications onto Kubernetes, "
                    "translating GPU training workloads to TPU.",
    )
    p.add_argument("--verbose", "-v", action="store_true",
                   default=_env_bool("verbose"))
    sub = p.add_subparsers(dest="command")

    pp = sub.add_parser("plan", help="analyse sources and write m2kt.plan")
    pp.add_argument("--source", "-s", default=_env_default("source", "."),
                    help="source directory")
    pp.add_argument("--name", "-n", default=_env_default("name", ""),
                    help="project name")
    pp.add_argument("--plan", "-p", default=_env_default("plan", common.DEFAULT_PLAN_FILE),
                    help="plan file to write")

    tp = sub.add_parser("translate", help="translate sources into deployment artifacts")
    tp.add_argument("--source", "-s", default=_env_default("source", ""),
                    help="source directory")
    tp.add_argument("--plan", "-p", default=_env_default("plan", ""),
                    help="existing plan file")
    tp.add_argument("--outpath", "-o", default=_env_default("outpath", "."),
                    help="output directory")
    tp.add_argument("--name", "-n", default=_env_default("name", ""))
    tp.add_argument("--curate", "-c", action="store_true", default=False,
                    help="interactively curate the plan")
    tp.add_argument("--qa-skip", action="store_true",
                    default=_env_bool("qa_skip"),
                    help="accept defaults for all questions")
    tp.add_argument("--qa-port", type=int, default=int(_env_default("qa_port", 0) or 0),
                    help="serve questions over REST on this port")
    tp.add_argument("--qa-cache", default=_env_default("qa_cache", ""),
                    help="replay answers from a previous run's cache file")
    tp.add_argument("--qa-disable-cli", action="store_true",
                    default=_env_bool("qa_disable_cli"),
                    help="never prompt on the terminal; answer over REST "
                         "(--qa-port, or an OS-assigned port) instead")
    tp.add_argument("--ignore-env", action="store_true", default=False,
                    help="derive nothing from the local environment")
    tp.add_argument("--profile", action="store_true",
                    default=_env_bool("profile"),
                    help="write per-stage timings/counters to "
                         "<out>/m2kt-metrics.json")

    cp = sub.add_parser("collect", help="collect metadata from cluster/docker")
    cp.add_argument("--source", "-s", default=_env_default("source", "."))
    cp.add_argument("--outpath", "-o", default=_env_default("outpath", "."))
    cp.add_argument("--annotations", "-a", default="",
                    help="comma-separated collector annotations filter")

    sub.add_parser("version", help="print version")
    return p


def plan_handler(args) -> int:
    source = os.path.abspath(args.source)
    if not os.path.isdir(source):
        log.error("source directory %s does not exist", source)
        return 1
    plan = create_plan(source, args.name)
    plantypes.write_plan(args.plan, plan)
    n = sum(len(v) for v in plan.services.values())
    print(f"plan written to {args.plan} ({len(plan.services)} services, {n} options)")
    return 0


def translate_handler(args) -> int:
    if args.ignore_env:
        common.IGNORE_ENVIRONMENT = True
    # the span recorder is module-global: without a per-run reset a second
    # in-process translate() (tests, REST drivers) reports the first run's
    # spans and counters on top of its own
    trace.reset()
    qa.reset_engines()
    interactive = (
        args.curate or bool(args.qa_port) or args.qa_disable_cli
    ) and not args.qa_skip
    qa.start_engine(interactive=interactive, qa_skip=args.qa_skip,
                    qa_port=args.qa_port, qa_disable_cli=args.qa_disable_cli)
    if args.qa_cache:
        qa.add_cache_engine(args.qa_cache)

    out_dir = os.path.abspath(args.outpath)
    if args.plan and os.path.isfile(args.plan):
        try:
            plan = plantypes.read_plan(args.plan)
        except ValueError as e:
            log.error("cannot read plan: %s", e)
            return 1
        if args.source:
            plan.set_root_dir(os.path.abspath(args.source))
        if args.name:
            plan.name = common.make_dns_label(args.name)
    else:
        if not args.source:
            log.error("either --plan or --source is required")
            return 1
        source = os.path.abspath(args.source)
        if not os.path.isdir(source):
            log.error("source directory %s does not exist", source)
            return 1
        plan = create_plan(source, args.name)
    for cache in plan.qa_caches:
        qa.add_cache_engine(cache)
    qa.set_write_cache(os.path.join(out_dir, common.QA_CACHE_FILE))
    plan = curate_plan(plan)
    translate(plan, out_dir)
    if args.profile:
        path = trace.write_metrics(out_dir)
        print(f"run metrics written to {path}")
    print(f"artifacts written to {out_dir}")
    return 0


def collect_handler(args) -> int:
    annotations = [a.strip() for a in args.annotations.split(",") if a.strip()]
    collect(os.path.abspath(args.source), os.path.abspath(args.outpath), annotations)
    print(f"collect output written to {os.path.join(args.outpath, common.COLLECT_OUTPUT_DIR)}")
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    configure(verbose=bool(args.verbose))
    if args.command == "plan":
        return plan_handler(args)
    if args.command == "translate":
        return translate_handler(args)
    if args.command == "collect":
        return collect_handler(args)
    if args.command == "version":
        print(f"move2kube-tpu {move2kube_tpu.__version__}")
        return 0
    parser.print_help()
    return 0


if __name__ == "__main__":
    sys.exit(main())
