"""Optimizer passes over the IR.

Parity: ``internal/optimizer/`` — sequential, failure-tolerant registry
``[normalizeCharacter, ingress, replica, imagePullPolicy, portMerge]``
(optimizer.go:31-52). The ingress and port-merge passes are interactive
via the QA engine.
"""

from __future__ import annotations

import re

from move2kube_tpu import qa
from move2kube_tpu.types.ir import IR
from move2kube_tpu.utils import common
from move2kube_tpu.utils.log import get_logger

log = get_logger("optimize")


def normalize_character_optimizer(ir: IR) -> IR:
    """Strip quotes/control chars from env values (normalizecharactersoptimizer.go:30)."""
    for svc in ir.services.values():
        for container in svc.containers:
            for env in container.get("env", []) or []:
                val = str(env.get("value", ""))
                val = val.strip().strip("'\"")
                env["value"] = re.sub(r"[\x00-\x08\x0b-\x1f]", "", val)
    return ir


def ingress_optimizer(ir: IR) -> IR:
    """QA: which services to expose + per-service URL path
    (ingressoptimizer.go:35-107)."""
    candidates = [
        name for name, svc in ir.services.items()
        if svc.port_forwardings and not svc.job
    ]
    if not candidates:
        return ir
    chosen = qa.fetch_multi_select(
        "m2kt.services.expose",
        "Select the services to expose externally",
        ["The chosen services will be reachable through an ingress"],
        candidates,
        candidates,
    )
    for name in chosen:
        svc = ir.services[name]
        rel_path = qa.fetch_input(
            f"m2kt.services.{name}.urlpath",
            f"URL path for service [{name}]",
            [],
            svc.service_rel_path or "/" + name,
        )
        if rel_path and not rel_path.startswith("/"):
            rel_path = "/" + rel_path
        svc.service_rel_path = rel_path
        svc.annotations[common.EXPOSE_SERVICE_ANNOTATION] = "true"
    return ir


def replica_optimizer(ir: IR) -> IR:
    """Minimum 2 replicas for serving workloads (replicaoptimizer.go:24-40)."""
    for svc in ir.services.values():
        if not svc.job and not svc.daemon and svc.replicas < 2:
            svc.replicas = 2
    return ir


def image_pull_policy_optimizer(ir: IR) -> IR:
    """imagePullPolicy: Always on every container (imagepullpolicyoptimizer.go:28)."""
    for svc in ir.services.values():
        for container in svc.containers:
            container["imagePullPolicy"] = "Always"
    return ir


def port_merge_optimizer(ir: IR) -> IR:
    """Merge container/exposed-port info; ask when ambiguous
    (portmergeoptimizer.go:36-140)."""
    for svc in ir.services.values():
        if svc.job:
            continue
        container_ports: list[int] = []
        for c in svc.containers:
            for p in c.get("ports", []) or []:
                if p.get("containerPort"):
                    container_ports.append(int(p["containerPort"]))
        image_ports: list[int] = []
        for img_container in ir.containers:
            if any(c.get("image") in img_container.image_names for c in svc.containers):
                image_ports.extend(img_container.exposed_ports)
        known = [pf.container_port for pf in svc.port_forwardings]
        all_ports = [p for p in dict.fromkeys(container_ports + image_ports) if p]
        missing = [p for p in all_ports if p not in known]
        if not svc.port_forwardings and not all_ports:
            port_str = qa.fetch_select(
                f"m2kt.services.{svc.name}.port",
                f"Select port to expose for service [{svc.name}]",
                [], str(common.DEFAULT_SERVICE_PORT),
                [str(common.DEFAULT_SERVICE_PORT)],
            )
            svc.add_port_forwarding(int(port_str), int(port_str))
            if svc.containers:
                svc.containers[0].setdefault("ports", []).append(
                    {"containerPort": int(port_str)}
                )
        else:
            for p in missing:
                svc.add_port_forwarding(p, p)
        # ensure container port lists include everything forwarded
        for pf in svc.port_forwardings:
            for c in svc.containers:
                ports = c.setdefault("ports", [])
                if all(x.get("containerPort") != pf.container_port for x in ports):
                    ports.append({"containerPort": pf.container_port})
    return ir


def tpu_training_optimizer(ir: IR) -> IR:
    """Bake the training knobs into accelerated services' pod env.

    Asks the SAME QA problems as the jax-xla emitter
    (``m2kt.services.<name>.tpu.precision`` / ``.tpu.gradaccum`` /
    ``.train.fusedce``) — one logical knob per service, answered once,
    cache-consistent: the emitted trainer's baked-in default and the
    JobSet's explicit ``M2KT_PRECISION`` / ``M2KT_GRAD_ACCUM`` /
    ``M2KT_FUSED_CE`` env always agree. The env
    entries win inside the trainer (os.environ.get over the template
    default), so editing the YAML retunes a deployed run without a
    rebuild. Existing entries of the same name are never overwritten."""
    from move2kube_tpu.models.precision import PRECISION_OPTIONS

    for svc in ir.services.values():
        acc = getattr(svc, "accelerator", None)
        if acc is None or getattr(acc, "serving", False):
            continue  # serving services get the serving knobs instead
        name = common.make_dns_label(svc.name)
        family = getattr(acc, "model_family", "") or "generic"
        default_precision = ("bf16" if family in ("llama", "gpt", "gpt2",
                                                  "bert") else "fp32")
        precision = qa.fetch_select(
            f"m2kt.services.{name}.tpu.precision",
            f"Select the training precision policy for [{name}]",
            ["bf16 compute + fp32 master weights; bf16-scaled adds loss "
             "scaling; fp32 for conv nets / numerics debugging"],
            default_precision, list(PRECISION_OPTIONS))
        if precision not in PRECISION_OPTIONS:
            precision = default_precision
        raw = qa.fetch_input(
            f"m2kt.services.{name}.tpu.gradaccum",
            f"Enter gradient accumulation microbatches for [{name}]",
            ["1 disables accumulation; k>1 folds k microbatches into one "
             "optimizer update"],
            "1")
        try:
            grad_accum = max(1, int(raw))
        except (TypeError, ValueError):
            grad_accum = 1
        raw = qa.fetch_select(
            f"m2kt.services.{name}.train.fusedce",
            f"Select the fused LM-head cross-entropy mode for [{name}]",
            ["auto fuses the chunked online-logsumexp loss when the vocab "
             "spans multiple chunks (the [B,T,V] logit tensor never "
             "materializes); on forces it; off keeps the jnp reference "
             "loss"],
            "auto", ["auto", "on", "off"])
        fused_ce = raw if raw in ("auto", "on", "off") else "auto"
        for container in svc.containers:
            env = container.setdefault("env", [])
            existing = {e.get("name") for e in env}
            for env_name, value in (("M2KT_PRECISION", precision),
                                    ("M2KT_GRAD_ACCUM", str(grad_accum)),
                                    ("M2KT_FUSED_CE", fused_ce)):
                if env_name not in existing:
                    env.append({"name": env_name, "value": value})
    return ir


def tpu_serving_optimizer(ir: IR) -> IR:
    """Bake the serving capacity knobs into accelerated serving services'
    pod env. Same QA ids as the jax-xla emitter's ``_ask_serving_knobs``
    (``m2kt.services.<name>.serve.maxbatch`` / ``.maxseq`` / ``.kvblock``)
    — answered once, cached, so the emitted server's baked-in defaults and
    the YAML's explicit env always agree. The Knative apiresource reads
    ``M2KT_SERVE_MAX_BATCH`` back to set the revision's
    containerConcurrency. Existing env entries are never overwritten."""
    for svc in ir.services.values():
        acc = getattr(svc, "accelerator", None)
        if acc is None or not getattr(acc, "serving", False):
            continue
        name = common.make_dns_label(svc.name)
        knobs = {}
        for env_name, qid, desc, default in (
            ("M2KT_SERVE_MAX_BATCH", "serve.maxbatch",
             "Enter the max concurrent decode batch for [{name}]", "8"),
            ("M2KT_SERVE_MAX_SEQ", "serve.maxseq",
             "Enter the max context length (prompt + generation) for "
             "[{name}]", "2048"),
            ("M2KT_KV_BLOCK_SIZE", "serve.kvblock",
             "Enter the paged KV cache block size (tokens/page) for "
             "[{name}]", "16"),
        ):
            raw = qa.fetch_input(
                f"m2kt.services.{name}.{qid}", desc.format(name=name),
                ["bounds compiled shapes and HBM footprint of the serving "
                 "engine's paged KV cache"],
                default)
            try:
                knobs[env_name] = str(max(1, int(raw)))
            except (TypeError, ValueError):
                knobs[env_name] = default
        # low-precision + speculative-decoding knobs (same QA ids as the
        # jax-xla emitter's _ask_serving_knobs, so the baked template
        # defaults and this env never disagree)
        raw = qa.fetch_select(
            f"m2kt.services.{name}.serve.quant",
            f"Select the serving quantization policy for [{name}]",
            ["int8 halves weight (and optionally KV-cache) HBM traffic — "
             "decode is bandwidth-bound, so bytes are tokens/s"],
            "off", ["off", "int8", "int8-kv"])
        knobs["M2KT_SERVE_QUANT"] = (
            raw if raw in ("off", "int8", "int8-kv") else "off")
        raw = qa.fetch_select(
            f"m2kt.services.{name}.serve.kernels",
            f"Select the fused serving-kernel mode for [{name}]",
            ["auto enables the fused Pallas paged-decode kernel and "
             "collective-overlapped decode matmul on TPU backends only; "
             "on forces them (interpreter off-TPU); off keeps the jnp "
             "reference path"],
            "auto", ["auto", "on", "off"])
        knobs["M2KT_SERVE_KERNELS"] = (
            raw if raw in ("auto", "on", "off") else "auto")
        raw = qa.fetch_input(
            f"m2kt.services.{name}.serve.speck",
            f"Enter the speculative-decoding proposal length for [{name}]",
            ["tokens the draft model proposes per verify step; 0 disables "
             "speculative decoding"],
            "0")
        try:
            knobs["M2KT_SPEC_K"] = str(max(0, int(raw)))
        except (TypeError, ValueError):
            knobs["M2KT_SPEC_K"] = "0"
        raw = qa.fetch_select(
            f"m2kt.services.{name}.serve.async",
            f"Select the async decode pipeline mode for [{name}]",
            ["auto overlaps host-side token consumption with the next "
             "device decode step whenever spec decoding is off; off "
             "keeps the synchronous reference loop"],
            "auto", ["auto", "on", "off"])
        knobs["M2KT_ASYNC_DECODE"] = (
            raw if raw in ("auto", "on", "off") else "auto")
        raw = qa.fetch_input(
            f"m2kt.services.{name}.serve.substeps",
            f"Enter the in-graph decode substeps for [{name}]",
            ["decode micro-steps fused into one dispatch (fori_loop); "
             "the host touches the device once per N tokens — needs the "
             "async pipeline, 1 = one token per dispatch"],
            "1")
        try:
            knobs["M2KT_DECODE_SUBSTEPS"] = str(max(1, int(raw)))
        except (TypeError, ValueError):
            knobs["M2KT_DECODE_SUBSTEPS"] = "1"
        for container in svc.containers:
            env = container.setdefault("env", [])
            existing = {e.get("name") for e in env}
            for env_name, value in knobs.items():
                if env_name not in existing:
                    env.append({"name": env_name, "value": value})
    return ir


def tpu_fleet_optimizer(ir: IR) -> IR:
    """Bake the fleet-serving knobs into accelerated serving services'
    pod env. Delegates to ``apiresource.fleet_wiring.fleet_knobs`` — the
    SAME QA ids (``m2kt.services.<name>.serve.fleet`` / ``.routers`` /
    ``.prefill`` / ``.decode`` / ``.salt``) the per-role workload
    emitters ask, answered once and cached, so the pod env, the chart
    values, and the role replica counts cannot disagree. Also turns the
    prefix cache on (``M2KT_SERVE_PREFIX_CACHE``): the router's session
    affinity only pays off when the engines keep their caches."""
    from move2kube_tpu.apiresource.fleet_wiring import fleet_knobs

    for svc in ir.services.values():
        acc = getattr(svc, "accelerator", None)
        if acc is None or not getattr(acc, "serving", False):
            continue
        knobs = fleet_knobs(svc.name)
        if knobs is None:
            continue
        entries = [
            ("M2KT_FLEET", "1"),
            ("M2KT_FLEET_ROUTERS", str(knobs["routers"])),
            ("M2KT_FLEET_PREFILL", str(knobs["prefill"])),
            ("M2KT_FLEET_DECODE", str(knobs["decode"])),
            ("M2KT_SERVE_PREFIX_CACHE", "1"),
            # fault-tolerance contract: every hop (router admission,
            # replica wait, engine shed) derives its budget from this
            # deadline; the drain grace feeds both the preStop hook and
            # the in-process SIGTERM handler; min-available feeds the
            # per-role PodDisruptionBudgets
            ("M2KT_DEADLINE_S", f"{knobs['deadline']:g}"),
            ("M2KT_DRAIN_GRACE_S", f"{knobs['draingrace']:g}"),
            ("M2KT_FLEET_MIN_AVAILABLE", str(knobs["minavailable"])),
            # weight plane: P2P shard streaming for joining replicas
            # plus the POST /swap rolling live weight swap
            ("M2KT_FLEET_SWAP", "1" if knobs.get("swap") else "0"),
            ("M2KT_WEIGHTS_PORT", str(knobs.get("weightsport", 0) or 0)),
        ]
        if knobs.get("salt"):
            entries.append(("M2KT_FLEET_AFFINITY_SALT", str(knobs["salt"])))
        # predictive autoscaling: baked so the autoscaler-role pod and
        # the fleet_wiring HPA-suppression guard read the same answer
        entries.append(("M2KT_AUTOSCALE",
                        "1" if knobs.get("autoscale") else "0"))
        if knobs.get("autoscale"):
            entries.extend([
                ("M2KT_AUTOSCALE_LEAD_S",
                 f"{knobs.get('autoscalelead', 120.0):g}"),
                ("M2KT_AUTOSCALE_MAX",
                 str(int(knobs.get("autoscalemax", 8)))),
                ("M2KT_AUTOSCALE_TARGET_UTIL",
                 f"{knobs.get('autoscaleutil', 0.7):g}"),
            ])
        for container in svc.containers:
            env = container.setdefault("env", [])
            existing = {e.get("name") for e in env}
            for env_name, value in entries:
                if env_name not in existing:
                    env.append({"name": env_name, "value": value})
    return ir


def tpu_elastic_optimizer(ir: IR) -> IR:
    """Bake the elastic-restart knobs into multislice training services'
    pod env (``M2KT_ELASTIC`` / ``M2KT_ELASTIC_MIN_SLICES``).

    Delegates to ``apiresource.deployment.elastic_knobs`` — the SAME QA
    ids (``m2kt.services.<name>.elastic`` / ``.elastic.minslices``) the
    JobSet emitter asks, answered once and cached, so the pod env and the
    failure-policy wiring can't disagree. Single-slice services are
    skipped: with no surviving slice to re-plan onto, elastic mode is
    meaningless and the knob would only confuse the operator."""
    from move2kube_tpu.apiresource.deployment import elastic_knobs

    for svc in ir.services.values():
        acc = getattr(svc, "accelerator", None)
        if (acc is None or getattr(acc, "serving", False)
                or not getattr(svc, "job", False)
                or max(1, getattr(acc, "num_slices", 1)) < 2):
            continue
        name = common.make_dns_label(svc.name)
        elastic, min_slices = elastic_knobs(name)
        if not elastic:
            continue
        for container in svc.containers:
            env = container.setdefault("env", [])
            existing = {e.get("name") for e in env}
            for env_name, value in (
                ("M2KT_ELASTIC", "1"),
                ("M2KT_ELASTIC_MIN_SLICES", str(min_slices)),
            ):
                if env_name not in existing:
                    env.append({"name": env_name, "value": value})
    return ir


def tpu_observability_optimizer(ir: IR) -> IR:
    """Bake the telemetry port into accelerated services' pod env + a
    named ``metrics`` container port.

    Asks the SAME QA problem as the jax-xla emitter
    (``m2kt.services.<name>.obs.port``) — cache-consistent with the
    baked-in template default, env wins inside the workload. Port 0
    disables telemetry entirely (no env, no port, and downstream no
    scrape annotations). Runs AFTER port_merge on purpose: the metrics
    port must not become a Service port forwarding. The named port is
    what the optional PodMonitor's podMetricsEndpoints reference."""
    for svc in ir.services.values():
        if getattr(svc, "accelerator", None) is None:
            continue
        name = common.make_dns_label(svc.name)
        raw = qa.fetch_input(
            f"m2kt.services.{name}.obs.port",
            f"Enter the telemetry (/metrics) port for [{name}]",
            ["Prometheus exposition + on-demand XLA profiling; 0 disables"],
            "9090")
        try:
            port = int(raw)
        except (TypeError, ValueError):
            port = 9090
        if port <= 0:
            continue
        for container in svc.containers:
            env = container.setdefault("env", [])
            if "M2KT_METRICS_PORT" not in {e.get("name") for e in env}:
                env.append({"name": "M2KT_METRICS_PORT",
                            "value": str(port)})
            ports = container.setdefault("ports", [])
            if not any(p.get("name") == "metrics" for p in ports):
                ports.append({"containerPort": port, "name": "metrics"})
    return ir


def tpu_slo_optimizer(ir: IR) -> IR:
    """Bake the per-tenant SLO targets into accelerated *serving*
    services' pod env (``M2KT_SLO_TTFT_P95_S`` / ``M2KT_SLO_AVAILABILITY``
    / ``M2KT_OBS_MAX_TENANTS``).

    Asks the SAME QA problems as the jax-xla emitter
    (``m2kt.services.<name>.obs.slo.*``) — answered once and cached, so
    the serve template's baked-in defaults and the workload env agree;
    the tpu_slo_parameterizer then lifts these env values into Helm
    values (tpuslottftp95 etc.) so operators retune without a rebuild.
    Training services are skipped: the SLO ledger measures request
    latency, which only the serving engine has."""
    for svc in ir.services.values():
        acc = getattr(svc, "accelerator", None)
        if acc is None or not getattr(acc, "serving", False):
            continue
        name = common.make_dns_label(svc.name)
        entries = []
        for qid, desc, extra, default, env_name, is_int in (
            ("obs.slo.ttftp95",
             f"Enter the TTFT p95 SLO target in seconds for [{name}]",
             "requests whose time-to-first-token exceeds this count "
             "against the error budget; burn-rate alerts fire on budget "
             "spend", "0.5", "M2KT_SLO_TTFT_P95_S", False),
            ("obs.slo.availability",
             f"Enter the availability SLO objective for [{name}]",
             "fraction of requests that must complete AND meet latency "
             "targets (e.g. 0.99 = 1% error budget)", "0.99",
             "M2KT_SLO_AVAILABILITY", False),
            ("obs.slo.maxtenants",
             f"Enter the max distinct tenant labels for [{name}]",
             "bounded metric cardinality: tenants beyond this collapse "
             "into the 'other' series", "8", "M2KT_OBS_MAX_TENANTS", True),
        ):
            raw = qa.fetch_input(f"m2kt.services.{name}.{qid}", desc,
                                 [extra], default)
            try:
                value = (str(max(1, int(raw))) if is_int
                         else str(float(raw)))
            except (TypeError, ValueError):
                value = default
            entries.append((env_name, value))
        for container in svc.containers:
            env = container.setdefault("env", [])
            existing = {e.get("name") for e in env}
            for env_name, value in entries:
                if env_name not in existing:
                    env.append({"name": env_name, "value": value})
    return ir


def tpu_sched_optimizer(ir: IR) -> IR:
    """Bake the scheduler-plane knobs into accelerated *serving*
    services' pod env (``M2KT_SCHED_PRIORITIES`` / ``M2KT_SCHED_QUOTAS``
    / ``M2KT_SCHED_CHUNK_PREFILL`` / ``M2KT_SCHED_MAX_LORAS``).

    Asks the SAME QA problems as the jax-xla emitter
    (``m2kt.services.<name>.serve.sched.*``) — answered once and cached,
    so the serve template's baked-in defaults and the workload env
    agree; the tpu_sched_parameterizer then lifts these env values into
    Helm values (tpuschedpriorities etc.) so operators retune tenants
    without a rebuild. The spec strings are carried verbatim — the
    serving/sched parser is the tolerant layer (malformed entries warn
    and are skipped at runtime, never crash a pod)."""
    for svc in ir.services.values():
        acc = getattr(svc, "accelerator", None)
        if acc is None or not getattr(acc, "serving", False):
            continue
        name = common.make_dns_label(svc.name)
        entries = []
        for qid, desc, extra, default, env_name, is_int in (
            ("serve.sched.priorities",
             f"Enter the tenant priority classes for [{name}]",
             "tenant:class pairs ('gold:high;free:besteffort'); higher "
             "classes may preempt lower under slot/page pressure — empty "
             "keeps the flat, never-preempt default", "",
             "M2KT_SCHED_PRIORITIES", False),
            ("serve.sched.quotas",
             f"Enter the tenant admission quotas for [{name}]",
             "tenant:rate/burst token buckets ('gold:50/100'); over-quota "
             "requests are refused 429 at the router front — empty means "
             "unlimited", "", "M2KT_SCHED_QUOTAS", False),
            ("serve.sched.chunkprefill",
             f"Enter the chunked-prefill chunk size in tokens for [{name}]",
             "prompts longer than this prefill in chunks interleaved with "
             "decode steps, bounding decode stalls; 0 disables chunking",
             "0", "M2KT_SCHED_CHUNK_PREFILL", True),
            ("serve.sched.maxloras",
             f"Enter the max resident LoRA adapters for [{name}]",
             "paged adapter slots served from one engine (S-LoRA style); "
             "0 disables multi-LoRA serving", "0",
             "M2KT_SCHED_MAX_LORAS", True),
        ):
            raw = qa.fetch_input(f"m2kt.services.{name}.{qid}", desc,
                                 [extra], default)
            if is_int:
                try:
                    value = str(max(0, int(raw)))
                except (TypeError, ValueError):
                    value = default
            else:
                value = str(raw) if raw is not None else default
            entries.append((env_name, value))
        for container in svc.containers:
            env = container.setdefault("env", [])
            existing = {e.get("name") for e in env}
            for env_name, value in entries:
                if env_name not in existing:
                    env.append({"name": env_name, "value": value})
    return ir


def tpu_planreport_optimizer(ir: IR) -> IR:
    """Bake ``M2KT_PLAN_REPORT=1`` into accelerated *training* services
    behind the ``m2kt.services.<name>.obs.planreport`` QA knob
    (``apiresource.obs_wiring.plan_report_enabled`` — shared + cached, so
    every consumer of the knob agrees). The emitted trainer then writes
    ``m2kt-plan-report.{json,md}`` (obs/costmodel.py) into
    M2KT_METRICS_DIR on startup: the analytic HBM plan checked against
    the compiled step's own memory_analysis. Serving services are
    skipped — the engine's cost model rides compile_report instead of a
    startup artifact. Existing env entries are never overwritten."""
    from move2kube_tpu.apiresource.obs_wiring import plan_report_enabled

    for svc in ir.services.values():
        acc = getattr(svc, "accelerator", None)
        if acc is None or getattr(acc, "serving", False):
            continue
        if not plan_report_enabled(svc.name):
            continue
        for container in svc.containers:
            env = container.setdefault("env", [])
            if "M2KT_PLAN_REPORT" not in {e.get("name") for e in env}:
                env.append({"name": "M2KT_PLAN_REPORT", "value": "1"})
    return ir


def tpu_numerics_optimizer(ir: IR) -> IR:
    """Bake the numerics-plane env into accelerated services behind the
    ``m2kt.services.<name>.obs.numerics`` QA knob
    (``apiresource.obs_wiring.numerics_enabled`` — shared + cached, so
    jax_emit's template default and the pod env agree). Training pods
    get ``M2KT_NUMERICS``; serving pods additionally get the
    quant-drift audit rate (``M2KT_QUANT_AUDIT_RATE``, its own sub-knob
    — the fp reference copy is a deliberate memory spend). A knob
    answered off bakes ``M2KT_NUMERICS=0`` explicitly rather than
    omitting it: the runtime default is on, and the pod env must record
    the decision. Existing env entries are never overwritten."""
    from move2kube_tpu.apiresource.obs_wiring import (
        numerics_audit_rate,
        numerics_enabled,
    )

    for svc in ir.services.values():
        acc = getattr(svc, "accelerator", None)
        if acc is None:
            continue
        entries = [("M2KT_NUMERICS",
                    "1" if numerics_enabled(svc.name) else "0")]
        if getattr(acc, "serving", False):
            entries.append(("M2KT_QUANT_AUDIT_RATE",
                            numerics_audit_rate(svc.name)))
        for container in svc.containers:
            env = container.setdefault("env", [])
            existing = {e.get("name") for e in env}
            for env_name, value in entries:
                if env_name not in existing:
                    env.append({"name": env_name, "value": value})
    return ir


def tpu_usage_optimizer(ir: IR) -> IR:
    """Bake the usage-ledger and anomaly-diagnostics env into
    accelerated services behind the ``m2kt.services.<name>.obs.usage``
    and ``.obs.diag`` QA knobs (``apiresource.obs_wiring`` — shared +
    cached, so every consumer agrees). Both runtime defaults are on, so
    a knob answered off bakes an explicit ``0``: the pod env must
    record the decision. Enabled pods also carry the tuning env —
    ``M2KT_USAGE_INTERVAL_S`` / ``M2KT_USAGE_RING`` and
    ``M2KT_DIAG_MIN_INTERVAL_S`` — at the runtime defaults so the Helm
    parameterizer has literals to lift into chart values. Existing env
    entries are never overwritten."""
    from move2kube_tpu.apiresource.obs_wiring import (
        diag_enabled,
        usage_enabled,
    )
    from move2kube_tpu.obs import bridge as obs_bridge
    from move2kube_tpu.obs import ledger as obs_ledger

    for svc in ir.services.values():
        acc = getattr(svc, "accelerator", None)
        if acc is None:
            continue
        use = usage_enabled(svc.name)
        diag = diag_enabled(svc.name)
        entries = [("M2KT_USAGE", "1" if use else "0"),
                   ("M2KT_DIAG", "1" if diag else "0")]
        if use:
            entries += [
                ("M2KT_USAGE_INTERVAL_S",
                 f"{obs_ledger.DEFAULT_INTERVAL_S:g}"),
                ("M2KT_USAGE_RING", str(obs_ledger.DEFAULT_RING)),
            ]
        if diag:
            entries.append(
                ("M2KT_DIAG_MIN_INTERVAL_S",
                 f"{obs_bridge.DEFAULT_DIAG_MIN_INTERVAL_S:g}"))
        for container in svc.containers:
            env = container.setdefault("env", [])
            existing = {e.get("name") for e in env}
            for env_name, value in entries:
                if env_name not in existing:
                    env.append({"name": env_name, "value": value})
    return ir


OPTIMIZERS = [
    normalize_character_optimizer,
    ingress_optimizer,
    replica_optimizer,
    image_pull_policy_optimizer,
    port_merge_optimizer,
    tpu_training_optimizer,
    tpu_serving_optimizer,
    tpu_fleet_optimizer,
    tpu_elastic_optimizer,
    tpu_observability_optimizer,
    tpu_slo_optimizer,
    tpu_sched_optimizer,
    tpu_planreport_optimizer,
    tpu_numerics_optimizer,
    tpu_usage_optimizer,
]


def optimize(ir: IR) -> IR:
    """Run all optimizers, tolerating per-pass failure (optimizer.go:37-52)."""
    for opt in OPTIMIZERS:
        try:
            ir = opt(ir)
        except Exception as e:  # noqa: BLE001
            log.warning("optimizer %s failed: %s", opt.__name__, e)
    return ir
