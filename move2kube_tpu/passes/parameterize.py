"""Helm parameterizers: rewrite IR values into ``{{ .Values.* }}`` refs.

Parity: ``internal/parameterizer/`` — registry ``[imageName, ingress,
storageClass]`` (parameterizer.go:31-50); populates ``ir.values`` for
values.yaml emission. Only runs for Helm artifact output.
"""

from __future__ import annotations

from move2kube_tpu.types.ir import IR, StorageKind
from move2kube_tpu.utils.log import get_logger

log = get_logger("parameterize")


def image_name_parameterizer(ir: IR) -> IR:
    """imagenameparameterizer.go:31 — per-service per-container image tags."""
    for svc_name, svc in ir.services.items():
        for container in svc.containers:
            image = container.get("image", "")
            if not image:
                continue
            built = any(
                image in c.image_names for c in ir.containers if c.new
            )
            if not built:
                continue
            tail = image.split("/")[-1]
            ir.values.set_image(svc_name, container["name"], tail)
            # `index` syntax: DNS-1123 names contain '-', which dotted Go
            # template paths cannot parse
            container["image"] = (
                "{{ .Values.registryurl }}/{{ .Values.registrynamespace }}/"
                f'{{{{ index .Values.services "{svc_name}" "containers" "{container["name"]}" }}}}'
            )
    return ir


def ingress_parameterizer(ir: IR) -> IR:
    """ingressparameterizer.go:27 — host comes from values."""
    if ir.values.ingress_host:
        ir.values.global_variables.setdefault("ingresshost", ir.values.ingress_host)
    return ir


def storage_class_parameterizer(ir: IR) -> IR:
    """storageclassparameterizer.go:29."""
    for storage in ir.storages:
        if storage.kind == StorageKind.PVC and storage.pvc_spec.get("storageClassName"):
            ir.values.storage_class = storage.pvc_spec["storageClassName"]
            storage.pvc_spec["storageClassName"] = "{{ .Values.storageclass }}"
    return ir


def tpu_training_parameterizer(ir: IR) -> IR:
    """Lift the training knobs the optimizer pass injected
    (``M2KT_PRECISION`` / ``M2KT_GRAD_ACCUM`` / ``M2KT_FUSED_CE``) into
    chart values, so a Helm install retunes precision, accumulation, and
    the fused LM-head cross-entropy dispatch per environment
    (``--set tpuprecision=bf16-scaled --set tpufusedce=off``) without
    touching the manifests. First accelerated service seeds the defaults
    (one global knob set — same shape as ``ingresshost``)."""
    lifted = {"M2KT_PRECISION": "tpuprecision",
              "M2KT_GRAD_ACCUM": "tpugradaccum",
              "M2KT_FUSED_CE": "tpufusedce"}
    for svc in ir.services.values():
        if getattr(svc, "accelerator", None) is None:
            continue
        for container in svc.containers:
            for env in container.get("env", []) or []:
                key = lifted.get(env.get("name"))
                value = env.get("value")
                if not key or value is None or "{{" in str(value):
                    continue
                ir.values.global_variables.setdefault(key, str(value))
                env["value"] = f"{{{{ .Values.{key} }}}}"
    return ir


def tpu_serving_parameterizer(ir: IR) -> IR:
    """Lift the serving capacity knobs the serving optimizer injected
    (``M2KT_SERVE_MAX_BATCH`` / ``M2KT_SERVE_MAX_SEQ`` /
    ``M2KT_KV_BLOCK_SIZE`` / ``M2KT_SERVE_QUANT`` /
    ``M2KT_SERVE_KERNELS`` / ``M2KT_SPEC_K`` / ``M2KT_ASYNC_DECODE`` /
    ``M2KT_DECODE_SUBSTEPS``)
    into chart values, so a Helm install resizes the decode batch,
    context length, and KV page size — or flips quantization and
    speculative decoding — per environment
    (``--set tpuservemaxbatch=16 --set tpuservequant=int8-kv``) without
    touching the manifests. Same first-service-seeds-defaults shape as
    the training parameterizer."""
    lifted = {"M2KT_SERVE_MAX_BATCH": "tpuservemaxbatch",
              "M2KT_SERVE_MAX_SEQ": "tpuservemaxseq",
              "M2KT_KV_BLOCK_SIZE": "tpukvblocksize",
              "M2KT_SERVE_QUANT": "tpuservequant",
              "M2KT_SERVE_KERNELS": "tpuservekernels",
              "M2KT_SPEC_K": "tpuspeck",
              "M2KT_ASYNC_DECODE": "tpuserveasync",
              "M2KT_DECODE_SUBSTEPS": "tpuservesubsteps"}
    for svc in ir.services.values():
        acc = getattr(svc, "accelerator", None)
        if acc is None or not getattr(acc, "serving", False):
            continue
        for container in svc.containers:
            for env in container.get("env", []) or []:
                key = lifted.get(env.get("name"))
                value = env.get("value")
                if not key or value is None or "{{" in str(value):
                    continue
                ir.values.global_variables.setdefault(key, str(value))
                env["value"] = f"{{{{ .Values.{key} }}}}"
    return ir


def tpu_fleet_parameterizer(ir: IR) -> IR:
    """Lift the fleet-serving knobs the fleet optimizer injected
    (``M2KT_FLEET`` / role replica counts / affinity salt) into chart
    values, so a Helm install resizes the fleet or reshuffles the
    tenant->replica placement per environment
    (``--set tpufleetdecode=8 --set tpufleetsalt=blue``) without
    touching the manifests. Same first-service-seeds-defaults shape as
    the serving parameterizer."""
    lifted = {"M2KT_FLEET": "tpufleet",
              "M2KT_FLEET_ROUTERS": "tpufleetrouters",
              "M2KT_FLEET_PREFILL": "tpufleetprefill",
              "M2KT_FLEET_DECODE": "tpufleetdecode",
              "M2KT_FLEET_AFFINITY_SALT": "tpufleetsalt",
              # resilience knobs (split contract with fleet_wiring's PDB
              # emitter: seeding tpufleetminavailable here makes the
              # PodDisruptionBudgets bake the .Values ref)
              "M2KT_DEADLINE_S": "tpufleetdeadline",
              "M2KT_DRAIN_GRACE_S": "tpufleetdraingrace",
              "M2KT_FLEET_MIN_AVAILABLE": "tpufleetminavailable",
              # weight plane (P2P streaming + live swap)
              "M2KT_FLEET_SWAP": "tpufleetswap",
              "M2KT_WEIGHTS_PORT": "tpufleetweightsport",
              # predictive autoscaling (serving/fleet/autoscaler.py):
              # retune the forecast lead / ceiling / utilization per
              # environment with --set tpufleetautoscale*
              "M2KT_AUTOSCALE": "tpufleetautoscale",
              "M2KT_AUTOSCALE_LEAD_S": "tpufleetautoscalelead",
              "M2KT_AUTOSCALE_MAX": "tpufleetautoscalemax",
              "M2KT_AUTOSCALE_TARGET_UTIL": "tpufleetautoscaleutil"}
    for svc in ir.services.values():
        acc = getattr(svc, "accelerator", None)
        if acc is None or not getattr(acc, "serving", False):
            continue
        for container in svc.containers:
            for env in container.get("env", []) or []:
                key = lifted.get(env.get("name"))
                value = env.get("value")
                if not key or value is None or "{{" in str(value):
                    continue
                ir.values.global_variables.setdefault(key, str(value))
                env["value"] = f"{{{{ .Values.{key} }}}}"
    return ir


def tpu_elastic_parameterizer(ir: IR) -> IR:
    """Lift the elastic-restart knobs the elastic optimizer / JobSet
    emitter injected (``M2KT_ELASTIC`` / ``M2KT_ELASTIC_MIN_SLICES``)
    into chart values, so a Helm install flips slice-loss behavior per
    environment (``--set tpuelastic=0``) without touching the manifests.

    Only env entries with a literal ``value`` are lifted: the multislice
    block also injects ``valueFrom``/fieldRef entries (``M2KT_SLICE_ID``,
    ``MEGASCALE_SLICE_ID`` read the JobSet job-index annotation) and
    those must survive parameterization untouched — a fieldRef rewritten
    into a template string would break every slice's identity."""
    lifted = {"M2KT_ELASTIC": "tpuelastic",
              "M2KT_ELASTIC_MIN_SLICES": "tpuelasticminslices"}
    for svc in ir.services.values():
        if getattr(svc, "accelerator", None) is None:
            continue
        for container in svc.containers:
            for env in container.get("env", []) or []:
                key = lifted.get(env.get("name"))
                value = env.get("value")
                if not key or value is None or "{{" in str(value):
                    continue
                ir.values.global_variables.setdefault(key, str(value))
                env["value"] = f"{{{{ .Values.{key} }}}}"
    return ir


def tpu_obs_parameterizer(ir: IR) -> IR:
    """Lift the telemetry port the observability optimizer injected
    (``M2KT_METRICS_PORT``) into chart values
    (``--set tpumetricsport=9464``). The scrape annotation reads the SAME
    env value at apiresource time, so in Helm output the annotation
    becomes ``{{ .Values.tpumetricsport }}`` too — port and annotation
    cannot drift."""
    for svc in ir.services.values():
        if getattr(svc, "accelerator", None) is None:
            continue
        for container in svc.containers:
            for env in container.get("env", []) or []:
                if env.get("name") != "M2KT_METRICS_PORT":
                    continue
                value = env.get("value")
                if value is None or "{{" in str(value):
                    continue
                ir.values.global_variables.setdefault("tpumetricsport",
                                                      str(value))
                env["value"] = "{{ .Values.tpumetricsport }}"
    return ir


def tpu_slo_parameterizer(ir: IR) -> IR:
    """Lift the SLO env the slo optimizer injected into chart values, so
    a Helm install retunes the SLO plane per environment
    (``--set tpuslottftp95=0.3``) without a rebuild. The values names
    match obs/rules.py ``THRESHOLDS`` where they overlap
    (``tpuslottftp95``), so the burn-rate PrometheusRule's alert floor
    and the runtime target stay one knob."""
    lifted = {
        "M2KT_SLO_TTFT_P95_S": "tpuslottftp95",
        "M2KT_SLO_AVAILABILITY": "tpusloavailability",
        "M2KT_OBS_MAX_TENANTS": "tpuslomaxtenants",
    }
    for svc in ir.services.values():
        acc = getattr(svc, "accelerator", None)
        if acc is None or not getattr(acc, "serving", False):
            continue
        for container in svc.containers:
            for env in container.get("env", []) or []:
                key = lifted.get(env.get("name"))
                if key is None:
                    continue
                value = env.get("value")
                if value is None or "{{" in str(value):
                    continue
                ir.values.global_variables.setdefault(key, str(value))
                env["value"] = "{{ .Values.%s }}" % key
    return ir


def tpu_sched_parameterizer(ir: IR) -> IR:
    """Lift the scheduler-plane env the sched optimizer injected into
    chart values, so a Helm install retunes tenants per environment
    (``--set tpuschedpriorities='gold:high;free:besteffort'``) without a
    rebuild. Empty spec values lift too: the knob then exists in
    values.yaml for operators to fill in, and the runtime treats empty
    as the flat, never-preempt default."""
    lifted = {
        "M2KT_SCHED_PRIORITIES": "tpuschedpriorities",
        "M2KT_SCHED_QUOTAS": "tpuschedquotas",
        "M2KT_SCHED_CHUNK_PREFILL": "tpuschedchunkprefill",
        "M2KT_SCHED_MAX_LORAS": "tpuschedmaxloras",
    }
    for svc in ir.services.values():
        acc = getattr(svc, "accelerator", None)
        if acc is None or not getattr(acc, "serving", False):
            continue
        for container in svc.containers:
            for env in container.get("env", []) or []:
                key = lifted.get(env.get("name"))
                if key is None:
                    continue
                value = env.get("value")
                if value is None or "{{" in str(value):
                    continue
                ir.values.global_variables.setdefault(key, str(value))
                env["value"] = "{{ .Values.%s }}" % key
    return ir


def tpu_numerics_parameterizer(ir: IR) -> IR:
    """Lift the numerics-plane env the numerics optimizer injected into
    chart values: ``M2KT_NUMERICS`` -> ``tpunumerics`` (any accelerated
    service) and ``M2KT_QUANT_AUDIT_RATE`` -> ``tpuquantauditrate``
    (serving), so a Helm install can kill the plane or retune the audit
    sampling (``--set tpuquantauditrate=0.1``) without a rebuild. The
    alert floor for the drift the audits report lives with the other
    rule thresholds (``tpunumdriftmax``, seeded by
    ``tpu_rules_parameterizer`` off obs/rules.py THRESHOLDS)."""
    lifted = {
        "M2KT_NUMERICS": "tpunumerics",
        "M2KT_QUANT_AUDIT_RATE": "tpuquantauditrate",
    }
    for svc in ir.services.values():
        if getattr(svc, "accelerator", None) is None:
            continue
        for container in svc.containers:
            for env in container.get("env", []) or []:
                key = lifted.get(env.get("name"))
                if key is None:
                    continue
                value = env.get("value")
                if value is None or "{{" in str(value):
                    continue
                ir.values.global_variables.setdefault(key, str(value))
                env["value"] = "{{ .Values.%s }}" % key
    return ir


def tpu_usage_parameterizer(ir: IR) -> IR:
    """Lift the usage-ledger / diagnostics env the usage optimizer
    injected into chart values: ``M2KT_USAGE`` -> ``tpuusage``,
    ``M2KT_USAGE_INTERVAL_S`` -> ``tpuusageinterval``,
    ``M2KT_USAGE_RING`` -> ``tpuusagering``, ``M2KT_DIAG`` ->
    ``tpudiag`` and ``M2KT_DIAG_MIN_INTERVAL_S`` ->
    ``tpudiagmininterval`` — so a Helm install can turn off chargeback
    collection, retune the snapshot cadence, or relax the diag-capture
    rate limit (``--set tpudiagmininterval=60``) without a rebuild."""
    lifted = {
        "M2KT_USAGE": "tpuusage",
        "M2KT_USAGE_INTERVAL_S": "tpuusageinterval",
        "M2KT_USAGE_RING": "tpuusagering",
        "M2KT_DIAG": "tpudiag",
        "M2KT_DIAG_MIN_INTERVAL_S": "tpudiagmininterval",
    }
    for svc in ir.services.values():
        if getattr(svc, "accelerator", None) is None:
            continue
        for container in svc.containers:
            for env in container.get("env", []) or []:
                key = lifted.get(env.get("name"))
                if key is None:
                    continue
                value = env.get("value")
                if value is None or "{{" in str(value):
                    continue
                ir.values.global_variables.setdefault(key, str(value))
                env["value"] = "{{ .Values.%s }}" % key
    return ir


def tpu_rules_parameterizer(ir: IR) -> IR:
    """Lift the alert-rule thresholds (obs/rules.py ``THRESHOLDS``) into
    chart values for every service whose ``m2kt.services.<name>.obs.rules``
    knob is on, so a Helm install retunes alert floors per environment
    (``--set tpugoodputmin=0.8``) without touching the manifests.

    Unlike the env-lifting parameterizers this one cannot rewrite the
    manifests itself — the PrometheusRule objects are built *after*
    parameterization, at apiresource time. The contract is split: this
    pass seeds the values (keys double as the ``.Values`` names), and
    ``apiresource/obs_wiring.maybe_rules_objects`` sees them seeded and
    bakes ``{{ .Values.<key> }}`` refs into the PromQL instead of the
    literals. The QA knob is fetched with the same id the emitters use,
    so one cached answer keeps both sides agreed."""
    from move2kube_tpu.apiresource.obs_wiring import (
        metrics_port_value, rules_enabled)
    from move2kube_tpu.obs.rules import THRESHOLDS

    for svc in ir.services.values():
        if getattr(svc, "accelerator", None) is None:
            continue
        if not metrics_port_value(svc) or not rules_enabled(svc.name):
            continue
        for key, default in THRESHOLDS.items():
            ir.values.global_variables.setdefault(key, default)
        break  # one global threshold set — same shape as ingresshost
    return ir


PARAMETERIZERS = [image_name_parameterizer, ingress_parameterizer,
                  storage_class_parameterizer, tpu_training_parameterizer,
                  tpu_serving_parameterizer, tpu_fleet_parameterizer,
                  tpu_elastic_parameterizer,
                  tpu_obs_parameterizer, tpu_slo_parameterizer,
                  tpu_sched_parameterizer,
                  tpu_numerics_parameterizer, tpu_usage_parameterizer,
                  tpu_rules_parameterizer]


def parameterize(ir: IR) -> IR:
    for p in PARAMETERIZERS:
        try:
            ir = p(ir)
        except Exception as e:  # noqa: BLE001
            log.warning("parameterizer %s failed: %s", p.__name__, e)
    return ir
