"""Customizer passes: registry, storage and ingress QA.

Parity: ``internal/customizer/`` — registry ``[registry, storage,
ingress]`` (customizer.go:30-49).
"""

from __future__ import annotations

import json

from move2kube_tpu import qa
from move2kube_tpu.types.ir import IR, Storage, StorageKind
from move2kube_tpu.utils import common
from move2kube_tpu.utils.log import get_logger

log = get_logger("customize")


def registry_customizer(ir: IR) -> IR:
    """QA: registry + namespace to push built images to; pull secret if the
    registry needs auth (registrycustomizer.go:45)."""
    if not any(c.new for c in ir.containers):
        return ir
    registry = qa.fetch_select(
        "m2kt.target.registry.url",
        "Select the registry to push images to",
        ["Built images will be tagged and pushed here"],
        ir.kubernetes.registry_url or common.DEFAULT_REGISTRY_URL,
        [ir.kubernetes.registry_url or common.DEFAULT_REGISTRY_URL,
         "quay.io", "gcr.io", "docker.io", "Other"],
    )
    if registry == "Other":
        registry = qa.fetch_input(
            "m2kt.target.registry.url.other", "Enter the registry URL", [],
            common.DEFAULT_REGISTRY_URL,
        )
    namespace = qa.fetch_input(
        "m2kt.target.registry.namespace",
        "Enter the registry namespace",
        [], ir.kubernetes.registry_namespace or ir.name,
    )
    ir.kubernetes.registry_url = registry
    ir.kubernetes.registry_namespace = namespace
    ir.values.registry_url = registry
    ir.values.registry_namespace = namespace
    needs_auth = qa.fetch_bool(
        "m2kt.target.registry.auth",
        f"Does the registry [{registry}] need authentication to pull?",
        [], False,
    )
    if needs_auth:
        secret_name = common.make_dns_label(registry) + "-imagepullsecret"
        docker_config = {"auths": {registry: {"auth": ""}}}
        ir.add_storage(Storage(
            name=secret_name,
            kind=StorageKind.PULL_SECRET,
            content={".dockerconfigjson": json.dumps(docker_config).encode()},
        ))
        for svc in ir.services.values():
            if secret_name not in svc.image_pull_secrets:
                svc.image_pull_secrets.append(secret_name)
    # rewrite image names to registry/namespace/name:tag for new images
    for container in ir.containers:
        if not container.new or not container.image_names:
            continue
        image = container.image_names[0]
        if "/" not in image:
            full = f"{registry}/{namespace}/{image}"
            container.image_names.insert(0, full)
            for svc in ir.services.values():
                for c in svc.containers:
                    if c.get("image") == image:
                        c["image"] = full
    return ir


def storage_customizer(ir: IR) -> IR:
    """QA: storage class selection for PVCs (storagecustomizer.go:42-210)."""
    pvcs = [s for s in ir.storages if s.kind == StorageKind.PVC]
    if not pvcs:
        return ir
    classes = ir.target_cluster_spec.storage_classes or [common.DEFAULT_STORAGE_CLASS]
    chosen = qa.fetch_select(
        "m2kt.storage.class",
        "Select the storage class for persistent volume claims",
        [f"PVCs: {', '.join(s.name for s in pvcs)}"],
        classes[0], classes,
    )
    for pvc in pvcs:
        pvc.pvc_spec.setdefault("storageClassName", chosen)
        pvc.pvc_spec["storageClassName"] = chosen
    ir.values.storage_class = chosen
    return ir


def ingress_customizer(ir: IR) -> IR:
    """QA: ingress host + optional TLS secret (ingresscustomizer.go:33-60)."""
    exposed = [
        s for s in ir.services.values()
        if s.has_valid_annotation(common.EXPOSE_SERVICE_ANNOTATION)
    ]
    if not exposed:
        return ir
    host = qa.fetch_input(
        "m2kt.target.ingress.host",
        "Enter the ingress host domain",
        ["Services will be exposed under this domain"],
        ir.name + ".com",
    )
    tls_secret = qa.fetch_input(
        "m2kt.target.ingress.tls",
        "Enter the TLS secret name (empty for none)",
        [], "",
    )
    ir.values.ingress_host = host
    ir.ingress_tls_secret_name = tls_secret
    return ir


CUSTOMIZERS = [registry_customizer, storage_customizer, ingress_customizer]


def customize(ir: IR) -> IR:
    for c in CUSTOMIZERS:
        try:
            ir = c(ir)
        except Exception as e:  # noqa: BLE001
            log.warning("customizer %s failed: %s", c.__name__, e)
    return ir
