from move2kube_tpu.passes.optimize import optimize  # noqa: F401
from move2kube_tpu.passes.customize import customize  # noqa: F401
from move2kube_tpu.passes.parameterize import parameterize  # noqa: F401
