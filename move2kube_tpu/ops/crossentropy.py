"""Fused chunked cross-entropy for LM heads (the training-side kernel).

``cross_entropy_loss`` (models/train.py) upcasts the whole ``[B, T, V]``
logit tensor to float32 and materializes a second ``[B, T, V]``
log-softmax; at 32k vocab those two tensors are the largest non-matmul
HBM cost of the LM step. This module removes them with the flash-
attention trick applied to the vocab axis:

* :func:`fused_cross_entropy` — blockwise online-logsumexp forward over
  vocab chunks (running max / sum-of-exp, one ``[N, chunk]`` float32
  tile live at a time) with a ``custom_vjp`` whose backward emits
  ``(softmax(logits) - onehot(labels)) * g / N`` chunk by chunk, never
  building the full-softmax intermediate jax's log_softmax VJP would.

* :func:`fused_linear_cross_entropy` — the same, with the lm-head
  matmul folded INTO the chunk loop: the forward computes
  ``hidden @ W[:, chunk]`` per chunk, so the full ``[N, V]`` logit
  tensor never exists at all; the backward recomputes each chunk and
  contracts it straight into ``d_hidden`` / ``dW[:, chunk]``. This is
  what moves the lm-head off the HBM roofline (bench.py llama phase
  records the peak delta).

Everything here is plain jnp/XLA — backend-independent, differentiable,
and exactly equivalent to the reference at fp32 (chunk reassociation of
the logsumexp is the only difference; tests gate it at 1e-6).

Dispatch mirrors the ``M2KT_SERVE_KERNELS`` ladder (attention.py
serve_kernels_mode): ``M2KT_FUSED_CE=auto|on|off``, with any trace-time
failure of the fused path logged once and falling back to the jnp
reference. Unlike the serving ladder, ``auto`` is not TPU-gated —
chunked CE is XLA, not Pallas — it instead engages when the vocab is
large enough to span more than one ``M2KT_CE_CHUNK``-sized chunk
(chunking a tiny classifier head would only add loop overhead).
"""

from __future__ import annotations

import functools
import logging
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

DEFAULT_CHUNK = 2048

_warned: set[str] = set()


def _warn_once(site: str, exc: Exception) -> None:
    if site in _warned:
        return
    _warned.add(site)
    logging.getLogger(__name__).warning(
        "fused cross-entropy: %s failed (%s: %s); falling back to the jnp "
        "reference path", site, type(exc).__name__, exc)


def fused_ce_mode() -> str:
    """``M2KT_FUSED_CE`` -> 'auto' | 'on' | 'off' (same spellings the
    serving ladder accepts; anything unrecognized reads as auto)."""
    raw = os.environ.get("M2KT_FUSED_CE", "auto").strip().lower()
    if raw in ("on", "1", "true"):
        return "on"
    if raw in ("off", "0", "false"):
        return "off"
    return "auto"


def ce_chunk_size() -> int:
    """Requested vocab chunk size (``M2KT_CE_CHUNK``, default 2048)."""
    try:
        c = int(os.environ.get("M2KT_CE_CHUNK", str(DEFAULT_CHUNK)))
    except ValueError:
        c = DEFAULT_CHUNK
    return max(c, 8)


def pick_chunk(vocab: int, requested: int) -> int:
    """Largest divisor of ``vocab`` <= ``requested`` (the chunk loop is
    ``vocab // chunk`` iterations; a non-divisor would drop columns).
    Pathological vocabs whose best divisor is tiny (primes) collapse to
    a single chunk rather than thousands of slivers."""
    c = max(1, min(int(requested), int(vocab)))
    while vocab % c:
        c -= 1
    if c < 128 and vocab > 128:
        return vocab
    return c


def should_fuse(vocab: int) -> bool:
    """The ladder decision for a head of width ``vocab``: on -> always,
    off -> never, auto -> only when the vocab spans multiple chunks."""
    mode = fused_ce_mode()
    if mode == "on":
        return True
    if mode == "off":
        return False
    return int(vocab) > ce_chunk_size()


def reference_cross_entropy(logits, labels) -> jax.Array:
    """The unfused baseline: full fp32 upcast + log_softmax + gather.
    Identical math to models/train.py cross_entropy_loss."""
    logits = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    picked = jnp.take_along_axis(logp, labels[..., None].astype(jnp.int32),
                                 axis=-1)
    return -jnp.mean(picked)


def _float0_like(labels):
    """Cotangent for integer labels (custom_vjp requires float0, not a
    zero int array)."""
    return np.zeros(labels.shape, dtype=jax.dtypes.float0)


# ------------------------------------------------------------------ chunked
# logits-level fused CE: logits exist (the model computed them) but the
# fp32 upcast + log-softmax copies never do.

@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _fused_ce(logits, labels, chunk: int):
    loss, _ = _ce_forward(logits, labels, chunk)
    return loss


def _ce_forward(logits, labels, chunk: int):
    n, v = logits.shape
    labels = labels.astype(jnp.int32)
    m0 = jnp.full((n,), -1e30, jnp.float32)

    def body(i, carry):
        m, s, picked = carry
        lo = i * chunk
        blk = lax.dynamic_slice_in_dim(logits, lo, chunk,
                                       axis=1).astype(jnp.float32)
        bm = jnp.max(blk, axis=1)
        m2 = jnp.maximum(m, bm)
        s = s * jnp.exp(m - m2) + jnp.sum(jnp.exp(blk - m2[:, None]), axis=1)
        idx = jnp.clip(labels - lo, 0, chunk - 1)
        val = jnp.take_along_axis(blk, idx[:, None], axis=1)[:, 0]
        hit = (labels >= lo) & (labels < lo + chunk)
        picked = jnp.where(hit, val, picked)
        return m2, s, picked

    zeros = jnp.zeros((n,), jnp.float32)
    m, s, picked = lax.fori_loop(0, v // chunk, body, (m0, zeros, zeros))
    lse = m + jnp.log(s)
    return jnp.mean(lse - picked), lse


def _ce_fwd(logits, labels, chunk: int):
    loss, lse = _ce_forward(logits, labels, chunk)
    return loss, (logits, labels, lse)


def _ce_bwd(chunk: int, res, g):
    logits, labels, lse = res
    n, v = logits.shape
    labels = labels.astype(jnp.int32)
    scale = (g / n).astype(jnp.float32)

    def body(i, dl):
        lo = i * chunk
        blk = lax.dynamic_slice_in_dim(logits, lo, chunk,
                                       axis=1).astype(jnp.float32)
        p = jnp.exp(blk - lse[:, None])
        col = lo + lax.broadcasted_iota(jnp.int32, (n, chunk), 1)
        p = p - (col == labels[:, None]).astype(jnp.float32)
        return lax.dynamic_update_slice_in_dim(
            dl, (p * scale).astype(dl.dtype), lo, axis=1)

    dl = lax.fori_loop(0, v // chunk, body, jnp.zeros_like(logits))
    return dl, _float0_like(labels)


_fused_ce.defvjp(_ce_fwd, _ce_bwd)


def fused_cross_entropy(logits, labels, chunk: int | None = None) -> jax.Array:
    """Chunked online-logsumexp CE over the last axis of ``logits``
    (any leading shape; ``labels`` matches the leading shape)."""
    v = logits.shape[-1]
    c = pick_chunk(v, chunk or ce_chunk_size())
    flat = logits.reshape(-1, v)
    return _fused_ce(flat, labels.reshape(-1), c)


# ----------------------------------------------------------- linear-fused
# head-folded CE: logits never materialize. hidden [N, D], weight [D, V].

@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _fused_linear_ce(hidden, weight, labels, chunk: int):
    loss, _ = _linear_forward(hidden, weight, labels, chunk)
    return loss


def _linear_forward(hidden, weight, labels, chunk: int):
    n = hidden.shape[0]
    v = weight.shape[1]
    h32 = hidden.astype(jnp.float32)
    labels = labels.astype(jnp.int32)
    m0 = jnp.full((n,), -1e30, jnp.float32)

    def body(i, carry):
        m, s, picked = carry
        lo = i * chunk
        wc = lax.dynamic_slice_in_dim(weight, lo, chunk,
                                      axis=1).astype(jnp.float32)
        blk = jnp.dot(h32, wc, preferred_element_type=jnp.float32)
        bm = jnp.max(blk, axis=1)
        m2 = jnp.maximum(m, bm)
        s = s * jnp.exp(m - m2) + jnp.sum(jnp.exp(blk - m2[:, None]), axis=1)
        idx = jnp.clip(labels - lo, 0, chunk - 1)
        val = jnp.take_along_axis(blk, idx[:, None], axis=1)[:, 0]
        hit = (labels >= lo) & (labels < lo + chunk)
        picked = jnp.where(hit, val, picked)
        return m2, s, picked

    zeros = jnp.zeros((n,), jnp.float32)
    m, s, picked = lax.fori_loop(0, v // chunk, body, (m0, zeros, zeros))
    lse = m + jnp.log(s)
    return jnp.mean(lse - picked), lse


def _linear_fwd(hidden, weight, labels, chunk: int):
    loss, lse = _linear_forward(hidden, weight, labels, chunk)
    return loss, (hidden, weight, labels, lse)


def _linear_bwd(chunk: int, res, g):
    hidden, weight, labels, lse = res
    n, dm = hidden.shape
    v = weight.shape[1]
    h32 = hidden.astype(jnp.float32)
    labels = labels.astype(jnp.int32)
    scale = (g / n).astype(jnp.float32)

    def body(i, carry):
        dh, dw = carry
        lo = i * chunk
        wc = lax.dynamic_slice_in_dim(weight, lo, chunk,
                                      axis=1).astype(jnp.float32)
        blk = jnp.dot(h32, wc, preferred_element_type=jnp.float32)
        p = jnp.exp(blk - lse[:, None])
        col = lo + lax.broadcasted_iota(jnp.int32, (n, chunk), 1)
        p = (p - (col == labels[:, None]).astype(jnp.float32)) * scale
        dh = dh + jnp.dot(p, wc.T, preferred_element_type=jnp.float32)
        dwc = jnp.dot(h32.T, p, preferred_element_type=jnp.float32)
        dw = lax.dynamic_update_slice_in_dim(dw, dwc.astype(dw.dtype), lo,
                                             axis=1)
        return dh, dw

    dh0 = jnp.zeros((n, dm), jnp.float32)
    dw0 = jnp.zeros(weight.shape, weight.dtype)
    dh, dw = lax.fori_loop(0, v // chunk, body, (dh0, dw0))
    return dh.astype(hidden.dtype), dw, _float0_like(labels)


_fused_linear_ce.defvjp(_linear_fwd, _linear_bwd)


def fused_linear_cross_entropy(hidden, weight, labels,
                               chunk: int | None = None) -> jax.Array:
    """CE of ``hidden @ weight`` against ``labels`` without ever building
    the ``[N, V]`` logits. ``hidden``: [..., D]; ``weight``: [D, V]."""
    v = weight.shape[1]
    c = pick_chunk(v, chunk or ce_chunk_size())
    flat = hidden.reshape(-1, hidden.shape[-1])
    return _fused_linear_ce(flat, weight, labels.reshape(-1), c)


# ------------------------------------------------------------- dispatchers

def cross_entropy(logits, labels) -> jax.Array:
    """Ladder-dispatching CE: fused chunked path per :func:`should_fuse`,
    jnp reference otherwise, with a warn-once trace-time fallback."""
    if not should_fuse(logits.shape[-1]):
        return reference_cross_entropy(logits, labels)
    try:
        return fused_cross_entropy(logits, labels)
    except Exception as e:  # noqa: BLE001 - fall back rather than fail
        _warn_once("fused_cross_entropy", e)
        return reference_cross_entropy(logits, labels)


def lm_head_weight(params):
    """``[D, V]`` LM-head weight for the model zoo's head layouts, or
    None when the tree has no recognizable head: llama-style separate
    ``lm_head`` Dense, or the gpt2 tied token embedding (transposed).
    Grads flow back through the returned view, so the tied head keeps
    accumulating both embedding and head contributions."""
    try:
        if "lm_head" in params:
            return params["lm_head"]["kernel"]
        if "wte" in params:
            return params["wte"]["embedding"].T
    except (KeyError, TypeError):
        return None
    return None


def linear_lm_loss(hidden, weight, input_ids,
                   chunk: int | None = None) -> jax.Array:
    """Next-token-prediction loss straight from the pre-head hidden
    states: shift, flatten, head-folded chunked CE."""
    h = hidden[:, :-1, :]
    t = input_ids[:, 1:]
    return fused_linear_cross_entropy(h, weight, t, chunk=chunk)
