"""Flash attention as a Pallas TPU kernel.

Blockwise attention with online softmax: for each Q block the kernel scans
K/V blocks resident in VMEM, maintaining running max / sum / accumulator,
so the full [seq, seq] score matrix never touches HBM. Scores accumulate in
float32 on the MXU (pallas_guide.md: "Math and Compute Operations" —
jnp.dot with preferred_element_type=jnp.float32; tiling constraints
(8/16, 128) motivate the 128-multiple block sizes; bigger tiles amortize
the per-block softmax bookkeeping across more MXU work — the 256x512
defaults measured ~35% over 128x128 within one chip session, and the
session-to-session bench capture roughly doubled; the stable comparator
is vs_official_kernel in BENCH_OPPORTUNISTIC.json, same shape and chip).

Off-TPU (tests run on a CPU mesh) the public entrypoint falls back to a
mathematically identical jnp implementation.
"""

from __future__ import annotations

import functools
import json
import logging
import os
import time

import jax
import jax.numpy as jnp

_NEG_INF = -1e30

# Residual logsumexp rows are stored broadcast across a 128-lane minor dim
# (the float32 TPU tile is (8, 128); a rank-1 [seq] residual would not
# tile) — same layout the public jax TPU flash kernel uses for its l/m
# residuals.
_LANES = 128

# Tests flip this to run the real kernel bodies through the Pallas
# interpreter on CPU (including through the custom_vjp); on TPU it stays
# False and the kernels compile to Mosaic.
_INTERPRET = False


# --------------------------------------------------------------------------
# Block-size autotuner
#
# The measured-good 256x512 stays the default, but the best (block_q,
# block_k) shifts with sequence length, head_dim and dtype (VMEM budget
# per core is ~16 MB; the fori_loop bookkeeping amortizes differently as
# tiles grow — pallas_guide.md "Tiling Constraints"). On first use per
# (shape, dtype, causal, platform) the tuner times a small candidate grid
# with the real kernel, then caches the winner in-process and on disk so
# steady-state calls (and the next process) pay nothing.
# --------------------------------------------------------------------------

DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 512

# every candidate is a 128-multiple (float32/bf16 lane tiling); _pick_block
# clamps to divisors of the actual sequence, and duplicates after clamping
# are swept once
_BLOCK_CANDIDATES = ((128, 128), (128, 512), (256, 256), (256, 512),
                     (256, 1024), (512, 512), (512, 1024))

_block_cache: dict[str, tuple[int, int]] = {}
_disk_cache_path_loaded: str | None = None

# every kernel that stores winners in the shared disk cache; keys are
# prefixed with the kernel name so one kernel's geometry can never be
# served to another (pre-PR-11 cache files carried bare flash keys —
# _load_disk_cache migrates those by prepending "flash:")
_KERNEL_NAMES = ("flash", "flash_bwd", "paged_decode")


def _autotune_enabled() -> bool:
    """M2KT_FLASH_AUTOTUNE=1/0 forces the sweep on/off; default is
    TPU-only (sweeping the interpreter on CPU would time Python, not
    silicon)."""
    flag = os.environ.get("M2KT_FLASH_AUTOTUNE", "")
    if flag in ("0", "1"):
        return flag == "1"
    return jax.default_backend() == "tpu"


def _tune_cache_path() -> str:
    return os.path.expanduser(
        os.environ.get("M2KT_FLASH_TUNE_CACHE",
                       "~/.cache/move2kube_tpu/flash_blocks.json"))


def _load_disk_cache() -> None:
    """Merge the on-disk winners into the in-process cache, once per
    path (a changed M2KT_FLASH_TUNE_CACHE triggers a reload)."""
    global _disk_cache_path_loaded
    path = _tune_cache_path()
    if _disk_cache_path_loaded == path:
        return
    _disk_cache_path_loaded = path
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        for k, v in data.items():
            # tolerant migration: cache files written before the key
            # carried a kernel name hold flash winners only — claim them
            # for "flash" instead of discarding the sweep work
            if k.split(":", 1)[0] not in _KERNEL_NAMES:
                k = f"flash:{k}"
            _block_cache.setdefault(k, (int(v[0]), int(v[1])))
    except (OSError, ValueError, TypeError, IndexError):
        pass  # missing or corrupt cache: resweep


def _store_disk_cache(key: str, blocks: tuple[int, int]) -> None:
    path = _tune_cache_path()
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        try:
            with open(path, encoding="utf-8") as f:
                data = json.load(f)
        except (OSError, ValueError):
            data = {}
        data[key] = list(blocks)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(data, f, indent=0, sort_keys=True)
        os.replace(tmp, path)
    except OSError as e:
        logging.getLogger(__name__).warning(
            "flash autotune: cannot persist block cache to %s (%s)", path, e)


def _reset_block_cache() -> None:
    """Testing hook: forget in-process winners and the loaded-disk-path
    memo so the next get_block_sizes re-reads M2KT_FLASH_TUNE_CACHE."""
    global _disk_cache_path_loaded
    _block_cache.clear()
    _disk_cache_path_loaded = None


def _cache_key(q_shape, kv_seq: int, dtype: str, causal: bool,
               kernel: str = "flash", geometry: str = "") -> str:
    """Disk/in-process cache key: kernel name + backend + problem shape
    (+ an optional kernel-specific geometry suffix, e.g. the paged-decode
    page layout). Keying by kernel keeps paged-decode winners from ever
    answering a flash lookup that happens to share a shape."""
    shape = "x".join(str(int(d)) for d in q_shape)
    key = (f"{kernel}:{jax.default_backend()}:{shape}:k{int(kv_seq)}:"
           f"{dtype}:{'causal' if causal else 'full'}")
    return f"{key}:{geometry}" if geometry else key


def _measure_blocks(q, k, v, causal: bool, scale: float,
                    block_q: int, block_k: int) -> float:
    """Wall seconds for a few timed forward calls at the given blocks
    (compile + one warmup excluded). Separated out so tests can stub the
    timing without touching the sweep/caching logic."""
    run = jax.jit(lambda q_, k_, v_: _flash_attention_tpu(
        q_, k_, v_, causal, scale, block_q=block_q, block_k=block_k))
    jax.block_until_ready(run(q, k, v))  # compile + warm
    t0 = time.perf_counter()
    for _ in range(3):
        out = run(q, k, v)
    jax.block_until_ready(out)
    return time.perf_counter() - t0


def _sweep_blocks(q_shape, kv_seq: int, dtype: str,
                  causal: bool) -> tuple[int, int]:
    b, s, h, d = (int(x) for x in q_shape)
    scale = d ** -0.5
    keys = jax.random.split(jax.random.PRNGKey(0), 3)
    jdt = jnp.dtype(dtype)
    q = jax.random.normal(keys[0], (b, s, h, d), jdt)
    k = jax.random.normal(keys[1], (b, kv_seq, h, d), jdt)
    v = jax.random.normal(keys[2], (b, kv_seq, h, d), jdt)
    best, best_t = (DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K), float("inf")
    seen: set[tuple[int, int]] = set()
    for bq, bk in _BLOCK_CANDIDATES:
        eff = (_pick_block(bq, s), _pick_block(bk, kv_seq))
        if eff in seen:
            continue
        seen.add(eff)
        try:
            t = _measure_blocks(q, k, v, causal, scale, *eff)
        except Exception:  # noqa: BLE001 - candidate may exceed VMEM
            continue
        if t < best_t:
            best, best_t = eff, t
    logging.getLogger(__name__).info(
        "flash autotune: %s -> block_q=%d block_k=%d",
        _cache_key(q_shape, kv_seq, dtype, causal), *best)
    return best


def get_block_sizes(q_shape, kv_seq: int, dtype: str, causal: bool,
                    allow_sweep: bool = True) -> tuple[int, int]:
    """Tuned (block_q, block_k) for a flash-attention call. Cached
    winners (in-process, then disk) are returned immediately; otherwise a
    sweep runs when enabled (see _autotune_enabled) and ``allow_sweep``
    (False under tracing: timing through a tracer is meaningless). The
    fallback everywhere else is the measured 256x512 default."""
    key = _cache_key(q_shape, kv_seq, dtype, causal)
    if key in _block_cache:
        return _block_cache[key]
    _load_disk_cache()
    if key in _block_cache:
        return _block_cache[key]
    if not (allow_sweep and _autotune_enabled()):
        return (DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K)
    winner = _sweep_blocks(q_shape, kv_seq, dtype, causal)
    _block_cache[key] = winner
    _store_disk_cache(key, winner)
    return winner


def _measure_bwd_blocks(q, k, v, o, lse, g, causal: bool, scale: float,
                        block_q: int, block_k: int) -> float:
    """Wall seconds for a few timed backward calls (dq + dk/dv grids) at
    the given blocks. Separated out so tests can stub the timing."""
    run = jax.jit(lambda *a: _flash_attention_bwd_tpu(
        *a, causal, scale, block_q=block_q, block_k=block_k))
    jax.block_until_ready(run(q, k, v, o, lse, g))  # compile + warm
    t0 = time.perf_counter()
    for _ in range(3):
        out = run(q, k, v, o, lse, g)
    jax.block_until_ready(out)
    return time.perf_counter() - t0


def _sweep_bwd_blocks(q_shape, kv_seq: int, dtype: str,
                      causal: bool) -> tuple[int, int]:
    """Sweep (block_q, block_k) over the SAME candidate grid as the
    forward, but timing the two backward pallas_calls: their best blocks
    differ from the forward's (the dkv kernel holds whole Q/dO/lse rows
    in VMEM per K block, so its budget tilts toward smaller tiles)."""
    b, s, h, d = (int(x) for x in q_shape)
    scale = d ** -0.5
    keys = jax.random.split(jax.random.PRNGKey(1), 4)
    jdt = jnp.dtype(dtype)
    q = jax.random.normal(keys[0], (b, s, h, d), jdt)
    k = jax.random.normal(keys[1], (b, kv_seq, h, d), jdt)
    v = jax.random.normal(keys[2], (b, kv_seq, h, d), jdt)
    g = jax.random.normal(keys[3], (b, s, h, d), jdt)
    o, lse = _flash_attention_tpu(q, k, v, causal, scale,
                                  return_residuals=True)
    best, best_t = (DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K), float("inf")
    seen: set[tuple[int, int]] = set()
    for bq, bk in _BLOCK_CANDIDATES:
        eff = (_pick_block(bq, s), _pick_block(bk, kv_seq))
        if eff in seen:
            continue
        seen.add(eff)
        try:
            t = _measure_bwd_blocks(q, k, v, o, lse, g, causal, scale, *eff)
        except Exception:  # noqa: BLE001 - candidate may exceed VMEM
            continue
        if t < best_t:
            best, best_t = eff, t
    logging.getLogger(__name__).info(
        "flash bwd autotune: %s -> block_q=%d block_k=%d",
        _cache_key(q_shape, kv_seq, dtype, causal, kernel="flash_bwd",
                   geometry="dq+dkv"), *best)
    return best


def get_bwd_block_sizes(q_shape, kv_seq: int, dtype: str, causal: bool,
                        allow_sweep: bool = True) -> tuple[int, int]:
    """Tuned (block_q, block_k) for the flash-attention BACKWARD (shared
    by the dq and dk/dv grids), keyed ``flash_bwd:<shape>:dq+dkv`` in the
    same disk cache as the forward winners. The backward only ever runs
    under grad tracing, but that does not block the sweep: the timing
    runs on fresh CONCRETE arrays synthesized from the (static) shapes,
    so a cache miss sweeps once at trace time and the winner is baked
    into the compiled program — lookups themselves stay trace-safe. With
    tuning unavailable the forward's cached winner for the shape is the
    fallback (its lookup never sweeps), then the measured defaults."""
    key = _cache_key(q_shape, kv_seq, dtype, causal, kernel="flash_bwd",
                     geometry="dq+dkv")
    if key in _block_cache:
        return _block_cache[key]
    _load_disk_cache()
    if key in _block_cache:
        return _block_cache[key]
    if not (allow_sweep and _autotune_enabled()):
        return get_block_sizes(q_shape, kv_seq, dtype, causal,
                               allow_sweep=False)
    winner = _sweep_bwd_blocks(q_shape, kv_seq, dtype, causal)
    _block_cache[key] = winner
    _store_disk_cache(key, winner)
    return winner


def _reference_attention(q, k, v, causal: bool, scale: float):
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        qi = jnp.arange(q.shape[1])[:, None]
        ki = jnp.arange(k.shape[1])[None, :]
        s = jnp.where(qi >= ki, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref=None, *, block_k: int,
                  causal: bool, scale: float, q_block_idx_axis: int):
    """One (batch*head, q_block) grid cell; scans K blocks.

    Refs are [block_q, d] / [seq_k, d] slices staged into VMEM by BlockSpec.
    When ``lse_ref`` is given (training forward), the per-row logsumexp is
    written alongside the output so the backward kernels can recompute the
    probabilities blockwise instead of materializing [seq, seq] scores.
    """
    from jax.experimental import pallas as pl

    block_q, d = q_ref.shape
    seq_k = k_ref.shape[0]
    q = q_ref[:].astype(jnp.float32) * scale
    qi = pl.program_id(q_block_idx_axis) * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)

    def body(start, carry):
        o_acc, m_acc, l_acc = carry
        k_blk = k_ref[pl.dslice(start * block_k, block_k), :]
        v_blk = v_ref[pl.dslice(start * block_k, block_k), :]
        s = jnp.dot(q, k_blk.astype(jnp.float32).T,
                    preferred_element_type=jnp.float32)  # [bq, bk]
        if causal:
            ki = start * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(qi >= ki, s, _NEG_INF)
        m_blk = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_acc, m_blk)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_acc - m_new)
        l_new = l_acc * alpha + jnp.sum(p, axis=-1, keepdims=True)
        o_new = o_acc * alpha + jnp.dot(
            p, v_blk.astype(jnp.float32), preferred_element_type=jnp.float32)
        return o_new, m_new, l_new

    n_blocks = seq_k // block_k
    if causal:
        # only scan blocks that intersect the causal frontier
        last_needed = (pl.program_id(q_block_idx_axis) + 1) * block_q
        n_needed = jax.lax.div(last_needed + block_k - 1, block_k)
        n_iter = jnp.minimum(n_blocks, n_needed)
    else:
        n_iter = n_blocks
    o0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    o, m, l = jax.lax.fori_loop(0, n_iter, body, (o0, m0, l0))
    o_ref[:] = (o / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    if lse_ref is not None:
        lse = m + jnp.log(jnp.maximum(l, 1e-30))  # [block_q, 1]
        lse_ref[:] = jnp.broadcast_to(lse, (block_q, _LANES))


def _merge_heads(t):
    """[b, s, h, d] -> [b*h, s, d] so kernel grids are (bh, seq_blocks)."""
    b, s, h, d = t.shape
    return t.transpose(0, 2, 1, 3).reshape(b * h, s, d)


def _pick_block(preferred: int, seq: int) -> int:
    """Largest 128-multiple block <= preferred that divides seq (grids
    are seq // block; a non-divisor would silently drop rows): seq 384
    with preferred 512 -> 384, seq 768 with preferred 512 -> 384, seq 384
    with preferred 256 -> 128. Sub-128 seqs (interpret-mode tests) fall
    back to halving."""
    b = min(preferred, seq)
    b -= b % 128
    while b >= 128:
        if seq % b == 0:
            return b
        b -= 128
    b = min(preferred, seq)
    while seq % b:
        b //= 2
    return max(b, 1)


def _flash_attention_tpu(q, k, v, causal: bool, scale: float,
                         block_q: int | None = None,
                         block_k: int | None = None,
                         interpret: bool | None = None,
                         return_residuals: bool = False):
    """``interpret=True`` runs the kernel body through the Pallas
    interpreter on any backend — how CI validates the actual kernel math
    without silicon (tests/test_models.py). With ``return_residuals`` the
    call also returns the logsumexp rows ([b*h, s, _LANES], lane-
    broadcast) the backward kernels consume. ``block_q``/``block_k``
    default to the autotuned sizes for this shape (cached winner, or the
    256x512 defaults when tuning is off/off-TPU/under tracing)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        interpret = _INTERPRET
    if block_q is None or block_k is None:
        # tracers carry concrete shapes, so cached winners apply inside
        # jit; only the timing sweep itself needs concrete arrays
        tq, tk = get_block_sizes(
            q.shape, k.shape[1], str(q.dtype), causal,
            allow_sweep=not (interpret or isinstance(q, jax.core.Tracer)))
        block_q = tq if block_q is None else block_q
        block_k = tk if block_k is None else block_k
    b, s, h, d = q.shape
    sk = k.shape[1]
    block_q = _pick_block(block_q, s)
    block_k = _pick_block(block_k, sk)
    qm, km, vm = _merge_heads(q), _merge_heads(k), _merge_heads(v)
    grid = (b * h, s // block_q)
    out_shape = [jax.ShapeDtypeStruct((b * h, s, d), q.dtype)]
    out_specs = [pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0))]
    if return_residuals:
        out_shape.append(
            jax.ShapeDtypeStruct((b * h, s, _LANES), jnp.float32))
        out_specs.append(
            pl.BlockSpec((None, block_q, _LANES), lambda i, j: (i, j, 0)))
    res = pl.pallas_call(
        functools.partial(_flash_kernel, block_k=block_k, causal=causal,
                          scale=scale, q_block_idx_axis=1),
        out_shape=out_shape,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, sk, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, sk, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=out_specs,
        interpret=interpret,
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
    )(qm, km, vm)
    out = res[0].reshape(b, h, s, d).transpose(0, 2, 1, 3)
    if return_residuals:
        return out, res[1]
    return out


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, *, block_k: int, causal: bool, scale: float,
                         q_block_idx_axis: int):
    """dQ for one (batch*head, q_block) grid cell; scans K blocks.

    Probabilities are recomputed from the saved logsumexp, so only
    [block_q, block_k] score tiles ever exist — the [seq, seq] matrix the
    round-3 jnp backward materialized never does.
    """
    from jax.experimental import pallas as pl

    block_q, d = q_ref.shape
    seq_k = k_ref.shape[0]
    q = q_ref[:].astype(jnp.float32)
    do = do_ref[:].astype(jnp.float32)
    lse = lse_ref[:][:, :1]      # lane-broadcast -> [block_q, 1]
    delta = delta_ref[:][:, :1]
    qi = pl.program_id(q_block_idx_axis) * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)

    def body(start, dq_acc):
        k_blk = k_ref[pl.dslice(start * block_k, block_k), :].astype(
            jnp.float32)
        v_blk = v_ref[pl.dslice(start * block_k, block_k), :].astype(
            jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32) * scale
        if causal:
            ki = start * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(qi >= ki, s, _NEG_INF)
        p = jnp.exp(s - lse)
        dp = jnp.dot(do, v_blk.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        return dq_acc + jnp.dot(ds, k_blk,
                                preferred_element_type=jnp.float32)

    n_blocks = seq_k // block_k
    if causal:
        last_needed = (pl.program_id(q_block_idx_axis) + 1) * block_q
        n_iter = jnp.minimum(
            n_blocks, jax.lax.div(last_needed + block_k - 1, block_k))
    else:
        n_iter = n_blocks
    dq = jax.lax.fori_loop(0, n_iter, body,
                           jnp.zeros((block_q, d), jnp.float32))
    dq_ref[:] = (dq * scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, *, block_q: int, causal: bool,
                          scale: float, k_block_idx_axis: int):
    """dK and dV for one (batch*head, k_block) grid cell; scans Q blocks
    (from the causal frontier when masked — earlier Q rows can't attend to
    this K block, so their tiles are all-zero and skipped)."""
    from jax.experimental import pallas as pl

    block_k, d = k_ref.shape
    seq_q = q_ref.shape[0]
    k = k_ref[:].astype(jnp.float32)
    v = v_ref[:].astype(jnp.float32)
    ki = pl.program_id(k_block_idx_axis) * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)

    def body(qb, carry):
        dk_acc, dv_acc = carry
        qs = q_ref[pl.dslice(qb * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[pl.dslice(qb * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[pl.dslice(qb * block_q, block_q), :][:, :1]
        delta = delta_ref[pl.dslice(qb * block_q, block_q), :][:, :1]
        s = jnp.dot(qs, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            qi = qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            s = jnp.where(qi >= ki, s, _NEG_INF)
        p = jnp.exp(s - lse)
        dv_acc = dv_acc + jnp.dot(p.T, do,
                                  preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk_acc = dk_acc + jnp.dot(ds.T, qs,
                                  preferred_element_type=jnp.float32)
        return dk_acc, dv_acc

    n_q_blocks = seq_q // block_q
    start = (jax.lax.div(pl.program_id(k_block_idx_axis) * block_k, block_q)
             if causal else 0)
    dk, dv = jax.lax.fori_loop(
        start, n_q_blocks, body,
        (jnp.zeros((block_k, d), jnp.float32),
         jnp.zeros((block_k, d), jnp.float32)))
    dk_ref[:] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


def _flash_attention_bwd_tpu(q, k, v, o, lse, g, causal: bool, scale: float,
                             block_q: int | None = None,
                             block_k: int | None = None,
                             interpret: bool | None = None):
    """Blockwise flash-attention backward: dq gridded over Q blocks, dk/dv
    gridded over K blocks, probabilities recomputed from ``lse``. HBM
    traffic and VMEM footprint scale O(seq*d), not O(seq^2), matching the
    forward kernel's point. Blocks default to the backward's own tuned
    sizes (get_bwd_block_sizes): the sweep times synthetic concrete
    arrays, so it runs even though this function only executes under
    grad tracing — only interpreter mode (CPU kernel-body validation)
    skips straight to the cached/forward/default ladder."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        interpret = _INTERPRET
    if block_q is None or block_k is None:
        tq, tk = get_bwd_block_sizes(q.shape, k.shape[1], str(q.dtype),
                                     causal, allow_sweep=not interpret)
        block_q = tq if block_q is None else block_q
        block_k = tk if block_k is None else block_k
    b, s, h, d = q.shape
    sk = k.shape[1]
    block_q = _pick_block(block_q, s)
    block_k = _pick_block(block_k, sk)
    qm, km, vm = _merge_heads(q), _merge_heads(k), _merge_heads(v)
    om, gm = _merge_heads(o), _merge_heads(g)
    # delta_i = rowsum(dO_i * O_i): cheap elementwise, fused by XLA; lane-
    # broadcast to the same [bh, s, _LANES] layout as lse
    delta = jnp.sum(gm.astype(jnp.float32) * om.astype(jnp.float32),
                    axis=-1)
    delta = jnp.broadcast_to(delta[..., None], (b * h, s, _LANES))

    common = dict(
        interpret=interpret,
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
    )
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, block_k=block_k,
                          causal=causal, scale=scale, q_block_idx_axis=1),
        out_shape=jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
        grid=(b * h, s // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, sk, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, sk, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, block_q, _LANES), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, block_q, _LANES), lambda i, j: (i, j, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
        **common,
    )(qm, km, vm, gm, lse, delta)
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, block_q=block_q,
                          causal=causal, scale=scale, k_block_idx_axis=1),
        out_shape=[jax.ShapeDtypeStruct((b * h, sk, d), k.dtype),
                   jax.ShapeDtypeStruct((b * h, sk, d), v.dtype)],
        grid=(b * h, sk // block_k),
        in_specs=[
            pl.BlockSpec((None, s, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, s, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, s, _LANES), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, s, _LANES), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda i, j: (i, j, 0)),
        ],
        **common,
    )(qm, km, vm, gm, lse, delta)

    def unmerge(t, seq):
        return t.reshape(b, h, seq, d).transpose(0, 2, 1, 3)

    return unmerge(dq, s), unmerge(dk, sk), unmerge(dv, sk)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_attention_diff(q, k, v, causal: bool, scale: float):
    """Differentiable wrapper: Pallas kernels have no automatic reverse-
    mode rule, so without this custom_vjp ``jax.grad`` through a training
    step would fail at trace time on TPU."""
    return _flash_attention_tpu(q, k, v, causal, scale)


def _flash_diff_fwd(q, k, v, causal, scale):
    out, lse = _flash_attention_tpu(q, k, v, causal, scale,
                                    return_residuals=True)
    return out, (q, k, v, out, lse)


def _flash_diff_bwd(causal, scale, residuals, g):
    # Blockwise Pallas backward (dq/dk/dv with logsumexp recompute): the
    # [b, h, s, s] score matrix never materializes, matching the forward
    # kernel's memory profile in training. The jnp reference vjp remains
    # as a TRACE-TIME fallback: a Mosaic/XLA failure that only surfaces
    # when the enclosing jit compiles happens outside this handler and
    # cannot be caught here. For that case operators can export
    # M2KT_FORCE_REFERENCE_VJP=1 to skip the Pallas backward outright
    # (correctness over throughput until the backend regression is fixed).
    q, k, v, o, lse = residuals

    def reference_vjp():
        _, vjp = jax.vjp(
            lambda q_, k_, v_: _reference_attention(q_, k_, v_, causal,
                                                    scale),
            q, k, v)
        return vjp(g)

    if os.environ.get("M2KT_FORCE_REFERENCE_VJP", "") not in ("", "0"):
        return reference_vjp()  # deliberate operator opt-out: no warning
    try:
        return _flash_attention_bwd_tpu(q, k, v, o, lse, g, causal, scale)
    except Exception as e:  # noqa: BLE001 - fall back rather than fail
        logging.getLogger(__name__).warning(
            "pallas flash attention backward failed (%s: %s); falling back "
            "to jnp reference vjp", type(e).__name__, e)
        return reference_vjp()


_flash_attention_diff.defvjp(_flash_diff_fwd, _flash_diff_bwd)


def flash_attention(q, k, v, *, causal: bool = False, scale: float | None = None):
    """Fused attention. q/k/v: [batch, seq, heads, head_dim].

    Uses the Pallas kernel on TPU when shapes are tile-friendly (seq a
    multiple of 128, head_dim >= 64); otherwise the jnp reference (which
    XLA still fuses reasonably well). Differentiable on both paths.
    """
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    on_tpu = jax.default_backend() == "tpu"
    s, d = q.shape[1], q.shape[3]
    if on_tpu and s % 128 == 0 and k.shape[1] % 128 == 0 and d % 64 == 0:
        try:
            return _flash_attention_diff(q, k, v, causal, scale)
        except Exception as e:  # noqa: BLE001 - fall back rather than fail
            logging.getLogger(__name__).warning(
                "pallas flash attention failed (%s: %s); falling back to "
                "jnp reference attention", type(e).__name__, e)
    return _reference_attention(q, k, v, causal, scale)


# --------------------------------------------------------------------------
# Paged decode attention (serving hot path)
#
# Single-token decode over a paged KV cache (serving/kvcache.py): K/V live
# in fixed-size pages, a per-sequence block table says which pages hold its
# context, and every step attends one new query token per sequence against
# that context. The Pallas kernel streams pages straight out of the cache
# via scalar-prefetched block-table indices (pallas_guide.md
# "PrefetchScalarGridSpec": index maps may read prefetched scalars, so no
# [batch, max_seq] gather ever materializes); off TPU the jnp fallback
# gathers pages with XLA and masks by sequence length — identical math.
# Inference-only: no vjp, no residuals.
# --------------------------------------------------------------------------


def quantize_kv_rows(x):
    """Symmetric per-(token, kv-head) int8 quantization of K/V rows.

    ``x``: [..., kv_heads, head_dim] floating K or V. Returns
    ``(q, scale)`` — ``q`` int8 with the same shape, ``scale`` fp32
    shaped [..., kv_heads] such that ``q * scale[..., None]``
    reconstructs ``x``. One scale per written row keeps decode appends
    O(1): a new token never re-quantizes tokens already resident in its
    page (a per-page amax would clip or force a rewrite)."""
    x32 = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x32), axis=-1)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(x32 / scale[..., None]), -127, 127)
    return q.astype(jnp.int8), scale


def _dequant_pages(pages, scales):
    """[num_pages, block_size, kvh, d] int8 + [num_pages, block_size, kvh]
    fp32 -> fp32 pages."""
    return pages.astype(jnp.float32) * scales[..., None]


def _paged_decode_reference(q, k_pages, v_pages, block_tables, seq_lens,
                            scale: float, k_scale=None, v_scale=None):
    b, h, d = q.shape
    _, block_size, kvh, _ = k_pages.shape
    mb = block_tables.shape[1]
    # gather each sequence's pages into a contiguous context; int8 caches
    # gather the quantized pages + their row scales and DEFER the scales
    # past the contractions: a row scale is constant over head_dim, so
    #   q . (k8 * s_k) == (q . k8) * s_k      (one mul per SCORE)
    #   sum_s p * (v8 * s_v) == sum_s (p * s_v) * v8
    # — the jnp mirror of what the fused kernel does in-register. No fp32
    # [S, kvh, d] context is ever materialized, and GQA stays a batched
    # dot over the kv-head axis instead of a repeat.
    if k_scale is not None:
        seq = mb * block_size
        rep = h // kvh
        k8 = k_pages[block_tables].reshape(b, seq, kvh, d)
        v8 = v_pages[block_tables].reshape(b, seq, kvh, d)
        ks = k_scale[block_tables].reshape(b, seq, kvh)
        vs = v_scale[block_tables].reshape(b, seq, kvh)
        qh = (q.astype(jnp.float32) * scale).reshape(b, kvh, rep, d)
        s = jnp.einsum("bkrd,bskd->bkrs", qh, k8.astype(jnp.float32))
        s = s * ks.transpose(0, 2, 1)[:, :, None, :]
        valid = (jnp.arange(seq)[None, None, None, :]
                 < seq_lens[:, None, None, None])
        s = jnp.where(valid, s, _NEG_INF)
        p = jax.nn.softmax(s, axis=-1)
        pv = jnp.einsum("bkrs,bskd->bkrd",
                        p * vs.transpose(0, 2, 1)[:, :, None, :],
                        v8.astype(jnp.float32))
        return pv.reshape(b, h, d).astype(q.dtype)
    k = k_pages[block_tables].reshape(b, mb * block_size, kvh, d)
    v = v_pages[block_tables].reshape(b, mb * block_size, kvh, d)
    rep = h // kvh
    k = jnp.repeat(k, rep, axis=2)
    v = jnp.repeat(v, rep, axis=2)
    s = jnp.einsum("bhd,bshd->bhs", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    valid = (jnp.arange(mb * block_size)[None, None, :]
             < seq_lens[:, None, None])
    s = jnp.where(valid, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhs,bshd->bhd", p.astype(v.dtype), v)


def _paged_decode_kernel(bt_ref, sl_ref, q_ref, k_ref, v_ref, o_ref,
                         acc_ref, m_ref, l_ref, *, block_size: int,
                         rep: int, scale: float):
    """One (sequence, page) grid cell: the page's K/V tile was staged into
    VMEM by the scalar-prefetched index map, so the body is pure online
    softmax. Scratch (acc/m/l) persists across the sequential page axis;
    pages at or past the sequence length are skipped (their table entries
    point at page 0, which the allocator reserves)."""
    from jax.experimental import pallas as pl

    i = pl.program_id(0)
    j = pl.program_id(1)
    seq_len = sl_ref[i]

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    @pl.when(j * block_size < seq_len)
    def _page():
        h, d = q_ref.shape
        kvh = h // rep
        q = q_ref[:].astype(jnp.float32) * scale          # [h, d]
        k = k_ref[:].astype(jnp.float32)                  # [bs, kvh, d]
        v = v_ref[:].astype(jnp.float32)
        # GQA: each kv head serves `rep` query heads — batch the dot over
        # the kv-head axis instead of materializing repeated K/V
        qh = q.reshape(kvh, rep, d)
        kT = k.transpose(1, 0, 2)                         # [kvh, bs, d]
        s = jax.lax.dot_general(
            qh, kT, dimension_numbers=(((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)           # [kvh, rep, bs]
        pos = j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 2)
        s = jnp.where(pos < seq_len, s, _NEG_INF).reshape(h, block_size)
        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_blk = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_blk)
        p = jnp.exp(s - m_new)                            # [h, bs]
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        vh = v.transpose(1, 0, 2)                         # [kvh, bs, d]
        pv = jax.lax.dot_general(
            p.reshape(kvh, rep, block_size), vh,
            dimension_numbers=(((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)           # [kvh, rep, d]
        acc_ref[:] = acc_ref[:] * alpha + pv.reshape(h, d)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == pl.num_programs(1) - 1)
    def _finish():
        l = l_ref[:, :1]
        o_ref[:] = (acc_ref[:] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _paged_decode_tpu(q, k_pages, v_pages, block_tables, seq_lens,
                      scale: float, interpret: bool | None = None):
    """Pallas paged-decode: grid (batch, pages-per-sequence); the K/V page
    for cell (i, j) is selected by ``block_tables[i, j]`` inside the
    BlockSpec index map (scalar prefetch), so only live pages are DMA'd."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        interpret = _INTERPRET
    b, h, d = q.shape
    _, block_size, kvh, _ = k_pages.shape
    mb = block_tables.shape[1]
    rep = h // kvh
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, mb),
        in_specs=[
            pl.BlockSpec((None, h, d), lambda i, j, bt, sl: (i, 0, 0)),
            pl.BlockSpec((None, block_size, kvh, d),
                         lambda i, j, bt, sl: (bt[i, j], 0, 0, 0)),
            pl.BlockSpec((None, block_size, kvh, d),
                         lambda i, j, bt, sl: (bt[i, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, h, d), lambda i, j, bt, sl: (i, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, d), jnp.float32),
            pltpu.VMEM((h, _LANES), jnp.float32),
            pltpu.VMEM((h, _LANES), jnp.float32),
        ],
    )
    return pl.pallas_call(
        functools.partial(_paged_decode_kernel, block_size=block_size,
                          rep=rep, scale=scale),
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
    )(block_tables, seq_lens, q, k_pages, v_pages)


def serve_kernels_mode() -> str:
    """M2KT_SERVE_KERNELS: ``auto`` (default — compiled fused kernel on
    TPU, jnp reference elsewhere), ``on``/``1`` (fused kernel everywhere;
    off-TPU it runs through the Pallas interpreter, which is how CI
    proves the real kernel bodies on CPU), ``off``/``0`` (jnp reference
    only — the documented no-kernel fallback)."""
    raw = os.environ.get("M2KT_SERVE_KERNELS", "auto").strip().lower()
    if raw in ("on", "1", "true"):
        return "on"
    if raw in ("off", "0", "false"):
        return "off"
    return "auto"


def _paged_decode_packed_kernel(bt_ref, sl_ref, q_ref, k_ref, v_ref,
                                *refs, block_size: int, ppt: int, rep: int,
                                scale: float, quantized: bool):
    """Fused (optionally int8) paged-decode attention over PACKED page
    tiles. Grid (sequence, tile, page-in-tile): the int8 minimum tile is
    (32, 128) sublanes x lanes (pallas_guide.md "Tiling Constraints") and
    a serving page is only 8-16 token rows, so single-page int8 blocks
    would underfill the sublane dimension — instead each of the ``ppt``
    pages the index map gathers for a tile is appended into a
    [ppt*block_size, kvh, d] VMEM scratch, and the online-softmax update
    runs once per packed tile on the last page's grid cell. Ragged tails
    pad with the reserved null page and are masked by ``seq_len``; dead
    tiles (wholly past the sequence) skip both the pack and the update.
    Row scales ride along as [ppt*block_size, kvh] scratch and are
    applied AFTER the contractions (one mul per score / per probability,
    never per element of the context), so no fp32 context exists anywhere
    — not in HBM, not even in VMEM."""
    from jax.experimental import pallas as pl

    if quantized:
        ks_ref, vs_ref, o_ref, kt_ref, vt_ref, kst_ref, vst_ref, \
            acc_ref, m_ref, l_ref = refs
    else:
        o_ref, kt_ref, vt_ref, acc_ref, m_ref, l_ref = refs
    i = pl.program_id(0)
    t = pl.program_id(1)
    p = pl.program_id(2)
    seq_len = sl_ref[i]
    tile = ppt * block_size
    tile_start = t * tile

    @pl.when((t == 0) & (p == 0))
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    @pl.when(tile_start < seq_len)
    def _pack():
        kt_ref[pl.ds(p * block_size, block_size)] = k_ref[:]
        vt_ref[pl.ds(p * block_size, block_size)] = v_ref[:]
        if quantized:
            kst_ref[pl.ds(p * block_size, block_size)] = ks_ref[:]
            vst_ref[pl.ds(p * block_size, block_size)] = vs_ref[:]

    @pl.when((p == ppt - 1) & (tile_start < seq_len))
    def _tile():
        h, d = q_ref.shape
        kvh = h // rep
        qh = (q_ref[:].astype(jnp.float32) * scale).reshape(kvh, rep, d)
        kT = kt_ref[:].astype(jnp.float32).transpose(1, 0, 2)
        s = jax.lax.dot_general(
            qh, kT, dimension_numbers=(((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)       # [kvh, rep, tile]
        if quantized:
            s = s * kst_ref[:].transpose(1, 0)[:, None, :]
        pos = tile_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        s = jnp.where(pos < seq_len, s, _NEG_INF).reshape(h, tile)
        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        pr = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(pr, axis=-1, keepdims=True)
        pr = pr.reshape(kvh, rep, tile)
        if quantized:
            pr = pr * vst_ref[:].transpose(1, 0)[:, None, :]
        vh = vt_ref[:].astype(jnp.float32).transpose(1, 0, 2)
        pv = jax.lax.dot_general(
            pr, vh, dimension_numbers=(((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)       # [kvh, rep, d]
        acc_ref[:] = acc_ref[:] * alpha + pv.reshape(h, d)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when((t == pl.num_programs(1) - 1) & (p == ppt - 1))
    def _finish():
        l = l_ref[:, :1]
        o_ref[:] = (acc_ref[:] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _paged_decode_packed(q, k_pages, v_pages, block_tables, seq_lens,
                         scale: float, k_scale=None, v_scale=None,
                         pages_per_tile: int | None = None,
                         interpret: bool | None = None):
    """pallas_call wrapper for the packed paged-decode kernel. Works on
    fp32/bf16 pools (no scales) and int8 pools (+ per-row scale pools).
    ``block_tables`` is padded to a pages_per_tile multiple with the null
    page so every tile is full-width; the kernel masks the padding."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        interpret = _INTERPRET or jax.default_backend() != "tpu"
    b, h, d = q.shape
    _, block_size, kvh, _ = k_pages.shape
    mb = block_tables.shape[1]
    quantized = k_scale is not None
    if pages_per_tile is None:
        pages_per_tile = get_paged_pages_per_tile(
            q.shape, k_pages.shape, str(k_pages.dtype),
            allow_sweep=not (interpret or isinstance(q, jax.core.Tracer)))
    ppt = max(1, min(int(pages_per_tile), mb))
    pad = (-mb) % ppt
    if pad:
        block_tables = jnp.pad(block_tables, ((0, 0), (0, pad)))
    rep = h // kvh
    tile = ppt * block_size

    def page_map(i, t, p, bt, sl):
        return (bt[i, t * ppt + p], 0, 0, 0)

    def scale_map(i, t, p, bt, sl):
        return (bt[i, t * ppt + p], 0, 0)

    in_specs = [
        pl.BlockSpec((None, h, d), lambda i, t, p, bt, sl: (i, 0, 0)),
        pl.BlockSpec((None, block_size, kvh, d), page_map),
        pl.BlockSpec((None, block_size, kvh, d), page_map),
    ]
    scratch = [
        pltpu.VMEM((tile, kvh, d), k_pages.dtype),
        pltpu.VMEM((tile, kvh, d), v_pages.dtype),
    ]
    operands = [block_tables, seq_lens, q, k_pages, v_pages]
    if quantized:
        in_specs += [pl.BlockSpec((None, block_size, kvh), scale_map),
                     pl.BlockSpec((None, block_size, kvh), scale_map)]
        scratch += [pltpu.VMEM((tile, kvh), jnp.float32),
                    pltpu.VMEM((tile, kvh), jnp.float32)]
        operands += [k_scale, v_scale]
    scratch += [
        pltpu.VMEM((h, d), jnp.float32),
        pltpu.VMEM((h, _LANES), jnp.float32),
        pltpu.VMEM((h, _LANES), jnp.float32),
    ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, (mb + pad) // ppt, ppt),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((None, h, d),
                               lambda i, t, p, bt, sl: (i, 0, 0)),
        scratch_shapes=scratch,
    )
    return pl.pallas_call(
        functools.partial(_paged_decode_packed_kernel,
                          block_size=block_size, ppt=ppt, rep=rep,
                          scale=scale, quantized=quantized),
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")),
    )(*operands)


def _default_pages_per_tile(block_size: int, dtype: str) -> int:
    """Pack enough pages that the packed scratch tile meets the minimum
    sublane count for its dtype — 32 rows for int8, 8 for fp32/bf16
    (pallas_guide.md "Tiling Constraints")."""
    rows = 32 if jnp.dtype(dtype).itemsize == 1 else 8
    return max(1, -(-rows // int(block_size)))


def _measure_paged(q, k_pages, v_pages, k_scale, v_scale, block_tables,
                   seq_lens, scale: float, ppt: int) -> float:
    """Wall seconds for a few timed packed-kernel calls at a candidate
    pages-per-tile (compile + warmup excluded; stubbed by tests)."""
    run = jax.jit(functools.partial(_paged_decode_packed, scale=scale,
                                    k_scale=k_scale, v_scale=v_scale,
                                    pages_per_tile=ppt))
    args = (q, k_pages, v_pages, block_tables, seq_lens)
    jax.block_until_ready(run(*args))
    t0 = time.perf_counter()
    for _ in range(3):
        out = run(*args)
    jax.block_until_ready(out)
    return time.perf_counter() - t0


def _sweep_paged(q_shape, pool_shape, dtype: str) -> int:
    """Time the packed kernel over candidate pages-per-tile on synthetic
    pools shaped like the caller's cache and return the winner."""
    b, h, d = (int(x) for x in q_shape)
    num_pages, block_size, kvh, _ = (int(x) for x in pool_shape)
    mb = max(1, (num_pages - 1) // max(1, b))
    quantized = jnp.dtype(dtype).itemsize == 1
    keys = jax.random.split(jax.random.PRNGKey(0), 2)
    q = jax.random.normal(keys[0], (b, h, d), jnp.float32)
    if quantized:
        kp = jax.random.randint(keys[1], (num_pages, block_size, kvh, d),
                                -127, 128, jnp.int8)
        sc = jnp.full((num_pages, block_size, kvh), 0.01, jnp.float32)
        ks, vs = sc, sc
    else:
        kp = jax.random.normal(keys[1], (num_pages, block_size, kvh, d),
                               jnp.dtype(dtype))
        ks = vs = None
    bt = jnp.arange(b * mb, dtype=jnp.int32).reshape(b, mb) % num_pages
    sl = jnp.full((b,), mb * block_size // 2, jnp.int32)
    scale = d ** -0.5
    base = _default_pages_per_tile(block_size, dtype)
    cands = sorted({min(mb, c) for c in (1, base, 2 * base, 4 * base, mb)})
    best, best_t = base, float("inf")
    for ppt in cands:
        try:
            t = _measure_paged(q, kp, kp, ks, vs, bt, sl, scale, ppt)
        except Exception:  # noqa: BLE001 - candidate may exceed VMEM
            continue
        if t < best_t:
            best, best_t = ppt, t
    logging.getLogger(__name__).info(
        "paged-decode autotune: %s -> pages_per_tile=%d",
        _cache_key(q_shape, mb * block_size, dtype, False,
                   kernel="paged_decode"), best)
    return best


def get_paged_pages_per_tile(q_shape, pool_shape, dtype: str,
                             allow_sweep: bool = True) -> int:
    """Tuned pages-per-tile for the packed paged-decode kernel — same
    cache discipline as get_block_sizes (in-process dict, then the shared
    disk file, then a sweep when autotuning is enabled), under its own
    ``paged_decode:``-prefixed key so flash winners can never leak in.
    The geometry suffix pins the page layout; the stored pair is
    (pages_per_tile, tile_tokens)."""
    num_pages, block_size, kvh, d = (int(x) for x in pool_shape)
    key = _cache_key(tuple(q_shape), num_pages * block_size, dtype, False,
                     kernel="paged_decode",
                     geometry=f"bs{block_size}xkvh{kvh}")
    if key in _block_cache:
        return _block_cache[key][0]
    _load_disk_cache()
    if key in _block_cache:
        return _block_cache[key][0]
    if not (allow_sweep and _autotune_enabled()):
        return _default_pages_per_tile(block_size, dtype)
    winner = _sweep_paged(q_shape, pool_shape, dtype)
    _block_cache[key] = (winner, winner * block_size)
    _store_disk_cache(key, (winner, winner * block_size))
    return winner


def paged_decode_attention(q, k_pages, v_pages, block_tables, seq_lens, *,
                           scale: float | None = None,
                           k_scale=None, v_scale=None):
    """Decode-step attention against a paged KV cache. GQA-aware.

    - ``q``: [batch, heads, head_dim] — ONE new query token per slot
    - ``k_pages``/``v_pages``: [num_pages, block_size, kv_heads, head_dim]
    - ``block_tables``: [batch, max_pages_per_seq] int32 page indices
      (unused entries must point at page 0, reserved by the allocator)
    - ``seq_lens``: [batch] int32 valid-token counts, INCLUDING the token
      being decoded (its K/V must already be written to the cache)
    - ``k_scale``/``v_scale``: optional [num_pages, block_size, kv_heads]
      fp32 row scales for int8 page pools (serving/kvcache.py quantized
      caches); dequantization happens here, on the gathered context only

    Dispatch is a fallback ladder — compiled kernel, interpreted kernel,
    jnp reference — governed by M2KT_SERVE_KERNELS (serve_kernels_mode):

    - ``auto``: TPU takes the packed fused kernel (int8 pools dequantize
      in-register with deferred row scales; fp32 pools use the per-page
      kernel when head_dim is lane-aligned, the packed one otherwise);
      off-TPU takes the jnp reference, whose int8 branch folds scales
      after the contractions — the kernel's algorithm, XLA-compiled.
    - ``on``: packed fused kernel everywhere; off-TPU it runs through the
      Pallas interpreter (slow — for tests/CI proving kernel bodies).
    - ``off``: jnp reference only.

    Any kernel failure logs a warning and drops to the jnp reference.
    """
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    d = q.shape[-1]
    block_size = k_pages.shape[1]
    mode = serve_kernels_mode()
    on_tpu = jax.default_backend() == "tpu"
    use_kernel = mode == "on" or (mode == "auto" and on_tpu
                                  and (d % 128 == 0 or _INTERPRET))
    if use_kernel:
        try:
            if (k_scale is None and on_tpu and not _INTERPRET
                    and d % 128 == 0 and block_size % 8 == 0):
                return _paged_decode_tpu(q, k_pages, v_pages, block_tables,
                                         seq_lens, scale)
            return _paged_decode_packed(q, k_pages, v_pages, block_tables,
                                        seq_lens, scale, k_scale=k_scale,
                                        v_scale=v_scale)
        except Exception as e:  # noqa: BLE001 - fall back rather than fail
            logging.getLogger(__name__).warning(
                "pallas paged decode failed (%s: %s); falling back to jnp "
                "reference", type(e).__name__, e)
    return _paged_decode_reference(q, k_pages, v_pages, block_tables,
                                   seq_lens, scale, k_scale=k_scale,
                                   v_scale=v_scale)
