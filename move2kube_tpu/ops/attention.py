"""Flash attention as a Pallas TPU kernel.

Blockwise attention with online softmax: for each Q block the kernel scans
K/V blocks resident in VMEM, maintaining running max / sum / accumulator,
so the full [seq, seq] score matrix never touches HBM. Scores accumulate in
float32 on the MXU (pallas_guide.md: "Math and Compute Operations" —
jnp.dot with preferred_element_type=jnp.float32; tiling constraints
(8/16, 128) motivate the 128-multiple block sizes).

Off-TPU (tests run on a CPU mesh) the public entrypoint falls back to a
mathematically identical jnp implementation.
"""

from __future__ import annotations

import functools
import logging

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def _reference_attention(q, k, v, causal: bool, scale: float):
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        qi = jnp.arange(q.shape[1])[:, None]
        ki = jnp.arange(k.shape[1])[None, :]
        s = jnp.where(qi >= ki, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, causal: bool,
                  scale: float, q_block_idx_axis: int):
    """One (batch*head, q_block) grid cell; scans K blocks.

    Refs are [block_q, d] / [seq_k, d] slices staged into VMEM by BlockSpec.
    """
    from jax.experimental import pallas as pl

    block_q, d = q_ref.shape
    seq_k = k_ref.shape[0]
    q = q_ref[:].astype(jnp.float32) * scale
    qi = pl.program_id(q_block_idx_axis) * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)

    def body(start, carry):
        o_acc, m_acc, l_acc = carry
        k_blk = k_ref[pl.dslice(start * block_k, block_k), :]
        v_blk = v_ref[pl.dslice(start * block_k, block_k), :]
        s = jnp.dot(q, k_blk.astype(jnp.float32).T,
                    preferred_element_type=jnp.float32)  # [bq, bk]
        if causal:
            ki = start * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(qi >= ki, s, _NEG_INF)
        m_blk = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_acc, m_blk)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_acc - m_new)
        l_new = l_acc * alpha + jnp.sum(p, axis=-1, keepdims=True)
        o_new = o_acc * alpha + jnp.dot(
            p, v_blk.astype(jnp.float32), preferred_element_type=jnp.float32)
        return o_new, m_new, l_new

    n_blocks = seq_k // block_k
    if causal:
        # only scan blocks that intersect the causal frontier
        last_needed = (pl.program_id(q_block_idx_axis) + 1) * block_q
        n_needed = jax.lax.div(last_needed + block_k - 1, block_k)
        n_iter = jnp.minimum(n_blocks, n_needed)
    else:
        n_iter = n_blocks
    o0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    o, m, l = jax.lax.fori_loop(0, n_iter, body, (o0, m0, l0))
    o_ref[:] = (o / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_attention_tpu(q, k, v, causal: bool, scale: float,
                         block_q: int = 128, block_k: int = 128,
                         interpret: bool = False):
    """``interpret=True`` runs the kernel body through the Pallas
    interpreter on any backend — how CI validates the actual kernel math
    without silicon (tests/test_models.py)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, s, h, d = q.shape
    sk = k.shape[1]
    block_q = min(block_q, s)
    block_k = min(block_k, sk)
    # [b, s, h, d] -> [b*h, s, d] so the grid is (bh, q_blocks)
    def merge(t):
        return t.transpose(0, 2, 1, 3).reshape(b * h, t.shape[1], d)

    qm, km, vm = merge(q), merge(k), merge(v)
    grid = (b * h, s // block_q)
    out = pl.pallas_call(
        functools.partial(_flash_kernel, block_k=block_k, causal=causal,
                          scale=scale, q_block_idx_axis=1),
        out_shape=jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, sk, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, sk, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
        interpret=interpret,
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
    )(qm, km, vm)
    return out.reshape(b, h, s, d).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_attention_diff(q, k, v, causal: bool, scale: float):
    """Differentiable wrapper: Pallas kernels have no automatic reverse-
    mode rule, so without this custom_vjp ``jax.grad`` through a training
    step would fail at trace time on TPU."""
    return _flash_attention_tpu(q, k, v, causal, scale)


def _flash_diff_fwd(q, k, v, causal, scale):
    return _flash_attention_tpu(q, k, v, causal, scale), (q, k, v)


def _flash_diff_bwd(causal, scale, residuals, g):
    # exact attention backward via the reference math (recompute, no
    # saved probabilities). The [b, h, s, s] score matrix is transient
    # and freed per layer; a fused Pallas backward kernel can replace
    # this without touching callers.
    q, k, v = residuals
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _reference_attention(q_, k_, v_, causal, scale),
        q, k, v)
    return vjp(g)


_flash_attention_diff.defvjp(_flash_diff_fwd, _flash_diff_bwd)


def flash_attention(q, k, v, *, causal: bool = False, scale: float | None = None):
    """Fused attention. q/k/v: [batch, seq, heads, head_dim].

    Uses the Pallas kernel on TPU when shapes are tile-friendly (seq a
    multiple of 128, head_dim >= 64); otherwise the jnp reference (which
    XLA still fuses reasonably well). Differentiable on both paths.
    """
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    on_tpu = jax.default_backend() == "tpu"
    s, d = q.shape[1], q.shape[3]
    if on_tpu and s % 128 == 0 and k.shape[1] % 128 == 0 and d % 64 == 0:
        try:
            return _flash_attention_diff(q, k, v, causal, scale)
        except Exception as e:  # noqa: BLE001 - fall back rather than fail
            logging.getLogger(__name__).warning(
                "pallas flash attention failed (%s: %s); falling back to "
                "jnp reference attention", type(e).__name__, e)
    return _reference_attention(q, k, v, causal, scale)
