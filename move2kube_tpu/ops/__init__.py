"""Pallas TPU kernels for the hot ops, with jnp fallbacks off-TPU.

XLA fuses most elementwise chains into the MXU matmuls already; kernels
live here only where fusion can't reach: flash attention (blockwise
softmax-matmul with online normalisation keeps the [s, s] score matrix out
of HBM entirely).
"""

from move2kube_tpu.ops.attention import flash_attention  # noqa: F401
from move2kube_tpu.ops.crossentropy import (  # noqa: F401
    fused_cross_entropy,
    fused_linear_cross_entropy,
)
