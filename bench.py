#!/usr/bin/env python
"""Benchmark: ResNet-50 training throughput on the attached TPU chip.

This is BASELINE config 2 ("PyTorch ResNet-50 CUDA train.py -> jax-xla
containerizer, single v5e chip") driven through the same model-zoo code the
containerizer vendors into emitted images — i.e. it measures what a
translated workload actually achieves.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference (Move2Kube) publishes no performance numbers (BASELINE.md);
``vs_baseline`` is therefore measured against the BASELINE.json north-star
criterion — parity with a hand-ported JAX ResNet-50 on v5e-1. The
hand-ported baseline constant below was set from the first measured run of
this exact program (it IS the hand-port: straight flax/optax, bf16, no
framework overhead), so vs_baseline == value / HAND_PORTED_IMG_S.
"""

import json
import sys
import time

HAND_PORTED_IMG_S = 2014.6  # measured r1 on v5e-1 (see BENCH_NOTES.md)

BATCH = 128
IMAGE = 224
WARMUP_STEPS = 3
MEASURE_STEPS = 20


def main() -> int:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from move2kube_tpu.models import train as m2kt_train
    from move2kube_tpu.models.resnet import resnet50
    from move2kube_tpu.parallel.mesh import MeshConfig, make_mesh

    n = jax.device_count()
    mesh = make_mesh(MeshConfig(data=n))
    model = resnet50(num_classes=1000)
    state = m2kt_train.create_sharded_state(
        jax.random.PRNGKey(0), model,
        {"x": jnp.zeros((BATCH, IMAGE, IMAGE, 3), jnp.float32), "train": False},
        optax.sgd(0.1, momentum=0.9), mesh, has_batch_stats=True,
    )
    step = m2kt_train.make_classifier_train_step(mesh, has_batch_stats=True)
    gen = np.random.default_rng(0)
    batch = {
        "input": jnp.asarray(gen.random((BATCH, IMAGE, IMAGE, 3), np.float32)),
        "label": jnp.asarray(gen.integers(0, 1000, BATCH)),
    }
    for _ in range(WARMUP_STEPS):
        state, loss = step(state, batch)
    # device->host transfer, NOT block_until_ready: remote-tunnel backends
    # can report ready before execution completes, a transfer cannot lie
    float(loss)
    t0 = time.perf_counter()
    for _ in range(MEASURE_STEPS):
        state, loss = step(state, batch)
    final_loss = float(loss)
    dt = time.perf_counter() - t0
    img_s = MEASURE_STEPS * BATCH / dt
    if final_loss != final_loss:  # NaN: refuse to report a throughput
        raise RuntimeError(f"training diverged: loss={final_loss}")
    print(json.dumps({
        "metric": "resnet50_train_throughput_v5e1",
        "value": round(img_s, 1),
        "unit": "img/s",
        "vs_baseline": round(img_s / HAND_PORTED_IMG_S, 3),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
