#!/usr/bin/env python
"""Benchmark: translated-workload training throughput on the attached TPU.

Measures BASELINE config 2 (PyTorch ResNet-50 CUDA train.py -> jax-xla
containerizer, single v5e chip, img/s) as the primary metric, plus in
``extra``: BASELINE config 3 (HF BERT fine-tune, samples/s), a Pallas
flash-attention on-silicon proof (fwd + blockwise bwd vs the jnp
reference, TFLOP/s, and vs_official_kernel against the public hand-
written TPU kernel), and a long-context Llama-class training phase
(config 5's single-chip analogue: attn_impl="flash" drives the Pallas
fwd AND bwd kernels inside a real remat+AdamW train step, tokens/s) —
all from ONE plain ``python bench.py`` invocation. The model phases
drive the same model-zoo code the containerizer vendors into emitted
images, i.e. they measure what a translated workload actually achieves.

Prints exactly ONE JSON line on stdout:
  {"metric", "value", "unit", "vs_baseline", "extra": {...}}
and NEVER exits non-zero for backend trouble: on total failure the line
carries value 0 and ``extra.status`` explaining why (rounds 1 and 2 both
died rc=1 with no artifact; this harness treats every phase as retryable).

Architecture: the parent process (this file, no args) NEVER imports jax.
It spawns a child (``--child phase,phase``) that does backend init,
compile and the timed loop, and prints one ``RESULT {json}`` line per
completed phase (flushed immediately). The tunneled TPU plugin has two
failure modes — fast RuntimeError(UNAVAILABLE) and a plain hang inside
make_c_api_client — and a hung C call cannot be interrupted in-process,
so the parent enforces a timeout per child, harvests whatever RESULT
lines arrived, and retries only the missing phases until a wall-clock
deadline (default 440s, driver kills around 560s).

The reference (Move2Kube) publishes no performance numbers (BASELINE.md),
so ``vs_baseline`` is anchored to an external roofline-derived number for
a well-tuned single-chip JAX run rather than to this program's own first
run: TPU v5e peak is 197 bf16 TFLOP/s and well-tuned models sustain ~30%
MFU. ResNet-50 @ 224x224 is ~12.3 GFLOP/img fwd+bwd => anchor 4805 img/s.
BERT-base @ seq 128 is ~84.5 GFLOP/sample => anchor 700 samples/s. See
BENCH_NOTES.md.
"""

import argparse
import json
import os
import subprocess
import sys
import time

V5E_PEAK_BF16_FLOPS = 197e12
ANCHOR_MFU = 0.30  # well-tuned MFU on TPU (see BENCH_NOTES.md)

RESNET50_FLOPS_PER_IMG = 12.3e9  # fwd+bwd at 224x224 (3x fwd of 4.1 GFLOP)
BERT_SEQ = 128
BERT_FLOPS_PER_SAMPLE = 6 * 110e6 * BERT_SEQ  # 6*N*T rule, bert-base N=110M

RESNET_BATCH = int(os.environ.get("M2KT_BENCH_RESNET_BATCH", "256"))
RESNET_IMAGE = int(os.environ.get("M2KT_BENCH_RESNET_IMAGE", "224"))
BERT_BATCH = int(os.environ.get("M2KT_BENCH_BERT_BATCH", "128"))

# optimizer steps fused into one device call (lax.scan)
SCAN_STEPS = int(os.environ.get("M2KT_BENCH_SCAN_STEPS", "10"))
# adaptive warmup: the tunneled backend streams executables/weights on
# the first call or two after compile (observed: 20-30s for calls the
# steady state runs in 0.7s), so warm until a call is fast or the cap
# is hit — a fixed single warmup under-reports throughput ~10x
MAX_WARMUP_CALLS = int(os.environ.get("M2KT_BENCH_MAX_WARMUP", "4"))
WARM_FAST_S = float(os.environ.get("M2KT_BENCH_WARM_FAST_S", "3.0"))
MEASURE_CALLS = int(os.environ.get("M2KT_BENCH_MEASURE_CALLS", "3"))

PHASES = ("resnet", "bert", "pallas", "llama", "translate", "goodput",
          "scaling", "serving", "fleet", "quant", "kernels", "obs",
          "chaos", "swap", "numerics", "sched", "autoscale", "usage")
# single source of truth for each phase's reported metric name + unit,
# shared by the measurement functions and the parent's failure fallback
PHASE_METRICS = {
    "resnet": ("resnet50_train_throughput_v5e1", "img/s"),
    "bert": ("bert_finetune_throughput_v5e1", "samples/s"),
    "pallas": ("pallas_flash_attention_tflops_v5e1", "TFLOP/s"),
    "llama": ("llama_train_throughput_v5e1", "tokens/s"),
    "translate": ("gpu2tpu_translate_throughput", "services/s"),
    "goodput": ("train_goodput_fraction_faulted", "fraction"),
    "scaling": ("multichip_scaling_efficiency_host8", "fraction"),
    "serving": ("decode_throughput_tokens_s", "tok/s"),
    "fleet": ("fleet_p95_ttft_speedup_prefix_cache", "x"),
    "quant": ("int8_decode_speedup_vs_fp32", "x"),
    "kernels": ("fused_paged_decode_speedup_vs_ref", "x"),
    "obs": ("telemetry_overhead_fraction", "fraction"),
    "chaos": ("chaos_recovered_token_exact_fraction", "fraction"),
    "swap": ("swap_cold_join_ttft_speedup", "x"),
    "numerics": ("numerics_telemetry_overhead_fraction", "fraction"),
    "sched": ("multilora_aggregate_tokens_s", "tok/s"),
    "autoscale": ("autoscale_replica_hours_saving", "fraction"),
    "usage": ("usage_replay_fidelity_err", "fraction"),
}
# phases that need the TPU backend; "translate" is pure-CPU tool work and
# runs in a child with the TPU plugin hook disabled, so a hung tunnel can
# never cost the artifact its one always-measurable number
TPU_PHASES = ("resnet", "bert", "pallas", "llama")
# On-silicon results captured opportunistically during a builder session
# (``--opportunistic``): when the tunnel is down at the driver's single
# end-of-round invocation, run_parent folds these in (clearly labeled
# with the capture timestamp) instead of reporting zeros — a down-window
# at round end must not erase numbers a live window already produced.
OPPORTUNISTIC_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "BENCH_OPPORTUNISTIC.json")
BUDGET_S = float(os.environ.get("M2KT_BENCH_BUDGET_S", "440"))
CHILD_TIMEOUT_S = float(os.environ.get("M2KT_BENCH_CHILD_TIMEOUT_S", "240"))
RETRY_BACKOFF_S = 15.0

RESNET_ANCHOR = V5E_PEAK_BF16_FLOPS * ANCHOR_MFU / RESNET50_FLOPS_PER_IMG
BERT_ANCHOR = V5E_PEAK_BF16_FLOPS * ANCHOR_MFU / BERT_FLOPS_PER_SAMPLE


# --------------------------------------------------------------------------
# Child: real measurement. Runs in a subprocess the parent can kill.
# --------------------------------------------------------------------------

def _emit(result: dict) -> None:
    print("RESULT " + json.dumps(result), flush=True)


def _measure(step, state, batches, items_per_step: int):
    """Timed loop. Timing boundaries force a device->host transfer, NOT
    block_until_ready: remote-tunnel backends can report ready before
    execution completes, a transfer cannot lie."""
    for i in range(MAX_WARMUP_CALLS):
        t0 = time.perf_counter()
        state, losses = step(state, batches)
        float(losses[-1])
        dt = time.perf_counter() - t0
        if dt < WARM_FAST_S:
            break
        print(f"[bench] warmup call {i}: {dt:.1f}s", file=sys.stderr)
    t0 = time.perf_counter()
    for _ in range(MEASURE_CALLS):
        state, losses = step(state, batches)
    final_loss = float(losses[-1])
    dt = time.perf_counter() - t0
    if final_loss != final_loss:  # NaN: refuse to report a throughput
        raise RuntimeError(f"training diverged: loss={final_loss}")
    throughput = MEASURE_CALLS * SCAN_STEPS * items_per_step / dt
    return throughput, final_loss


def _is_oom(e: Exception) -> bool:
    return "RESOURCE_EXHAUSTED" in str(e) or "Out of memory" in str(e)


def _with_batch_fallback(measure_at, batch: int, min_batch: int = 32,
                         phase: str = ""):
    """Run ``measure_at(batch)``, halving the batch on device OOM — a too-
    ambitious default batch must degrade the number, not zero it. Each
    halving is announced on stdout (OOMBATCH line) so the parent can
    restart a timed-out child directly at the reduced batch instead of
    replaying the known-OOM sizes."""
    while True:
        try:
            return measure_at(batch), batch
        except Exception as e:  # noqa: BLE001 - only OOM is retryable
            if not _is_oom(e) or batch // 2 < min_batch:
                raise
            batch //= 2
            if phase:
                print("OOMBATCH " + json.dumps(
                    {"phase": phase, "batch": batch}), flush=True)
            print(f"[bench] OOM; retrying {phase} at batch {batch}",
                  file=sys.stderr)


def _official_style_resnet50():
    """Hand-ported comparator: ResNet-50 exactly as the public Flax
    imagenet example writes it (bf16 convs AND bf16-compute BatchNorm
    with f32 params, zero-init residual BN scale) — independent of the
    framework's model zoo and train machinery. The north-star bar
    (BASELINE.json: >= 90% of hand-ported MFU) is measured against THIS
    on the same chip in the same session, the way the pallas phase
    measures vs_official_kernel."""
    import functools

    import flax.linen as nn
    import jax.numpy as jnp

    class Block(nn.Module):
        features: int
        strides: int = 1

        @nn.compact
        def __call__(self, x, train=True):
            norm = functools.partial(
                nn.BatchNorm, use_running_average=not train, momentum=0.9,
                epsilon=1e-5, dtype=jnp.bfloat16)
            conv = functools.partial(nn.Conv, use_bias=False,
                                     dtype=jnp.bfloat16)
            residual = x
            y = nn.relu(norm()(conv(self.features, (1, 1))(x)))
            y = nn.relu(norm()(conv(self.features, (3, 3),
                                    strides=(self.strides, self.strides))(y)))
            y = norm(scale_init=nn.initializers.zeros)(
                conv(self.features * 4, (1, 1))(y))
            if residual.shape != y.shape:
                residual = norm()(conv(self.features * 4, (1, 1),
                                       strides=(self.strides,
                                                self.strides))(residual))
            return nn.relu(residual + y)

    class OfficialResNet50(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            x = nn.Conv(64, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                        use_bias=False, dtype=jnp.bfloat16)(
                            x.astype(jnp.bfloat16))
            x = nn.relu(nn.BatchNorm(use_running_average=not train,
                                     momentum=0.9, epsilon=1e-5,
                                     dtype=jnp.bfloat16)(x))
            x = nn.max_pool(x, (3, 3), (2, 2), padding=[(1, 1), (1, 1)])
            for i, n_blocks in enumerate([3, 4, 6, 3]):
                for j in range(n_blocks):
                    x = Block(64 * 2 ** i,
                              strides=2 if i > 0 and j == 0 else 1)(x, train)
            x = jnp.mean(x, axis=(1, 2))
            return nn.Dense(1000, dtype=jnp.float32)(x)

    return OfficialResNet50()


def _bench_official_resnet(batch: int) -> float:
    """img/s of the hand-ported comparator: plain jit + lax.scan SGD loop,
    no framework machinery (no mesh, no sharded init, no TrainState)."""
    import functools

    import jax
    import jax.numpy as jnp
    import optax

    image = RESNET_IMAGE
    model = _official_style_resnet50()
    variables = jax.jit(lambda k, x: model.init(k, x, train=False))(
        jax.random.PRNGKey(0), jnp.zeros((batch, image, image, 3),
                                         jnp.bfloat16))
    params, batch_stats = variables["params"], variables["batch_stats"]
    tx = optax.sgd(0.1, momentum=0.9)
    opt_state = tx.init(params)

    def one_step(carry, b):
        params, batch_stats, opt_state = carry

        def loss_fn(p):
            logits, upd = model.apply(
                {"params": p, "batch_stats": batch_stats}, b["input"],
                mutable=["batch_stats"])
            logp = jax.nn.log_softmax(logits.astype(jnp.float32))
            picked = jnp.take_along_axis(logp, b["label"][:, None], axis=-1)
            return -jnp.mean(picked), upd["batch_stats"]

        (loss, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return (optax.apply_updates(params, updates), new_stats,
                opt_state), loss

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(carry, batches):
        return jax.lax.scan(one_step, carry, batches, length=SCAN_STEPS)

    make = jax.jit(lambda key: {
        "input": jax.random.uniform(
            key, (SCAN_STEPS, batch, image, image, 3), jnp.bfloat16),
        "label": jax.random.randint(
            key, (SCAN_STEPS, batch), 0, 1000, jnp.int32)})
    batches = make(jax.random.PRNGKey(1))
    float(jnp.sum(batches["label"]))  # transfer = true sync
    carry = (params, batch_stats, opt_state)
    # shared warmup/timing loop (keeps _measure's NaN-divergence guard)
    img_s, _loss = _measure(step, carry, batches, batch)
    return img_s


def bench_resnet(n: int) -> dict:
    import jax
    import jax.numpy as jnp
    import optax

    from move2kube_tpu.models import train as m2kt_train
    from move2kube_tpu.models.resnet import resnet50
    from move2kube_tpu.parallel.mesh import MeshConfig, make_mesh

    image = RESNET_IMAGE
    mesh = make_mesh(MeshConfig(data=n))
    model = resnet50(num_classes=1000)

    def measure_at(batch: int):
        state = m2kt_train.create_sharded_state(
            jax.random.PRNGKey(0), model,
            {"x": jnp.zeros((batch, image, image, 3), jnp.bfloat16),
             "train": False},
            optax.sgd(0.1, momentum=0.9), mesh, has_batch_stats=True,
        )
        step = m2kt_train.make_classifier_train_step(
            mesh, has_batch_stats=True, scan_steps=SCAN_STEPS)
        # batches generated ON DEVICE: the tunnel's host->device path
        # runs at ~0.03 GB/s (measured), so staging 1.5GB of host data
        # would eat the phase budget without measuring anything
        make = jax.jit(lambda key: {
            "input": jax.random.uniform(
                key, (SCAN_STEPS, batch, image, image, 3), jnp.bfloat16),
            "label": jax.random.randint(
                key, (SCAN_STEPS, batch), 0, 1000, jnp.int32),
        })
        batches = make(jax.random.PRNGKey(1))
        float(jnp.sum(batches["label"]))  # transfer = true sync
        return _measure(step, state, batches, batch)

    (img_s, loss), batch = _with_batch_fallback(measure_at, RESNET_BATCH,
                                                phase="resnet")
    mfu = img_s * RESNET50_FLOPS_PER_IMG / V5E_PEAK_BF16_FLOPS
    print(f"[bench] resnet loss={loss:.3f} mfu={mfu:.1%}", file=sys.stderr)
    metric, unit = PHASE_METRICS["resnet"]
    result = {
        "phase": "resnet",
        "metric": metric,
        "value": round(img_s, 1),
        "unit": unit,
        "mfu": round(mfu, 4),
        "batch": batch,
        "vs_baseline": round(img_s / RESNET_ANCHOR, 3),
    }
    # north-star comparison (BASELINE.json: >= 90% of hand-ported MFU):
    # the official-recipe hand-port, same batch/chip/session — the conv
    # analogue of the pallas phase's vs_official_kernel. Best-effort: a
    # comparator failure must not cost the phase its primary number, and
    # neither may a comparator HANG — flush the primary result line
    # before measuring it (the parent keeps the LAST RESULT per phase,
    # so the enriched line below supersedes this one when it lands).
    if os.environ.get("M2KT_BENCH_RESNET_CMP", "1") not in ("", "0"):
        _emit(result)
        try:
            official_img_s = _bench_official_resnet(batch)
            result["official_img_s"] = round(official_img_s, 1)
            result["vs_official_resnet"] = round(img_s / official_img_s, 3)
            print(f"[bench] resnet comparator {official_img_s:.1f} img/s "
                  f"vs_official_resnet={result['vs_official_resnet']}",
                  file=sys.stderr)
        except Exception as e:  # noqa: BLE001 - comparison is best-effort
            print(f"[bench] official-resnet comparison failed: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
    return result


def bench_bert(n: int) -> dict:
    import jax
    import jax.numpy as jnp
    import optax

    from move2kube_tpu.models import train as m2kt_train
    from move2kube_tpu.models.bert import bert_base
    from move2kube_tpu.parallel.mesh import MeshConfig, make_mesh

    mesh = make_mesh(MeshConfig(data=n))
    model = bert_base(num_classes=2)

    def measure_at(batch: int):
        ids0 = jnp.zeros((batch, BERT_SEQ), jnp.int32)
        state = m2kt_train.create_sharded_state(
            jax.random.PRNGKey(0), model, {"input_ids": ids0},
            optax.adamw(2e-5), mesh,
        )
        step = m2kt_train.make_bert_train_step(mesh, scan_steps=SCAN_STEPS)
        # on-device batches (see bench_resnet: 0.03 GB/s h2d tunnel)
        make = jax.jit(lambda key: {
            "input_ids": jax.random.randint(
                key, (SCAN_STEPS, batch, BERT_SEQ), 0, 30522, jnp.int32),
            "attention_mask": jnp.ones((SCAN_STEPS, batch, BERT_SEQ), bool),
            "label": jax.random.randint(
                key, (SCAN_STEPS, batch), 0, 2, jnp.int32),
        })
        batches = make(jax.random.PRNGKey(1))
        float(jnp.sum(batches["label"]))  # transfer = true sync
        return _measure(step, state, batches, batch)

    (samples_s, loss), batch = _with_batch_fallback(measure_at, BERT_BATCH,
                                                    phase="bert")
    mfu = samples_s * BERT_FLOPS_PER_SAMPLE / V5E_PEAK_BF16_FLOPS
    print(f"[bench] bert loss={loss:.3f} mfu={mfu:.1%}", file=sys.stderr)
    metric, unit = PHASE_METRICS["bert"]
    return {
        "phase": "bert",
        "metric": metric,
        "value": round(samples_s, 1),
        "unit": unit,
        "mfu": round(mfu, 4),
        "batch": batch,
        "vs_baseline": round(samples_s / BERT_ANCHOR, 3),
    }


LLAMA_BATCH = int(os.environ.get("M2KT_BENCH_LLAMA_BATCH", "4"))
LLAMA_SEQ = int(os.environ.get("M2KT_BENCH_LLAMA_SEQ", "2048"))


def bench_llama(n: int) -> dict:
    """Decoder-LM training throughput at long context: a ~200M-param
    Llama-class model with attn_impl="flash", so the Pallas forward AND
    blockwise backward kernels run inside a REAL jitted train step (remat
    + AdamW), not just the pallas phase's isolated grad check. The 6*N*T
    rule anchors vs_baseline the same way as BERT."""
    import jax
    import jax.numpy as jnp
    import optax

    from move2kube_tpu.models import train as m2kt_train
    from move2kube_tpu.models.llama import Llama, LlamaConfig
    from move2kube_tpu.parallel.mesh import MeshConfig, make_mesh

    mesh = make_mesh(MeshConfig(data=n))
    cfg = LlamaConfig(
        vocab_size=32000, d_model=1024, num_layers=8, num_heads=16,
        num_kv_heads=8, mlp_dim=2816, max_len=LLAMA_SEQ,
        attn_impl="flash")

    def n_params(c):
        per_layer = (c.d_model * (c.num_heads + 2 * c.num_kv_heads)
                     * (c.d_model // c.num_heads)   # qkv
                     + c.d_model * c.d_model         # attn_out
                     + 3 * c.d_model * c.mlp_dim)    # gate_up + down
        return (c.vocab_size * c.d_model * 2         # embed + lm_head
                + c.num_layers * per_layer)

    flops_per_token = 6 * n_params(cfg)
    cost_holder: dict = {}

    def measure_at(batch: int):
        ids0 = jnp.zeros((batch, LLAMA_SEQ), jnp.int32)
        state = m2kt_train.create_sharded_state(
            jax.random.PRNGKey(0), Llama(cfg), {"input_ids": ids0},
            optax.adamw(3e-4), mesh)
        step = m2kt_train.make_lm_train_step(mesh)
        make = jax.jit(lambda key: {"input_ids": jax.random.randint(
            key, (batch, LLAMA_SEQ), 0, cfg.vocab_size, jnp.int32)})
        batch_data = make(jax.random.PRNGKey(1))
        float(jnp.sum(batch_data["input_ids"]))  # transfer = true sync
        # no scan wrapper here (make_lm_train_step is single-step); the
        # adaptive warmup below absorbs executable streaming, and each
        # measured call is seconds long so dispatch latency is noise
        for i in range(MAX_WARMUP_CALLS):
            t0 = time.perf_counter()
            state, loss = step(state, batch_data)
            float(loss)
            dt = time.perf_counter() - t0
            if dt < WARM_FAST_S:
                break
            print(f"[bench] llama warmup call {i}: {dt:.1f}s",
                  file=sys.stderr)
        # compiled-program cost model (analyze_step_fn is exception-safe
        # and returns None when the backend exposes no cost analysis)
        from move2kube_tpu.obs import costmodel
        cost_holder["report"] = costmodel.analyze_step_fn(
            step, state, batch_data)
        # fused-CE memory delta: compile (never run) the SAME step with
        # the reference [B,T,V] logit loss and compare compiled HBM
        # peaks. vocab=32000 >> the 2048 chunk, so the default path
        # above dispatched the chunked lm-head CE (ops/crossentropy.py);
        # best-effort — a lowering failure must not cost the phase.
        prev_ce = os.environ.get("M2KT_FUSED_CE")
        try:
            os.environ["M2KT_FUSED_CE"] = "off"
            ref_report = costmodel.analyze_step_fn(
                m2kt_train.make_lm_train_step(mesh), state, batch_data)
            if ref_report is not None:
                cost_holder["reference_hbm"] = ref_report.peak_hbm_bytes
        except Exception as e:  # noqa: BLE001 - comparison is best-effort
            print(f"[bench] reference-CE compile failed: {e}",
                  file=sys.stderr)
        finally:
            if prev_ce is None:
                os.environ.pop("M2KT_FUSED_CE", None)
            else:
                os.environ["M2KT_FUSED_CE"] = prev_ce
        t0 = time.perf_counter()
        for _ in range(MEASURE_CALLS):
            state, loss = step(state, batch_data)
        final_loss = float(loss)
        dt = time.perf_counter() - t0
        if final_loss != final_loss:
            raise RuntimeError(f"training diverged: loss={final_loss}")
        return MEASURE_CALLS * batch * LLAMA_SEQ / dt, final_loss

    (tok_s, loss), batch = _with_batch_fallback(measure_at, LLAMA_BATCH,
                                                min_batch=1, phase="llama")
    mfu = tok_s * flops_per_token / V5E_PEAK_BF16_FLOPS
    print(f"[bench] llama loss={loss:.3f} mfu={mfu:.1%}", file=sys.stderr)
    # the measured counterpart of the analytic 6*N*T mfu above: XLA's own
    # per-step flop count over the measured step time, plus the compiled
    # peak-HBM footprint. Null on backends without cost analysis.
    from move2kube_tpu.obs import costmodel
    train_mfu = train_hbm = None
    ref_hbm = cost_holder.get("reference_hbm")
    report = cost_holder.get("report")
    if report is not None:
        spec, _ = costmodel.chip_spec(
            os.environ.get(costmodel.ACCELERATOR_ENV, ""))
        train_mfu = report.mfu(batch * LLAMA_SEQ / tok_s, spec)
        train_hbm = report.peak_hbm_bytes
    metric, unit = PHASE_METRICS["llama"]
    anchor = V5E_PEAK_BF16_FLOPS * ANCHOR_MFU / flops_per_token
    return {
        "phase": "llama",
        "metric": metric,
        "value": round(tok_s, 1),
        "unit": unit,
        "mfu": round(mfu, 4),
        "train_mfu": round(train_mfu, 6) if train_mfu is not None else None,
        "train_hbm_peak_bytes": train_hbm,
        # compiled HBM peak of the same step with the reference
        # materialized-logits loss; the ratio is the chunked-CE win
        "train_hbm_peak_bytes_reference_ce": ref_hbm,
        "fused_ce_hbm_ratio": (round(ref_hbm / train_hbm, 3)
                               if ref_hbm and train_hbm else None),
        "batch": batch,
        "seq_len": LLAMA_SEQ,
        "vs_baseline": round(tok_s / anchor, 3),
    }


def bench_pallas(n: int) -> dict:
    """Prove the Pallas flash-attention kernels on silicon: forward AND
    blockwise backward (via the custom_vjp), compared against the jnp
    reference, then report forward TFLOP/s with the per-dispatch tunnel
    latency (~2.4ms measured) amortized by scanning the kernel inside
    one jit."""
    import jax
    import jax.numpy as jnp

    from move2kube_tpu.ops.attention import (
        _flash_attention_diff, _flash_attention_tpu, _reference_attention)

    metric, unit = PHASE_METRICS["pallas"]
    if jax.default_backend() != "tpu":
        return {"phase": "pallas", "metric": metric, "value": 0,
                "unit": unit, "vs_baseline": 0.0,
                "status": "skipped_not_tpu", "backend": jax.default_backend()}

    b, s, h, d = 8, 2048, 8, 64
    scale = d ** -0.5
    keys = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(key, (b, s, h, d), jnp.bfloat16)
               for key in keys)
    kernel = jax.jit(lambda q, k, v: _flash_attention_tpu(q, k, v, True, scale))
    ref = jax.jit(lambda q, k, v: _reference_attention(q, k, v, True, scale))
    out = kernel(q, k, v)
    expect = ref(q, k, v)
    err = float(jnp.max(jnp.abs(out.astype(jnp.float32)
                                - expect.astype(jnp.float32))))
    # bf16 inputs, f32 accumulation: online-softmax reassociation keeps the
    # error at the bf16 resolution of the output (~1/128 of max |o|<=~1).
    # `not (err <= tol)` so NaN fails instead of slipping past `err > tol`
    tol = 2e-2
    if not (err <= tol):
        raise RuntimeError(f"pallas kernel mismatch: max_abs_err={err}")

    # backward kernels (dq/dk/dv blockwise, lse recompute) on silicon:
    # grads of the kernel path must match grads of the reference
    def loss_kernel(q, k, v):
        return jnp.sum(_flash_attention_diff(q, k, v, True, scale)
                       .astype(jnp.float32) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_reference_attention(q, k, v, True, scale)
                       .astype(jnp.float32) ** 2)

    gk = jax.jit(jax.grad(loss_kernel, argnums=(0, 1, 2)))(q, k, v)
    gr = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
    # grads scale with |dO|~2*s_q... compare relative to the ref magnitude
    bwd_err = max(
        float(jnp.max(jnp.abs(a.astype(jnp.float32)
                              - b_.astype(jnp.float32))))
        / max(1.0, float(jnp.max(jnp.abs(b_.astype(jnp.float32)))))
        for a, b_ in zip(gk, gr))
    bwd_tol = 4e-2  # bf16 grad resolution, relative
    if not (bwd_err <= bwd_tol):
        raise RuntimeError(
            f"pallas backward mismatch: rel_err={bwd_err}")

    # throughput: scan the kernel K times inside ONE jit so the ~2.4ms
    # per-dispatch tunnel roundtrip doesn't dominate the measurement
    # (o has q's shape, so it feeds back as the next query block)
    scan_iters = 10
    official_tflops = None

    def timed_tflops(call):
        run = jax.jit(lambda q, k, v: jax.lax.scan(
            lambda c, _: (call(c, k, v), None), q, None,
            length=scan_iters)[0])
        float(jnp.sum(run(q, k, v)))  # warm (compile + exe streaming)
        float(jnp.sum(run(q, k, v)))  # warm (steady state)
        iters = 4
        t0 = time.perf_counter()
        for _ in range(iters):
            out = run(q, k, v)
        float(jnp.sum(out))
        dt = time.perf_counter() - t0
        # causal fwd flops: 2 matmuls * 2 flops/MAC * b*h*s*s*d, /2 mask
        flops = 2 * 2 * b * h * s * s * d / 2
        return flops * scan_iters * iters / dt / 1e12

    tflops = timed_tflops(
        lambda c, k, v: _flash_attention_tpu(c, k, v, True, scale))

    # backward throughput: full grad (forward recompute + the dq and
    # dk/dv kernels) scanned inside one jit, same dispatch-amortization
    # as the forward number. This is the path the flash_bwd autotuner
    # (ops/attention.py get_bwd_block_sizes) feeds — its sweep runs at
    # trace time here, so the reported TFLOP/s uses the tuned blocks.
    def timed_bwd_tflops():
        grad_fn = jax.grad(loss_kernel, argnums=(0, 1, 2))

        def one(c, _):
            dq, _dk, _dv = grad_fn(c, k, v)
            # renormalize the carry so scanned grads stay finite
            c2 = (dq / (jnp.max(jnp.abs(dq)) + 1e-6)).astype(c.dtype)
            return c2, None

        run = jax.jit(lambda q: jax.lax.scan(one, q, None,
                                             length=scan_iters)[0])
        float(jnp.sum(run(q)))  # warm (compile + sweep + streaming)
        float(jnp.sum(run(q)))  # warm (steady state)
        iters = 4
        t0 = time.perf_counter()
        for _ in range(iters):
            out = run(q)
        float(jnp.sum(out))
        dt = time.perf_counter() - t0
        # causal grad flops: 2 fwd-recompute + 5 bwd matmuls
        # (dv, dp, ds->dq, ds->dk, score recompute), 2 flops/MAC, /2 mask
        flops = 7 * 2 * b * h * s * s * d / 2
        return flops * scan_iters * iters / dt / 1e12

    bwd_tflops = None
    try:
        bwd_tflops = round(timed_bwd_tflops(), 2)
    except Exception as e:  # noqa: BLE001 - bwd timing is best-effort
        print(f"[bench] backward timing failed: {type(e).__name__}: {e}",
              file=sys.stderr)

    # flush the primary numbers BEFORE the best-effort official-kernel
    # comparison: a comparator hang kills the child on the parent's
    # timeout, and must not cost the phase its TFLOP/s (the parent keeps
    # the LAST RESULT per phase; the enriched return supersedes this)
    _emit({"phase": "pallas", "metric": metric, "value": round(tflops, 2),
           "unit": unit,
           "vs_baseline": round(tflops * 1e12 / (V5E_PEAK_BF16_FLOPS
                                                 * ANCHOR_MFU), 3),
           "pallas_ok": True, "pallas_bwd_ok": True,
           "bwd_tflops": bwd_tflops,
           "max_abs_err": round(err, 5), "bwd_rel_err": round(bwd_err, 5)})

    # north-star comparison (BASELINE.json: >=90% of a hand-ported
    # kernel): the public jax TPU flash kernel on the same shape/chip
    vs_official = None
    try:
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention as official_fa)

        def official(c, k, v):
            # official kernel takes [b, h, s, d]
            t = lambda x: x.transpose(0, 2, 1, 3)  # noqa: E731
            return t(official_fa(t(c), t(k), t(v), causal=True,
                                 sm_scale=scale))

        official_tflops = timed_tflops(official)
        vs_official = round(tflops / official_tflops, 3)
    except Exception as e:  # noqa: BLE001 - comparison is best-effort
        print(f"[bench] official-kernel comparison failed: "
              f"{type(e).__name__}: {e}", file=sys.stderr)

    print(f"[bench] pallas max_abs_err={err:.4f} bwd_rel_err={bwd_err:.4f} "
          f"{tflops:.1f} TFLOP/s bwd={bwd_tflops} TFLOP/s "
          f"vs_official={vs_official}", file=sys.stderr)
    result = {"phase": "pallas", "metric": metric,
              "value": round(tflops, 2), "unit": unit}
    if vs_official is not None:
        # the like-for-like ratio leads: same shape, same chip, same
        # session as the public hand-written TPU kernel — immune to the
        # environment's absolute-throughput variance, which the roofline
        # vs_baseline below is fully exposed to (BENCH_NOTES.md round 4)
        result["vs_official_kernel"] = vs_official
        result["official_kernel_tflops"] = round(official_tflops, 2)
    result.update({
        "vs_baseline": round(tflops * 1e12 / (V5E_PEAK_BF16_FLOPS
                                              * ANCHOR_MFU), 3),
        "vs_baseline_note": "roofline anchor (30% of nominal chip peak); "
                            "vs_official_kernel is the controlled "
                            "same-chip comparison",
        "pallas_ok": True, "pallas_bwd_ok": True,
        "bwd_tflops": bwd_tflops,
        "max_abs_err": round(err, 5),
        "bwd_rel_err": round(bwd_err, 5)})
    return result


def bench_translate(n: int) -> dict:
    """Tool-side throughput: plan+translate the bundled GPU-training and
    python samples end-to-end (headless), report services translated per
    second. Pure CPU — measurable even with no TPU attached."""
    import shutil
    import tempfile

    repo = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, repo)
    from move2kube_tpu.engine import planner, translator
    from move2kube_tpu.qa import engine as qaengine

    sample_dirs = [os.path.join(repo, "samples", "gpu-training"),
                   os.path.join(repo, "samples", "python")]
    n_services = 0
    t0 = time.perf_counter()
    for src in sample_dirs:
        out = tempfile.mkdtemp(prefix="m2kt-bench-")
        qaengine.reset_engines()
        qaengine.start_engine(qa_skip=True)
        try:
            plan = planner.create_plan(src, name="bench")
            n_services += len(plan.services)
            translator.translate(plan, out)
        finally:
            qaengine.reset_engines()
            shutil.rmtree(out, ignore_errors=True)
    dt = time.perf_counter() - t0
    metric, unit = PHASE_METRICS["translate"]
    print(f"[bench] translate {n_services} services in {dt:.1f}s",
          file=sys.stderr)
    # the reference publishes no translate-throughput number (BASELINE.md),
    # so there is nothing to normalise against; 0.0 = "no baseline exists"
    return {"phase": "translate", "metric": metric,
            "value": round(n_services / dt, 3), "unit": unit,
            "vs_baseline": 0.0, "baseline": "none_published",
            "services": n_services, "wall_s": round(dt, 2)}


def bench_goodput(n: int) -> dict:
    """Resilience-path goodput: run the supervised minitrain with one
    injected kill mid-run (resilience subsystem's CI workload) and report
    the merged productive-time fraction across attempts — the number that
    decides what preemptible capacity actually costs. Pure CPU; padded
    steps so the fraction reflects step time, not process startup."""
    import subprocess
    import tempfile

    repo = os.path.dirname(os.path.abspath(__file__))
    work = tempfile.mkdtemp(prefix="m2kt-goodput-")
    exit_file = os.path.join(work, "exit.json")
    env = dict(
        os.environ,
        PYTHONPATH=repo,
        JAX_PLATFORMS="cpu",
        M2KT_STEPS="12",
        M2KT_STEP_SLEEP_S="0.05",
        M2KT_CKPT_DIR=os.path.join(work, "ckpt"),
        M2KT_CKPT_EVERY="3",
        M2KT_FAULT_STEP="8",
        M2KT_FAULT_KIND="exit",
        M2KT_FAULT_MARKER=os.path.join(work, "fault-fired"),
        M2KT_RETRY_MAX="2",
        M2KT_RETRY_BACKOFF_S="0.1",
        M2KT_EXIT_FILE=exit_file,
        M2KT_GOODPUT_FILE=os.path.join(work, "goodput.json"),
    )
    t0 = time.perf_counter()
    res = subprocess.run(
        [sys.executable, "-m", "move2kube_tpu.resilience.supervisor", "--",
         sys.executable, "-m", "move2kube_tpu.resilience.minitrain"],
        env=env, cwd=work, capture_output=True, text=True, timeout=600)
    dt = time.perf_counter() - t0
    if res.returncode != 0:
        raise RuntimeError(
            f"supervised minitrain rc={res.returncode}: {res.stderr[-300:]}")
    with open(exit_file, encoding="utf-8") as f:
        summary = json.load(f)
    merged = summary["goodput"]
    print(f"[bench] goodput {merged['goodput_fraction']:.2%} over "
          f"{len(summary['attempts'])} attempts "
          f"(lost {merged['seconds']['lost']:.1f}s) in {dt:.1f}s",
          file=sys.stderr)

    # second drill: lose one of two forced-host slices mid-run with the
    # elastic supervisor on — the goodput fraction of a run that pays a
    # re-plan + restore instead of dying. Same tiny workload, so the two
    # fractions are directly comparable.
    ework = tempfile.mkdtemp(prefix="m2kt-goodput-elastic-")
    eexit = os.path.join(ework, "exit.json")
    eenv = dict(
        env,
        M2KT_CKPT_DIR=os.path.join(ework, "ckpt"),
        M2KT_FAULT_KIND="slice_loss",
        M2KT_FAULT_MARKER=os.path.join(ework, "fault-fired"),
        M2KT_FORCE_DEVICES="8",
        M2KT_NUM_SLICES="2",
        M2KT_BATCH_PER_DEVICE="2",
        M2KT_ELASTIC="1",
        M2KT_EXIT_FILE=eexit,
        M2KT_GOODPUT_FILE=os.path.join(ework, "goodput.json"),
    )
    t1 = time.perf_counter()
    eres = subprocess.run(
        [sys.executable, "-m", "move2kube_tpu.resilience.supervisor", "--",
         sys.executable, "-m", "move2kube_tpu.resilience.minitrain"],
        env=eenv, cwd=ework, capture_output=True, text=True, timeout=600)
    edt = time.perf_counter() - t1
    if eres.returncode != 0:
        raise RuntimeError(
            f"elastic minitrain rc={eres.returncode}: {eres.stderr[-300:]}")
    with open(eexit, encoding="utf-8") as f:
        esummary = json.load(f)
    emerged = esummary["goodput"]
    print(f"[bench] slice-loss goodput {emerged['goodput_fraction']:.2%} "
          f"(replan {emerged['seconds']['replan']:.2f}s, "
          f"{len(esummary['replan_events'])} re-plan(s)) in {edt:.1f}s",
          file=sys.stderr)

    metric, unit = PHASE_METRICS["goodput"]
    # no published baseline for faulted-run goodput on this workload
    return {"phase": "goodput", "metric": metric,
            "value": merged["goodput_fraction"], "unit": unit,
            "vs_baseline": 0.0, "baseline": "none_published",
            "attempts": len(summary["attempts"]),
            "lost_s": merged["seconds"]["lost"],
            "retry_s": merged["seconds"]["retry"],
            "steps_done": merged["steps_done"],
            "train_goodput_fraction_slice_loss":
                emerged["goodput_fraction"],
            "replan_s": emerged["seconds"]["replan"],
            "replan_events": len(esummary["replan_events"]),
            "wall_s": round(dt + edt, 2)}


def bench_scaling(n: int) -> dict:
    """Step-time scaling efficiency on 8 forced host devices: the tiny-LM
    train step on a 1-device mesh vs the topology planner's 8-device mesh
    with overlapped 2-microbatch gradient accumulation. Per-device
    throughput ratio — 1.0 would be perfect linear scaling. On a CPU host
    the 8 "devices" share the same cores, so the absolute number mostly
    tracks collective/overlap overhead, not real ICI speedup; what the
    phase guards is that the planner+overlap machinery runs end-to-end
    and doesn't collapse. Runs in its OWN subprocess because
    ``--xla_force_host_platform_device_count`` must be set before jax
    imports — the surrounding child may already have a 1-device jax."""
    import subprocess

    env = dict(os.environ, JAX_PLATFORMS="cpu", JAX_PLATFORM_NAME="cpu",
               PALLAS_AXON_POOL_IPS="")
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if not f.startswith("--xla_force_host_platform_device_count")]
    flags.append("--xla_force_host_platform_device_count=8")
    env["XLA_FLAGS"] = " ".join(flags)
    t0 = time.perf_counter()
    res = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--scaling-probe"],
        env=env, capture_output=True, text=True, timeout=CHILD_TIMEOUT_S)
    if res.returncode != 0:
        raise RuntimeError(
            f"scaling probe rc={res.returncode}: {res.stderr[-300:]}")
    probe = json.loads(res.stdout.strip().splitlines()[-1])
    dt = time.perf_counter() - t0
    print(f"[bench] scaling efficiency {probe['efficiency']:.3f} "
          f"(1dev {probe['per_device_items_s_1']:.1f} vs 8dev "
          f"{probe['per_device_items_s_8']:.1f} items/s/dev, "
          f"mesh {probe['mesh_2x4']}; 2-slice "
          f"{probe['efficiency_2slice']:.3f} dcn_dp={probe['dcn_dp']}) "
          f"in {dt:.1f}s", file=sys.stderr)
    metric, unit = PHASE_METRICS["scaling"]
    # no published baseline: the phase is a machinery guard, the fraction
    # is only comparable across rounds of this repo
    return {"phase": "scaling", "metric": metric,
            "value": probe["efficiency"], "unit": unit,
            "vs_baseline": 0.0, "baseline": "none_published",
            "mesh_2x4": probe["mesh_2x4"], "mesh_4x4x4": probe["mesh_4x4x4"],
            "mesh_2slice": probe["mesh_2slice"], "dcn_dp": probe["dcn_dp"],
            "per_device_items_s_1": probe["per_device_items_s_1"],
            "per_device_items_s_8": probe["per_device_items_s_8"],
            "per_device_items_s_2slice": probe["per_device_items_s_2slice"],
            "efficiency_2slice": probe["efficiency_2slice"],
            "overlap_path": probe["overlap_path"], "wall_s": round(dt, 2)}


def run_scaling_probe() -> int:
    """In-process half of the scaling phase (spawned by bench_scaling
    with the 8-device XLA flag set). Prints one JSON line."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    import optax

    from move2kube_tpu.models import train as m2kt_train
    from move2kube_tpu.models.llama import Llama, llama_tiny
    from move2kube_tpu.models.precision import policy
    from move2kube_tpu.parallel.mesh import MeshConfig, make_mesh
    from move2kube_tpu.parallel.overlap import is_pure_data_parallel
    from move2kube_tpu.parallel.topology import plan_parallelism

    n = jax.device_count()
    if n < 8:
        print(f"[bench] scaling probe needs 8 devices, got {n}",
              file=sys.stderr)
        return 1
    # the two documented planner goldens ride along in the report: 2x4
    # pure-DP (this probe's mesh) and the 4x4x4 tp4+zero3 case (no
    # devices needed — the plan is pure arithmetic)
    plan = plan_parallelism(8, topology="2x4")
    plan44 = plan_parallelism(64, topology="4x4x4", zero_stage=3,
                              tensor_parallel=4)
    mesh8 = make_mesh(plan)
    mesh1 = make_mesh(MeshConfig(data=1), devices=jax.devices()[:1])
    fp32 = policy("fp32")
    cfg = dataclasses.replace(llama_tiny(), dtype=jnp.float32)
    model = Llama(cfg)
    b_per_dev, seq, accum, calls = 4, 64, 2, 5

    def run(mesh, batch_shape, grad_accum):
        ids = jax.random.randint(jax.random.PRNGKey(0), batch_shape, 0,
                                 cfg.vocab_size)
        params = model.init(jax.random.PRNGKey(1), ids.reshape(
            -1, batch_shape[-1])[:1])["params"]
        state = m2kt_train.TrainState.create(
            apply_fn=model.apply, params=params, tx=optax.sgd(1e-2))
        step = m2kt_train.make_lm_train_step(
            mesh, remat=False, grad_accum=grad_accum, precision=fp32)
        state, loss = step(state, {"input_ids": ids})  # compile
        jax.block_until_ready(loss)
        t0 = time.perf_counter()
        for _ in range(calls):
            state, loss = step(state, {"input_ids": ids})
        jax.block_until_ready(loss)
        return calls / (time.perf_counter() - t0)

    # multislice variant: the same 8 host devices planned as 2 slices of
    # 2x2 — DP crosses the (simulated) DCN boundary, the slice-major perm
    # reorders the device list. On one CPU host both meshes hit the same
    # cores, so the interesting guard is that the dcn_dp plan compiles and
    # steps at parity with the flat plan, not a real DCN cost.
    plan2s = plan_parallelism(8, topology="2x2", num_slices=2)
    mesh2s = make_mesh(plan2s)

    steps_s_1 = run(mesh1, (b_per_dev, seq), 1)
    steps_s_8 = run(mesh8, (accum, 8 * b_per_dev, seq), accum)
    steps_s_2s = run(mesh2s, (accum, 8 * b_per_dev, seq), accum)
    per_dev_1 = steps_s_1 * b_per_dev
    per_dev_8 = steps_s_8 * accum * 8 * b_per_dev / 8
    per_dev_2s = steps_s_2s * accum * 8 * b_per_dev / 8
    print(json.dumps({
        "efficiency": round(per_dev_8 / per_dev_1, 4),
        "efficiency_2slice": round(per_dev_2s / per_dev_1, 4),
        "per_device_items_s_1": round(per_dev_1, 2),
        "per_device_items_s_8": round(per_dev_8, 2),
        "per_device_items_s_2slice": round(per_dev_2s, 2),
        "mesh_2x4": "x".join(str(d) for d in plan.config.dims()),
        "mesh_4x4x4": "x".join(str(d) for d in plan44.config.dims()),
        "mesh_2slice": "x".join(str(d) for d in plan2s.config.dims()),
        "dcn_dp": plan2s.dcn_dp,
        "overlap_path": bool(is_pure_data_parallel(mesh8)),
    }), flush=True)
    return 0


def bench_serving(n: int) -> dict:
    """Continuous-batching decode throughput on forced host devices: a
    16-request mixed-length stream through the paged-KV ServingEngine
    (serving/engine.py) on the tiny llama, run through BOTH the async
    double-buffered pipeline (substeps=4) and the synchronous reference
    loop, interleaved round by round so CPU load drift can't invert the
    comparison. The phase FAILS unless async ≥ sync tok/s, the dispatch
    gap shrinks, the greedy streams are byte-identical, the async chaos
    drill journals exactly N tokens, and the compiled-executable count
    holds the num_buckets + 2 budget. CPU host numbers are only
    comparable across rounds of this repo. Own subprocess for the same
    reason as the scaling phase: the probe must own jax's platform env
    before import, independent of this child's backend."""
    import subprocess

    env = dict(os.environ, JAX_PLATFORMS="cpu", JAX_PLATFORM_NAME="cpu",
               PALLAS_AXON_POOL_IPS="")
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if not f.startswith("--xla_force_host_platform_device_count")]
    flags.append("--xla_force_host_platform_device_count=8")
    env["XLA_FLAGS"] = " ".join(flags)
    t0 = time.perf_counter()
    res = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--serving-probe"],
        env=env, capture_output=True, text=True, timeout=CHILD_TIMEOUT_S)
    if res.returncode != 0:
        raise RuntimeError(
            f"serving probe rc={res.returncode}: {res.stderr[-300:]}")
    probe = json.loads(res.stdout.strip().splitlines()[-1])
    dt = time.perf_counter() - t0
    # async-pipeline gates (PR 19): the interleaved capture must show the
    # overlap paying for itself, token streams must be byte-identical to
    # the synchronous reference, and the chaos drill's journal must hold
    # exactly N tokens — a faster pipeline that drops or invents tokens
    # is a regression, not a data point
    if not probe["compile_bound_ok"]:
        raise RuntimeError(
            f"serving: {probe['total_executables']} executables for "
            f"{probe['num_buckets']} buckets breaks the num_buckets+2 "
            "budget under async decode")
    if probe["token_exact_fraction"] < 1.0:
        raise RuntimeError(
            f"serving: async-vs-sync token exactness "
            f"{probe['token_exact_fraction']:.3f} < 1.0")
    if not probe["chaos_exact"]:
        raise RuntimeError(
            f"serving: async chaos drill journaled "
            f"{probe['chaos_journal_tokens']} tokens, expected the "
            "kill point exactly")
    if probe["async_tokens_s"] < probe["sync_tokens_s"]:
        raise RuntimeError(
            f"serving: async {probe['async_tokens_s']} tok/s did not "
            f"beat sync {probe['sync_tokens_s']} tok/s on the "
            "interleaved capture")
    if probe["dispatch_gap_async_s"] >= probe["dispatch_gap_sync_s"]:
        raise RuntimeError(
            f"serving: async dispatch gap {probe['dispatch_gap_async_s']}s "
            f"did not shrink vs sync {probe['dispatch_gap_sync_s']}s")
    print(f"[bench] serving async {probe['async_tokens_s']:.1f} vs sync "
          f"{probe['sync_tokens_s']:.1f} tok/s "
          f"(x{probe['async_speedup']:.2f} interleaved, gap "
          f"{probe['dispatch_gap_async_s']:.3f}s vs "
          f"{probe['dispatch_gap_sync_s']:.3f}s, "
          f"p50 {probe['decode_p50_latency_ms']:.2f}ms, "
          f"p95 {probe['decode_p95_latency_ms']:.2f}ms, "
          f"{probe['total_executables']} executables for "
          f"{probe['num_buckets']} buckets) in {dt:.1f}s", file=sys.stderr)
    metric, unit = PHASE_METRICS["serving"]
    # no published baseline: host-CPU decode throughput of a toy model is
    # not a literature number — only cross-round comparable
    return {"phase": "serving", "metric": metric,
            "value": probe["async_tokens_s"], "unit": unit,
            "vs_baseline": 0.0, "baseline": "none_published",
            "decode_p50_latency_ms": probe["decode_p50_latency_ms"],
            "decode_p95_latency_ms": probe["decode_p95_latency_ms"],
            "decode_tokens": probe["decode_tokens"],
            "requests": probe["requests"],
            "num_buckets": probe["num_buckets"],
            "total_executables": probe["total_executables"],
            "compile_bound_ok": probe["compile_bound_ok"],
            "async_tokens_s": probe["async_tokens_s"],
            "sync_tokens_s": probe["sync_tokens_s"],
            "async_speedup": probe["async_speedup"],
            "dispatch_gap_async_s": probe["dispatch_gap_async_s"],
            "dispatch_gap_sync_s": probe["dispatch_gap_sync_s"],
            "token_exact_fraction": probe["token_exact_fraction"],
            "chaos_exact": probe["chaos_exact"],
            "host_overhead_ratio": probe.get("host_overhead_ratio"),
            "wall_s": round(dt, 2)}


def run_serving_probe() -> int:
    """In-process half of the serving phase (spawned by bench_serving with
    jax forced onto host devices). Drives the continuous-batching engine
    over a mixed-length 16-request stream twice over — an async
    double-buffered pipeline (substeps=4) and the synchronous reference
    loop — INTERLEAVED round by round (the PR-10 lesson: sequential
    measurement lets CPU load drift invert results), plus a chaos drill
    (kill at token N under async) proving the journal hook still sees
    exactly N tokens. Prints one JSON line."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from move2kube_tpu.models.llama import Llama, llama_tiny
    from move2kube_tpu.serving.engine import (
        EngineConfig,
        Request,
        ServingEngine,
    )

    cfg = dataclasses.replace(llama_tiny(), dtype=jnp.float32)
    model = Llama(cfg)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 8), jnp.int32))

    # decode-heavy shape (24 generated tokens per request): the serving
    # regime the pipeline exists for — a prefill-dominated stream hides
    # the decode loop the phase is gating
    def build(async_mode: str, substeps: int) -> ServingEngine:
        return ServingEngine(model, variables, EngineConfig(
            max_batch=4, max_seq=128, block_size=8, buckets=(8, 16, 32),
            max_new_tokens=24, async_decode=async_mode, substeps=substeps))

    # mixed prompt lengths spanning all three buckets; enough requests
    # that slots recycle mid-flight (16 requests through 4 slots)
    lengths = [3, 7, 12, 20, 30, 5, 16, 25, 9, 31, 4, 14, 22, 6, 28, 11]

    def make_requests() -> list:
        rng = np.random.default_rng(0)
        return [
            Request(rid=f"r{i}",
                    prompt=rng.integers(1, cfg.vocab_size, size=n).tolist())
            for i, n in enumerate(lengths)]

    engines = {"async": build("on", 4), "sync": build("off", 1)}
    # warmup pass per mode: compiles + first-touch costs, uncounted —
    # and the token-exactness capture (greedy fp32 streams must match
    # byte for byte between the pipelines)
    streams: dict = {}
    for mode, eng in engines.items():
        comps = eng.run(make_requests())
        assert len(comps) == len(lengths), (
            f"{mode}: {len(comps)}/{len(lengths)} requests completed")
        streams[mode] = {c.rid: list(c.tokens) for c in comps}
    exact = sum(1 for rid in streams["sync"]
                if streams["async"].get(rid) == streams["sync"][rid])
    token_exact_fraction = exact / len(streams["sync"])

    totals = {"async": [0.0, 0], "sync": [0.0, 0]}  # wall_s, tokens
    rounds = 4
    for r in range(rounds):
        order = ("async", "sync") if r % 2 == 0 else ("sync", "async")
        for mode in order:
            t0 = time.perf_counter()
            comps = engines[mode].run(make_requests())
            wall = time.perf_counter() - t0
            totals[mode][0] += wall
            totals[mode][1] += sum(len(c.tokens) for c in comps)
    async_tps = totals["async"][1] / max(totals["async"][0], 1e-9)
    sync_tps = totals["sync"][1] / max(totals["sync"][0], 1e-9)

    # chaos drill on a fresh async engine: the journal hook raises on
    # its Nth token (PR-13 kill-at-token-N). Lag-1 must never have
    # journaled a token the host hadn't consumed — exactly N survive.
    kill_at = 5
    drill = build("on", 4)
    journal: list = []

    def _cb(rid, tok):
        journal.append((rid, tok))
        if len(journal) == kill_at:
            raise RuntimeError("chaos: kill at token N")

    drill.on_token = _cb
    killed = False
    try:
        drill.run([Request(rid="drill", prompt=[1, 2, 3, 4, 5])])
    except RuntimeError:
        killed = True
    chaos_exact = bool(killed and len(journal) == kill_at)

    stats = engines["async"].stats()
    sync_stats = engines["sync"].stats()
    report = engines["async"].compile_report()
    total = report.get("total_executables", -1)
    print(json.dumps({
        **{k: round(v, 3) if isinstance(v, float) else v
           for k, v in stats.items()},
        "requests": len(lengths),
        "num_buckets": report["num_buckets"],
        "total_executables": total,
        "compile_bound_ok": bool(
            0 <= total <= report["num_buckets"] + 2),
        "rounds": rounds,
        "async_tokens_s": round(async_tps, 2),
        "sync_tokens_s": round(sync_tps, 2),
        "async_speedup": round(async_tps / max(sync_tps, 1e-9), 3),
        "dispatch_gap_async_s": round(stats["dispatch_gap_total_s"], 4),
        "dispatch_gap_sync_s": round(
            sync_stats["dispatch_gap_total_s"], 4),
        "token_exact_fraction": token_exact_fraction,
        "chaos_exact": chaos_exact,
        "chaos_journal_tokens": len(journal),
    }), flush=True)
    return 0


def bench_fleet(n: int) -> dict:
    """Fleet-serving phase on forced host devices: a zipfian multi-tenant
    replay through the request router over real in-process engine
    replicas, once with the refcounted prefix cache on and once with it
    off. Reports the p95 TTFT speedup the cache buys on hits (the primary
    number), plus tok/s and hit rate for both configurations. The phase
    FAILS when the replay produces zero cache hits or the cached p95 TTFT
    is not better — a prefix cache that doesn't pay for itself under a
    skewed tenant mix is a regression, not a data point.

    The replay is tenant-tagged end to end (X-M2KT-Tenant semantics via
    the router's tenant kwarg), so the probe also reports per-tenant p95
    TTFT, drives a synthetic best-effort flood through the burn-rate
    drill (M2KT_SLO_WINDOW_SCALE shrinks the SRE windows to seconds; the
    fast-burn alert MUST fire), and asserts that a disagg request traced
    router -> prefill -> decode stitches into ONE trace whose e2e
    decomposes exactly (residual < 1ns). Own subprocess for the same
    reason as the serving phase: the probe must own jax's platform env
    before import."""
    import subprocess

    env = dict(os.environ, JAX_PLATFORMS="cpu", JAX_PLATFORM_NAME="cpu",
               PALLAS_AXON_POOL_IPS="")
    # drill-scale the SLO windows (fast pair 36s/3s) so the flood
    # registers inside the probe's lifetime; an explicit operator value
    # wins
    env.setdefault("M2KT_SLO_WINDOW_SCALE", "0.01")
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if not f.startswith("--xla_force_host_platform_device_count")]
    flags.append("--xla_force_host_platform_device_count=8")
    env["XLA_FLAGS"] = " ".join(flags)
    t0 = time.perf_counter()
    res = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--fleet-probe"],
        env=env, capture_output=True, text=True, timeout=CHILD_TIMEOUT_S)
    if res.returncode != 0:
        raise RuntimeError(
            f"fleet probe rc={res.returncode}: {res.stderr[-300:]}")
    probe = json.loads(res.stdout.strip().splitlines()[-1])
    dt = time.perf_counter() - t0
    print(f"[bench] fleet x{probe['replicas']}: p95 TTFT "
          f"{probe['p95_ttft_ms_cached']:.2f}ms cached vs "
          f"{probe['p95_ttft_ms_uncached']:.2f}ms uncached "
          f"(x{probe['p95_ttft_speedup']:.2f}, hit rate "
          f"{probe['prefix_hit_rate']:.2f}), "
          f"{probe['throughput_tok_s_cached']:.1f} vs "
          f"{probe['throughput_tok_s_uncached']:.1f} tok/s in {dt:.1f}s; "
          f"burn drill fired={probe['burn_drill_fired']}, trace residual "
          f"{probe['trace_residual_s']:.1e}s over "
          f"{probe['trace_parts']} parts", file=sys.stderr)
    metric, unit = PHASE_METRICS["fleet"]
    return {"phase": "fleet", "metric": metric,
            "value": probe["p95_ttft_speedup"], "unit": unit,
            "vs_baseline": 0.0, "baseline": "none_published",
            "replicas": probe["replicas"],
            "requests": probe["requests"],
            "tenants": probe["tenants"],
            "prefix_hit_rate": probe["prefix_hit_rate"],
            "p95_ttft_ms_cached": probe["p95_ttft_ms_cached"],
            "p95_ttft_ms_uncached": probe["p95_ttft_ms_uncached"],
            "p50_ttft_ms_cached": probe["p50_ttft_ms_cached"],
            "p50_ttft_ms_uncached": probe["p50_ttft_ms_uncached"],
            "throughput_tok_s_cached": probe["throughput_tok_s_cached"],
            "throughput_tok_s_uncached": probe["throughput_tok_s_uncached"],
            "affinity_hit_fraction": probe["affinity_hit_fraction"],
            "per_tenant_p95_ttft_ms": probe["per_tenant_p95_ttft_ms"],
            "burn_drill_fired": probe["burn_drill_fired"],
            "burn_rate_fast_short": probe["burn_rate_fast_short"],
            "slo_window_scale": probe["slo_window_scale"],
            "trace_residual_s": probe["trace_residual_s"],
            "trace_parts": probe["trace_parts"],
            "trace_e2e_ms": probe["trace_e2e_ms"],
            "wall_s": round(dt, 2)}


def run_fleet_probe() -> int:
    """In-process half of the fleet phase (spawned by bench_fleet with jax
    forced onto host devices). Builds two router+replica fleets — prefix
    cache on and off — replays the same zipfian multi-tenant stream
    through each (tenant-tagged, so the engines' per-tenant SLO ledgers
    fill), runs the burn-rate drill and the disagg trace-stitching
    check, and prints one JSON line."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from move2kube_tpu.models.llama import Llama, llama_tiny
    from move2kube_tpu.obs import tracing
    from move2kube_tpu.obs.fleetview import SYNTH_HOP, FleetTraceCollector
    from move2kube_tpu.serving.engine import EngineConfig, ServingEngine
    from move2kube_tpu.serving.fleet.disagg import PrefillReplica
    from move2kube_tpu.serving.fleet.router import (InProcessReplica,
                                                    Router, RouterConfig,
                                                    build_fleet)

    replicas = int(os.environ.get("M2KT_BENCH_FLEET_REPLICAS", "4"))
    n_tenants = int(os.environ.get("M2KT_BENCH_FLEET_TENANTS", "8"))
    n_requests = int(os.environ.get("M2KT_BENCH_FLEET_REQUESTS", "48"))

    cfg = dataclasses.replace(llama_tiny(), dtype=jnp.float32,
                              attn_impl="dense")
    model = Llama(cfg)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 8), jnp.int32))

    rng = np.random.default_rng(7)
    # tenant popularity is zipfian: a few hot system prompts dominate,
    # the long tail barely repeats — the regime prefix caching targets.
    # Prefixes are long (240 of a 256-token bucket) so prefill carries
    # real compute; on host CPU a short-prompt prefill costs about the
    # same as the 2-3 decode dispatches a hit pays, and the cache's win
    # would drown in dispatch overhead.
    prefixes = [rng.integers(1, cfg.vocab_size, size=240).tolist()
                for _ in range(n_tenants)]
    tenant_ids = np.minimum(rng.zipf(1.6, size=n_requests),
                            n_tenants) - 1
    prompts = [prefixes[t] + rng.integers(1, cfg.vocab_size,
                                          size=2).tolist()
               for t in tenant_ids]

    def replay(prefix_cache: bool) -> dict:
        # max_batch sizes the page pool (1 + max_batch * max_seq / bs):
        # 4 slots leave room for the hot tenants' pages to stay resident
        ecfg = EngineConfig(max_batch=4, max_seq=256, block_size=8,
                            buckets=(256,), prefix_cache=prefix_cache)
        router = build_fleet(model, variables, replicas,
                             engine_config=ecfg)
        try:
            # warm pass: every replica compiles its own prefill/decode
            # executables (a hedge or spill can land anywhere), then the
            # full stream once to compile the hit/COW install path and
            # pre-populate the cache — the timed pass measures steady
            # state, not first-touch compilation
            # max_new_tokens > 1 matters: a 1-token cold request finishes
            # at prefill and never compiles the decode executable
            for rep in router.replicas:
                rep.generate(prompts[0][:10], max_new_tokens=8)
            for p in prompts:
                router.generate(list(p), max_new_tokens=8)
            ttft_ms = []
            by_tenant: dict[str, list[float]] = {}
            for p, tid in zip(prompts, tenant_ids):
                # max_new_tokens=1: client latency IS TTFT
                t = time.perf_counter()
                router.generate(list(p), max_new_tokens=1,
                                tenant=f"tenant-{tid}")
                dt_ms = (time.perf_counter() - t) * 1e3
                ttft_ms.append(dt_ms)
                by_tenant.setdefault(f"tenant-{tid}", []).append(dt_ms)
            t = time.perf_counter()
            toks = sum(len(router.generate(list(p), max_new_tokens=8)
                           ["tokens"]) for p in prompts[:replicas * 4])
            tput = toks / (time.perf_counter() - t)
            hits = sum(r.engine.stats().get("prefix_hits", 0)
                       for r in router.replicas)
            misses = sum(r.engine.stats().get("prefix_misses", 0)
                         for r in router.replicas)
            out = {"p50": float(np.percentile(ttft_ms, 50)),
                   "p95": float(np.percentile(ttft_ms, 95)),
                   "tput": tput,
                   "hit_rate": hits / max(1, hits + misses),
                   "affinity": router._affinity_hits.value,
                   "per_tenant_p95": {
                       k: float(np.percentile(v, 95))
                       for k, v in sorted(by_tenant.items())}}
            if prefix_cache:
                # the tenant label must have flowed router -> engine
                # into the bounded-cardinality serve histograms and the
                # SLO ledger's per-tenant gauges
                text = "\n".join(r.engine.registry.render()
                                 for r in router.replicas)
                assert "m2kt_serve_tenant_ttft_seconds" in text
                assert 'tenant="tenant-0"' in text, \
                    "tenant label did not reach any engine registry"
                assert "m2kt_slo_tenant_ttft_p95_seconds" in text
                eng = router.replicas[0].engine
                # burn-rate drill: a synthetic best-effort flood of
                # rejected requests against the drill-scaled windows
                # (M2KT_SLO_WINDOW_SCALE) — the fast-burn alert input
                # MUST fire, and recover state is visible in the gauges
                for _ in range(64):
                    eng.slo.record("best-effort", ok=True, ttft_s=0.005)
                for _ in range(2000):
                    eng.slo.record("best-effort", ok=False)
                assert eng.slo.fast_burn_firing(), \
                    "best-effort flood did not fire the fast-burn alert"
                eng.registry.render()  # export hook: gauges refresh
                out["burn_drill_fired"] = True
                out["burn_fast_short"] = eng.slo.burn_rate(
                    eng.slo.spec.fast_windows[1])
            return out
        finally:
            for rep in router.replicas:
                rep.close()

    warm = replay(prefix_cache=True)
    cold = replay(prefix_cache=False)
    speedup = cold["p95"] / max(1e-9, warm["p95"])
    assert warm["hit_rate"] > 0, "zipfian replay produced zero cache hits"
    assert speedup > 1.0, (
        f"prefix cache did not improve p95 TTFT: "
        f"{warm['p95']:.2f}ms cached vs {cold['p95']:.2f}ms uncached")

    # acceptance drill: one disagg request traced router -> prefill ->
    # decode must stitch into ONE trace whose router-observed e2e
    # decomposes EXACTLY into child spans + synthesized hop gaps
    router_tr = tracing.SpanRecorder(role="router")
    decode_tr = tracing.SpanRecorder(role="decode")
    prefill_tr = tracing.SpanRecorder(role="prefill")
    ecfg = EngineConfig(max_batch=2, max_seq=256, block_size=8,
                        buckets=(256,))
    rep = InProcessReplica(
        "decode-0",
        ServingEngine(model, variables, ecfg, tracer=decode_tr)).start()
    pre = PrefillReplica(model, variables, ecfg, tracer=prefill_tr)
    rtr = Router([rep], config=RouterConfig(disagg_threshold=8),
                 prefill_replicas=[pre], tracer=router_tr)
    try:
        rtr.generate(list(prompts[0]), max_new_tokens=2,
                     tenant="tenant-0")
        col = FleetTraceCollector()
        docs = [router_tr.ring_doc(), decode_tr.ring_doc(),
                prefill_tr.ring_doc()]
        merged = col.stitch(docs)
        [root] = [s for s in merged["spans"]
                  if s["name"] == "router.request"
                  and not s["parent_id"]]
        names = {s["name"] for s in merged["traces"][root["trace_id"]]}
        assert {"prefill.request", "serve.request", SYNTH_HOP} <= names, (
            f"disagg trace did not stitch across roles: {sorted(names)}")
        decomp = col.decompose(root["trace_id"], docs=docs)
        assert abs(decomp["residual_s"]) < 1e-9, (
            f"stitched decomposition not exact: {decomp['residual_s']}")
    finally:
        rep.close()

    total_routed = 2 * (2 * n_requests + replicas * 4)
    print(json.dumps({
        "replicas": replicas, "tenants": n_tenants,
        "requests": n_requests,
        "prefix_hit_rate": round(warm["hit_rate"], 3),
        "p95_ttft_speedup": round(speedup, 3),
        "p95_ttft_ms_cached": round(warm["p95"], 3),
        "p95_ttft_ms_uncached": round(cold["p95"], 3),
        "p50_ttft_ms_cached": round(warm["p50"], 3),
        "p50_ttft_ms_uncached": round(cold["p50"], 3),
        "throughput_tok_s_cached": round(warm["tput"], 1),
        "throughput_tok_s_uncached": round(cold["tput"], 1),
        "affinity_hit_fraction": round(
            (warm["affinity"] + cold["affinity"]) / max(1, total_routed), 3),
        "per_tenant_p95_ttft_ms": {
            k: round(v, 3) for k, v in warm["per_tenant_p95"].items()},
        "burn_drill_fired": warm["burn_drill_fired"],
        "burn_rate_fast_short": round(warm["burn_fast_short"], 1),
        "slo_window_scale": float(
            os.environ.get("M2KT_SLO_WINDOW_SCALE", "1") or "1"),
        "trace_residual_s": decomp["residual_s"],
        "trace_parts": len(decomp["parts"]),
        "trace_e2e_ms": round(decomp["e2e_s"] * 1e3, 3),
    }), flush=True)
    return 0


# round-14 prefix-cached fleet throughput capture (BENCH_NOTES round 14:
# "674 vs 269 tok/s") — the scheduler plane's multi-LoRA batch must not
# give back what the cache bought
SCHED_TPUT_BASELINE = 674.0


def bench_sched(n: int) -> dict:
    """Scheduler-plane phase on forced host devices: a best-effort flood
    holds every decode slot of a single replica while a high-priority
    tenant keeps arriving, so each gold request can only land by
    preempting a victim; then a paged multi-LoRA batch serves two
    adapters plus the base model from ONE engine. The phase FAILS unless
    (a) the gold tenant's p95 TTFT holds the SLO target under the flood
    and its per-tenant fast-burn input stays quiet, (b) every preempted
    best-effort request finishes token-exactly (fraction 1.0) vs an
    uninterrupted greedy run, and (c) each adapter's batched output
    matches a dedicated merged-weight engine. Reports the multi-LoRA
    aggregate tok/s against the round-14 fleet capture. Own subprocess
    for the usual reason: the probe must own jax's platform env before
    import."""
    import subprocess

    env = dict(os.environ, JAX_PLATFORMS="cpu", JAX_PLATFORM_NAME="cpu",
               PALLAS_AXON_POOL_IPS="")
    # drill-scale the SLO windows so the gold tenant's burn-rate gate
    # reads a window its handful of requests can actually fill
    env.setdefault("M2KT_SLO_WINDOW_SCALE", "0.01")
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if not f.startswith("--xla_force_host_platform_device_count")]
    flags.append("--xla_force_host_platform_device_count=8")
    env["XLA_FLAGS"] = " ".join(flags)
    t0 = time.perf_counter()
    res = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--sched-probe"],
        env=env, capture_output=True, text=True, timeout=CHILD_TIMEOUT_S)
    if res.returncode != 0:
        raise RuntimeError(
            f"sched probe rc={res.returncode}: {res.stderr[-300:]}")
    probe = json.loads(res.stdout.strip().splitlines()[-1])
    dt = time.perf_counter() - t0
    print(f"[bench] sched: {probe['preempted']} preemptions, resume "
          f"exact fraction {probe['preempt_exact_fraction']:.2f}, gold "
          f"p95 TTFT {probe['gold_p95_ttft_ms']:.2f}ms (SLO "
          f"{probe['gold_ttft_slo_ms']:.0f}ms, burn "
          f"{probe['gold_burn_fast_short']:.1f}<{probe['fast_burn_limit']}"
          f"); multi-LoRA x{probe['lora_adapters']} "
          f"{probe['multilora_aggregate_tokens_s']:.1f} tok/s in {dt:.1f}s",
          file=sys.stderr)
    metric, unit = PHASE_METRICS["sched"]
    return {"phase": "sched", "metric": metric,
            "value": probe["multilora_aggregate_tokens_s"], "unit": unit,
            "vs_baseline": round(
                probe["multilora_aggregate_tokens_s"]
                / SCHED_TPUT_BASELINE, 3),
            "baseline": "round14_fleet_cached_674_tok_s",
            "preempted": probe["preempted"],
            "preempt_exact_fraction": probe["preempt_exact_fraction"],
            "resumed_reasons": probe["resumed_reasons"],
            "gold_p95_ttft_ms": probe["gold_p95_ttft_ms"],
            "gold_ttft_slo_ms": probe["gold_ttft_slo_ms"],
            "gold_burn_fast_short": probe["gold_burn_fast_short"],
            "fast_burn_limit": probe["fast_burn_limit"],
            "lora_adapters": probe["lora_adapters"],
            "multilora_requests": probe["multilora_requests"],
            "multilora_executables": probe["multilora_executables"],
            "slo_window_scale": probe["slo_window_scale"],
            "wall_s": round(dt, 2)}


def run_sched_probe() -> int:
    """In-process half of the sched phase (spawned by bench_sched with
    jax forced onto host devices). Part 1: priority-preemption drill
    through the router — two best-effort streams saturate a 2-slot
    engine, gold requests arrive and must evict to land, the victims
    resume token-exactly from the journal. Part 2: multi-LoRA batch —
    base + two adapters decode together in one engine; each adapter's
    tokens must equal a dedicated engine built with the LoRA delta
    merged into the lm_head weights. Prints one JSON line."""
    import dataclasses
    import threading

    import jax
    import jax.numpy as jnp
    import numpy as np

    from move2kube_tpu.models.llama import Llama, llama_tiny
    from move2kube_tpu.obs.slo import FAST_BURN
    from move2kube_tpu.serving.engine import (EngineConfig, Request,
                                              ServingEngine)
    from move2kube_tpu.serving.fleet.router import RouterConfig, build_fleet

    cfg = dataclasses.replace(llama_tiny(), dtype=jnp.float32,
                              attn_impl="dense")
    model = Llama(cfg)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 8), jnp.int32))
    rng = np.random.default_rng(11)

    # ---- part 1: preemption drill ------------------------------------
    # one replica, TWO slots: both held by best-effort decode so a gold
    # arrival can only land by preempting. Best-effort streams are long
    # (160 new tokens) so they are still mid-decode for every gold shot.
    tenants = "gold:prio=high;free:prio=besteffort"
    be_new = 160
    ecfg = EngineConfig(max_batch=2, max_seq=256, block_size=8,
                        buckets=(32, 256), sched_tenants=tenants)
    rcfg = RouterConfig(sched_tenants=tenants)
    router = build_fleet(model, variables, 1, engine_config=ecfg,
                         router_config=rcfg)
    eng = router.replicas[0].engine
    be_prompts = [rng.integers(1, cfg.vocab_size, size=24).tolist()
                  for _ in range(2)]
    gold_prompts = [rng.integers(1, cfg.vocab_size, size=24).tolist()
                    for _ in range(6)]
    try:
        # warm: compile both prefill buckets + decode before the drill,
        # so gold client latencies measure scheduling, not XLA. Warmed
        # under the best-effort tenant: compile-time TTFTs are SLO-bad
        # events and must not land in gold's burn-rate ledger
        router.generate(gold_prompts[0], max_new_tokens=2, tenant="free")
        router.generate(list(range(1, 200)), max_new_tokens=2,
                        tenant="free")
        # ground truth BEFORE contention: the uninterrupted greedy
        # output each best-effort stream must reproduce after being
        # preempted and journal-resumed mid-flight
        truth = [router.generate(list(p), max_new_tokens=be_new,
                                 tenant="free")["tokens"]
                 for p in be_prompts]
        results: dict[int, dict] = {}

        def _flood(i: int) -> None:
            results[i] = router.generate(list(be_prompts[i]),
                                         max_new_tokens=be_new,
                                         tenant="free")

        threads = [threading.Thread(target=_flood, args=(i,))
                   for i in range(2)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            if eng.stats().get("active_slots", 0) >= 2:
                break
            time.sleep(0.002)
        ttft_ms = []
        for p in gold_prompts:
            t0 = time.perf_counter()
            router.generate(list(p), max_new_tokens=1, tenant="gold")
            ttft_ms.append((time.perf_counter() - t0) * 1e3)
        for t in threads:
            t.join(timeout=CHILD_TIMEOUT_S)
        preempted = int(eng.stats().get("preempted", 0))
        assert preempted > 0, (
            "gold flood over a saturated engine produced zero "
            "preemptions — the drill never exercised eviction")
        exact = sum(1 for i in range(2)
                    if results.get(i, {}).get("tokens") == truth[i])
        exact_fraction = exact / 2.0
        assert exact_fraction == 1.0, (
            f"preempted best-effort streams did not resume token-exact: "
            f"{exact}/2 matched the uninterrupted run")
        # the router must have resumed paused work via the journal (the
        # counter is reason-labeled; "preempted" is the only reason here)
        resumed = int(router._sched_resumed.labels(
            reason="preempted").value)
        assert resumed > 0, "no journal resume was recorded for a preempt"
        spec = eng.slo.spec
        p95_ms = float(np.percentile(ttft_ms, 95))
        assert p95_ms <= spec.ttft_p95_s * 1e3, (
            f"gold p95 TTFT {p95_ms:.1f}ms blew the "
            f"{spec.ttft_p95_s * 1e3:.0f}ms SLO under the flood — "
            "preemption is not protecting the high-priority tenant")
        # per-tenant fast-burn input for gold must be quiet: the flood
        # may burn the best-effort tenant's budget, never gold's
        gold_burn = max(eng.slo.burn_rate(w, tenant="gold")
                        for w in spec.fast_windows)
        assert gold_burn < FAST_BURN, (
            f"gold fast-burn input {gold_burn:.1f} >= {FAST_BURN} — "
            "the high-priority tenant is burning error budget")
    finally:
        for rep in router.replicas:
            rep.close()

    # ---- part 2: paged multi-LoRA batch ------------------------------
    lcfg = EngineConfig(max_batch=4, max_seq=64, block_size=8,
                        buckets=(32,), max_loras=4, lora_rank=8)
    e = ServingEngine(model, variables, lcfg)
    adapters: dict[str, tuple] = {}
    for name, rank in (("fin", 4), ("legal", 2)):
        a = (rng.normal(size=(cfg.d_model, rank)) * 0.1).astype(np.float32)
        b = (rng.normal(size=(rank, cfg.vocab_size)) * 0.1).astype(
            np.float32)
        e.register_adapter(name, a, b)
        adapters[name] = (a, b)
    assert int(e.stats().get("lora_adapters", 0)) >= 2, \
        "multi-LoRA drill needs at least two resident adapters"
    lora_new = 16
    lprompt = rng.integers(1, cfg.vocab_size, size=12).tolist()
    mix = ["", "fin", "legal", "", "fin", "legal"]
    reqs = [Request(rid=f"r{i}", prompt=list(lprompt),
                    max_new_tokens=lora_new, adapter=nm)
            for i, nm in enumerate(mix)]
    # warm pass compiles prefill + the single lora-threaded decode
    e.run([Request(rid=f"w{i}", prompt=list(lprompt), max_new_tokens=2,
                   adapter=nm) for i, nm in enumerate(("", "fin"))])
    t0 = time.perf_counter()
    outs = e.run(reqs)
    lora_dt = time.perf_counter() - t0
    agg = sum(len(c.tokens) for c in outs) / lora_dt
    # the adapter mix must NOT have multiplied executables: the stacks
    # are traced operands of the one decode program
    report = e.compile_report()
    assert report["total_executables"] <= len(lcfg.buckets) + 2, report
    by = {r.rid: nm for r, nm in zip(reqs, mix)}
    got = {c.rid: c.tokens for c in outs}
    for name, (a, b) in adapters.items():
        # dedicated reference: the LoRA delta merged into lm_head, so
        # the paged gather-apply path must reproduce it token for token
        merged = {"params": {
            **variables["params"],
            "lm_head": {"kernel":
                        variables["params"]["lm_head"]["kernel"] + a @ b}}}
        ded = ServingEngine(model, merged, EngineConfig(
            max_batch=4, max_seq=64, block_size=8, buckets=(32,)))
        want = ded.run([Request(rid="x", prompt=list(lprompt),
                                max_new_tokens=lora_new)])[0].tokens
        for rid, nm in by.items():
            if nm == name:
                assert got[rid] == want, (
                    f"{rid} (adapter {name}): batched tokens diverged "
                    f"from the dedicated merged-weight engine")
    base = ServingEngine(model, variables, EngineConfig(
        max_batch=4, max_seq=64, block_size=8, buckets=(32,)))
    want = base.run([Request(rid="x", prompt=list(lprompt),
                             max_new_tokens=lora_new)])[0].tokens
    for rid, nm in by.items():
        if not nm:
            assert got[rid] == want, (
                f"{rid}: base-model rows in the LoRA batch diverged "
                "from a no-adapter engine")

    print(json.dumps({
        "preempted": preempted,
        "preempt_exact_fraction": exact_fraction,
        "resumed_reasons": {"preempted": resumed},
        "gold_p95_ttft_ms": round(p95_ms, 3),
        "gold_ttft_slo_ms": round(spec.ttft_p95_s * 1e3, 1),
        "gold_burn_fast_short": round(gold_burn, 2),
        "fast_burn_limit": FAST_BURN,
        "lora_adapters": int(e.stats().get("lora_adapters", 0)),
        "multilora_requests": len(reqs),
        "multilora_aggregate_tokens_s": round(agg, 1),
        "multilora_executables": report["total_executables"],
        "slo_window_scale": float(
            os.environ.get("M2KT_SLO_WINDOW_SCALE", "1") or "1"),
    }), flush=True)
    return 0


def bench_autoscale(n: int) -> dict:
    """Predictive-autoscaling phase, two halves in one probe child.
    Half 1 (the scale the probe can never serve): the discrete-event
    fleet simulator replays a >=24h diurnal+bursty trace with over a
    million DISTINCT simulated users in seconds of wall clock, running
    the REAL production controller (DemandForecaster +
    PredictiveAutoscaler) against a faithful reactive-HPA model on the
    SAME trace — the phase FAILS unless predictive wins on BOTH SLO
    attainment AND replica-hours. Half 2 (the scale it can): a live
    in-process fleet where a forecasted traffic ramp grows the fleet
    BEFORE the PR-12 fast-burn alert fires, and the forecast collapse
    afterwards shrinks it through the PR-13 drain path with zero lost
    streams. Reports the replica-hours saving fraction vs reactive HPA
    on the simulated day."""
    import subprocess

    env = dict(os.environ, JAX_PLATFORMS="cpu", JAX_PLATFORM_NAME="cpu",
               PALLAS_AXON_POOL_IPS="")
    # drill-scale the SLO windows so the live smoke's burn-rate gate
    # reads a window its seconds-long ramp can actually fill
    env.setdefault("M2KT_SLO_WINDOW_SCALE", "0.01")
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if not f.startswith("--xla_force_host_platform_device_count")]
    flags.append("--xla_force_host_platform_device_count=8")
    env["XLA_FLAGS"] = " ".join(flags)
    t0 = time.perf_counter()
    res = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--autoscale-probe"],
        env=env, capture_output=True, text=True, timeout=CHILD_TIMEOUT_S)
    if res.returncode != 0:
        raise RuntimeError(
            f"autoscale probe rc={res.returncode}: {res.stderr[-300:]}")
    probe = json.loads(res.stdout.strip().splitlines()[-1])
    dt = time.perf_counter() - t0
    saving = 1.0 - (probe["sim_predictive_replica_hours"]
                    / probe["sim_reactive_replica_hours"])
    print(f"[bench] autoscale: sim {probe['sim_requests']} reqs / "
          f"{probe['sim_distinct_users']} users in "
          f"{probe['sim_wall_s']:.1f}s — attainment "
          f"{probe['sim_predictive_attainment']:.4f} vs "
          f"{probe['sim_reactive_attainment']:.4f}, hours "
          f"{probe['sim_predictive_replica_hours']:.1f} vs "
          f"{probe['sim_reactive_replica_hours']:.1f} "
          f"({saving:.1%} saved); live smoke scaled in "
          f"{probe['live_scale_up_s']:.1f}s (cold-join lead "
          f"{probe['live_cold_join_s']:.1f}s) with burn "
          f"{probe['live_burn_at_scale_up']:.2f}<"
          f"{probe['fast_burn_limit']} and "
          f"{probe['live_lost_streams']} lost streams in {dt:.1f}s",
          file=sys.stderr)
    metric, unit = PHASE_METRICS["autoscale"]
    return {"phase": "autoscale", "metric": metric,
            "value": round(saving, 4), "unit": unit,
            "vs_baseline": round(
                probe["sim_reactive_replica_hours"]
                / probe["sim_predictive_replica_hours"], 3),
            "baseline": "reactive_hpa_same_trace",
            "sim_requests": probe["sim_requests"],
            "sim_distinct_users": probe["sim_distinct_users"],
            "sim_duration_s": probe["sim_duration_s"],
            "sim_wall_s": probe["sim_wall_s"],
            "sim_predictive_attainment":
                probe["sim_predictive_attainment"],
            "sim_reactive_attainment": probe["sim_reactive_attainment"],
            "sim_predictive_replica_hours":
                probe["sim_predictive_replica_hours"],
            "sim_reactive_replica_hours":
                probe["sim_reactive_replica_hours"],
            "sim_predictive_p95_ttft_s":
                probe["sim_predictive_p95_ttft_s"],
            "sim_reactive_p95_ttft_s": probe["sim_reactive_p95_ttft_s"],
            "live_cold_join_s": probe["live_cold_join_s"],
            "live_scale_up_s": probe["live_scale_up_s"],
            "live_burn_at_scale_up": probe["live_burn_at_scale_up"],
            "fast_burn_limit": probe["fast_burn_limit"],
            "live_requests_ok": probe["live_requests_ok"],
            "live_lost_streams": probe["live_lost_streams"],
            "slo_window_scale": probe["slo_window_scale"],
            "wall_s": round(dt, 2)}


def run_autoscale_probe() -> int:
    """In-process half of the autoscale phase (spawned by
    bench_autoscale with jax forced onto host devices). Part 1: the
    fleet simulator's 24h predictive-vs-reactive gate at million-user
    scale. Part 2: live smoke — a 1-replica llama_tiny fleet under a
    ramping load; the forecaster sees the ramp in the router's
    admitted-token counter, the controller grows the fleet to 2 while
    the fast-burn alert is still quiet, then the post-ramp forecast
    collapse drain-shrinks back to 1 losing zero streams. Prints one
    JSON line."""
    import dataclasses
    import threading

    import jax
    import jax.numpy as jnp
    import numpy as np

    from move2kube_tpu.models.llama import Llama, llama_tiny
    from move2kube_tpu.obs.slo import FAST_BURN
    from move2kube_tpu.serving.engine import EngineConfig, ServingEngine
    from move2kube_tpu.serving.fleet.autoscaler import (
        AutoscaleConfig, FleetActuator, PredictiveAutoscaler)
    from move2kube_tpu.serving.fleet.forecast import (
        CounterDemand, DemandForecaster, ForecastConfig)
    from move2kube_tpu.serving.fleet.router import (InProcessReplica,
                                                    build_fleet)
    from move2kube_tpu.serving.fleet.sim import compare_policies

    # ---- part 1: million-user simulated day --------------------------
    sim = compare_policies()
    react, pred = sim["reactive"], sim["predictive"]
    assert sim["trace"]["duration_s"] >= 86400, sim["trace"]
    assert sim["trace"]["distinct_users"] >= 1_000_000, (
        f"only {sim['trace']['distinct_users']} distinct simulated "
        "users — the trace is below the million-user gate")
    assert sim["wall_s"] < 60.0, (
        f"simulated day took {sim['wall_s']:.1f}s wall — over the 60s "
        "CPU CI budget")
    assert react["lost_streams"] == 0 and pred["lost_streams"] == 0
    assert sim["predictive_wins"], (
        "predictive policy did not beat reactive HPA on BOTH axes: "
        f"attainment {pred['attainment']:.4f} vs "
        f"{react['attainment']:.4f}, replica-hours "
        f"{pred['replica_hours']:.1f} vs {react['replica_hours']:.1f}")

    # ---- part 2: live smoke ------------------------------------------
    cfg = dataclasses.replace(llama_tiny(), dtype=jnp.float32,
                              attn_impl="dense")
    model = Llama(cfg)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 8), jnp.int32))
    rng = np.random.default_rng(23)
    ecfg = EngineConfig(max_batch=2, max_seq=128, block_size=8,
                        buckets=(32,))
    router = build_fleet(model, variables, 1, engine_config=ecfg)
    prompt = rng.integers(1, cfg.vocab_size, size=16).tolist()
    results: list = []
    errors: list = []
    try:
        # warm replica-0 (compile) before anything is measured
        router.generate(list(prompt), max_new_tokens=2)
        # measured cold-join: how long a NEW replica takes from factory
        # to first served token — this becomes the forecast lead time
        t0 = time.perf_counter()
        probe_rep = InProcessReplica(
            "replica-joinprobe", ServingEngine(model, variables,
                                               ecfg)).start()
        probe_rep.generate(list(prompt), max_new_tokens=1, rid="joinwarm")
        cold_join_s = time.perf_counter() - t0
        probe_rep.drain(2.0)
        probe_rep.close()

        def factory(name):
            return InProcessReplica(
                name, ServingEngine(model, variables, ecfg)).start()

        actuator = FleetActuator(router, factory, drain_grace_s=10.0)
        forecaster = DemandForecaster(
            ForecastConfig(alpha=0.5, beta=0.3, max_trend_frac=0.05,
                           mean_tau_s=2.0))
        # capacity deliberately conservative (tokens admitted per
        # second one replica should carry): the ramp crosses the
        # scale-up threshold while replica-0 still serves comfortably,
        # which is the whole point of predictive — grow BEFORE burn
        tokens_per_req = len(prompt) + 4
        capacity_tps = 8.0 * tokens_per_req
        scaler = PredictiveAutoscaler(
            forecaster, capacity_tps,
            config=AutoscaleConfig(
                interval_s=0.2, min_replicas=1, max_replicas=2,
                target_util=0.7, lead_time_s=cold_join_s,
                down_delay_s=1.5))
        demand = CounterDemand(router.admitted_tokens, forecaster,
                               window_s=2.0)
        stop = threading.Event()
        threads: list = []

        def fire():
            try:
                results.append(router.generate(list(prompt),
                                               max_new_tokens=4))
            except Exception as err:  # noqa: BLE001 - counted, asserted
                errors.append(err)

        def ramp():
            # request rate ramps 2/s -> 12/s over ~8s: the token
            # demand the forecaster must see coming
            t_start = time.monotonic()
            while not stop.is_set():
                dt = time.monotonic() - t_start
                rate = min(12.0, 2.0 + 1.25 * dt)
                th = threading.Thread(target=fire)
                th.start()
                threads.append(th)
                stop.wait(1.0 / rate)

        ramper = threading.Thread(target=ramp)
        ramper.start()
        scale_up_s = -1.0
        burn_at_scale_up = float("inf")
        t_ramp0 = time.perf_counter()
        deadline = t_ramp0 + 60.0
        while time.perf_counter() < deadline:
            demand.tick()
            cur = actuator.replicas()
            target = scaler.decide(cur)
            if target > cur:
                # the gate: the forecast-driven grow must land while
                # the fast-burn alert is still quiet on every engine
                spec = router.replicas[0].engine.slo.spec
                burn_at_scale_up = max(
                    rep.engine.slo.burn_rate(w)
                    for rep in router.replicas
                    for w in spec.fast_windows)
                actuator.scale_to(target)
                scale_up_s = time.perf_counter() - t_ramp0
                break
            time.sleep(0.2)
        stop.set()
        ramper.join(timeout=10)
        for th in threads:
            th.join(timeout=CHILD_TIMEOUT_S)
        assert scale_up_s >= 0, (
            "the forecasted ramp never triggered a scale-up within 60s")
        assert burn_at_scale_up < FAST_BURN, (
            f"fast-burn alert ({burn_at_scale_up:.1f} >= {FAST_BURN}) "
            "was already firing when the autoscaler grew the fleet — "
            "predictive scaling arrived late")
        assert len(router.replicas) == 2
        assert not errors, f"{len(errors)} requests failed: {errors[:3]}"
        # forecast collapse: demand is now zero; the down-delay lapses
        # and the controller drain-shrinks back to 1
        shrink_deadline = time.perf_counter() + 30.0
        while time.perf_counter() < shrink_deadline:
            demand.tick()
            cur = actuator.replicas()
            target = scaler.decide(cur)
            if target < cur:
                actuator.scale_to(target)
                break
            time.sleep(0.2)
        assert len(router.replicas) == 1, (
            "forecast collapse never shrank the fleet within 30s")
        assert actuator.lost_streams == 0, (
            f"scale-down lost {actuator.lost_streams} streams — drain "
            "must absorb every in-flight request")
        ok = sum(1 for r in results if r.get("tokens"))
        assert ok == len(results), (
            f"only {ok}/{len(results)} ramp requests returned tokens")
    finally:
        for rep in router.replicas:
            rep.close()

    print(json.dumps({
        "sim_requests": sim["trace"]["requests"],
        "sim_distinct_users": sim["trace"]["distinct_users"],
        "sim_duration_s": sim["trace"]["duration_s"],
        "sim_wall_s": round(sim["wall_s"], 2),
        "sim_predictive_attainment": round(pred["attainment"], 5),
        "sim_reactive_attainment": round(react["attainment"], 5),
        "sim_predictive_replica_hours": round(pred["replica_hours"], 2),
        "sim_reactive_replica_hours": round(react["replica_hours"], 2),
        "sim_predictive_p95_ttft_s": round(pred["p95_ttft_s"], 3),
        "sim_reactive_p95_ttft_s": round(react["p95_ttft_s"], 3),
        "live_cold_join_s": round(cold_join_s, 2),
        "live_scale_up_s": round(scale_up_s, 2),
        "live_burn_at_scale_up": round(burn_at_scale_up, 2),
        "fast_burn_limit": FAST_BURN,
        "live_requests_ok": len(results),
        "live_lost_streams": actuator.lost_streams,
        "slo_window_scale": float(
            os.environ.get("M2KT_SLO_WINDOW_SCALE", "1") or "1"),
    }), flush=True)
    return 0


def bench_usage(n: int) -> dict:
    """Usage-ledger / capture→replay / auto-diagnostics phase. One
    probe child drives multi-tenant traffic through a real llama_tiny
    fleet with the usage ledger snapshotting, then gates the three
    claims of the observability plane: (1) the chargeback identity —
    per-tenant TPU-seconds sum to pods × wall within 1%; (2) replay
    fidelity — the capture built from the ledger rings, replayed as a
    simulator trace, reproduces the measured aggregate token rate and
    per-tenant shares within 10%; (3) the anomaly watchdog — an induced
    SLO fast-burn produces EXACTLY one diag bundle (profiler trace +
    span ring + ledger window), an immediate re-trigger is rate-limit
    suppressed, and the interval lapse re-arms it. Also measures ledger
    snapshot overhead (must stay under 1% of the snapshot interval)."""
    import subprocess

    env = dict(os.environ, JAX_PLATFORMS="cpu", JAX_PLATFORM_NAME="cpu",
               PALLAS_AXON_POOL_IPS="")
    env.setdefault("M2KT_SLO_WINDOW_SCALE", "0.01")
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if not f.startswith("--xla_force_host_platform_device_count")]
    flags.append("--xla_force_host_platform_device_count=8")
    env["XLA_FLAGS"] = " ".join(flags)
    t0 = time.perf_counter()
    res = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--usage-probe"],
        env=env, capture_output=True, text=True, timeout=CHILD_TIMEOUT_S)
    if res.returncode != 0:
        raise RuntimeError(
            f"usage probe rc={res.returncode}: {res.stderr[-300:]}")
    probe = json.loads(res.stdout.strip().splitlines()[-1])
    dt = time.perf_counter() - t0
    fid_err = max(probe["replay_rate_err"], probe["replay_max_share_err"])
    print(f"[bench] usage: chargeback identity err "
          f"{probe['chargeback_identity_err']:.4f} over "
          f"{probe['pods']} pods / {probe['total_wall_s']:.1f}s wall; "
          f"replay rate err {probe['replay_rate_err']:.4f}, share err "
          f"{probe['replay_max_share_err']:.4f} "
          f"({probe['recorded_tokens']:.0f} tokens, "
          f"{probe['tenants']} tenants); diag bundles "
          f"{probe['diag_bundles_first']}→{probe['diag_bundles_final']} "
          f"(suppressed {probe['diag_suppressed']}); snapshot "
          f"{probe['snapshot_mean_s'] * 1e3:.2f}ms -> overhead "
          f"{probe['ledger_overhead_fraction']:.5f} in {dt:.1f}s",
          file=sys.stderr)
    metric, unit = PHASE_METRICS["usage"]
    return {"phase": "usage", "metric": metric,
            "value": round(fid_err, 5), "unit": unit,
            "chargeback_identity_err": probe["chargeback_identity_err"],
            "total_wall_s": probe["total_wall_s"],
            "total_tpu_seconds": probe["total_tpu_seconds"],
            "pods": probe["pods"],
            "tenants": probe["tenants"],
            "recorded_tokens": probe["recorded_tokens"],
            "replayed_tokens": probe["replayed_tokens"],
            "replay_rate_err": probe["replay_rate_err"],
            "replay_max_share_err": probe["replay_max_share_err"],
            "replay_requests": probe["replay_requests"],
            "diag_bundles_first": probe["diag_bundles_first"],
            "diag_bundles_final": probe["diag_bundles_final"],
            "diag_suppressed": probe["diag_suppressed"],
            "diag_bundle_parts": probe["diag_bundle_parts"],
            "snapshot_mean_s": probe["snapshot_mean_s"],
            "ledger_overhead_fraction":
                probe["ledger_overhead_fraction"],
            "wall_s": round(dt, 2)}


def run_usage_probe() -> int:
    """In-process half of the usage phase (spawned by bench_usage with
    jax forced onto host devices). Prints one JSON line."""
    import dataclasses
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np

    from move2kube_tpu.models.llama import Llama, llama_tiny
    from move2kube_tpu.obs.bridge import DiagWatchdog
    from move2kube_tpu.obs.ledger import (UsageLedger, engine_source,
                                          router_source)
    from move2kube_tpu.obs.metrics import Registry
    from move2kube_tpu.obs.slo import SLOTracker
    from move2kube_tpu.obs.tracing import SpanRecorder
    from move2kube_tpu.serving.engine import EngineConfig
    from move2kube_tpu.serving.fleet.capture import (CapturedTrace,
                                                     build_capture,
                                                     chargeback, fidelity)
    from move2kube_tpu.serving.fleet.router import build_fleet

    # ---- multi-tenant traffic through a real fleet -------------------
    cfg = dataclasses.replace(llama_tiny(), dtype=jnp.float32,
                              attn_impl="dense")
    model = Llama(cfg)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 8), jnp.int32))
    rng = np.random.default_rng(7)
    ecfg = EngineConfig(max_batch=2, max_seq=128, block_size=8,
                        buckets=(32,))
    router = build_fleet(model, variables, 1, engine_config=ecfg)
    engine = router.replicas[0].engine
    # two pods' worth of ledgers, as the fleet runs them: the decode
    # pod snapshots the engine counters, the router pod its admissions
    eng_ledger = UsageLedger(registry=Registry(), role="decode",
                             interval_s=0.1)
    eng_ledger.add_source(engine_source(engine), "engine")
    rt_ledger = UsageLedger(registry=Registry(), role="router",
                            interval_s=0.1)
    rt_ledger.add_source(router_source(router), "router")
    prompt = rng.integers(1, cfg.vocab_size, size=16).tolist()
    tenants = {"acme": 5, "globex": 3, "initech": 1}
    try:
        router.generate(list(prompt), max_new_tokens=2)  # compile warm
        eng_ledger.snapshot()
        rt_ledger.snapshot()
        for _wave in range(3):
            for tenant, weight in tenants.items():
                for _ in range(weight):
                    out = router.generate(list(prompt), max_new_tokens=4,
                                          tenant=tenant)
                    assert out.get("tokens"), out
            eng_ledger.snapshot()
            rt_ledger.snapshot()
    finally:
        for rep in router.replicas:
            rep.close()
    docs = [eng_ledger.doc(), rt_ledger.doc()]

    # ---- gate 1: chargeback identity ---------------------------------
    report = chargeback(docs)
    identity_err = (abs(report["total_tpu_seconds"]
                        - report["total_wall_s"])
                    / max(1e-9, report["total_wall_s"]))
    assert identity_err <= 0.01, (
        f"TPU-seconds {report['total_tpu_seconds']:.3f} vs wall "
        f"{report['total_wall_s']:.3f}: identity err {identity_err:.4f} "
        "over the 1% gate")
    billed = set(report["tenants"]) - {"unattributed"}
    assert billed >= set(tenants), (
        f"chargeback lost tenants: billed {sorted(billed)}, "
        f"drove {sorted(tenants)}")

    # ---- gate 2: capture -> replay fidelity --------------------------
    capture = build_capture(docs, bin_s=0.5)
    trace = CapturedTrace(capture, seed=0)
    fid = fidelity(capture, trace)
    assert fid["rate_err"] <= 0.10, (
        f"replayed aggregate rate off by {fid['rate_err']:.3f} "
        f"({fid['replayed_tps']:.1f} vs {fid['recorded_tps']:.1f} "
        "tok/s) — over the 10% gate")
    assert fid["max_share_err"] <= 0.10, (
        f"per-tenant share error {fid['max_share_err']:.3f} over the "
        f"10% gate: {fid['share_err']}")

    # ---- gate 3: anomaly-triggered auto-profiling --------------------
    # injected clocks make the burn/rate-limit timeline deterministic
    t_now = [1000.0]

    def clk() -> float:
        return t_now[0]

    slo = SLOTracker(registry=Registry(), clock=clk)
    wd_reg = Registry()
    diag_out = tempfile.mkdtemp(prefix="m2kt-diag-")
    wd = DiagWatchdog(registry=wd_reg, slo=slo, tracer=SpanRecorder(),
                      ledger=eng_ledger, out_dir=diag_out,
                      min_interval_s=600.0, profile_seconds=0.2,
                      clock=clk)

    def burn(bad: bool, n_events: int = 40, dt: float = 1.0) -> None:
        for _ in range(n_events):
            t_now[0] += dt
            slo.record(ok=not bad, ttft_s=10.0 if bad else 0.01)

    burn(bad=True)
    first = wd.check()
    assert first is not None, "induced fast-burn did not trigger a capture"
    for _ in range(5):  # still firing: hysteresis holds, no re-capture
        wd.check()
    bundles_first = len(wd.captures)
    assert bundles_first == 1, (
        f"{bundles_first} bundles from one sustained burn — wanted "
        "exactly one")
    # join before the re-arm capture: jax allows one active profiler
    wd.wait(timeout_s=30.0)
    # recover, re-burn inside the rate-limit interval: suppressed
    burn(bad=False, n_events=120)
    wd.check()
    burn(bad=True)
    assert wd.check() is None, "rate limit failed to suppress a re-burn"
    suppressed = sum(
        v for _lv, v in wd._c_suppressed.samples())  # noqa: SLF001
    assert suppressed >= 1, "suppression was not counted"
    # interval lapse re-arms: the next edge captures again
    burn(bad=False, n_events=120)
    wd.check()
    t_now[0] += 601.0
    burn(bad=True)
    assert wd.check() is not None, (
        "watchdog did not re-arm after the rate-limit interval")
    wd.wait(timeout_s=30.0)
    bundle = wd.captures[0]
    manifest_path = os.path.join(bundle, "manifest.json")
    assert os.path.exists(manifest_path), f"no manifest in {bundle}"
    with open(manifest_path, encoding="utf-8") as f:
        manifest = json.load(f)
    parts = sorted(manifest.get("parts", []))
    for part in ("traces.json", "usage.json", "profile"):
        assert part in parts, f"bundle missing {part}: {parts}"
        assert os.path.exists(os.path.join(bundle, part)), part
    assert os.listdir(os.path.join(bundle, "profile")), (
        "profiler capture produced no files")

    # ---- ledger overhead ---------------------------------------------
    reps = 50
    t0 = time.perf_counter()
    for _ in range(reps):
        eng_ledger.snapshot()
    snap_mean_s = (time.perf_counter() - t0) / reps
    from move2kube_tpu.obs.ledger import DEFAULT_INTERVAL_S
    overhead = snap_mean_s / DEFAULT_INTERVAL_S
    assert overhead <= 0.01, (
        f"ledger snapshot costs {snap_mean_s * 1e3:.1f}ms — "
        f"{overhead:.4f} of the {DEFAULT_INTERVAL_S:g}s interval, over "
        "the 1% gate")

    print(json.dumps({
        "chargeback_identity_err": round(identity_err, 6),
        "total_wall_s": round(report["total_wall_s"], 3),
        "total_tpu_seconds": round(report["total_tpu_seconds"], 3),
        "pods": len(report["pods"]),
        "tenants": len(billed),
        "recorded_tokens": round(fid["recorded_tokens"], 1),
        "replayed_tokens": round(fid["replayed_tokens"], 1),
        "replay_rate_err": round(fid["rate_err"], 6),
        "replay_max_share_err": round(fid["max_share_err"], 6),
        "replay_requests": int(trace.n),
        "diag_bundles_first": bundles_first,
        "diag_bundles_final": len(wd.captures),
        "diag_suppressed": int(suppressed),
        "diag_bundle_parts": parts,
        "snapshot_mean_s": round(snap_mean_s, 6),
        "ledger_overhead_fraction": round(overhead, 6),
    }), flush=True)
    return 0


def bench_chaos(n: int) -> dict:
    """Serving-fleet fault-tolerance phase on forced host devices: a
    zipfian replay through the router while a chaos injector kills one
    replica mid-stream (at an exact token) and another replica is
    gracefully drained mid-replay. The phase FAILS unless ZERO requests
    are lost, every completion is token-identical to an uninterrupted
    golden replay (greedy decode + journal resume => byte-exact), at
    least one request was resumed, the drained replica emptied cleanly,
    the deadline-shed drill rejected an unmeetable request, and the
    faulted replay's p95 latency stayed within the recovery budget
    (M2KT_BENCH_CHAOS_LAT_BUDGET x the golden p95). Own subprocess for
    the same reason as the other serving phases: the probe must own
    jax's platform env before import."""
    import subprocess

    env = dict(os.environ, JAX_PLATFORMS="cpu", JAX_PLATFORM_NAME="cpu",
               PALLAS_AXON_POOL_IPS="")
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if not f.startswith("--xla_force_host_platform_device_count")]
    flags.append("--xla_force_host_platform_device_count=8")
    env["XLA_FLAGS"] = " ".join(flags)
    t0 = time.perf_counter()
    res = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--chaos-probe"],
        env=env, capture_output=True, text=True, timeout=CHILD_TIMEOUT_S)
    if res.returncode != 0:
        raise RuntimeError(
            f"chaos probe rc={res.returncode}: {res.stderr[-300:]}")
    probe = json.loads(res.stdout.strip().splitlines()[-1])
    dt = time.perf_counter() - t0
    print(f"[bench] chaos x{probe['replicas']}: killed "
          f"{probe['victim']} at token {probe['kill_token']}, drained "
          f"{probe['drained']} (clean={probe['drain_clean']}); "
          f"{probe['resumed_total']} resumed, token-exact fraction "
          f"{probe['recovered_token_exact_fraction']:.3f}, p95 "
          f"{probe['chaos_p95_ms']:.1f}ms vs golden "
          f"{probe['golden_p95_ms']:.1f}ms "
          f"(x{probe['latency_ratio']:.2f} <= "
          f"x{probe['latency_budget']:.1f}), deadline sheds "
          f"{probe['deadline_shed_total']} in {dt:.1f}s",
          file=sys.stderr)
    metric, unit = PHASE_METRICS["chaos"]
    return {"phase": "chaos", "metric": metric,
            "value": probe["recovered_token_exact_fraction"], "unit": unit,
            "vs_baseline": 0.0, "baseline": "none_published",
            "replicas": probe["replicas"],
            "requests": probe["requests"],
            "kill_token": probe["kill_token"],
            "victim": probe["victim"],
            "drained": probe["drained"],
            "drain_clean": probe["drain_clean"],
            "resumed_total": probe["resumed_total"],
            "deadline_shed_total": probe["deadline_shed_total"],
            "golden_p95_ms": probe["golden_p95_ms"],
            "chaos_p95_ms": probe["chaos_p95_ms"],
            "latency_ratio": probe["latency_ratio"],
            "latency_budget": probe["latency_budget"],
            "wall_s": round(dt, 2)}


def run_chaos_probe() -> int:
    """In-process half of the chaos phase (spawned by bench_chaos with
    jax forced onto host devices). Golden replay on an unfaulted fleet,
    then the same stream against a fleet where one replica dies at an
    exact mid-stream token (exactly-once, marker-gated) and another is
    drained halfway through; asserts nothing is lost, every stream is
    token-identical, and the deadline plane sheds an unmeetable
    request. Prints one JSON line."""
    import dataclasses
    import re
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np

    from move2kube_tpu.models.llama import Llama, llama_tiny
    from move2kube_tpu.serving.engine import DeadlineExceeded, EngineConfig
    from move2kube_tpu.serving.fleet.chaos import ChaosConfig, ServingChaos
    from move2kube_tpu.serving.fleet.router import build_fleet

    n_replicas = int(os.environ.get("M2KT_BENCH_CHAOS_REPLICAS", "3"))
    n_tenants = int(os.environ.get("M2KT_BENCH_CHAOS_TENANTS", "4"))
    n_requests = int(os.environ.get("M2KT_BENCH_CHAOS_REQUESTS", "20"))
    kill_at = int(os.environ.get("M2KT_BENCH_CHAOS_KILL_TOKEN", "4"))
    max_new = 8
    budget = float(os.environ.get("M2KT_BENCH_CHAOS_LAT_BUDGET", "5.0"))

    cfg = dataclasses.replace(llama_tiny(), dtype=jnp.float32,
                              attn_impl="dense")
    model = Llama(cfg)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 8), jnp.int32))
    ecfg = EngineConfig(max_batch=2, max_seq=128, block_size=8,
                        buckets=(64,), prefix_cache=True)

    rng = np.random.default_rng(11)
    prefixes = [rng.integers(1, cfg.vocab_size, size=40).tolist()
                for _ in range(n_tenants)]
    tenant_ids = np.minimum(rng.zipf(1.6, size=n_requests), n_tenants) - 1
    prompts = [prefixes[t] + rng.integers(1, cfg.vocab_size,
                                          size=2).tolist()
               for t in tenant_ids]

    def replay(router, on_index=None):
        tokens, lat_ms = [], []
        for i, (p, tid) in enumerate(zip(prompts, tenant_ids)):
            if on_index is not None:
                on_index(i)
            t = time.perf_counter()
            out = router.generate(list(p), max_new_tokens=max_new,
                                  tenant=f"tenant-{tid}")
            lat_ms.append((time.perf_counter() - t) * 1e3)
            tokens.append(list(out["tokens"]))
        return tokens, lat_ms

    def warm(router):
        # every replica compiles its prefill/decode executables before
        # the replay (a failover or spill can land anywhere), so the
        # faulted pass measures recovery, not first-touch compilation
        for rep in router.replicas:
            rep.generate(prompts[0][:10], max_new_tokens=4)

    # golden: the uninterrupted fleet's per-request token streams
    router_g = build_fleet(model, variables, n_replicas,
                           engine_config=ecfg)
    try:
        warm(router_g)
        golden, golden_lat = replay(router_g)
    finally:
        for rep in router_g.replicas:
            rep.close()

    # faulted fleet: same stream, one replica killed at a mid-stream
    # token (the affine owner of the hottest tenant, so the kill lands
    # on real traffic), another drained halfway through the replay
    router_c = build_fleet(model, variables, n_replicas,
                           engine_config=ecfg)
    marker = os.path.join(tempfile.mkdtemp(prefix="m2kt-chaos-"),
                          "fired")
    try:
        warm(router_c)
        victim = router_c.pick(prompts[0])
        victim.chaos = ServingChaos(
            ChaosConfig(kill_token=kill_at, marker=marker))
        drained = next(r for r in router_c.replicas
                       if r.name != victim.name)
        drain_state = {}

        def on_index(i):
            if i == n_requests // 2 and "clean" not in drain_state:
                drain_state["clean"] = drained.drain(grace_s=10.0)

        chaos, chaos_lat = replay(router_c, on_index)
        assert not drained.healthy(), "drained replica still in the ring"

        # zero lost + token-exact: every request completed, and every
        # stream (including the resumed one) matches the golden replay
        assert len(chaos) == n_requests, "requests were lost under chaos"
        exact = sum(1 for a, b in zip(chaos, golden) if a == b)
        frac = exact / n_requests
        assert frac == 1.0, (
            f"only {exact}/{n_requests} streams token-identical after "
            f"kill+drain")
        assert os.path.exists(marker), "the kill never fired"

        text = router_c.registry.render()
        resumed = sum(
            float(m.group(1)) for m in re.finditer(
                r"m2kt_router_resumed_total\{[^}]*\} ([0-9.e+-]+)", text))
        assert resumed >= 1, "no request was resumed mid-stream"

        # deadline plane: an unmeetable budget is shed at admission,
        # not timed out slowly
        shed_err = None
        try:
            router_c.generate(list(prompts[0]), max_new_tokens=max_new,
                              deadline_s=1e-4)
        except DeadlineExceeded as err:
            shed_err = err
        assert shed_err is not None, "unmeetable deadline was not shed"
        sheds = sum(
            float(m.group(1)) for rep in router_c.replicas
            for m in re.finditer(
                r"m2kt_serve_deadline_shed_total\{[^}]*\} ([0-9.e+-]+)",
                rep.engine.registry.render()))
        assert sheds >= 1, "deadline shed left no counter trace"

        golden_p95 = float(np.percentile(golden_lat, 95))
        chaos_p95 = float(np.percentile(chaos_lat, 95))
        ratio = chaos_p95 / max(1e-9, golden_p95)
        assert ratio <= budget, (
            f"recovery blew the latency budget: p95 {chaos_p95:.1f}ms vs "
            f"golden {golden_p95:.1f}ms (x{ratio:.2f} > x{budget})")
    finally:
        for rep in router_c.replicas:
            rep.close()

    print(json.dumps({
        "replicas": n_replicas, "requests": n_requests,
        "kill_token": kill_at, "victim": victim.name,
        "drained": drained.name,
        "drain_clean": bool(drain_state.get("clean")),
        "resumed_total": int(resumed),
        "deadline_shed_total": int(sheds),
        "recovered_token_exact_fraction": round(frac, 3),
        "golden_p95_ms": round(golden_p95, 3),
        "chaos_p95_ms": round(chaos_p95, 3),
        "latency_ratio": round(ratio, 3),
        "latency_budget": budget,
    }), flush=True)
    return 0


def bench_swap(n: int) -> dict:
    """Weight-plane phase on forced host devices, two halves in one
    capture. (1) Cold-replica join TTFT: the same replica boot measured
    twice — checkpoint restore + full XLA compile (the pre-weight-plane
    path) vs P2P shard streaming from serving peers + prewarm-seeded
    compile cache; the reported number is the speedup, gated at
    M2KT_BENCH_SWAP_SPEEDUP_FLOOR. (2) Live swap under chaos: a threaded
    zipfian replay is mid-flight while the new generation is fetched
    P2P from peers where one peer corrupts a shard and another dies
    mid-stream, then rolled across the fleet while chaos kills one
    replica inside its swap. FAILS unless the fetch survives both
    faults (digest re-fetch + different-peer finish), zero in-flight
    requests are lost, every stream stays token-identical to the golden
    replay across the swap, and the survivors converge on the new
    generation. Own subprocess: the probe must own jax's platform env
    and the M2KT_COMPILE_CACHE*/M2KT_PREWARM_DIR knobs before import."""
    import subprocess

    env = dict(os.environ, JAX_PLATFORMS="cpu", JAX_PLATFORM_NAME="cpu",
               PALLAS_AXON_POOL_IPS="")
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if not f.startswith("--xla_force_host_platform_device_count")]
    flags.append("--xla_force_host_platform_device_count=8")
    env["XLA_FLAGS"] = " ".join(flags)
    t0 = time.perf_counter()
    res = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--swap-probe"],
        env=env, capture_output=True, text=True, timeout=CHILD_TIMEOUT_S)
    if res.returncode != 0:
        raise RuntimeError(
            f"swap probe rc={res.returncode}: {res.stderr[-300:]}")
    probe = json.loads(res.stdout.strip().splitlines()[-1])
    dt = time.perf_counter() - t0
    print(f"[bench] swap: cold join {probe['ttft_store_s']:.2f}s "
          f"store+compile vs {probe['ttft_p2p_s']:.2f}s P2P+prewarm "
          f"(x{probe['cold_join_ttft_speedup']:.2f} >= "
          f"x{probe['speedup_floor']:.1f}; {probe['prewarm_entries']} "
          f"baked, {probe['seeded_entries']} seeded); live swap -> v"
          f"{probe['swapped_version']}: {probe['swap_ok']} ok / "
          f"{probe['swap_failed']} killed mid-swap, "
          f"{probe['in_flight_at_swap']} in flight, token-exact "
          f"{probe['swap_token_exact_fraction']:.3f} "
          f"(digest_mismatch={probe['digest_mismatch_total']}, "
          f"peer_deaths={probe['connection_total']}) in {dt:.1f}s",
          file=sys.stderr)
    metric, unit = PHASE_METRICS["swap"]
    return {"phase": "swap", "metric": metric,
            "value": probe["cold_join_ttft_speedup"], "unit": unit,
            "vs_baseline": 0.0, "baseline": "none_published",
            "ttft_store_s": probe["ttft_store_s"],
            "ttft_p2p_s": probe["ttft_p2p_s"],
            "speedup_floor": probe["speedup_floor"],
            "prewarm_entries": probe["prewarm_entries"],
            "seeded_entries": probe["seeded_entries"],
            "replicas": probe["replicas"],
            "requests": probe["requests"],
            "swapped_version": probe["swapped_version"],
            "swap_ok": probe["swap_ok"],
            "swap_failed": probe["swap_failed"],
            "in_flight_at_swap": probe["in_flight_at_swap"],
            "swap_token_exact_fraction": probe["swap_token_exact_fraction"],
            "digest_mismatch_total": probe["digest_mismatch_total"],
            "connection_total": probe["connection_total"],
            "wall_s": round(dt, 2)}


def run_swap_boot_probe() -> int:
    """Innermost swap-phase probe: ONE genuinely cold replica boot, in
    its own fresh process so no in-memory jax cache can flatter the
    measurement. ``M2KT_SWAP_BOOT`` picks the weight source — ``store``
    restores from the checkpoint dir in ``M2KT_SWAP_CKPT_DIR``, ``p2p``
    streams shards over HTTP from ``M2KT_WEIGHTS_PEERS`` — and the
    compile cache / prewarm artifact ride the production env knobs
    (``M2KT_COMPILE_CACHE_DIR`` / ``M2KT_PREWARM_DIR``). Prints one
    JSON line with the boot-to-first-token time."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from move2kube_tpu.models import checkpoint as m2kt_ckpt
    from move2kube_tpu.models.compile_cache import setup_compilation_cache
    from move2kube_tpu.models.llama import Llama, llama_tiny
    from move2kube_tpu.serving.engine import (EngineConfig, Request,
                                              ServingEngine)
    from move2kube_tpu.serving.fleet import weights as weightslib

    mode = os.environ.get("M2KT_SWAP_BOOT", "store")
    cfg = dataclasses.replace(llama_tiny(), dtype=jnp.float32,
                              attn_impl="dense")
    model = Llama(cfg)
    ecfg = EngineConfig(max_batch=2, max_seq=128, block_size=8,
                        buckets=(64,), prefix_cache=True)
    rng = np.random.default_rng(11)
    prompt = rng.integers(1, cfg.vocab_size, size=42).tolist()

    t0 = time.perf_counter()
    setup_compilation_cache()
    template = model.init(jax.random.PRNGKey(0),
                          jnp.zeros((1, 8), jnp.int32))
    if mode == "p2p":
        got = weightslib.fetch_from_peers(weightslib.peers_from_env())
        assert got is not None, "cold boot: P2P fetch failed"
        variables, version = got
    else:
        variables = m2kt_ckpt.restore_variables(
            os.environ["M2KT_SWAP_CKPT_DIR"], template)
        version = 1
    eng = ServingEngine(model, variables, ecfg)
    eng.submit(Request(rid="cold-join", prompt=list(prompt),
                       max_new_tokens=2))
    while eng.has_work():
        if eng.step():
            break
    print(json.dumps({"ttft_s": round(time.perf_counter() - t0, 3),
                      "source": mode, "version": int(version)}),
          flush=True)
    return 0


def run_swap_probe() -> int:
    """In-process half of the swap phase (spawned by bench_swap with jax
    forced onto host devices). The cold-join halves run as grandchild
    processes (``--swap-boot-probe``) so each boot is honestly cold:
    the store boot pays checkpoint restore + full XLA compile, the P2P
    boot streams shards over real HTTP from this process's weight plane
    and thaws executables from the prewarm artifact the store boot's
    cache was baked into. The live-swap chaos drill then runs in-process
    against the fleet. Prints one JSON line."""
    import dataclasses
    import http.server
    import re
    import subprocess
    import tempfile
    import threading
    import urllib.parse
    from concurrent.futures import ThreadPoolExecutor

    import jax
    import jax.numpy as jnp
    import numpy as np

    from move2kube_tpu.models import checkpoint as m2kt_ckpt
    from move2kube_tpu.models.compile_cache import bake_prewarm
    from move2kube_tpu.models.llama import Llama, llama_tiny
    from move2kube_tpu.obs.metrics import Registry
    from move2kube_tpu.serving.engine import EngineConfig
    from move2kube_tpu.serving.fleet import weights as weightslib
    from move2kube_tpu.serving.fleet.chaos import ChaosConfig, ServingChaos
    from move2kube_tpu.serving.fleet.router import build_fleet

    # the probe owns the cache/prewarm knobs: ambient developer settings
    # must not leak into the before/after measurement
    for key in ("M2KT_COMPILE_CACHE", "M2KT_COMPILE_CACHE_DIR",
                "M2KT_PREWARM_DIR", "M2KT_WEIGHTS_PEERS"):
        os.environ.pop(key, None)

    n_replicas = int(os.environ.get("M2KT_BENCH_SWAP_REPLICAS", "4"))
    assert n_replicas >= 3, "swap drill needs >= 3 replicas/peers"
    n_tenants = int(os.environ.get("M2KT_BENCH_SWAP_TENANTS", "4"))
    n_requests = int(os.environ.get("M2KT_BENCH_SWAP_REQUESTS", "16"))
    max_new = 8
    floor = float(os.environ.get("M2KT_BENCH_SWAP_SPEEDUP_FLOOR", "1.2"))

    cfg = dataclasses.replace(llama_tiny(), dtype=jnp.float32,
                              attn_impl="dense")
    model = Llama(cfg)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 8), jnp.int32))
    ecfg = EngineConfig(max_batch=2, max_seq=128, block_size=8,
                        buckets=(64,), prefix_cache=True)

    root = tempfile.mkdtemp(prefix="m2kt-swap-")
    ckpt_dir = os.path.join(root, "ckpt")
    prewarm_dir = os.path.join(root, "prewarm")
    cache_store = os.path.join(root, "cache-store")
    cache_p2p = os.path.join(root, "cache-p2p")

    # the object store a cold replica restores from when no peer serves
    mngr = m2kt_ckpt.CheckpointManager(ckpt_dir, every=1)
    mngr.maybe_save(0, {"params": variables["params"]}, force=True)
    mngr.wait()
    mngr.close()

    rng = np.random.default_rng(11)
    prefixes = [rng.integers(1, cfg.vocab_size, size=40).tolist()
                for _ in range(n_tenants)]
    tenant_ids = np.minimum(rng.zipf(1.6, size=n_requests), n_tenants) - 1
    prompts = [prefixes[t] + rng.integers(1, cfg.vocab_size,
                                          size=2).tolist()
               for t in tenant_ids]

    def cold_boot(mode, **extra_env):
        """One genuinely cold replica boot in a grandchild process."""
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   JAX_PLATFORM_NAME="cpu", PALLAS_AXON_POOL_IPS="",
                   M2KT_SWAP_BOOT=mode, M2KT_SWAP_CKPT_DIR=ckpt_dir,
                   **extra_env)
        res = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--swap-boot-probe"],
            env=env, capture_output=True, text=True,
            timeout=CHILD_TIMEOUT_S)
        if res.returncode != 0:
            raise RuntimeError(
                f"{mode} boot rc={res.returncode}: {res.stderr[-300:]}")
        return json.loads(res.stdout.strip().splitlines()[-1])

    # the loaded fleet the cold replica joins: serves traffic (golden
    # replay for the drill) and weight shards over HTTP (the P2P boot's
    # peer — the same listener contract as the serve template's
    # weights port)
    router_g = build_fleet(model, variables, n_replicas,
                           engine_config=ecfg)
    plane = weightslib.WeightPlane(
        router_g.replicas[0].engine.variables,
        router_g.replicas[0].engine.weights_version)

    class WeightsHandler(http.server.BaseHTTPRequestHandler):
        def log_message(self, *args):
            pass

        def do_GET(self):
            try:
                if self.path == "/weights/manifest":
                    body = plane.manifest().to_bytes()
                else:
                    tail = urllib.parse.unquote(
                        self.path[len("/weights/"):])
                    body = plane.shard_bytes(tail)
            except ValueError:
                self.send_error(404)
                return
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    weights_srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0),
                                                  WeightsHandler)
    threading.Thread(target=weights_srv.serve_forever,
                     daemon=True).start()
    weights_port = weights_srv.server_address[1]
    try:
        for rep in router_g.replicas:
            rep.generate(prompts[0][:10], max_new_tokens=4)
        golden = [list(router_g.generate(list(p), max_new_tokens=max_new,
                                         tenant=f"tenant-{t}")["tokens"])
                  for p, t in zip(prompts, tenant_ids)]

        # boot 1 — the pre-weight-plane path: checkpoint restore + full
        # compile into an empty cache dir (which the bake then snapshots)
        boot_store = cold_boot("store",
                               M2KT_COMPILE_CACHE_DIR=cache_store)
        ttft_store = float(boot_store["ttft_s"])

        baked = bake_prewarm(prewarm_dir, cache_dir=cache_store)
        assert baked > 0, "bake_prewarm produced an empty artifact"

        # boot 2 — the weight-plane path: shards streamed over HTTP
        # from the serving fleet, executables thawed from the prewarm
        # artifact into a fresh empty cache dir
        boot_p2p = cold_boot(
            "p2p", M2KT_COMPILE_CACHE_DIR=cache_p2p,
            M2KT_PREWARM_DIR=prewarm_dir,
            M2KT_WEIGHTS_PEERS=f"127.0.0.1:{weights_port}")
        ttft_p2p = float(boot_p2p["ttft_s"])
        assert boot_p2p["version"] == 1
    finally:
        weights_srv.shutdown()
        for rep in router_g.replicas:
            rep.close()

    seeded = len([f for f in os.listdir(cache_p2p)
                  if f.endswith("-cache")])
    assert seeded > 0, "prewarm seeded nothing into the cold cache"
    speedup = ttft_store / max(1e-9, ttft_p2p)
    assert speedup >= floor, (
        f"cold join via P2P+prewarm ({ttft_p2p:.2f}s) is not "
        f"x{floor} faster than store+compile ({ttft_store:.2f}s): "
        f"x{speedup:.2f}")

    # ---- live swap under chaos: threaded replay mid-flight while the
    # new generation streams P2P past a corrupting peer and a dying
    # peer, then rolls across the fleet killing one replica mid-swap
    router_c = build_fleet(model, variables, n_replicas,
                           engine_config=ecfg)
    reg_b = Registry()
    try:
        for rep in router_c.replicas:
            rep.generate(prompts[0][:10], max_new_tokens=4)

        results: list = [None] * n_requests
        done_lock = threading.Lock()
        done_count = [0]

        def one(i):
            out = router_c.generate(list(prompts[i]),
                                    max_new_tokens=max_new,
                                    tenant=f"tenant-{tenant_ids[i]}")
            with done_lock:
                done_count[0] += 1
            results[i] = list(out["tokens"])

        planes = [weightslib.WeightPlane(rep.engine.variables,
                                         rep.engine.weights_version)
                  for rep in router_c.replicas]
        # separate exactly-once markers: a shared marker would let the
        # first fault claim it and disarm the second
        chaos_peers = [
            weightslib.InProcessWeightPeer(
                "peer-0", planes[0], chaos=ServingChaos(ChaosConfig(
                    shard_kill_n=2,
                    marker=os.path.join(root, "peer-kill-fired")))),
            weightslib.InProcessWeightPeer(
                "peer-1", planes[1], chaos=ServingChaos(ChaosConfig(
                    shard="corrupt",
                    marker=os.path.join(root, "corrupt-fired")))),
        ] + [weightslib.InProcessWeightPeer(f"peer-{i}", planes[i])
             for i in range(2, n_replicas)]

        with ThreadPoolExecutor(max_workers=3) as pool:
            futs = [pool.submit(one, i) for i in range(n_requests)]
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                with done_lock:
                    done_at_swap = done_count[0]
                if done_at_swap >= max(1, n_requests // 3):
                    break
                time.sleep(0.01)
            in_flight = n_requests - done_at_swap
            assert in_flight >= 1, "replay drained before the swap fired"

            fetched = weightslib.fetch_from_peers(chaos_peers,
                                                  registry=reg_b)
            assert fetched is not None, (
                "P2P fetch did not survive shard corruption + peer death")
            new_vars, _ = fetched

            router_c.replicas[-1].chaos = ServingChaos(ChaosConfig(
                swap="kill",
                marker=os.path.join(root, "swap-kill-fired")))
            swap_out = router_c.swap(variables=new_vars, version=2)
            for f in futs:
                f.result(timeout=120)

        assert all(r is not None for r in results), (
            "requests were lost across the live swap")
        exact = sum(1 for a, b in zip(results, golden) if a == b)
        frac = exact / n_requests
        assert frac == 1.0, (
            f"only {exact}/{n_requests} streams token-identical across "
            f"the live swap")
        for name in ("peer-kill-fired", "corrupt-fired",
                     "swap-kill-fired"):
            assert os.path.exists(os.path.join(root, name)), (
                f"chaos fault {name} never fired")

        def total(text, pat):
            return sum(float(m.group(1)) for m in re.finditer(pat, text))

        fetch_text = reg_b.render()
        mismatches = total(
            fetch_text, r'm2kt_weights_fetch_total\{[^}]*'
                        r'reason="digest_mismatch"[^}]*\} ([0-9.e+-]+)')
        deaths = total(
            fetch_text, r'm2kt_weights_fetch_total\{[^}]*'
                        r'reason="connection"[^}]*\} ([0-9.e+-]+)')
        assert mismatches >= 1, "corrupted shard was not digest-caught"
        assert deaths >= 1, "peer death left no connection trace"

        assert swap_out["weights_version"] == 2
        assert swap_out["failed"] == 1, (
            f"expected exactly the chaos victim to fail its swap: "
            f"{swap_out}")
        assert swap_out["swapped"] == n_replicas - 1, (
            f"swap did not roll across the survivors: {swap_out}")
        router_text = router_c.registry.render()
        swap_ok = total(
            router_text, r'm2kt_router_swap_total\{[^}]*'
                         r'outcome="ok"[^}]*\} ([0-9.e+-]+)')
        assert swap_ok == n_replicas - 1
        survivors = [rep for rep in router_c.replicas if rep.healthy()]
        assert survivors and all(
            rep.engine.weights_version == 2 for rep in survivors), (
            "a surviving replica did not converge on the new generation")
    finally:
        for rep in router_c.replicas:
            rep.close()

    print(json.dumps({
        "replicas": n_replicas, "requests": n_requests,
        "ttft_store_s": round(ttft_store, 3),
        "ttft_p2p_s": round(ttft_p2p, 3),
        "cold_join_ttft_speedup": round(speedup, 3),
        "speedup_floor": floor,
        "prewarm_entries": int(baked),
        "seeded_entries": int(seeded),
        "swapped_version": 2,
        "swap_ok": int(swap_out["swapped"]),
        "swap_failed": int(swap_out["failed"]),
        "in_flight_at_swap": int(in_flight),
        "swap_token_exact_fraction": round(frac, 3),
        "digest_mismatch_total": int(mismatches),
        "connection_total": int(deaths),
    }), flush=True)
    return 0


def bench_quant(n: int) -> dict:
    """Low-precision serving phase on forced host devices: the serving
    probe's mixed-length stream decoded at fp32, int8 weights, int8
    weights + int8 KV, and int8-kv + speculative decoding. The primary
    number is the int8/fp32 decode speedup; the phase FAILS when any of
    the deterministic gates break — int8 must beat fp32, the int8 logit
    gate must hold while trajectories coincide, quantized params must
    shrink below half, spec-decode streams must equal plain greedy
    exactly with acceptance >= 0.5, and every mode must hold the
    compiled-executable bound. int8-kv must beat fp32 outright: the
    fused paged-decode kernel's folded-scale algorithm (its jnp
    reference path off-TPU) applies row scales after the contractions,
    so dequant costs one multiply per score instead of per context
    element. The pre-kernel tolerance floor survives only as an
    explicit opt-in for no-kernel fallback runs — set BOTH
    M2KT_SERVE_KERNELS=off and M2KT_BENCH_QUANT_KV_FLOOR (docs/USAGE).
    Own subprocess for the same reason as the serving phase: the probe
    must own jax's platform env before import."""
    import subprocess

    env = dict(os.environ, JAX_PLATFORMS="cpu", JAX_PLATFORM_NAME="cpu",
               PALLAS_AXON_POOL_IPS="")
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if not f.startswith("--xla_force_host_platform_device_count")]
    flags.append("--xla_force_host_platform_device_count=8")
    env["XLA_FLAGS"] = " ".join(flags)
    t0 = time.perf_counter()
    res = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--quant-probe"],
        env=env, capture_output=True, text=True, timeout=CHILD_TIMEOUT_S)
    if res.returncode != 0:
        raise RuntimeError(
            f"quant probe rc={res.returncode}: {res.stderr[-300:]}")
    probe = json.loads(res.stdout.strip().splitlines()[-1])
    dt = time.perf_counter() - t0
    print(f"[bench] quant fp32 {probe['fp32_tokens_s']:.1f} -> int8 "
          f"{probe['int8_tokens_s']:.1f} -> int8-kv "
          f"{probe['int8_kv_tokens_s']:.1f} tok/s "
          f"(spec {probe['spec_tokens_s']:.1f} tok/s @ acceptance "
          f"{probe['spec_acceptance_rate']:.2f}, "
          f"params x{probe['param_bytes_ratio']:.2f}, "
          f"logit rel err {probe['int8_logit_max_rel_err']:.4f}) "
          f"in {dt:.1f}s", file=sys.stderr)
    metric, unit = PHASE_METRICS["quant"]
    return {"phase": "quant", "metric": metric,
            "value": probe["int8_speedup_vs_fp32"], "unit": unit,
            # cross-round anchor: the round-9 serving phase captured
            # 143 tok/s fp32 decode on this host probe (BENCH_NOTES)
            "vs_baseline": 0.0, "baseline": "none_published",
            **{k: probe[k] for k in (
                "fp32_tokens_s", "int8_tokens_s", "int8_kv_tokens_s",
                "fp32_long_tokens_s",
                "spec_tokens_s", "int8_speedup_vs_fp32",
                "int8_kv_ratio_vs_fp32", "spec_acceptance_rate",
                "spec_tokens_per_step", "param_bytes_ratio",
                "int8_logit_max_rel_err", "compile_bound_ok")},
            "wall_s": round(dt, 2)}


def _quant_kv_floor() -> float | None:
    """Opt-in int8-kv tolerance floor for NO-KERNEL runs only. With the
    fused kernel's folded-scale path active (the default), int8-kv must
    beat fp32 outright and this returns None; the floor is honored only
    when the run explicitly disables kernels (M2KT_SERVE_KERNELS=off)
    AND explicitly sets M2KT_BENCH_QUANT_KV_FLOOR."""
    raw = os.environ.get("M2KT_BENCH_QUANT_KV_FLOOR", "")
    kernels_off = os.environ.get("M2KT_SERVE_KERNELS", "").strip().lower() \
        in ("off", "0", "false")
    if raw and kernels_off:
        return float(raw)
    return None


def bench_kernels(n: int) -> dict:
    """Serving-kernel microbench on forced host devices: each PR-11
    kernel against its reference path at the serving decode geometry,
    with roofline placement from obs/costmodel. The phase FAILS when the
    fused paged-decode path loses to its own pre-kernel reference — a
    kernel that regresses its baseline is a bug, not a data point. Own
    subprocess for the same platform-env reason as the quant phase."""
    import subprocess

    env = dict(os.environ, JAX_PLATFORMS="cpu", JAX_PLATFORM_NAME="cpu",
               PALLAS_AXON_POOL_IPS="")
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if not f.startswith("--xla_force_host_platform_device_count")]
    flags.append("--xla_force_host_platform_device_count=8")
    env["XLA_FLAGS"] = " ".join(flags)
    t0 = time.perf_counter()
    res = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--kernels-probe"],
        env=env, capture_output=True, text=True, timeout=CHILD_TIMEOUT_S)
    if res.returncode != 0:
        raise RuntimeError(
            f"kernels probe rc={res.returncode}: {res.stderr[-300:]}")
    probe = json.loads(res.stdout.strip().splitlines()[-1])
    dt = time.perf_counter() - t0
    print(f"[bench] kernels paged-decode int8 fused "
          f"{probe['fused_int8_tok_s']:.0f} tok/s vs naive ref "
          f"{probe['naive_ref_tok_s']:.0f} "
          f"(x{probe['fused_speedup_vs_ref']:.2f}, roofline "
          f"{probe['fused_roofline']}, fp32 path "
          f"{probe['fp32_path_tok_s']:.0f}, collective matmul "
          f"x{probe['collective_matmul_ratio']:.2f}) in {dt:.1f}s",
          file=sys.stderr)
    metric, unit = PHASE_METRICS["kernels"]
    return {"phase": "kernels", "metric": metric,
            "value": probe["fused_speedup_vs_ref"], "unit": unit,
            "vs_baseline": 0.0, "baseline": "none_published",
            **{k: probe[k] for k in (
                "fused_int8_tok_s", "naive_ref_tok_s",
                "fused_speedup_vs_ref", "fp32_path_tok_s",
                "interpret_kernel_tok_s", "fused_roofline",
                "fused_arith_intensity", "fused_mfu_int8",
                "collective_matmul_ratio", "backend")},
            "wall_s": round(dt, 2)}


def run_kernels_probe() -> int:
    """In-process half of the kernels phase. Times, at the long-context
    serving decode geometry (llama_tiny heads, 256-token fixed-shape
    context, ragged fill):

    - the DISPATCHED fused paged-decode path (what serving actually
      runs on this backend: compiled Pallas kernel on TPU, the folded-
      scale jnp reference off-TPU) vs the pre-kernel naive reference
      that gathers and materializes the dequantized fp32 context —
      GATED: losing to your own baseline fails the phase;
    - the fp32 dispatched path (context);
    - ONE interpret-mode fused-kernel call (reported, not gated: the
      Pallas interpreter proves kernel bodies, not performance);
    - the collective-overlapped decode matmul vs plain ``x @ w`` on the
      8-device host mesh (reported, not gated off-TPU: ppermute hops
      are real sends on a host mesh, the overlap win needs ICI).

    Roofline placement: the fused path's compiled executable goes
    through obs/costmodel (flops, bytes, intensity -> compute- or
    bandwidth-bound, MFU against the int8 peak) and the probe asserts
    the placement is derivable — a kernel the cost model cannot see
    would silently fall out of the serving fit reports."""
    import functools

    import numpy as np

    import jax
    import jax.numpy as jnp

    from move2kube_tpu.obs import costmodel
    from move2kube_tpu.ops import attention as A
    from move2kube_tpu.parallel import overlap as OV

    trials = int(os.environ.get("M2KT_BENCH_KERNELS_TRIALS", "5"))
    b, h, kvh, d = 4, 4, 2, 32          # llama_tiny decode heads
    bs, mb = 8, 32                      # 256-token fixed-shape context
    num_pages = 1 + b * mb
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(b, h, d)), jnp.float32)
    kp8 = jnp.asarray(rng.integers(-127, 128, size=(num_pages, bs, kvh, d)),
                      jnp.int8)
    vp8 = jnp.asarray(rng.integers(-127, 128, size=(num_pages, bs, kvh, d)),
                      jnp.int8)
    ks = jnp.asarray(rng.uniform(0.001, 0.02, size=(num_pages, bs, kvh)),
                     jnp.float32)
    vs = jnp.asarray(rng.uniform(0.001, 0.02, size=(num_pages, bs, kvh)),
                     jnp.float32)
    kpf = jnp.asarray(rng.normal(size=(num_pages, bs, kvh, d)), jnp.float32)
    vpf = jnp.asarray(rng.normal(size=(num_pages, bs, kvh, d)), jnp.float32)
    lens = [45, 230, 120, 175]          # ragged fill, one near-full
    sl = jnp.asarray(lens, jnp.int32)
    bt = np.zeros((b, mb), np.int32)
    used = 1
    for i, length in enumerate(lens):
        pages = -(-length // bs)
        bt[i, :pages] = np.arange(used, used + pages)
        used += pages
    bt = jnp.asarray(bt)
    scale = d ** -0.5

    def naive_ref(q, kp, vp, bt, sl, ks, vs):
        # the pre-PR-11 reference: gather, materialize the dequantized
        # fp32 context in memory, repeat for GQA, then attend
        k = (kp[bt].astype(jnp.float32) * ks[bt][..., None]).reshape(
            b, mb * bs, kvh, d)
        v = (vp[bt].astype(jnp.float32) * vs[bt][..., None]).reshape(
            b, mb * bs, kvh, d)
        k = jnp.repeat(k, h // kvh, axis=2)
        v = jnp.repeat(v, h // kvh, axis=2)
        s = jnp.einsum("bhd,bshd->bhs", q.astype(jnp.float32), k) * scale
        valid = jnp.arange(mb * bs)[None, None, :] < sl[:, None, None]
        s = jnp.where(valid, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhs,bshd->bhd", p, v).astype(q.dtype)

    fused = jax.jit(lambda q, kp, vp, bt, sl, ks, vs:
                    A.paged_decode_attention(q, kp, vp, bt, sl,
                                             k_scale=ks, v_scale=vs))
    naive = jax.jit(naive_ref)
    fp32_path = jax.jit(lambda q, kp, vp, bt, sl:
                        A.paged_decode_attention(q, kp, vp, bt, sl))

    def tok_s(fn, *args, calls: int = 50) -> float:
        jax.block_until_ready(fn(*args))          # compile + warm
        best = 0.0
        for _ in range(max(1, trials)):
            t0 = time.perf_counter()
            for _ in range(calls):
                out = fn(*args)
            jax.block_until_ready(out)
            best = max(best, b * calls / (time.perf_counter() - t0))
        return best

    fused_tok_s = tok_s(fused, q, kp8, vp8, bt, sl, ks, vs)
    naive_tok_s = tok_s(naive, q, kp8, vp8, bt, sl, ks, vs)
    fp32_tok_s = tok_s(fp32_path, q, kpf, vpf, bt, sl)

    # one interpreted fused-kernel call (proves the body runs; perf is
    # interpreter overhead, so a single timed call, never gated)
    t0 = time.perf_counter()
    jax.block_until_ready(A._paged_decode_packed(
        q, kp8, vp8, bt, sl, scale, k_scale=ks, v_scale=vs,
        interpret=True))
    interp_tok_s = b / (time.perf_counter() - t0)

    # collective-overlapped decode matmul vs plain on the host mesh
    coll_ratio = 0.0
    if len(jax.devices()) >= 2:
        from jax.sharding import Mesh

        ndev = len(jax.devices())
        mesh = Mesh(np.array(jax.devices()).reshape(ndev), ("model",))
        x = jnp.asarray(rng.normal(size=(b, 256)), jnp.float32)
        w = jnp.asarray(rng.normal(size=(256, 512)), jnp.float32)
        plain = jax.jit(lambda x, w: x @ w)
        coll = jax.jit(functools.partial(OV.collective_decode_matmul, mesh))
        err = float(jnp.max(jnp.abs(coll(x, w) - plain(x, w))))
        assert err < 1e-3, f"collective matmul diverged: {err}"
        coll_ratio = tok_s(coll, x, w) / tok_s(plain, x, w)

    # roofline placement of the fused path's compiled executable
    compiled = costmodel.lower_and_compile(fused, q, kp8, vp8, bt, sl,
                                           ks, vs)
    report = costmodel.analyze_compiled(compiled) if compiled else None
    spec, _ = costmodel.chip_spec()
    roofline = report.roofline(spec) if report else "unknown"
    intensity = report.arithmetic_intensity if report else None
    step_s = b * 50 / fused_tok_s / 50  # seconds per fused call
    mfu = report.mfu(step_s, spec, int8=True) if report else None
    assert report is not None and roofline != "unknown", (
        "fused paged-decode kernel is invisible to the cost model")

    # THE gate: the fused path must beat the pre-kernel reference
    assert fused_tok_s > naive_tok_s, (
        f"fused paged-decode {fused_tok_s:.0f} tok/s lost to its own "
        f"reference {naive_tok_s:.0f} tok/s")

    print(json.dumps({
        "fused_int8_tok_s": round(fused_tok_s, 1),
        "naive_ref_tok_s": round(naive_tok_s, 1),
        "fused_speedup_vs_ref": round(fused_tok_s / naive_tok_s, 3),
        "fp32_path_tok_s": round(fp32_tok_s, 1),
        "interpret_kernel_tok_s": round(interp_tok_s, 1),
        "fused_roofline": roofline,
        "fused_arith_intensity": (round(intensity, 3)
                                  if intensity else None),
        "fused_mfu_int8": round(mfu, 6) if mfu else None,
        "collective_matmul_ratio": round(coll_ratio, 3),
        "backend": jax.default_backend(),
    }), flush=True)
    return 0


def run_quant_probe() -> int:
    """In-process half of the quant phase (spawned by bench_quant with
    jax forced onto host devices). Decodes the serving probe's stream
    under four engine configs, checks every deterministic gate, and
    prints one JSON line."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from move2kube_tpu.models.llama import Llama, llama_tiny
    from move2kube_tpu.serving import quant as quantlib
    from move2kube_tpu.serving.engine import (
        EngineConfig,
        Request,
        ServingEngine,
    )

    cfg = dataclasses.replace(llama_tiny(), dtype=jnp.float32)
    model = Llama(cfg)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 8), jnp.int32))
    # TWO geometries (round 16), because the two quant wins live in
    # different regimes and the decode step is fixed-shape (per-step
    # cost tracks max_seq pages, not actual prompt lengths): the short
    # geometry keeps per-step fixed cost dominant, where int8 WEIGHTS
    # win; the long geometry (256-token fixed-shape context) is the
    # KV-bytes-dominated regime the int8-kv policy exists for, where the
    # fused kernel's folded-scale path must beat fp32 outright.
    lengths = [3, 7, 12, 20, 30, 5, 16, 25, 9, 31, 4, 14, 22, 6, 28, 11]
    long_lengths = [55, 120, 200, 90, 230, 70, 150, 45,
                    175, 105, 60, 135, 220, 80, 190, 110]
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, cfg.vocab_size, size=l).tolist()
               for l in lengths]
    long_prompts = [rng.integers(1, cfg.vocab_size, size=l).tolist()
                    for l in long_lengths]

    def stream():
        return [Request(rid=f"r{i}", prompt=list(p))
                for i, p in enumerate(prompts)]

    def long_stream():
        return [Request(rid=f"L{i}", prompt=list(p))
                for i, p in enumerate(long_prompts)]

    def engine(**over):
        return ServingEngine(model, variables, EngineConfig(
            **{**dict(max_batch=4, max_seq=64, block_size=8,
                      buckets=(8, 16, 32), max_new_tokens=8), **over}))

    def long_engine(**over):
        return ServingEngine(model, variables, EngineConfig(
            **{**dict(max_batch=4, max_seq=256, block_size=8,
                      buckets=(64, 128, 256), max_new_tokens=8), **over}))

    # one engine per mode, all warmed up front, then trials interleaved
    # round-robin across modes: host-CPU load drifts on the scale of a
    # full stream replay, so sequential per-mode measurement lets drift
    # masquerade as a mode difference and invert the int8-vs-fp32
    # ordering — interleaving makes every mode sample the same drift.
    # Per-interval throughput comes from the engine's own decode
    # counters as deltas (compilation never pollutes it); best-of wins
    # per mode because dispatch jitter is one-sided noise.
    trials = int(os.environ.get("M2KT_BENCH_QUANT_TRIALS", "5"))
    engines = {
        "fp32": (engine(), stream),
        "int8": (engine(quant="int8"), stream),
        "int8_kv": (engine(quant="int8-kv"), stream),
        "spec": (engine(quant="int8-kv", spec_k=3, spec_draft_factor=1),
                 stream),
        "fp32_long": (long_engine(), long_stream),
        "int8_kv_long": (long_engine(quant="int8-kv"), long_stream),
    }
    best = {m: 0.0 for m in engines}
    toks = {}
    for eng, mk in engines.values():
        eng.run(mk())
    for _ in range(trials):
        for mode, (eng, mk) in engines.items():
            t0, k0 = eng._decode_time, eng._decode_tokens
            comps = eng.run(mk())
            best[mode] = max(best[mode], (eng._decode_tokens - k0)
                             / max(1e-9, eng._decode_time - t0))
            toks[mode] = {c.rid: c.tokens for c in comps}
    bounds_ok = True
    for eng, _ in engines.values():
        report = eng.compile_report()
        total = report.get("total_executables", -1)
        bounds_ok &= bool(0 <= total <= report["num_buckets"] + 2)
    fp32_tok_s, int8_tok_s = best["fp32"], best["int8"]
    spec_tok_s = best["spec"]
    fp32_long_tok_s, kv_tok_s = best["fp32_long"], best["int8_kv_long"]
    kv_toks, spec_toks = toks["int8_kv"], toks["spec"]
    stats = engines["spec"][0].stats()

    # gate 1: spec decode is greedy-exact vs plain decode at the same
    # quant level, and the full-depth draft clears the acceptance bar
    assert spec_toks == kv_toks, "spec-decode stream diverged from greedy"
    assert stats["spec_acceptance_rate"] >= 0.5, stats
    # gate 2: quantized parameters actually shrink
    ratio = (quantlib.param_bytes(quantlib.quantize_variables(variables))
             / quantlib.param_bytes(variables))
    assert ratio < 0.5, f"int8 params only x{ratio:.2f} of fp32"
    # gate 3: int8 logits stay inside the relative-error gate while the
    # greedy trajectories coincide
    cap_ref = engine()
    cap_int8 = engine(quant="int8")
    cap_ref.capture_logits = cap_int8.capture_logits = True
    reqs = stream()[:4]
    ref_c = {c.rid: c for c in cap_ref.run(
        [Request(r.rid, list(r.prompt)) for r in reqs])}
    got_c = {c.rid: c for c in cap_int8.run(reqs)}
    max_rel = 0.0
    for r in reqs:
        a_t, b_t = ref_c[r.rid].tokens, got_c[r.rid].tokens
        agree = 0
        while agree < min(len(a_t), len(b_t)) and a_t[agree] == b_t[agree]:
            agree += 1
        for i in range(min(agree + 1, len(cap_ref.logit_log[r.rid]),
                           len(cap_int8.logit_log[r.rid]))):
            gate = quantlib.logit_gate(cap_ref.logit_log[r.rid][i],
                                       cap_int8.logit_log[r.rid][i])
            max_rel = max(max_rel, gate["max_rel_err"])
    assert max_rel < 0.05, f"int8 logit gate blew up: {max_rel:.4f}"
    # gate 4: perf — int8 weights must beat fp32 (fewer HBM bytes AND
    # fewer fp32 flops after dequant folding), and int8-kv must beat
    # fp32 outright on the fused kernel's folded-scale reference path;
    # only an explicit no-kernel run (_quant_kv_floor) keeps a floor
    assert int8_tok_s > fp32_tok_s, (
        f"int8 {int8_tok_s:.1f} tok/s did not beat fp32 "
        f"{fp32_tok_s:.1f} tok/s")
    floor = _quant_kv_floor()
    if floor is not None:
        assert kv_tok_s >= floor * fp32_long_tok_s, (
            f"int8-kv {kv_tok_s:.1f} tok/s fell below the opt-in "
            f"{floor:.2f}x fp32 floor ({fp32_long_tok_s:.1f} tok/s)")
    else:
        assert kv_tok_s > fp32_long_tok_s, (
            f"int8-kv {kv_tok_s:.1f} tok/s did not beat fp32 "
            f"{fp32_long_tok_s:.1f} tok/s at long context "
            f"(folded-scale path)")
    assert bounds_ok, "compile bound broken in some mode"

    print(json.dumps({
        "fp32_tokens_s": round(fp32_tok_s, 1),
        "int8_tokens_s": round(int8_tok_s, 1),
        "int8_kv_tokens_s": round(kv_tok_s, 1),
        "fp32_long_tokens_s": round(fp32_long_tok_s, 1),
        "spec_tokens_s": round(spec_tok_s, 1),
        "int8_speedup_vs_fp32": round(int8_tok_s / fp32_tok_s, 3),
        "int8_kv_ratio_vs_fp32": round(kv_tok_s / fp32_long_tok_s, 3),
        "spec_acceptance_rate": round(stats["spec_acceptance_rate"], 3),
        "spec_tokens_per_step": round(stats["spec_tokens_per_step"], 3),
        "param_bytes_ratio": round(ratio, 3),
        "int8_logit_max_rel_err": round(max_rel, 5),
        "compile_bound_ok": True,
    }), flush=True)
    return 0


OBS_OVERHEAD_MAX = float(os.environ.get("M2KT_BENCH_OBS_OVERHEAD_MAX",
                                        "0.03"))


def bench_obs(n: int) -> dict:
    """Telemetry-plane guard on forced host devices: the tiny-LM train
    step with per-step StepTelemetry recording vs bare, plus a real HTTP
    scrape of the registry. The phase FAILS (not just reports) when
    recording costs more than OBS_OVERHEAD_MAX of step time or the
    exposition isn't well-formed Prometheus text — observability that
    taxes the hot path or emits unscrapable output is a regression. Own
    subprocess for the same reason as the scaling phase: the probe must
    own jax's platform env before import."""
    import subprocess

    env = dict(os.environ, JAX_PLATFORMS="cpu", JAX_PLATFORM_NAME="cpu",
               PALLAS_AXON_POOL_IPS="")
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if not f.startswith("--xla_force_host_platform_device_count")]
    flags.append("--xla_force_host_platform_device_count=8")
    env["XLA_FLAGS"] = " ".join(flags)
    t0 = time.perf_counter()
    res = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--obs-probe"],
        env=env, capture_output=True, text=True, timeout=CHILD_TIMEOUT_S)
    if res.returncode != 0:
        raise RuntimeError(
            f"obs probe rc={res.returncode}: {res.stderr[-300:]}")
    probe = json.loads(res.stdout.strip().splitlines()[-1])
    dt = time.perf_counter() - t0
    overhead = probe["telemetry_overhead_fraction"]
    if not probe["exposition_ok"]:
        raise RuntimeError(
            f"malformed Prometheus exposition: bad_lines="
            f"{probe.get('bad_lines')} content_type="
            f"{probe.get('scrape_content_type')}")
    if overhead > OBS_OVERHEAD_MAX:
        raise RuntimeError(
            f"telemetry overhead {overhead:.1%} exceeds the "
            f"{OBS_OVERHEAD_MAX:.0%} budget "
            f"(base {probe['baseline_step_ms']:.2f}ms vs instrumented "
            f"{probe['instrumented_step_ms']:.2f}ms per step)")
    tracing_overhead = probe["tracing_overhead_fraction"]
    if tracing_overhead > OBS_OVERHEAD_MAX:
        raise RuntimeError(
            f"tracing-enabled overhead {tracing_overhead:.1%} exceeds the "
            f"{OBS_OVERHEAD_MAX:.0%} budget "
            f"(base {probe['baseline_step_ms']:.2f}ms vs traced "
            f"{probe['traced_step_ms']:.2f}ms per step)")
    print(f"[bench] obs overhead {overhead:.2%} "
          f"(tracing {tracing_overhead:.2%}; "
          f"{probe['baseline_step_ms']:.2f}ms -> "
          f"{probe['instrumented_step_ms']:.2f}ms/step), "
          f"{probe['exposition_samples']} samples scraped in {dt:.1f}s",
          file=sys.stderr)
    metric, unit = PHASE_METRICS["obs"]
    # no published baseline: the phase is an overhead budget guard
    return {"phase": "obs", "metric": metric, "value": overhead,
            "unit": unit, "vs_baseline": 0.0, "baseline": "none_published",
            "overhead_budget": OBS_OVERHEAD_MAX,
            "tracing_overhead_fraction": tracing_overhead,
            "baseline_step_ms": probe["baseline_step_ms"],
            "instrumented_step_ms": probe["instrumented_step_ms"],
            "traced_step_ms": probe["traced_step_ms"],
            "steps_per_run": probe["steps"],
            "exposition_ok": probe["exposition_ok"],
            "exposition_samples": probe["exposition_samples"],
            "scrape_content_type": probe["scrape_content_type"],
            "wall_s": round(dt, 2)}


def run_obs_probe() -> int:
    """In-process half of the obs phase (spawned by bench_obs with jax
    forced onto host devices). Times the tiny-LM step bare vs with
    per-step StepTelemetry recording (min of 3 runs each — host noise
    must not fail the budget), scrapes a live TelemetryServer, and
    prints one JSON line."""
    import dataclasses
    import re
    import urllib.request

    import jax
    import jax.numpy as jnp
    import optax

    from move2kube_tpu.models import train as m2kt_train
    from move2kube_tpu.models.llama import Llama, llama_tiny
    from move2kube_tpu.obs.metrics import Registry
    from move2kube_tpu.obs.server import TelemetryServer
    from move2kube_tpu.parallel.mesh import MeshConfig, make_mesh

    cfg = dataclasses.replace(llama_tiny(), dtype=jnp.float32)
    model = Llama(cfg)
    mesh = make_mesh(MeshConfig(data=1), devices=jax.devices()[:1])
    batch, seq, steps = 4, 64, 20
    ids = jax.random.randint(jax.random.PRNGKey(0), (batch, seq), 0,
                             cfg.vocab_size)
    state = m2kt_train.create_sharded_state(
        jax.random.PRNGKey(1), model, {"input_ids": ids},
        m2kt_train.instrument_optimizer(optax.adamw(3e-4)), mesh)
    step = m2kt_train.make_lm_train_step(mesh, remat=False)
    state, loss = step(state, {"input_ids": ids})  # compile
    jax.block_until_ready(loss)

    def run(telem):
        nonlocal state
        t0 = time.perf_counter()
        for i in range(1, steps + 1):
            ts = time.perf_counter()
            state, loss = step(state, {"input_ids": ids})
            loss = jax.block_until_ready(loss)
            if telem is not None:
                # worst case: every step records loss AND grad norm (the
                # emitted trainer only reads those back every 10th step)
                telem.record_step(i, time.perf_counter() - ts,
                                  loss=float(loss), state=state)
        return time.perf_counter() - t0

    from move2kube_tpu.obs.tracing import SpanRecorder

    reg = Registry()
    telem = m2kt_train.StepTelemetry(registry=reg,
                                     items_per_step=batch * seq,
                                     tracer=False)
    # third variant: telemetry + runtime tracing (per-step spans into the
    # bounded ring) — M2KT_TRACE defaults on, so its cost rides the same
    # <=3% budget as the metrics
    traced_telem = m2kt_train.StepTelemetry(registry=reg,
                                            items_per_step=batch * seq,
                                            tracer=SpanRecorder())
    # INTERLEAVED min-of-4: back-to-back blocks would attribute a
    # machine-load drift entirely to whichever variant ran second (round
    # 10: a sequential measurement failed the budget at "4.5%" that a
    # rerun measured as 0%)
    base = instrumented = traced = float("inf")
    for _ in range(4):
        base = min(base, run(None))
        instrumented = min(instrumented, run(telem))
        traced = min(traced, run(traced_telem))
    overhead = max(0.0, instrumented / base - 1.0)
    tracing_overhead = max(0.0, traced / base - 1.0)

    srv = TelemetryServer(port=0, registry=reg)
    srv.start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics", timeout=10) as resp:
            ctype = resp.headers.get("Content-Type", "")
            text = resp.read().decode("utf-8")
    finally:
        srv.close()
    # well-formed v0.0.4 text: every sample line is `name{labels} value`
    sample_re = re.compile(
        r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{.*\})? [^ ]+$')
    lines = [ln for ln in text.splitlines() if ln and not ln.startswith("#")]
    bad = [ln for ln in lines if not sample_re.match(ln)]
    exposition_ok = bool(
        not bad and lines and "# HELP" in text and "# TYPE" in text
        and "m2kt_train_step_seconds_bucket" in text
        and 'le="+Inf"' in text and "version=0.0.4" in ctype)
    print(json.dumps({
        "telemetry_overhead_fraction": round(overhead, 4),
        "tracing_overhead_fraction": round(tracing_overhead, 4),
        "baseline_step_ms": round(base / steps * 1e3, 3),
        "instrumented_step_ms": round(instrumented / steps * 1e3, 3),
        "traced_step_ms": round(traced / steps * 1e3, 3),
        "steps": steps,
        "exposition_ok": exposition_ok,
        "exposition_samples": len(lines),
        "bad_lines": bad[:3],
        "scrape_content_type": ctype,
    }), flush=True)
    return 0


def bench_numerics(n: int) -> dict:
    """Tensor-health-plane guard (PR 15): the tiny-LM step with the
    in-graph per-layer-group numerics summaries recording + StepTelemetry
    read-back vs the same chain with recording off, plus one live
    quant-drift audit on an int8 engine. FAILS when the numerics plane
    costs more than OBS_OVERHEAD_MAX of step time, when the auditor
    never fires, or when a *clean* int8 engine already reads as drifted
    (the alert floor would be noise, not signal). Own subprocess for the
    same platform-env reason as the obs phase."""
    import subprocess

    env = dict(os.environ, JAX_PLATFORMS="cpu", JAX_PLATFORM_NAME="cpu",
               PALLAS_AXON_POOL_IPS="")
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if not f.startswith("--xla_force_host_platform_device_count")]
    flags.append("--xla_force_host_platform_device_count=8")
    env["XLA_FLAGS"] = " ".join(flags)
    t0 = time.perf_counter()
    res = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--numerics-probe"],
        env=env, capture_output=True, text=True, timeout=CHILD_TIMEOUT_S)
    if res.returncode != 0:
        raise RuntimeError(
            f"numerics probe rc={res.returncode}: {res.stderr[-300:]}")
    probe = json.loads(res.stdout.strip().splitlines()[-1])
    dt = time.perf_counter() - t0
    overhead = probe["numerics_overhead_fraction"]
    if overhead > OBS_OVERHEAD_MAX:
        raise RuntimeError(
            f"numerics-plane overhead {overhead:.1%} exceeds the "
            f"{OBS_OVERHEAD_MAX:.0%} budget "
            f"(base {probe['baseline_step_ms']:.2f}ms vs instrumented "
            f"{probe['instrumented_step_ms']:.2f}ms per step)")
    if probe["drift_audits"] < 1:
        raise RuntimeError("quant-drift auditor never fired at rate=1.0")
    if probe["drift_clean_rel"] >= probe["drift_threshold"]:
        raise RuntimeError(
            f"clean int8 engine reads as drifted "
            f"({probe['drift_clean_rel']:.4f} >= "
            f"{probe['drift_threshold']} alert floor)")
    print(f"[bench] numerics overhead {overhead:.2%} "
          f"({probe['baseline_step_ms']:.2f}ms -> "
          f"{probe['instrumented_step_ms']:.2f}ms/step, "
          f"{probe['groups']} layer groups), clean int8 drift "
          f"{probe['drift_clean_rel']:.4f} in {dt:.1f}s",
          file=sys.stderr)
    metric, unit = PHASE_METRICS["numerics"]
    # no published baseline: the phase is an overhead budget guard
    return {"phase": "numerics", "metric": metric, "value": overhead,
            "unit": unit, "vs_baseline": 0.0, "baseline": "none_published",
            "overhead_budget": OBS_OVERHEAD_MAX,
            "baseline_step_ms": probe["baseline_step_ms"],
            "instrumented_step_ms": probe["instrumented_step_ms"],
            "steps_per_run": probe["steps"],
            "layer_groups": probe["groups"],
            "drift_clean_rel": probe["drift_clean_rel"],
            "drift_audits": probe["drift_audits"],
            "wall_s": round(dt, 2)}


def run_numerics_probe() -> int:
    """In-process half of the numerics phase. Times the tiny-LM step
    with the tensor-health recorder ON (in-graph summaries + per-step
    StepTelemetry read-back into the gauges) vs OFF — the identity-state
    chain, so both sides compile the same opt-state pytree — interleaved
    min-of-4 like the obs probe. Then runs one audited prefill on a
    clean int8 engine and prints one JSON line."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    import optax

    from move2kube_tpu.models import train as m2kt_train
    from move2kube_tpu.models.llama import Llama, llama_tiny
    from move2kube_tpu.obs import numerics as numericslib
    from move2kube_tpu.obs.metrics import Registry
    from move2kube_tpu.obs.rules import THRESHOLDS
    from move2kube_tpu.parallel.mesh import MeshConfig, make_mesh

    cfg = dataclasses.replace(llama_tiny(), dtype=jnp.float32)
    model = Llama(cfg)
    mesh = make_mesh(MeshConfig(data=1), devices=jax.devices()[:1])
    # larger token budget than the obs probe: the summaries' cost is
    # PARAM-bound (a few fixed passes over weights+grads, ~3ms here)
    # while step cost is TOKEN-bound, so a toy 4x64 batch makes a
    # constant cost read as a fat fraction (+8% measured) that no real
    # workload would see. 8x256 is still tiny but token-shaped enough
    # for the fraction to be honest.
    batch, seq, steps = 8, 256, 10
    ids = jax.random.randint(jax.random.PRNGKey(0), (batch, seq), 0,
                             cfg.vocab_size)

    def make_state(record):
        tx = optax.chain(m2kt_train.grad_norm_recorder(),
                         numericslib.health_recorder(record=record),
                         optax.adamw(3e-4))
        return m2kt_train.create_sharded_state(
            jax.random.PRNGKey(1), model, {"input_ids": ids}, tx, mesh)

    step = m2kt_train.make_lm_train_step(mesh, remat=False)

    def make_telem(numerics_on):
        # StepTelemetry resolves M2KT_NUMERICS at construction
        os.environ["M2KT_NUMERICS"] = "1" if numerics_on else "0"
        return m2kt_train.StepTelemetry(registry=Registry(),
                                        items_per_step=batch * seq,
                                        tracer=False)

    def run(record):
        state = make_state(record)
        telem = make_telem(record)
        state, loss = step(state, {"input_ids": ids})  # compile
        jax.block_until_ready(loss)
        per_step = []
        for i in range(1, steps + 1):
            ts = time.perf_counter()
            state, loss = step(state, {"input_ids": ids})
            loss = jax.block_until_ready(loss)
            dt = time.perf_counter() - ts
            # worst case: EVERY step reads the health vectors back (the
            # emitted trainer syncs every 10th step)
            telem.record_step(i, dt, loss=float(loss), state=state)
            per_step.append(time.perf_counter() - ts)
        return per_step

    # interleaved rounds, then min over PER-STEP durations (read-back
    # included): loop totals on a loaded host are dominated by scheduler
    # noise — a 10-step block absorbs whole load spikes and min-of-4
    # totals still mis-measured this plane by 30+ms/step — while the
    # fastest single step each variant ever achieves is the honest
    # unloaded cost (see run_obs_probe for the interleaving rationale)
    base_steps: list[float] = []
    inst_steps: list[float] = []
    for r in range(4):
        # alternate order each round so load ramping WITHIN a round
        # can't systematically tax one variant
        for rec in ((False, True) if r % 2 == 0 else (True, False)):
            (inst_steps if rec else base_steps).extend(run(rec))
    base = min(base_steps) * steps
    instrumented = min(inst_steps) * steps
    overhead = max(0.0, instrumented / base - 1.0)
    groups = len(numericslib.group_index(
        make_state(False).params)[0])

    # live quant-drift audit: every cold admission on a clean int8
    # engine re-runs through the fp reference; the drift must sit well
    # under the alert floor or M2KTQuantDriftHigh is unusable
    from move2kube_tpu.serving.engine import (
        EngineConfig, Request, ServingEngine,
    )

    svars = model.init(jax.random.PRNGKey(2),
                       jnp.zeros((1, 8), jnp.int32))
    eng = ServingEngine(model, svars, EngineConfig(
        max_batch=2, max_seq=32, block_size=8, buckets=(8,),
        quant="int8", quant_audit_rate=1.0))
    eng.run([Request("audit", [1, 2, 3, 4], 2)])
    stats = eng.stats()

    print(json.dumps({
        "numerics_overhead_fraction": round(overhead, 4),
        "baseline_step_ms": round(base / steps * 1e3, 3),
        "instrumented_step_ms": round(instrumented / steps * 1e3, 3),
        "steps": steps,
        "groups": groups,
        "drift_audits": stats.get("quant_audits", 0),
        "drift_clean_rel": round(stats.get("quant_drift_max_rel", 0.0), 5),
        "drift_threshold": float(THRESHOLDS["tpunumdriftmax"]),
    }), flush=True)
    return 0


def _setup_compile_cache() -> None:
    """Persistent XLA compile cache for this child: a re-spawned child
    (retry, OOM batch-halving) deserializes the previous child's
    executables instead of recompiling — the compile time that used to
    eat the wall-clock budget. Import stays inside the child: the parent
    never touches jax."""
    try:
        from move2kube_tpu.models.compile_cache import setup_compilation_cache

        d = setup_compilation_cache()
        if d:
            print(f"[bench] compile cache: {d}", file=sys.stderr)
    except Exception as e:  # noqa: BLE001 - caching is best-effort
        print(f"[bench] compile cache setup failed: {type(e).__name__}: {e}",
              file=sys.stderr)


def run_child(phases: list[str]) -> int:
    """Measure the requested phases, emitting one RESULT line per success.

    TPU phases run first (in PHASES order), pure-CPU phases after: if the
    child dies mid-run or the parent's budget expires, the scarce TPU
    numbers are already on stdout — `translate` can run in any child.
    The TPU backend is initialized lazily, only when a TPU phase is
    requested — a CPU-only child must not touch the (possibly hung)
    tunnel. Exit code is advisory (parent trusts RESULT lines, not rc):
    0 iff all requested phases succeeded."""
    phases = sorted(phases, key=lambda p: (
        p not in TPU_PHASES, PHASES.index(p) if p in PHASES else len(PHASES)))
    _setup_compile_cache()
    n = None
    if any(p in TPU_PHASES for p in phases):
        try:
            import jax

            n = jax.device_count()
            print(f"[bench] backend={jax.default_backend()} devices={n}",
                  file=sys.stderr)
        except Exception as e:  # noqa: BLE001 - report init failure and bail
            print(f"[bench] backend init failed: {type(e).__name__}: {e}",
                  file=sys.stderr)
            return 1
    fns = {"resnet": bench_resnet, "bert": bench_bert,
           "pallas": bench_pallas, "llama": bench_llama,
           "translate": bench_translate, "goodput": bench_goodput,
           "scaling": bench_scaling, "serving": bench_serving,
           "fleet": bench_fleet, "quant": bench_quant,
           "kernels": bench_kernels, "obs": bench_obs,
           "chaos": bench_chaos, "swap": bench_swap,
           "numerics": bench_numerics, "sched": bench_sched,
           "autoscale": bench_autoscale, "usage": bench_usage}
    ok = True
    for phase in phases:
        try:
            _emit(fns[phase](n))
        except Exception as e:  # noqa: BLE001 - next phase may still work
            ok = False
            print("PHASEFAIL " + json.dumps(
                {"phase": phase,
                 "error": f"{type(e).__name__}: {e}"[:300]}), flush=True)
            print(f"[bench] phase {phase} failed: {type(e).__name__}: {e}",
                  file=sys.stderr)
    return 0 if ok else 1


# --------------------------------------------------------------------------
# Parent: orchestration. No jax import anywhere on this path.
# --------------------------------------------------------------------------

MAX_PHASE_FAILS = 2  # in-child exceptions per phase before giving up on it


# env var carrying a phase's batch size into the child (module constants
# RESNET_BATCH/BERT_BATCH/LLAMA_BATCH read these at import)
PHASE_BATCH_ENV = {"resnet": "M2KT_BENCH_RESNET_BATCH",
                   "bert": "M2KT_BENCH_BERT_BATCH",
                   "llama": "M2KT_BENCH_LLAMA_BATCH"}


def _harvest(text: str, results: dict, fails: dict,
             oom_batches: dict | None = None) -> None:
    for line in text.splitlines():
        if line.startswith("RESULT "):
            try:
                r = json.loads(line[len("RESULT "):])
                results[r["phase"]] = r
            except (json.JSONDecodeError, KeyError):
                pass
        elif line.startswith("PHASEFAIL "):
            try:
                f = json.loads(line[len("PHASEFAIL "):])
                fails.setdefault(f["phase"], []).append(f.get("error", ""))
            except (json.JSONDecodeError, KeyError):
                pass
        elif line.startswith("OOMBATCH ") and oom_batches is not None:
            try:
                o = json.loads(line[len("OOMBATCH "):])
                oom_batches[o["phase"]] = min(
                    int(o["batch"]),
                    oom_batches.get(o["phase"], int(o["batch"])))
            except (json.JSONDecodeError, KeyError, ValueError):
                pass


def _cpu_child_env() -> dict:
    """Env for CPU-only children: the TPU plugin hook (sitecustomize
    registration) is disabled entirely, so a hung tunnel cannot stall a
    child that never needed the backend."""
    return dict(os.environ, PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu",
                JAX_PLATFORM_NAME="cpu")


def _spawn(phases: list[str], timeout: float, results: dict, fails: dict,
           errors: list, env: dict | None = None,
           oom_batches: dict | None = None) -> str:
    """Run one child; returns "rc=N" or "timeout=Ns"."""
    cmd = [sys.executable, os.path.abspath(__file__), "--child", ",".join(phases)]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout, env=env)
        out, err, what = proc.stdout, proc.stderr, f"rc={proc.returncode}"
    except subprocess.TimeoutExpired as e:
        def _s(b):
            return b.decode(errors="replace") if isinstance(b, bytes) else (b or "")
        out, err, what = _s(e.stdout), _s(e.stderr), f"timeout={timeout:.0f}s"
    _harvest(out, results, fails, oom_batches)
    errors.append(what)
    tail = err.strip().splitlines()[-6:]
    for line in tail:
        print(f"[bench-child] {line}", file=sys.stderr)
    print(f"[bench] child {what}: have {sorted(results)}", file=sys.stderr)
    return what


def run_parent(requested: list[str]) -> int:
    t_start = time.perf_counter()
    deadline = t_start + BUDGET_S
    results: dict = {}
    fails: dict = {}    # phase -> list of in-child error strings
    errors: list = []   # per-child-attempt outcome (rc / timeout)
    oom_batches: dict = {}  # phase -> smallest batch a child fell back to
    attempt = 0
    while True:
        # a phase that raised inside a *live* child MAX_PHASE_FAILS times
        # is deterministic (fixed seeds) — drop it; keep retrying phases
        # that never ran (hang / init failure produce no PHASEFAIL line)
        missing = [p for p in requested if p not in results
                   and len(fails.get(p, ())) < MAX_PHASE_FAILS]
        if not missing:
            break
        remaining = deadline - time.perf_counter()
        if remaining < 30:
            print(f"[bench] budget exhausted with {missing} missing",
                  file=sys.stderr)
            break
        if attempt:
            time.sleep(min(RETRY_BACKOFF_S, max(0.0, remaining - 30)))
        attempt += 1
        print(f"[bench] attempt {attempt}: phases={missing} "
              f"remaining={remaining:.0f}s", file=sys.stderr)
        # TPU phases first — they carry the primary metric and their
        # hangs are transient (tunnel); CPU phases run after, in their
        # own tunnel-immune child
        tpu_missing = [p for p in missing if p in TPU_PHASES]
        cpu_missing = [p for p in missing if p not in TPU_PHASES]
        if tpu_missing:
            # restart a timed-out-mid-OOM-fallback child at the reduced
            # batch instead of replaying the known-OOM sizes
            tpu_env = None
            if oom_batches:
                tpu_env = dict(os.environ)
                for phase, batch in oom_batches.items():
                    tpu_env[PHASE_BATCH_ENV[phase]] = str(batch)
            _spawn(tpu_missing, min(CHILD_TIMEOUT_S, remaining - 10),
                   results, fails, errors, env=tpu_env,
                   oom_batches=oom_batches)
        if cpu_missing:
            remaining = deadline - time.perf_counter()
            if remaining < 20:
                continue
            fails_before = {p: len(fails.get(p, ())) for p in cpu_missing}
            what = _spawn(cpu_missing, min(120.0, remaining - 10), results,
                          fails, errors, env=_cpu_child_env())
            # cpu_missing phases had no result before this spawn, so any
            # presence in results (or new PHASEFAIL entry) is its output
            produced_output = any(
                p in results or len(fails.get(p, ())) > fails_before[p]
                for p in cpu_missing)
            if what.startswith("timeout") or (what != "rc=0"
                                              and not produced_output):
                # a pure-CPU hang or an rc!=0 exit where THIS spawn
                # produced no RESULT/PHASEFAIL line (e.g. an import
                # error) is deterministic (no flaky tunnel in play):
                # don't let it eat the TPU phases' retry budget by
                # re-spawning it every attempt
                for p in cpu_missing:
                    if p not in results:
                        fails.setdefault(p, []).extend(
                            [f"cpu child died without a result ({what}; "
                             "not retried)"] * MAX_PHASE_FAILS)

    # fold in any opportunistic on-silicon capture for phases the live
    # run could not produce because the backend was unreachable (tunnel
    # down at round end). A phase that deterministically FAILED inside a
    # live child must stay a failure — masking a code regression with a
    # stale capture would report healthy throughput for code that can no
    # longer run the phase. Transient tunnel errors don't count as
    # deterministic.
    def _transient(errs: list) -> bool:
        # ONLY the tunnel's own failure signatures: broad markers like
        # bare "connection"/"timeout" would classify deterministic code
        # failures (ConnectionError, a message mentioning a timeout) as
        # transient and let a stale capture mask a real regression
        markers = ("UNAVAILABLE", "DEADLINE_EXCEEDED", "Socket closed",
                   "Connection reset by peer")
        return all(any(m.lower() in e.lower() for m in markers)
                   for e in errs)

    captured = _load_opportunistic()
    for phase in requested:
        live = results.get(phase)
        live_is_zero = live is not None and not live.get("value")
        live_failed_deterministically = (
            phase in fails and not _transient(fails[phase]))
        if (phase not in results or live_is_zero) \
                and not live_failed_deterministically \
                and captured.get("phases", {}).get(phase, {}).get("value"):
            r = dict(captured["phases"][phase])
            r["source"] = "opportunistic_capture"
            r.setdefault("captured_at", captured.get("captured_at", ""))
            live_fails = fails.pop(phase, None)
            if live_fails:
                r["live_attempt_error"] = live_fails[-1]
            results[phase] = r
            print(f"[bench] folding in opportunistic capture for {phase} "
                  f"({r['captured_at']})", file=sys.stderr)

    primary_phase = requested[0]
    extra = {k: v for k, v in results.items() if k != primary_phase}
    for phase, errs in fails.items():
        if phase not in results:
            extra[phase] = {"status": "failed", "error": errs[-1]}
    extra["attempts"] = attempt
    extra["wall_s"] = round(time.perf_counter() - t_start, 1)
    if primary_phase in results:
        primary = dict(results[primary_phase])
        primary.pop("phase", None)
    else:
        extra["status"] = ("phase_failed" if primary_phase in fails
                           else "backend_unavailable")
        extra["attempt_log"] = errors[-4:]
        metric, unit = PHASE_METRICS[primary_phase]
        primary = {"metric": metric, "value": 0, "unit": unit,
                   "vs_baseline": 0.0}
    primary["extra"] = extra
    print(json.dumps(primary))
    return 0


def _load_opportunistic() -> dict:
    try:
        with open(OPPORTUNISTIC_PATH, encoding="utf-8") as f:
            data = json.load(f)
        return data if isinstance(data, dict) else {}
    except (OSError, json.JSONDecodeError):
        return {}


def _probe_tpu(timeout: float = 90.0) -> bool:
    """Cheap subprocess probe: is the TPU tunnel answering right now?
    Runs out-of-process because a hung tunnel blocks uninterruptibly
    inside the plugin's C client init."""
    code = ("import jax, sys; "
            "sys.exit(0 if jax.default_backend() == 'tpu' "
            "and jax.device_count() >= 1 else 1)")
    try:
        return subprocess.run([sys.executable, "-c", code], timeout=timeout,
                              capture_output=True).returncode == 0
    except subprocess.TimeoutExpired:
        return False


def run_opportunistic() -> int:
    """Probe the tunnel; if it answers, measure the TPU phases and merge
    the results into BENCH_OPPORTUNISTIC.json (newest capture wins per
    phase, since code improvements should be reflected). Designed to be
    invoked repeatedly (cron/loop) during a builder session; exits 0 with
    nothing written when the tunnel is down — cheap to call often."""
    if not _probe_tpu():
        print("[bench] opportunistic: tunnel down", file=sys.stderr)
        return 0
    print("[bench] opportunistic: tunnel UP, measuring", file=sys.stderr)
    results: dict = {}
    fails: dict = {}
    errors: list = []
    oom: dict = {}
    deadline = time.perf_counter() + BUDGET_S
    for _ in range(3):
        # serving and obs ride along: they run on forced host devices, so
        # an opportunistic capture window is also a chance to refresh them
        missing = [p for p in TPU_PHASES + ("serving", "obs")
                   if p not in results
                   and len(fails.get(p, ())) < MAX_PHASE_FAILS]
        remaining = deadline - time.perf_counter()
        if not missing or remaining < 30:
            break
        env = None
        if oom:
            env = dict(os.environ)
            for phase, batch in oom.items():
                env[PHASE_BATCH_ENV[phase]] = str(batch)
        _spawn(missing, min(CHILD_TIMEOUT_S, remaining - 10), results,
               fails, errors, env=env, oom_batches=oom)
    if not results:
        print("[bench] opportunistic: probe answered but no phase "
              "completed", file=sys.stderr)
        return 0
    import datetime

    data = _load_opportunistic()
    data.setdefault("phases", {})
    now = datetime.datetime.now(
        datetime.timezone.utc).isoformat(timespec="seconds")
    data["captured_at"] = now
    data["source"] = "opportunistic_capture"
    data["note"] = ("latest opportunistic on-silicon capture (newest per "
                    "phase wins; each phase carries its own captured_at)")
    for phase, r in results.items():
        # newest capture wins: the artifact must reflect what the CURRENT
        # code measures, including fixes that legitimately lower a number
        # (the round-end live run outranks captures anyway — folding only
        # happens when the tunnel is down at that moment)
        r = dict(r)
        r["captured_at"] = now
        data["phases"][phase] = r
    tmp = OPPORTUNISTIC_PATH + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1)
    os.replace(tmp, OPPORTUNISTIC_PATH)
    print(f"[bench] opportunistic: captured {sorted(results)} -> "
          f"{OPPORTUNISTIC_PATH}", file=sys.stderr)
    return 0


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--child", default=None,
                        help="comma-separated phases to measure in-process")
    parser.add_argument("--model", choices=PHASES, default=None,
                        help="restrict the parent to one phase")
    parser.add_argument("--opportunistic", action="store_true",
                        help="probe the tunnel; capture TPU phases to "
                             "BENCH_OPPORTUNISTIC.json if it answers")
    parser.add_argument("--scaling-probe", action="store_true",
                        help="internal: 8-host-device scaling measurement "
                             "(spawned by the scaling phase)")
    parser.add_argument("--serving-probe", action="store_true",
                        help="internal: continuous-batching decode "
                             "measurement (spawned by the serving phase)")
    parser.add_argument("--fleet-probe", action="store_true",
                        help="internal: router + prefix-cache zipfian "
                             "replay measurement (spawned by the fleet "
                             "phase)")
    parser.add_argument("--quant-probe", action="store_true",
                        help="internal: fp32 vs int8 vs int8-kv vs "
                             "spec-decode throughput + gates (spawned by "
                             "the quant phase)")
    parser.add_argument("--kernels-probe", action="store_true",
                        help="internal: serving-kernel microbench vs "
                             "reference paths + roofline placement "
                             "(spawned by the kernels phase)")
    parser.add_argument("--obs-probe", action="store_true",
                        help="internal: telemetry overhead + exposition "
                             "scrape measurement (spawned by the obs phase)")
    parser.add_argument("--numerics-probe", action="store_true",
                        help="internal: tensor-health-plane overhead + "
                             "live quant-drift audit (spawned by the "
                             "numerics phase)")
    parser.add_argument("--chaos-probe", action="store_true",
                        help="internal: kill/drain/deadline fault drill "
                             "with token-exact recovery gates (spawned by "
                             "the chaos phase)")
    parser.add_argument("--swap-probe", action="store_true",
                        help="internal: P2P cold-join TTFT vs "
                             "store+compile, plus live-weight-swap chaos "
                             "drill (spawned by the swap phase)")
    parser.add_argument("--sched-probe", action="store_true",
                        help="internal: priority-preemption drill + "
                             "multi-LoRA batch gates (spawned by the "
                             "sched phase)")
    parser.add_argument("--autoscale-probe", action="store_true",
                        help="internal: million-user simulator gate + "
                             "live predictive scale-up smoke (spawned "
                             "by the autoscale phase)")
    parser.add_argument("--usage-probe", action="store_true",
                        help="internal: usage-ledger chargeback "
                             "identity, capture replay fidelity and "
                             "diag-watchdog gates (spawned by the "
                             "usage phase)")
    parser.add_argument("--swap-boot-probe", action="store_true",
                        help="internal: one cold replica boot to first "
                             "token (spawned by the swap probe; "
                             "M2KT_SWAP_BOOT picks the weight source)")
    args = parser.parse_args()
    if args.swap_boot_probe:
        return run_swap_boot_probe()
    if args.swap_probe:
        return run_swap_probe()
    if args.chaos_probe:
        return run_chaos_probe()
    if args.scaling_probe:
        return run_scaling_probe()
    if args.serving_probe:
        return run_serving_probe()
    if args.fleet_probe:
        return run_fleet_probe()
    if args.quant_probe:
        return run_quant_probe()
    if args.kernels_probe:
        return run_kernels_probe()
    if args.obs_probe:
        return run_obs_probe()
    if args.numerics_probe:
        return run_numerics_probe()
    if args.sched_probe:
        return run_sched_probe()
    if args.autoscale_probe:
        return run_autoscale_probe()
    if args.usage_probe:
        return run_usage_probe()
    if args.child:
        return run_child(args.child.split(","))
    if args.opportunistic:
        return run_opportunistic()
    requested = list(PHASES) if args.model is None else [args.model]
    return run_parent(requested)


if __name__ == "__main__":
    sys.exit(main())
