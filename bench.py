#!/usr/bin/env python
"""Benchmark: translated-workload training throughput on the attached TPU.

Default mode is BASELINE config 2 ("PyTorch ResNet-50 CUDA train.py ->
jax-xla containerizer, single v5e chip"); ``--model bert`` measures
BASELINE config 3 (HF BERT fine-tune, samples/s). Both drive the same
model-zoo code the containerizer vendors into emitted images — i.e. they
measure what a translated workload actually achieves.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference (Move2Kube) publishes no performance numbers (BASELINE.md),
so ``vs_baseline`` is anchored to an external roofline-derived number for
a well-tuned single-chip JAX run rather than to this program's own first
run (which made vs_baseline circular in round 1): TPU v5e peak is 197
bf16 TFLOP/s, and well-tuned models on TPU sustain ~30% MFU. ResNet-50 @
224x224 is ~12.3 GFLOP/img fwd+bwd (3x the 4.1 GFLOP forward) => anchor
4805 img/s. BERT-base @ seq 128 is ~6*110e6*128 = 84.5 GFLOP/sample =>
anchor 700 samples/s. See BENCH_NOTES.md.
"""

import argparse
import json
import sys
import time

V5E_PEAK_BF16_FLOPS = 197e12
ANCHOR_MFU = 0.30  # well-tuned MFU on TPU (see BENCH_NOTES.md)

RESNET50_FLOPS_PER_IMG = 12.3e9  # fwd+bwd at 224x224 (3x fwd of 4.1 GFLOP)
BERT_SEQ = 128
BERT_FLOPS_PER_SAMPLE = 6 * 110e6 * BERT_SEQ  # 6*N*T rule, bert-base N=110M

RESNET_BATCH, RESNET_IMAGE = 256, 224
BERT_BATCH = 128

SCAN_STEPS = 10          # optimizer steps fused into one device call
WARMUP_CALLS = 1
MEASURE_CALLS = 2        # 2 x 10 = 20 measured steps

INIT_RETRIES = 4
INIT_BACKOFF_S = 20.0
INIT_PROBE_TIMEOUT_S = 150.0  # first TPU contact can take tens of seconds


def _probe_backend_subprocess() -> None:
    """Touch the backend in a throwaway subprocess first.

    The tunneled TPU plugin has two failure modes (both hit round 1's
    official artifacts): a fast RuntimeError(UNAVAILABLE), and a plain
    HANG inside make_c_api_client. A hung C call can't be interrupted
    in-process, so each retry probes via subprocess with a timeout; only
    after a probe succeeds do we initialize in-process (which then hits a
    warmed-up tunnel)."""
    import subprocess

    subprocess.run(
        [sys.executable, "-c", "import jax; print(jax.device_count())"],
        check=True, capture_output=True, timeout=INIT_PROBE_TIMEOUT_S)


def _init_devices():
    """jax backend init with bounded retries (see _probe_backend_subprocess)."""
    import subprocess

    last: Exception | None = None
    for attempt in range(INIT_RETRIES):
        try:
            _probe_backend_subprocess()
            import jax

            return jax.device_count()
        except (RuntimeError, subprocess.SubprocessError) as e:
            last = e
            print(f"[bench] backend init failed (attempt {attempt + 1}/"
                  f"{INIT_RETRIES}): {type(e).__name__}: {e}", file=sys.stderr)
            time.sleep(INIT_BACKOFF_S * (attempt + 1))
    raise RuntimeError(f"TPU backend unavailable after {INIT_RETRIES} "
                       f"attempts: {last}")


def _measure(step, state, batches, items_per_step: int):
    """Timed loop. Timing boundaries force a device->host transfer, NOT
    block_until_ready: remote-tunnel backends can report ready before
    execution completes, a transfer cannot lie."""
    for _ in range(WARMUP_CALLS):
        state, losses = step(state, batches)
    float(losses[-1])
    t0 = time.perf_counter()
    for _ in range(MEASURE_CALLS):
        state, losses = step(state, batches)
    final_loss = float(losses[-1])
    dt = time.perf_counter() - t0
    if final_loss != final_loss:  # NaN: refuse to report a throughput
        raise RuntimeError(f"training diverged: loss={final_loss}")
    throughput = MEASURE_CALLS * SCAN_STEPS * items_per_step / dt
    return throughput, final_loss


def bench_resnet(n: int) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from move2kube_tpu.models import train as m2kt_train
    from move2kube_tpu.models.resnet import resnet50
    from move2kube_tpu.parallel.mesh import MeshConfig, make_mesh

    batch, image = RESNET_BATCH, RESNET_IMAGE
    mesh = make_mesh(MeshConfig(data=n))
    model = resnet50(num_classes=1000)
    state = m2kt_train.create_sharded_state(
        jax.random.PRNGKey(0), model,
        {"x": jnp.zeros((batch, image, image, 3), jnp.bfloat16), "train": False},
        optax.sgd(0.1, momentum=0.9), mesh, has_batch_stats=True,
    )
    step = m2kt_train.make_classifier_train_step(
        mesh, has_batch_stats=True, scan_steps=SCAN_STEPS)
    gen = np.random.default_rng(0)
    # bf16 input batch: halves host->device and HBM traffic vs f32
    batches = {
        "input": jnp.asarray(
            gen.random((SCAN_STEPS, batch, image, image, 3), np.float32),
            jnp.bfloat16),
        "label": jnp.asarray(
            gen.integers(0, 1000, (SCAN_STEPS, batch)), jnp.int32),
    }
    img_s, loss = _measure(step, state, batches, batch)
    mfu = img_s * RESNET50_FLOPS_PER_IMG / V5E_PEAK_BF16_FLOPS
    print(f"[bench] resnet loss={loss:.3f} mfu={mfu:.1%}", file=sys.stderr)
    anchor = V5E_PEAK_BF16_FLOPS * ANCHOR_MFU / RESNET50_FLOPS_PER_IMG
    return {
        "metric": "resnet50_train_throughput_v5e1",
        "value": round(img_s, 1),
        "unit": "img/s",
        "vs_baseline": round(img_s / anchor, 3),
    }


def bench_bert(n: int) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from move2kube_tpu.models import train as m2kt_train
    from move2kube_tpu.models.bert import bert_base
    from move2kube_tpu.parallel.mesh import MeshConfig, make_mesh

    batch = BERT_BATCH
    mesh = make_mesh(MeshConfig(data=n))
    model = bert_base(num_classes=2)
    ids0 = jnp.zeros((batch, BERT_SEQ), jnp.int32)
    state = m2kt_train.create_sharded_state(
        jax.random.PRNGKey(0), model, {"input_ids": ids0},
        optax.adamw(2e-5), mesh,
    )
    step = m2kt_train.make_bert_train_step(mesh, scan_steps=SCAN_STEPS)
    gen = np.random.default_rng(0)
    batches = {
        "input_ids": jnp.asarray(
            gen.integers(0, 30522, (SCAN_STEPS, batch, BERT_SEQ)), jnp.int32),
        "attention_mask": jnp.ones((SCAN_STEPS, batch, BERT_SEQ), bool),
        "label": jnp.asarray(gen.integers(0, 2, (SCAN_STEPS, batch)), jnp.int32),
    }
    samples_s, loss = _measure(step, state, batches, batch)
    mfu = samples_s * BERT_FLOPS_PER_SAMPLE / V5E_PEAK_BF16_FLOPS
    print(f"[bench] bert loss={loss:.3f} mfu={mfu:.1%}", file=sys.stderr)
    anchor = V5E_PEAK_BF16_FLOPS * ANCHOR_MFU / BERT_FLOPS_PER_SAMPLE
    return {
        "metric": "bert_finetune_throughput_v5e1",
        "value": round(samples_s, 1),
        "unit": "samples/s",
        "vs_baseline": round(samples_s / anchor, 3),
    }


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", choices=("resnet", "bert"), default="resnet")
    args = parser.parse_args()
    n = _init_devices()
    result = bench_resnet(n) if args.model == "resnet" else bench_bert(n)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
