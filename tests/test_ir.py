from move2kube_tpu.types import ir as irtypes
from move2kube_tpu.types.plan import ContainerBuildType


def test_container_merge_dedup():
    a = irtypes.Container(image_names=["app:latest"], exposed_ports=[8080])
    b = irtypes.Container(image_names=["app:latest", "app:v1"], exposed_ports=[8080, 9090])
    assert a.merge(b)
    assert a.image_names == ["app:latest", "app:v1"]
    assert a.exposed_ports == [8080, 9090]
    c = irtypes.Container(image_names=["other:latest"])
    assert not a.merge(c)


def test_ir_add_container_dedup():
    ir = irtypes.IR()
    ir.add_container(irtypes.Container(image_names=["app:latest"]))
    ir.add_container(irtypes.Container(image_names=["app:latest"], exposed_ports=[80]))
    assert len(ir.containers) == 1
    assert ir.containers[0].exposed_ports == [80]


def test_service_merge():
    a = irtypes.Service(name="web")
    a.containers.append({"name": "web", "image": "app:latest"})
    a.add_port_forwarding(80, 8080)
    b = irtypes.Service(name="web")
    b.add_port_forwarding(80, 9090)  # same service port -> ignored
    b.add_port_forwarding(443, 8443)
    b.replicas = 3
    a.merge(b)
    assert len(a.port_forwardings) == 2
    assert a.port_forwardings[0].container_port == 8080
    assert a.replicas == 3


def test_ir_merge():
    a = irtypes.IR()
    a.add_service(irtypes.Service(name="web"))
    a.add_container(irtypes.Container(image_names=["web:latest"]))
    b = irtypes.IR()
    b.add_service(irtypes.Service(name="api"))
    b.add_service(irtypes.Service(name="web", replicas=2))
    b.add_container(irtypes.Container(image_names=["api:latest"]))
    b.add_storage(irtypes.Storage(name="cfg", kind=irtypes.StorageKind.CONFIGMAP))
    a.merge(b)
    assert set(a.services) == {"web", "api"}
    assert a.services["web"].replicas == 2
    assert len(a.containers) == 2
    assert len(a.storages) == 1


def test_storage_merge():
    ir = irtypes.IR()
    ir.add_storage(
        irtypes.Storage(name="cfg", kind=irtypes.StorageKind.CONFIGMAP, content={"a": b"1"})
    )
    ir.add_storage(
        irtypes.Storage(name="cfg", kind=irtypes.StorageKind.CONFIGMAP, content={"b": b"2"})
    )
    assert len(ir.storages) == 1
    assert ir.storages[0].content == {"a": b"1", "b": b"2"}


def test_pod_spec_assembly():
    svc = irtypes.Service(name="web", restart_policy="Always")
    svc.containers.append({"name": "web", "image": "app:latest"})
    svc.image_pull_secrets.append("regcred")
    spec = svc.pod_spec()
    assert spec["containers"][0]["image"] == "app:latest"
    assert spec["imagePullSecrets"] == [{"name": "regcred"}]
    assert spec["restartPolicy"] == "Always"


def test_container_build_types():
    c = irtypes.Container(build_type=ContainerBuildType.JAX_XLA)
    c.add_file("Dockerfile", "FROM python:3.11\n")
    c.add_file("train_tpu.py", "import jax\n")
    assert set(c.new_files) == {"Dockerfile", "train_tpu.py"}
