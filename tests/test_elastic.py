"""Elastic multislice training: slice-loss classification, the
supervisor's re-plan-on-survivors restart, the checkpoint flush
guarantee on the slice-death exit path, and the elastic emission surface
(JobSet env + coordinator Service + exit-83 failure policy, raw YAML and
Helm parameterization).

The headline drill runs the real minitrain child on CPU: two forced-host
slices, ``slice_loss`` injected at step 5, the supervisor shrinks the
world to the survivor (rescaling the per-device batch to preserve the
global batch) and the restarted attempt resumes from the last
checkpoint — finishing with the SAME final loss a never-faulted
single-slice control run produces, because minitrain's data stream is a
function of (step, global batch) only, never of the mesh."""

from __future__ import annotations

import json
import os
import re
import signal
import subprocess
import sys

import pytest

from move2kube_tpu.models import checkpoint as m2kt_ckpt
from move2kube_tpu.qa import engine as qaengine
from move2kube_tpu.resilience import supervisor
from move2kube_tpu.resilience.faults import SLICE_LOST_EXIT_CODE

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_supervised(workdir, extra: dict) -> subprocess.CompletedProcess:
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu", **extra)
    # knobs from the outer test environment must not leak into the drill
    for leak in ("M2KT_METRICS_DIR", "M2KT_FAULT_STEP", "M2KT_FAULT_KIND",
                 "M2KT_FAULT_MARKER", "M2KT_ELASTIC", "M2KT_NUM_SLICES",
                 "M2KT_FORCE_DEVICES", "M2KT_BATCH_PER_DEVICE"):
        if leak not in extra:
            env.pop(leak, None)
    return subprocess.run(
        [sys.executable, "-m", "move2kube_tpu.resilience.supervisor", "--",
         sys.executable, "-m", "move2kube_tpu.resilience.minitrain"],
        env=env, cwd=str(workdir), capture_output=True, text=True,
        timeout=600)


# -- the headline drill: lose one of two slices, finish on the survivor ------


def test_elastic_drill_two_slices_lose_one(tmp_path):
    """2 slices x 4 devices x batch 2 (global 16); slice_loss at step 5.
    The supervisor re-plans to 1 slice x 4 devices x batch 4 (global 16
    preserved), resumes from the step-4 checkpoint, and the final loss
    is exactly the never-faulted single-slice control run's."""
    common = dict(M2KT_STEPS="8", M2KT_CKPT_EVERY="2",
                  M2KT_RETRY_BACKOFF_S="0.1")
    res = _run_supervised(tmp_path, dict(
        common,
        M2KT_CKPT_DIR=str(tmp_path / "ckpt"),
        M2KT_FORCE_DEVICES="8",
        M2KT_NUM_SLICES="2",
        M2KT_BATCH_PER_DEVICE="2",
        M2KT_ELASTIC="1",
        M2KT_FAULT_STEP="5",
        M2KT_FAULT_KIND="slice_loss",
        M2KT_FAULT_MARKER=str(tmp_path / "fault-fired"),
        M2KT_EXIT_FILE=str(tmp_path / "exit.json"),
        M2KT_GOODPUT_FILE=str(tmp_path / "goodput.json"),
    ))
    assert res.returncode == 0, res.stderr
    # attempt 1: the full 2-slice world
    assert "dcn_dp=2" in res.stdout
    assert "devices=8 global_batch=16" in res.stdout
    assert "FAULT: slice_loss" in res.stderr
    assert "elastic re-plan 2->1" in res.stdout
    # attempt 2: half the devices, same global batch, resumed not restarted
    assert "devices=4 global_batch=16" in res.stdout
    assert "resumed from step 4" in res.stdout
    assert "done steps=8" in res.stdout

    summary = json.loads((tmp_path / "exit.json").read_text())
    assert summary["exit_class"] == "ok"
    assert [a["class"] for a in summary["attempts"]] == ["slice_lost", "ok"]
    assert summary["attempts"][0]["returncode"] == SLICE_LOST_EXIT_CODE
    assert summary["attempts"][1]["report"]["resumed_from"] == 4
    [event] = summary["replan_events"]
    assert event == {"attempt": 1, "from_slices": 2, "to_slices": 1,
                     "batch_per_device": 4, "global_batch_preserved": True}
    merged = summary["goodput"]
    assert merged["last_saved_step"] == 8
    # the re-plan pause is its own ledger category, not a retry
    assert merged["seconds"]["replan"] > 0
    assert merged["seconds"]["retry"] == 0

    # loss continuity: a never-faulted control run on the survivor world
    control = tmp_path / "control"
    control.mkdir()
    res_c = _run_supervised(control, dict(
        common,
        M2KT_CKPT_DIR=str(control / "ckpt"),
        M2KT_FORCE_DEVICES="4",
        M2KT_BATCH_PER_DEVICE="4",
        M2KT_EXIT_FILE=str(control / "exit.json"),
        M2KT_GOODPUT_FILE=str(control / "goodput.json"),
    ))
    assert res_c.returncode == 0, res_c.stderr

    def final_loss(out: str) -> float:
        return float(re.findall(r"loss=([0-9.]+)", out)[-1])

    assert final_loss(res.stdout) == pytest.approx(
        final_loss(res_c.stdout), abs=1e-5)


def test_slice_loss_without_elastic_is_terminal(tmp_path):
    """Elastic off: the supervisor surfaces exit code 83 / class
    slice_lost without retrying, handing the decision to the JobSet
    failure policy (whose exit-83 rule restarts the set for free)."""
    res = _run_supervised(tmp_path, dict(
        M2KT_STEPS="4",
        M2KT_FORCE_DEVICES="2",
        M2KT_NUM_SLICES="2",
        M2KT_BATCH_PER_DEVICE="2",
        M2KT_FAULT_STEP="2",
        M2KT_FAULT_KIND="slice_loss",
        M2KT_RETRY_BACKOFF_S="0.05",
        M2KT_EXIT_FILE=str(tmp_path / "exit.json"),
        M2KT_GOODPUT_FILE=str(tmp_path / "goodput.json"),
    ))
    assert res.returncode == SLICE_LOST_EXIT_CODE
    assert "FAULT: slice_loss" in res.stderr
    summary = json.loads((tmp_path / "exit.json").read_text())
    assert summary["exit_class"] == "slice_lost"
    assert len(summary["attempts"]) == 1
    assert summary["replan_events"] == []


# -- classification ----------------------------------------------------------


@pytest.mark.parametrize("rc,tail", [
    (SLICE_LOST_EXIT_CODE, ""),
    (1, "[m2kt] FAULT: slice_loss: slice 1 reclaimed at step 5"),
    (1, "megascale slice unreachable"),
    # the pattern outranks the generic SIGKILL -> retryable rule: a slice
    # loss kills its processes too, and slice_lost is the better answer
    (-signal.SIGKILL, "slice lost"),
])
def test_slice_loss_classification(rc, tail):
    assert supervisor.classify(rc, tail) == supervisor.SLICE_LOST


# -- re-plan unit semantics --------------------------------------------------


def test_plan_elastic_restart_rescales_batch_and_devices(monkeypatch):
    monkeypatch.setenv("M2KT_ELASTIC", "1")
    monkeypatch.delenv("M2KT_ELASTIC_MIN_SLICES", raising=False)
    monkeypatch.setenv("M2KT_NUM_SLICES", "2")
    monkeypatch.setenv("M2KT_BATCH_PER_DEVICE", "2")
    monkeypatch.setenv("M2KT_FORCE_DEVICES", "8")
    sup = supervisor.Supervisor(["true"], max_retries=0, backoff_s=0.0)
    event = sup._plan_elastic_restart(1)
    assert event == {"attempt": 1, "from_slices": 2, "to_slices": 1,
                     "batch_per_device": 4, "global_batch_preserved": True}
    assert sup._env_overrides == {"M2KT_NUM_SLICES": "1",
                                  "M2KT_FORCE_DEVICES": "4",
                                  "M2KT_BATCH_PER_DEVICE": "4"}
    # a second loss reads the overridden world: 1 survivor - 1 < floor
    assert sup._plan_elastic_restart(2) is None
    assert len(sup._replan_events) == 1


def test_plan_elastic_restart_indivisible_batch_degrades(monkeypatch):
    """3 -> 2 slices with batch-per-device 3: 9 is not divisible by 2, so
    the per-device batch is kept and the event records the degradation
    instead of silently changing the convergence math."""
    monkeypatch.setenv("M2KT_ELASTIC", "1")
    monkeypatch.delenv("M2KT_ELASTIC_MIN_SLICES", raising=False)
    monkeypatch.setenv("M2KT_NUM_SLICES", "3")
    monkeypatch.setenv("M2KT_BATCH_PER_DEVICE", "3")
    monkeypatch.delenv("M2KT_FORCE_DEVICES", raising=False)
    sup = supervisor.Supervisor(["true"], max_retries=0, backoff_s=0.0)
    event = sup._plan_elastic_restart(1)
    assert event["from_slices"] == 3 and event["to_slices"] == 2
    assert event["global_batch_preserved"] is False
    assert "batch_per_device" not in event
    assert sup._env_overrides == {"M2KT_NUM_SLICES": "2"}


def test_plan_elastic_restart_honors_min_slices_floor(monkeypatch):
    monkeypatch.setenv("M2KT_ELASTIC", "1")
    monkeypatch.setenv("M2KT_ELASTIC_MIN_SLICES", "2")
    monkeypatch.setenv("M2KT_NUM_SLICES", "2")
    sup = supervisor.Supervisor(["true"], max_retries=0, backoff_s=0.0)
    assert sup.min_slices == 2
    assert sup._plan_elastic_restart(1) is None  # 1 survivor < floor
    assert sup._replan_events == []
    assert sup._env_overrides == {}


# -- checkpoint flush on the death path --------------------------------------


def test_install_exit_flush_lands_async_save(tmp_path):
    """An async save started just before a slice-loss ``sys.exit(83)``
    must be durable when the process dies: without the atexit flush the
    restarted attempt resumes one cadence early."""
    script = (
        "import sys\n"
        "import jax.numpy as jnp\n"
        "from move2kube_tpu.models.checkpoint import CheckpointManager\n"
        "m = CheckpointManager(sys.argv[1], every=2)\n"
        "m.install_exit_flush()\n"
        "assert m.maybe_save(2, {'w': jnp.arange(4.0)})\n"
        "sys.exit(83)\n"
    )
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    env.pop("M2KT_CKPT_SYNC", None)  # the async path is what's under test
    res = subprocess.run(
        [sys.executable, "-c", script, str(tmp_path / "ckpt")],
        env=env, capture_output=True, text=True, timeout=300)
    assert res.returncode == 83, res.stderr
    mngr = m2kt_ckpt.CheckpointManager(str(tmp_path / "ckpt"), every=2)
    assert mngr.latest_step() == 2
    mngr.close()


# -- emission: raw YAML ------------------------------------------------------


class _AnswerEngine(qaengine.Engine):
    """Resolve specific QA ids with canned answers; everything else falls
    through to the default engine installed after it."""

    def __init__(self, answers: dict):
        self.answers = answers

    def fetch_answer(self, problem):
        if problem.id in self.answers:
            problem.set_answer(self.answers[problem.id])
        return problem


def _qa(answers: dict | None = None):
    qaengine.reset_engines()
    if answers:
        qaengine.add_engine(_AnswerEngine(answers))
    qaengine.start_engine(qa_skip=True)


def _slice_service(name="trainer", num_slices=2):
    from move2kube_tpu.types.ir import Service
    from move2kube_tpu.types.plan import AcceleratorInfo

    svc = Service(name=name)
    svc.containers = [{"name": "t", "image": "x"}]
    svc.accelerator = AcceleratorInfo(
        gpu_count=8 * num_slices, tpu_accelerator="tpu-v5-lite-podslice",
        tpu_topology="2x4", num_hosts=2, num_slices=num_slices)
    svc.job = True
    return svc


@pytest.fixture
def _clean_env(monkeypatch):
    for var in ("M2KT_ELASTIC", "M2KT_ELASTIC_MIN_SLICES",
                "M2KT_MAX_RESTARTS", "M2KT_BACKOFF_LIMIT"):
        monkeypatch.delenv(var, raising=False)


def test_multislice_jobset_carries_elastic_env_and_exit83_rule(_clean_env):
    from move2kube_tpu.apiresource.deployment import DeploymentAPIResource

    _qa()
    try:
        obj = DeploymentAPIResource()._create_workload(
            _slice_service(), {"JobSet"})
    finally:
        qaengine.reset_engines()
    assert obj["spec"]["replicatedJobs"][0]["replicas"] == 2
    job_spec = obj["spec"]["replicatedJobs"][0]["template"]["spec"]
    pod = job_spec["template"]["spec"]
    env = {e["name"]: e for e in pod["containers"][0]["env"]}
    assert env["M2KT_ELASTIC"]["value"] == "1"  # QA default: elastic on
    assert env["M2KT_ELASTIC_MIN_SLICES"]["value"] == "1"
    # coordinator resolves through the dedicated headless Service
    assert env["MEGASCALE_COORDINATOR_ADDRESS"]["value"] == \
        "trainer-coord:8080"
    assert "fieldRef" in env["M2KT_SLICE_ID"]["valueFrom"]

    rules = job_spec["podFailurePolicy"]["rules"]
    assert len(rules) == 2  # disruption rule + terminal slice loss
    [exit_rule] = [r for r in rules if "onExitCodes" in r]
    assert exit_rule["action"] == "FailJob"
    assert exit_rule["onExitCodes"] == {
        "operator": "In", "values": [SLICE_LOST_EXIT_CODE]}


def test_elastic_knob_off_drops_env_keeps_exit_rule(_clean_env):
    from move2kube_tpu.apiresource.deployment import DeploymentAPIResource

    _qa({"m2kt.services.trainer.elastic": False})
    try:
        obj = DeploymentAPIResource()._create_workload(
            _slice_service(), {"JobSet"})
    finally:
        qaengine.reset_engines()
    job_spec = obj["spec"]["replicatedJobs"][0]["template"]["spec"]
    names = {e["name"]
             for e in job_spec["template"]["spec"]["containers"][0]["env"]}
    assert "M2KT_ELASTIC" not in names
    assert "M2KT_ELASTIC_MIN_SLICES" not in names
    # the exit-83 rule stays: a non-elastic slice loss still wants the
    # free JobSet-level restart lane
    assert any("onExitCodes" in r
               for r in job_spec["podFailurePolicy"]["rules"])


def test_coordinator_headless_service_emitted_for_multislice(_clean_env):
    from move2kube_tpu.apiresource.deployment import DeploymentAPIResource
    from move2kube_tpu.types.ir import IR

    ir = IR(name="p")
    ir.add_service(_slice_service())
    ir.add_service(_slice_service(name="single", num_slices=1))
    _qa()
    try:
        objs = DeploymentAPIResource().create_new_resources(ir, {"JobSet"})
    finally:
        qaengine.reset_engines()
    coords = [o for o in objs if o.get("kind") == "Service"
              and o["metadata"]["name"].endswith("-coord")]
    [coord] = coords  # the single-slice service gets none
    assert coord["metadata"]["name"] == "trainer-coord"
    spec = coord["spec"]
    assert spec["clusterIP"] == "None"
    assert spec["publishNotReadyAddresses"] is True
    # pins slice 0's pod 0 via the JobSet controller's pod labels
    assert spec["selector"] == {
        "jobset.sigs.k8s.io/jobset-name": "trainer",
        "jobset.sigs.k8s.io/job-index": "0",
        "batch.kubernetes.io/job-completion-index": "0",
    }
    assert {p["port"] for p in spec["ports"]} == {8080, 8476}


def test_single_slice_jobset_has_no_elastic_surface(_clean_env):
    from move2kube_tpu.apiresource.deployment import DeploymentAPIResource

    _qa()
    try:
        obj = DeploymentAPIResource()._create_workload(
            _slice_service(num_slices=1), {"JobSet"})
    finally:
        qaengine.reset_engines()
    job_spec = obj["spec"]["replicatedJobs"][0]["template"]["spec"]
    names = {e["name"]
             for e in job_spec["template"]["spec"]["containers"][0]["env"]}
    assert "M2KT_ELASTIC" not in names
    # single-slice keeps the original single-rule failure policy
    [rule] = job_spec["podFailurePolicy"]["rules"]
    assert "onExitCodes" not in rule


# -- emission: optimizer pass + Helm parameterization ------------------------


def test_elastic_optimizer_injects_env_for_multislice_jobs(_clean_env):
    from move2kube_tpu.passes.optimize import tpu_elastic_optimizer
    from move2kube_tpu.types.ir import IR

    ir = IR(name="p")
    multi = _slice_service()
    single = _slice_service(name="single", num_slices=1)
    serving = _slice_service(name="decode")
    serving.accelerator.serving = True
    serving.job = False
    for svc in (multi, single, serving):
        ir.add_service(svc)
    _qa()
    try:
        ir = tpu_elastic_optimizer(ir)
        ir = tpu_elastic_optimizer(ir)  # idempotent
    finally:
        qaengine.reset_engines()
    env = {e["name"]: e["value"] for e in multi.containers[0]["env"]}
    assert env == {"M2KT_ELASTIC": "1", "M2KT_ELASTIC_MIN_SLICES": "1"}
    assert len(multi.containers[0]["env"]) == 2
    assert "env" not in single.containers[0]
    assert "env" not in serving.containers[0]


def test_elastic_parameterizer_lifts_knobs_and_preserves_fieldref():
    """Helm output: the elastic knobs become ``{{ .Values.tpuelastic }}``
    refs seeded into values, while the multislice fieldRef entries
    (M2KT_SLICE_ID reads the JobSet job-index annotation) must survive
    parameterization byte-identical — a templated fieldRef would break
    every slice's identity."""
    from move2kube_tpu.passes.parameterize import tpu_elastic_parameterizer
    from move2kube_tpu.types.ir import IR

    ir = IR(name="p")
    svc = _slice_service()
    slice_ref = {"fieldRef": {"fieldPath":
        "metadata.annotations['jobset.sigs.k8s.io/job-index']"}}
    svc.containers[0]["env"] = [
        {"name": "M2KT_ELASTIC", "value": "1"},
        {"name": "M2KT_ELASTIC_MIN_SLICES", "value": "1"},
        {"name": "M2KT_SLICE_ID", "valueFrom": dict(slice_ref)},
        {"name": "MEGASCALE_SLICE_ID", "valueFrom": dict(slice_ref)},
    ]
    ir.add_service(svc)
    ir = tpu_elastic_parameterizer(ir)
    env = {e["name"]: e for e in svc.containers[0]["env"]}
    assert env["M2KT_ELASTIC"]["value"] == "{{ .Values.tpuelastic }}"
    assert env["M2KT_ELASTIC_MIN_SLICES"]["value"] == \
        "{{ .Values.tpuelasticminslices }}"
    assert ir.values.global_variables["tpuelastic"] == "1"
    assert ir.values.global_variables["tpuelasticminslices"] == "1"
    for name in ("M2KT_SLICE_ID", "MEGASCALE_SLICE_ID"):
        assert env[name]["valueFrom"] == slice_ref
        assert "value" not in env[name]
    # idempotent: already-templated values are not double-lifted
    ir = tpu_elastic_parameterizer(ir)
    assert env["M2KT_ELASTIC"]["value"] == "{{ .Values.tpuelastic }}"


def test_helm_chain_workload_to_parameterized_yaml(_clean_env):
    """Full Helm-side chain over the real workload emission: optimizer ->
    parameterizer -> convert_objects. The JobSet env carries the values
    refs AND the untouched fieldRef entries."""
    from move2kube_tpu.apiresource.base import convert_objects
    from move2kube_tpu.apiresource.deployment import DeploymentAPIResource
    from move2kube_tpu.passes.optimize import tpu_elastic_optimizer
    from move2kube_tpu.passes.parameterize import tpu_elastic_parameterizer
    from move2kube_tpu.types.ir import IR

    ir = IR(name="p")
    svc = _slice_service()
    ir.add_service(svc)
    _qa()
    try:
        ir = tpu_elastic_optimizer(ir)
        ir = tpu_elastic_parameterizer(ir)
        objs = convert_objects(ir, [DeploymentAPIResource()])
    finally:
        qaengine.reset_engines()
    [jobset] = [o for o in objs if o.get("kind") == "JobSet"]
    pod = (jobset["spec"]["replicatedJobs"][0]["template"]["spec"]
           ["template"]["spec"])
    env = {e["name"]: e for e in pod["containers"][0]["env"]}
    assert env["M2KT_ELASTIC"]["value"] == "{{ .Values.tpuelastic }}"
    assert "fieldRef" in env["M2KT_SLICE_ID"]["valueFrom"]
    assert ir.values.global_variables["tpuelastic"] == "1"
    [coord] = [o for o in objs if o.get("kind") == "Service"
               and o["metadata"]["name"] == "trainer-coord"]
    assert coord["spec"]["clusterIP"] == "None"


# -- kube2kube round trip ----------------------------------------------------


def test_kube2kube_reingests_num_slices(_clean_env):
    """A re-ingested GPU workload big enough to span slices must read the
    slice fan-out back: 512 GPUs -> 2 v5p-256 slices, and re-emission
    carries the multislice JobSet surface."""
    from move2kube_tpu.apiresource.deployment import DeploymentAPIResource
    from move2kube_tpu.source.kube2kube import tpu_service_from_gpu_workload

    job = {
        "apiVersion": "batch/v1", "kind": "Job",
        "metadata": {"name": "big-train"},
        "spec": {
            "parallelism": 64,
            "template": {"spec": {"containers": [{
                "name": "t", "image": "x",
                "resources": {"limits": {"nvidia.com/gpu": 8}},
            }]}},
        },
    }
    svc = tpu_service_from_gpu_workload(job)
    assert svc is not None
    assert svc.accelerator.num_slices == 2
    assert svc.accelerator.gpu_count == 512

    _qa()
    try:
        obj = DeploymentAPIResource()._create_workload(svc, {"JobSet"})
    finally:
        qaengine.reset_engines()
    assert obj["spec"]["replicatedJobs"][0]["replicas"] == 2
    pod = (obj["spec"]["replicatedJobs"][0]["template"]["spec"]
           ["template"]["spec"])
    env = {e["name"]: e.get("value") for e in pod["containers"][0]["env"]}
    assert env["M2KT_NUM_SLICES"] == "2"
    assert env["MEGASCALE_COORDINATOR_ADDRESS"] == "big-train-coord:8080"
