"""Fleet serving tests: router, refcounted prefix cache + COW, disagg.

The load-bearing property mirrors test_serving.py's: a prefix-cache hit
must produce logits equal (fp32 tolerance) to the uncached path — the
cache installs shared KV pages instead of re-running prefill, and any
bookkeeping slip (refcount, COW, suffix force-feed) shows up as a logit
diff. Around that core: allocator refcount/COW invariants, router
placement/failover/hedging units, the disagg KV handoff wire format,
and the emitted per-role fleet manifests.
"""

from __future__ import annotations

import dataclasses
import json
import time
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from move2kube_tpu.models.llama import Llama, llama_tiny
from move2kube_tpu.serving.engine import EngineConfig, Request, ServingEngine
from move2kube_tpu.serving.fleet.disagg import (
    DisaggPair,
    InProcessTransport,
    KVHandoff,
    PrefillReplica,
)
from move2kube_tpu.serving.fleet.prefixcache import PrefixCache
from move2kube_tpu.serving.fleet.router import (
    ReplicaHandle,
    Router,
    RouterConfig,
    RouterHTTPServer,
    build_fleet,
    prefix_hash,
)
from move2kube_tpu.serving.kvcache import (
    NULL_PAGE,
    PageAllocator,
)


@pytest.fixture(scope="module")
def llama_parts():
    cfg = dataclasses.replace(llama_tiny(), dtype=jnp.float32,
                              attn_impl="dense")
    model = Llama(cfg)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 8), jnp.int32))
    return model, variables


def _engine(model, variables, **over) -> ServingEngine:
    cfg = EngineConfig(**{**dict(max_batch=2, max_seq=64, block_size=8,
                                 buckets=(16, 32)), **over})
    return ServingEngine(model, variables, cfg)


# ----------------------------------------------------------------------
# refcounted allocator
# ----------------------------------------------------------------------

def test_allocator_refcounts_and_release_to_zero():
    alloc = PageAllocator(9)
    pages = alloc.alloc(3)
    assert all(alloc.refcount(p) == 1 for p in pages)
    alloc.incref(pages)
    assert all(alloc.refcount(p) == 2 for p in pages)
    assert all(alloc.is_shared(p) for p in pages)
    alloc.free(pages)  # decref: still held once, NOT back in the pool
    assert alloc.available == 5
    assert all(alloc.refcount(p) == 1 for p in pages)
    assert not any(alloc.is_shared(p) for p in pages)
    alloc.free(pages)  # release to zero: pages return to the pool
    assert alloc.available == 8
    with pytest.raises(ValueError):
        alloc.free(pages)  # double free detected even after reuse-free
    with pytest.raises(ValueError):
        alloc.incref([NULL_PAGE])
    with pytest.raises(ValueError):
        alloc.incref([7])  # never allocated


def test_allocator_free_keeps_lifo_order():
    """The O(n^2) list-scan free is gone; the set-backed free list must
    keep the allocator's LIFO behavior (freshly freed pages are handed
    out first — warmest pages stay warm) and stay correct at size."""
    alloc = PageAllocator(1025)
    a = alloc.alloc(512)
    b = alloc.alloc(512)
    assert alloc.alloc(1) is None
    alloc.free(b)
    # LIFO: the most recently freed pages come back first
    assert alloc.alloc(512) == list(reversed(b))
    alloc.free(a)
    got = alloc.alloc(3)
    assert got == a[-1:-4:-1]  # freed [..., x, y, z] -> alloc [z, y, x]


# ----------------------------------------------------------------------
# prefix-cache trie (host-side, no model)
# ----------------------------------------------------------------------

def test_prefix_trie_lookup_insert_evict():
    alloc = PageAllocator(33)
    cache = PrefixCache(4, alloc)
    toks = list(range(100, 110))  # 10 tokens, bs=4: 2 full pages + 2 tail
    pages = alloc.alloc(3)
    assert cache.insert(toks, pages) == 3
    # the cache took one ref per adopted page on top of the donor's
    assert all(alloc.refcount(p) == 2 for p in pages)
    alloc.free(pages)  # donor slot releases; cache keeps them alive
    assert all(alloc.refcount(p) == 1 for p in pages)

    hit = cache.lookup(toks)
    assert hit is not None
    assert hit.covered == 10 and hit.pages == list(pages)
    assert all(alloc.refcount(p) == 2 for p in pages)  # caller holds refs
    alloc.free(hit.pages)

    # shared-prefix lookup: full pages match, foreign tail does not
    hit = cache.lookup(toks[:8] + [999, 998])
    assert hit is not None and hit.covered == 8
    assert hit.pages == list(pages[:2])
    alloc.free(hit.pages)

    # a shorter *partial* prefix of the tail page does not match (the
    # cached partial chunk must be a prefix of the remainder, not vice
    # versa — the page holds K/V for positions the query never covers)
    hit = cache.lookup(toks[:9])
    assert hit is not None and hit.covered == 8
    alloc.free(hit.pages)

    before = alloc.available
    assert cache.evict(1) >= 1
    assert alloc.available > before
    cache.clear()
    assert len(cache) == 0
    assert alloc.available == 32


def test_prefix_trie_dedups_existing_chunks():
    alloc = PageAllocator(17)
    cache = PrefixCache(4, alloc)
    toks = list(range(1, 9))
    first = alloc.alloc(2)
    assert cache.insert(toks, first) == 2
    dup = alloc.alloc(2)
    # same tokens again: existing nodes keep their pages; nothing adopted
    assert cache.insert(toks, dup) == 0
    assert cache.total_pages == 2
    alloc.free(dup)
    assert alloc.available == 16 - 2  # only `first` pages remain out
    alloc.free(first)  # donor drops its refs; cache alone keeps them alive
    assert alloc.available == 16 - 2
    assert all(alloc.refcount(p) == 1 for p in first)


# ----------------------------------------------------------------------
# prefix-cache hit path: logit equivalence + COW invariants
# ----------------------------------------------------------------------

def _run_capture(eng, requests):
    eng.capture_logits = True
    comps = {c.rid: c for c in eng.run(requests)}
    return comps, eng.logit_log


def test_prefix_hit_logit_equivalence(llama_parts):
    """The acceptance bar: rerunning a cached prompt (full-cover hit)
    and a shared-prefix-different-tail prompt (partial hit) must emit
    the same tokens AND the same logits as an engine with the cache
    off. The hit path installs shared pages + COW instead of prefill,
    so any aliasing bug surfaces here."""
    model, variables = llama_parts
    rng = np.random.default_rng(21)
    shared = rng.integers(1, 200, size=12).tolist()
    reqs = [
        Request("cold", list(shared), 4),
        Request("rerun", list(shared), 4),           # full-cover hit
        Request("fork", shared[:12] + [7, 9], 4),    # partial hit
    ]
    cached = _engine(model, variables, prefix_cache=True)
    plain = _engine(model, variables, prefix_cache=False)
    got, got_log = _run_capture(cached, [Request(r.rid, list(r.prompt),
                                                 r.max_new_tokens)
                                         for r in reqs])
    want, want_log = _run_capture(plain, reqs)
    stats = cached.stats()
    assert stats["prefix_hits"] >= 2
    assert stats["prefix_hit_rate"] > 0
    assert stats["prefix_hit_tokens"] > 0
    for r in reqs:
        assert got[r.rid].tokens == want[r.rid].tokens, r.rid
        assert len(got_log[r.rid]) == len(want_log[r.rid])
        for i, (a, b) in enumerate(zip(got_log[r.rid], want_log[r.rid])):
            np.testing.assert_allclose(
                a, b, atol=1e-5, rtol=1e-5,
                err_msg=f"{r.rid} generated token {i}")


def test_shared_pages_are_never_mutated_in_place(llama_parts):
    """Pages the cache shares out are immutable: a borrowing request
    that generates past the shared prefix must COW, not write. Byte
    snapshot of the shared pages before/after a borrowing generation."""
    model, variables = llama_parts
    rng = np.random.default_rng(22)
    shared = rng.integers(1, 200, size=12).tolist()
    eng = _engine(model, variables, prefix_cache=True)
    eng.run([Request("seed", list(shared), 2)])

    hit = eng._prefix.lookup(shared)
    assert hit is not None and hit.pages
    snap = [(np.asarray(eng._cache["k"][0][p]).copy(),
             np.asarray(eng._cache["v"][0][p]).copy()) for p in hit.pages]
    eng._allocator.free(hit.pages)

    eng.run([Request("borrow", shared[:12] + [3, 5], 6)])
    assert eng.stats()["cow_copies"] >= 1
    hit2 = eng._prefix.lookup(shared)
    assert hit2 is not None and hit2.pages == hit.pages
    for p, (k0, v0) in zip(hit2.pages, snap):
        np.testing.assert_array_equal(
            np.asarray(eng._cache["k"][0][p]), k0,
            err_msg=f"shared page {p} K mutated")
        np.testing.assert_array_equal(
            np.asarray(eng._cache["v"][0][p]), v0,
            err_msg=f"shared page {p} V mutated")
    eng._allocator.free(hit2.pages)

    # release-to-zero: dropping the cache returns every page
    eng._prefix.clear()
    assert eng._allocator.available == eng.cache_cfg.num_pages - 1


def test_prefix_hit_logit_gate_int8_kv(llama_parts):
    """Prefix cache over *quantized* pages: a cached hit replays the
    same int8 bytes and per-row scales, but the hit path force-feeds
    the uncovered prompt tail through decode — which attends over
    DEQUANTIZED context, where the no-cache engine's prefill attends
    over exact fp32 K/V. So hit-path logits sit at quantization noise,
    not 1e-5: the bar is the relative-error logit gate while the
    greedy trajectories coincide (ISSUE's "cached hit passes logit
    gate"), plus the hits actually happening."""
    from move2kube_tpu.serving import quant as quantlib

    model, variables = llama_parts
    rng = np.random.default_rng(24)
    shared = rng.integers(1, 200, size=12).tolist()
    reqs = [
        Request("cold", list(shared), 4),
        Request("rerun", list(shared), 4),
        Request("fork", shared[:12] + [7, 9], 4),
    ]
    cached = _engine(model, variables, quant="int8-kv", prefix_cache=True)
    plain = _engine(model, variables, quant="int8-kv", prefix_cache=False)
    got, got_log = _run_capture(cached, [Request(r.rid, list(r.prompt),
                                                 r.max_new_tokens)
                                         for r in reqs])
    want, want_log = _run_capture(plain, reqs)
    assert cached.stats()["prefix_hits"] >= 2
    gated_rows = 0
    for r in reqs:
        a_t, b_t = want[r.rid].tokens, got[r.rid].tokens
        agree = 0
        while agree < min(len(a_t), len(b_t)) and a_t[agree] == b_t[agree]:
            agree += 1
        for i in range(min(agree + 1, len(want_log[r.rid]),
                           len(got_log[r.rid]))):
            gate = quantlib.logit_gate(want_log[r.rid][i],
                                       got_log[r.rid][i])
            assert gate["max_rel_err"] < 0.05, (r.rid, i, gate)
            gated_rows += 1
    assert gated_rows >= len(reqs)


def test_shared_int8_pages_cow_copies_scales(llama_parts):
    """COW on a quantized cache: the shared page's int8 bytes AND its
    k/v scale rows stay byte-immutable while a borrower generates past
    the shared prefix, and release-to-zero still returns every page
    (double-free guards hold with the extra scale pools in play)."""
    model, variables = llama_parts
    rng = np.random.default_rng(25)
    shared = rng.integers(1, 200, size=12).tolist()
    eng = _engine(model, variables, quant="int8-kv", prefix_cache=True)
    eng.run([Request("seed", list(shared), 2)])

    hit = eng._prefix.lookup(shared)
    assert hit is not None and hit.pages
    keys = ("k", "v", "k_scale", "v_scale")
    snap = {key: [np.asarray(eng._cache[key][0][p]).copy()
                  for p in hit.pages] for key in keys}
    eng._allocator.free(hit.pages)

    eng.run([Request("borrow", shared[:12] + [3, 5], 6)])
    assert eng.stats()["cow_copies"] >= 1
    hit2 = eng._prefix.lookup(shared)
    assert hit2 is not None and hit2.pages == hit.pages
    for key in keys:
        for p, before in zip(hit2.pages, snap[key]):
            np.testing.assert_array_equal(
                np.asarray(eng._cache[key][0][p]), before,
                err_msg=f"shared page {p} pool {key} mutated")
    eng._allocator.free(hit2.pages)

    eng._prefix.clear()
    assert eng._allocator.available == eng.cache_cfg.num_pages - 1
    # double-free still detected after the cache released everything
    with pytest.raises(ValueError):
        eng._allocator.free(hit.pages)


def test_admit_burst_fills_all_free_slots(llama_parts):
    """M2KT_SERVE_ADMIT_BURST regression: burst<=0 admits every free
    slot in one step; the default (1) keeps the one-admission-per-step
    pacing."""
    model, variables = llama_parts
    rng = np.random.default_rng(23)
    reqs = [Request(f"r{i}", rng.integers(1, 200, size=6).tolist(), 8)
            for i in range(4)]

    burst = _engine(model, variables, max_batch=4, admit_burst=0)
    for r in reqs:
        burst.submit(Request(r.rid, list(r.prompt), r.max_new_tokens))
    burst.step()
    assert sum(s is not None for s in burst._slots) == 4

    paced = _engine(model, variables, max_batch=4)  # admit_burst=1
    for r in reqs:
        paced.submit(Request(r.rid, list(r.prompt), r.max_new_tokens))
    paced.step()
    assert sum(s is not None for s in paced._slots) == 1
    # both drain to the same completions regardless of admission pacing
    done_b = {c.rid: c.tokens for c in burst.run([])}
    done_p = {c.rid: c.tokens for c in paced.run([])}
    assert done_b == done_p and set(done_b) == {r.rid for r in reqs}


# ----------------------------------------------------------------------
# disaggregated prefill/decode
# ----------------------------------------------------------------------

def test_kv_handoff_wire_roundtrip():
    rng = np.random.default_rng(5)
    kv = [(rng.standard_normal((1, 16, 2, 8)).astype(np.float32),
           rng.standard_normal((1, 16, 2, 8)).astype(np.float32))
          for _ in range(3)]
    h = KVHandoff(rid="x", prompt=[1, 2, 3], prompt_len=3, bucket=16,
                  first_token=42, kv=kv, max_new_tokens=7)
    h2 = KVHandoff.from_bytes(h.to_bytes())
    assert (h2.rid, h2.prompt, h2.prompt_len, h2.bucket, h2.first_token,
            h2.max_new_tokens) == ("x", [1, 2, 3], 3, 16, 42, 7)
    assert len(h2.kv) == 3
    for (k, v), (k2, v2) in zip(kv, h2.kv):
        np.testing.assert_array_equal(k, k2)
        np.testing.assert_array_equal(v, v2)

    # future wire versions must be rejected, not mis-parsed
    blob = h.to_bytes()
    import io
    import zipfile

    with zipfile.ZipFile(io.BytesIO(blob)) as z:
        names = z.namelist()
    assert "meta.npy" in names
    bad = dataclasses.replace(h)
    bad_bytes = bad.to_bytes().replace(b'"v": 1', b'"v": 9')
    # savez compresses, so flip the version through the dataclass instead
    import move2kube_tpu.serving.fleet.disagg as disagg

    old = disagg._WIRE_VERSION
    try:
        disagg._WIRE_VERSION = 9
        blob9 = h.to_bytes()
    finally:
        disagg._WIRE_VERSION = old
    with pytest.raises(ValueError):
        KVHandoff.from_bytes(blob9)
    del bad_bytes


def test_disagg_handoff_equivalence(llama_parts):
    """Prefill-on-replica-A + install-on-engine-B must decode the same
    tokens as the engine doing its own prefill."""
    model, variables = llama_parts
    rng = np.random.default_rng(31)
    reqs = [Request(f"d{i}", rng.integers(1, 200, size=n).tolist(), 4)
            for i, n in enumerate((6, 12, 9))]

    plain = _engine(model, variables)
    want = {c.rid: c.tokens for c in plain.run(
        [Request(r.rid, list(r.prompt), r.max_new_tokens) for r in reqs])}

    prefill = PrefillReplica(model, variables,
                             EngineConfig(max_batch=2, max_seq=64,
                                          block_size=8, buckets=(16, 32)))
    decode = _engine(model, variables)
    pair = DisaggPair(prefill, decode, InProcessTransport())
    got = {c.rid: c.tokens for c in pair.run(reqs)}
    assert got == want


# ----------------------------------------------------------------------
# router placement / failover / hedging
# ----------------------------------------------------------------------

class FakeReplica(ReplicaHandle):
    def __init__(self, name, depth=0.0):
        self.name = name
        self.depth = depth
        self.calls = 0
        self.fail_next = 0
        self.hold_s = 0.0
        self.up = True

    def generate(self, prompt, max_new_tokens=None, rid=None,
                 tenant="", traceparent="", deadline_s=None,
                 on_token=None):
        if self.fail_next > 0:
            self.fail_next -= 1
            raise RuntimeError(f"{self.name}: injected failure")
        if self.hold_s:
            time.sleep(self.hold_s)
        self.calls += 1
        tokens = [1, 2]
        if on_token is not None:
            for t in tokens:
                on_token(t)
        return {"rid": rid or "r", "replica": self.name,
                "prompt_len": len(prompt), "tokens": tokens,
                "finish_reason": "length"}

    def queue_depth(self):
        return self.depth

    def healthy(self):
        return self.up


def _fake_router(n=3, **cfg):
    replicas = [FakeReplica(f"rep-{i}") for i in range(n)]
    return Router(replicas, config=RouterConfig(**cfg)), replicas


def test_router_affinity_is_stable():
    router, replicas = _fake_router()
    prompt = list(range(50, 70))
    first = router.generate(prompt)["replica"]
    for _ in range(5):
        assert router.generate(prompt)["replica"] == first
    assert router._affinity_hits.value >= 6
    # a different salt may remap the tenant; the hash must at least move
    assert prefix_hash(prompt, "a") != prefix_hash(prompt, "b")
    # only keys owned by a removed replica move (rendezvous property)
    survivors = [r for r in replicas if r.name != first]
    rerouted = Router(survivors, config=RouterConfig())
    other_prompt = None
    for seed in range(100):
        p = list(range(seed, seed + 8))
        owner = router.pick(p).name
        if owner != first:
            other_prompt = (p, owner)
            break
    assert other_prompt is not None
    p, owner = other_prompt
    assert rerouted.pick(p).name == owner


def test_router_failover_marks_down_and_probe_recovers():
    router, replicas = _fake_router()
    prompt = list(range(10))
    affine = router.pick(prompt)
    affine.fail_next = 1
    out = router.generate(prompt)
    assert out["replica"] != affine.name
    assert router._retries.value == 1
    assert router._up[affine.name] is False
    # the replica answers its health check again -> probe() readmits it
    router.probe()
    assert router._up[affine.name] is True
    assert router.generate(prompt)["replica"] == affine.name


def test_router_spills_on_deep_queue():
    router, replicas = _fake_router(spill_queue_depth=2.0)
    prompt = list(range(30, 40))
    affine = router.pick(prompt)
    affine.depth = 10.0
    others = [r for r in replicas if r.name is not affine.name]
    others[0].depth = 1.0
    picked = router.pick(prompt)
    assert picked.name != affine.name
    assert router._spills.value >= 1


def test_router_hedging_fires_and_first_wins():
    router, replicas = _fake_router(hedge_after_s=0.05)
    prompt = list(range(5))
    affine = router.pick(prompt)
    affine.hold_s = 0.5
    t0 = time.perf_counter()
    out = router.generate(prompt)
    dt = time.perf_counter() - t0
    assert out["replica"] != affine.name  # the hedge won
    assert router._hedges.value == 1
    assert dt < 0.5  # did not wait out the slow primary


def test_router_all_down_raises():
    router, replicas = _fake_router(max_retries=1)
    for r in replicas:
        r.fail_next = 5
    with pytest.raises(RuntimeError):
        router.generate([1, 2, 3])
    assert router._requests.labels(outcome="error").value == 1


def test_router_http_front():
    router, replicas = _fake_router()
    srv = RouterHTTPServer(router, port=0).start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        body = json.dumps({"prompt": [1, 2, 3],
                           "max_new_tokens": 2}).encode()
        req = urllib.request.Request(
            f"{base}/generate", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            out = json.loads(resp.read().decode())
        assert out["tokens"] == [1, 2]
        with urllib.request.urlopen(f"{base}/readyz", timeout=10) as resp:
            assert resp.status == 200
        with urllib.request.urlopen(f"{base}/metrics", timeout=10) as resp:
            text = resp.read().decode()
        assert "m2kt_router_requests_total" in text
        assert "m2kt_router_replica_up" in text
    finally:
        srv.close()


@pytest.mark.slow
def test_in_process_fleet_end_to_end(llama_parts):
    """Two real engine replicas behind the router: same-tenant requests
    stick to one replica and the second one hits its prefix cache."""
    model, variables = llama_parts
    cfg = EngineConfig(max_batch=2, max_seq=64, block_size=8,
                       buckets=(16, 32), prefix_cache=True)
    router = build_fleet(model, variables, 2, engine_config=cfg)
    try:
        rng = np.random.default_rng(41)
        tenant = rng.integers(1, 200, size=12).tolist()
        outs = [router.generate(list(tenant), 3) for _ in range(3)]
        assert len({o["replica"] for o in outs}) == 1
        hits = sum(r.engine.stats().get("prefix_hits", 0)
                   for r in router.replicas)
        assert hits >= 2
        tok0 = outs[0]["tokens"]
        assert all(o["tokens"] == tok0 for o in outs)
    finally:
        for r in router.replicas:
            r.close()


# ----------------------------------------------------------------------
# emission: per-role manifests, HPAs, Helm lift
# ----------------------------------------------------------------------

def _serving_ir():
    from move2kube_tpu.types.ir import IR, Service
    from move2kube_tpu.types.plan import AcceleratorInfo

    svc = Service(
        name="llm",
        containers=[{
            "name": "llm", "image": "llm:latest",
            "ports": [{"containerPort": 8080},
                      {"name": "metrics", "containerPort": 9090}],
            "env": [{"name": "M2KT_METRICS_PORT", "value": "9090"},
                    {"name": "M2KT_SERVE_MAX_BATCH", "value": "8"}],
        }],
        accelerator=AcceleratorInfo(serving=True, serving_port=8080,
                                    tpu_accelerator="tpu-v5-lite-podslice",
                                    tpu_topology="2x2"),
    )
    return IR(services={"llm": svc}), svc


def _fleet_env(monkeypatch, prefill="1"):
    monkeypatch.setenv("M2KT_FLEET", "1")
    monkeypatch.setenv("M2KT_FLEET_ROUTERS", "1")
    monkeypatch.setenv("M2KT_FLEET_PREFILL", prefill)
    monkeypatch.setenv("M2KT_FLEET_DECODE", "3")
    monkeypatch.setenv("M2KT_FLEET_AFFINITY_SALT", "blue")


def test_fleet_deployment_emission(monkeypatch):
    from move2kube_tpu.apiresource.deployment import DeploymentAPIResource

    _fleet_env(monkeypatch)
    ir, svc = _serving_ir()
    objs = DeploymentAPIResource().create_new_resources(
        ir, {"Deployment", "JobSet"})
    by = {(o["kind"], o["metadata"]["name"]): o for o in objs}
    assert set(by) == {
        ("Deployment", "llm-router"), ("Deployment", "llm-prefill"),
        ("Deployment", "llm-decode"),
        ("HorizontalPodAutoscaler", "llm-router"),
        ("HorizontalPodAutoscaler", "llm-prefill"),
        ("HorizontalPodAutoscaler", "llm-decode"),
        ("PodDisruptionBudget", "llm-router"),
        ("PodDisruptionBudget", "llm-prefill"),
        ("PodDisruptionBudget", "llm-decode"),
        ("Service", "llm-prefill"), ("Service", "llm-decode"),
    }
    # router pods keep the front Service's selector label; engines don't
    router = by[("Deployment", "llm-router")]
    assert router["spec"]["selector"]["matchLabels"][
        "move2kube-tpu.io/service"] == "llm"
    decode = by[("Deployment", "llm-decode")]
    assert decode["spec"]["selector"]["matchLabels"][
        "move2kube-tpu.io/service"] == "llm-decode"
    assert decode["spec"]["replicas"] == 3
    rc = router["spec"]["template"]["spec"]["containers"][0]
    renv = {e["name"]: e.get("value") for e in rc["env"]}
    assert renv["M2KT_FLEET_ROLE"] == "router"
    assert renv["M2KT_ROUTER_BACKENDS"] == "llm-decode:8080"
    assert renv["M2KT_FLEET_PREFILL_SERVICE"] == "llm-prefill:8080"
    assert renv["M2KT_FLEET_AFFINITY_SALT"] == "blue"
    assert "google.com/tpu" not in rc.get("resources", {}).get("limits", {})
    assert rc["readinessProbe"]["httpGet"]["path"] == "/readyz"
    dc = decode["spec"]["template"]["spec"]["containers"][0]
    denv = {e["name"]: e.get("value") for e in dc["env"]}
    assert denv["M2KT_FLEET_ROLE"] == "decode"
    assert denv["M2KT_SERVE_PREFIX_CACHE"] == "1"
    assert dc["resources"]["limits"]["google.com/tpu"] == 4
    # HPA targets: queue depth for router/prefill, slot occupancy decode
    assert by[("HorizontalPodAutoscaler", "llm-router")]["spec"][
        "metrics"][0]["pods"]["metric"]["name"] == "m2kt_serve_queue_depth"
    assert by[("HorizontalPodAutoscaler", "llm-decode")]["spec"][
        "metrics"][0]["pods"]["metric"]["name"] == \
        "m2kt_serve_slot_occupancy"
    # backend role Services are headless (router needs pod IPs)
    assert by[("Service", "llm-decode")]["spec"]["clusterIP"] == "None"
    assert by[("Service", "llm-decode")]["spec"]["selector"][
        "move2kube-tpu.io/service"] == "llm-decode"
    # per-role PDBs select exactly the pods their Deployment manages
    pdb = by[("PodDisruptionBudget", "llm-decode")]
    assert pdb["apiVersion"] == "policy/v1"
    assert pdb["spec"]["selector"]["matchLabels"] == \
        decode["spec"]["selector"]["matchLabels"]
    assert pdb["spec"]["minAvailable"] == 1
    # drain wiring: grace period covers the drain budget, and the decode
    # role's preStop POSTs /drain so in-flight streams finish first
    tmpl = decode["spec"]["template"]["spec"]
    assert tmpl["terminationGracePeriodSeconds"] >= 30
    cmd = tmpl["containers"][0]["lifecycle"]["preStop"]["exec"]["command"]
    assert "/drain" in " ".join(cmd)
    rtmpl = router["spec"]["template"]["spec"]
    assert rtmpl["terminationGracePeriodSeconds"] >= 30


def test_fleet_off_keeps_single_workload(monkeypatch):
    from move2kube_tpu.apiresource.deployment import DeploymentAPIResource

    monkeypatch.setenv("M2KT_FLEET", "0")
    ir, svc = _serving_ir()
    objs = DeploymentAPIResource().create_new_resources(
        ir, {"Deployment", "JobSet"})
    kinds = [(o["kind"], o["metadata"]["name"]) for o in objs]
    assert ("Deployment", "llm") in kinds
    assert not any("router" in n for _, n in kinds)


def test_fleet_knative_emission(monkeypatch):
    from move2kube_tpu.apiresource.knative import KnativeServiceAPIResource

    _fleet_env(monkeypatch)
    ir, svc = _serving_ir()
    objs = KnativeServiceAPIResource(create=True).create_new_resources(
        ir, {"Service"})
    kn = {o["metadata"]["name"]: o for o in objs if o["kind"] == "Service"}
    assert set(kn) == {"llm-router", "llm-prefill", "llm-decode"}
    ann = kn["llm-decode"]["spec"]["template"]["metadata"]["annotations"]
    assert ann["autoscaling.knative.dev/class"] == \
        "hpa.autoscaling.knative.dev"
    assert ann["autoscaling.knative.dev/metric"] == \
        "m2kt_serve_slot_occupancy"
    rann = kn["llm-router"]["spec"]["template"]["metadata"]["annotations"]
    assert rann["autoscaling.knative.dev/metric"] == "m2kt_serve_queue_depth"
    assert rann["autoscaling.knative.dev/minScale"] == "1"


def test_fleet_optimizer_and_helm_lift(monkeypatch):
    from move2kube_tpu.passes.optimize import tpu_fleet_optimizer
    from move2kube_tpu.passes.parameterize import tpu_fleet_parameterizer

    _fleet_env(monkeypatch)
    ir, svc = _serving_ir()
    ir = tpu_fleet_optimizer(ir)
    env = {e["name"]: e["value"] for e in svc.containers[0]["env"]}
    assert env["M2KT_FLEET"] == "1"
    assert env["M2KT_FLEET_DECODE"] == "3"
    assert env["M2KT_SERVE_PREFIX_CACHE"] == "1"
    assert env["M2KT_DEADLINE_S"] == "120"
    assert env["M2KT_DRAIN_GRACE_S"] == "30"
    assert env["M2KT_FLEET_MIN_AVAILABLE"] == "1"
    ir = tpu_fleet_parameterizer(ir)
    gv = ir.values.global_variables
    assert gv["tpufleet"] == "1"
    assert gv["tpufleetrouters"] == "1"
    assert gv["tpufleetprefill"] == "1"
    assert gv["tpufleetdecode"] == "3"
    assert gv["tpufleetsalt"] == "blue"
    assert gv["tpufleetdeadline"] == "120"
    assert gv["tpufleetdraingrace"] == "30"
    assert gv["tpufleetminavailable"] == "1"
    env = {e["name"]: e["value"] for e in svc.containers[0]["env"]}
    assert env["M2KT_FLEET_DECODE"] == "{{ .Values.tpufleetdecode }}"
    assert env["M2KT_FLEET_AFFINITY_SALT"] == "{{ .Values.tpufleetsalt }}"
    # idempotent: already-lifted refs are not double-wrapped
    ir = tpu_fleet_parameterizer(ir)
    env = {e["name"]: e["value"] for e in svc.containers[0]["env"]}
    assert env["M2KT_FLEET_DECODE"] == "{{ .Values.tpufleetdecode }}"


def test_fleet_package_is_vendored():
    from move2kube_tpu.containerizer.jax_emit import _vendor_package
    from move2kube_tpu.types.ir import Container

    c = Container()
    _vendor_package(c)
    for mod in ("__init__", "router", "prefixcache", "disagg"):
        assert f"move2kube_tpu/serving/fleet/{mod}.py" in c.new_files
