"""Legacy cluster version tables + write-time downgrade (VERDICT r4 #6).

Parity: ``internal/metadata/clusters/constants.go:23-1116`` (per-cluster
multi-version preference lists) + ``k8stransformer.go:94-156`` (every
object converted to the cluster's preferred supported version at write
time). The first same-group entry in the profile's list wins.
"""


from move2kube_tpu.apiresource.base import convert_objects
from move2kube_tpu.metadata.clusters import get_cluster
from move2kube_tpu.transformer.k8s import k8s_api_resources
from move2kube_tpu.types.collection import ClusterMetadataSpec
from move2kube_tpu.types.ir import IR, Service


def _ir(cluster_name: str | None = None,
        spec: ClusterMetadataSpec | None = None) -> IR:
    ir = IR(name="legacy")
    if cluster_name:
        ir.target_cluster_spec = get_cluster(cluster_name).spec
    if spec is not None:
        ir.target_cluster_spec = spec
    return ir


def _web_service() -> Service:
    svc = Service(name="web")
    svc.containers.append({"name": "web", "image": "web:1",
                           "ports": [{"containerPort": 8080}]})
    svc.add_port_forwarding(80, 8080, "http")
    from move2kube_tpu.utils import common
    svc.annotations[common.EXPOSE_SERVICE_ANNOTATION] = "true"
    return svc


def test_eks_profile_downgrades_emitted_ingress():
    """The EKS vintage table prefers networking.k8s.io/v1beta1: a newly
    created Ingress must downgrade WITH the legacy backend schema (same
    group, different version — the group-rename path alone misses it)."""
    ir = _ir("AWS-EKS")
    ir.add_service(_web_service())
    out = convert_objects(ir, k8s_api_resources())
    ing = [o for o in out if o.get("kind") == "Ingress"]
    assert ing, "no ingress emitted"
    assert ing[0]["apiVersion"] == "networking.k8s.io/v1beta1"
    path = ing[0]["spec"]["rules"][0]["http"]["paths"][0]
    assert "serviceName" in path["backend"], path
    assert "pathType" not in path


def test_modern_kubernetes_profile_keeps_ingress_v1():
    ir = _ir("Kubernetes")
    ir.add_service(_web_service())
    out = convert_objects(ir, k8s_api_resources())
    ing = [o for o in out if o.get("kind") == "Ingress"]
    assert ing[0]["apiVersion"] == "networking.k8s.io/v1"
    path = ing[0]["spec"]["rules"][0]["http"]["paths"][0]
    assert "service" in path["backend"]


def test_old_collected_cluster_downgrades_deployment():
    """A collected vintage cluster advertising only apps/v1beta1 gets
    apps/v1beta1 Deployments (k8stransformer.go:94-156 equivalence)."""
    spec = ClusterMetadataSpec(api_kind_version_map={
        "Deployment": ["apps/v1beta1"], "Service": ["v1"],
    })
    ir = _ir(spec=spec)
    ir.add_service(_web_service())
    out = convert_objects(ir, k8s_api_resources())
    deps = [o for o in out if o.get("kind") == "Deployment"]
    assert deps and deps[0]["apiVersion"] == "apps/v1beta1"


def test_cached_cronjob_downgrades_to_v1beta1_on_builtin_profiles():
    """Every reference vintage profile prefers batch/v1beta1 for CronJob
    (GA came in k8s 1.21): a modern batch/v1 CronJob downgrades."""
    cron = {
        "apiVersion": "batch/v1", "kind": "CronJob",
        "metadata": {"name": "tick"},
        "spec": {"schedule": "* * * * *", "jobTemplate": {"spec": {
            "template": {"spec": {"containers": [{"name": "t", "image": "x"}],
                                  "restartPolicy": "Never"}}}}},
    }
    ir = _ir("GCP-GKE")
    ir.cached_objects.append(cron)
    out = convert_objects(ir, k8s_api_resources())
    cj = [o for o in out if o.get("kind") == "CronJob"]
    assert cj and cj[0]["apiVersion"] == "batch/v1beta1"
    # schema untouched: schedule + jobTemplate survive
    assert cj[0]["spec"]["schedule"] == "* * * * *"


def test_hpa_v2_downgrades_to_v1_with_metric_rewrite():
    """autoscaling/v2 metrics collapse to targetCPUUtilizationPercentage
    when the profile prefers autoscaling/v1 (all vintage profiles do)."""
    hpa = {
        "apiVersion": "autoscaling/v2", "kind": "HorizontalPodAutoscaler",
        "metadata": {"name": "web"},
        "spec": {
            "minReplicas": 1, "maxReplicas": 5,
            "scaleTargetRef": {"apiVersion": "apps/v1", "kind": "Deployment",
                               "name": "web"},
            "metrics": [
                {"type": "Resource", "resource": {
                    "name": "cpu",
                    "target": {"type": "Utilization", "averageUtilization": 70}}},
                {"type": "Resource", "resource": {
                    "name": "memory",
                    "target": {"type": "Utilization", "averageUtilization": 60}}},
            ],
        },
    }
    ir = _ir("Kubernetes")
    ir.cached_objects.append(hpa)
    out = convert_objects(ir, k8s_api_resources())
    got = [o for o in out if o.get("kind") == "HorizontalPodAutoscaler"]
    assert got, "HPA dropped"
    assert got[0]["apiVersion"] == "autoscaling/v1"
    spec = got[0]["spec"]
    assert spec["targetCPUUtilizationPercentage"] == 70
    assert "metrics" not in spec
    assert spec["maxReplicas"] == 5


def test_openshift_profile_prefers_extensions_ingress():
    """The vintage Openshift tables list Ingress ONLY under the
    extensions umbrella (Routes are the native path)."""
    ir = _ir("Openshift")
    # openshift targets convert ingress to Route; use a cached Ingress on
    # the spec directly to exercise the version table
    versions = ir.target_cluster_spec.get_supported_versions("Ingress")
    assert versions == ["extensions/v1beta1"]
    dep_versions = ir.target_cluster_spec.get_supported_versions("Deployment")
    assert dep_versions[0] == "apps/v1"  # modern first, legacy served after
    assert "apps/v1beta1" in dep_versions


def test_gke_tpu_profile_stays_modern():
    spec = get_cluster("GCP-GKE-TPU").spec
    assert spec.get_supported_versions("Ingress") == ["networking.k8s.io/v1"]
    assert spec.get_supported_versions("CronJob") == ["batch/v1"]
    assert spec.get_supported_versions("HorizontalPodAutoscaler") == [
        "autoscaling/v2"]
    assert spec.get_supported_versions("JobSet") == ["jobset.x-k8s.io/v1alpha2"]


def test_profiles_match_reference_vintages():
    """Spot-check the table entries against the reference constants.go
    vintages (first-preference semantics)."""
    eks = get_cluster("AWS-EKS").spec
    assert eks.get_supported_versions("Ingress")[0] == "networking.k8s.io/v1beta1"
    assert eks.get_supported_versions("CronJob")[0] == "batch/v1beta1"
    assert eks.get_supported_versions("HorizontalPodAutoscaler")[0] == \
        "autoscaling/v1"
    iks = get_cluster("IBM-IKS").spec
    assert iks.get_supported_versions("CronJob") == ["batch/v1beta1",
                                                     "batch/v2alpha1"]
    assert iks.get_supported_versions("Ingress")[0] == "networking.k8s.io/v1"
    osf = get_cluster("IBM-Openshift").spec
    dep = osf.get_supported_versions("Deployment")
    assert dep[0] == "apps/v1"  # preference-sorted; callers take [0]
    assert set(dep) == {"apps/v1", "apps/v1beta1", "apps/v1beta2",
                        "extensions/v1beta1"}
    assert set(osf.get_supported_versions("PodSecurityPolicy")) == {
        "extensions/v1beta1", "policy/v1beta1"}


def test_hpa_v2beta1_metrics_reshape_to_v2():
    """Cross-v2 conversion rewrites the per-metric shape, not just the
    apiVersion (v2beta1 flat fields <-> v2 target objects)."""
    hpa = {
        "apiVersion": "autoscaling/v2beta1", "kind": "HorizontalPodAutoscaler",
        "metadata": {"name": "web"},
        "spec": {"maxReplicas": 4,
                 "scaleTargetRef": {"kind": "Deployment", "name": "web"},
                 "metrics": [{"type": "Resource", "resource": {
                     "name": "cpu", "targetAverageUtilization": 50}}]},
    }
    ir = _ir("GCP-GKE-TPU")  # prefers autoscaling/v2
    ir.cached_objects.append(hpa)
    out = convert_objects(ir, k8s_api_resources())
    got = [o for o in out if o.get("kind") == "HorizontalPodAutoscaler"][0]
    assert got["apiVersion"] == "autoscaling/v2"
    res = got["spec"]["metrics"][0]["resource"]
    assert res["target"] == {"type": "Utilization", "averageUtilization": 50}
    assert "targetAverageUtilization" not in res


def test_hpa_v2_metrics_reshape_to_v2beta1():
    from move2kube_tpu.apiresource.base import _convert_hpa_spec

    hpa = {
        "apiVersion": "autoscaling/v2", "kind": "HorizontalPodAutoscaler",
        "metadata": {"name": "web"},
        "spec": {"metrics": [
            {"type": "Resource", "resource": {
                "name": "memory",
                "target": {"type": "AverageValue", "averageValue": "1Gi"}}},
        ]},
    }
    _convert_hpa_spec(hpa, "autoscaling/v2beta1")
    res = hpa["spec"]["metrics"][0]["resource"]
    assert res["targetAverageValue"] == "1Gi"
    assert "target" not in res


def test_hpa_pods_metric_reshapes_across_v2_versions():
    """Non-Resource metric types (Pods/Object/External) also reshape
    between v2beta1 flat fields and v2 metric/target objects."""
    from move2kube_tpu.apiresource.base import (
        _hpa_metric_from_v2beta1, _hpa_metric_to_v2beta1)

    legacy = {"type": "Pods", "pods": {"metricName": "qps",
                                       "targetAverageValue": "100"}}
    modern = _hpa_metric_from_v2beta1(legacy)
    assert modern["pods"]["metric"] == {"name": "qps"}
    assert modern["pods"]["target"] == {"type": "AverageValue",
                                        "averageValue": "100"}
    back = _hpa_metric_to_v2beta1(modern)
    assert back["pods"]["metricName"] == "qps"
    assert back["pods"]["targetAverageValue"] == "100"
    assert "target" not in back["pods"]


def test_hpa_behavior_stripped_on_v2beta1_downgrade():
    from move2kube_tpu.apiresource.base import _convert_hpa_spec

    hpa = {"apiVersion": "autoscaling/v2", "kind": "HorizontalPodAutoscaler",
           "metadata": {"name": "web"},
           "spec": {"behavior": {"scaleDown": {"stabilizationWindowSeconds": 300}},
                    "metrics": []}}
    _convert_hpa_spec(hpa, "autoscaling/v2beta1")
    assert "behavior" not in hpa["spec"]


def test_gke_tpu_profile_drops_psp():
    """PodSecurityPolicy was removed in k8s 1.25; the JobSet-capable TPU
    profile must not advertise it."""
    spec = get_cluster("GCP-GKE-TPU").spec
    assert spec.get_supported_versions("PodSecurityPolicy") == []


def test_hpa_object_metric_round_trips_described_object():
    """Object metrics name the scaled object ``target`` in v2beta1 and
    ``describedObject`` in v2 — colliding with v2's metric-target
    ``target``. Both conversion directions must rename it, and the
    modern-shape marker is the nested ``metric`` (NOT ``target``, which
    legacy Object metrics also carry)."""
    from move2kube_tpu.apiresource.base import (
        _hpa_metric_from_v2beta1, _hpa_metric_to_v2beta1)

    ref = {"apiVersion": "networking.k8s.io/v1", "kind": "Ingress",
           "name": "main-route"}
    legacy = {"type": "Object",
              "object": {"metricName": "requests-per-second",
                         "targetValue": "10k", "target": dict(ref)}}
    modern = _hpa_metric_from_v2beta1(legacy)
    obj = modern["object"]
    assert obj["describedObject"] == ref
    assert obj["metric"] == {"name": "requests-per-second"}
    assert obj["target"] == {"type": "Value", "value": "10k"}
    assert "metricName" not in obj

    back = _hpa_metric_to_v2beta1(modern)
    assert back["object"]["target"] == ref
    assert back["object"]["metricName"] == "requests-per-second"
    assert back["object"]["targetValue"] == "10k"
    assert "describedObject" not in back["object"]

    # already-modern input passes through untouched: its structured
    # metric-target must not be mistaken for an object reference
    assert _hpa_metric_from_v2beta1(modern) == modern
