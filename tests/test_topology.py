"""Topology-aware multichip stack (parallel/topology.py + overlap.py +
models/precision.py): planner goldens for documented slice shapes, the
physical device-order permutation, batch sharding over all data-like
axes, overlapped gradient accumulation vs the sequential reference, and
the precision policy / compile-cache fingerprint plumbing.

Planner and precision tests are pure python; device tests run on the 8
forced host devices conftest.py provides (skipped when unavailable)."""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from move2kube_tpu.models import precision as m2kt_precision
from move2kube_tpu.models import train as m2kt_train
from move2kube_tpu.models.compile_cache import (
    setup_compilation_cache,
    topology_fingerprint,
)
from move2kube_tpu.parallel.mesh import MeshConfig, make_mesh
from move2kube_tpu.parallel.overlap import (
    is_pure_data_parallel,
    ring_all_reduce,
)
from move2kube_tpu.parallel.compat import shard_map
from move2kube_tpu.parallel.topology import (
    parse_topology,
    plan_parallelism,
    resolve_mesh_plan,
)

needs_8 = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs 8 (forced host) devices")


# ---------------------------------------------------------------- parser

def test_parse_topology():
    assert parse_topology("2x4") == (2, 4)
    assert parse_topology("4x4x4") == (4, 4, 4)
    assert parse_topology("8") == (8,)


@pytest.mark.parametrize("bad", ["", "0x4", "-2x4", "2xbanana", "x4"])
def test_parse_topology_rejects_malformed(bad):
    with pytest.raises(ValueError):
        parse_topology(bad)


# --------------------------------------------------------- planner goldens

def test_plan_2x4_data_parallel():
    plan = plan_parallelism(8, topology="2x4")
    assert plan.config.dims() == (8, 1, 1, 1, 1, 1)
    # the single axis spans both dims, wraparound (size-4) dim first
    assert plan.layout == {"data": (1, 0)}
    assert plan.source == "planner"
    assert sorted(plan.perm) == list(range(8))
    assert plan.describe() == (
        "mesh=8x1x1x1x1x1 topology=2x4 layout=[data@1+0] source=planner")


def test_plan_2x4_zero3():
    plan = plan_parallelism(8, topology="2x4", zero_stage=3)
    assert plan.config.dims() == (1, 8, 1, 1, 1, 1)
    assert plan.layout == {"fsdp": (1, 0)}
    # fsdp (weight 10) straddling 2 dims: 10 * 2*2 hops
    assert plan.ici_cost == 40.0


def test_plan_2x4_tensor2():
    plan = plan_parallelism(8, topology="2x4", tensor_parallel=2)
    assert plan.config.dims() == (4, 1, 1, 2, 1, 1)
    # tensor (heaviest) carves its factor out of the wraparound dim first
    assert plan.layout["tensor"] == (1,)


def test_plan_4x4x4_tensor4_zero3():
    plan = plan_parallelism(64, topology="4x4x4", zero_stage=3,
                            tensor_parallel=4)
    assert plan.config.dims() == (1, 16, 1, 4, 1, 1)
    # tensor occupies exactly one wraparound dim: ring all-reduce cost 1
    (tdim,) = plan.layout["tensor"]
    assert plan.topology.wraparound[tdim]
    assert sorted(plan.perm) == list(range(64))


def test_plan_single_chip():
    plan = plan_parallelism(1)
    assert plan.source == "single-chip"
    assert plan.perm == (0,)
    assert plan.config.total() == 1


def test_plan_4x2_tensor2_permutation():
    """tensor=2 on a (4,2) grid: the wraparound size-4 dim goes to data
    (gcd(2,4)=2 still lands tensor there first, so check the realized
    perm: logical neighbours on the heaviest axis are physically
    adjacent in the row-major enumeration)."""
    plan = plan_parallelism(8, topology="4x2", tensor_parallel=2)
    assert plan.config.dims() == (4, 1, 1, 2, 1, 1)
    assert plan.perm == (0, 2, 4, 6, 1, 3, 5, 7)


def test_plan_memory_split_resplits_fsdp():
    """30 GB of params on v5e (16 GB HBM): fp32 master state can't fit
    replicated, so the planner re-splits the dp pool into fsdp=8."""
    plan = plan_parallelism(8, topology="2x4",
                            slice_type="tpu-v5-lite-podslice",
                            param_bytes=int(30e9))
    assert plan.config.fsdp == 8
    assert plan.config.data == 1


def test_plan_mismatched_topology_falls_back_to_chain():
    for topo in ("2x2", "2xbanana"):
        plan = plan_parallelism(8, topology=topo)
        assert plan.source == "fallback-chain"
        assert plan.topology.dims == (8,)
        assert plan.config.total() == 8


def test_resolve_env_topology_and_mesh_override():
    plan = resolve_mesh_plan(8, env={"M2KT_TPU_TOPOLOGY": "2x4"})
    assert plan.source == "planner"
    assert plan.topology.dims == (2, 4)

    plan = resolve_mesh_plan(
        8, default_topology="2x4",
        env={"M2KT_MESH_DATA": "2", "M2KT_MESH_TENSOR": "4"})
    assert plan.source == "env-mesh"
    assert plan.config.dims() == (2, 1, 1, 4, 1, 1)

    # an override that doesn't match the device count is ignored
    plan = resolve_mesh_plan(8, default_topology="2x4",
                             env={"M2KT_MESH_DATA": "4"})
    assert plan.source == "planner"
    assert plan.config.dims() == (8, 1, 1, 1, 1, 1)


def test_device_order_identity_on_length_mismatch():
    plan = plan_parallelism(8, topology="2x4")
    devs = list(range(4))  # wrong length: permutation must not apply
    assert plan.device_order(devs) == devs


# ------------------------------------------------- multislice (DCN) planning

def test_plan_multislice_dcn_dp_outer_data_axis():
    """2 slices of 2x4: the data extent doubles (dcn_dp=2 outer factor),
    while topology / layout / per-slice permutation are EXACTLY the
    single-slice plan — only DP rides DCN, everything else stays ICI."""
    single = plan_parallelism(8, topology="2x4")
    multi = plan_parallelism(16, topology="2x4", num_slices=2)
    assert multi.config.dims() == (16, 1, 1, 1, 1, 1)
    assert multi.dcn_dp == 2
    assert multi.topology.dims == single.topology.dims == (2, 4)
    assert multi.layout == single.layout
    assert multi.ici_cost == single.ici_cost  # per-slice semantics
    # slice-major blocks: slice s's devices stay contiguous, each block
    # internally ordered by the single-slice permutation
    assert multi.perm[:8] == single.perm
    assert multi.perm[8:] == tuple(8 + p for p in single.perm)
    assert " dcn_dp=2 " in multi.describe()
    assert " dcn_dp=" not in single.describe()


def test_plan_multislice_model_axes_stay_per_slice():
    single = plan_parallelism(8, topology="2x4", tensor_parallel=2)
    multi = plan_parallelism(16, topology="2x4", tensor_parallel=2,
                             num_slices=2)
    assert multi.config.dims() == (8, 1, 1, 2, 1, 1)  # data x2, tensor same
    assert multi.layout["tensor"] == single.layout["tensor"] == (1,)
    assert multi.ici_cost == single.ici_cost


def test_plan_multislice_memory_resplit_is_per_slice():
    """The 30 GB fp32-state model that forces fsdp=8 on one v5e 2x4 slice
    must re-split each slice the same way: DCN neighbours can't shard
    params, so fsdp stays per-slice and only data multiplies."""
    multi = plan_parallelism(16, topology="2x4",
                             slice_type="tpu-v5-lite-podslice",
                             param_bytes=int(30e9), num_slices=2)
    assert multi.config.fsdp == 8
    assert multi.config.data == 2  # dcn_dp x per-slice data (1)
    assert multi.dcn_dp == 2


def test_plan_indivisible_slices_falls_back_to_single():
    plan = plan_parallelism(8, topology="2x4", num_slices=3)
    assert plan.dcn_dp == 1
    assert plan.config.dims() == (8, 1, 1, 1, 1, 1)
    assert plan.source == "planner"


def test_resolve_num_slices_from_env():
    plan = resolve_mesh_plan(
        16, env={"M2KT_TPU_TOPOLOGY": "2x4", "M2KT_NUM_SLICES": "2"})
    assert plan.dcn_dp == 2
    assert plan.config.data == 16
    # malformed env value must not kill a real run
    plan = resolve_mesh_plan(
        8, env={"M2KT_TPU_TOPOLOGY": "2x4", "M2KT_NUM_SLICES": "banana"})
    assert plan.dcn_dp == 1


# ------------------------------------------------------ mesh construction

@needs_8
def test_make_mesh_accepts_plan():
    plan = plan_parallelism(8, topology="2x4", tensor_parallel=2)
    mesh = make_mesh(plan)
    assert dict(mesh.shape) == {"data": 4, "fsdp": 1, "pipe": 1,
                                "tensor": 2, "seq": 1, "expert": 1}
    # the mesh holds every local device exactly once, in plan order
    got = [d.id for d in mesh.devices.ravel()]
    want = [jax.devices()[i].id for i in plan.perm]
    assert got == want


# ---------------------------------------------------------- batch sharding

def test_data_axes_covers_dp_and_fsdp():
    from jax.sharding import AbstractMesh

    amesh = AbstractMesh((("data", 4), ("fsdp", 2), ("pipe", 1),
                          ("tensor", 1), ("seq", 1), ("expert", 1)))
    assert m2kt_train.data_axes(amesh) == ("data", "fsdp")


@needs_8
@pytest.mark.parametrize("config", [
    MeshConfig(data=4, fsdp=2),   # memory-model split
    MeshConfig(fsdp=8),           # ZeRO: all devices on fsdp
    MeshConfig(data=8),           # pure dp
])
def test_batch_sharding_spans_all_data_axes(config):
    """Regression: sharding over only ``data`` on a dp x fsdp (or
    fsdp-only) mesh replicates the batch across the other axis; the
    batch must land one row per device on all three shapes."""
    mesh = make_mesh(config)
    s = m2kt_train.batch_sharding(mesh)
    assert s.spec == P(("data", "fsdp"))
    x = jax.device_put(jnp.arange(32.0).reshape(8, 4), s)
    shard_shapes = {tuple(sh.data.shape) for sh in x.addressable_shards}
    assert shard_shapes == {(1, 4)}


# ----------------------------------------------------- ring all-reduce

@needs_8
@pytest.mark.parametrize("width", [3, 16])  # 3 exercises the pad path
def test_ring_all_reduce_matches_sum(width):
    mesh = make_mesh(MeshConfig(data=8))
    x = jax.random.normal(jax.random.PRNGKey(0), (8, width))

    def f(block):
        return ring_all_reduce({"a": block}, "data")["a"]

    out = shard_map(f, mesh=mesh, in_specs=(P("data", None),),
                    out_specs=P("data", None))(x)
    want = jnp.broadcast_to(x.sum(axis=0), (8, width))
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               atol=1e-5)


# ------------------------------------- overlapped gradient accumulation

def _llama_fixture():
    import optax

    from move2kube_tpu.models.llama import Llama, llama_tiny

    cfg = dataclasses.replace(llama_tiny(), dtype=jnp.float32)
    model = Llama(cfg)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (16, 32)))
    params = model.init(jax.random.PRNGKey(0), ids[:2])["params"]

    def fresh_state(params_):
        # donation deletes the input buffers: every state gets copies
        return m2kt_train.TrainState.create(
            apply_fn=model.apply,
            params=jax.tree.map(lambda a: a.copy(), params_),
            tx=optax.sgd(1e-2))

    return params, ids, fresh_state


def test_is_pure_data_parallel():
    from jax.sharding import AbstractMesh

    def amesh(**sizes):
        base = {"data": 1, "fsdp": 1, "pipe": 1, "tensor": 1, "seq": 1,
                "expert": 1}
        base.update(sizes)
        return AbstractMesh(tuple(base.items()))

    assert is_pure_data_parallel(amesh(data=8))
    assert not is_pure_data_parallel(amesh(data=4, tensor=2))
    assert not is_pure_data_parallel(amesh(fsdp=8))
    assert not is_pure_data_parallel(amesh())


@needs_8
def test_overlapped_accum_matches_plain_step():
    """grad_accum=2 on a pure-dp mesh (the overlapped ring path) must
    reproduce the single-step update on the flattened batch: lm_loss is
    a batch mean, so averaging two half-batch gradients is exact."""
    params, ids, fresh_state = _llama_fixture()
    mesh = make_mesh(MeshConfig(data=8))
    assert is_pure_data_parallel(mesh)

    step_plain = m2kt_train.make_lm_train_step(mesh, remat=False)
    step_accum = m2kt_train.make_lm_train_step(mesh, remat=False,
                                               grad_accum=2)
    s_plain, loss_plain = step_plain(fresh_state(params),
                                     {"input_ids": ids})
    s_accum, loss_accum = step_accum(fresh_state(params),
                                     {"input_ids": ids.reshape(2, 8, 32)})
    np.testing.assert_allclose(float(loss_plain), float(loss_accum),
                               atol=1e-5)
    for a, b in zip(jax.tree.leaves(s_plain.params),
                    jax.tree.leaves(s_accum.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


@needs_8
def test_sequential_accum_matches_plain_step_on_mp_mesh():
    """grad_accum on a mesh with a model-parallel axis takes the GSPMD
    sequential-scan fallback; same 1e-5 equivalence."""
    params, ids, fresh_state = _llama_fixture()
    mesh = make_mesh(MeshConfig(data=4, tensor=2))
    assert not is_pure_data_parallel(mesh)

    step_plain = m2kt_train.make_lm_train_step(mesh, remat=False)
    step_accum = m2kt_train.make_lm_train_step(mesh, remat=False,
                                               grad_accum=2)
    s_plain, loss_plain = step_plain(fresh_state(params),
                                     {"input_ids": ids})
    s_accum, loss_accum = step_accum(fresh_state(params),
                                     {"input_ids": ids.reshape(2, 8, 32)})
    np.testing.assert_allclose(float(loss_plain), float(loss_accum),
                               atol=1e-5)
    for a, b in zip(jax.tree.leaves(s_plain.params),
                    jax.tree.leaves(s_accum.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


@needs_8
def test_classifier_accum_matches_plain_step():
    import flax.linen as nn
    import optax

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(10)(nn.relu(nn.Dense(32)(x)))

    model = Tiny()
    x = jax.random.normal(jax.random.PRNGKey(0), (16, 8))
    y = jnp.asarray(np.random.default_rng(1).integers(0, 10, (16,)))
    params = model.init(jax.random.PRNGKey(1), x[:2])["params"]
    mesh = make_mesh(MeshConfig(data=8))

    def fresh_state(p):
        return m2kt_train.TrainState.create(
            apply_fn=model.apply,
            params=jax.tree.map(lambda a: a.copy(), p),
            tx=optax.sgd(1e-2))

    step_plain = m2kt_train.make_classifier_train_step(mesh)
    step_accum = m2kt_train.make_classifier_train_step(mesh, grad_accum=2)
    s1, l1 = step_plain(fresh_state(params), {"input": x, "label": y})
    s2, l2 = step_accum(fresh_state(params),
                        {"input": x.reshape(2, 8, 8),
                         "label": y.reshape(2, 8)})
    np.testing.assert_allclose(float(l1), float(l2), atol=1e-5)
    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


# -------------------------------------------------------- precision policy

def test_precision_policies():
    bf16 = m2kt_precision.policy("bf16")
    assert bf16.compute_dtype == "bfloat16"
    assert bf16.param_dtype == "float32"
    assert bf16.loss_scale == 0.0
    assert m2kt_precision.policy("bf16-scaled").loss_scale == 1024.0
    assert m2kt_precision.policy("fp32").jnp_compute_dtype == jnp.float32
    with pytest.raises(ValueError):
        m2kt_precision.policy("fp16")


def test_precision_from_env():
    assert m2kt_precision.from_env(env={}).name == "bf16"
    assert m2kt_precision.from_env(
        env={"M2KT_PRECISION": "fp32"}).name == "fp32"
    # env typos fall back to the default instead of killing the job
    assert m2kt_precision.from_env(
        default="fp32", env={"M2KT_PRECISION": "banana"}).name == "fp32"
    pol = m2kt_precision.from_env(
        env={"M2KT_PRECISION": "bf16-scaled", "M2KT_LOSS_SCALE": "256"})
    assert pol.loss_scale == 256.0


def test_precision_cast_and_scale():
    bf16 = m2kt_precision.policy("bf16")
    tree = {"w": jnp.ones((2, 2), jnp.float32), "step": jnp.int32(3)}
    cast = bf16.cast_params(tree)
    assert cast["w"].dtype == jnp.bfloat16
    assert cast["step"].dtype == jnp.int32  # non-float passes through
    # fp32 policy is the identity
    fp32 = m2kt_precision.policy("fp32")
    assert fp32.cast_params(tree)["w"].dtype == jnp.float32

    scaled = m2kt_precision.policy("bf16-scaled")
    loss = jnp.float32(2.0)
    assert float(scaled.unscale(scaled.scale_loss(loss))) == 2.0
    assert float(bf16.scale_loss(loss)) == 2.0


def test_precision_wrap_optimizer_and_model_config():
    import optax

    from move2kube_tpu.models.llama import llama_tiny

    tx = optax.sgd(1e-2)
    assert m2kt_precision.policy("bf16").wrap_optimizer(tx) is tx
    wrapped = m2kt_precision.policy("bf16-scaled").wrap_optimizer(tx)
    assert wrapped is not tx and hasattr(wrapped, "update")

    cfg = m2kt_precision.policy("bf16").apply_to_model_config(llama_tiny())
    assert cfg.dtype == jnp.bfloat16
    assert m2kt_precision.policy("bf16").apply_to_model_config("x") == "x"


@needs_8
def test_lm_step_with_scaled_precision_is_finite():
    params, ids, fresh_state = _llama_fixture()
    mesh = make_mesh(MeshConfig(data=8))
    step = m2kt_train.make_lm_train_step(
        mesh, remat=False, grad_accum=2,
        precision=m2kt_precision.policy("bf16-scaled"))
    _, loss = step(fresh_state(params), {"input_ids": ids.reshape(2, 8, 32)})
    assert np.isfinite(float(loss))


# ------------------------------------------------ compile-cache fingerprint

def test_topology_fingerprint_empty_for_no_mesh():
    from jax.sharding import AbstractMesh

    assert topology_fingerprint(None) == ""
    amesh = AbstractMesh((("data", 8), ("fsdp", 1), ("pipe", 1),
                          ("tensor", 1), ("seq", 1), ("expert", 1)))
    assert topology_fingerprint(amesh) == ""


@needs_8
def test_topology_fingerprint_distinguishes_mesh_shapes(tmp_path,
                                                        monkeypatch):
    m_dp = make_mesh(MeshConfig(data=8))
    m_split = make_mesh(MeshConfig(data=4, fsdp=2))
    fp_dp, fp_split = topology_fingerprint(m_dp), topology_fingerprint(m_split)
    assert fp_dp and fp_split and fp_dp != fp_split
    assert "n8" in fp_dp and "8x1x1x1x1x1" in fp_dp

    monkeypatch.setenv("M2KT_COMPILE_CACHE_DIR", str(tmp_path))
    monkeypatch.delenv("M2KT_COMPILE_CACHE", raising=False)
    path = setup_compilation_cache(mesh=m_dp)
    assert path == str(tmp_path / fp_dp)
    # restore the default dir so later tests don't write under tmp_path
    monkeypatch.delenv("M2KT_COMPILE_CACHE_DIR")
    setup_compilation_cache()


@needs_8
def test_topology_fingerprint_slice_tag(tmp_path, monkeypatch):
    """The same logical mesh compiles different DCN collectives per slice
    count, so an elastic shrink (2 slices -> 1) must land in a different
    cache partition instead of replaying stale 2-slice executables."""
    mesh = make_mesh(MeshConfig(data=8))
    fp1 = topology_fingerprint(mesh)
    fp2 = topology_fingerprint(mesh, num_slices=2)
    assert fp2 == fp1 + "-s2"

    monkeypatch.setenv("M2KT_COMPILE_CACHE_DIR", str(tmp_path))
    monkeypatch.delenv("M2KT_COMPILE_CACHE", raising=False)
    path = setup_compilation_cache(mesh=mesh, num_slices=2)
    assert path == str(tmp_path / fp2)
    monkeypatch.delenv("M2KT_COMPILE_CACHE_DIR")
    setup_compilation_cache()
