"""Resilience subsystem (move2kube_tpu/resilience): kill-at-step-N →
resume-from-N under the supervisor, corrupt-checkpoint fallback, exit
classification, preemption watcher, goodput accounting, and the JobSet
failure-policy YAML. All CPU-only and deterministic."""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys

import jax
import jax.numpy as jnp
import optax
import pytest
from flax import linen as nn

from move2kube_tpu.models import checkpoint as m2kt_ckpt
from move2kube_tpu.models import train as m2kt_train
from move2kube_tpu.parallel.mesh import MeshConfig, make_mesh
from move2kube_tpu.resilience import faults, goodput, preemption, supervisor

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- the headline proof: kill at step N, supervisor restarts, resume ---------


@pytest.mark.slow  # heavy; runs unfiltered in make ci and the file's smoke target
def test_kill_at_step_resumes_from_checkpoint(tmp_path):
    """The full in-pod story in one subprocess: minitrain dies at step 5
    (injected, exactly-once), the supervisor classifies it retryable and
    restarts it, the second attempt resumes from the step-4 checkpoint —
    not step 0 — and the merged goodput report carries the lost span."""
    env = dict(
        os.environ,
        PYTHONPATH=REPO,
        JAX_PLATFORMS="cpu",
        M2KT_STEPS="8",
        M2KT_CKPT_DIR=str(tmp_path / "ckpt"),
        M2KT_CKPT_EVERY="2",
        M2KT_FAULT_STEP="5",
        M2KT_FAULT_KIND="exit",
        M2KT_FAULT_MARKER=str(tmp_path / "fault-fired"),
        M2KT_RETRY_MAX="2",
        M2KT_RETRY_BACKOFF_S="0.1",
        M2KT_EXIT_FILE=str(tmp_path / "exit.json"),
        M2KT_GOODPUT_FILE=str(tmp_path / "goodput.json"),
    )
    env.pop("M2KT_METRICS_DIR", None)
    res = subprocess.run(
        [sys.executable, "-m", "move2kube_tpu.resilience.supervisor", "--",
         sys.executable, "-m", "move2kube_tpu.resilience.minitrain"],
        env=env, cwd=str(tmp_path), capture_output=True, text=True,
        timeout=300)
    assert res.returncode == 0, res.stderr
    assert "FAULT: injected exit at step 5" in res.stdout
    assert "resumed from step 4" in res.stdout  # N, not 0
    assert "done steps=8" in res.stdout

    summary = json.loads((tmp_path / "exit.json").read_text())
    assert summary["exit_class"] == "ok"
    assert [a["class"] for a in summary["attempts"]] == ["retryable", "ok"]
    assert summary["attempts"][1]["report"]["resumed_from"] == 4
    merged = summary["goodput"]
    assert merged["last_saved_step"] == 8
    # attempt 1's death tail (post-flush work) is attributed to lost
    assert merged["seconds"]["lost"] > 0
    assert merged["seconds"]["retry"] > 0
    assert 0 < merged["goodput_fraction"] < 1


def test_retry_exhaustion_reports_last_rc(tmp_path):
    """Without a marker the fault fires every attempt; the supervisor must
    give up after M2KT_RETRY_MAX retries and surface the child's rc."""
    env = dict(
        os.environ,
        PYTHONPATH=REPO,
        JAX_PLATFORMS="cpu",
        M2KT_STEPS="4",
        M2KT_FAULT_STEP="2",
        M2KT_FAULT_KIND="exit",
        M2KT_FAULT_EXIT_CODE="7",
        M2KT_RETRY_MAX="1",
        M2KT_RETRY_BACKOFF_S="0.05",
        M2KT_EXIT_FILE=str(tmp_path / "exit.json"),
        M2KT_GOODPUT_FILE=str(tmp_path / "goodput.json"),
    )
    env.pop("M2KT_CKPT_DIR", None)
    env.pop("M2KT_FAULT_MARKER", None)
    env.pop("M2KT_METRICS_DIR", None)
    res = subprocess.run(
        [sys.executable, "-m", "move2kube_tpu.resilience.supervisor", "--",
         sys.executable, "-m", "move2kube_tpu.resilience.minitrain"],
        env=env, cwd=str(tmp_path), capture_output=True, text=True,
        timeout=300)
    assert res.returncode == 7
    summary = json.loads((tmp_path / "exit.json").read_text())
    assert summary["exit_class"] == "retries_exhausted"
    assert len(summary["attempts"]) == 2  # first try + one retry


# -- corrupt-checkpoint fallback ---------------------------------------------


@pytest.fixture(scope="module")
def tiny_state():
    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(4)(nn.relu(nn.Dense(8)(x)))

    mesh = make_mesh(MeshConfig(data=jax.device_count()))
    return m2kt_train.create_sharded_state(
        jax.random.PRNGKey(0), Tiny(), {"x": jnp.zeros((8, 8))},
        optax.sgd(1e-2), mesh)


def _save_steps(ckpt_dir, state, steps=(2, 4)):
    mngr = m2kt_ckpt.CheckpointManager(str(ckpt_dir), every=2)
    for s in steps:
        assert mngr.maybe_save(s, state)
    mngr.close()


@pytest.mark.parametrize("mode", ["truncate", "scribble", "remove"])
def test_corrupt_latest_falls_back_to_previous_step(tmp_path, tiny_state, mode):
    d = tmp_path / "ckpt"
    _save_steps(d, tiny_state)
    assert faults.corrupt_latest(str(d), mode=mode) == 4
    mngr = m2kt_ckpt.CheckpointManager(str(d), every=2)
    restored, start = mngr.restore_or_init(tiny_state)
    assert start == 2  # previous retained step, not a crash, not 0
    assert restored is not tiny_state
    mngr.close()


def test_all_corrupt_restarts_from_zero(tmp_path, tiny_state):
    """Every retained step unreadable → loud error + fresh start, never a
    crashloop that burns the JobSet's maxRestarts on a dead artifact."""
    d = tmp_path / "ckpt"
    _save_steps(d, tiny_state)
    faults.corrupt_latest(str(d))           # step 4
    for _step, sdir in faults.step_dirs(str(d)):
        for dirpath, _dirs, names in os.walk(sdir):
            if os.path.basename(dirpath) == "d":
                for n in names:
                    os.remove(os.path.join(dirpath, n))
    mngr = m2kt_ckpt.CheckpointManager(str(d), every=2)
    restored, start = mngr.restore_or_init(tiny_state)
    assert start == 0
    assert restored is tiny_state
    mngr.close()


def test_corrupt_latest_empty_dir_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        faults.corrupt_latest(str(tmp_path))


# -- fault injection ---------------------------------------------------------


def test_fault_marker_fires_exactly_once(tmp_path, monkeypatch):
    marker = tmp_path / "marker"
    monkeypatch.setenv("M2KT_FAULT_STEP", "3")
    monkeypatch.setenv("M2KT_FAULT_KIND", "raise")
    monkeypatch.setenv("M2KT_FAULT_MARKER", str(marker))
    faults.maybe_inject(2)  # off-step: no-op
    with pytest.raises(faults.FaultInjected):
        faults.maybe_inject(3)
    assert marker.exists()
    faults.maybe_inject(3)  # second hit: marker claims it, no fault


def test_fault_unconfigured_is_inert(monkeypatch):
    monkeypatch.delenv("M2KT_FAULT_STEP", raising=False)
    faults.maybe_inject(1)
    monkeypatch.setenv("M2KT_FAULT_STEP", "banana")
    faults.maybe_inject(1)  # malformed knob must not kill a real run


# -- exit classification -----------------------------------------------------


@pytest.mark.parametrize("rc,tail,expected", [
    (0, "", supervisor.OK),
    (-signal.SIGTERM, "", supervisor.PREEMPTED),
    (143, "", supervisor.PREEMPTED),
    (-signal.SIGKILL, "", supervisor.RETRYABLE),
    (137, "", supervisor.RETRYABLE),
    (1, "ImportError: No module named flax", supervisor.FATAL),
    (1, "ValueError: global batch 7 not divisible by 8", supervisor.FATAL),
    (1, "DEADLINE_EXCEEDED: barrier timed out", supervisor.RETRYABLE),
    (1, "FaultInjected: injected transient fault", supervisor.RETRYABLE),
    (1, "something unprecedented", supervisor.RETRYABLE),
])
def test_classification_table(rc, tail, expected):
    assert supervisor.classify(rc, tail) == expected


# -- preemption watcher ------------------------------------------------------


def test_watcher_sigterm_triggers_stop(tmp_path):
    w = preemption.PreemptionWatcher(
        grace_seconds=30.0, sentinel=str(tmp_path / "nope"))
    w.install()
    try:
        assert not w.requested()
        assert not w.should_stop(1)
        os.kill(os.getpid(), signal.SIGTERM)
        assert w.requested()
        assert w.should_stop(2)  # single-process: no cadence wait
        left = w.time_left()
        assert left is not None and 0 < left <= 30.0
    finally:
        w.uninstall()


def test_watcher_sentinel_file(tmp_path):
    sentinel = tmp_path / "m2kt-preempt"
    w = preemption.PreemptionWatcher(sentinel=str(sentinel))
    assert not w.requested()
    sentinel.touch()  # what the emitted preStop hook does
    assert w.requested()
    assert w.should_stop(7)


def test_watcher_from_env(monkeypatch, tmp_path):
    monkeypatch.setenv("M2KT_PREEMPT", "0")
    assert preemption.from_env() is None
    monkeypatch.setenv("M2KT_PREEMPT", "1")
    monkeypatch.setenv("M2KT_PREEMPT_GRACE_S", "77")
    monkeypatch.setenv("M2KT_PREEMPT_FILE", str(tmp_path / "s"))
    monkeypatch.setenv("M2KT_PREEMPT_SYNC_EVERY", "5")
    w = preemption.from_env()
    assert w is not None
    assert w.grace_seconds == 77.0
    assert w.sync_every == 5


def test_grace_period_derivation(monkeypatch):
    monkeypatch.delenv("M2KT_GRACE_PERIOD_S", raising=False)
    monkeypatch.delenv("M2KT_CKPT_BUDGET_S", raising=False)
    assert preemption.grace_period_seconds() == 300  # 240 budget + 60 margin
    monkeypatch.setenv("M2KT_CKPT_BUDGET_S", "100")
    assert preemption.grace_period_seconds() == 160
    monkeypatch.setenv("M2KT_GRACE_PERIOD_S", "42")  # explicit wins verbatim
    assert preemption.grace_period_seconds() == 42


# -- goodput accounting ------------------------------------------------------


def test_goodput_tracker_roundtrip(tmp_path):
    gp = goodput.GoodputTracker()
    gp.note_resume(4)  # restore happens before any stepping
    with gp.phase("restore"):
        pass
    gp.add("compile", 1.0, steps=1)
    gp.add("productive", 3.0, steps=6)
    gp.note_saved(10)
    gp.note_saved(8)  # monotonic max
    path = gp.write(str(tmp_path / "gp.json"))
    rep = goodput.read_report(path)
    assert rep["last_saved_step"] == 10
    assert rep["resumed_from"] == 4
    assert rep["steps_done"] == 4 + 6 + 1
    # accounted time (4s) >> wall here, so the denominator is accounted
    assert rep["goodput_fraction"] == pytest.approx(3.0 / 4.0, abs=0.01)
    assert goodput.read_report(str(tmp_path / "absent.json")) is None


def test_goodput_merge_charges_lost_to_failed_attempts():
    flushed = {"seconds": {"productive": 2.0, "compile": 1.0},
               "steps_done": 4, "last_saved_step": 4}
    attempts = [
        # died 5s in; flushed report only accounts for 3s → 2s lost
        {"report": flushed, "wall_seconds": 5.0, "ok": False},
        # clean finish: nothing lost
        {"report": {"seconds": {"productive": 3.0}, "steps_done": 8,
                    "last_saved_step": 8}, "wall_seconds": 4.0, "ok": True},
        # died before its first flush: the whole attempt is lost
        {"report": None, "wall_seconds": 1.0, "ok": False},
    ]
    merged = goodput.merge_attempts(attempts)
    assert merged["seconds"]["lost"] == pytest.approx(3.0)
    assert merged["seconds"]["productive"] == pytest.approx(5.0)
    assert merged["steps_done"] == 8
    assert merged["last_saved_step"] == 8
    assert merged["wall_seconds"] == pytest.approx(10.0)
    assert merged["goodput_fraction"] == pytest.approx(0.5)


def test_goodput_report_path_env(monkeypatch, tmp_path):
    monkeypatch.setenv("M2KT_GOODPUT_FILE", "/x/y.json")
    assert goodput.report_path() == "/x/y.json"
    monkeypatch.delenv("M2KT_GOODPUT_FILE")
    monkeypatch.setenv("M2KT_METRICS_DIR", str(tmp_path))
    assert goodput.report_path() == str(tmp_path / "m2kt-goodput.json")


# -- JobSet failure-policy emission ------------------------------------------


def _train_service(name="trainer", restart_policy=""):
    from move2kube_tpu.types.ir import Service
    from move2kube_tpu.types.plan import AcceleratorInfo

    svc = Service(name=name)
    svc.containers = [{"name": "t", "image": "x"}]
    svc.accelerator = AcceleratorInfo(
        gpu_count=8, tpu_accelerator="tpu-v5-lite-podslice",
        tpu_topology="2x4", num_hosts=2)
    svc.job = True
    if restart_policy:
        svc.restart_policy = restart_policy
    return svc


def test_jobset_carries_failure_policy_grace_and_prestop(monkeypatch):
    from move2kube_tpu.apiresource.deployment import DeploymentAPIResource

    for var in ("M2KT_MAX_RESTARTS", "M2KT_BACKOFF_LIMIT",
                "M2KT_GRACE_PERIOD_S", "M2KT_CKPT_BUDGET_S"):
        monkeypatch.delenv(var, raising=False)
    obj = DeploymentAPIResource()._create_workload(_train_service(), {"JobSet"})
    fp = obj["spec"]["failurePolicy"]
    assert fp["maxRestarts"] == 3
    [rule] = fp["rules"]
    assert rule["action"] == "RestartJobSetAndIgnoreMaxRestarts"
    assert rule["onJobFailureReasons"] == ["PodFailurePolicy"]

    job_spec = obj["spec"]["replicatedJobs"][0]["template"]["spec"]
    # preemption fails the job fast via the DisruptionTarget condition...
    [pod_rule] = job_spec["podFailurePolicy"]["rules"]
    assert pod_rule["action"] == "FailJob"
    assert pod_rule["onPodConditions"] == [
        {"type": "DisruptionTarget", "status": "True"}]

    pod = job_spec["template"]["spec"]
    assert pod["restartPolicy"] == "Never"  # podFailurePolicy requires it
    # grace sized to the checkpoint budget, same number the env mirrors
    assert pod["terminationGracePeriodSeconds"] == 300
    c = pod["containers"][0]
    prestop = c["lifecycle"]["preStop"]["exec"]["command"]
    assert preemption.DEFAULT_SENTINEL in " ".join(prestop)
    env = {e["name"]: e.get("value") for e in c["env"]}
    assert env["M2KT_PREEMPT_GRACE_S"] == "300"
    assert env["M2KT_PREEMPT_FILE"] == preemption.DEFAULT_SENTINEL


def test_jobset_honors_source_declared_onfailure(monkeypatch):
    from move2kube_tpu.apiresource.deployment import DeploymentAPIResource

    monkeypatch.delenv("M2KT_MAX_RESTARTS", raising=False)
    svc = _train_service(restart_policy="OnFailure")
    obj = DeploymentAPIResource()._create_workload(svc, {"JobSet"})
    job_spec = obj["spec"]["replicatedJobs"][0]["template"]["spec"]
    assert job_spec["template"]["spec"]["restartPolicy"] == "OnFailure"
    # podFailurePolicy is only legal with restartPolicy Never
    assert "podFailurePolicy" not in job_spec


def test_retry_budgets_env_overrides(monkeypatch):
    from move2kube_tpu.apiresource.deployment import DeploymentAPIResource

    monkeypatch.setenv("M2KT_MAX_RESTARTS", "7")
    obj = DeploymentAPIResource()._create_workload(_train_service(), {"JobSet"})
    assert obj["spec"]["failurePolicy"]["maxRestarts"] == 7

    # cluster without JobSet → indexed Job; backoffLimit knob drives it
    monkeypatch.setenv("M2KT_BACKOFF_LIMIT", "9")
    obj = DeploymentAPIResource()._create_workload(_train_service(), {"Job"})
    assert obj["kind"] == "Job"
    assert obj["spec"]["backoffLimit"] == 9
    pod = obj["spec"]["template"]["spec"]
    assert pod["terminationGracePeriodSeconds"] == 300  # TPU job: same hooks

    monkeypatch.setenv("M2KT_BACKOFF_LIMIT", "not-a-number")
    obj = DeploymentAPIResource()._create_workload(_train_service(), {"Job"})
    assert obj["spec"]["backoffLimit"] == 4  # bad override → builtin default


# -- compose restart-policy passthrough --------------------------------------


def test_source_restart_policy_from_compose(tmp_path):
    from move2kube_tpu.source.gpu2tpu import source_restart_policy

    (tmp_path / "docker-compose.yaml").write_text(
        "services:\n  train:\n    image: x\n    restart: on-failure:3\n")
    assert source_restart_policy(str(tmp_path)) == "OnFailure"

    (tmp_path / "docker-compose.yaml").write_text(
        'services:\n  train:\n    image: x\n    restart: "no"\n')
    assert source_restart_policy(str(tmp_path)) == "Never"

    # always has no Job equivalent → OnFailure (logged)
    (tmp_path / "docker-compose.yaml").write_text(
        "services:\n  train:\n    image: x\n    restart: always\n")
    assert source_restart_policy(str(tmp_path)) == "OnFailure"

    # several services disagree, none GPU → ambiguous, ignored
    (tmp_path / "docker-compose.yaml").write_text(
        "services:\n"
        "  a:\n    image: x\n    restart: always\n"
        '  b:\n    image: y\n    restart: "no"\n')
    assert source_restart_policy(str(tmp_path)) == ""

    # the GPU-reserving service's declaration wins
    (tmp_path / "docker-compose.yaml").write_text(
        "services:\n"
        '  web:\n    image: x\n    restart: "no"\n'
        "  train:\n"
        "    image: y\n    restart: on-failure\n"
        "    deploy:\n      resources:\n        reservations:\n"
        "          devices:\n            - capabilities: [gpu]\n")
    assert source_restart_policy(str(tmp_path)) == "OnFailure"


def test_source_restart_policy_absent_or_broken(tmp_path):
    from move2kube_tpu.source.gpu2tpu import source_restart_policy

    assert source_restart_policy(str(tmp_path)) == ""  # no compose file
    (tmp_path / "compose.yaml").write_text(": {{ not yaml")
    assert source_restart_policy(str(tmp_path)) == ""


# -- loader context-manager protocol -----------------------------------------


def test_every_loader_variant_is_a_context_manager(tmp_path):
    from move2kube_tpu.models import data as m2kt_data

    mesh = make_mesh(MeshConfig(data=jax.device_count()))
    with m2kt_data.make_loader(
            "", 8, mesh, synthetic_fn=lambda i: {"x": jnp.zeros((8, 2))}
    ) as loader:
        batch = next(iter(loader))
        assert batch["x"].shape == (8, 2)

    import numpy as np
    np.savez(tmp_path / "d.npz", x=np.zeros((32, 2), np.float32))
    with m2kt_data.make_loader(str(tmp_path / "d.npz"), 8, mesh) as loader:
        batch = next(iter(loader))
        assert batch["x"].shape == (8, 2)
    # the pump thread is down: iterating a closed prefetch loader raises
    with pytest.raises((StopIteration, RuntimeError)):
        for _ in range(10):
            next(loader)
