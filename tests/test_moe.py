"""MoE expert parallelism (models/moe.py): routing invariants and
expert-sharded vs single-device numerical equivalence."""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from move2kube_tpu.models.moe import MoEMlp, top_k_routing
from move2kube_tpu.parallel.mesh import MeshConfig, make_mesh


def test_routing_respects_capacity():
    t, e, cap = 16, 4, 3
    logits = jax.random.normal(jax.random.PRNGKey(0), (t, e))
    dispatch, combine, aux = top_k_routing(logits, e, 2, cap)
    # every expert queue holds at most `cap` tokens, one per slot
    per_slot = np.asarray(dispatch).sum(axis=0)  # [E, C]
    assert per_slot.max() <= 1.0 + 1e-6
    assert dispatch.shape == (t, e, cap)
    # combine weights of surviving tokens sum to <= 1 per token
    per_token = np.asarray(combine).sum(axis=(1, 2))
    assert (per_token <= 1.0 + 1e-5).all()
    assert np.isfinite(float(aux))


def test_routing_top1_routes_every_token_with_room():
    t, e = 8, 4
    logits = jnp.eye(t, e) * 5.0  # tokens spread over experts
    dispatch, _combine, _aux = top_k_routing(logits, e, 1, capacity=t)
    assert float(np.asarray(dispatch).sum()) == t  # nothing dropped


def test_moe_expert_sharded_matches_unsharded():
    from move2kube_tpu.models.train import _mesh_context

    model = MoEMlp(num_experts=4, mlp_dim=32, top_k=2,
                   capacity_factor=2.0, dtype=jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 16))
    params = model.init(jax.random.PRNGKey(2), x)["params"]
    ref, aux_ref = model.apply({"params": params}, x)

    mesh = make_mesh(MeshConfig(data=1, tensor=2, expert=4))
    p_sh = jax.device_put(
        params, jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec()))
    with _mesh_context(mesh):
        out, aux = jax.jit(lambda p, i: model.apply({"params": p}, i))(p_sh, x)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=2e-4)
    np.testing.assert_allclose(float(aux_ref), float(aux), atol=1e-5)


def test_moe_trains():
    """Gradients flow through routing + experts (dropped tokens included)."""
    model = MoEMlp(num_experts=4, mlp_dim=32, dtype=jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 8, 16))
    params = model.init(jax.random.PRNGKey(4), x)["params"]

    def loss_fn(p):
        y, aux = model.apply({"params": p}, x)
        return jnp.mean(y ** 2) + 0.01 * aux

    grads = jax.grad(loss_fn)(params)
    flat = jax.tree.leaves(grads)
    assert all(np.isfinite(np.asarray(g)).all() for g in flat)
    assert any(float(jnp.abs(g).sum()) > 0 for g in flat)


def test_llama_moe_trains_on_expert_mesh():
    """Full MoE Llama train step on a dp x tp x ep mesh: loss finite and
    decreasing, aux loss plumbed through the losses collection."""
    import dataclasses

    import optax

    from move2kube_tpu.models import llama
    from move2kube_tpu.models import train as m2kt_train

    cfg = dataclasses.replace(llama.llama_tiny(), moe_experts=4, moe_top_k=2,
                              dtype=jnp.float32)
    model = llama.Llama(cfg)
    mesh = make_mesh(MeshConfig(data=2, tensor=2, expert=2))
    ids = jnp.zeros((4, 16), jnp.int32)
    state = m2kt_train.create_sharded_state(
        jax.random.PRNGKey(0), model, {"input_ids": ids}, optax.adamw(1e-3), mesh,
    )
    step = m2kt_train.make_lm_train_step(mesh)
    batch = {"input_ids": jnp.asarray(
        np.random.default_rng(0).integers(0, 500, (4, 16)))}
    state, loss1 = step(state, batch)
    state, loss2 = step(state, batch)
    assert np.isfinite(float(loss1))
    assert float(loss2) < float(loss1)
