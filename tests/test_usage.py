"""Usage ledger + capture→replay + anomaly auto-profiling (PR 20):
ledger determinism under an injected clock, the JSONL exit-flush
round-trip, the /usage endpoint, chargeback's Σ TPU-seconds ≡ pods ×
wall identity, the capture schema round-trip into a replayable
simulator trace, the diag watchdog's hysteresis / rate limit / re-arm,
and the observability satellites (series-cap drop counter, bounded
/traces drain, fail-open Prometheus text parsing)."""

from __future__ import annotations

import json
import math
import threading
import urllib.request

import pytest

from move2kube_tpu.obs import ledger as ledger_mod
from move2kube_tpu.obs.bridge import DiagWatchdog
from move2kube_tpu.obs.ledger import (
    UsageLedger,
    engine_source,
    hist_doc,
    hist_from_doc,
    install_usage_flush,
    load_jsonl,
    router_source,
)
from move2kube_tpu.obs.metrics import (
    DROPPED_SERIES,
    OVERFLOW_LABEL,
    HistogramSnapshot,
    Registry,
)
from move2kube_tpu.obs.server import TelemetryServer, default_trace_limit
from move2kube_tpu.obs.slo import SLOTracker
from move2kube_tpu.obs.tracing import SpanRecorder
from move2kube_tpu.serving.fleet.autoscaler import (
    parse_counter_by_label,
    parse_counter_total,
)
from move2kube_tpu.serving.fleet.capture import (
    UNATTRIBUTED,
    build_capture,
    chargeback,
    fidelity,
    load_capture,
    pod_summary,
    write_capture,
)


class FakeClock:
    def __init__(self, t: float = 0.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += dt
        return self.t


def _get(url: str):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.status, resp.read().decode()


# ---------------------------------------------------------------------------
# histogram (de)serialization
# ---------------------------------------------------------------------------


def test_hist_doc_round_trip_preserves_inf_edge():
    snap = HistogramSnapshot((1.0, 8.0, math.inf), (2, 3, 1), 14.5, 6)
    doc = hist_doc(snap)
    assert doc["buckets"][-1] is None  # +Inf has no JSON literal
    back = hist_from_doc(json.loads(json.dumps(doc)))
    assert back.buckets == snap.buckets
    assert back.bucket_counts == snap.bucket_counts
    assert back.sum == snap.sum and back.count == snap.count
    assert back.buckets[-1] == math.inf


# ---------------------------------------------------------------------------
# ledger determinism + ring semantics
# ---------------------------------------------------------------------------


def _strip_wall(snaps: list[dict]) -> list[dict]:
    # t_unix is anchored to wall clock at construction; everything else
    # must be bit-identical under the same synthetic timeline
    return [{k: v for k, v in s.items() if k != "t_unix"} for s in snaps]


def test_ledger_deterministic_under_injected_clock():
    def source():
        return {"tenants": {"acme": {"admitted_tokens": 10.0}},
                "counters": {"steps": 3.0}}

    rings = []
    for _ in range(2):
        clk = FakeClock(100.0)
        led = UsageLedger(clock=clk, interval_s=10.0, role="decode",
                          host="pod-a")
        led.add_source(source, "s")
        for _ in range(4):
            led.snapshot()
            clk.advance(10.0)
        rings.append(_strip_wall(led.snapshots()))
    assert rings[0] == rings[1]
    assert [s["t_mono"] for s in rings[0]] == [100.0, 110.0, 120.0, 130.0]
    assert [s["seq"] for s in rings[0]] == [1, 2, 3, 4]


def test_maybe_snapshot_gates_on_interval_and_ring_is_bounded():
    clk = FakeClock()
    led = UsageLedger(clock=clk, interval_s=10.0, max_snapshots=3)
    assert led.maybe_snapshot() is not None  # first is always due
    clk.advance(5.0)
    assert led.maybe_snapshot() is None  # inside the interval
    clk.advance(5.0)
    assert led.maybe_snapshot() is not None
    for _ in range(5):
        clk.advance(10.0)
        led.snapshot()
    assert len(led) == 3  # deque(maxlen) keeps the newest
    assert [s["seq"] for s in led.snapshots()] == [5, 6, 7]


def test_ledger_source_error_degrades_not_dies():
    led = UsageLedger(clock=FakeClock(), interval_s=1.0)
    led.add_source(lambda: {"tenants": {"a": {"requests": 1.0}}}, "good")

    def bad():
        raise RuntimeError("backend gone")

    led.add_source(bad, "bad")
    snap = led.snapshot()
    assert snap["tenants"]["a"]["requests"] == 1.0
    assert any("bad" in e for e in snap["errors"])


def test_ledger_sources_deep_merge_tenants():
    led = UsageLedger(clock=FakeClock(), interval_s=1.0)
    led.add_source(lambda: {"tenants": {"a": {"admitted_tokens": 5.0}}})
    led.add_source(lambda: {"tenants": {"a": {"ttft": {"count": 1}},
                                        "b": {"admitted_tokens": 2.0}}})
    snap = led.snapshot()
    assert snap["tenants"]["a"] == {"admitted_tokens": 5.0,
                                    "ttft": {"count": 1}}
    assert snap["tenants"]["b"] == {"admitted_tokens": 2.0}


def test_flush_and_load_jsonl_round_trip(tmp_path):
    clk = FakeClock(50.0)
    led = UsageLedger(clock=clk, interval_s=10.0, role="router",
                      host="pod-r")
    led.add_source(lambda: {"counters": {"admitted_tokens_net": 9.0}})
    for _ in range(3):
        led.snapshot()
        clk.advance(10.0)
    path = tmp_path / "m2kt-usage.jsonl"
    assert led.flush(str(path)) == str(path)
    doc = load_jsonl(str(path))
    assert doc["schema"] == ledger_mod.SCHEMA
    assert doc["role"] == "router" and doc["host"] == "pod-r"
    assert _strip_wall(doc["snapshots"]) == _strip_wall(led.snapshots())
    # header is its own line: the file is greppable line-by-line JSON
    lines = path.read_text().strip().splitlines()
    assert len(lines) == 4
    assert all(json.loads(line) for line in lines)


def test_install_usage_flush_takes_final_snapshot(tmp_path, monkeypatch):
    monkeypatch.setattr(ledger_mod, "_flush_installed", False)
    captured = []
    monkeypatch.setattr(
        ledger_mod.threading, "_register_atexit",
        lambda fn: captured.append(fn), raising=False)
    led = UsageLedger(clock=FakeClock(), interval_s=1.0)
    path = tmp_path / "usage.jsonl"
    install_usage_flush(led, str(path))
    assert len(captured) == 1
    captured[0]()  # the exit path
    doc = load_jsonl(str(path))
    assert len(doc["snapshots"]) == 1  # the at-death snapshot


def test_usage_endpoint_serves_ledger_doc():
    led = UsageLedger(clock=FakeClock(), interval_s=1.0, role="decode")
    led.snapshot()
    srv = TelemetryServer(port=0, registry=Registry()).start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(f"{base}/usage")  # no ledger installed yet
        assert exc.value.code == 404
        srv.set_ledger(led)
        code, body = _get(f"{base}/usage")
        doc = json.loads(body)
        assert code == 200
        assert doc["schema"] == ledger_mod.SCHEMA
        assert len(doc["snapshots"]) == 1
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# sources over real metric families
# ---------------------------------------------------------------------------


class _StubEngine:
    def __init__(self, reg: Registry) -> None:
        self.weights_version = 7
        self._gauge_snapshot = {"slot_occupancy": 0.5, "queue_depth": 2.0}
        self._decode_tokens = reg.counter("e_decode", "d")
        self._decode_tokens.inc(40)
        self._tenant_admitted = reg.counter("e_adm", "a",
                                            labels=("tenant",))
        self._tenant_admitted.labels("acme").inc(3)
        self._tenant_prompt_tokens = reg.histogram(
            "e_pt", "p", labels=("tenant",), buckets=(16.0, 64.0))
        self._tenant_prompt_tokens.labels("acme").observe(20.0)
        self.slo = SLOTracker(registry=Registry(), clock=FakeClock(1.0))
        self.slo.record(tenant="acme", ok=True, ttft_s=0.01)


def test_engine_source_reads_families_and_slo():
    reg = Registry()
    out = engine_source(_StubEngine(reg))()
    assert out["weights_version"] == 7
    assert out["slot_occupancy"] == 0.5
    assert out["counters"]["decode_tokens"] == 40.0
    acme = out["tenants"]["acme"]
    assert acme["requests"] == 3.0
    assert acme["prompt_tokens"]["sum"] == 20.0
    assert acme["attainment"] == 1.0


def test_router_source_net_tokens():
    reg = Registry()

    class _StubRouter:
        _admitted_tokens = reg.counter("r_adm", "a", labels=("tenant",))
        _admitted_unused = reg.counter("r_un", "u", labels=("tenant",))

        def admitted_tokens(self) -> float:
            return 90.0

    _StubRouter._admitted_tokens.labels("acme").inc(100)
    _StubRouter._admitted_unused.labels("acme").inc(10)
    out = router_source(_StubRouter())()
    assert out["tenants"]["acme"] == {"admitted_tokens": 100.0,
                                      "unused_tokens": 10.0}
    assert out["counters"]["admitted_tokens_net"] == 90.0


# ---------------------------------------------------------------------------
# chargeback
# ---------------------------------------------------------------------------


def _pod_doc(role: str, wall_s: float, tenants_first: dict,
             tenants_last: dict, t0: float = 1000.0) -> dict:
    return {
        "schema": ledger_mod.SCHEMA, "role": role, "host": f"pod-{role}",
        "pid": 1,
        "snapshots": [
            {"seq": 1, "t_mono": t0, "t_unix": t0, "role": role,
             "tenants": tenants_first, "counters": {}},
            {"seq": 2, "t_mono": t0 + wall_s, "t_unix": t0 + wall_s,
             "role": role, "tenants": tenants_last, "counters": {}},
        ],
    }


def test_chargeback_tpu_seconds_sum_to_pod_walls():
    docs = [
        _pod_doc("router", 100.0,
                 {"acme": {"admitted_tokens": 0.0},
                  "globex": {"admitted_tokens": 0.0}},
                 {"acme": {"admitted_tokens": 750.0},
                  "globex": {"admitted_tokens": 250.0}}),
        # a pod with zero attributable tokens bills to "unattributed"
        _pod_doc("prefill", 50.0, {}, {}),
    ]
    report = chargeback(docs)
    total = sum(r["tpu_seconds"] for r in report["tenants"].values())
    assert total == pytest.approx(150.0, rel=1e-9)
    assert report["total_tpu_seconds"] == pytest.approx(
        report["total_wall_s"], rel=0.01)
    assert report["tenants"]["acme"]["tpu_seconds"] == pytest.approx(75.0)
    assert report["tenants"]["globex"]["tpu_seconds"] == pytest.approx(
        25.0)
    assert report["tenants"][UNATTRIBUTED]["tpu_seconds"] == (
        pytest.approx(50.0))


def test_chargeback_attainment_weighting_discounts_missed_slo():
    docs = [_pod_doc(
        "decode", 100.0,
        {"acme": {"admitted_tokens": 0.0, "attainment": 0.5}},
        {"acme": {"admitted_tokens": 100.0, "attainment": 0.5}})]
    report = chargeback(docs)
    acme = report["tenants"]["acme"]
    assert acme["tpu_seconds"] == pytest.approx(100.0)
    # missed-SLO seconds are the operator's cost, not the tenant's
    assert acme["tpu_seconds_weighted"] == pytest.approx(50.0)


def test_pod_summary_router_net_and_engine_hist_tokens():
    router = pod_summary(_pod_doc(
        "router", 10.0,
        {"a": {"admitted_tokens": 100.0, "unused_tokens": 0.0}},
        {"a": {"admitted_tokens": 300.0, "unused_tokens": 50.0}}))
    assert router["tenants"]["a"]["tokens"] == pytest.approx(150.0)
    engine = pod_summary(_pod_doc(
        "decode", 10.0,
        {"a": {"prompt_tokens": {"buckets": [None], "counts": [0],
                                 "sum": 0.0, "count": 0},
               "decode_tokens": {"buckets": [None], "counts": [0],
                                 "sum": 0.0, "count": 0}}},
        {"a": {"prompt_tokens": {"buckets": [None], "counts": [4],
                                 "sum": 64.0, "count": 4},
               "decode_tokens": {"buckets": [None], "counts": [4],
                                 "sum": 16.0, "count": 4}}}))
    assert engine["tenants"]["a"]["tokens"] == pytest.approx(80.0)
    assert engine["tenants"]["a"]["requests"] == pytest.approx(4.0)


# ---------------------------------------------------------------------------
# capture -> replay
# ---------------------------------------------------------------------------


def _ramp_docs(duration_s: float = 600.0, step_s: float = 60.0) -> list:
    """One router pod's ring: acme ramps 3x faster than globex."""
    snaps = []
    t0 = 5000.0
    n = int(duration_s / step_s) + 1
    for i in range(n):
        t = t0 + i * step_s
        snaps.append({
            "seq": i + 1, "t_mono": t, "t_unix": t, "role": "router",
            "tenants": {
                "acme": {"admitted_tokens": 900.0 * i,
                         "unused_tokens": 0.0, "requests": 15.0 * i},
                "globex": {"admitted_tokens": 300.0 * i,
                           "unused_tokens": 0.0, "requests": 5.0 * i},
            },
            "counters": {},
        })
    return [{"schema": ledger_mod.SCHEMA, "role": "router",
             "host": "pod-r", "pid": 1, "snapshots": snaps}]


def test_build_capture_schema_and_round_trip(tmp_path):
    docs = _ramp_docs()
    cap = build_capture(docs, bin_s=60.0)
    assert cap["schema"] == "m2kt-capture/v1"
    assert set(cap["tenants"]) == {"acme", "globex"}
    assert sum(cap["tenants"]["acme"]["tokens_per_bin"]) == (
        pytest.approx(9000.0))
    path = write_capture(cap, str(tmp_path))
    back = load_capture(path)
    assert back == json.loads(json.dumps(cap))
    with pytest.raises(ValueError, match="schema"):
        bad = dict(cap, schema="m2kt-capture/v999")
        load_capture(write_capture(bad, str(tmp_path / "bad")))


def test_captured_trace_replays_recorded_rate_and_shares():
    pytest.importorskip("numpy")
    from move2kube_tpu.serving.fleet.capture import CapturedTrace

    cap = build_capture(_ramp_docs(), bin_s=60.0)
    trace = CapturedTrace(cap, seed=3)
    fid = fidelity(cap, trace)
    # the bench gate is 10%; the per-tenant rescale makes totals exact
    assert fid["rate_err"] <= 0.10
    assert fid["max_share_err"] <= 0.10
    assert fid["replayed_tokens"] == pytest.approx(
        fid["recorded_tokens"], rel=1e-6)
    # duck-typed Trace surface the simulator needs
    assert trace.n == len(trace.arrival_s) == len(trace.tokens)
    assert trace.cfg.duration_s == pytest.approx(600.0)
    assert float(trace.rate_shape([0.0])[0]) >= 0.0


# ---------------------------------------------------------------------------
# diag watchdog
# ---------------------------------------------------------------------------


class _Firing:
    def __init__(self) -> None:
        self.firing = False

    def fast_burn_firing(self) -> bool:
        return self.firing


def _watchdog(tmp_path, clk, **kw):
    slo = _Firing()
    led = UsageLedger(clock=clk, interval_s=1.0)
    led.snapshot()
    kw.setdefault("min_interval_s", 600.0)
    kw.setdefault("profile_seconds", 0.0)  # no jax in unit tests
    wd = DiagWatchdog(registry=Registry(), slo=slo,
                      tracer=SpanRecorder(), ledger=led,
                      out_dir=str(tmp_path), clock=clk, **kw)
    return wd, slo


def test_watchdog_fires_once_per_level_episode(tmp_path):
    clk = FakeClock(0.0)
    wd, slo = _watchdog(tmp_path, clk)
    assert wd.check() is None  # quiet: nothing to do
    slo.firing = True
    bundle = wd.check()
    assert bundle is not None
    for _ in range(5):  # still firing: the hysteresis set holds it
        clk.advance(1.0)
        assert wd.check() is None
    assert len(wd.captures) == 1
    wd.wait()
    manifest = json.loads(
        (tmp_path / f"{bundle.rsplit('/', 1)[-1]}" / "manifest.json")
        .read_text())
    assert manifest["reason"] == "slo_fast_burn"
    assert sorted(manifest["parts"]) == ["traces.json", "usage.json"]
    usage = json.loads(
        (tmp_path / bundle.rsplit("/", 1)[-1] / "usage.json").read_text())
    assert usage["schema"] == ledger_mod.SCHEMA


def test_watchdog_rate_limit_then_rearm(tmp_path):
    clk = FakeClock(0.0)
    wd, slo = _watchdog(tmp_path, clk, min_interval_s=600.0)
    slo.firing = True
    assert wd.check() is not None
    # recover, then re-fire inside the interval: suppressed + counted
    slo.firing = False
    wd.check()
    clk.advance(10.0)
    slo.firing = True
    assert wd.check() is None
    assert sum(v for _lv, v in wd._c_suppressed.samples()) == 1
    # recover again; past the interval the next episode captures
    slo.firing = False
    wd.check()
    clk.advance(600.0)
    slo.firing = True
    assert wd.check() is not None
    assert len(wd.captures) == 2


def test_watchdog_max_captures_cap(tmp_path):
    clk = FakeClock(0.0)
    wd, slo = _watchdog(tmp_path, clk, min_interval_s=0.0,
                        max_captures=2)
    for _ in range(4):
        slo.firing = True
        wd.check()
        slo.firing = False
        wd.check()
        clk.advance(1.0)
    assert len(wd.captures) == 2  # a watchdog must not flood the disk


def test_watchdog_step_regression_trigger(tmp_path):
    clk = FakeClock(0.0)
    wd, _slo = _watchdog(tmp_path, clk, factor=2.0, short_window=8,
                         baseline_window=32, min_baseline=16)
    fired = []
    for _ in range(40):  # healthy baseline
        fired.append(wd.observe_step(0.1))
    assert not any(fired)
    for _ in range(8):  # 5x regression across the short window
        fired.append(wd.observe_step(0.5))
    assert any(fired)
    wd.wait()
    assert wd.captures and "step_regression" in wd.captures[0]


def test_watchdog_nonfinite_edge_trigger(tmp_path):
    clk = FakeClock(0.0)
    wd, _slo = _watchdog(tmp_path, clk)
    assert wd.note_nonfinite() is not None
    assert wd.note_nonfinite() is None  # rate-limited, not re-armed
    wd.wait()
    assert "nonfinite" in wd.captures[0]


# ---------------------------------------------------------------------------
# satellites: series cap counter, bounded /traces, fail-open parsing
# ---------------------------------------------------------------------------


def test_series_cap_trips_dropped_counter():
    reg = Registry()
    fam = reg.counter("m2kt_cap_total", "capped", labels=("tenant",),
                      max_series=2)
    fam.labels("a").inc()
    fam.labels("b").inc()
    fam.labels("c").inc()  # beyond the cap: folds into "other"
    fam.labels("d").inc()
    text = reg.render()
    assert f'tenant="{OVERFLOW_LABEL}"' in text
    dropped = {
        values: value
        for values, value in reg._families[DROPPED_SERIES].samples()}
    assert dropped[("m2kt_cap_total",)] == 2.0


def test_traces_drain_is_bounded_and_reports_drops(monkeypatch):
    monkeypatch.setenv("M2KT_TRACE_RING_SECONDS", "1")
    rec = SpanRecorder(ring_seconds=3600.0)
    for i in range(default_trace_limit() + 7):
        with rec.span(f"s{i}"):
            pass
    srv = TelemetryServer(port=0, registry=Registry(), tracer=rec).start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        _code, body = _get(f"{base}/traces")
        doc = json.loads(body)
        assert len(doc["spans"]) == default_trace_limit()
        assert doc["truncated"] == 7  # the drain says what it cut
        _code, body = _get(f"{base}/traces?limit=3")
        assert len(json.loads(body)["spans"]) == 3
    finally:
        srv.close()


def test_parse_counter_total_hardening():
    text = "\n".join((
        "# HELP m2kt_router_admitted_tokens_total tokens",
        "# TYPE m2kt_router_admitted_tokens_total counter",
        # a '}' inside a quoted label value must not truncate the parse
        'm2kt_router_admitted_tokens_total{tenant="a}b"} 5 1700000000',
        'm2kt_router_admitted_tokens_total{tenant="c"} 7',
        "m2kt_router_admitted_tokens_totally_not 99",  # name prefix trap
        'm2kt_router_admitted_tokens_total{tenant="d"} not-a-number',
    ))
    name = "m2kt_router_admitted_tokens_total"
    assert parse_counter_total(text, name) == pytest.approx(12.0)
    by = parse_counter_by_label(text, name, "tenant")
    assert by == {"a}b": 5.0, "c": 7.0}


def test_scrape_admitted_tokens_fails_open():
    from move2kube_tpu.serving.fleet.autoscaler import (
        scrape_admitted_tokens)

    assert scrape_admitted_tokens(
        "http://127.0.0.1:1/metrics", timeout_s=0.2) is None


# ---------------------------------------------------------------------------
# aggregator
# ---------------------------------------------------------------------------


def test_usage_aggregator_scrapes_and_publishes(tmp_path):
    from move2kube_tpu.serving.fleet.capture import UsageAggregator

    led = UsageLedger(clock=FakeClock(100.0), interval_s=1.0,
                      role="router")
    led.add_source(lambda: {"tenants": {
        "acme": {"admitted_tokens": 100.0 * len(led)}}})
    clk = led._clock  # noqa: SLF001 - drive the synthetic timeline
    for _ in range(3):
        led.snapshot()
        led._clock.advance(60.0)  # noqa: SLF001
    srv = TelemetryServer(port=0, registry=Registry(),
                          ledger=led).start()
    try:
        agg = UsageAggregator(
            [f"http://127.0.0.1:{srv.port}",
             "http://127.0.0.1:1"],  # a dead pod degrades, never crashes
            out_dir=str(tmp_path), interval_s=60.0, registry=Registry())
        report = agg.poll()
    finally:
        srv.close()
    assert report is not None
    assert "acme" in report["tenants"]
    assert (tmp_path / "m2kt-usage-report.json").exists()
    assert (tmp_path / "m2kt-usage-report.md").exists()
    cap = load_capture(str(tmp_path / "m2kt-capture.json"))
    assert "acme" in cap["tenants"]
    del clk
