"""Runtime tracing plane: span-ring semantics, Chrome/OTLP export
well-formedness, the serving engine's per-request TTFT decomposition,
straggler scoring, and the crash flight recorder exercised end-to-end by
the forced-host 2-slice slice-loss drill (``make trace-smoke``)."""

from __future__ import annotations

import dataclasses
import json
import os
import re
import subprocess
import sys
import time

import pytest

from move2kube_tpu.obs import tracing
from move2kube_tpu.obs.bridge import StragglerDetector
from move2kube_tpu.obs.metrics import Registry
from move2kube_tpu.obs.tracing import SpanRecorder

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ----------------------------------------------------------------------
# recorder semantics
# ----------------------------------------------------------------------

def test_span_ids_and_context_nesting():
    rec = SpanRecorder()
    with rec.span("outer") as outer:
        assert re.fullmatch(r"[0-9a-f]{32}", outer.trace_id)
        assert re.fullmatch(r"[0-9a-f]{16}", outer.span_id)
        assert outer.parent_id == ""
        assert rec.current() is outer
        with rec.span("inner") as inner:
            # nested spans inherit identity through the contextvar
            assert inner.trace_id == outer.trace_id
            assert inner.parent_id == outer.span_id
            assert rec.current() is inner
        assert rec.current() is outer
    assert rec.current() is None
    snap = rec.snapshot()
    assert [s["name"] for s in snap] == ["inner", "outer"]  # end order
    assert all(not s["in_flight"] and s["dur_s"] >= 0 for s in snap)


def test_detached_spans_do_not_chain():
    """The serving engine interleaves many live request traces in one
    thread: detached roots must neither inherit nor become the current
    context span."""
    rec = SpanRecorder()
    with rec.span("step"):
        a = rec.start("req-a", detached=True)
        b = rec.start("req-b", detached=True)
        assert a.parent_id == "" and b.parent_id == ""
        assert a.trace_id != b.trace_id
        assert rec.current().name == "step"
    rec.end(a)
    rec.end(b)


def test_ring_bounded_by_max_spans():
    rec = SpanRecorder(ring_seconds=3600.0, max_spans=16)
    now = time.perf_counter()
    for i in range(100):
        rec.record(f"s{i}", now, now)
    snap = rec.snapshot()
    assert len(snap) == 16
    assert rec.dropped == 84
    assert snap[0]["name"] == "s84"  # oldest survivors evicted first


def test_ring_evicts_by_time_horizon():
    rec = SpanRecorder(ring_seconds=0.5)
    now = time.perf_counter()
    rec.record("old", now - 10.0, now - 9.0)  # ended far past the window
    rec.record("fresh", now - 0.01, now)
    names = [s["name"] for s in rec.snapshot()]
    assert names == ["fresh"]
    assert rec.dropped == 1


def test_in_flight_spans_appear_in_snapshot():
    rec = SpanRecorder()
    s = rec.start("hung")
    snap = rec.snapshot()
    assert snap[-1]["name"] == "hung"
    assert snap[-1]["in_flight"]
    assert snap[-1]["dur_s"] >= 0
    rec.end(s)


def test_record_preserves_exact_endpoints():
    rec = SpanRecorder()
    t0 = time.perf_counter()
    t1 = t0 + 0.125
    span = rec.record("exact", t0, t1, attrs={"step": 3})
    assert span.t0 == t0 and span.t1 == t1
    [snap] = rec.snapshot()
    assert snap["dur_s"] == pytest.approx(0.125, abs=1e-9)
    assert snap["attrs"] == {"step": 3}


def test_env_knobs(monkeypatch, tmp_path):
    monkeypatch.setenv("M2KT_TRACE", "0")
    assert not tracing.enabled()
    monkeypatch.setenv("M2KT_TRACE", "off")
    assert not tracing.enabled()
    monkeypatch.delenv("M2KT_TRACE", raising=False)
    assert tracing.enabled()  # default ON
    monkeypatch.setenv("M2KT_TRACE_RING_SECONDS", "7.5")
    assert tracing.ring_seconds() == 7.5
    monkeypatch.setenv("M2KT_TRACE_RING_SECONDS", "garbage")
    assert tracing.ring_seconds() == tracing.DEFAULT_RING_SECONDS
    monkeypatch.setenv("M2KT_FLIGHT_PATH", str(tmp_path / "f.json"))
    assert tracing.flight_path() == str(tmp_path / "f.json")
    assert tracing.ring_path() == str(tmp_path / "f.json") + ".ring"
    monkeypatch.delenv("M2KT_FLIGHT_PATH", raising=False)
    monkeypatch.setenv("M2KT_METRICS_DIR", str(tmp_path))
    assert tracing.flight_path() == str(tmp_path / "m2kt-flight.json")


# ----------------------------------------------------------------------
# exports
# ----------------------------------------------------------------------

def _populated_recorder() -> SpanRecorder:
    rec = SpanRecorder(slice_id=1)
    time.sleep(0.002)  # spans start measurably after the clock anchor
    with rec.span("train.step", attrs={"step": 1, "loss": 2.5}):
        with rec.span("ckpt.save_submit", attrs={"async": True}):
            pass
    return rec


def test_chrome_trace_well_formed():
    rec = _populated_recorder()
    doc = json.loads(json.dumps(rec.chrome_trace()))  # JSON round-trip
    events = doc["traceEvents"]
    assert len(events) == 2
    for ev in events:
        assert ev["ph"] == "X"
        assert isinstance(ev["ts"], (int, float)) and ev["ts"] >= 0
        assert isinstance(ev["dur"], (int, float)) and ev["dur"] >= 0
        assert ev["tid"] == 1  # slice id
        assert ev["cat"] == "m2kt"
        assert re.fullmatch(r"[0-9a-f]{32}", ev["args"]["trace_id"])
    assert doc["displayTimeUnit"] == "ms"
    assert doc["otherData"]["slice_id"] == 1
    # parent/child linkage survives the export
    by_name = {e["name"]: e for e in events}
    assert (by_name["ckpt.save_submit"]["args"]["parent_id"]
            == by_name["train.step"]["args"]["span_id"])


def test_otlp_lines_parse_and_carry_resource():
    rec = _populated_recorder()
    lines = rec.otlp_lines()
    assert len(lines) == 2
    for line in lines:
        doc = json.loads(line)
        [rs] = doc["resourceSpans"]
        keys = {a["key"] for a in rs["resource"]["attributes"]}
        assert {"host.name", "m2kt.slice_id", "service.name"} <= keys
        [span] = rs["scopeSpans"][0]["spans"]
        assert int(span["endTimeUnixNano"]) >= int(span["startTimeUnixNano"])
    # typed attributes: int step, double loss, bool async
    merged = "\n".join(lines)
    assert '"intValue":"1"' in merged
    assert '"doubleValue":2.5' in merged
    assert '"boolValue":true' in merged


def test_flush_ring_atomic_dump(tmp_path):
    rec = _populated_recorder()
    path = str(tmp_path / "sub" / "ring.json")
    assert rec.flush_ring(path) == path
    doc = json.loads(open(path, encoding="utf-8").read())
    assert doc["slice_id"] == 1
    assert doc["pid"] == os.getpid()
    assert doc["ring_seconds"] == rec.ring_seconds
    assert [s["name"] for s in doc["spans"]] == ["ckpt.save_submit",
                                                 "train.step"]


# ----------------------------------------------------------------------
# serving: per-request trace decomposes the TTFT histogram sample
# ----------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny_llama_parts():
    import jax
    import jax.numpy as jnp

    from move2kube_tpu.models.llama import Llama, llama_tiny

    cfg = dataclasses.replace(llama_tiny(), dtype=jnp.float32,
                              attn_impl="dense")
    model = Llama(cfg)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 8), jnp.int32))
    return model, variables


def test_engine_request_trace_decomposes_ttft(tiny_llama_parts):
    """One trace per request: queue_wait + prefill spans must sum to the
    exact TTFT the engine's histogram observed (same clock readings close
    both), and decode steps/complete hang off the same trace id."""
    from move2kube_tpu.serving.engine import (
        EngineConfig, Request, ServingEngine)

    model, variables = tiny_llama_parts
    tracer = SpanRecorder()
    eng = ServingEngine(
        model, variables,
        EngineConfig(max_batch=2, max_seq=64, block_size=8, buckets=(8,)),
        registry=Registry(), tracer=tracer)
    comps = eng.run([Request("r0", [5, 9, 12], 3)])
    assert len(comps) == 1 and len(comps[0].tokens) == 3

    by_name = {}
    for s in tracer.snapshot():
        by_name.setdefault(s["name"], []).append(s)
    [root] = by_name["serve.request"]
    [queue] = by_name["serve.queue_wait"]
    [prefill] = by_name["serve.prefill"]
    decodes = by_name["serve.decode_step"]
    # single trace: every span carries the request's trace id, children
    # point at the root
    for s in [queue, prefill] + decodes:
        assert s["trace_id"] == root["trace_id"]
        assert s["parent_id"] == root["span_id"]
    assert prefill["attrs"]["bucket"] == 8
    assert root["attrs"]["finish_reason"] == "length"
    assert root["attrs"]["tokens"] == 3
    # prefill emits the first token; each decode step appends one
    assert len(decodes) == 2

    # the acceptance bound is 1ms; construction makes it exact, so assert
    # far tighter than the criterion
    ttft_hist = eng._ttft_hist
    assert ttft_hist.count == 1
    decomposed = queue["dur_s"] + prefill["dur_s"]
    assert decomposed == pytest.approx(ttft_hist.sum, abs=1e-6)
    assert root["attrs"]["ttft_s"] == pytest.approx(ttft_hist.sum, abs=1e-9)


def test_engine_without_tracer_records_nothing(tiny_llama_parts,
                                               monkeypatch):
    from move2kube_tpu.serving.engine import (
        EngineConfig, Request, ServingEngine)

    monkeypatch.setenv("M2KT_TRACE", "0")
    model, variables = tiny_llama_parts
    eng = ServingEngine(
        model, variables,
        EngineConfig(max_batch=2, max_seq=64, block_size=8, buckets=(8,)),
        registry=Registry())
    assert eng.tracer is None
    comps = eng.run([Request("r0", [5, 9], 2)])
    assert len(comps) == 1  # tracing off is purely observational


# ----------------------------------------------------------------------
# straggler detection
# ----------------------------------------------------------------------

def test_straggler_scores_and_hysteresis():
    reg = Registry()
    det = StragglerDetector(registry=reg, threshold=1.5, window=8)
    # 3 healthy hosts + one 2x straggler
    for step in range(8):
        for h in ("h0", "h1", "h2"):
            det.report(h, step, 0.10)
        det.report("h3", step, 0.20)
    scores = det.scores()
    assert scores["h0"] == pytest.approx(1.0)
    assert scores["h3"] == pytest.approx(2.0)
    # one event per excursion, not one per step
    assert det.events == 1
    # recovery re-arms: dilute the window back under the threshold...
    for step in range(8, 16):
        for h in ("h0", "h1", "h2", "h3"):
            det.report(h, step, 0.10)
    assert det.scores()["h3"] == pytest.approx(1.0)
    # ...then a second excursion fires a second event
    for step in range(16, 24):
        for h in ("h0", "h1", "h2"):
            det.report(h, step, 0.10)
        det.report("h3", step, 0.30)
    assert det.events == 2
    # scores surface in the exposition for the PrometheusRule to alert on
    text = reg.render()
    assert 'm2kt_straggler_score{host="h3"}' in text
    assert 'm2kt_straggler_events_total{host="h3"} 2' in text


def test_straggler_single_host_is_baseline():
    det = StragglerDetector(registry=Registry())
    for step in range(4):
        det.report("only", step, 0.5)
    assert det.scores()["only"] == pytest.approx(1.0)
    assert det.events == 0


# ----------------------------------------------------------------------
# the drill: slice loss must leave a flight recording
# ----------------------------------------------------------------------

def _run_supervised(workdir, extra: dict) -> subprocess.CompletedProcess:
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu", **extra)
    for leak in ("M2KT_METRICS_DIR", "M2KT_FAULT_STEP", "M2KT_FAULT_KIND",
                 "M2KT_FAULT_MARKER", "M2KT_ELASTIC", "M2KT_NUM_SLICES",
                 "M2KT_FORCE_DEVICES", "M2KT_BATCH_PER_DEVICE",
                 "M2KT_TRACE", "M2KT_FLIGHT_PATH",
                 "M2KT_TRACE_RING_SECONDS"):
        if leak not in extra:
            env.pop(leak, None)
    return subprocess.run(
        [sys.executable, "-m", "move2kube_tpu.resilience.supervisor", "--",
         sys.executable, "-m", "move2kube_tpu.resilience.minitrain"],
        env=env, cwd=str(workdir), capture_output=True, text=True,
        timeout=600)


def test_slice_loss_drill_writes_flight_recording(tmp_path):
    """The 2-slice forced-host drill with ``slice_loss`` injected at step
    4: the dying child flushes its span ring on the exit-83 teardown
    path, the supervisor folds it into ``m2kt-flight.json`` with the
    slice-lost classification and the goodput ledger — and the elastic
    re-plan still finishes the run (the flight records the dead attempt,
    not the pod's final state)."""
    flight = tmp_path / "m2kt-flight.json"
    res = _run_supervised(tmp_path, dict(
        M2KT_STEPS="6",
        M2KT_CKPT_EVERY="2",
        M2KT_RETRY_BACKOFF_S="0.1",
        M2KT_CKPT_DIR=str(tmp_path / "ckpt"),
        M2KT_FORCE_DEVICES="8",
        M2KT_NUM_SLICES="2",
        M2KT_BATCH_PER_DEVICE="2",
        M2KT_ELASTIC="1",
        M2KT_FAULT_STEP="4",
        M2KT_FAULT_KIND="slice_loss",
        M2KT_FAULT_MARKER=str(tmp_path / "fault-fired"),
        M2KT_EXIT_FILE=str(tmp_path / "exit.json"),
        M2KT_GOODPUT_FILE=str(tmp_path / "goodput.json"),
        M2KT_FLIGHT_PATH=str(flight),
    ))
    assert res.returncode == 0, res.stderr
    assert "done steps=6" in res.stdout
    # straggler scoring ran on the per-step reports
    assert "straggler: hosts=" in res.stdout

    doc = json.loads(flight.read_text())
    assert doc["exit_class"] == "slice_lost"
    assert doc["returncode"] == 83
    assert doc["attempt"] == 1
    # the dead attempt's ledger rode along
    assert doc["goodput"].get("steps_done", 0) >= 1
    # the child's ring was flushed on the sys.exit(83) teardown path and
    # carries the spans of the final completed step before the loss
    assert doc["ring"]["pid"]
    steps = [s["attrs"].get("step") for s in doc["spans"]
             if s["name"] == "train.step"]
    assert steps, doc["spans"]
    assert max(steps) == 3  # fault fires at step 4, before its step runs
    # every span in the flight is export-grade: ids + timing present
    for s in doc["spans"]:
        assert re.fullmatch(r"[0-9a-f]{32}", s["trace_id"])
        assert s["dur_s"] >= 0 and s["ts_unix"] > 0
    # the .ring file next to the flight is the *latest* flush — the
    # surviving attempt overwrote the dead one's at its own clean exit —
    # but it must always be a well-formed dump
    ring = json.loads((tmp_path / "m2kt-flight.json.ring").read_text())
    assert isinstance(ring["spans"], list) and ring["spans"]
    assert ring["pid"] and ring["ring_seconds"] > 0


def test_trace_disabled_drill_writes_flight_without_spans(tmp_path):
    """M2KT_TRACE=0: no ring, but the flight recorder still captures the
    classification + ledger (observability off must not cost the
    postmortem everything)."""
    flight = tmp_path / "m2kt-flight.json"
    res = _run_supervised(tmp_path, dict(
        M2KT_STEPS="4",
        M2KT_CKPT_EVERY="2",
        M2KT_RETRY_BACKOFF_S="0.1",
        M2KT_CKPT_DIR=str(tmp_path / "ckpt"),
        M2KT_FORCE_DEVICES="4",
        M2KT_FAULT_STEP="3",
        M2KT_FAULT_KIND="raise",
        M2KT_FAULT_MARKER=str(tmp_path / "fault-fired"),
        M2KT_EXIT_FILE=str(tmp_path / "exit.json"),
        M2KT_GOODPUT_FILE=str(tmp_path / "goodput.json"),
        M2KT_FLIGHT_PATH=str(flight),
        M2KT_TRACE="0",
    ))
    assert res.returncode == 0, res.stderr  # crash is retryable
    doc = json.loads(flight.read_text())
    assert doc["exit_class"] == "retryable"
    assert doc["spans"] == []
    assert doc["ring"] == {}
    assert doc["goodput"].get("steps_done", 0) >= 1
