"""Cluster collector against recorded kubectl fixtures — the layer the
reference leaves untested (clustercollector.go has no tests; SURVEY §4).
Covers the discovery-API path (kubectl get --raw), the CLI fallback, and
the full collect() -> ClusterMetadata yaml round trip."""

import json

import yaml

from move2kube_tpu.collector.cluster import ClusterCollector
from move2kube_tpu.types import collection as collecttypes

APIS = {
    "groups": [
        {
            "name": "apps",
            "preferredVersion": {"groupVersion": "apps/v1"},
            "versions": [
                {"groupVersion": "apps/v1"},
                {"groupVersion": "apps/v1beta2"},
                {"groupVersion": "apps/v1beta1"},
            ],
        },
        {
            "name": "networking.k8s.io",
            "preferredVersion": {"groupVersion": "networking.k8s.io/v1"},
            "versions": [
                {"groupVersion": "networking.k8s.io/v1"},
                {"groupVersion": "networking.k8s.io/v1beta1"},
            ],
        },
        {
            "name": "extensions",
            "preferredVersion": {"groupVersion": "extensions/v1beta1"},
            "versions": [{"groupVersion": "extensions/v1beta1"}],
        },
        {
            "name": "jobset.x-k8s.io",
            "preferredVersion": {"groupVersion": "jobset.x-k8s.io/v1alpha2"},
            "versions": [{"groupVersion": "jobset.x-k8s.io/v1alpha2"}],
        },
    ]
}

RESOURCES = {
    "/api/v1": ["Pod", "Service", "ConfigMap", "Secret",
                "PersistentVolumeClaim", "ReplicationController"],
    "/apis/apps/v1": ["Deployment", "DaemonSet", "StatefulSet", "ReplicaSet"],
    "/apis/apps/v1beta2": ["Deployment", "DaemonSet"],
    "/apis/apps/v1beta1": ["Deployment"],
    "/apis/networking.k8s.io/v1": ["Ingress", "NetworkPolicy"],
    "/apis/networking.k8s.io/v1beta1": ["Ingress"],
    "/apis/extensions/v1beta1": ["Ingress", "Deployment"],
    "/apis/jobset.x-k8s.io/v1alpha2": ["JobSet"],
}


def fake_discovery_runner(*args):
    if args[:2] == ("get", "--raw"):
        path = args[2]
        if path == "/apis":
            return json.dumps(APIS)
        if path == "/api":
            return json.dumps({"versions": ["v1"]})
        if path in RESOURCES:
            return json.dumps({"resources": [
                {"name": k.lower() + "s", "kind": k} for k in RESOURCES[path]
            ] + [{"name": "deployments/scale", "kind": "Scale"}]})
        return None
    if args == ("get", "storageclass", "-o", "name"):
        return "storageclass.storage.k8s.io/standard\nstorageclass.storage.k8s.io/premium-rwo\n"
    if args[0] == "get" and args[1] == "nodes":
        return "tpu-v5-lite-podslice\n\ntpu-v5-lite-podslice\n"
    if args == ("config", "current-context"):
        return "gke_proj_us-central1_tpu-cluster\n"
    return None


def test_discovery_api_full_version_lists():
    c = ClusterCollector(runner=fake_discovery_runner)
    kind_map = c.collect_using_api()
    # full per-kind version lists, not just the preferred one
    assert kind_map["Deployment"] == [
        "apps/v1", "apps/v1beta2", "apps/v1beta1", "extensions/v1beta1"]
    assert kind_map["Ingress"] == [
        "networking.k8s.io/v1", "networking.k8s.io/v1beta1",
        "extensions/v1beta1"]
    assert kind_map["JobSet"] == ["jobset.x-k8s.io/v1alpha2"]
    assert kind_map["Pod"] == ["v1"]
    assert "Scale" not in kind_map  # subresources skipped


def test_discovery_preferred_version_first():
    # flip the preferred version: the server prefers apps/v1beta2
    apis = json.loads(json.dumps(APIS))
    apis["groups"][0]["preferredVersion"] = {"groupVersion": "apps/v1beta2"}

    def runner(*args):
        if args[:2] == ("get", "--raw") and args[2] == "/apis":
            return json.dumps(apis)
        return fake_discovery_runner(*args)

    kind_map = ClusterCollector(runner=runner).collect_using_api()
    assert kind_map["Deployment"][0] == "apps/v1beta2"


def test_cli_fallback_backfills_group_versions():
    def runner(*args):
        if args[:2] == ("get", "--raw"):
            return None  # discovery blocked (RBAC)
        if args == ("api-resources", "--no-headers"):
            return (
                "deployments  deploy  apps/v1  true  Deployment\n"
                "ingresses  ing  networking.k8s.io/v1  true  Ingress\n"
                "pods  po  v1  true  Pod\n"
                "malformed line without namespaced\n"
            )
        if args == ("api-versions",):
            return "apps/v1\napps/v1beta1\nnetworking.k8s.io/v1\nnetworking.k8s.io/v1beta1\nv1\n"
        return None

    c = ClusterCollector(runner=runner)
    assert c.collect_using_api() is None
    kind_map = c.collect_using_cli()
    # preferred (from api-resources) first, rest of the group backfilled
    assert kind_map["Deployment"] == ["apps/v1", "apps/v1beta1"]
    assert kind_map["Ingress"] == ["networking.k8s.io/v1",
                                   "networking.k8s.io/v1beta1"]
    assert kind_map["Pod"] == ["v1"]


def test_cli_fallback_no_shortnames_column():
    def runner(*args):
        if args[:2] == ("get", "--raw"):
            return None
        if args == ("api-resources", "--no-headers"):
            # some kinds print no SHORTNAMES column
            return "bindings   v1  true  Binding\n"
        return None

    kind_map = ClusterCollector(runner=runner).collect_using_cli()
    assert kind_map == {"Binding": ["v1"]}


def test_collect_writes_cluster_metadata(tmp_path):
    ClusterCollector(runner=fake_discovery_runner).collect(
        str(tmp_path), str(tmp_path / "m2kt_collect"))
    out = tmp_path / "m2kt_collect" / "clusters"
    files = list(out.glob("*.yaml"))
    assert len(files) == 1
    doc = yaml.safe_load(files[0].read_text())
    cm = collecttypes.ClusterMetadata.from_dict(doc)
    assert cm.spec.supports_kind("JobSet")
    assert cm.spec.supports_tpu()
    assert cm.spec.tpu_accelerators == ["tpu-v5-lite-podslice"]
    assert cm.spec.storage_classes == ["standard", "premium-rwo"]
    assert cm.spec.get_supported_versions("Deployment")[0] == "apps/v1"


def test_collect_skips_when_kubectl_unavailable(tmp_path):
    ClusterCollector(runner=lambda *a: None).collect(
        str(tmp_path), str(tmp_path / "m2kt_collect"))
    assert not (tmp_path / "m2kt_collect").exists()
