"""Serving hot-path tests: paged KV cache, decode kernel, engine.

The load-bearing property is *logit equivalence*: prefill + N incremental
paged-decode steps must reproduce the full-sequence forward's logits at
every generated position (<= 1e-5 in fp32) for both decoder families,
including steps that cross a page boundary. Everything else — allocator
bookkeeping, compile-count bounds, donation — guards the performance
contract around that correctness core.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from move2kube_tpu.models.gpt2 import GPT2, gpt2_tiny
from move2kube_tpu.models.llama import Llama, llama_tiny
from move2kube_tpu.ops.attention import (
    _paged_decode_reference,
    _paged_decode_tpu,
)
from move2kube_tpu.serving.engine import EngineConfig, Request, ServingEngine
from move2kube_tpu.serving.kvcache import (
    NULL_PAGE,
    PageAllocator,
    init_cache,
    pages_for,
    scatter_prefill,
    spec_for_model,
)


# ----------------------------------------------------------------------
# allocator + geometry
# ----------------------------------------------------------------------

def test_pages_for():
    assert pages_for(1, 8) == 1
    assert pages_for(8, 8) == 1
    assert pages_for(9, 8) == 2
    assert pages_for(64, 16) == 4


def test_spec_for_model_defaults():
    spec = spec_for_model(llama_tiny(), block_size=8, max_batch=4,
                          max_seq=64)
    assert spec.num_kv_heads == 2          # GQA: fewer KV heads than Q
    assert spec.head_dim == 128 // 4
    assert spec.max_pages_per_seq == 8
    assert spec.num_pages == 1 + 4 * 8     # +1: reserved null page
    assert spec.max_seq == 64
    # MHA model has no num_kv_heads attribute -> falls back to num_heads
    spec = spec_for_model(gpt2_tiny(), block_size=8, max_batch=2)
    assert spec.num_kv_heads == gpt2_tiny().num_heads
    assert spec.max_seq == gpt2_tiny().n_positions


def test_page_allocator():
    alloc = PageAllocator(9)
    assert alloc.available == 8
    a = alloc.alloc(3)
    assert a is not None and len(a) == 3 and NULL_PAGE not in a
    b = alloc.alloc(5)
    assert b is not None and not (set(a) & set(b))
    # all-or-nothing: pool is empty now
    assert alloc.alloc(1) is None
    alloc.free(a)
    assert alloc.available == 3
    # partial requests never succeed partially
    assert alloc.alloc(4) is None
    assert alloc.available == 3
    with pytest.raises(ValueError):
        alloc.free(a)          # double free
    with pytest.raises(ValueError):
        alloc.free([NULL_PAGE])  # page 0 never circulates


# ----------------------------------------------------------------------
# paged decode kernel (interpret mode) vs reference
# ----------------------------------------------------------------------

def test_paged_decode_kernel_matches_reference():
    """Pallas kernel in interpret mode vs the jnp reference, GQA shapes
    (4 query heads over 2 KV heads) with TPU-friendly head_dim=128."""
    b, h, kvh, d, bs, mpps, npages = 3, 4, 2, 128, 8, 4, 13
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((b, h, d)), jnp.float32)
    k_pages = jnp.asarray(rng.standard_normal((npages, bs, kvh, d)),
                          jnp.float32)
    v_pages = jnp.asarray(rng.standard_normal((npages, bs, kvh, d)),
                          jnp.float32)
    tables = np.zeros((b, mpps), np.int32)
    seq_lens = np.array([5, 8 + 3, 4 * 8], np.int32)  # partial/cross/full
    pool = list(range(1, npages))
    for i in range(b):
        n = pages_for(int(seq_lens[i]), bs)
        tables[i, :n] = [pool.pop() for _ in range(n)]
    tables = jnp.asarray(tables)
    seq_lens = jnp.asarray(seq_lens)
    ref = _paged_decode_reference(q, k_pages, v_pages, tables, seq_lens,
                                  scale=d ** -0.5)
    out = _paged_decode_tpu(q, k_pages, v_pages, tables, seq_lens,
                            scale=d ** -0.5, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


# ----------------------------------------------------------------------
# prefill + incremental decode == full forward (both model families)
# ----------------------------------------------------------------------

def _fp32_model(family):
    if family == "llama":
        cfg = dataclasses.replace(llama_tiny(), dtype=jnp.float32,
                                  attn_impl="dense")
        return Llama(cfg)
    cfg = dataclasses.replace(gpt2_tiny(), dtype=jnp.float32)
    return GPT2(cfg)


def _incremental_decode_logits(model, variables, prompt, n_steps,
                               block_size=8):
    """Prefill the prompt into a paged cache, then decode ``n_steps``
    single tokens (greedy continuation from the full forward, so both
    paths see identical inputs). Returns [n_steps, vocab] paged logits
    and the token ids used."""
    spec = spec_for_model(model.cfg, block_size=block_size, max_batch=2,
                          max_seq=64)
    cache = init_cache(spec)
    alloc = PageAllocator(spec.num_pages)
    plen = len(prompt)
    pages = alloc.alloc(pages_for(plen + n_steps, block_size))
    bt_row = np.full((spec.max_pages_per_seq,), NULL_PAGE, np.int32)
    bt_row[:len(pages)] = pages

    ids = jnp.asarray(np.array(prompt)[None, :], jnp.int32)
    _, kvs = model.apply(variables, ids, return_kv=True)
    cache = scatter_prefill(cache, kvs, 1, jnp.asarray(bt_row),
                            plen, block_size)

    full = jax.jit(lambda v, x: model.apply(v, x))
    toks = list(prompt)
    paged_logits = []
    for _ in range(n_steps):
        nxt = int(jnp.argmax(full(variables, jnp.asarray(
            np.array(toks)[None, :], jnp.int32))[0, -1]))
        toks.append(nxt)
        pos = jnp.asarray(np.array([0, len(toks) - 1]), jnp.int32)
        step_ids = jnp.asarray(np.array([0, nxt]), jnp.int32)
        model_cache = {
            "k": cache["k"], "v": cache["v"],
            "block_tables": cache["block_tables"],
            # INCLUDING the token being decoded (its K/V gets written
            # before attention reads the table)
            "seq_lens": jnp.asarray(np.array([1, len(toks)]), jnp.int32),
        }
        logits, model_cache = model.apply(variables, step_ids,
                                          positions=pos, cache=model_cache)
        cache = dict(cache, k=model_cache["k"], v=model_cache["v"])
        paged_logits.append(np.asarray(logits[1]))
    return np.stack(paged_logits), toks


@pytest.mark.parametrize("family", ["llama", "gpt2"])
def test_prefill_decode_matches_full_forward(family):
    """The acceptance bar: block_size=8, prompt of 6, 7 decode steps —
    generation crosses the first page boundary at position 8 and fills
    into a second page. Every decoded position's logits must match the
    full-sequence forward <= 1e-5 in fp32."""
    model = _fp32_model(family)
    rng = np.random.default_rng(1)
    prompt = rng.integers(1, 200, size=6).tolist()
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 8), jnp.int32))
    n_steps = 7
    paged, toks = _incremental_decode_logits(model, variables, prompt,
                                             n_steps)
    full = model.apply(variables, jnp.asarray(np.array(toks)[None, :],
                                              jnp.int32))
    for i in range(n_steps):
        # paged step i decodes the token AT position plen+i, so its
        # logits line up with the full forward's row plen+i
        want = np.asarray(full[0, len(prompt) + i])
        np.testing.assert_allclose(paged[i], want, atol=1e-5, rtol=1e-5,
                                   err_msg=f"{family} decode step {i}")


def test_prompt_at_exact_block_boundary():
    """Prompt length == block_size: the first decoded token starts a
    fresh page; off-by-one in the scatter index would read garbage."""
    model = _fp32_model("llama")
    variables = model.init(jax.random.PRNGKey(2),
                           jnp.zeros((1, 8), jnp.int32))
    prompt = np.random.default_rng(3).integers(1, 200, size=8).tolist()
    paged, toks = _incremental_decode_logits(model, variables, prompt, 3)
    full = model.apply(variables, jnp.asarray(np.array(toks)[None, :],
                                              jnp.int32))
    for i in range(3):
        np.testing.assert_allclose(
            paged[i], np.asarray(full[0, len(prompt) + i]),
            atol=1e-5, rtol=1e-5)


# ----------------------------------------------------------------------
# continuous-batching engine
# ----------------------------------------------------------------------

@pytest.fixture(scope="module")
def llama_engine_parts():
    model = _fp32_model("llama")
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 8), jnp.int32))
    return model, variables


def _greedy_reference(model, variables, prompt, n):
    full = jax.jit(lambda v, x: model.apply(v, x))
    toks = list(prompt)
    for _ in range(n):
        toks.append(int(jnp.argmax(full(
            variables, jnp.asarray(np.array(toks)[None, :], jnp.int32)
        )[0, -1])))
    return toks[len(prompt):]


@pytest.mark.slow  # heavy; runs unfiltered in make ci and the file's smoke target
def test_engine_early_finish_and_readmission(llama_engine_parts):
    """max_batch=2 with 4 requests of different lengths: short sequences
    finish early, free their slot and pages, and queued requests are
    admitted mid-flight. Every completion must equal the isolated greedy
    continuation — i.e. slot reuse never corrupts a neighbour's cache."""
    model, variables = llama_engine_parts
    cfg = EngineConfig(max_batch=2, max_seq=64, block_size=8,
                       buckets=(8, 16))
    eng = ServingEngine(model, variables, cfg)
    rng = np.random.default_rng(7)
    reqs = [
        Request("short-a", rng.integers(1, 200, size=4).tolist(), 2),
        Request("long-b", rng.integers(1, 200, size=10).tolist(), 9),
        Request("short-c", rng.integers(1, 200, size=3).tolist(), 1),
        Request("mid-d", rng.integers(1, 200, size=12).tolist(), 5),
    ]
    comps = {c.rid: c for c in eng.run(reqs)}
    assert set(comps) == {r.rid for r in reqs}
    for r in reqs:
        want = _greedy_reference(model, variables, r.prompt,
                                 r.max_new_tokens)
        assert comps[r.rid].tokens == want, r.rid
    # everything was released: the pool is whole again
    assert eng._allocator.available == eng.cache_cfg.num_pages - 1


def test_engine_mixed_stream_bounded_compiles(llama_engine_parts):
    """16 requests with prompt lengths spread across every bucket must
    compile at most num_buckets prefill executables + 1 decode step
    (acceptance bound: num_buckets + 2)."""
    model, variables = llama_engine_parts
    cfg = EngineConfig(max_batch=4, max_seq=64, block_size=8,
                       buckets=(8, 16, 32))
    eng = ServingEngine(model, variables, cfg)
    rng = np.random.default_rng(11)
    lengths = [3, 30, 9, 17, 8, 25, 5, 12, 31, 6, 16, 20, 4, 10, 28, 7]
    reqs = [Request(f"r{i}", rng.integers(1, 200, size=n).tolist(),
                    int(rng.integers(1, 5)))
            for i, n in enumerate(lengths)]
    comps = eng.run(reqs)
    assert len(comps) == 16
    report = eng.compile_report()
    assert report["decode_executables"] == 1
    assert report["prefill_executables"] <= len(eng.buckets)
    assert report["total_executables"] <= report["num_buckets"] + 2
    stats = eng.stats()
    assert stats["decode_tokens"] > 0
    assert stats["decode_throughput_tokens_s"] > 0


def test_engine_rejects_oversized_requests(llama_engine_parts):
    model, variables = llama_engine_parts
    cfg = EngineConfig(max_batch=2, max_seq=32, block_size=8,
                       buckets=(8, 16))
    eng = ServingEngine(model, variables, cfg)
    with pytest.raises(ValueError):
        eng.submit(Request("empty", [], 4))
    with pytest.raises(ValueError):
        eng.submit(Request("too-long", list(range(1, 40)), 4))
    with pytest.raises(ValueError):  # prompt fits, prompt+new does not
        eng.submit(Request("overflow", list(range(1, 30)), 8))


def test_engine_decode_cache_is_donated(llama_engine_parts):
    """The compiled decode step must alias the KV page pools in-place
    (>= 2 per layer); a copied cache would double HBM per step."""
    model, variables = llama_engine_parts
    cfg = EngineConfig(max_batch=2, max_seq=32, block_size=8,
                       buckets=(8,))
    eng = ServingEngine(model, variables, cfg)
    n = eng.verify_cache_donated()
    assert n >= 2 * eng.cache_cfg.num_layers
