"""Serving-kernel tests: the fused paged-decode Pallas kernel and the
collective-overlapped decode matmul.

All kernel equivalence tests run the REAL kernel body through the
Pallas interpreter on CPU (ops/attention.py `_paged_decode_packed`
interprets automatically off-TPU) — not a shadow implementation. The
numeric bar is tiered like tests/test_quant.py: exact-path comparisons
(fused vs the folded jnp reference on the same int8 pools) get a tight
absolute gate, since both consume identical quantized rows and differ
only in summation order; engine-level kernels-on vs kernels-off runs
get the quant suite's relative logit gate (< 0.05) over the agreed
greedy prefix.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from move2kube_tpu.models.llama import Llama, llama_tiny
from move2kube_tpu.ops import attention
from move2kube_tpu.parallel import overlap
from move2kube_tpu.serving import quant as quantlib
from move2kube_tpu.serving.engine import (
    EngineConfig,
    Request,
    ServingEngine,
    select_decode_matmul,
)
from move2kube_tpu.serving.kvcache import (
    KVCacheConfig,
    copy_page,
    init_cache,
    install_block_table,
    scatter_prefill,
)

ATOL = 2e-5  # same-inputs paths, fp32 accumulation, different sum order


def _int8_pools(rng, num_pages, bs, kvh, d):
    kp = jnp.asarray(rng.integers(-127, 128, size=(num_pages, bs, kvh, d)),
                     jnp.int8)
    vp = jnp.asarray(rng.integers(-127, 128, size=(num_pages, bs, kvh, d)),
                     jnp.int8)
    ks = jnp.asarray(rng.uniform(0.001, 0.02, size=(num_pages, bs, kvh)),
                     jnp.float32)
    vs = jnp.asarray(rng.uniform(0.001, 0.02, size=(num_pages, bs, kvh)),
                     jnp.float32)
    return kp, vp, ks, vs


def _tables(lens, mb, bs):
    """Disjoint page runs per sequence, pages 1.. (0 reserved null)."""
    bt = np.zeros((len(lens), mb), np.int32)
    used = 1
    for i, length in enumerate(lens):
        pages = -(-length // bs)
        bt[i, :pages] = np.arange(used, used + pages)
        used += pages
    return jnp.asarray(bt), jnp.asarray(lens, jnp.int32)


# ----------------------------------------------------------------------
# fused packed kernel vs the jnp reference (interpret mode on CPU)
# ----------------------------------------------------------------------

@pytest.mark.parametrize("ppt", [1, 2, 4, 8])
def test_packed_kernel_int8_matches_reference(ppt):
    rng = np.random.default_rng(0)
    b, h, kvh, d, bs, mb = 3, 4, 2, 32, 8, 8
    q = jnp.asarray(rng.normal(size=(b, h, d)), jnp.float32)
    kp, vp, ks, vs = _int8_pools(rng, 30, bs, kvh, d)
    # ragged: partial page tail, full row, tiny row
    bt, sl = _tables([5, 37, 64], mb, bs)
    out = attention._paged_decode_packed(q, kp, vp, bt, sl, d ** -0.5,
                                         k_scale=ks, v_scale=vs,
                                         pages_per_tile=ppt)
    ref = attention._paged_decode_reference(q, kp, vp, bt, sl, d ** -0.5,
                                            k_scale=ks, v_scale=vs)
    assert float(jnp.max(jnp.abs(out - ref))) < ATOL


def test_packed_kernel_fp32_matches_reference():
    rng = np.random.default_rng(1)
    b, h, kvh, d, bs, mb = 2, 4, 2, 32, 8, 6
    q = jnp.asarray(rng.normal(size=(b, h, d)), jnp.float32)
    kp = jnp.asarray(rng.normal(size=(20, bs, kvh, d)), jnp.float32)
    vp = jnp.asarray(rng.normal(size=(20, bs, kvh, d)), jnp.float32)
    bt, sl = _tables([11, 48], mb, bs)
    out = attention._paged_decode_packed(q, kp, vp, bt, sl, d ** -0.5,
                                         pages_per_tile=2)
    ref = attention._paged_decode_reference(q, kp, vp, bt, sl, d ** -0.5)
    assert float(jnp.max(jnp.abs(out - ref))) < ATOL


def test_null_page_padding_at_ragged_tails():
    """mb not a multiple of pages-per-tile: the wrapper pads the block
    table with the reserved null page; padded positions and positions
    past seq_len must not leak into the softmax. Poisoning the null
    page with huge values makes any leak blow past the gate."""
    rng = np.random.default_rng(2)
    b, h, kvh, d, bs, mb = 2, 4, 2, 32, 8, 5   # 5 pages, ppt=4 -> pad 3
    q = jnp.asarray(rng.normal(size=(b, h, d)), jnp.float32)
    kp, vp, ks, vs = _int8_pools(rng, 12, bs, kvh, d)
    ks = ks.at[0].set(50.0)
    vs = vs.at[0].set(50.0)
    bt, sl = _tables([3, 33], mb, bs)           # partial first/last pages
    out = attention._paged_decode_packed(q, kp, vp, bt, sl, d ** -0.5,
                                         k_scale=ks, v_scale=vs,
                                         pages_per_tile=4)
    ref = attention._paged_decode_reference(q, kp, vp, bt, sl, d ** -0.5,
                                            k_scale=ks, v_scale=vs)
    assert float(jnp.max(jnp.abs(out - ref))) < ATOL


def test_prefix_shared_pages():
    """Two sequences whose block tables point at the SAME prefix pages
    (refcounted prefix-cache sharing): the kernel gathers pages per
    (sequence, position), so shared pages must read identically from
    both rows."""
    rng = np.random.default_rng(3)
    b, h, kvh, d, bs, mb = 2, 4, 2, 32, 8, 6
    q = jnp.asarray(rng.normal(size=(b, h, d)), jnp.float32)
    kp, vp, ks, vs = _int8_pools(rng, 16, bs, kvh, d)
    bt = jnp.asarray([[1, 2, 3, 4, 0, 0],      # prefix pages 1-3 shared
                      [1, 2, 3, 5, 6, 0]], jnp.int32)
    sl = jnp.asarray([28, 44], jnp.int32)
    out = attention._paged_decode_packed(q, kp, vp, bt, sl, d ** -0.5,
                                         k_scale=ks, v_scale=vs,
                                         pages_per_tile=4)
    ref = attention._paged_decode_reference(q, kp, vp, bt, sl, d ** -0.5,
                                            k_scale=ks, v_scale=vs)
    assert float(jnp.max(jnp.abs(out - ref))) < ATOL


def test_cow_copied_pages():
    """COW page copy (kvcache.copy_page) duplicates quantized rows AND
    their scales; the fused kernel must read the copy identically to
    the original while a divergent write to the copy stays private."""
    cfg = KVCacheConfig(num_layers=1, num_pages=8, block_size=8,
                        num_kv_heads=2, head_dim=32, max_batch=2,
                        max_pages_per_seq=4, dtype=jnp.int8)
    cache = init_cache(cfg)
    rng = np.random.default_rng(4)
    rows = jnp.asarray(rng.normal(size=(16, 2, 32)), jnp.float32)
    q8, sc = attention.quantize_kv_rows(rows)
    for pool, arr in (("k", q8), ("v", q8)):
        cache[pool][0] = cache[pool][0].at[1:3].set(arr.reshape(2, 8, 2, 32))
    for pool in ("k_scale", "v_scale"):
        cache[pool][0] = cache[pool][0].at[1:3].set(sc.reshape(2, 8, 2))
    cache = copy_page(cache, 2, 3)              # COW: page 2 -> page 3
    # same query in both slots: identical context must give identical out
    q = jnp.broadcast_to(
        jnp.asarray(rng.normal(size=(1, 4, 32)), jnp.float32), (2, 4, 32))
    bt = jnp.asarray([[1, 2, 0, 0], [1, 3, 0, 0]], jnp.int32)
    sl = jnp.asarray([16, 16], jnp.int32)
    args = (q, cache["k"][0], cache["v"][0], bt, sl, 32 ** -0.5)
    kw = dict(k_scale=cache["k_scale"][0], v_scale=cache["v_scale"][0])
    out = attention._paged_decode_packed(*args, pages_per_tile=2, **kw)
    ref = attention._paged_decode_reference(*args, **kw)
    assert float(jnp.max(jnp.abs(out - ref))) < ATOL
    # rows 0 and 1 saw identical context (page 3 is a byte copy of 2)
    assert float(jnp.max(jnp.abs(out[0] - out[1]))) < ATOL
    # a write to the copy diverges the copy holder only
    cache["k"][0] = cache["k"][0].at[3].set(jnp.int8(7))
    out2 = attention._paged_decode_packed(
        q, cache["k"][0], cache["v"][0], bt, sl, 32 ** -0.5,
        pages_per_tile=2, **kw)
    assert float(jnp.max(jnp.abs(out2[0] - out[0]))) < ATOL
    assert float(jnp.max(jnp.abs(out2[1] - out[1]))) > 1e-3


# ----------------------------------------------------------------------
# dispatch ladder + env knob
# ----------------------------------------------------------------------

def test_serve_kernels_mode_parsing(monkeypatch):
    for raw, want in [("", "auto"), ("auto", "auto"), ("on", "on"),
                      ("1", "on"), ("true", "on"), ("off", "off"),
                      ("0", "off"), ("garbage", "auto")]:
        monkeypatch.setenv("M2KT_SERVE_KERNELS", raw)
        assert attention.serve_kernels_mode() == want
    monkeypatch.delenv("M2KT_SERVE_KERNELS")
    assert attention.serve_kernels_mode() == "auto"


def test_dispatch_on_runs_kernel_off_runs_reference(monkeypatch):
    rng = np.random.default_rng(5)
    b, h, kvh, d, bs, mb = 2, 4, 2, 32, 8, 4
    q = jnp.asarray(rng.normal(size=(b, h, d)), jnp.float32)
    kp, vp, ks, vs = _int8_pools(rng, 10, bs, kvh, d)
    bt, sl = _tables([9, 26], mb, bs)
    monkeypatch.setenv("M2KT_SERVE_KERNELS", "off")
    off = attention.paged_decode_attention(q, kp, vp, bt, sl,
                                           k_scale=ks, v_scale=vs)
    monkeypatch.setenv("M2KT_SERVE_KERNELS", "on")
    called = {}
    real = attention._paged_decode_packed

    def spy(*args, **kwargs):
        called["yes"] = True
        return real(*args, **kwargs)

    monkeypatch.setattr(attention, "_paged_decode_packed", spy)
    on = attention.paged_decode_attention(q, kp, vp, bt, sl,
                                          k_scale=ks, v_scale=vs)
    assert called.get("yes"), "mode=on did not reach the packed kernel"
    assert float(jnp.max(jnp.abs(on - off))) < ATOL


# ----------------------------------------------------------------------
# engine integration: kernels-on decode + donation
# ----------------------------------------------------------------------

def _llama_parts():
    cfg = dataclasses.replace(llama_tiny(), dtype=jnp.float32,
                              attn_impl="dense")
    model = Llama(cfg)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 8), jnp.int32))
    return model, variables


@pytest.mark.slow
def test_engine_kernel_path_logits_and_donation(monkeypatch):
    """With M2KT_SERVE_KERNELS=on the engine's decode step runs the
    interpreted kernel body end-to-end: the greedy logits must agree
    with the kernels-off run inside the quant suite's relative gate
    over the agreed prefix, and the decode step must still donate every
    KV page pool (the kernel reads pools positionally, which must not
    break input-output aliasing)."""
    model, variables = _llama_parts()
    cfg = EngineConfig(max_batch=2, max_seq=32, block_size=8,
                       buckets=(16,), max_new_tokens=3, quant="int8-kv")
    reqs = [Request("r0", list(range(1, 9)), 3)]

    monkeypatch.setenv("M2KT_SERVE_KERNELS", "off")
    ref_eng = ServingEngine(model, variables, cfg)
    ref_eng.capture_logits = True
    ref_c = {c.rid: c for c in ref_eng.run(
        [Request(r.rid, list(r.prompt), r.max_new_tokens)
         for r in reqs])}

    monkeypatch.setenv("M2KT_SERVE_KERNELS", "on")
    eng = ServingEngine(model, variables, cfg)
    eng.capture_logits = True
    got_c = {c.rid: c for c in eng.run(reqs)}

    for r in reqs:
        a_t, b_t = ref_c[r.rid].tokens, got_c[r.rid].tokens
        agree = 0
        while agree < min(len(a_t), len(b_t)) and a_t[agree] == b_t[agree]:
            agree += 1
        for i in range(min(agree + 1, len(ref_eng.logit_log[r.rid]),
                           len(eng.logit_log[r.rid]))):
            gate = quantlib.logit_gate(ref_eng.logit_log[r.rid][i],
                                       eng.logit_log[r.rid][i])
            assert gate["max_rel_err"] < 0.05, gate
    aliases = eng.verify_cache_donated()
    assert aliases >= 2 * eng.cache_cfg.num_layers


# ----------------------------------------------------------------------
# collective-overlapped decode matmul
# ----------------------------------------------------------------------

def test_collective_matmul_matches_plain():
    rng = np.random.default_rng(6)
    x = jnp.asarray(rng.normal(size=(4, 64)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(64, 100)), jnp.float32)  # pad path
    mesh = Mesh(np.array(jax.devices()).reshape(-1), ("model",))
    y = overlap.collective_decode_matmul(mesh, x, w)
    assert y.shape == (4, 100)
    assert float(jnp.max(jnp.abs(y - x @ w))) < 1e-4


def test_collective_matmul_2d_mesh_under_jit():
    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.normal(size=(2, 64)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(64, 128)), jnp.float32)
    mesh = Mesh(np.array(jax.devices()).reshape(2, -1), ("data", "model"))
    y = jax.jit(lambda x, w: overlap.collective_decode_matmul(mesh, x, w))(
        x, w)
    assert float(jnp.max(jnp.abs(y - x @ w))) < 1e-4


def test_select_decode_matmul(monkeypatch):
    monkeypatch.delenv("M2KT_SERVE_KERNELS", raising=False)
    devices = np.array(jax.devices())
    model_mesh = Mesh(devices.reshape(-1), ("model",))
    data_mesh = Mesh(devices.reshape(-1), ("data",))
    x = jnp.ones((2, 8), jnp.float32)
    w = jnp.ones((8, 4), jnp.float32)
    # model axis -> collective path, still numerically x @ w
    fn = select_decode_matmul(model_mesh)
    assert float(jnp.max(jnp.abs(fn(x, w) - x @ w))) < 1e-5
    assert overlap.has_model_axis(model_mesh)
    # no mesh / data-only mesh / kernels off -> plain matmul
    assert not overlap.has_model_axis(data_mesh)
    for mesh in (None, data_mesh):
        assert select_decode_matmul(mesh)(x, w).shape == (2, 4)
    monkeypatch.setenv("M2KT_SERVE_KERNELS", "off")
    assert select_decode_matmul(model_mesh)(x, w).shape == (2, 4)


# ----------------------------------------------------------------------
# kvcache page-pool schema guard
# ----------------------------------------------------------------------

def _tiny_cache(dtype=jnp.float32):
    return init_cache(KVCacheConfig(
        num_layers=1, num_pages=4, block_size=4, num_kv_heads=1,
        head_dim=8, max_batch=1, max_pages_per_seq=2, dtype=dtype))


def test_page_schema_guard():
    cache = _tiny_cache(jnp.int8)               # init_cache asserts clean
    cache["adapter"] = [jnp.zeros((4, 4, 1, 8))]  # future pool, untaught
    with pytest.raises(ValueError, match="page-pool schema"):
        copy_page(cache, 1, 2)
    kvs = [(jnp.zeros((1, 4, 1, 8)), jnp.zeros((1, 4, 1, 8)))]
    with pytest.raises(ValueError, match="page-pool schema"):
        scatter_prefill(cache, kvs, 0, jnp.zeros((2,), jnp.int32), 2, 4)
    # install_block_table touches no pools and stays permissive
    clean = _tiny_cache()
    out = install_block_table(clean, 0, jnp.zeros((2,), jnp.int32), 2)
    assert int(out["seq_lens"][0]) == 2
