"""Buffer donation is verified at the executable level (models/train.py).

``donate_argnums`` is only a *request*: the assertion here checks the
compiled HLO's ``input_output_alias`` table, so a wrapper or engine
change that silently drops donation (doubling peak memory) fails CI on
CPU — no TPU needed.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from move2kube_tpu.models import train as m2kt_train
from move2kube_tpu.parallel.mesh import MeshConfig, make_mesh


class _TinyMLP(nn.Module):
    classes: int = 8

    @nn.compact
    def __call__(self, x):
        x = nn.relu(nn.Dense(16)(x))
        return nn.Dense(self.classes)(x)


def _state_and_batch(mesh, batch=8, dim=4):
    state = m2kt_train.create_sharded_state(
        jax.random.PRNGKey(0), _TinyMLP(), {"x": jnp.zeros((batch, dim))},
        optax.adam(1e-3), mesh)
    gen = np.random.default_rng(0)
    b = {"input": jnp.asarray(gen.random((batch, dim), np.float32)),
         "label": jnp.asarray(gen.integers(0, 8, batch))}
    return state, b


def test_donation_reaches_executable_on_sharded_mesh():
    mesh = make_mesh(MeshConfig(data=4, fsdp=2))
    state, batch = _state_and_batch(mesh)
    step = m2kt_train.make_classifier_train_step(mesh)
    n = m2kt_train.assert_state_donated(step, state, batch)
    # at least one alias per param leaf (kernel+bias x 2 layers)
    assert n >= len(jax.tree.leaves(state.params))


def test_donation_reaches_executable_on_trivial_mesh():
    """The single-device path returns the raw jit object (no _with_mesh
    wrapper); .lower() must work directly on it."""
    mesh = make_mesh(MeshConfig(data=1), devices=jax.devices()[:1])
    state, batch = _state_and_batch(mesh)
    step = m2kt_train.make_classifier_train_step(mesh)
    n = m2kt_train.assert_state_donated(step, state, batch)
    assert n >= len(jax.tree.leaves(state.params))


def test_assert_state_donated_rejects_non_donating_step():
    """Negative control: the assertion must actually FAIL for a step
    compiled without donation — otherwise it verifies nothing."""
    mesh = make_mesh(MeshConfig(data=4, fsdp=2))
    state, batch = _state_and_batch(mesh)

    @jax.jit  # no donate_argnums
    def plain_step(state, batch):
        def loss_fn(params):
            logits = state.apply_fn({"params": params}, batch["input"])
            return m2kt_train.cross_entropy_loss(logits, batch["label"])

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        return state.apply_gradients(grads=grads), loss

    with pytest.raises(AssertionError, match="aliases only"):
        m2kt_train.assert_state_donated(plain_step, state, batch)


def test_assert_state_donated_rejects_plain_function():
    mesh = make_mesh(MeshConfig(data=4, fsdp=2))
    state, batch = _state_and_batch(mesh)
    with pytest.raises(TypeError, match="lower"):
        m2kt_train.assert_state_donated(lambda s, b: (s, 0.0), state, batch)


def test_bert_train_step_donates():
    """A second step factory: donation carries through the _with_mesh
    wrapper (via _m2kt_jit) for the BERT fine-tune step too."""
    from move2kube_tpu.models.bert import BertEncoder

    mesh = make_mesh(MeshConfig(data=4, fsdp=2))
    model = BertEncoder(vocab_size=64, num_layers=1, num_heads=2,
                        d_model=16, mlp_dim=32, max_len=16, num_classes=2)
    ids = jnp.zeros((8, 16), jnp.int32)
    state = m2kt_train.create_sharded_state(
        jax.random.PRNGKey(0), model, {"input_ids": ids},
        optax.adam(1e-3), mesh)
    step = m2kt_train.make_bert_train_step(mesh)
    assert hasattr(step, "_m2kt_jit")
    batch = {"input_ids": ids, "label": jnp.zeros((8,), jnp.int32)}
    n = m2kt_train.assert_state_donated(step, state, batch)
    assert n >= len(jax.tree.leaves(state.params))
